"""CSI adaptor: container storage volumes for YARN apps.

Parity with the reference's CSI module (ref: hadoop-yarn-csi —
CsiAdaptorProtocolService.java translating YARN's volume lifecycle to a
CSI driver's gRPC surface: ValidateVolumeCapabilities /
NodePublishVolume / NodeUnpublishVolume; the NM invokes the adaptor
around container launch via ContainerVolumePublisher): here the
adaptor is an RPC service hosting pluggable DRIVERS, and the built-in
driver mounts the DFS itself through the fuse-dfs daemon
(native/src/fuse_dfs.c), so a container can request
``htpufs://nn-http-host:port`` volumes and read the namespace as plain
files under its own work dir.

Container launch contexts carry ``volumes``:
``[{"driver": "htpufs", "id": "htpufs://host:port", "target": "data"}]``
— the NM publishes each volume under ``<workdir>/<target>`` before the
process starts and unpublishes after it exits.
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading
import time
from typing import Dict, Optional

log = logging.getLogger(__name__)


class CsiDriver:
    """One storage backend (ref: the CSI plugin the adaptor fronts)."""

    def validate_volume(self, volume_id: str, capability: Dict) -> bool:
        raise NotImplementedError

    def node_publish_volume(self, volume_id: str, target_path: str,
                            options: Dict) -> None:
        raise NotImplementedError

    def node_unpublish_volume(self, volume_id: str,
                              target_path: str) -> None:
        raise NotImplementedError


class DfsFuseDriver(CsiDriver):
    """Mount the DFS at the target via the fuse-dfs daemon.

    volume id: ``htpufs://<nn-http-host>:<nn-http-port>``. Each publish
    runs one htpu-fuse-dfs process on the target dir; unpublish
    fusermounts it away and reaps the daemon.
    """

    def __init__(self, binary: Optional[str] = None):
        self.binary = binary or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "native", "htpu-fuse-dfs")
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def available(self) -> bool:
        return os.path.exists(self.binary) and os.path.exists("/dev/fuse")

    @staticmethod
    def _parse(volume_id: str):
        if not volume_id.startswith("htpufs://"):
            raise ValueError(f"not an htpufs volume: {volume_id!r}")
        hostport = volume_id[len("htpufs://"):].strip("/")
        host, _, port = hostport.rpartition(":")
        return host or "127.0.0.1", int(port)

    def validate_volume(self, volume_id: str, capability: Dict) -> bool:
        self._parse(volume_id)
        if capability.get("access_mode", "ro") not in ("ro", "rw"):
            return False
        return self.available()

    def node_publish_volume(self, volume_id: str, target_path: str,
                            options: Dict) -> None:
        host, port = self._parse(volume_id)
        os.makedirs(target_path, exist_ok=True)
        # stderr goes to a FILE, never a pipe: the daemon is long-lived
        # and nothing drains a pipe after publish — ~64KB of warnings
        # would block its next stderr write inside a FUSE handler and
        # hang the mounted volume for every reader
        errlog_path = target_path.rstrip("/") + ".fuse.log"
        errlog = open(errlog_path, "wb")
        try:
            proc = subprocess.Popen(
                [self.binary, host, str(port), target_path, "-f"],
                stdout=subprocess.DEVNULL, stderr=errlog)
        finally:
            errlog.close()  # the child holds its own fd
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if os.path.ismount(target_path):
                with self._lock:
                    self._procs[target_path] = proc
                return
            if proc.poll() is not None:
                try:
                    with open(errlog_path, "rb") as f:
                        err = f.read().decode()[-300:]
                except OSError:
                    err = ""
                raise IOError(f"fuse mount of {volume_id} failed: {err}")
            # bounded poll for the fuse mount to appear
            time.sleep(0.1)  # lint: disable=rpc/retry-no-backoff
        proc.terminate()
        raise IOError(f"mount of {volume_id} at {target_path} timed out")

    def node_unpublish_volume(self, volume_id: str,
                              target_path: str) -> None:
        subprocess.run(["fusermount", "-u", target_path],
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)
        with self._lock:
            proc = self._procs.pop(target_path, None)
        if proc is not None:
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()


class CsiAdaptor:
    """Driver registry + the adaptor protocol surface (ref:
    CsiAdaptorProtocolService / CsiAdaptorFactory). Registered as an
    RPC protocol when hosted standalone; the NM also calls it
    in-process around container launch."""

    def __init__(self):
        self._drivers: Dict[str, CsiDriver] = {}
        fuse = DfsFuseDriver()
        if fuse.available():
            self._drivers["htpufs"] = fuse

    def register_driver(self, name: str, driver: CsiDriver) -> None:
        self._drivers[name] = driver

    def _driver(self, name: str) -> CsiDriver:
        drv = self._drivers.get(name)
        if drv is None:
            raise ValueError(f"no CSI driver {name!r} "
                             f"(have {sorted(self._drivers)})")
        return drv

    # ------------------------------------------------- protocol surface

    def validate_volume(self, driver: str, volume_id: str,
                        capability: Optional[Dict] = None) -> bool:
        return self._driver(driver).validate_volume(volume_id,
                                                    capability or {})

    def node_publish_volume(self, driver: str, volume_id: str,
                            target_path: str,
                            options: Optional[Dict] = None) -> bool:
        self._driver(driver).node_publish_volume(volume_id, target_path,
                                                 options or {})
        log.info("published %s volume %s at %s", driver, volume_id,
                 target_path)
        return True

    def node_unpublish_volume(self, driver: str, volume_id: str,
                              target_path: str) -> bool:
        self._driver(driver).node_unpublish_volume(volume_id, target_path)
        log.info("unpublished %s from %s", volume_id, target_path)
        return True

    def drivers(self) -> list:
        return sorted(self._drivers)
