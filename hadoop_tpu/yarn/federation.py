"""YARN federation: state store + Router over many subclusters.

Counterparts: hadoop-yarn-server-common federation (FederationStateStore
— subcluster registry + home-subcluster table, ref:
FederationStateStoreFacade.java; policies ref:
federation/policies/router/*Policy.java) and hadoop-yarn-server-router
(Router.java — the client-facing ApplicationClientProtocol that routes
each app to its home subcluster; ref:
clientrm/FederationClientInterceptor.java).

Model: every application gets a *home subcluster* chosen at
``get_new_application`` time by the routing policy; every subsequent
call for that app (submit/report/kill) follows the home mapping, and
aggregate reads (list/metrics/nodes) fan out over all ACTIVE
subclusters — the same shape as the reference's interceptor chain.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import Client, Server, get_proxy
from hadoop_tpu.service import AbstractService
from hadoop_tpu.util.misc import Daemon, parse_addr_list
from hadoop_tpu.yarn.records import ApplicationId

log = logging.getLogger(__name__)

SC_ACTIVE = "ACTIVE"
SC_LOST = "LOST"
SC_DEREGISTERED = "DEREGISTERED"


class FederationStateStore:
    """Subcluster registry + app→home-subcluster table, file-backed the
    way the RM's FileRMStateStore is (ref: FederationStateStore.java;
    the reference ships ZK/SQL/in-memory impls)."""

    def __init__(self, store_path: Optional[str] = None):
        self._path = store_path
        self._subclusters: Dict[str, Dict] = {}
        self._homes: Dict[str, str] = {}       # app_id str → subcluster id
        self._policies: Dict[str, Dict] = {}   # queue → policy config
        self._lock = threading.Lock()
        if store_path and os.path.exists(store_path):
            with open(store_path) as f:
                data = json.load(f)
            self._subclusters = data.get("subclusters", {})
            self._homes = data.get("homes", {})
            self._policies = data.get("policies", {})

    def _save_locked(self) -> None:
        if not self._path:
            return
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"subclusters": self._subclusters,
                       "homes": self._homes,
                       "policies": self._policies}, f)
        os.replace(tmp, self._path)

    def register_subcluster(self, sc_id: str, rm_addr: str) -> None:
        with self._lock:
            self._subclusters[sc_id] = {
                "addr": rm_addr, "state": SC_ACTIVE,
                "last_heartbeat": time.time()}
            self._save_locked()

    def deregister_subcluster(self, sc_id: str) -> bool:
        with self._lock:
            sc = self._subclusters.get(sc_id)
            if sc is None:
                return False
            sc["state"] = SC_DEREGISTERED
            self._save_locked()
            return True

    def subcluster_heartbeat(self, sc_id: str, state: str = SC_ACTIVE
                             ) -> bool:
        with self._lock:
            sc = self._subclusters.get(sc_id)
            # DEREGISTERED is administrative and final (until an explicit
            # re-register): neither a failure demotion (mark_lost) nor a
            # successful liveness probe may overwrite it — both race the
            # admin's deregister, and an overwrite resurrects a drained
            # RM into routing. Enforced HERE, under the store lock, so
            # every caller's check-then-act window closes at once.
            if sc is not None and sc["state"] != SC_DEREGISTERED:
                sc["state"] = state
                sc["last_heartbeat"] = time.time()
                self._save_locked()
                return True
        return False

    def subclusters(self, active_only: bool = False) -> Dict[str, Dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._subclusters.items()
                    if not active_only or v["state"] == SC_ACTIVE}

    def set_home(self, app_id: str, sc_id: str) -> None:
        with self._lock:
            self._homes[app_id] = sc_id
            self._save_locked()

    def home_of(self, app_id: str) -> Optional[str]:
        with self._lock:
            return self._homes.get(app_id)

    # policy table (ref: FederationPolicyStore — per-queue policy
    # configurations the router's policy facade resolves)

    def set_policy(self, queue: str, policy: Dict) -> None:
        with self._lock:
            self._policies[queue] = dict(policy)
            self._save_locked()

    def policy_for(self, queue: str) -> Optional[Dict]:
        with self._lock:
            p = self._policies.get(queue)
            return dict(p) if p is not None else None


# ------------------------------------------------------------------ policies

class RouterPolicy:
    """Home-subcluster selection (ref: federation/policies/router/
    *RouterPolicy.java). ``choose(active, queue)`` returns a subcluster
    id from the ACTIVE map or raises IOError."""

    def choose(self, active: Dict[str, Dict], queue: str) -> str:
        raise NotImplementedError


class UniformRandomPolicy(RouterPolicy):
    """Ref: UniformRandomRouterPolicy."""

    def choose(self, active, queue):
        import random
        return random.choice(sorted(active))


class RoundRobinPolicy(RouterPolicy):
    def __init__(self):
        self._rr = 0
        self._lock = threading.Lock()

    def choose(self, active, queue):
        order = sorted(active)
        with self._lock:
            sc = order[self._rr % len(order)]
            self._rr += 1
        return sc


class WeightedRandomPolicy(RouterPolicy):
    """Per-subcluster weights, usually per queue (ref:
    WeightedRandomRouterPolicy + the policy manager's per-queue
    WeightedPolicyInfo). Unknown/zero-weight subclusters are skipped;
    weights renormalize over whatever is ACTIVE."""

    def __init__(self, weights: Dict[str, float]):
        self.weights = {k: float(v) for k, v in weights.items()}

    def choose(self, active, queue):
        import random
        cands = [(sc, self.weights.get(sc, 0.0)) for sc in sorted(active)]
        total = sum(w for _, w in cands if w > 0)
        if total <= 0:
            raise IOError(f"no ACTIVE subcluster with weight for {queue!r}")
        r = random.random() * total
        acc = 0.0
        for sc, w in cands:
            if w <= 0:
                continue
            acc += w
            if r <= acc:
                return sc
        return cands[-1][0]


class LoadBasedPolicy(RouterPolicy):
    """Fewest running apps wins (ref: LoadBasedRouterPolicy)."""

    def __init__(self, router: "YarnRouter"):
        self.router = router

    def choose(self, active, queue):
        best, best_load = None, float("inf")
        for sc_id in sorted(active):
            try:
                m = self.router.rm_proxy(sc_id).get_cluster_metrics()
                load = m.get("apps", 0)
            except (OSError, IOError):
                continue
            if load < best_load:
                best, best_load = sc_id, load
        if best is None:
            raise IOError("no reachable ACTIVE subcluster")
        return best


class RejectPolicy(RouterPolicy):
    """Ref: RejectRouterPolicy — a queue administratively closed."""

    def choose(self, active, queue):
        raise IOError(f"queue {queue!r} rejects new applications")


def make_policy(wire: Dict, router: "YarnRouter") -> RouterPolicy:
    kind = (wire or {}).get("type", "load")
    if kind in ("uniform", "random"):
        return UniformRandomPolicy()
    if kind == "round-robin":
        return RoundRobinPolicy()
    if kind == "weighted":
        return WeightedRandomPolicy(wire.get("weights", {}))
    if kind == "reject":
        return RejectPolicy()
    if kind == "load":
        return LoadBasedPolicy(router)
    # A typo'd type must fail set_policy's validation loudly, not route
    # by the wrong policy forever.
    raise ValueError(f"unknown router policy type {kind!r}")


# -------------------------------------------------------------- interceptors

class ClientInterceptor:
    """One link of the router's client-RM interceptor chain (ref:
    router/clientrm/AbstractClientRequestInterceptor.java — Router.java
    builds the pipeline from conf). Unhandled methods flow to the next
    link via ``__getattr__``, so a link only implements what it
    intercepts."""

    def __init__(self, router: "YarnRouter"):
        self.router = router
        self.next: Optional["ClientInterceptor"] = None

    def set_next(self, nxt: "ClientInterceptor") -> "ClientInterceptor":
        self.next = nxt
        return nxt

    def __getattr__(self, name):
        nxt = object.__getattribute__(self, "__dict__").get("next")
        if nxt is None or name.startswith("_"):
            raise AttributeError(name)
        return getattr(nxt, name)


class RouterAuditInterceptor(ClientInterceptor):
    """Counts + audit-logs every client call before passing it on (ref:
    RouterAuditLogger + the metrics the router keeps per method)."""

    def __init__(self, router):
        super().__init__(router)
        self.counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def __getattr__(self, name):
        target = super().__getattr__(name)
        if not callable(target):
            return target

        def wrapped(*a, **kw):
            with self._lock:
                self.counts[name] = self.counts.get(name, 0) + 1
            log.debug("router audit: %s", name)
            return target(*a, **kw)
        return wrapped


class FederationClientInterceptor(ClientInterceptor):
    """Terminal link: the actual federated routing (ref:
    clientrm/FederationClientInterceptor.java).

    Failure semantics: a subcluster whose RM stops answering is marked
    LOST by the liveness loop AND eagerly here on first failure, so new
    applications immediately route around it (the reference's
    submitApplication retry loop over the policy does the same);
    aggregate reads skip unreachable members. Per-app calls follow the
    home mapping — the home RM restarting with work-preserving recovery
    resumes them (AM spanning via AMRMProxy/UAMs is out of scope)."""

    SUBMIT_RETRIES = 3

    def get_new_application(self) -> Dict:
        """Mint an id from any reachable subcluster. The HOME binding
        happens at submit time, when the submission's QUEUE is known and
        the per-queue policy can speak (ref: FederationClientInterceptor
        binds in submitApplication; RMs accept ids minted elsewhere)."""
        last: Optional[Exception] = None
        for _ in range(self.SUBMIT_RETRIES):
            # any reachable member will do for minting — must NOT
            # consume the queue policy's sequence (that belongs to the
            # home binding at submit time)
            sc_id = self.router.any_active()
            try:
                return self.router.rm_proxy(sc_id).get_new_application()
            except (OSError, IOError) as e:
                last = e
                self.router.mark_lost(sc_id)
        raise IOError(f"no subcluster could issue an application: {last}")

    def submit_application(self, ctx_wire: Dict) -> Dict:
        app_id = str(ApplicationId.from_wire(ctx_wire["id"]))
        queue = ctx_wire.get("q", "default")
        home = self.router.store.home_of(app_id)
        if home is not None:
            # resubmission/retry: sticky home
            try:
                return self.router.rm_proxy(home).submit_application(
                    ctx_wire)
            except (OSError, IOError):
                self.router.mark_lost(home)
                raise
        last: Optional[Exception] = None
        for _ in range(self.SUBMIT_RETRIES):
            sc_id = self.router.choose_subcluster(queue)  # queue policy
            try:
                out = self.router.rm_proxy(sc_id).submit_application(
                    ctx_wire)
            except (OSError, IOError) as e:
                last = e
                self.router.mark_lost(sc_id)
                continue
            self.router.store.set_home(app_id, sc_id)
            return out
        raise IOError(f"no subcluster accepted {app_id}: {last}")

    def get_application_report(self, app_id_wire: Dict) -> Dict:
        app_id = str(ApplicationId.from_wire(app_id_wire))
        sc_id = self.router.home_or_raise(app_id)
        return self.router.rm_proxy(sc_id).get_application_report(
            app_id_wire)

    def kill_application(self, app_id_wire: Dict) -> bool:
        app_id = str(ApplicationId.from_wire(app_id_wire))
        sc_id = self.router.home_or_raise(app_id)
        return self.router.rm_proxy(sc_id).kill_application(app_id_wire)

    def list_applications(self) -> List[Dict]:
        out: List[Dict] = []
        for sc_id in self.router.store.subclusters(active_only=True):
            try:
                out.extend(self.router.rm_proxy(sc_id).list_applications())
            except (OSError, IOError) as e:
                log.warning("list_applications on %s failed: %s", sc_id, e)
        return out

    def get_cluster_metrics(self) -> Dict:
        agg = {"num_node_managers": 0, "apps": 0, "subclusters": 0,
               "total_resource": {"m": 0, "v": 0, "c": 0}}
        for sc_id in self.router.store.subclusters(active_only=True):
            try:
                m = self.router.rm_proxy(sc_id).get_cluster_metrics()
            except (OSError, IOError):
                continue
            agg["subclusters"] += 1
            agg["num_node_managers"] += m.get("num_node_managers", 0)
            agg["apps"] += m.get("apps", 0)
            tr = m.get("total_resource", {})
            for k in ("m", "v", "c"):
                agg["total_resource"][k] += tr.get(k, 0)
        return agg

    def get_nodes(self) -> List[Dict]:
        out: List[Dict] = []
        for sc_id in self.router.store.subclusters(active_only=True):
            try:
                for n in self.router.rm_proxy(sc_id).get_nodes():
                    n["subcluster"] = sc_id
                    out.append(n)
            except (OSError, IOError):
                continue
        return out

    def get_service_status(self) -> Dict:
        return {"state": "active", "role": "router"}


INTERCEPTORS = {
    "audit": RouterAuditInterceptor,
    "federation": FederationClientInterceptor,
}


def build_interceptor_chain(router: "YarnRouter",
                            spec: str) -> ClientInterceptor:
    """Ref: Router's interceptor-class.pipeline conf — comma list, last
    must be the terminal federation link."""
    names = [n.strip() for n in spec.split(",") if n.strip()]
    if not names or names[-1] != "federation":
        names = names + ["federation"]
    links = [INTERCEPTORS[n](router) for n in names]
    for a, b in zip(links, links[1:]):
        a.set_next(b)
    return links[0]


class _RouterAdminProtocol:
    """Ref: router RouterAdminProtocol / FederationStateStore admin."""

    def __init__(self, router: "YarnRouter"):
        self.router = router

    def register_subcluster(self, sc_id: str, rm_addr: str) -> bool:
        self.router.store.register_subcluster(sc_id, rm_addr)
        return True

    def deregister_subcluster(self, sc_id: str) -> bool:
        return self.router.store.deregister_subcluster(sc_id)

    def list_subclusters(self) -> Dict[str, Dict]:
        return self.router.store.subclusters()

    def set_policy(self, queue: str, policy: Dict) -> bool:
        """Per-queue routing policy (ref: the policy store's
        setPolicyConfiguration; e.g. {"type": "weighted",
        "weights": {"sc1": 3, "sc2": 1}})."""
        make_policy(policy, self.router)  # validate before persisting
        self.router.store.set_policy(queue, policy)
        return True

    def get_policy(self, queue: str) -> Optional[Dict]:
        return self.router.store.policy_for(queue)

    def interceptor_counts(self) -> Dict[str, int]:
        head = self.router.chain
        while head is not None:
            if isinstance(head, RouterAuditInterceptor):
                return dict(head.counts)
            head = head.next
        return {}


class YarnRouter(AbstractService):
    """Client-facing router over federated RMs (ref: router/Router.java
    :82 — a pipeline of interceptors in front of many subclusters)."""

    def __init__(self, conf: Configuration,
                 state_dir: Optional[str] = None):
        super().__init__("YarnRouter")
        self.state_dir = state_dir or conf.get(
            "yarn.federation.state-store.dir", "/tmp/htpu-yarn-router")
        self.store = FederationStateStore(
            os.path.join(self.state_dir, "federation.json"))
        self.default_policy = {"type": conf.get("yarn.federation.policy",
                                                "load")}
        self._proxies: Dict[str, object] = {}
        self._policy_cache: Dict[str, RouterPolicy] = {}
        self._client: Optional[Client] = None
        self._lock = threading.Lock()
        self.rpc: Optional[Server] = None
        self.chain: Optional[ClientInterceptor] = None
        self._stop_event = threading.Event()

    def service_init(self, conf: Configuration) -> None:
        # Static registration: yarn.federation.subcluster.<id> = host:port
        for key, value in conf.to_dict().items():
            if key.startswith("yarn.federation.subcluster."):
                sc_id = key[len("yarn.federation.subcluster."):]
                self.store.register_subcluster(sc_id, value)
        self._client = Client(conf)
        self.rpc = Server(conf, bind=("127.0.0.1", conf.get_int(
            "yarn.federation.router.port", 0)), num_handlers=8,
            name="yarn-router")
        self.chain = build_interceptor_chain(self, conf.get(
            "yarn.router.clientrm.interceptors", "audit,federation"))
        self.rpc.register_protocol("ClientRMProtocol", self.chain)
        self.rpc.register_protocol("RouterAdminProtocol",
                                   _RouterAdminProtocol(self))

    def service_start(self) -> None:
        self.rpc.start()
        Daemon(self._liveness_loop, "yarn-router-liveness").start()
        log.info("YARN Router on :%d (%d subclusters, default policy=%s)",
                 self.rpc.port, len(self.store.subclusters()),
                 self.default_policy)

    def service_stop(self) -> None:
        self._stop_event.set()
        if self.rpc:
            self.rpc.stop()
        if self._client:
            self._client.stop()

    @property
    def port(self) -> int:
        return self.rpc.port

    # ------------------------------------------------------------- routing

    def rm_proxy(self, sc_id: str):
        with self._lock:
            p = self._proxies.get(sc_id)
            if p is None:
                sc = self.store.subclusters().get(sc_id)
                if sc is None:
                    raise ValueError(f"unknown subcluster {sc_id!r}")
                addr = parse_addr_list(sc["addr"])[0]
                p = get_proxy("ClientRMProtocol", addr,
                              client=self._client)
                self._proxies[sc_id] = p
            return p

    def home_or_raise(self, app_id: str) -> str:
        sc_id = self.store.home_of(app_id)
        if sc_id is None:
            raise ValueError(f"no home subcluster for {app_id}")
        return sc_id

    def choose_subcluster(self, queue: str = "default") -> str:
        """Resolve the queue's policy from the store (falling back to
        the conf-wide default) and let it pick over ACTIVE subclusters
        (ref: FederationPolicyStoreFacade resolving per-queue policy
        managers)."""
        active = self.store.subclusters(active_only=True)
        if not active:
            raise IOError("no ACTIVE subclusters")
        wire = self.store.policy_for(queue) or self.default_policy
        cache_key = f"{queue}|{json.dumps(wire, sort_keys=True)}"
        with self._lock:
            policy = self._policy_cache.get(cache_key)
            if policy is None:
                policy = make_policy(wire, self)
                self._policy_cache[cache_key] = policy
        return policy.choose(active, queue)

    def any_active(self) -> str:
        """Rotate over ACTIVE members outside any queue policy (id
        minting, health probes)."""
        active = sorted(self.store.subclusters(active_only=True))
        if not active:
            raise IOError("no ACTIVE subclusters")
        with self._lock:
            self._mint_rr = getattr(self, "_mint_rr", 0) + 1
            return active[self._mint_rr % len(active)]

    def mark_lost(self, sc_id: str) -> None:
        """Eager failure demotion: the next routing decision must not
        wait for the liveness sweep to notice a dead RM. (The state
        store itself refuses to overwrite an administrative DEREGISTER —
        the atomicity lives under its lock, not here.)"""
        with self._lock:
            self._proxies.pop(sc_id, None)
        if self.store.subcluster_heartbeat(sc_id, SC_LOST):
            log.warning("subcluster %s marked LOST after call failure",
                        sc_id)

    # ------------------------------------------------------------ liveness

    def _liveness_loop(self) -> None:
        interval = self.config.get_time_seconds(
            "yarn.federation.liveness-interval", 2.0)
        while not self._stop_event.wait(interval):
            for sc_id in list(self.store.subclusters()):
                sc = self.store.subclusters().get(sc_id)
                if sc is None or sc["state"] == SC_DEREGISTERED:
                    continue
                try:
                    self.rm_proxy(sc_id).get_service_status()
                    self.store.subcluster_heartbeat(sc_id, SC_ACTIVE)
                except (OSError, IOError):
                    log.warning("subcluster %s unreachable", sc_id)
                    with self._lock:
                        self._proxies.pop(sc_id, None)
                    self.store.subcluster_heartbeat(sc_id, SC_LOST)
