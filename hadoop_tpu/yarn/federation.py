"""YARN federation: state store + Router over many subclusters.

Counterparts: hadoop-yarn-server-common federation (FederationStateStore
— subcluster registry + home-subcluster table, ref:
FederationStateStoreFacade.java; policies ref:
federation/policies/router/*Policy.java) and hadoop-yarn-server-router
(Router.java — the client-facing ApplicationClientProtocol that routes
each app to its home subcluster; ref:
clientrm/FederationClientInterceptor.java).

Model: every application gets a *home subcluster* chosen at
``get_new_application`` time by the routing policy; every subsequent
call for that app (submit/report/kill) follows the home mapping, and
aggregate reads (list/metrics/nodes) fan out over all ACTIVE
subclusters — the same shape as the reference's interceptor chain.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import Client, Server, get_proxy
from hadoop_tpu.service import AbstractService
from hadoop_tpu.util.misc import Daemon, parse_addr_list
from hadoop_tpu.yarn.records import ApplicationId

log = logging.getLogger(__name__)

SC_ACTIVE = "ACTIVE"
SC_LOST = "LOST"
SC_DEREGISTERED = "DEREGISTERED"


class FederationStateStore:
    """Subcluster registry + app→home-subcluster table, file-backed the
    way the RM's FileRMStateStore is (ref: FederationStateStore.java;
    the reference ships ZK/SQL/in-memory impls)."""

    def __init__(self, store_path: Optional[str] = None):
        self._path = store_path
        self._subclusters: Dict[str, Dict] = {}
        self._homes: Dict[str, str] = {}       # app_id str → subcluster id
        self._lock = threading.Lock()
        if store_path and os.path.exists(store_path):
            with open(store_path) as f:
                data = json.load(f)
            self._subclusters = data.get("subclusters", {})
            self._homes = data.get("homes", {})

    def _save_locked(self) -> None:
        if not self._path:
            return
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"subclusters": self._subclusters,
                       "homes": self._homes}, f)
        os.replace(tmp, self._path)

    def register_subcluster(self, sc_id: str, rm_addr: str) -> None:
        with self._lock:
            self._subclusters[sc_id] = {
                "addr": rm_addr, "state": SC_ACTIVE,
                "last_heartbeat": time.time()}
            self._save_locked()

    def deregister_subcluster(self, sc_id: str) -> bool:
        with self._lock:
            sc = self._subclusters.get(sc_id)
            if sc is None:
                return False
            sc["state"] = SC_DEREGISTERED
            self._save_locked()
            return True

    def subcluster_heartbeat(self, sc_id: str, state: str = SC_ACTIVE
                             ) -> None:
        with self._lock:
            sc = self._subclusters.get(sc_id)
            if sc is not None:
                sc["state"] = state
                sc["last_heartbeat"] = time.time()
                self._save_locked()

    def subclusters(self, active_only: bool = False) -> Dict[str, Dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._subclusters.items()
                    if not active_only or v["state"] == SC_ACTIVE}

    def set_home(self, app_id: str, sc_id: str) -> None:
        with self._lock:
            self._homes[app_id] = sc_id
            self._save_locked()

    def home_of(self, app_id: str) -> Optional[str]:
        with self._lock:
            return self._homes.get(app_id)


class _RouterClientProtocol:
    """The router's ApplicationClientProtocol face (ref:
    FederationClientInterceptor.java)."""

    def __init__(self, router: "YarnRouter"):
        self.router = router

    def get_new_application(self) -> Dict:
        sc_id = self.router.choose_subcluster()
        out = self.router.rm_proxy(sc_id).get_new_application()
        app_id = str(ApplicationId.from_wire(out["app_id"]))
        self.router.store.set_home(app_id, sc_id)
        return out

    def submit_application(self, ctx_wire: Dict) -> Dict:
        app_id = str(ApplicationId.from_wire(ctx_wire["id"]))
        sc_id = self.router.home_or_raise(app_id)
        return self.router.rm_proxy(sc_id).submit_application(ctx_wire)

    def get_application_report(self, app_id_wire: Dict) -> Dict:
        app_id = str(ApplicationId.from_wire(app_id_wire))
        sc_id = self.router.home_or_raise(app_id)
        return self.router.rm_proxy(sc_id).get_application_report(
            app_id_wire)

    def kill_application(self, app_id_wire: Dict) -> bool:
        app_id = str(ApplicationId.from_wire(app_id_wire))
        sc_id = self.router.home_or_raise(app_id)
        return self.router.rm_proxy(sc_id).kill_application(app_id_wire)

    def list_applications(self) -> List[Dict]:
        out: List[Dict] = []
        for sc_id in self.router.store.subclusters(active_only=True):
            try:
                out.extend(self.router.rm_proxy(sc_id).list_applications())
            except (OSError, IOError) as e:
                log.warning("list_applications on %s failed: %s", sc_id, e)
        return out

    def get_cluster_metrics(self) -> Dict:
        agg = {"num_node_managers": 0, "apps": 0, "subclusters": 0,
               "total_resource": {"m": 0, "v": 0, "c": 0}}
        for sc_id in self.router.store.subclusters(active_only=True):
            try:
                m = self.router.rm_proxy(sc_id).get_cluster_metrics()
            except (OSError, IOError):
                continue
            agg["subclusters"] += 1
            agg["num_node_managers"] += m.get("num_node_managers", 0)
            agg["apps"] += m.get("apps", 0)
            tr = m.get("total_resource", {})
            for k in ("m", "v", "c"):
                agg["total_resource"][k] += tr.get(k, 0)
        return agg

    def get_nodes(self) -> List[Dict]:
        out: List[Dict] = []
        for sc_id in self.router.store.subclusters(active_only=True):
            try:
                for n in self.router.rm_proxy(sc_id).get_nodes():
                    n["subcluster"] = sc_id
                    out.append(n)
            except (OSError, IOError):
                continue
        return out

    def get_service_status(self) -> Dict:
        return {"state": "active", "role": "router"}


class _RouterAdminProtocol:
    """Ref: router RouterAdminProtocol / FederationStateStore admin."""

    def __init__(self, router: "YarnRouter"):
        self.router = router

    def register_subcluster(self, sc_id: str, rm_addr: str) -> bool:
        self.router.store.register_subcluster(sc_id, rm_addr)
        return True

    def deregister_subcluster(self, sc_id: str) -> bool:
        return self.router.store.deregister_subcluster(sc_id)

    def list_subclusters(self) -> Dict[str, Dict]:
        return self.router.store.subclusters()


class YarnRouter(AbstractService):
    """Client-facing router over federated RMs (ref: router/Router.java
    :82 — a pipeline of interceptors in front of many subclusters)."""

    def __init__(self, conf: Configuration,
                 state_dir: Optional[str] = None):
        super().__init__("YarnRouter")
        self.state_dir = state_dir or conf.get(
            "yarn.federation.state-store.dir", "/tmp/htpu-yarn-router")
        self.store = FederationStateStore(
            os.path.join(self.state_dir, "federation.json"))
        self.policy = conf.get("yarn.federation.policy", "load")
        self._proxies: Dict[str, object] = {}
        self._client: Optional[Client] = None
        self._rr = 0
        self._lock = threading.Lock()
        self.rpc: Optional[Server] = None
        self._stop_event = threading.Event()

    def service_init(self, conf: Configuration) -> None:
        # Static registration: yarn.federation.subcluster.<id> = host:port
        for key, value in conf.to_dict().items():
            if key.startswith("yarn.federation.subcluster."):
                sc_id = key[len("yarn.federation.subcluster."):]
                self.store.register_subcluster(sc_id, value)
        self._client = Client(conf)
        self.rpc = Server(conf, bind=("127.0.0.1", conf.get_int(
            "yarn.federation.router.port", 0)), num_handlers=8,
            name="yarn-router")
        self.rpc.register_protocol("ClientRMProtocol",
                                   _RouterClientProtocol(self))
        self.rpc.register_protocol("RouterAdminProtocol",
                                   _RouterAdminProtocol(self))

    def service_start(self) -> None:
        self.rpc.start()
        Daemon(self._liveness_loop, "yarn-router-liveness").start()
        log.info("YARN Router on :%d (%d subclusters, policy=%s)",
                 self.rpc.port, len(self.store.subclusters()), self.policy)

    def service_stop(self) -> None:
        self._stop_event.set()
        if self.rpc:
            self.rpc.stop()
        if self._client:
            self._client.stop()

    @property
    def port(self) -> int:
        return self.rpc.port

    # ------------------------------------------------------------- routing

    def rm_proxy(self, sc_id: str):
        with self._lock:
            p = self._proxies.get(sc_id)
            if p is None:
                sc = self.store.subclusters().get(sc_id)
                if sc is None:
                    raise ValueError(f"unknown subcluster {sc_id!r}")
                addr = parse_addr_list(sc["addr"])[0]
                p = get_proxy("ClientRMProtocol", addr,
                              client=self._client)
                self._proxies[sc_id] = p
            return p

    def home_or_raise(self, app_id: str) -> str:
        sc_id = self.store.home_of(app_id)
        if sc_id is None:
            raise ValueError(f"no home subcluster for {app_id}")
        return sc_id

    def choose_subcluster(self) -> str:
        """Routing policy (ref: LoadBasedRouterPolicy /
        UniformRandomRouterPolicy)."""
        active = sorted(self.store.subclusters(active_only=True))
        if not active:
            raise IOError("no ACTIVE subclusters")
        if self.policy == "round-robin":
            with self._lock:
                sc = active[self._rr % len(active)]
                self._rr += 1
            return sc
        # load-based: fewest running apps wins
        best, best_load = active[0], float("inf")
        for sc_id in active:
            try:
                m = self.rm_proxy(sc_id).get_cluster_metrics()
                load = m.get("apps", 0)
            except (OSError, IOError):
                continue
            if load < best_load:
                best, best_load = sc_id, load
        return best

    # ------------------------------------------------------------ liveness

    def _liveness_loop(self) -> None:
        interval = self.config.get_time_seconds(
            "yarn.federation.liveness-interval", 2.0)
        while not self._stop_event.wait(interval):
            for sc_id in list(self.store.subclusters()):
                sc = self.store.subclusters().get(sc_id)
                if sc is None or sc["state"] == SC_DEREGISTERED:
                    continue
                try:
                    self.rm_proxy(sc_id).get_service_status()
                    self.store.subcluster_heartbeat(sc_id, SC_ACTIVE)
                except (OSError, IOError):
                    log.warning("subcluster %s unreachable", sc_id)
                    with self._lock:
                        self._proxies.pop(sc_id, None)
                    self.store.subcluster_heartbeat(sc_id, SC_LOST)
