"""NodeAgent: the per-host daemon that runs containers.

Parity with the reference NodeManager (ref: nodemanager/NodeManager.java
(1,055 LoC), containermanager/ContainerManagerImpl.java:933 startContainers,
localizer/ (resource localization), launcher/ContainerLaunch.java:103/:194,
DefaultContainerExecutor, monitor/ContainersMonitorImpl.java:60,
logaggregation/LogAggregationService.java): registers with the RM, runs
containers as real OS processes in per-container work dirs with localized
resources and captured stdout/stderr, monitors them, reports exits on the RM
heartbeat, executes cleanup commands, and aggregates finished containers'
logs to the DFS.

TPU-first: the node advertises ``tpu_chips`` and assigns each container an
exclusive chip set via ``HTPU_TPU_CHIPS`` (comma-separated indices) — the
device-plugin role (ref: resourceplugin/ GPU/FPGA plugins), expressed as env
isolation because TPU chips bind per-process via runtime env.

The reference's setuid C container-executor (main.c:656) maps to the
``executor`` seam: DefaultExecutor (same-uid subprocess) here; the native
launcher lands with hadoop_tpu/native.
"""

from __future__ import annotations

import logging
import os
import shutil
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import Client, Server, get_proxy
from hadoop_tpu.service import AbstractService
from hadoop_tpu.util.misc import Daemon
from hadoop_tpu.yarn.records import (Container, ContainerId,
                                     ContainerLaunchContext, ContainerStatus,
                                     NodeId, Resource)

log = logging.getLogger(__name__)


class ContainerExecutor:
    """Seam for container launch (ref: server/nodemanager/ContainerExecutor
    .java; LinuxContainerExecutor.java:519 launchContainer is the native
    variant)."""

    def launch(self, workdir: str, commands: List[str],
               env: Dict[str, str]) -> subprocess.Popen:
        raise NotImplementedError

    def signal(self, proc: subprocess.Popen, sig: int) -> None:
        raise NotImplementedError


class DefaultExecutor(ContainerExecutor):
    """Same-uid subprocess with its own process group.
    Ref: DefaultContainerExecutor.java."""

    def launch(self, workdir: str, commands: List[str],
               env: Dict[str, str]) -> subprocess.Popen:
        full_env = dict(os.environ)
        full_env.update(env)
        out = open(os.path.join(workdir, "stdout"), "wb")
        err = open(os.path.join(workdir, "stderr"), "wb")
        return subprocess.Popen(
            commands, cwd=workdir, env=full_env, stdout=out, stderr=err,
            start_new_session=True)  # own pgid → kill the whole tree

    def signal(self, proc: subprocess.Popen, sig: int) -> None:
        try:
            os.killpg(os.getpgid(proc.pid), sig)
        except (ProcessLookupError, PermissionError):
            pass


class NativeExecutor(ContainerExecutor):
    """Launch through the C++ htpu-container-executor binary: the
    container runs in its own session with rlimits (and a cgroup when
    configured) applied BEFORE user code starts — the reference's
    LinuxContainerExecutor.java:519 → native launch_container_as_user
    chain, with the setuid arm active only when the binary runs as root.
    Selected via conf ``yarn.nodemanager.container-executor.class =
    native`` when the binary is built."""

    def __init__(self, mem_limit_mb: int = 0, nofile: int = 8192,
                 cgroup_root: str = ""):
        import hadoop_tpu.native as _nat
        binary = os.path.join(os.path.dirname(
            os.path.abspath(_nat.__file__)), "htpu-container-executor")
        if not os.path.exists(binary):
            _nat._build()
        if not os.path.exists(binary):
            raise FileNotFoundError(
                "htpu-container-executor not built (no toolchain?)")
        self.binary = binary
        self.mem_limit_mb = mem_limit_mb
        self.nofile = nofile
        self.cgroup_root = cgroup_root

    def launch(self, workdir: str, commands: List[str],
               env: Dict[str, str]) -> subprocess.Popen:
        full_env = dict(os.environ)
        full_env.update(env)
        cgroup = "-"
        if self.cgroup_root:
            cgroup = os.path.join(self.cgroup_root,
                                  os.path.basename(workdir))
        argv = [self.binary, workdir,
                os.path.join(workdir, "stdout"),
                os.path.join(workdir, "stderr"),
                str(self.mem_limit_mb), str(self.nofile), cgroup,
                "--"] + commands
        return subprocess.Popen(argv, cwd=workdir, env=full_env,
                                stdout=subprocess.DEVNULL,
                                start_new_session=True)

    def signal(self, proc: subprocess.Popen, sig: int) -> None:
        try:
            os.killpg(os.getpgid(proc.pid), sig)
        except (ProcessLookupError, PermissionError):
            pass


class _KilledBeforeLaunch(Exception):
    """Internal: stop_container won the race against the launch step."""


class _RunningContainer:
    def __init__(self, container: Container, ctx: ContainerLaunchContext,
                 workdir: str, chips: List[int]):
        self.container = container
        self.ctx = ctx
        self.workdir = workdir
        self.chips = chips
        self.proc: Optional[subprocess.Popen] = None
        self.state = "NEW"
        self.exit_code: Optional[int] = None
        self.diagnostics = ""
        self.start_ts = time.time()
        self.published_volumes = []
        # closes the kill-during-localization hole: _kill and the launch
        # step synchronize on this, so a stop that lands before the
        # process exists prevents the launch instead of no-oping (the
        # process would otherwise run forever unmanaged)
        self.killed = False
        self.lock = threading.Lock()


class ContainerManagerProtocol:
    """NM's RPC surface (ref: ContainerManagerImpl.java:933 startContainers;
    ClientAMProtocol-ish status calls)."""

    def __init__(self, nm: "NodeAgent"):
        self.nm = nm

    def start_container(self, container_wire: Dict, ctx_wire: Dict) -> Dict:
        container = Container.from_wire(container_wire)
        ctx = ContainerLaunchContext.from_wire(ctx_wire)
        self.nm.start_container(container, ctx)
        return {"ok": True}

    def stop_container(self, container_id_wire: Dict) -> bool:
        self.nm.stop_container(ContainerId.from_wire(container_id_wire))
        return True

    def get_container_status(self, container_id_wire: Dict) -> Optional[Dict]:
        cid = ContainerId.from_wire(container_id_wire)
        rc = self.nm.containers.get(cid)
        if rc is None:
            return None
        return ContainerStatus(cid, rc.state, rc.exit_code
                               if rc.exit_code is not None else -1000,
                               rc.diagnostics).to_wire()


class NodeAgent(AbstractService):
    def __init__(self, conf: Configuration, rm_addr: Tuple[str, int],
                 work_root: Optional[str] = None,
                 executor: Optional[ContainerExecutor] = None):
        super().__init__("NodeAgent")
        self.rm_addr = rm_addr
        self.work_root = work_root or conf.get(
            "yarn.nodemanager.local-dirs", "/tmp/htpu-nm")
        if executor is None and conf.get(
                "yarn.nodemanager.container-executor.class", "") == "native":
            executor = NativeExecutor(
                mem_limit_mb=conf.get_int(
                    "yarn.nodemanager.container.memory-limit-mb", 0),
                cgroup_root=conf.get(
                    "yarn.nodemanager.cgroups.root", ""))
        self.executor = executor or DefaultExecutor()
        self.containers: Dict[ContainerId, _RunningContainer] = {}
        self._lock = threading.Lock()
        self._completed_unreported: List[ContainerStatus] = []
        self._stop_event = threading.Event()
        self._client: Optional[Client] = None
        self.rpc: Optional[Server] = None
        self._chip_pool: List[int] = []
        self.aux_services: List = []

    # ------------------------------------------------------------- lifecycle

    def service_init(self, conf: Configuration) -> None:
        os.makedirs(self.work_root, exist_ok=True)
        # Auxiliary services (ref: containermanager/AuxServices.java — how
        # ShuffleHandler rides the NM): conf lists module:Class entries; each
        # gets start()/stop() and injects env into every container.
        self.aux_services = []
        for ref in conf.get_list("yarn.nodemanager.aux-services"):
            mod, _, name = ref.partition(":")
            import importlib
            cls = getattr(importlib.import_module(mod), name)
            self.aux_services.append(cls(conf, self.work_root))
        self.resource = Resource(
            conf.get_int("yarn.nodemanager.resource.memory-mb", 8192),
            conf.get_int("yarn.nodemanager.resource.cpu-vcores", 8),
            conf.get_int("yarn.nodemanager.resource.tpu-chips", 0))
        self._chip_pool = list(range(self.resource.tpu_chips))
        self.heartbeat_interval = conf.get_time_seconds(
            "yarn.nodemanager.heartbeat.interval", 1.0)
        self._client = Client(conf)
        bind_host = conf.get("yarn.nodemanager.bind-host", "127.0.0.1")
        self.rpc = Server(conf, bind=(bind_host, 0), num_handlers=4,
                          name="nm")
        self.rpc.register_protocol("ContainerManagerProtocol",
                                   ContainerManagerProtocol(self))
        self.host = bind_host
        # ATSv2-style per-app timeline collectors (ref:
        # PerNodeTimelineCollectorsAuxService): spun up with an app's
        # first container here, stopped when the RM reports the app
        # finished (heartbeat response).
        # CSI adaptor (ref: yarn-csi CsiAdaptorServices on the NM)
        from hadoop_tpu.yarn.csi import CsiAdaptor
        try:
            self.csi = CsiAdaptor()
        except Exception:  # noqa: BLE001 — volume support is optional
            self.csi = None
        self.timeline = None
        if conf.get_bool("yarn.timeline-service.enabled", False):
            from hadoop_tpu.conf.keys import YARN_TIMELINE_STORE_DIR
            from hadoop_tpu.yarn.timeline import TimelineCollectorManager
            self.timeline = TimelineCollectorManager(
                conf.get(YARN_TIMELINE_STORE_DIR,
                         os.path.join(self.work_root, "timeline")),
                backend=conf.get(
                    "yarn.timeline-service.store.backend", "auto"))

    def service_start(self) -> None:
        for aux in self.aux_services:
            aux.start()
        self.rpc.start()
        self.node_id = NodeId(self.host, self.rpc.port)
        self._rm = get_proxy("ResourceTrackerProtocol", self.rm_addr,
                             client=self._client)
        Daemon(self._heartbeat_loop, f"nm-{self.rpc.port}").start()
        log.info("NodeAgent %s up (%r)", self.node_id, self.resource)

    def service_stop(self) -> None:
        self._stop_event.set()
        with self._lock:
            running = list(self.containers.values())
        for rc in running:
            self._kill(rc)
        for aux in self.aux_services:
            try:
                aux.stop()
            except Exception as e:  # noqa: BLE001 — aux is plugin code
                log.debug("aux service stop failed: %s", e)
        if self.timeline is not None:
            self.timeline.stop_all()
        if self.rpc:
            self.rpc.stop()
        if self._client:
            self._client.stop()

    @property
    def nm_address(self) -> str:
        return f"{self.host}:{self.rpc.port}"

    # ------------------------------------------------------------ containers

    def start_container(self, container: Container,
                        ctx: ContainerLaunchContext) -> None:
        cid = container.container_id
        with self._lock:
            if cid in self.containers:
                return  # idempotent retry
            chips = self._take_chips(container.resource.tpu_chips)
            workdir = os.path.join(self.work_root, str(cid))
            rc = _RunningContainer(container, ctx, workdir, chips)
            self.containers[cid] = rc
        if self.timeline is not None:
            self.timeline.collector_for(str(cid.app_id)).put_entity(
                "YARN_CONTAINER", str(cid), "CREATED",
                node=str(self.node_id) if hasattr(self, "node_id")
                else "", memory_mb=container.resource.memory_mb)
        Daemon(self._launch, f"launch-{cid}", args=(rc,)).start()

    def _take_chips(self, n: int) -> List[int]:
        if n > len(self._chip_pool):
            # refuse rather than under-allocate: a TPU job granted fewer
            # chips than its resource ask (or zero, which disables the
            # accelerator runtime entirely) would run wrong silently
            raise IOError(f"insufficient TPU chips: want {n}, "
                          f"have {len(self._chip_pool)}")
        chips = self._chip_pool[:n]
        del self._chip_pool[:n]
        return chips

    def _launch(self, rc: _RunningContainer) -> None:
        """Localize → launch → wait. Ref: ContainerLaunch.call:194."""
        cid = rc.container.container_id
        try:
            os.makedirs(rc.workdir, exist_ok=True)
            rc.state = "LOCALIZING"
            self._localize(rc)
            self._publish_volumes(rc)
            env = dict(rc.ctx.env)
            for aux in self.aux_services:
                env.update(aux.container_env())
                if rc.ctx.service_data and hasattr(aux, "initialize_app"):
                    # per-app payloads for aux services (ref:
                    # AuxServices.initializeApplication — the shuffle
                    # service learns the job's token secret this way);
                    # idempotent, so per-container delivery is fine
                    try:
                        aux.initialize_app(rc.ctx.service_data)
                    except Exception as e:  # noqa: BLE001 — advisory
                        log.warning("aux service_data init failed: %s", e)
            env["HTPU_CONTAINER_ID"] = str(cid)
            env["HTPU_WORK_DIR"] = rc.workdir
            if rc.chips:
                env["HTPU_TPU_CHIPS"] = ",".join(map(str, rc.chips))
            else:
                # Device isolation both ways: a container that was not
                # granted chips must not attach to the host's TPU runtime
                # (the accelerator plugin initializes via sitecustomize and
                # costs ~2s of process startup — the dominant term in task
                # launch latency). Clearing the trigger var disables it;
                # empty string is falsy for the plugin's gate.
                env["PALLAS_AXON_POOL_IPS"] = ""
            with rc.lock:
                if rc.killed:
                    raise _KilledBeforeLaunch()
                rc.proc = self.executor.launch(rc.workdir,
                                               rc.ctx.commands, env)
            rc.state = "RUNNING"
            exit_code = rc.proc.wait()
            rc.exit_code = exit_code
            rc.state = "COMPLETE"
            if exit_code != 0:
                rc.diagnostics = self._tail_stderr(rc)
        except _KilledBeforeLaunch:
            rc.state = "COMPLETE"
            rc.exit_code = -105  # the reference's KILLED_BY_RESOURCEMANAGER
            rc.diagnostics = "killed before launch"
        except Exception as e:  # noqa: BLE001
            rc.state = "COMPLETE"
            rc.exit_code = -1001
            rc.diagnostics = f"launch failed: {e}"
            log.warning("Container %s launch failed: %s", cid, e)
        finally:
            # volumes must unmount BEFORE the workdir is ever rmtree'd
            # (a live fuse mount under rmtree would walk the DFS)
            self._unpublish_volumes(rc)
            with self._lock:
                self._chip_pool.extend(rc.chips)
                self._completed_unreported.append(ContainerStatus(
                    cid, "COMPLETE", rc.exit_code, rc.diagnostics))
            if self.timeline is not None:
                # Publish only through a LIVE collector, atomically — a
                # straggler finishing after the app's collector stopped
                # must be dropped, not resurrect it (put_if_active holds
                # the manager lock across check+put; the old
                # has_collector/collector_for pair raced the linger
                # timer into re-creating a stopped collector).
                # resource-time metrics ride the FINISHED event so the
                # ATSv2 reader can aggregate flow-run cost.
                dur = max(0.0, time.time() - rc.start_ts)
                self.timeline.put_if_active(
                    str(cid.app_id),
                    "YARN_CONTAINER", str(cid), "FINISHED",
                    exit_code=rc.exit_code,
                    duration_s=round(dur, 3),
                    memory_mb=rc.container.resource.memory_mb,
                    vcores=rc.container.resource.vcores,
                    mb_seconds=round(
                        dur * rc.container.resource.memory_mb, 1),
                    vcore_seconds=round(
                        dur * rc.container.resource.vcores, 3))

    def _publish_volumes(self, rc: _RunningContainer) -> None:
        """CSI volume publish under the workdir (ref: yarn-csi's
        ContainerVolumePublisher running before ContainerLaunch)."""
        vols = getattr(rc.ctx, "volumes", None) or []
        if not vols:
            return
        if self.csi is None:
            raise IOError("container requests volumes but this NM has "
                          "no CSI adaptor")
        published = []
        try:
            for v in vols:
                target = os.path.join(rc.workdir,
                                      v.get("target", "volume"))
                self.csi.node_publish_volume(v["driver"], v["id"], target,
                                             v.get("options"))
                published.append((v, target))
        except Exception:
            for v, target in published:
                try:
                    self.csi.node_unpublish_volume(v["driver"], v["id"],
                                                   target)
                except (OSError, IOError) as e:
                    log.debug("rollback unpublish failed: %s", e)
            raise
        rc.published_volumes = published

    def _unpublish_volumes(self, rc: _RunningContainer) -> None:
        for v, target in getattr(rc, "published_volumes", None) or []:
            try:
                self.csi.node_unpublish_volume(v["driver"], v["id"],
                                               target)
            except Exception as e:  # noqa: BLE001
                log.warning("unpublish of %s failed: %s", v.get("id"), e)
        rc.published_volumes = []

    def _localize(self, rc: _RunningContainer) -> None:
        """Fetch DFS resources into the work dir.
        Ref: containermanager/localizer/ResourceLocalizationService."""
        if not rc.ctx.local_resources:
            return
        from hadoop_tpu.fs import FileSystem
        for name, uri in rc.ctx.local_resources.items():
            dst = os.path.join(rc.workdir, name)
            if uri.startswith("file:") or uri.startswith("/"):
                src = uri[len("file://"):] if uri.startswith("file://") \
                    else uri
                shutil.copyfile(src, dst)
            else:
                fs = FileSystem.get(uri, self.config)
                from hadoop_tpu.fs.filesystem import Path
                with open(dst, "wb") as f:
                    f.write(fs.read_all(Path(uri).path))
                fs.close()

    def _tail_stderr(self, rc: _RunningContainer, n: int = 2048) -> str:
        try:
            with open(os.path.join(rc.workdir, "stderr"), "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - n))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    def stop_container(self, cid: ContainerId) -> None:
        with self._lock:
            rc = self.containers.get(cid)
        if rc is not None:
            self._kill(rc)

    def _kill(self, rc: _RunningContainer) -> None:
        """SIGTERM, grace, SIGKILL. Ref: ContainerLaunch.cleanupContainer."""
        with rc.lock:
            rc.killed = True  # a not-yet-launched process must never start
            if rc.proc is None or rc.proc.poll() is not None:
                return
        self.executor.signal(rc.proc, signal.SIGTERM)

        def force_kill():
            try:
                rc.proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                self.executor.signal(rc.proc, signal.SIGKILL)
        Daemon(force_kill, "container-killer").start()

    # -------------------------------------------------------------- RM link

    def _heartbeat_loop(self) -> None:
        registered = False
        while not self._stop_event.is_set():
            statuses: List[ContainerStatus] = []
            try:
                if not registered:
                    # report live containers so a restarted RM re-adopts
                    # them (work-preserving restart; ref:
                    # NMContainerStatus in RegisterNodeManagerRequest)
                    with self._lock:
                        live = [rc.container.to_wire()
                                for rc in self.containers.values()
                                if rc.state in ("NEW", "LOCALIZING",
                                                "RUNNING")]
                    resp0 = self._rm.register_node_manager(
                        self.node_id.to_wire(), self.resource.to_wire(),
                        self.nm_address, live)
                    for cw in (resp0 or {}).get("cleanup", []):
                        self.stop_container(ContainerId.from_wire(cw))
                    registered = True
                with self._lock:
                    statuses = self._completed_unreported
                    self._completed_unreported = []
                resp = self._rm.node_heartbeat(
                    self.node_id.to_wire(), [s.to_wire() for s in statuses])
                if resp.get("action") == "reregister":
                    registered = False
                    continue
                for cw in resp.get("cleanup", []):
                    cid = ContainerId.from_wire(cw)
                    self.stop_container(cid)
                    with self._lock:
                        rc = self.containers.pop(cid, None)
                    if rc is not None and os.path.isdir(rc.workdir):
                        shutil.rmtree(rc.workdir, ignore_errors=True)
                if self.timeline is not None:
                    for app_id in resp.get("finished_apps", []):
                        self.timeline.stop_collector(app_id)
            except Exception as e:  # noqa: BLE001 — survive RM bounces
                if statuses:
                    with self._lock:  # don't lose exit reports
                        self._completed_unreported = (
                            statuses + self._completed_unreported)
                log.debug("NM heartbeat failed (%s); retrying", e)
                registered = False
                self._rm = get_proxy("ResourceTrackerProtocol", self.rm_addr,
                                     client=self._client)
            self._stop_event.wait(self.heartbeat_interval)
