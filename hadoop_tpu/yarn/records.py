"""YARN protocol records.

Parity with yarn-api's record types (ref: hadoop-yarn-api
ApplicationId.java, Resource.java, Container.java,
ContainerLaunchContext.java, ApplicationSubmissionContext.java,
NodeReport.java; protos yarn_protos.proto). TPU-first deviation: ``Resource``
carries ``tpu_chips`` as a first-class dimension next to memory/vcores — the
role GPUs play via the reference's pluggable resource types
(ref: nodemanager resourceplugin/, resource-types.xml mechanism).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional


class Resource:
    __slots__ = ("memory_mb", "vcores", "tpu_chips")

    def __init__(self, memory_mb: int = 0, vcores: int = 0, tpu_chips: int = 0):
        self.memory_mb = memory_mb
        self.vcores = vcores
        self.tpu_chips = tpu_chips

    def fits_in(self, other: "Resource") -> bool:
        return (self.memory_mb <= other.memory_mb
                and self.vcores <= other.vcores
                and self.tpu_chips <= other.tpu_chips)

    def add(self, other: "Resource") -> "Resource":
        return Resource(self.memory_mb + other.memory_mb,
                        self.vcores + other.vcores,
                        self.tpu_chips + other.tpu_chips)

    def subtract(self, other: "Resource") -> "Resource":
        return Resource(self.memory_mb - other.memory_mb,
                        self.vcores - other.vcores,
                        self.tpu_chips - other.tpu_chips)

    def dominant_share(self, total: "Resource") -> float:
        """Dominant resource fairness share (ref: DominantResourceCalculator)."""
        shares = []
        if total.memory_mb:
            shares.append(self.memory_mb / total.memory_mb)
        if total.vcores:
            shares.append(self.vcores / total.vcores)
        if total.tpu_chips:
            shares.append(self.tpu_chips / total.tpu_chips)
        return max(shares) if shares else 0.0

    def is_empty(self) -> bool:
        return self.memory_mb <= 0 and self.vcores <= 0 and self.tpu_chips <= 0

    def to_wire(self) -> Dict:
        return {"m": self.memory_mb, "v": self.vcores, "t": self.tpu_chips}

    @classmethod
    def from_wire(cls, d: Dict) -> "Resource":
        return cls(d.get("m", 0), d.get("v", 0), d.get("t", 0))

    def __eq__(self, o):
        return (isinstance(o, Resource) and o.memory_mb == self.memory_mb
                and o.vcores == self.vcores and o.tpu_chips == self.tpu_chips)

    def __repr__(self):
        s = f"<mem {self.memory_mb}MB, {self.vcores} cores"
        if self.tpu_chips:
            s += f", {self.tpu_chips} tpu"
        return s + ">"


class ApplicationId:
    """app_<cluster_ts>_<seq>. Ref: ApplicationId.java."""

    __slots__ = ("cluster_ts", "seq")

    def __init__(self, cluster_ts: int, seq: int):
        self.cluster_ts = cluster_ts
        self.seq = seq

    def __str__(self):
        return f"application_{self.cluster_ts}_{self.seq:04d}"

    def to_wire(self) -> Dict:
        return {"ts": self.cluster_ts, "s": self.seq}

    @classmethod
    def from_wire(cls, d: Dict) -> "ApplicationId":
        return cls(d["ts"], d["s"])

    @classmethod
    def parse(cls, s: str) -> "ApplicationId":
        _, ts, seq = s.split("_")
        return cls(int(ts), int(seq))

    def __eq__(self, o):
        return isinstance(o, ApplicationId) and str(o) == str(self)

    def __hash__(self):
        return hash((self.cluster_ts, self.seq))


class ContainerId:
    """container_<app>_<attempt>_<seq>. Ref: ContainerId.java."""

    __slots__ = ("app_id", "attempt_no", "seq")

    def __init__(self, app_id: ApplicationId, attempt_no: int, seq: int):
        self.app_id = app_id
        self.attempt_no = attempt_no
        self.seq = seq

    def __str__(self):
        return (f"container_{self.app_id.cluster_ts}_{self.app_id.seq:04d}"
                f"_{self.attempt_no:02d}_{self.seq:06d}")

    def to_wire(self) -> Dict:
        return {"a": self.app_id.to_wire(), "n": self.attempt_no, "s": self.seq}

    @classmethod
    def from_wire(cls, d: Dict) -> "ContainerId":
        return cls(ApplicationId.from_wire(d["a"]), d["n"], d["s"])

    def __eq__(self, o):
        return isinstance(o, ContainerId) and str(o) == str(self)

    def __hash__(self):
        return hash(str(self))


class NodeId:
    __slots__ = ("host", "port")

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port

    def __str__(self):
        return f"{self.host}:{self.port}"

    def to_wire(self) -> Dict:
        return {"h": self.host, "p": self.port}

    @classmethod
    def from_wire(cls, d: Dict) -> "NodeId":
        return cls(d["h"], d["p"])

    def __eq__(self, o):
        return isinstance(o, NodeId) and str(o) == str(self)

    def __hash__(self):
        return hash(str(self))


class Container:
    """An allocation: id + node + resource (+ the NM address to launch at).
    Ref: Container.java."""

    __slots__ = ("container_id", "node_id", "resource", "nm_address",
                 "execution_type")

    def __init__(self, container_id: ContainerId, node_id: NodeId,
                 resource: Resource, nm_address: str = "",
                 execution_type: str = "GUARANTEED"):
        self.container_id = container_id
        self.node_id = node_id
        self.resource = resource
        self.nm_address = nm_address
        # ref: Container.getExecutionType — carried on the wire so
        # O-ness survives RM restart / work-preserving recovery.
        self.execution_type = execution_type

    def to_wire(self) -> Dict:
        return {"id": self.container_id.to_wire(),
                "n": self.node_id.to_wire(), "r": self.resource.to_wire(),
                "nm": self.nm_address, "x": self.execution_type}

    @classmethod
    def from_wire(cls, d: Dict) -> "Container":
        return cls(ContainerId.from_wire(d["id"]), NodeId.from_wire(d["n"]),
                   Resource.from_wire(d["r"]), d.get("nm", ""),
                   d.get("x", "GUARANTEED"))


class ContainerLaunchContext:
    """What to run: command argv, env, local resources (DFS paths to
    localize). Ref: ContainerLaunchContext.java."""

    __slots__ = ("commands", "env", "local_resources", "volumes",
                 "service_data")

    def __init__(self, commands: List[str],
                 env: Optional[Dict[str, str]] = None,
                 local_resources: Optional[Dict[str, str]] = None,
                 volumes: Optional[List[Dict]] = None,
                 service_data: Optional[Dict[str, str]] = None):
        self.commands = commands            # argv
        self.env = env or {}
        self.local_resources = local_resources or {}  # name -> dfs uri
        # CSI volumes published under the workdir before launch (ref:
        # the yarn-csi volume resources on a container request):
        # [{"driver": "htpufs", "id": "htpufs://h:p", "target": "data"}]
        self.volumes = volumes or []
        # Per-application payloads for NM auxiliary services, keyed by
        # service name (ref: ContainerLaunchContext.setServiceData —
        # how the MR client hands the shuffle service its job token)
        self.service_data = service_data or {}

    def to_wire(self) -> Dict:
        d = {"c": self.commands, "e": self.env,
             "lr": self.local_resources}
        if self.volumes:
            d["vol"] = self.volumes
        if self.service_data:
            d["sd"] = self.service_data
        return d

    @classmethod
    def from_wire(cls, d: Dict) -> "ContainerLaunchContext":
        return cls(d["c"], d.get("e", {}), d.get("lr", {}),
                   d.get("vol"), d.get("sd"))


class ApplicationSubmissionContext:
    """Ref: ApplicationSubmissionContext.java."""

    __slots__ = ("app_id", "name", "queue", "am_launch_context", "am_resource",
                 "max_attempts", "app_type", "in_process_am", "unmanaged")

    def __init__(self, app_id: ApplicationId, name: str,
                 am_launch_context: ContainerLaunchContext,
                 am_resource: Resource, queue: str = "default",
                 max_attempts: int = 2, app_type: str = "YARN",
                 in_process_am: bool = False, unmanaged: bool = False):
        self.app_id = app_id
        self.name = name
        self.queue = queue
        self.am_launch_context = am_launch_context
        self.am_resource = am_resource
        self.max_attempts = max_attempts
        self.app_type = app_type
        # Minicluster mode: run the AM as a thread in the submitter's process
        # (ref: MiniYARNCluster's unmanaged-AM-style testing shortcut).
        self.in_process_am = in_process_am
        # Unmanaged AM (ref: setUnmanagedAM + the
        # hadoop-yarn-applications-unmanaged-am-launcher tool): the RM
        # allocates NO AM container; an external process registers as
        # the attempt's master and drives allocate itself.
        self.unmanaged = unmanaged

    def to_wire(self) -> Dict:
        d = {"id": self.app_id.to_wire(), "nm": self.name, "q": self.queue,
             "lc": self.am_launch_context.to_wire(),
             "r": self.am_resource.to_wire(), "ma": self.max_attempts,
             "t": self.app_type, "ip": self.in_process_am}
        if self.unmanaged:
            d["um"] = True
        return d

    @classmethod
    def from_wire(cls, d: Dict) -> "ApplicationSubmissionContext":
        return cls(ApplicationId.from_wire(d["id"]), d["nm"],
                   ContainerLaunchContext.from_wire(d["lc"]),
                   Resource.from_wire(d["r"]), d.get("q", "default"),
                   d.get("ma", 2), d.get("t", "YARN"), d.get("ip", False),
                   d.get("um", False))


# Application / attempt / container externally-visible states
# (ref: YarnApplicationState, ContainerState enums).
class AppState:
    NEW = "NEW"
    SUBMITTED = "SUBMITTED"
    ACCEPTED = "ACCEPTED"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    KILLED = "KILLED"
    TERMINAL = (FINISHED, FAILED, KILLED)


class ContainerState:
    NEW = "NEW"
    LOCALIZING = "LOCALIZING"
    RUNNING = "RUNNING"
    COMPLETE = "COMPLETE"


class ContainerStatus:
    __slots__ = ("container_id", "state", "exit_code", "diagnostics")

    def __init__(self, container_id: ContainerId, state: str,
                 exit_code: int = -1000, diagnostics: str = ""):
        self.container_id = container_id
        self.state = state
        self.exit_code = exit_code
        self.diagnostics = diagnostics

    def to_wire(self) -> Dict:
        return {"id": self.container_id.to_wire(), "st": self.state,
                "ec": self.exit_code, "d": self.diagnostics}

    @classmethod
    def from_wire(cls, d: Dict) -> "ContainerStatus":
        return cls(ContainerId.from_wire(d["id"]), d["st"], d.get("ec", -1000),
                   d.get("d", ""))


class ApplicationReport:
    __slots__ = ("app_id", "name", "user", "queue", "state", "final_status",
                 "diagnostics", "tracking_url", "start_time", "finish_time",
                 "attempt_no")

    def __init__(self, app_id: ApplicationId, name: str, user: str,
                 queue: str, state: str, final_status: str = "",
                 diagnostics: str = "", tracking_url: str = "",
                 start_time: float = 0.0, finish_time: float = 0.0,
                 attempt_no: int = 0):
        self.app_id = app_id
        self.name = name
        self.user = user
        self.queue = queue
        self.state = state
        self.final_status = final_status
        self.diagnostics = diagnostics
        self.tracking_url = tracking_url
        self.start_time = start_time
        self.finish_time = finish_time
        self.attempt_no = attempt_no

    def to_wire(self) -> Dict:
        return {"id": self.app_id.to_wire(), "nm": self.name, "u": self.user,
                "q": self.queue, "st": self.state, "fs": self.final_status,
                "d": self.diagnostics, "tu": self.tracking_url,
                "t0": self.start_time, "t1": self.finish_time,
                "at": self.attempt_no}

    @classmethod
    def from_wire(cls, d: Dict) -> "ApplicationReport":
        return cls(ApplicationId.from_wire(d["id"]), d["nm"], d["u"], d["q"],
                   d["st"], d.get("fs", ""), d.get("d", ""), d.get("tu", ""),
                   d.get("t0", 0.0), d.get("t1", 0.0), d.get("at", 0))


class ResourceRequest:
    """AM asks: (priority, count, capability, locality).
    Ref: ResourceRequest.java."""

    EXEC_GUARANTEED = "GUARANTEED"
    EXEC_OPPORTUNISTIC = "OPPORTUNISTIC"

    __slots__ = ("priority", "num_containers", "capability", "host",
                 "node_label", "execution_type")

    def __init__(self, priority: int, num_containers: int,
                 capability: Resource, host: str = "*",
                 node_label: str = "",
                 execution_type: str = EXEC_GUARANTEED):
        self.priority = priority
        self.num_containers = num_containers
        self.capability = capability
        self.host = host
        # Partition label (ref: ResourceRequest.getNodeLabelExpression):
        # "" = the default (unlabeled) partition, exclusive semantics.
        self.node_label = node_label
        # ref: ExecutionTypeRequest — OPPORTUNISTIC containers may be
        # allocated past a node's guaranteed capacity and queue at the
        # NM (YARN-2882 distributed/opportunistic scheduling).
        self.execution_type = execution_type

    def to_wire(self) -> Dict:
        return {"p": self.priority, "n": self.num_containers,
                "c": self.capability.to_wire(), "h": self.host,
                "l": self.node_label, "x": self.execution_type}

    @classmethod
    def from_wire(cls, d: Dict) -> "ResourceRequest":
        return cls(d["p"], d["n"], Resource.from_wire(d["c"]),
                   d.get("h", "*"), d.get("l", ""),
                   d.get("x", cls.EXEC_GUARANTEED))
