"""ResourceManager: app/attempt state machines, RPC services, liveness.

Parity with the reference RM (ref: resourcemanager/ResourceManager.java
(1,745 LoC), rmapp/RMAppImpl.java:117/:201, rmapp/attempt/RMAppAttemptImpl
.java, ClientRMService.java:588 submitApplication,
ApplicationMasterService.java:243 registerApplicationMaster / :390 allocate,
ResourceTrackerService.java, amlauncher/AMLauncher.java,
recovery/FileSystemRMStateStore): one dispatcher thread drives RMApp and
RMAppAttempt state machines; three RPC protocols face clients, AMs and node
agents; monitors expire silent AMs and NMs; an on-disk state store recovers
app submissions across RM restarts (non-work-preserving round-1 recovery:
incomplete apps restart with a fresh attempt).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import Client, Server, get_proxy, idempotent
from hadoop_tpu.metrics import metrics_system
from hadoop_tpu.security.ugi import current_user
from hadoop_tpu.service import AbstractService
from hadoop_tpu.util.misc import Daemon
from hadoop_tpu.yarn.common import AsyncDispatcher, Event, StateMachineFactory
from hadoop_tpu.yarn.records import (ApplicationId, ApplicationReport,
                                     ApplicationSubmissionContext, AppState,
                                     Container, ContainerId, ContainerStatus,
                                     NodeId, Resource, ResourceRequest)
from hadoop_tpu.yarn.scheduler import make_scheduler

log = logging.getLogger(__name__)

AM_PRIORITY = 0  # the RM's own request priority for AM containers


class RMApp:
    """Ref: rmapp/RMAppImpl.java — states NEW/SUBMITTED/ACCEPTED/RUNNING/
    FINISHED/FAILED/KILLED driven by dispatcher events."""

    _factory = (
        StateMachineFactory(AppState.NEW)
        .add(AppState.NEW, AppState.SUBMITTED, "submit",
             lambda app, _: app._on_submit())
        .add(AppState.SUBMITTED, AppState.ACCEPTED, "accepted",
             lambda app, _: app._new_attempt())
        .add(AppState.ACCEPTED, AppState.RUNNING, "attempt_registered",
             lambda app, _: None)
        .add(AppState.RUNNING, AppState.FINISHED, "attempt_finished",
             lambda app, diag: app._on_done(AppState.FINISHED, diag))
        .add_many([AppState.ACCEPTED, AppState.RUNNING],
                  (AppState.ACCEPTED, AppState.FAILED), "attempt_failed",
                  lambda app, diag: app._on_attempt_failed(diag))
        .add_many([AppState.NEW, AppState.SUBMITTED, AppState.ACCEPTED,
                   AppState.RUNNING], AppState.KILLED, "kill",
                  lambda app, _: app._on_done(AppState.KILLED, "killed by user"))
        # Terminal states swallow late events (hook keeps the current state).
        .add_many(list(AppState.TERMINAL), AppState.TERMINAL,
                  "attempt_finished", lambda app, _: app.sm.state)
        .add_many(list(AppState.TERMINAL), AppState.TERMINAL,
                  "attempt_failed", lambda app, _: app.sm.state)
        .add_many(list(AppState.TERMINAL), AppState.TERMINAL, "kill",
                  lambda app, _: app.sm.state)
    )

    def __init__(self, rm: "ResourceManager",
                 ctx: ApplicationSubmissionContext, user: str):
        self.rm = rm
        self.ctx = ctx
        self.user = user
        self.app_id = ctx.app_id
        self.sm = self._factory.make(self)
        self.attempt_no = 0
        self.current_attempt: Optional["RMAppAttempt"] = None
        self.diagnostics = ""
        self.final_status = ""
        self.start_time = time.time()
        self.finish_time = 0.0
        self.tracking_url = ""

    # hooks ----------------------------------------------------------------

    def _on_submit(self):
        try:
            self.rm.scheduler_queue_check(self.ctx.queue)
        except ValueError as e:
            self.diagnostics = str(e)
            # Reject: flip to FAILED via the dispatcher on the next tick.
            self.rm.dispatcher.dispatch("app", Event(
                "app_attempt_failed_terminal",
                (self.app_id, str(e))))
            return
        self.rm.dispatcher.dispatch("app", Event("app_accepted", self.app_id))

    def _new_attempt(self):
        self.attempt_no += 1
        attempt = RMAppAttempt(self, self.attempt_no)
        self.current_attempt = attempt
        self.rm.attempts[attempt.attempt_id] = attempt
        self.rm.state_store.store_attempt(self.app_id, self.attempt_no)
        self.rm.timeline.app_attempt(str(self.app_id), attempt.attempt_id)
        attempt.start()

    def recover_attempt(self, attempt_no: int) -> "RMAppAttempt":
        """Work-preserving restart: revive the attempt whose AM may still
        be running — no new AM container; the AM re-registers on its next
        allocate, or liveness expiry fails the attempt and the normal
        retry path takes over. Ref: RMAppAttemptImpl recovery +
        ZKRMStateStore.java:180."""
        self.attempt_no = attempt_no
        attempt = RMAppAttempt(self, attempt_no)
        attempt.state = "RUNNING"
        self.current_attempt = attempt
        self.rm.attempts[attempt.attempt_id] = attempt
        self.rm.scheduler.add_app(attempt.attempt_id, self.ctx.queue,
                                  self.user)
        self.sm.state = AppState.RUNNING
        return attempt

    def _on_attempt_failed(self, diag: str) -> str:
        self.diagnostics = diag or ""
        # Free the dead attempt's scheduler state and queue its live
        # containers for NM cleanup BEFORE retrying — otherwise every
        # failed attempt leaks its containers' capacity for the rest of
        # the app's life (ref: RMAppAttemptImpl's BaseFinalTransition →
        # scheduler APP_ATTEMPT_REMOVED).
        att = self.current_attempt
        if att is not None:
            self.rm.release_attempt(att)
        if self.attempt_no >= self.ctx.max_attempts:
            self._on_done(AppState.FAILED, f"exhausted {self.attempt_no} "
                          f"attempts; last: {diag}")
            return AppState.FAILED
        self._new_attempt()
        return AppState.ACCEPTED

    def _on_done(self, state: str, diag) -> None:
        self.finish_time = time.time()
        if diag:
            self.diagnostics = str(diag)
        self.final_status = state
        self.rm.note_app_finished(str(self.app_id))
        att = self.current_attempt
        if att is not None:
            self.rm.release_attempt(att)
        self.rm.state_store.store_app_done(self.app_id, state,
                                           self.diagnostics)
        self.rm.timeline.app_finished(str(self.app_id), state,
                                      self.diagnostics)

    def report(self) -> ApplicationReport:
        return ApplicationReport(
            self.app_id, self.ctx.name, self.user, self.ctx.queue,
            self.sm.state, self.final_status, self.diagnostics,
            self.tracking_url, self.start_time, self.finish_time,
            self.attempt_no)


class RMAppAttempt:
    """Ref: rmapp/attempt/RMAppAttemptImpl.java (simplified state set:
    SCHEDULED → ALLOCATED → LAUNCHED → RUNNING → FINISHED/FAILED)."""

    def __init__(self, app: RMApp, attempt_no: int):
        self.app = app
        self.attempt_no = attempt_no
        self.attempt_id = f"{app.app_id}_{attempt_no:02d}"
        self.state = "SCHEDULED"
        self.am_container: Optional[Container] = None
        self.progress = 0.0
        self.last_heartbeat = time.monotonic()
        self.tracking_url = ""

    def start(self) -> None:
        rm = self.app.rm
        rm.scheduler.add_app(self.attempt_id, self.app.ctx.queue,
                             self.app.user)
        if getattr(self.app.ctx, "unmanaged", False):
            # Unmanaged AM (ref: RMAppAttemptImpl's unmanaged transitions
            # + amlauncher skipping): no AM container is requested; the
            # external master finds its attempt id via the app report
            # and registers directly.
            self.state = "LAUNCHED"
            log.info("Attempt %s waiting for UNMANAGED AM registration",
                     self.attempt_id)
            return
        rm.scheduler.allocate(self.attempt_id, [ResourceRequest(
            AM_PRIORITY, 1, self.app.ctx.am_resource)], [])
        log.info("Attempt %s scheduled (AM resource %r)", self.attempt_id,
                 self.app.ctx.am_resource)

    def fail(self, diag: str) -> None:
        if self.state in ("FAILED", "FINISHED"):
            return  # already terminal; duplicates also die at the router
        self.state = "FAILED"
        # events carry the ATTEMPT identity: the liveness monitor and the
        # NM-heartbeat handler can both report one AM death, and without
        # the id the second event would fail the app's NEXT attempt
        # (ref: RMAppAttemptImpl events are per-attempt)
        self.app.rm.dispatcher.dispatch("app", Event(
            "app_attempt_failed", (self.app.app_id, self.attempt_id,
                                   diag)))

    def finish(self, final_status: str, diag: str) -> None:
        if self.state in ("FAILED", "FINISHED"):
            return
        self.state = "FINISHED"
        etype = "app_attempt_failed" if final_status in (
            "FAILED", "KILLED") else "app_attempt_finished"
        self.app.rm.dispatcher.dispatch("app", Event(
            etype, (self.app.app_id, self.attempt_id, diag)))


class FileRMStateStore:
    """App submissions + outcomes on local disk.
    Ref: recovery/FileSystemRMStateStore.java."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, app_id: ApplicationId) -> str:
        return os.path.join(self.dir, f"{app_id}.json")

    def store_app(self, ctx: ApplicationSubmissionContext, user: str) -> None:
        self._write(self._path(ctx.app_id),
                    {"ctx": _wire_to_jsonable(ctx.to_wire()),
                     "user": user, "state": "RUNNING"})

    @staticmethod
    def _write(path: str, d: Dict) -> None:
        # tmp + rename: a crash mid-dump must never leave a torn state
        # file (one corrupt file would block recovery of every app —
        # ref: FileSystemRMStateStore's updateFile write-to-temp dance)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(d, f)
        os.replace(tmp, path)

    def store_app_done(self, app_id: ApplicationId, state: str,
                       diag: str) -> None:
        self._update(app_id, state=state, diagnostics=diag)

    def store_attempt(self, app_id: ApplicationId, attempt_no: int) -> None:
        """Ref: RMStateStore.storeNewApplicationAttempt — the attempt
        number survives restart so work-preserving recovery can revive
        the attempt the live AM identifies as."""
        self._update(app_id, attempt_no=attempt_no)

    def _update(self, app_id: ApplicationId, **fields) -> None:
        path = self._path(app_id)
        if not os.path.exists(path):
            return
        with open(path) as f:
            d = json.load(f)
        d.update(fields)
        self._write(path, d)

    def load_all(self) -> List[Dict]:
        out = []
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.dir, name)
            try:
                with open(path) as f:
                    out.append(json.load(f))
            except (ValueError, OSError) as e:
                # a pre-atomic-write torn file (or disk bitrot) costs
                # that ONE app its recovery, never the whole RM restart
                log.error("Skipping unreadable RM state file %s: %s",
                          path, e)
        return out


def _wire_to_jsonable(obj):
    if isinstance(obj, bytes):
        import base64
        return {"__b64__": base64.b64encode(obj).decode()}
    if isinstance(obj, dict):
        return {k: _wire_to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_wire_to_jsonable(v) for v in obj]
    return obj


def _jsonable_to_wire(obj):
    if isinstance(obj, dict):
        if "__b64__" in obj:
            import base64
            return base64.b64decode(obj["__b64__"])
        return {k: _jsonable_to_wire(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_jsonable_to_wire(v) for v in obj]
    return obj


class RMNode:
    def __init__(self, node_id: NodeId, total: Resource, nm_address: str):
        self.node_id = node_id
        self.total = total
        self.nm_address = nm_address
        self.last_heartbeat = time.monotonic()
        self.state = "RUNNING"
        self.containers_to_cleanup: List[ContainerId] = []


class ClientRMProtocol:
    """Client ↔ RM. Ref: ClientRMService.java."""

    def __init__(self, rm: "ResourceManager"):
        self.rm = rm

    def get_new_application(self) -> Dict:
        app_id = self.rm.new_app_id()
        return {"app_id": app_id.to_wire(),
                "max_resource": self.rm.scheduler.cluster_resource().to_wire()}

    def submit_application(self, ctx_wire: Dict) -> Dict:
        """Ref: ClientRMService.submitApplication:588."""
        ctx = ApplicationSubmissionContext.from_wire(ctx_wire)
        user = current_user().user_name
        return self.rm.submit_application(ctx, user)

    @idempotent
    def get_application_report(self, app_id_wire: Dict) -> Dict:
        app = self.rm.apps.get(ApplicationId.from_wire(app_id_wire))
        if app is None:
            raise ValueError(f"unknown application")
        return app.report().to_wire()

    @idempotent
    def list_applications(self) -> List[Dict]:
        return [a.report().to_wire() for a in self.rm.apps.values()]

    def kill_application(self, app_id_wire: Dict) -> bool:
        app_id = ApplicationId.from_wire(app_id_wire)
        self.rm.dispatcher.dispatch("app", Event("app_kill", app_id))
        return True

    def submit_reservation(self, reservation_id: str, queue: str,
                           capability_wire: Dict, num_containers: int,
                           start: float, deadline: float) -> bool:
        """Ref: ClientRMService.submitReservation → ReservationSystem.
        Only capacity-scheduler deployments accept reservations."""
        from hadoop_tpu.yarn.scheduler import Reservation
        sched = self.rm.scheduler
        if not hasattr(sched, "submit_reservation"):
            raise ValueError("scheduler does not support reservations")
        sched.submit_reservation(Reservation(
            reservation_id, queue, Resource.from_wire(capability_wire),
            num_containers, start, deadline))
        return True

    def delete_reservation(self, reservation_id: str) -> bool:
        sched = self.rm.scheduler
        return hasattr(sched, "delete_reservation") and \
            sched.delete_reservation(reservation_id)

    @idempotent
    def get_cluster_metrics(self) -> Dict:
        nodes = self.rm.nodes
        return {
            "num_node_managers": len(nodes),
            "total_resource": self.rm.scheduler.cluster_resource().to_wire(),
            "apps": len(self.rm.apps),
        }

    @idempotent
    def get_nodes(self) -> List[Dict]:
        return [{"id": n.node_id.to_wire(), "r": n.total.to_wire(),
                 "state": n.state, "nm": n.nm_address}
                for n in self.rm.nodes.values()]

    @idempotent
    def get_service_status(self) -> Dict:
        return {"state": "active"}


class AMRMProtocol:
    """AM ↔ RM. Ref: ApplicationMasterService.java."""

    def __init__(self, rm: "ResourceManager"):
        self.rm = rm

    def register_application_master(self, attempt_id: str,
                                    tracking_url: str = "") -> Dict:
        """Ref: ApplicationMasterService.registerApplicationMaster:243."""
        attempt = self.rm.attempts.get(attempt_id)
        if attempt is None:
            raise ValueError(f"unknown attempt {attempt_id}")
        attempt.state = "RUNNING"
        attempt.last_heartbeat = time.monotonic()
        attempt.tracking_url = tracking_url
        attempt.app.tracking_url = tracking_url
        self.rm.dispatcher.dispatch("app", Event("app_attempt_registered",
                                                 attempt.app.app_id))
        return {"max_resource": self.rm.scheduler.cluster_resource().to_wire(),
                "queue": attempt.app.ctx.queue}

    def allocate(self, attempt_id: str, asks: List[Dict],
                 releases: List[Dict], progress: float = 0.0) -> Dict:
        """The AM↔RM heartbeat. Ref: ApplicationMasterService.allocate:390."""
        attempt = self.rm.attempts.get(attempt_id)
        if attempt is None:
            raise ValueError(f"unknown attempt {attempt_id}")
        attempt.last_heartbeat = time.monotonic()
        attempt.progress = progress
        allocated, completed = self.rm.scheduler.allocate(
            attempt_id,
            [ResourceRequest.from_wire(a) for a in asks],
            [ContainerId.from_wire(r) for r in releases])
        return {
            "allocated": [c.to_wire() for c in allocated],
            "completed": [s.to_wire() for s in completed],
            "num_nodes": len(self.rm.nodes),
        }

    def finish_application_master(self, attempt_id: str, final_status: str,
                                  diagnostics: str = "") -> bool:
        attempt = self.rm.attempts.get(attempt_id)
        if attempt is None:
            return True
        attempt.finish(final_status, diagnostics)
        return True


class ResourceTrackerProtocol:
    """NM ↔ RM. Ref: ResourceTrackerService.java."""

    def __init__(self, rm: "ResourceManager"):
        self.rm = rm

    def register_node_manager(self, node_id_wire: Dict, resource_wire: Dict,
                              nm_address: str,
                              running_containers: Optional[List[Dict]] = None
                              ) -> Dict:
        node_id = NodeId.from_wire(node_id_wire)
        total = Resource.from_wire(resource_wire)
        # Reconcile BEFORE adopting the fresh node: containers we still
        # count as live on this node but the (restarted) NM no longer
        # reports died with it — synthesize their completions so AMs
        # hear about them and queue usage deflates; without this a
        # crashed NM's containers stay "live" forever (ref:
        # ResourceTrackerService handling of NM re-register: previous
        # containers not in NMContainerStatus are completed as lost).
        reported = {Container.from_wire(cw).container_id
                    for cw in running_containers or []}
        with self.rm.nodes_lock:
            known_before = node_id in self.rm.nodes
            node = RMNode(node_id, total, nm_address)
            self.rm.nodes[node_id] = node
        if known_before:
            for c in self.rm.scheduler.containers_on_node(node_id):
                if c.container_id not in reported:
                    log.info("Container %s lost in NM %s restart",
                             c.container_id, node_id)
                    self.rm.on_container_complete(ContainerStatus(
                        c.container_id, "COMPLETE", exit_code=-100,
                        diagnostics="NodeManager restarted"))
        self.rm.scheduler.add_node(node_id, total, nm_address)
        # Work-preserving restart: re-adopt containers this NM kept alive
        # across our downtime (ref: ResourceTrackerService
        # .registerNodeManager's NMContainerStatus handling).
        orphans: List[Dict] = []
        for cw in running_containers or []:
            container = Container.from_wire(cw)
            cid = container.container_id
            attempt_id = f"{cid.app_id}_{cid.attempt_no:02d}"
            if self.rm.scheduler.recover_container(attempt_id, container):
                log.info("Re-adopted live container %s (%s)", cid,
                         attempt_id)
                att = self.rm.attempts.get(attempt_id)
                if att is not None and att.am_container is None and \
                        cid.seq == 1:
                    att.am_container = container
            else:
                orphans.append(cid.to_wire())  # app finished/unknown: kill
        log.info("Node %s registered (%r) at %s", node_id, total, nm_address)
        return {"ok": True, "cleanup": orphans}

    def node_heartbeat(self, node_id_wire: Dict,
                       container_statuses: List[Dict]) -> Dict:
        node_id = NodeId.from_wire(node_id_wire)
        with self.rm.nodes_lock:
            node = self.rm.nodes.get(node_id)
        if node is None:
            return {"action": "reregister"}
        node.last_heartbeat = time.monotonic()
        # Route completed containers to their attempt + the AM watcher.
        for sw in container_statuses:
            status = ContainerStatus.from_wire(sw)
            if status.state == "COMPLETE":
                self.rm.on_container_complete(status)
        # Offer this node to the scheduler, then launch any AM containers it
        # just granted.
        self.rm.scheduler.node_heartbeat(node_id)
        self.rm.launch_allocated_am_containers()
        cleanup = node.containers_to_cleanup
        node.containers_to_cleanup = []
        # Finished apps ride the heartbeat so NMs can stop per-app
        # timeline collectors / app resources (ref: NodeHeartbeatResponse
        # .getApplicationsToCleanup). An explicit terminal-event ring —
        # not a scan of rm.apps — so old finishes aren't silently
        # truncated away and heartbeats stay O(1).
        return {"action": "ok",
                "cleanup": [c.to_wire() for c in cleanup],
                "finished_apps": self.rm.recent_finished_apps()}


class ResourceManager(AbstractService):
    def __init__(self, conf: Configuration, state_dir: Optional[str] = None):
        super().__init__("ResourceManager")
        self._conf_in = conf
        # Milliseconds like the reference (ResourceManager uses
        # System.currentTimeMillis() as the cluster timestamp) — seconds
        # granularity made two RMs started in the same second mint
        # identical ApplicationIds, which collide under federation.
        self.cluster_ts = int(time.time() * 1000)
        self._app_seq = 0
        self._seq_lock = threading.Lock()
        self.apps: Dict[ApplicationId, RMApp] = {}
        # Recent terminal transitions, for NM heartbeat app-cleanup
        # (ref: the RMNode's finishedApplications tracking). A bounded
        # ring: old entries age out only after 200 newer finishes, far
        # past any NM heartbeat gap.
        from collections import deque
        self._finished_ring: "deque[str]" = deque(maxlen=200)
        self.attempts: Dict[str, RMAppAttempt] = {}
        self.nodes: Dict[NodeId, RMNode] = {}
        self.nodes_lock = threading.Lock()
        self.dispatcher = AsyncDispatcher("rm-dispatcher")
        self.state_dir = state_dir or conf.get(
            "yarn.resourcemanager.store.dir", "/tmp/htpu-rm-state")
        self.state_store = FileRMStateStore(self.state_dir)
        # App lifecycle → timeline store (ref: SystemMetricsPublisher;
        # serving side: yarn/timeline.py ApplicationHistoryServer)
        from hadoop_tpu.conf.keys import YARN_TIMELINE_STORE_DIR
        from hadoop_tpu.yarn.timeline import TimelinePublisher, make_store
        self.timeline = TimelinePublisher(make_store(
            conf.get(YARN_TIMELINE_STORE_DIR,
                     os.path.join(self.state_dir, "timeline")),
            conf.get("yarn.timeline-service.store.backend", "auto")))
        self.rpc: Optional[Server] = None
        self._stop_event = threading.Event()
        self._nm_client = Client(conf)
        reg = metrics_system().source("rm")
        reg.register_callback_gauge("apps", lambda: len(self.apps))
        reg.register_callback_gauge("nodes", lambda: len(self.nodes))
        self._m_submitted = reg.counter("apps_submitted")
        self._m_completed = reg.counter("apps_completed")

    @property
    def port(self) -> int:
        return self.rpc.port

    # ------------------------------------------------------------- lifecycle

    def service_init(self, conf: Configuration) -> None:
        self.scheduler = make_scheduler(conf, self._make_container_id)
        self.dispatcher.register("app", self._handle_app_event)
        self.dispatcher.init(conf)
        bind_host = conf.get("yarn.resourcemanager.bind-host", "127.0.0.1")
        self.rpc = Server(
            conf, bind=(bind_host, conf.get_int("yarn.resourcemanager.port", 0)),
            num_handlers=conf.get_int("yarn.resourcemanager.handler.count", 8),
            name="rm")
        self.rpc.register_protocol("ClientRMProtocol", ClientRMProtocol(self))
        self.rpc.register_protocol("AMRMProtocol", AMRMProtocol(self))
        self.rpc.register_protocol("ResourceTrackerProtocol",
                                   ResourceTrackerProtocol(self))
        self.am_expiry_s = conf.get_time_seconds(
            "yarn.am.liveness-monitor.expiry-interval", 60.0)
        self.nm_expiry_s = conf.get_time_seconds(
            "yarn.nm.liveness-monitor.expiry-interval", 60.0)

    def service_start(self) -> None:
        self.dispatcher.start()
        # recover BEFORE opening RPC: re-registering NMs must find the
        # revived attempts to hang their live-container reports on
        self._recover()
        self.rpc.start()
        # Admin HTTP: /jmx /conf /stacks plus cluster + app status JSON
        # (ref: the RM webapp's /ws/v1/cluster REST endpoints).
        self.http = None
        if self.config.get_bool("yarn.resourcemanager.http.enabled", True):
            from hadoop_tpu.http import HttpServer
            self.http = HttpServer(
                self.config,
                bind=("127.0.0.1", self.config.get_int(
                    "yarn.resourcemanager.http-port", 0)),
                daemon_name="resourcemanager")
            client_proto = ClientRMProtocol(self)
            self.http.add_handler(
                "/ws/v1/cluster/info",
                lambda q, b: (200, client_proto.get_cluster_metrics()))
            self.http.add_handler(
                "/ws/v1/cluster/apps",
                lambda q, b: (200, {"apps": client_proto.list_applications()}))
            self.http.add_handler(
                "/ws/v1/cluster/nodes",
                lambda q, b: (200, {"nodes": client_proto.get_nodes()}))
            from hadoop_tpu.http.webui import rm_cluster_page
            self.http.add_handler("/cluster", rm_cluster_page(self))
            self.http.start()
        Daemon(self._liveness_loop, "rm-liveness").start()
        if self.config.get_bool(
                "yarn.resourcemanager.scheduler.monitor.enable", False):
            Daemon(self._preemption_loop, "rm-preemption").start()
        log.info("ResourceManager up at 127.0.0.1:%d", self.rpc.port)

    def service_stop(self) -> None:
        self._stop_event.set()
        if getattr(self, "http", None) is not None:
            self.http.stop()
        if self.rpc:
            self.rpc.stop()
        self.dispatcher.stop()
        self._nm_client.stop()
        self.timeline.close()

    def _recover(self) -> None:
        """App recovery on restart. WORK-PRESERVING (default; ref:
        ZKRMStateStore.java:180 + RMAppAttemptImpl recovery): incomplete
        apps revive their stored attempt with no new AM launch — the
        running AM re-registers on its next allocate, NMs re-report live
        containers on re-registration, and the scheduler re-adopts them.
        With work-preserving disabled, incomplete apps restart with a
        fresh attempt (the old round-1 behavior)."""
        wp = self.config.get_bool(
            "yarn.resourcemanager.work-preserving-recovery.enabled", True)
        for d in self.state_store.load_all():
            if d.get("state") in (AppState.FINISHED, AppState.FAILED,
                                  AppState.KILLED):
                continue
            try:
                ctx = ApplicationSubmissionContext.from_wire(
                    _jsonable_to_wire(d["ctx"]))
                self._app_seq = max(self._app_seq, ctx.app_id.seq)
                attempt_no = int(d.get("attempt_no", 0))
                if wp and attempt_no > 0:
                    log.info("Work-preserving recovery of %s (attempt %d)",
                             ctx.app_id, attempt_no)
                    app = RMApp(self, ctx, d.get("user", "unknown"))
                    self.apps[ctx.app_id] = app
                    app.recover_attempt(attempt_no)
                else:
                    log.info("Recovering application %s (fresh attempt)",
                             ctx.app_id)
                    self.submit_application(ctx, d.get("user", "unknown"),
                                            store=False)
            except Exception:
                log.exception("Failed to recover an application")

    # --------------------------------------------------------------- events

    def _handle_app_event(self, ev: Event) -> None:
        if ev.etype == "app_kill":
            app = self.apps.get(ev.payload)
            if app is not None and app.sm.can_handle("kill"):
                app.sm.handle("kill")
            return
        if ev.etype == "app_accepted":
            app = self.apps.get(ev.payload)
            if app is not None:
                app.sm.handle("accepted")
            return
        if ev.etype == "app_attempt_registered":
            app = self.apps.get(ev.payload)
            if app is not None and app.sm.state == AppState.ACCEPTED:
                app.sm.handle("attempt_registered")
            return
        if ev.etype in ("app_attempt_finished", "app_attempt_failed"):
            app_id, attempt_id, diag = ev.payload
            app = self.apps.get(app_id)
            if app is None:
                return
            # Staleness filter: only the CURRENT attempt's outcome moves
            # the app. A duplicate failure report (liveness monitor and
            # heartbeat handler racing on one AM death) arrives after
            # _new_attempt switched current_attempt, and acting on it
            # would spawn a second live AM / double-charge max_attempts.
            cur = app.current_attempt
            if cur is None or cur.attempt_id != attempt_id:
                log.debug("Dropping stale %s for %s (current %s)",
                          ev.etype, attempt_id,
                          cur.attempt_id if cur else None)
                return
            event = ("attempt_finished" if ev.etype == "app_attempt_finished"
                     else "attempt_failed")
            if app.sm.can_handle(event):
                app.sm.handle(event, diag)
            if app.sm.state in AppState.TERMINAL:
                self._m_completed.incr()
            return
        if ev.etype == "app_attempt_failed_terminal":
            app_id, diag = ev.payload
            app = self.apps.get(app_id)
            if app is not None:
                app._on_done(AppState.FAILED, diag)
                app.sm.state = AppState.FAILED
            return
        log.warning("Unhandled app event %s", ev.etype)

    # ----------------------------------------------------------- operations

    def new_app_id(self) -> ApplicationId:
        with self._seq_lock:
            self._app_seq += 1
            return ApplicationId(self.cluster_ts, self._app_seq)

    def submit_application(self, ctx: ApplicationSubmissionContext,
                           user: str, store: bool = True) -> Dict:
        if ctx.app_id in self.apps:
            return {"ok": True, "dup": True}  # idempotent resubmission
        app = RMApp(self, ctx, user)
        self.apps[ctx.app_id] = app
        if store:
            self.state_store.store_app(ctx, user)
        self._m_submitted.incr()
        self.timeline.app_submitted(str(ctx.app_id), ctx.name, user,
                                    ctx.queue)
        app.sm.handle("submit")
        return {"ok": True}

    def scheduler_queue_check(self, queue: str) -> None:
        checker = getattr(self.scheduler, "queues", None)
        if checker is not None and queue not in checker:
            raise ValueError(f"unknown queue {queue!r}")

    def _make_container_id(self, attempt_id: str, seq: int) -> ContainerId:
        # attempt_id = application_<ts>_<seq>_<no>
        parts = attempt_id.rsplit("_", 1)
        app_id = ApplicationId.parse(parts[0])
        return ContainerId(app_id, int(parts[1]), seq)

    def on_container_complete(self, status: ContainerStatus) -> None:
        cid = status.container_id
        attempt_id = f"{cid.app_id}_{cid.attempt_no:02d}"
        self.scheduler.container_completed(attempt_id, status)
        attempt = self.attempts.get(attempt_id)
        if attempt is None:
            return
        am = attempt.am_container
        if am is not None and am.container_id == cid and \
                attempt.state in ("LAUNCHED", "RUNNING", "ALLOCATED"):
            # The AM container itself died.
            if status.exit_code == 0:
                attempt.finish("SUCCEEDED", "AM exited 0 without unregister")
            else:
                attempt.fail(f"AM container exited {status.exit_code}: "
                             f"{status.diagnostics}")

    def launch_allocated_am_containers(self) -> None:
        """Scan SCHEDULED attempts whose AM container was just granted.
        Ref: RMAppAttemptImpl.AMContainerAllocatedTransition + AMLauncher."""
        for attempt in list(self.attempts.values()):
            if attempt.state != "SCHEDULED":
                continue
            allocated, _ = self.scheduler.allocate(attempt.attempt_id, [], [])
            if not allocated:
                continue
            attempt.am_container = allocated[0]
            attempt.state = "ALLOCATED"
            Daemon(self._launch_am, "am-launcher",
                   args=(attempt,)).start()

    def _launch_am(self, attempt: RMAppAttempt) -> None:
        """Ref: amlauncher/AMLauncher.java — start the AM container on its NM."""
        c = attempt.am_container
        ctx = attempt.app.ctx.am_launch_context
        env = dict(ctx.env)
        env["HTPU_ATTEMPT_ID"] = attempt.attempt_id
        env["HTPU_RM_ADDRESS"] = f"127.0.0.1:{self.rpc.port}"
        env["HTPU_CONTAINER_ID"] = str(c.container_id)
        launch = type(ctx)(ctx.commands, env, ctx.local_resources)
        try:
            host, port = c.nm_address.rsplit(":", 1)
            nm = get_proxy("ContainerManagerProtocol", (host, int(port)),
                           client=self._nm_client)
            nm.start_container(c.to_wire(), launch.to_wire())
            attempt.state = "LAUNCHED"
            log.info("Launched AM for %s in %s on %s", attempt.attempt_id,
                     c.container_id, c.node_id)
        except Exception as e:  # noqa: BLE001
            log.warning("AM launch for %s failed: %s", attempt.attempt_id, e)
            attempt.fail(f"AM launch failed: {e}")

    def note_app_finished(self, app_id: str) -> None:
        if app_id not in self._finished_ring:
            self._finished_ring.append(app_id)

    def recent_finished_apps(self) -> List[str]:
        return list(self._finished_ring)

    def release_attempt(self, attempt: RMAppAttempt) -> None:
        freed = self.scheduler.remove_app(attempt.attempt_id)
        with self.nodes_lock:
            for c in freed:
                node = self.nodes.get(c.node_id)
                if node is not None:
                    node.containers_to_cleanup.append(c.container_id)

    # ----------------------------------------------------------- preemption

    def _preemption_loop(self) -> None:
        """Capacity/fair preemption monitor (ref: monitor/capacity/
        ProportionalCapacityPreemptionPolicy via SchedulingMonitor):
        periodically ask the scheduler for over-guarantee containers and
        kill them (exit -102 PREEMPTED) so starved queues can schedule.
        AM containers are protected."""
        interval = self.config.get_time_seconds(
            "yarn.resourcemanager.monitor.capacity.preemption"
            ".monitoring_interval", 3.0)
        while not self._stop_event.wait(interval):
            try:
                am_cids = {str(a.am_container.container_id)
                           for a in self.attempts.values()
                           if a.am_container is not None}
                victims = self.scheduler.preemption_candidates(
                    protect=lambda cid: str(cid) in am_cids)
                for attempt_id, container in victims:
                    log.info("Preempting %s of %s",
                             container.container_id, attempt_id)
                    self.scheduler.container_completed(
                        attempt_id, ContainerStatus(
                            container.container_id, "COMPLETE",
                            exit_code=-102,
                            diagnostics="container preempted by scheduler"))
                    with self.nodes_lock:
                        node = self.nodes.get(container.node_id)
                        if node is not None:
                            node.containers_to_cleanup.append(
                                container.container_id)
            except Exception:
                log.exception("Preemption monitor pass failed")

    # ------------------------------------------------------------- liveness

    def _liveness_loop(self) -> None:
        """AM + NM expiry. Ref: AMLivelinessMonitor, NMLivelinessMonitor.
        Guarded per pass: one bad attempt/node must not kill the monitor."""
        while not self._stop_event.wait(0.5):
            now = time.monotonic()
            try:
                for attempt in list(self.attempts.values()):
                    if attempt.state == "RUNNING" and \
                            now - attempt.last_heartbeat > self.am_expiry_s:
                        log.warning("Attempt %s expired (no AM heartbeat)",
                                    attempt.attempt_id)
                        attempt.fail("AM liveness expired")
                    elif attempt.state == "LAUNCHED" and \
                            getattr(attempt.app.ctx, "unmanaged", False) \
                            and now - attempt.last_heartbeat > \
                            self.am_expiry_s:
                        # an unmanaged AM has no NM container whose exit
                        # would fail the attempt — registration itself
                        # is on the liveness clock (ref: the unmanaged
                        # path of RMAppAttemptImpl expiring on the
                        # AMLivelinessMonitor)
                        log.warning("Attempt %s expired (unmanaged AM "
                                    "never registered)",
                                    attempt.attempt_id)
                        attempt.fail("unmanaged AM never registered")
                with self.nodes_lock:
                    nodes = list(self.nodes.items())
                for node_id, node in nodes:
                    if node.state == "RUNNING" and \
                            now - node.last_heartbeat > self.nm_expiry_s:
                        log.warning("Node %s expired", node_id)
                        node.state = "LOST"
                        self.scheduler.remove_node(node_id)
            except Exception:
                log.exception("Liveness monitor pass failed")
