"""Pluggable schedulers: FIFO and hierarchical capacity.

Parity with the reference's scheduler layer (ref:
scheduler/capacity/CapacityScheduler.java:174 (3,273 LoC; :1220 allocate,
:1747 allocateContainersToNode), scheduler/fifo/FifoScheduler.java, common
SchedulerNode/SchedulerApplicationAttempt): allocation is heartbeat-driven —
each NM heartbeat offers its node to the scheduler, which walks the queue
hierarchy (most-under-served first), picks an app, and matches its pending
resource requests against the node's headroom. AMs pick allocations up on
their next ``allocate`` call.

TPU-first: Resource is (memory, vcores, tpu_chips); queue ordering uses
dominant-resource share so chip-hungry and memory-hungry queues compare
sanely (ref: DominantResourceCalculator).
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.yarn.records import (Container, ContainerId, ContainerStatus,
                                     NodeId, Resource, ResourceRequest)

log = logging.getLogger(__name__)


class SchedulerNode:
    """Ref: scheduler/SchedulerNode.java."""

    def __init__(self, node_id: NodeId, total: Resource, nm_address: str,
                 label: str = ""):
        self.node_id = node_id
        self.total = total
        self.available = Resource(total.memory_mb, total.vcores,
                                  total.tpu_chips)
        self.nm_address = nm_address
        # Partition label, exclusive semantics (ref: the default
        # exclusive node-label partitions of CommonNodeLabelsManager):
        # only requests carrying this label land here; "" is the
        # default partition.
        self.label = label
        self.containers: Dict[ContainerId, Container] = {}
        # Opportunistic containers allocated past guaranteed capacity
        # (queued/run best-effort NM-side; ref: the per-node queue the
        # OpportunisticContainerAllocator bounds).
        self.opportunistic: Dict[ContainerId, Container] = {}

    def allocate(self, container: Container) -> None:
        self.available = self.available.subtract(container.resource)
        self.containers[container.container_id] = container

    def release(self, container_id: ContainerId) -> Optional[Container]:
        c = self.containers.pop(container_id, None)
        if c is not None:
            self.available = self.available.add(c.resource)
        return c


class SchedulerApp:
    """One app attempt's scheduling state.
    Ref: scheduler/SchedulerApplicationAttempt.java."""

    def __init__(self, attempt_id: str, queue: str, user: str):
        self.attempt_id = attempt_id
        self.queue = queue
        self.user = user
        # priority -> list of outstanding requests
        self.pending: Dict[int, List[ResourceRequest]] = {}
        self.allocated_unfetched: List[Container] = []
        self.live_containers: Dict[ContainerId, Container] = {}
        self.completed_unfetched: List[ContainerStatus] = []
        self.used = Resource()
        self._seq = 0

    def add_requests(self, asks: List[ResourceRequest]) -> None:
        for ask in asks:
            self.pending.setdefault(ask.priority, []).append(ask)

    def next_container_seq(self) -> int:
        self._seq += 1
        return self._seq

    def has_pending(self) -> bool:
        return any(r.num_containers > 0
                   for reqs in self.pending.values() for r in reqs)


class Scheduler:
    """Interface. Ref: scheduler/YarnScheduler.java."""

    def add_node(self, node_id: NodeId, total: Resource,
                 nm_address: str) -> None: ...
    def remove_node(self, node_id: NodeId) -> List[ContainerId]: ...
    def node_heartbeat(self, node_id: NodeId) -> None: ...
    def add_app(self, attempt_id: str, queue: str, user: str) -> None: ...
    def remove_app(self, attempt_id: str) -> List[Container]: ...
    def allocate(self, attempt_id: str, asks, releases) -> Tuple[List, List]: ...
    def cluster_resource(self) -> Resource: ...


class _BaseScheduler(Scheduler):
    def __init__(self, conf: Configuration,
                 container_id_factory) -> None:
        self.conf = conf
        self.nodes: Dict[NodeId, SchedulerNode] = {}
        self.apps: "OrderedDict[str, SchedulerApp]" = OrderedDict()
        self.lock = threading.RLock()
        self.make_container_id = container_id_factory
        self.min_alloc = Resource(
            conf.get_int("yarn.scheduler.minimum-allocation-mb", 128),
            1, 0)
        # host → partition label (ref: yarn.node-labels config +
        # RMAdminCLI -replaceLabelsOnNode; a conf map keeps the test
        # surface simple): "yarn.node-labels.map = h1=gpu,h2=gpu"
        self.node_labels: Dict[str, str] = {}
        for entry in conf.get_list("yarn.node-labels.map", []):
            host, _, lab = entry.partition("=")
            if lab:
                self.node_labels[host.strip()] = lab.strip()

    # ------------------------------------------------------------- nodes

    def add_node(self, node_id: NodeId, total: Resource,
                 nm_address: str, label: str = "") -> None:
        with self.lock:
            self.nodes[node_id] = SchedulerNode(
                node_id, total, nm_address,
                label or self.node_labels.get(node_id.host, ""))

    def remove_node(self, node_id: NodeId) -> List[ContainerId]:
        """Node lost: complete its containers as LOST."""
        with self.lock:
            node = self.nodes.pop(node_id, None)
            if node is None:
                return []
            lost = list(node.containers) + list(node.opportunistic)
            for cid in lost:
                for app in self.apps.values():
                    if cid in app.live_containers:
                        c = app.live_containers.pop(cid)
                        if getattr(c, "execution_type", "") != \
                                ResourceRequest.EXEC_OPPORTUNISTIC:
                            app.used = app.used.subtract(c.resource)
                        app.completed_unfetched.append(ContainerStatus(
                            cid, "COMPLETE", exit_code=-100,
                            diagnostics="container lost: node expired"))
            return lost

    def cluster_resource(self) -> Resource:
        with self.lock:
            total = Resource()
            for n in self.nodes.values():
                total = total.add(n.total)
            return total

    # -------------------------------------------------------------- apps

    def add_app(self, attempt_id: str, queue: str, user: str) -> None:
        with self.lock:
            self.apps[attempt_id] = SchedulerApp(attempt_id, queue, user)

    def remove_app(self, attempt_id: str) -> List[Container]:
        """App done: free its containers; returns them for NM cleanup."""
        with self.lock:
            app = self.apps.pop(attempt_id, None)
            if app is None:
                return []
            freed = list(app.live_containers.values())
            for c in freed:
                node = self.nodes.get(c.node_id)
                if node is not None:
                    if node.opportunistic.pop(c.container_id,
                                              None) is None:
                        node.release(c.container_id)
            return freed

    # Cap on queued opportunistic containers per node (ref:
    # yarn.opportunistic-container-allocation.nodes-used +
    # NM queue limits, collapsed to one knob).
    MAX_OPPORTUNISTIC_PER_NODE = 8

    def _allocate_opportunistic(self, app: SchedulerApp,
                                req: ResourceRequest) -> None:
        """Allocate O-containers IMMEDIATELY at ask time, past node
        capacity, round-robin over the least-loaded nodes (ref:
        OpportunisticContainerAllocatorAMService.allocate — the central
        allocator variant of YARN-2882; containers queue at the NM)."""
        nodes = sorted(self.nodes.values(),
                       key=lambda n: len(n.opportunistic))
        if not nodes:
            return
        i = 0
        while req.num_containers > 0:
            node = nodes[i % len(nodes)]
            if len(node.opportunistic) >= self.MAX_OPPORTUNISTIC_PER_NODE:
                if all(len(n.opportunistic) >=
                       self.MAX_OPPORTUNISTIC_PER_NODE for n in nodes):
                    return  # every queue full; leave the rest pending
                i += 1
                continue
            cid = self.make_container_id(app.attempt_id,
                                         app.next_container_seq())
            container = Container(
                cid, node.node_id, req.capability, node.nm_address,
                execution_type=ResourceRequest.EXEC_OPPORTUNISTIC)
            node.opportunistic[cid] = container
            app.live_containers[cid] = container
            app.allocated_unfetched.append(container)
            req.num_containers -= 1
            i += 1

    def allocate(self, attempt_id: str, asks: List[ResourceRequest],
                 releases: List[ContainerId]
                 ) -> Tuple[List[Container], List[ContainerStatus]]:
        """AM heartbeat: record asks, apply releases, hand back anything
        allocated since last call. Ref: CapacityScheduler.allocate:1220."""
        with self.lock:
            app = self.apps.get(attempt_id)
            if app is None:
                return [], []
            for ask in asks:
                if getattr(ask, "execution_type", "") == \
                        ResourceRequest.EXEC_OPPORTUNISTIC:
                    self._allocate_opportunistic(app, ask)
            # Remainders of O-asks (queues full) stay pending like any
            # other request and drain as per-node queues free up (see
            # node_heartbeat); _assign_on_node skips them.
            app.add_requests([a for a in asks if a.num_containers > 0])
            for cid in releases:
                c = app.live_containers.pop(cid, None)
                if c is not None:
                    node = self.nodes.get(c.node_id)
                    if node is not None:
                        node.opportunistic.pop(cid, None)
                    if getattr(c, "execution_type", "") == \
                            ResourceRequest.EXEC_OPPORTUNISTIC:
                        continue  # never held capacity or app.used
                    app.used = app.used.subtract(c.resource)
                    if node is not None:
                        node.release(cid)
            allocated = app.allocated_unfetched
            app.allocated_unfetched = []
            completed = app.completed_unfetched
            app.completed_unfetched = []
            return allocated, completed

    def recover_container(self, attempt_id: str,
                          container: Container) -> bool:
        """Work-preserving restart: re-adopt a container an NM reported as
        live on (re)registration. Ref: AbstractYarnScheduler
        .recoverContainersOnNode."""
        with self.lock:
            app = self.apps.get(attempt_id)
            node = self.nodes.get(container.node_id)
            if app is None or node is None:
                return False
            if container.container_id in node.containers or \
                    container.container_id in node.opportunistic:
                return True  # already known
            if getattr(container, "execution_type", "") == \
                    ResourceRequest.EXEC_OPPORTUNISTIC:
                # O-ness rides the container wire record: recover into
                # the O-queue, never into guaranteed capacity (which it
                # was allocated past by design).
                node.opportunistic[container.container_id] = container
                app.live_containers[container.container_id] = container
            else:
                node.allocate(container)
                app.live_containers[container.container_id] = container
                app.used = app.used.add(container.resource)
            app._seq = max(app._seq, container.container_id.seq)
            return True

    def containers_on_node(self, node_id: NodeId) -> List[Container]:
        """Live containers currently attributed to one node, across all
        apps (NM re-registration reconciliation)."""
        with self.lock:
            return [c for app in self.apps.values()
                    for c in app.live_containers.values()
                    if c.node_id == node_id]

    def container_completed(self, attempt_id: str,
                            status: ContainerStatus) -> None:
        """NM reported a container exit."""
        with self.lock:
            app = self.apps.get(attempt_id)
            for node in self.nodes.values():
                node.release(status.container_id)
                node.opportunistic.pop(status.container_id, None)
            if app is not None:
                c = app.live_containers.pop(status.container_id, None)
                if c is not None and getattr(
                        c, "execution_type", "") != \
                        ResourceRequest.EXEC_OPPORTUNISTIC:
                    # O-containers never added to app.used
                    app.used = app.used.subtract(c.resource)
                app.completed_unfetched.append(status)

    # --------------------------------------------------------- allocation

    # Re-evaluate the app order after every single assignment? Fairness-
    # based schedulers need this (one drain-all pass would hand the first
    # app the whole node); FIFO keeps the cheap drain-all.
    REORDER_PER_ASSIGNMENT = False

    def node_heartbeat(self, node_id: NodeId) -> None:
        """Offer the node to apps. Subclasses choose the app order.
        Ref: CapacityScheduler.allocateContainersToNode:1747."""
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None:
                return
            # Drain pending opportunistic remainders first — per-node
            # queue slots may have freed since the ask.
            for app in self.apps.values():
                for reqs in app.pending.values():
                    for req in reqs:
                        if req.num_containers > 0 and \
                                getattr(req, "execution_type", "") == \
                                ResourceRequest.EXEC_OPPORTUNISTIC:
                            self._allocate_opportunistic(app, req)
            if not self.REORDER_PER_ASSIGNMENT:
                for app in self._app_order():
                    self._assign_on_node(app, node)
                return
            while True:
                for app in self._app_order():
                    if self._assign_on_node(app, node, max_assign=1):
                        break
                else:
                    return

    def _may_assign(self, app: SchedulerApp, capability: Resource) -> bool:
        return True

    def _label_accessible(self, app: SchedulerApp, label: str) -> bool:
        """May this app's queue use the labeled partition? Base
        schedulers have no queue ACLs → everything accessible."""
        return True

    def _assign_on_node(self, app: SchedulerApp, node: SchedulerNode,
                        max_assign: int = 0) -> int:
        """Assign up to ``max_assign`` containers (0 = unlimited) from this
        app's asks onto the node; returns the number assigned."""
        assigned = 0
        for priority in sorted(app.pending):
            for req in app.pending[priority]:
                while req.num_containers > 0:
                    if getattr(req, "execution_type", "") == \
                            ResourceRequest.EXEC_OPPORTUNISTIC:
                        break  # O-asks drain via the O-allocator only
                    if req.host not in ("*", node.node_id.host):
                        break
                    # Exclusive partitions (ref: SchedulerNode's
                    # partition + RegularContainerAllocator's
                    # precheck): the request's label must equal the
                    # node's, and the queue must be allowed the label.
                    if getattr(req, "node_label", "") != node.label:
                        break
                    if not self._label_accessible(app, node.label):
                        break
                    if not req.capability.fits_in(node.available):
                        break
                    if not self._may_assign(app, req.capability):
                        return assigned
                    cid = self.make_container_id(app.attempt_id,
                                                 app.next_container_seq())
                    container = Container(cid, node.node_id, req.capability,
                                          node.nm_address)
                    node.allocate(container)
                    app.used = app.used.add(req.capability)
                    app.live_containers[cid] = container
                    app.allocated_unfetched.append(container)
                    req.num_containers -= 1
                    assigned += 1
                    if max_assign and assigned >= max_assign:
                        app.pending[priority] = [
                            r for r in app.pending[priority]
                            if r.num_containers > 0]
                        return assigned
            app.pending[priority] = [r for r in app.pending[priority]
                                     if r.num_containers > 0]
        return assigned

    def _app_order(self) -> List[SchedulerApp]:
        raise NotImplementedError

    # ----------------------------------------------------------- preemption

    def preemption_candidates(self, protect=lambda cid: False
                              ) -> List[Tuple[str, Container]]:
        """Containers to preempt so starved queues can reach their
        guarantee: while some queue with unmet pending demand is under its
        guaranteed share and another is over, take the over-queue's
        newest containers (skipping ``protect``-ed ones — AMs). Returns
        [(attempt_id, container)]. Ref: monitor/capacity/
        ProportionalCapacityPreemptionPolicy.java (ideal-allocation walk,
        natural-termination factor collapsed to one-container-per-pass
        granularity). Base schedulers have no guarantees → nothing."""
        return []

    def _guaranteed_share(self, queue: str) -> float:
        return 0.0

    def _preempt_over_guarantee(self, protect) -> List[Tuple[str, Container]]:
        with self.lock:
            total = self.cluster_resource()
            usage: Dict[str, Resource] = {}
            pending: Dict[str, bool] = {}
            for app in self.apps.values():
                usage[app.queue] = usage.get(app.queue, Resource()).add(
                    app.used)
                if app.has_pending():
                    pending[app.queue] = True
            starved = [q for q in pending
                       if usage.get(q, Resource()).dominant_share(total)
                       < self._guaranteed_share(q) - 1e-9]
            if not starved:
                return []
            victims: List[Tuple[str, Container]] = []
            for app in reversed(list(self.apps.values())):  # newest apps
                share = usage.get(app.queue, Resource()).dominant_share(
                    total)
                if share <= self._guaranteed_share(app.queue) + 1e-9:
                    continue
                for cid, c in reversed(list(app.live_containers.items())):
                    if protect(cid):
                        continue
                    victims.append((app.attempt_id, c))
                    break  # one per over-capacity app per pass
            return victims


class FifoScheduler(_BaseScheduler):
    """Single queue, submission order. Ref: scheduler/fifo/FifoScheduler.java."""

    def _app_order(self) -> List[SchedulerApp]:
        return list(self.apps.values())


class QueueConfig:
    def __init__(self, name: str, capacity: float, max_capacity: float = 1.0,
                 labels: Optional[set] = None):
        self.name = name
        self.capacity = capacity        # guaranteed fraction of the cluster
        self.max_capacity = max_capacity
        # accessible-node-labels (ref: CapacitySchedulerConfiguration
        # .getAccessibleNodeLabels); "*" = all partitions
        self.labels = labels or set()


class Reservation:
    """One admitted reservation (ref: reservation/ReservationDefinition +
    InMemoryReservationAllocation): ``amount`` of resource held for
    ``queue`` apps carrying this id during [start, deadline)."""

    __slots__ = ("reservation_id", "queue", "capability", "num_containers",
                 "start", "deadline")

    def __init__(self, reservation_id: str, queue: str,
                 capability: Resource, num_containers: int,
                 start: float, deadline: float):
        self.reservation_id = reservation_id
        self.queue = queue
        self.capability = capability
        self.num_containers = num_containers
        self.start = start
        self.deadline = deadline

    def amount(self) -> Resource:
        r = Resource()
        for _ in range(self.num_containers):
            r = r.add(self.capability)
        return r

    def active(self, now: float) -> bool:
        return self.start <= now < self.deadline


class CapacityScheduler(_BaseScheduler):
    """Flat leaf queues under root with capacity / max-capacity, served
    most-under-served-first by dominant-resource usage ratio; FIFO within a
    queue; hard cap at max_capacity.

    Ref: scheduler/capacity/CapacityScheduler.java + CapacitySchedulerConfiguration —
    config keys mirror the reference's shape:
        yarn.scheduler.capacity.root.queues = a,b
        yarn.scheduler.capacity.root.<q>.capacity = 60          (percent)
        yarn.scheduler.capacity.root.<q>.maximum-capacity = 100 (percent)
    (Hierarchical sub-queues collapse to leaves here; the reference's parent
    queues exist to subdivide capacity, which a flat list with fractions
    expresses equivalently for scheduling purposes.)
    """

    def __init__(self, conf: Configuration, container_id_factory,
                 now_fn=None):
        super().__init__(conf, container_id_factory)
        import time as _time
        self._now = now_fn or _time.time
        self.queues: Dict[str, QueueConfig] = {}
        self.reservations: Dict[str, Reservation] = {}
        # app attempt → reservation id (apps inside a reservation)
        self._app_reservation: Dict[str, str] = {}
        names = conf.get_list("yarn.scheduler.capacity.root.queues",
                              ["default"])
        for name in names:
            cap = conf.get_float(
                f"yarn.scheduler.capacity.root.{name}.capacity",
                100.0 / len(names)) / 100.0
            mx = conf.get_float(
                f"yarn.scheduler.capacity.root.{name}.maximum-capacity",
                100.0) / 100.0
            labels = set(conf.get_list(
                f"yarn.scheduler.capacity.root.{name}"
                f".accessible-node-labels", []))
            self.queues[name] = QueueConfig(name, cap, mx, labels)

    # ------------------------------------------------------- node labels

    def _label_accessible(self, app: SchedulerApp, label: str) -> bool:
        if not label:
            return True  # default partition: everyone
        labels = self.queues[app.queue].labels
        return "*" in labels or label in labels

    # ------------------------------------------------------ reservations

    def submit_reservation(self, res: Reservation) -> None:
        """Admission: concurrently-active reservations must fit in the
        cluster (ref: planning agents' capacity check — the greedy
        agent's availability test collapsed to peak-concurrency)."""
        with self.lock:
            total = self.cluster_resource()
            demand = res.amount()
            for other in self.reservations.values():
                if other.start < res.deadline and                         res.start < other.deadline:
                    demand = demand.add(other.amount())
            if not demand.fits_in(total):
                raise ValueError(
                    f"reservation {res.reservation_id} rejected: "
                    f"{demand!r} exceeds cluster {total!r}")
            self.reservations[res.reservation_id] = res

    def delete_reservation(self, reservation_id: str) -> bool:
        with self.lock:
            return self.reservations.pop(reservation_id, None) is not None

    def add_app(self, attempt_id: str, queue: str, user: str,
                reservation_id: Optional[str] = None) -> None:
        """``queue`` may be a reservation id (ref: apps submitted to the
        reservation's dynamic queue under ReservationSystem)."""
        if reservation_id is None and queue in self.reservations:
            reservation_id = queue
        if reservation_id is not None:
            res = self.reservations.get(reservation_id)
            if res is None:
                raise ValueError(f"unknown reservation {reservation_id!r}")
            queue = res.queue
        if queue not in self.queues:
            raise ValueError(f"unknown queue {queue!r} "
                             f"(have {sorted(self.queues)})")
        _BaseScheduler.add_app(self, attempt_id, queue, user)
        if reservation_id is not None:
            with self.lock:
                self._app_reservation[attempt_id] = reservation_id

    def remove_app(self, attempt_id: str):
        with self.lock:
            self._app_reservation.pop(attempt_id, None)
        return super().remove_app(attempt_id)

    def _reservation_usage(self, rid: str) -> Resource:
        used = Resource()
        for app in self.apps.values():
            if self._app_reservation.get(app.attempt_id) == rid:
                used = used.add(app.used)
        return used

    def _active_reserved_headroom(self) -> Resource:
        """Unconsumed resource of active reservations — the slice
        ordinary apps must keep free."""
        now = self._now()
        headroom = Resource()
        for rid, res in self.reservations.items():
            if not res.active(now):
                continue
            remaining = res.amount().subtract(self._reservation_usage(rid))
            headroom = headroom.add(Resource(
                max(0, remaining.memory_mb), max(0, remaining.vcores),
                max(0, remaining.tpu_chips)))
        return headroom

    def _may_assign(self, app: SchedulerApp, capability: Resource) -> bool:
        """Per-assignment enforcement: queue max-capacity, plus the
        reservation contract — a reserved app allocates against its
        reservation (bypassing queue caps up to the reserved amount,
        ref: the dynamic reservation queue's guaranteed capacity);
        an ordinary app may not eat into active reservations' unused
        headroom (ref: PlanFollower shrinking the default queue)."""
        rid = self._app_reservation.get(app.attempt_id)
        if rid is not None:
            res = self.reservations.get(rid)
            if res is not None and res.active(self._now()):
                used = self._reservation_usage(rid).add(capability)
                if used.fits_in(res.amount()):
                    return True  # inside the reserved envelope
        qc = self.queues[app.queue]
        total = self.cluster_resource()
        after = self._queue_usage()[app.queue].add(capability)
        if after.dominant_share(total) > qc.max_capacity + 1e-9:
            return False
        headroom = self._active_reserved_headroom()
        if not headroom.is_empty():
            free = Resource()
            for n in self.nodes.values():
                free = free.add(n.available)
            left = free.subtract(capability)
            if not headroom.fits_in(left):
                return False
        return True

    def _queue_usage(self) -> Dict[str, Resource]:
        usage: Dict[str, Resource] = {q: Resource() for q in self.queues}
        for app in self.apps.values():
            usage[app.queue] = usage[app.queue].add(app.used)
        return usage

    def _app_order(self) -> List[SchedulerApp]:
        total = self.cluster_resource()
        usage = self._queue_usage()
        # Most-under-served queue first: usage_share / capacity ascending.
        def queue_key(qname: str) -> float:
            qc = self.queues[qname]
            share = usage[qname].dominant_share(total)
            return share / max(qc.capacity, 1e-9)

        ordered_queues = sorted(self.queues, key=queue_key)
        out: List[SchedulerApp] = []
        # Active-reservation apps first: their envelope is promised
        # (ref: reservation queues served before the plan's residual).
        now = self._now()
        for app in self.apps.values():
            rid = self._app_reservation.get(app.attempt_id)
            if rid is not None:
                res = self.reservations.get(rid)
                if res is not None and res.active(now):
                    out.append(app)
        seen = {a.attempt_id for a in out}
        for qname in ordered_queues:
            qc = self.queues[qname]
            share = usage[qname].dominant_share(total)
            if share >= qc.max_capacity:
                continue  # hard cap (ref: maximum-capacity enforcement)
            out.extend(a for a in self.apps.values()
                       if a.queue == qname and a.attempt_id not in seen)
        return out

    def _guaranteed_share(self, queue: str) -> float:
        qc = self.queues.get(queue)
        return qc.capacity if qc is not None else 0.0

    def preemption_candidates(self, protect=lambda cid: False):
        return self._preempt_over_guarantee(protect)


class FairScheduler(_BaseScheduler):
    """Weighted fair sharing over queues, fair within a queue by app usage.

    Ref: scheduler/fair/FairScheduler.java (2,030 LoC) + FSQueue's
    fair-share ordering: queues are served lowest (usage_share / weight)
    first — the steady state puts every queue at usage proportional to
    its weight; apps inside a queue are served smallest-usage first.
    Config (the reference reads fair-scheduler.xml; same shape as keys):
        yarn.scheduler.fair.queues = a,b
        yarn.scheduler.fair.root.<q>.weight = 2.0
    Unknown queues are auto-created with weight 1 (the reference's
    aclSubmitApps/auto-create-by-user behavior, simplified)."""

    REORDER_PER_ASSIGNMENT = True

    def __init__(self, conf: Configuration, container_id_factory):
        super().__init__(conf, container_id_factory)
        self.weights: Dict[str, float] = {}
        for name in conf.get_list("yarn.scheduler.fair.queues", ["default"]):
            self.weights[name] = conf.get_float(
                f"yarn.scheduler.fair.root.{name}.weight", 1.0)

    def add_app(self, attempt_id: str, queue: str, user: str) -> None:
        self.weights.setdefault(queue, 1.0)
        super().add_app(attempt_id, queue, user)

    def _queue_usage(self) -> Dict[str, Resource]:
        usage: Dict[str, Resource] = {q: Resource() for q in self.weights}
        for app in self.apps.values():
            usage[app.queue] = usage[app.queue].add(app.used)
        return usage

    def fair_share(self, queue: str, total: Resource) -> float:
        """This queue's deserved share of the cluster (weight-normalized)."""
        wsum = sum(self.weights.values()) or 1.0
        return self.weights.get(queue, 1.0) / wsum

    def _app_order(self) -> List[SchedulerApp]:
        total = self.cluster_resource()
        usage = self._queue_usage()

        def queue_key(qname: str) -> float:
            share = usage[qname].dominant_share(total)
            return share / max(self.weights.get(qname, 1.0), 1e-9)

        out: List[SchedulerApp] = []
        for qname in sorted(self.weights, key=queue_key):
            apps = [a for a in self.apps.values() if a.queue == qname]
            apps.sort(key=lambda a: a.used.dominant_share(total))
            out.extend(apps)
        return out

    def _guaranteed_share(self, queue: str) -> float:
        return self.fair_share(queue, self.cluster_resource())

    def preemption_candidates(self, protect=lambda cid: False):
        """Fair-share preemption (ref: FSPreemptionThread)."""
        return self._preempt_over_guarantee(protect)


def make_scheduler(conf: Configuration, container_id_factory) -> Scheduler:
    kind = conf.get("yarn.resourcemanager.scheduler.class", "capacity")
    if kind in ("fifo", "FifoScheduler"):
        return FifoScheduler(conf, container_id_factory)
    if kind in ("fair", "FairScheduler"):
        return FairScheduler(conf, container_id_factory)
    return CapacityScheduler(conf, container_id_factory)
