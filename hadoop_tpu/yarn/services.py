"""Long-running YARN services framework.

Counterpart of hadoop-yarn-applications/hadoop-yarn-services (ref:
hadoop-yarn-services-core — ServiceMaster.java keeps each component at
its target instance count, restarting exited containers;
ServiceClient.java submits/flexes/stops; ClientAMProtocol.proto is the
client↔AM control channel; the service spec is the JSON "Service" model
of ServiceApiUtil).

The AM publishes its control RPC endpoint through the app report's
tracking URL (``htpu-am://host:port``) — the reference does the same
dance via the registry; the registry-based lookup also works here
(`hadoop_tpu.registry`), but the tracking URL needs no extra daemon.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc.errors import RpcError
from hadoop_tpu.ipc import Client, Server, get_proxy
from hadoop_tpu.yarn.client import AMRMClient, NMClient, YarnClient
from hadoop_tpu.yarn.records import (ApplicationSubmissionContext, AppState,
                                     ContainerLaunchContext, Resource)

log = logging.getLogger(__name__)

RESTART_ALWAYS = "ALWAYS"        # long-running daemons
RESTART_ON_FAILURE = "ON_FAILURE"
RESTART_NEVER = "NEVER"


class Component:
    """Ref: the 'Component' object of the YARN service REST model."""

    def __init__(self, name: str, number_of_containers: int,
                 launch_command: List[str],
                 resource: Optional[Resource] = None,
                 restart_policy: str = RESTART_ALWAYS):
        self.name = name
        self.number_of_containers = number_of_containers
        self.launch_command = launch_command
        self.resource = resource or Resource(128, 1)
        self.restart_policy = restart_policy

    def to_dict(self) -> Dict:
        return {"name": self.name, "n": self.number_of_containers,
                "cmd": self.launch_command,
                "r": self.resource.to_wire(),
                "restart": self.restart_policy}

    @classmethod
    def from_dict(cls, d: Dict) -> "Component":
        return cls(d["name"], d["n"], d["cmd"],
                   Resource.from_wire(d["r"]), d.get("restart",
                                                     RESTART_ALWAYS))


class ServiceSpec:
    """Ref: the 'Service' object (ServiceApiUtil.java validates it)."""

    def __init__(self, name: str, components: List[Component]):
        self.name = name
        self.components = components

    def to_json(self) -> str:
        return json.dumps({"name": self.name,
                           "components": [c.to_dict()
                                          for c in self.components]})

    @classmethod
    def from_json(cls, s: str) -> "ServiceSpec":
        d = json.loads(s)
        return cls(d["name"], [Component.from_dict(c)
                               for c in d["components"]])


class _ClientAMProtocol:
    """The AM-side control face (ref: ClientAMProtocol.proto —
    flexComponents / getStatus / stop)."""

    def __init__(self, master: "ServiceMaster"):
        self.master = master

    def get_status(self) -> Dict:
        return self.master.status()

    def flex_component(self, name: str, count: int) -> bool:
        return self.master.flex(name, count)

    def stop_service(self) -> bool:
        self.master.request_stop()
        return True


class _Instance:
    __slots__ = ("container", "index", "started_at")

    def __init__(self, container, index: int):
        self.container = container
        self.index = index
        self.started_at = time.time()


class ServiceMaster:
    """The service AM. Ref: ServiceMaster.java + ServiceScheduler.java:
    one allocate loop reconciling actual instances against each
    component's target, relaunching per restart policy."""

    def __init__(self, spec: ServiceSpec,
                 conf: Optional[Configuration] = None):
        self.spec = spec
        self.conf = conf or Configuration()
        self.targets: Dict[str, int] = {
            c.name: c.number_of_containers for c in spec.components}
        self.components: Dict[str, Component] = {
            c.name: c for c in spec.components}
        self.instances: Dict[str, List[_Instance]] = {
            c.name: [] for c in spec.components}
        # container_id str → (component, instance)
        self._by_container: Dict[str, Tuple[str, _Instance]] = {}
        self._outstanding: Dict[str, int] = {
            c.name: 0 for c in spec.components}
        self._next_index: Dict[str, int] = {
            c.name: 0 for c in spec.components}
        self._restarts = 0
        # Containers the AM itself stopped (flex-down / teardown): their
        # terminal exit must not count as a component instance finishing.
        self._am_stopped: set = set()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.amrm: Optional[AMRMClient] = None
        self.nm = NMClient()
        self.rpc: Optional[Server] = None

    # -------------------------------------------------------- control face

    def status(self) -> Dict:
        with self._lock:
            return {
                "name": self.spec.name,
                "state": "STOPPING" if self._stop.is_set() else "STABLE"
                if all(len(self.instances[c]) == self.targets[c]
                       for c in self.targets) else "FLEXING",
                "restarts": self._restarts,
                "components": {
                    c: {"target": self.targets[c],
                        "running": len(self.instances[c]),
                        "containers": [str(i.container.container_id)
                                       for i in self.instances[c]]}
                    for c in self.targets},
            }

    def flex(self, name: str, count: int) -> bool:
        if name not in self.targets or count < 0:
            return False
        with self._lock:
            self.targets[name] = count
            # Flexing down stops the newest surplus instances (ref:
            # ServiceScheduler's flex handling).
            surplus = sorted(self.instances[name],
                             key=lambda i: -i.index)[
                :max(0, len(self.instances[name]) - count)]
        for inst in surplus:
            with self._lock:
                self._am_stopped.add(str(inst.container.container_id))
            try:
                self.nm.stop_container(inst.container)
            except (OSError, IOError):
                pass
        log.info("service %s: flex %s -> %d", self.spec.name, name, count)
        return True

    def request_stop(self) -> None:
        self._stop.set()

    # ----------------------------------------------------------- main loop

    def run(self) -> int:
        self.amrm = AMRMClient.from_env(self.conf)
        self.rpc = Server(self.conf, bind=("127.0.0.1", 0),
                          num_handlers=2, name="service-am")
        self.rpc.register_protocol("ClientAMProtocol",
                                   _ClientAMProtocol(self))
        self.rpc.start()
        self.amrm.register(
            tracking_url=f"htpu-am://127.0.0.1:{self.rpc.port}")
        try:
            while not self._stop.is_set():
                self._reconcile()
                allocated, done = self.amrm.allocate(progress=0.5)
                self._place(allocated)
                self._completed(done)
                time.sleep(0.1)
            self._teardown()
            self.amrm.unregister("SUCCEEDED", "service stopped")
            return 0
        finally:
            self.amrm.close()
            self.rpc.stop()

    def _reconcile(self) -> None:
        """Ask for the gap between target and (running + outstanding)."""
        with self._lock:
            # Distinct priority per component (ref: ServiceScheduler
            # assigns each component its own priority so allocations can
            # be attributed back to the asking component).
            for prio, (name, comp) in enumerate(self.components.items(),
                                                start=1):
                gap = self.targets[name] - len(self.instances[name]) \
                    - self._outstanding[name]
                if gap > 0:
                    self.amrm.add_request(prio, gap, comp.resource)
                    self._outstanding[name] += gap

    def _place(self, allocated) -> None:
        for container in allocated:
            with self._lock:
                # Attribute the allocation to the component whose ask it
                # satisfies: match by capability first so heterogeneous
                # components never receive a container sized for another
                # component's Resource (ref: ServiceScheduler matches by
                # priority; the Container wire record here carries the
                # capability instead).
                name = next(
                    (n for n in self.targets
                     if self._outstanding[n] > 0
                     and self.components[n].resource.memory_mb
                     == container.resource.memory_mb
                     and self.components[n].resource.vcores
                     == container.resource.vcores),
                    None) or next((n for n in self.targets
                                   if self._outstanding[n] > 0), None)
                if name is None:
                    self.amrm.release(container.container_id)
                    continue
                self._outstanding[name] -= 1
                comp = self.components[name]
                # Over target (flexed down while outstanding)?
                if len(self.instances[name]) >= self.targets[name]:
                    self.amrm.release(container.container_id)
                    continue
                idx = self._next_index[name]
                self._next_index[name] += 1
                inst = _Instance(container, idx)
                self.instances[name].append(inst)
                self._by_container[str(container.container_id)] = (name,
                                                                   inst)
            env = {"HTPU_SERVICE": self.spec.name,
                   "HTPU_COMPONENT": name,
                   "HTPU_INSTANCE": str(inst.index)}
            try:
                self.nm.start_container(
                    container,
                    ContainerLaunchContext(comp.launch_command, env))
            except Exception as e:  # noqa: BLE001 — one dead NM must not
                # kill the whole service AM (teardown would skip and
                # every other live instance would orphan); mark this
                # instance failed and re-request a replacement
                log.warning("service %s: start of %s/%d on %s failed: "
                            "%s; re-requesting", self.spec.name, name,
                            inst.index, container.node_id, e)
                with self._lock:
                    self._by_container.pop(str(container.container_id),
                                           None)
                    if inst in self.instances[name]:
                        self.instances[name].remove(inst)
                try:
                    self.amrm.release(container.container_id)
                except (RpcError, OSError) as e:
                    log.debug("release of failed container: %s", e)

    def _completed(self, done) -> None:
        for status in done:
            cid = str(status.container_id)
            with self._lock:
                hit = self._by_container.pop(cid, None)
                if hit is None:
                    continue
                name, inst = hit
                if inst in self.instances[name]:
                    self.instances[name].remove(inst)
                comp = self.components[name]
                policy = comp.restart_policy
                if self._stop.is_set():
                    continue
                if cid in self._am_stopped:
                    # Killed by flex-down: not a completion and not a
                    # failure — never relaunch it, never shrink targets.
                    self._am_stopped.discard(cid)
                    continue
                restart = policy == RESTART_ALWAYS or (
                    policy == RESTART_ON_FAILURE and status.exit_code != 0)
                if restart and \
                        len(self.instances[name]) < self.targets[name]:
                    self._restarts += 1
                    log.info("service %s: %s instance %d exited (%d); "
                             "relaunching", self.spec.name, name,
                             inst.index, status.exit_code)
                elif not restart:
                    # Terminal exit (NEVER, or ON_FAILURE with exit 0):
                    # shrink the target so the next _reconcile doesn't
                    # see a gap and relaunch it forever (ref:
                    # ComponentInstance terminated-instance handling).
                    if self.targets[name] > 0:
                        self.targets[name] -= 1
        # replacements are requested by the next _reconcile pass

    def _teardown(self) -> None:
        """Flex everything to 0 and wait briefly for container exits."""
        with self._lock:
            for name in self.targets:
                self.targets[name] = 0
            live = list(self._by_container)
        for cid in live:
            try:
                name, inst = self._by_container.get(cid, (None, None))
                if inst is not None:
                    with self._lock:
                        self._am_stopped.add(cid)
                    self.nm.stop_container(inst.container)
            except (OSError, IOError, AttributeError):
                pass
        deadline = time.monotonic() + 5.0
        while self._by_container and time.monotonic() < deadline:
            _, done = self.amrm.allocate(progress=1.0)
            self._completed(done)
            time.sleep(0.1)


class ServiceClient:
    """Submit/control services (ref: ServiceClient.java: actionCreate,
    actionFlex, actionStop, getStatus)."""

    def __init__(self, rm_addr: Tuple[str, int],
                 conf: Optional[Configuration] = None):
        self.rm_addr = rm_addr
        self.conf = conf or Configuration()
        self.yc = YarnClient(rm_addr, self.conf)
        self._client = Client(self.conf)

    def submit(self, spec: ServiceSpec):
        app_id, _ = self.yc.create_application()
        env = {"PYTHONPATH": _repo_root(),
               "HTPU_SERVICE_SPEC": spec.to_json()}
        ctx = ApplicationSubmissionContext(
            app_id, spec.name,
            ContainerLaunchContext(
                [sys.executable, "-m", "hadoop_tpu.yarn.services", "--am"],
                env),
            am_resource=Resource(256, 1), app_type="yarn-service")
        self.yc.submit_application(ctx)
        return app_id

    def _am_proxy(self, app_id):
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            report = self.yc.application_report(app_id)
            if report.state in (AppState.FAILED, AppState.KILLED):
                raise IOError(f"service app {report.state}: "
                              f"{report.diagnostics}")
            url = report.tracking_url
            if url.startswith("htpu-am://"):
                host, port = url[len("htpu-am://"):].split(":")
                return get_proxy("ClientAMProtocol", (host, int(port)),
                                 client=self._client)
            time.sleep(0.2)
        raise TimeoutError("service AM did not publish its endpoint")

    def status(self, app_id) -> Dict:
        return self._am_proxy(app_id).get_status()

    def flex(self, app_id, component: str, count: int) -> bool:
        return self._am_proxy(app_id).flex_component(component, count)

    def stop(self, app_id, timeout: float = 30.0) -> bool:
        try:
            self._am_proxy(app_id).stop_service()
        except (OSError, IOError, TimeoutError):
            self.yc.kill_application(app_id)
        report = self.yc.wait_for_completion(app_id, timeout=timeout)
        return report.state == AppState.FINISHED

    def close(self) -> None:
        self.yc.close()
        self._client.stop()


def _repo_root() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    existing = os.environ.get("PYTHONPATH", "")
    return f"{here}:{existing}" if existing else here


def am_main() -> int:
    spec = ServiceSpec.from_json(os.environ["HTPU_SERVICE_SPEC"])
    master = ServiceMaster(spec)
    return master.run()


if __name__ == "__main__":
    if "--am" in sys.argv:
        sys.exit(am_main())
    print("usage: python -m hadoop_tpu.yarn.services --am", file=sys.stderr)
    sys.exit(2)
