"""SharedCacheManager — cluster-wide artifact cache keyed by checksum.

Parity with the reference SCM (ref: hadoop-yarn-server-sharedcachemanager
— ClientProtocolService (use/release), SharedCacheUploaderService
(SCMUploader.proto notify), CleanerService sweeping unreferenced
entries; client side SharedCacheClient.java): apps upload each resource
once, keyed by its SHA-256; later apps ``use`` the cached copy instead
of re-localizing, with per-app references keeping live entries pinned
and a cleaner evicting unreferenced ones after a TTL.

Store layout on the backing FileSystem:
    <root>/<checksum[:2]>/<checksum>/<filename>
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.ipc import Client, Server, get_proxy, idempotent
from hadoop_tpu.service import AbstractService
from hadoop_tpu.util.misc import Daemon

log = logging.getLogger(__name__)


def checksum_file(local_path: str) -> str:
    h = hashlib.sha256()
    with open(local_path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class SCMProtocol:
    def __init__(self, scm: "SharedCacheManager"):
        self.scm = scm

    def use(self, checksum: str, app_id: str) -> Optional[str]:
        return self.scm.use(checksum, app_id)

    def release(self, app_id: str) -> int:
        return self.scm.release(app_id)

    def notify_uploaded(self, checksum: str, filename: str) -> bool:
        return self.scm.notify_uploaded(checksum, filename)

    @idempotent
    def stats(self) -> Dict:
        return self.scm.stats()


class SharedCacheManager(AbstractService):
    def __init__(self, conf: Configuration, fs_uri: str,
                 root: str = "/sharedcache"):
        super().__init__("SharedCacheManager")
        self.fs_uri = fs_uri
        self.root = root
        # checksum → (filename, set of referencing app ids, last_use)
        self._entries: Dict[str, Tuple[str, Set[str], float]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.rpc: Optional[Server] = None
        self._fs: Optional[FileSystem] = None

    def service_init(self, conf: Configuration) -> None:
        self._fs = FileSystem.get(self.fs_uri, conf)
        self._fs.mkdirs(self.root)
        self._scan()
        self.ttl_s = conf.get_time_seconds(
            "yarn.sharedcache.cleaner.resource-ttl", 3600.0)
        self._clean_interval = conf.get_time_seconds(
            "yarn.sharedcache.cleaner.period", 60.0)
        self.rpc = Server(conf, bind=("127.0.0.1", conf.get_int(
            "yarn.sharedcache.port", 0)), num_handlers=4, name="scm")
        self.rpc.register_protocol("SCMProtocol", SCMProtocol(self))

    def service_start(self) -> None:
        self.rpc.start()
        Daemon(self._cleaner_loop, "scm-cleaner").start()
        log.info("SharedCacheManager on :%d (%d cached entries)",
                 self.rpc.port, len(self._entries))

    def service_stop(self) -> None:
        self._stop.set()
        if self.rpc:
            self.rpc.stop()
        if self._fs:
            self._fs.close()

    @property
    def port(self) -> int:
        return self.rpc.port

    # -------------------------------------------------------------- store

    def _entry_dir(self, checksum: str) -> str:
        return f"{self.root}/{checksum[:2]}/{checksum}"

    def _scan(self) -> None:
        """Recover the entry map from the store on restart (ref:
        InMemorySCMStore's initial app-less bootstrap)."""
        try:
            shards = self._fs.list_status(self.root)
        except (IOError, OSError, FileNotFoundError):
            return
        for shard in shards:
            if not shard.is_dir:
                continue
            for ent in self._fs.list_status(shard.path):
                if not ent.is_dir:
                    continue
                checksum = ent.path.rstrip("/").rsplit("/", 1)[-1]
                files = [s for s in self._fs.list_status(ent.path)
                         if not s.is_dir]
                if files:
                    name = files[0].path.rsplit("/", 1)[-1]
                    self._entries[checksum] = (name, set(), time.time())

    def use(self, checksum: str, app_id: str) -> Optional[str]:
        """Cache hit → path + a reference pinning it; miss → None (the
        caller uploads then notifies). Ref: ClientProtocolService.use."""
        with self._lock:
            ent = self._entries.get(checksum)
            if ent is None:
                return None
            name, refs, _ = ent
            refs.add(app_id)
            self._entries[checksum] = (name, refs, time.time())
            return f"{self._entry_dir(checksum)}/{name}"

    def release(self, app_id: str) -> int:
        """Drop every reference this app holds. Ref: the RM's
        AppChecker-driven release on app completion."""
        n = 0
        with self._lock:
            for checksum, (name, refs, ts) in self._entries.items():
                if app_id in refs:
                    refs.discard(app_id)
                    n += 1
        return n

    def notify_uploaded(self, checksum: str, filename: str) -> bool:
        """Ref: SharedCacheUploaderService.notify."""
        path = f"{self._entry_dir(checksum)}/{filename}"
        if not self._fs.exists(path):
            return False
        with self._lock:
            self._entries.setdefault(checksum,
                                     (filename, set(), time.time()))
        return True

    def stats(self) -> Dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "referenced": sum(1 for _, refs, _ in
                                      self._entries.values() if refs)}

    def _cleaner_loop(self) -> None:
        """Evict unreferenced entries past the TTL.
        Ref: CleanerService + CleanerTask."""
        while not self._stop.wait(self._clean_interval):
            now = time.time()
            with self._lock:
                dead = [c for c, (_, refs, ts) in self._entries.items()
                        if not refs and now - ts > self.ttl_s]
                for c in dead:
                    del self._entries[c]
                # delete UNDER the lock: releasing it between the map
                # removal and the fs delete let a concurrent
                # miss→re-upload→notify re-insert the entry, and the
                # delete then removed the fresh upload while the map
                # kept advertising it (every later use() returned a
                # path to nothing)
                for c in dead:
                    try:
                        self._fs.delete(self._entry_dir(c),
                                        recursive=True)
                        log.info("SCM cleaned %s", c)
                    except (IOError, OSError):
                        pass


class SharedCacheClient:
    """Upload/use helper (ref: client-side SharedCacheClient.java)."""

    def __init__(self, scm_addr, fs_uri: str,
                 conf: Optional[Configuration] = None,
                 root: str = "/sharedcache"):
        self.conf = conf or Configuration()
        self._client = Client(self.conf)
        self.scm = get_proxy("SCMProtocol", scm_addr, client=self._client)
        self.fs = FileSystem.get(fs_uri, self.conf)
        self.root = root

    def use(self, local_path: str, app_id: str) -> str:
        """Cached path for this file, uploading on first use."""
        checksum = checksum_file(local_path)
        cached = self.scm.use(checksum, app_id)
        if cached is not None:
            return cached
        name = local_path.rsplit("/", 1)[-1]
        dst = f"{self.root}/{checksum[:2]}/{checksum}/{name}"
        self.fs.mkdirs(dst.rsplit("/", 1)[0])
        with open(local_path, "rb") as src:
            with self.fs.create(dst, overwrite=True) as out:
                for chunk in iter(lambda: src.read(1 << 20), b""):
                    out.write(chunk)
        self.scm.notify_uploaded(checksum, name)
        got = self.scm.use(checksum, app_id)
        return got if got is not None else dst

    def release(self, app_id: str) -> int:
        return self.scm.release(app_id)

    def close(self) -> None:
        self._client.stop()
        self.fs.close()
