"""Timeline / application-history service.

Parity with the reference's app-history tier (ref:
hadoop-yarn-server-applicationhistoryservice — the v1 history store the
RM publishes app lifecycle into, with ApplicationHistoryServer's REST
face /ws/v1/applicationhistory; ATSv2's entity model collapses to the
same app/attempt entities at this scope): the RM writes one JSON event
per app transition into an append-only store, and the history server
serves finished (and live) apps REST-side so the cluster's job past
survives RM restarts and app completion.
"""

from __future__ import annotations

import json
import logging
import os
import sqlite3
import threading
import time
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.http.server import HttpServer
from hadoop_tpu.service import AbstractService

log = logging.getLogger(__name__)


class TimelineStore:
    """Append-only entity/event store on local disk (ref:
    applicationhistoryservice's FileSystemApplicationHistoryStore — one
    writer, many readers; events keyed by entity id)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "timeline.jsonl")
        self._lock = threading.Lock()

    def put_event(self, entity_type: str, entity_id: str, event: str,
                  **info) -> None:
        self.put_events([(entity_type, entity_id, event, info)])

    def put_events(self, batch) -> None:
        """Append many (type, id, event, info) records in one write —
        the batch API the NM collectors flush through. One unbuffered
        O_APPEND write(2) for the whole batch: buffered text IO would
        split a >8 KB batch across syscalls, letting another process's
        append land mid-record (RM publisher and NM collectors may
        share one store file)."""
        now = time.time()
        data = "".join(
            json.dumps({"type": t, "id": i, "event": e, "ts": now,
                        "info": info}) + "\n"
            for t, i, e, info in batch).encode()
        with self._lock:
            with open(self._path, "ab", buffering=0) as f:
                f.write(data)

    def close(self) -> None:  # symmetry with SqliteTimelineStore
        pass

    def events(self, entity_type: Optional[str] = None,
               entity_id: Optional[str] = None) -> List[Dict]:
        out: List[Dict] = []
        if not os.path.exists(self._path):
            return out
        with open(self._path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if entity_type and rec.get("type") != entity_type:
                    continue
                if entity_id and rec.get("id") != entity_id:
                    continue
                out.append(rec)
        return out

    def entities(self, entity_type: str) -> Dict[str, Dict]:
        """Fold events into per-entity summaries (latest info wins)."""
        ents: Dict[str, Dict] = {}
        for rec in self.events(entity_type):
            e = ents.setdefault(rec["id"], {"id": rec["id"], "events": []})
            e["events"].append(rec["event"])
            e.update({k: v for k, v in rec["info"].items()
                      if v is not None})
        return ents


class SqliteTimelineStore:
    """Indexed persistent store — the external-DB backend analog (ref:
    ATSv2's HBase / v1's leveldb timeline stores: the reference keeps
    timeline data in an indexed store precisely so reads don't scan the
    full event history). Same contract as TimelineStore, but
    (type, id)-indexed queries instead of a full-file fold, and WAL mode
    so a reader daemon in another process sees a writer's events live.
    """

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "timeline.db")
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(self._path,
                                     check_same_thread=False)
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS events("
                " seq INTEGER PRIMARY KEY AUTOINCREMENT,"
                " type TEXT NOT NULL, id TEXT NOT NULL,"
                " event TEXT NOT NULL, ts REAL NOT NULL,"
                " info TEXT NOT NULL)")
            self._conn.execute(
                "CREATE INDEX IF NOT EXISTS idx_events_type_id"
                " ON events(type, id)")
            self._conn.commit()

    def put_event(self, entity_type: str, entity_id: str, event: str,
                  **info) -> None:
        self.put_events([(entity_type, entity_id, event, info)])

    def put_events(self, batch) -> None:
        """One transaction per batch: a 32-event collector flush costs
        one commit, not 32."""
        now = time.time()
        rows = [(t, i, e, now, json.dumps(info))
                for t, i, e, info in batch]
        with self._lock:
            self._conn.executemany(
                "INSERT INTO events(type, id, event, ts, info)"
                " VALUES(?,?,?,?,?)", rows)
            self._conn.commit()

    def events(self, entity_type: Optional[str] = None,
               entity_id: Optional[str] = None) -> List[Dict]:
        sql = "SELECT type, id, event, ts, info FROM events"
        clauses, params = [], []
        if entity_type:
            clauses.append("type = ?")
            params.append(entity_type)
        if entity_id:
            clauses.append("id = ?")
            params.append(entity_id)
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += " ORDER BY seq"
        with self._lock:
            rows = self._conn.execute(sql, params).fetchall()
        return [{"type": t, "id": i, "event": e, "ts": ts,
                 "info": json.loads(info)}
                for t, i, e, ts, info in rows]

    # identical fold to TimelineStore, but over an indexed scan
    entities = TimelineStore.entities

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def make_store(directory: str, backend: str = "auto"):
    """Store factory. backend: "jsonl" | "sqlite" | "auto". Auto honors
    whatever already lives in the directory (a reader must open the
    format the writer chose); empty directories default to jsonl, the
    reference's FileSystem-store-like baseline.

    NOTE for readers: auto-detection is a snapshot of the directory at
    call time — a reader that may start before the writer's first event
    must re-resolve per query (see FlowRunAggregator), not bind once.
    """
    if backend == "auto":
        has_db = os.path.exists(os.path.join(directory, "timeline.db"))
        has_jl = os.path.exists(
            os.path.join(directory, "timeline.jsonl"))
        if has_db and has_jl:
            log.warning(
                "timeline dir %s holds BOTH timeline.db and "
                "timeline.jsonl (a backend switch without migration?); "
                "reading the sqlite store — jsonl history is invisible "
                "until migrated", directory)
        backend = "sqlite" if has_db else "jsonl"
    if backend == "sqlite":
        return SqliteTimelineStore(directory)
    if backend == "jsonl":
        return TimelineStore(directory)
    raise ValueError(f"unknown timeline store backend: {backend!r}")


class _AutoStoreView:
    """Read-side store handle that defers backend detection until the
    writer's file actually exists: a reader daemon brought up against a
    still-empty directory must not bind the jsonl default forever while
    the writer goes on to create timeline.db. Resolution is retried per
    query until a concrete store file is seen, then cached (so sqlite
    readers reuse one WAL connection)."""

    def __init__(self, directory: str, backend: str = "auto"):
        self.dir = directory
        self._backend = backend
        self._bound = None
        self._resolve_lock = threading.Lock()

    def _resolve(self):
        # Locked: handler threads share one view, and two racing first
        # queries must not each open (and one leak) a store connection.
        with self._resolve_lock:
            if self._bound is not None:
                return self._bound
            st = make_store(self.dir, self._backend)
            # Bind only when the file matching the RESOLVED store's own
            # format exists — checking for "any store file" would race a
            # writer creating timeline.db between our detection snapshot
            # and this check, caching the jsonl default forever.
            if self._backend != "auto" or os.path.exists(st._path):
                self._bound = st
            return st

    def events(self, *args, **kwargs):
        return self._resolve().events(*args, **kwargs)

    def entities(self, *args, **kwargs):
        return self._resolve().entities(*args, **kwargs)

    def close(self) -> None:
        if self._bound is not None:
            self._bound.close()
            self._bound = None


class TimelinePublisher:
    """RM-side publisher (ref: SystemMetricsPublisher — the RM component
    that forwards app/attempt transitions into the timeline)."""

    def __init__(self, store: TimelineStore):
        self.store = store

    def close(self) -> None:
        self.store.close()

    def app_submitted(self, app_id: str, name: str, user: str,
                      queue: str) -> None:
        self.store.put_event("YARN_APPLICATION", app_id, "SUBMITTED",
                             name=name, user=user, queue=queue)

    def app_attempt(self, app_id: str, attempt_id: str) -> None:
        self.store.put_event("YARN_APPLICATION", app_id, "ATTEMPT",
                             attempt=attempt_id)

    def app_finished(self, app_id: str, state: str, diagnostics: str
                     ) -> None:
        self.store.put_event("YARN_APPLICATION", app_id, "FINISHED",
                             state=state, diagnostics=diagnostics[:500])


class ApplicationHistoryServer(AbstractService):
    """REST over the store (ref: ApplicationHistoryServer + its
    WebServices — /ws/v1/applicationhistory/apps[/{appid}])."""

    def __init__(self, conf: Configuration, store_dir: str):
        super().__init__("ApplicationHistoryServer")
        self.store = _AutoStoreView(store_dir, conf.get(
            "yarn.timeline-service.store.backend", "auto"))
        self.http: Optional[HttpServer] = None

    def service_init(self, conf: Configuration) -> None:
        self.http = HttpServer(
            conf, ("127.0.0.1",
                   conf.get_int("yarn.timeline-service.webapp.port", 0)),
            daemon_name="ahs")
        self.http.add_handler("/ws/v1/applicationhistory/apps", self._apps)

    def service_start(self) -> None:
        self.http.start()
        log.info("ApplicationHistoryServer on :%d", self.http.port)

    def service_stop(self) -> None:
        if self.http:
            self.http.stop()
        self.store.close()

    @property
    def port(self) -> int:
        return self.http.port

    def _apps(self, query: Dict, body: bytes):
        path = query["__path__"]
        tail = path[len("/ws/v1/applicationhistory/apps"):].strip("/")
        ents = self.store.entities("YARN_APPLICATION")
        if not tail:
            return 200, {"apps": {"app": sorted(
                ents.values(), key=lambda e: e["id"])}}
        app = ents.get(tail)
        if app is None:
            raise FileNotFoundError(tail)
        return 200, {"app": app}


class AppLevelTimelineCollector:
    """Per-application collector (ref: ATSv2's
    hadoop-yarn-server-timelineservice TimelineCollector +
    AppLevelTimelineCollector): buffers one app's entities NM-side and
    flushes them to the backing store in batches, with a final flush on
    stop — the write path AMs/containers publish through in v2 instead
    of posting to a central daemon."""

    def __init__(self, app_id: str, store: TimelineStore,
                 flush_every: int = 32):
        self.app_id = app_id
        self.store = store
        self.flush_every = flush_every
        self._buf: List[Dict] = []
        self._lock = threading.Lock()
        self.stopped = False

    def put_entity(self, entity_type: str, entity_id: str, event: str,
                   **info) -> None:
        rec = {"type": entity_type, "id": entity_id, "event": event,
               "ts": time.time(),
               "info": dict(info, app_id=self.app_id)}
        with self._lock:
            if self.stopped:
                return
            self._buf.append(rec)
            # batch ordinary events; push terminal ones straight through
            # (a container's FINISHED carries the resource-time metrics
            # readers aggregate — it must not wait out the batch window)
            if len(self._buf) >= self.flush_every or event == "FINISHED":
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf:
            self.store.put_events([
                (rec["type"], rec["id"], rec["event"], rec["info"])
                for rec in self._buf])
        self._buf = []

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def stop(self) -> None:
        with self._lock:
            if self.stopped:
                return
            self._buf.append({"type": "YARN_APPLICATION",
                              "id": self.app_id,
                              "event": "COLLECTOR_STOPPED", "ts":
                              time.time(), "info": {}})
            self._flush_locked()
            self.stopped = True


class TimelineCollectorManager:
    """NM-side collector lifecycle (ref: ATSv2
    NodeTimelineCollectorManager / PerNodeTimelineCollectorsAuxService):
    a collector exists per app from its first container's start on this
    node until the RM reports the app finished."""

    def __init__(self, store_dir: str, backend: str = "auto"):
        self.store = make_store(store_dir, backend)
        self._collectors: Dict[str, AppLevelTimelineCollector] = {}
        self._lock = threading.Lock()

    def collector_for(self, app_id: str) -> AppLevelTimelineCollector:
        with self._lock:
            c = self._collectors.get(app_id)
            if c is None or c.stopped:
                c = AppLevelTimelineCollector(app_id, self.store)
                self._collectors[app_id] = c
                c.put_entity("YARN_APPLICATION", app_id,
                             "COLLECTOR_STARTED")
            return c

    def has_collector(self, app_id: str) -> bool:
        with self._lock:
            c = self._collectors.get(app_id)
            return c is not None and not c.stopped

    def put_if_active(self, app_id: str, *args, **kwargs) -> bool:
        """Atomic has_collector + put: a straggler event either lands on
        the still-live collector or is dropped — the separate
        check-then-put raced the linger timer and RESURRECTED a stopped
        collector (collector_for creates), leaking it forever."""
        with self._lock:
            c = self._collectors.get(app_id)
            if c is None or c.stopped:
                return False
            c.put_entity(*args, **kwargs)
            return True

    def stop_collector(self, app_id: str, linger_s: float = 1.0) -> None:
        """Stop after a short LINGER: the RM's app-finished report can
        beat the app's last container-FINISHED events to this NM by a
        heartbeat, and the final events carry the resource-time metrics
        flow aggregation needs (ref: the reference collector outliving
        the app until its final entities are published). The collector
        keeps accepting during the grace window; the timer closes it."""
        with self._lock:
            c = self._collectors.get(app_id)
        if c is None:
            return
        if linger_s <= 0:
            with self._lock:
                if self._collectors.get(app_id) is c:
                    self._collectors.pop(app_id)
            c.stop()
            return

        def _close():
            # keep the collector REACHABLE while lingering (late events
            # route through has_collector/collector_for); identity-guard
            # the pop so a resurrected app's fresh collector survives
            with self._lock:
                if self._collectors.get(app_id) is c:
                    self._collectors.pop(app_id)
            c.stop()
        t = threading.Timer(linger_s, _close)
        t.daemon = True
        t.start()

    def active_apps(self) -> List[str]:
        with self._lock:
            return sorted(a for a, c in self._collectors.items()
                          if not c.stopped)

    def stop_all(self) -> None:
        with self._lock:
            cs = list(self._collectors.values())
            self._collectors.clear()
        for c in cs:
            c.stop()
        self.store.close()


# ------------------------------------------------------------- ATSv2 reader

class FlowRunAggregator:
    """Fold raw timeline events into flows → flow runs → apps with
    aggregated resource metrics (ref: ATSv2's flow-run aggregation —
    hadoop-yarn-server-timelineservice FlowRunEntity /
    HBaseTimelineReaderImpl's flow tables; here computed from the
    JSONL stores on read, one pass).

    Flow semantics (reference defaults): flow name = the app's NAME,
    flow run = the submission DAY — apps resubmitted under one name
    aggregate into the same daily run, answering "what does this
    pipeline cost per day".
    """

    def __init__(self, store_dirs: List[str], backend: str = "auto"):
        self.stores = [_AutoStoreView(d, backend) for d in store_dirs]

    def _all_events(self) -> List[Dict]:
        out: List[Dict] = []
        for st in self.stores:
            out.extend(st.events())
        return out

    def snapshot(self) -> Dict:
        """One pass over every store: apps (with per-app aggregated
        container metrics) + flows + flow runs."""
        apps: Dict[str, Dict] = {}
        containers: Dict[str, Dict] = {}
        for rec in self._all_events():
            info = rec.get("info") or {}
            if rec.get("type") == "YARN_APPLICATION":
                a = apps.setdefault(rec["id"], {
                    "id": rec["id"], "events": [],
                    "metrics": {"containers": 0, "mb_seconds": 0.0,
                                "vcore_seconds": 0.0,
                                "container_seconds": 0.0}})
                a["events"].append(rec["event"])
                if rec["event"] == "SUBMITTED":
                    a["submit_ts"] = rec.get("ts")
                a.update({k: v for k, v in info.items()
                          if v is not None and k != "app_id"})
            elif rec.get("type") == "YARN_CONTAINER":
                c = containers.setdefault(rec["id"], {})
                c.update(info)
        for c in containers.values():
            app = apps.get(c.get("app_id"))
            if app is None or "mb_seconds" not in c:
                continue
            m = app["metrics"]
            m["containers"] += 1
            m["mb_seconds"] += c.get("mb_seconds", 0.0)
            m["vcore_seconds"] += c.get("vcore_seconds", 0.0)
            m["container_seconds"] += c.get("duration_s", 0.0)
        flows: Dict[str, Dict] = {}
        for app in apps.values():
            flow_name = app.get("flow_name") or app.get("name") \
                or app["id"]
            ts = app.get("submit_ts") or 0
            run_id = time.strftime("%Y%m%d", time.gmtime(ts))
            fl = flows.setdefault(flow_name, {"flow": flow_name,
                                              "runs": {}})
            run = fl["runs"].setdefault(run_id, {
                "run_id": run_id, "apps": [],
                "metrics": {"containers": 0, "mb_seconds": 0.0,
                            "vcore_seconds": 0.0,
                            "container_seconds": 0.0}})
            run["apps"].append(app["id"])
            for k in run["metrics"]:
                run["metrics"][k] += app["metrics"][k]
        return {"apps": apps, "flows": flows}


class TimelineReaderServer(AbstractService):
    """The ATSv2 READER half (ref: timelineservice's
    TimelineReaderServer + TimelineReaderWebServices — /ws/v2/timeline):
    REST queries over the collector stores, including flow-run
    aggregated metrics, so the timeline can answer "what did app X /
    flow Y cost"."""

    def __init__(self, conf: Configuration, store_dirs: List[str]):
        super().__init__("TimelineReaderServer")
        self.aggregator = FlowRunAggregator(store_dirs, conf.get(
            "yarn.timeline-service.store.backend", "auto"))
        self.http: Optional[HttpServer] = None

    def service_init(self, conf: Configuration) -> None:
        self.http = HttpServer(
            conf, ("127.0.0.1", conf.get_int(
                "yarn.timeline-service.reader.webapp.port", 0)),
            daemon_name="timeline-reader")
        self.http.add_handler("/ws/v2/timeline", self._route)

    def service_start(self) -> None:
        self.http.start()
        log.info("TimelineReaderServer on :%d", self.http.port)

    def service_stop(self) -> None:
        if self.http:
            self.http.stop()
        for st in self.aggregator.stores:
            st.close()

    @property
    def port(self) -> int:
        return self.http.port

    def _route(self, query: Dict, body: bytes):
        path = query["__path__"][len("/ws/v2/timeline"):].strip("/")
        parts = [p for p in path.split("/") if p]
        snap = self.aggregator.snapshot()
        if not parts or parts == ["flows"]:
            return 200, {"flows": [
                {"flow": f["flow"], "num_runs": len(f["runs"])}
                for f in sorted(snap["flows"].values(),
                                key=lambda x: x["flow"])]}
        if parts[0] == "flowruns" and len(parts) >= 2:
            fl = snap["flows"].get(parts[1])
            if fl is None:
                raise FileNotFoundError(parts[1])
            runs = sorted(fl["runs"].values(),
                          key=lambda r: r["run_id"])
            if len(parts) == 2:
                return 200, {"flow": parts[1], "runs": runs}
            run = fl["runs"].get(parts[2])
            if run is None:
                raise FileNotFoundError(parts[2])
            return 200, run
        if parts[0] == "apps" and len(parts) >= 2:
            app = snap["apps"].get(parts[1])
            if app is None:
                raise FileNotFoundError(parts[1])
            if len(parts) == 2:
                return 200, {"app": app}
            # /apps/{id}/entities/{type}: raw entities filtered to app
            if len(parts) == 4 and parts[2] == "entities":
                ents = []
                for st in self.aggregator.stores:
                    for rec in st.events(entity_type=parts[3]):
                        if (rec.get("info") or {}).get("app_id") == \
                                parts[1] or rec.get("id") == parts[1]:
                            ents.append(rec)
                return 200, {"entities": ents}
        raise FileNotFoundError(path)
