"""Timeline / application-history service.

Parity with the reference's app-history tier (ref:
hadoop-yarn-server-applicationhistoryservice — the v1 history store the
RM publishes app lifecycle into, with ApplicationHistoryServer's REST
face /ws/v1/applicationhistory; ATSv2's entity model collapses to the
same app/attempt entities at this scope): the RM writes one JSON event
per app transition into an append-only store, and the history server
serves finished (and live) apps REST-side so the cluster's job past
survives RM restarts and app completion.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from hadoop_tpu.conf import Configuration
from hadoop_tpu.http.server import HttpServer
from hadoop_tpu.service import AbstractService

log = logging.getLogger(__name__)


class TimelineStore:
    """Append-only entity/event store on local disk (ref:
    applicationhistoryservice's FileSystemApplicationHistoryStore — one
    writer, many readers; events keyed by entity id)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "timeline.jsonl")
        self._lock = threading.Lock()

    def put_event(self, entity_type: str, entity_id: str, event: str,
                  **info) -> None:
        rec = {"type": entity_type, "id": entity_id, "event": event,
               "ts": time.time(), "info": info}
        with self._lock:
            with open(self._path, "a") as f:
                f.write(json.dumps(rec) + "\n")

    def events(self, entity_type: Optional[str] = None,
               entity_id: Optional[str] = None) -> List[Dict]:
        out: List[Dict] = []
        if not os.path.exists(self._path):
            return out
        with open(self._path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if entity_type and rec.get("type") != entity_type:
                    continue
                if entity_id and rec.get("id") != entity_id:
                    continue
                out.append(rec)
        return out

    def entities(self, entity_type: str) -> Dict[str, Dict]:
        """Fold events into per-entity summaries (latest info wins)."""
        ents: Dict[str, Dict] = {}
        for rec in self.events(entity_type):
            e = ents.setdefault(rec["id"], {"id": rec["id"], "events": []})
            e["events"].append(rec["event"])
            e.update({k: v for k, v in rec["info"].items()
                      if v is not None})
        return ents


class TimelinePublisher:
    """RM-side publisher (ref: SystemMetricsPublisher — the RM component
    that forwards app/attempt transitions into the timeline)."""

    def __init__(self, store: TimelineStore):
        self.store = store

    def app_submitted(self, app_id: str, name: str, user: str,
                      queue: str) -> None:
        self.store.put_event("YARN_APPLICATION", app_id, "SUBMITTED",
                             name=name, user=user, queue=queue)

    def app_attempt(self, app_id: str, attempt_id: str) -> None:
        self.store.put_event("YARN_APPLICATION", app_id, "ATTEMPT",
                             attempt=attempt_id)

    def app_finished(self, app_id: str, state: str, diagnostics: str
                     ) -> None:
        self.store.put_event("YARN_APPLICATION", app_id, "FINISHED",
                             state=state, diagnostics=diagnostics[:500])


class ApplicationHistoryServer(AbstractService):
    """REST over the store (ref: ApplicationHistoryServer + its
    WebServices — /ws/v1/applicationhistory/apps[/{appid}])."""

    def __init__(self, conf: Configuration, store_dir: str):
        super().__init__("ApplicationHistoryServer")
        self.store = TimelineStore(store_dir)
        self.http: Optional[HttpServer] = None

    def service_init(self, conf: Configuration) -> None:
        self.http = HttpServer(
            conf, ("127.0.0.1",
                   conf.get_int("yarn.timeline-service.webapp.port", 0)),
            daemon_name="ahs")
        self.http.add_handler("/ws/v1/applicationhistory/apps", self._apps)

    def service_start(self) -> None:
        self.http.start()
        log.info("ApplicationHistoryServer on :%d", self.http.port)

    def service_stop(self) -> None:
        if self.http:
            self.http.stop()

    @property
    def port(self) -> int:
        return self.http.port

    def _apps(self, query: Dict, body: bytes):
        path = query["__path__"]
        tail = path[len("/ws/v1/applicationhistory/apps"):].strip("/")
        ents = self.store.entities("YARN_APPLICATION")
        if not tail:
            return 200, {"apps": {"app": sorted(
                ents.values(), key=lambda e: e["id"])}}
        app = ents.get(tail)
        if app is None:
            raise FileNotFoundError(tail)
        return 200, {"app": app}


class AppLevelTimelineCollector:
    """Per-application collector (ref: ATSv2's
    hadoop-yarn-server-timelineservice TimelineCollector +
    AppLevelTimelineCollector): buffers one app's entities NM-side and
    flushes them to the backing store in batches, with a final flush on
    stop — the write path AMs/containers publish through in v2 instead
    of posting to a central daemon."""

    def __init__(self, app_id: str, store: TimelineStore,
                 flush_every: int = 32):
        self.app_id = app_id
        self.store = store
        self.flush_every = flush_every
        self._buf: List[Dict] = []
        self._lock = threading.Lock()
        self.stopped = False

    def put_entity(self, entity_type: str, entity_id: str, event: str,
                   **info) -> None:
        rec = {"type": entity_type, "id": entity_id, "event": event,
               "ts": time.time(),
               "info": dict(info, app_id=self.app_id)}
        with self._lock:
            if self.stopped:
                return
            self._buf.append(rec)
            # batch ordinary events; push terminal ones straight through
            # (a container's FINISHED carries the resource-time metrics
            # readers aggregate — it must not wait out the batch window)
            if len(self._buf) >= self.flush_every or event == "FINISHED":
                self._flush_locked()

    def _flush_locked(self) -> None:
        for rec in self._buf:
            self.store.put_event(rec["type"], rec["id"], rec["event"],
                                 **rec["info"])
        self._buf = []

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def stop(self) -> None:
        with self._lock:
            if self.stopped:
                return
            self._buf.append({"type": "YARN_APPLICATION",
                              "id": self.app_id,
                              "event": "COLLECTOR_STOPPED", "ts":
                              time.time(), "info": {}})
            self._flush_locked()
            self.stopped = True


class TimelineCollectorManager:
    """NM-side collector lifecycle (ref: ATSv2
    NodeTimelineCollectorManager / PerNodeTimelineCollectorsAuxService):
    a collector exists per app from its first container's start on this
    node until the RM reports the app finished."""

    def __init__(self, store_dir: str):
        self.store = TimelineStore(store_dir)
        self._collectors: Dict[str, AppLevelTimelineCollector] = {}
        self._lock = threading.Lock()

    def collector_for(self, app_id: str) -> AppLevelTimelineCollector:
        with self._lock:
            c = self._collectors.get(app_id)
            if c is None or c.stopped:
                c = AppLevelTimelineCollector(app_id, self.store)
                self._collectors[app_id] = c
                c.put_entity("YARN_APPLICATION", app_id,
                             "COLLECTOR_STARTED")
            return c

    def has_collector(self, app_id: str) -> bool:
        with self._lock:
            c = self._collectors.get(app_id)
            return c is not None and not c.stopped

    def put_if_active(self, app_id: str, *args, **kwargs) -> bool:
        """Atomic has_collector + put: a straggler event either lands on
        the still-live collector or is dropped — the separate
        check-then-put raced the linger timer and RESURRECTED a stopped
        collector (collector_for creates), leaking it forever."""
        with self._lock:
            c = self._collectors.get(app_id)
            if c is None or c.stopped:
                return False
            c.put_entity(*args, **kwargs)
            return True

    def stop_collector(self, app_id: str, linger_s: float = 1.0) -> None:
        """Stop after a short LINGER: the RM's app-finished report can
        beat the app's last container-FINISHED events to this NM by a
        heartbeat, and the final events carry the resource-time metrics
        flow aggregation needs (ref: the reference collector outliving
        the app until its final entities are published). The collector
        keeps accepting during the grace window; the timer closes it."""
        with self._lock:
            c = self._collectors.get(app_id)
        if c is None:
            return
        if linger_s <= 0:
            with self._lock:
                if self._collectors.get(app_id) is c:
                    self._collectors.pop(app_id)
            c.stop()
            return

        def _close():
            # keep the collector REACHABLE while lingering (late events
            # route through has_collector/collector_for); identity-guard
            # the pop so a resurrected app's fresh collector survives
            with self._lock:
                if self._collectors.get(app_id) is c:
                    self._collectors.pop(app_id)
            c.stop()
        t = threading.Timer(linger_s, _close)
        t.daemon = True
        t.start()

    def active_apps(self) -> List[str]:
        with self._lock:
            return sorted(a for a, c in self._collectors.items()
                          if not c.stopped)

    def stop_all(self) -> None:
        with self._lock:
            cs = list(self._collectors.values())
            self._collectors.clear()
        for c in cs:
            c.stop()


# ------------------------------------------------------------- ATSv2 reader

class FlowRunAggregator:
    """Fold raw timeline events into flows → flow runs → apps with
    aggregated resource metrics (ref: ATSv2's flow-run aggregation —
    hadoop-yarn-server-timelineservice FlowRunEntity /
    HBaseTimelineReaderImpl's flow tables; here computed from the
    JSONL stores on read, one pass).

    Flow semantics (reference defaults): flow name = the app's NAME,
    flow run = the submission DAY — apps resubmitted under one name
    aggregate into the same daily run, answering "what does this
    pipeline cost per day".
    """

    def __init__(self, store_dirs: List[str]):
        self.stores = [TimelineStore(d) for d in store_dirs]

    def _all_events(self) -> List[Dict]:
        out: List[Dict] = []
        for st in self.stores:
            out.extend(st.events())
        return out

    def snapshot(self) -> Dict:
        """One pass over every store: apps (with per-app aggregated
        container metrics) + flows + flow runs."""
        apps: Dict[str, Dict] = {}
        containers: Dict[str, Dict] = {}
        for rec in self._all_events():
            info = rec.get("info") or {}
            if rec.get("type") == "YARN_APPLICATION":
                a = apps.setdefault(rec["id"], {
                    "id": rec["id"], "events": [],
                    "metrics": {"containers": 0, "mb_seconds": 0.0,
                                "vcore_seconds": 0.0,
                                "container_seconds": 0.0}})
                a["events"].append(rec["event"])
                if rec["event"] == "SUBMITTED":
                    a["submit_ts"] = rec.get("ts")
                a.update({k: v for k, v in info.items()
                          if v is not None and k != "app_id"})
            elif rec.get("type") == "YARN_CONTAINER":
                c = containers.setdefault(rec["id"], {})
                c.update(info)
        for c in containers.values():
            app = apps.get(c.get("app_id"))
            if app is None or "mb_seconds" not in c:
                continue
            m = app["metrics"]
            m["containers"] += 1
            m["mb_seconds"] += c.get("mb_seconds", 0.0)
            m["vcore_seconds"] += c.get("vcore_seconds", 0.0)
            m["container_seconds"] += c.get("duration_s", 0.0)
        flows: Dict[str, Dict] = {}
        for app in apps.values():
            flow_name = app.get("flow_name") or app.get("name") \
                or app["id"]
            ts = app.get("submit_ts") or 0
            run_id = time.strftime("%Y%m%d", time.gmtime(ts))
            fl = flows.setdefault(flow_name, {"flow": flow_name,
                                              "runs": {}})
            run = fl["runs"].setdefault(run_id, {
                "run_id": run_id, "apps": [],
                "metrics": {"containers": 0, "mb_seconds": 0.0,
                            "vcore_seconds": 0.0,
                            "container_seconds": 0.0}})
            run["apps"].append(app["id"])
            for k in run["metrics"]:
                run["metrics"][k] += app["metrics"][k]
        return {"apps": apps, "flows": flows}


class TimelineReaderServer(AbstractService):
    """The ATSv2 READER half (ref: timelineservice's
    TimelineReaderServer + TimelineReaderWebServices — /ws/v2/timeline):
    REST queries over the collector stores, including flow-run
    aggregated metrics, so the timeline can answer "what did app X /
    flow Y cost"."""

    def __init__(self, conf: Configuration, store_dirs: List[str]):
        super().__init__("TimelineReaderServer")
        self.aggregator = FlowRunAggregator(store_dirs)
        self.http: Optional[HttpServer] = None

    def service_init(self, conf: Configuration) -> None:
        self.http = HttpServer(
            conf, ("127.0.0.1", conf.get_int(
                "yarn.timeline-service.reader.webapp.port", 0)),
            daemon_name="timeline-reader")
        self.http.add_handler("/ws/v2/timeline", self._route)

    def service_start(self) -> None:
        self.http.start()
        log.info("TimelineReaderServer on :%d", self.http.port)

    def service_stop(self) -> None:
        if self.http:
            self.http.stop()

    @property
    def port(self) -> int:
        return self.http.port

    def _route(self, query: Dict, body: bytes):
        path = query["__path__"][len("/ws/v2/timeline"):].strip("/")
        parts = [p for p in path.split("/") if p]
        snap = self.aggregator.snapshot()
        if not parts or parts == ["flows"]:
            return 200, {"flows": [
                {"flow": f["flow"], "num_runs": len(f["runs"])}
                for f in sorted(snap["flows"].values(),
                                key=lambda x: x["flow"])]}
        if parts[0] == "flowruns" and len(parts) >= 2:
            fl = snap["flows"].get(parts[1])
            if fl is None:
                raise FileNotFoundError(parts[1])
            runs = sorted(fl["runs"].values(),
                          key=lambda r: r["run_id"])
            if len(parts) == 2:
                return 200, {"flow": parts[1], "runs": runs}
            run = fl["runs"].get(parts[2])
            if run is None:
                raise FileNotFoundError(parts[2])
            return 200, run
        if parts[0] == "apps" and len(parts) >= 2:
            app = snap["apps"].get(parts[1])
            if app is None:
                raise FileNotFoundError(parts[1])
            if len(parts) == 2:
                return 200, {"app": app}
            # /apps/{id}/entities/{type}: raw entities filtered to app
            if len(parts) == 4 and parts[2] == "entities":
                ents = []
                for st in self.aggregator.stores:
                    for rec in st.events(entity_type=parts[3]):
                        if (rec.get("info") or {}).get("app_id") == \
                                parts[1] or rec.get("id") == parts[1]:
                            ents.append(rec)
                return 200, {"entities": ents}
        raise FileNotFoundError(path)
