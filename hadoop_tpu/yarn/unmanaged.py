"""Unmanaged-AM launcher: run an ApplicationMaster OUTSIDE the cluster.

Parity with the reference tool (ref: hadoop-yarn-applications/
hadoop-yarn-applications-unmanaged-am-launcher/.../UnmanagedAMLauncher
.java): submit an application whose context sets the unmanaged flag —
the RM allocates NO AM container — then run the AM command as a LOCAL
subprocess with the same environment a container-launched AM would see
(attempt id + RM address), so the master registers and drives
``allocate`` from wherever the launcher runs. The standard debugging /
gateway-AM workflow: the AM is attachable, restartable, and lives
outside NM supervision while its containers run on the cluster.
"""

from __future__ import annotations

import logging
import os
import subprocess
import time
from typing import List, Optional, Tuple

from hadoop_tpu.conf import Configuration
from hadoop_tpu.yarn.client import YarnClient
from hadoop_tpu.yarn.records import (ApplicationSubmissionContext, AppState,
                                     ContainerLaunchContext, Resource)

log = logging.getLogger(__name__)


def launch(rm_addr: Tuple[str, int], am_command: List[str],
           name: str = "unmanaged-am",
           conf: Optional[Configuration] = None,
           env: Optional[dict] = None,
           attempt_timeout: float = 30.0):
    """Submit an unmanaged app + run its AM locally. Returns
    (app_id, subprocess returncode) once the AM process exits; the
    caller watches the app's report for the final state."""
    conf = conf or Configuration(load_defaults=False)
    yc = YarnClient(rm_addr, conf)
    try:
        app_id, _ = yc.create_application()
        ctx = ApplicationSubmissionContext(
            app_id, name,
            ContainerLaunchContext(am_command, dict(env or {}), {}),
            Resource(0, 0),  # no AM container — no AM resource ask
            unmanaged=True)
        yc.submit_application(ctx)

        # attempt id appears in the report once the attempt exists
        deadline = time.monotonic() + attempt_timeout
        attempt_no = 0
        while time.monotonic() < deadline:
            report = yc.application_report(app_id)
            if report.state in (AppState.FAILED, AppState.KILLED):
                raise RuntimeError(
                    f"app died before AM start: {report.diagnostics}")
            if report.attempt_no:
                attempt_no = report.attempt_no
                break
            time.sleep(0.1)
        if not attempt_no:
            raise TimeoutError("no attempt created for unmanaged app")
        attempt_id = f"{app_id}_{attempt_no:02d}"

        am_env = dict(os.environ)
        am_env.update(env or {})
        # the same contract amlauncher/AMLauncher.java sets up in a
        # container's environment (rm.py _launch_am)
        am_env["HTPU_ATTEMPT_ID"] = attempt_id
        am_env["HTPU_RM_ADDRESS"] = f"{rm_addr[0]}:{rm_addr[1]}"
        log.info("Launching unmanaged AM for %s locally: %s", attempt_id,
                 am_command)
        proc = subprocess.run(am_command, env=am_env)
        return app_id, proc.returncode
    finally:
        yc.close()


def main(argv=None) -> int:
    import argparse
    import json
    ap = argparse.ArgumentParser(
        prog="unmanaged-am-launcher",
        description="Run an ApplicationMaster outside the cluster "
                    "(ref: the unmanaged-am-launcher tool)")
    ap.add_argument("--rm", required=True, help="host:port")
    ap.add_argument("--name", default="unmanaged-am")
    ap.add_argument("cmd", nargs="+", help="AM command")
    args = ap.parse_args(argv)
    host, _, port = args.rm.rpartition(":")
    app_id, rc = launch((host, int(port)), args.cmd, name=args.name)
    print(json.dumps({"app_id": str(app_id), "am_exit": rc}))
    return 0 if rc == 0 else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
