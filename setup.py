"""Packaging: one wheel + console entry point (the reference's shaded-jar
+ bin/ scripts analog — ref: hadoop-client-modules, hadoop-dist,
src/main/bin/hadoop)."""

from setuptools import find_packages, setup

setup(
    name="hadoop-tpu",
    version="0.1.0",
    description=("TPU-native distributed storage, scheduling, and batch "
                 "compute framework"),
    packages=find_packages(include=["hadoop_tpu", "hadoop_tpu.*"]),
    package_data={"hadoop_tpu.native": ["Makefile", "src/*.cc"]},
    python_requires=">=3.9",
    entry_points={
        "console_scripts": [
            "hadoop-tpu = hadoop_tpu.cli.main:main",
        ],
    },
)
