"""Test harness configuration.

Multi-chip code paths are tested on a virtual 8-device CPU mesh (the
minicluster philosophy of the reference — real protocols, simulated fleet;
ref: MiniDFSCluster.java:157): JAX must see these flags before first import.
"""

import os

# Force, don't default: the environment's sitecustomize force-registers
# the tunneled TPU (axon) PJRT plugin and overrides JAX_PLATFORMS, so the
# env var alone is not enough — jax.config.update is authoritative.
# Tests always run on the virtual 8-device CPU mesh for determinism and
# multi-chip coverage.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import logging

import pytest

logging.basicConfig(level=logging.INFO)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')")


@pytest.fixture(autouse=True)
def _reset_global_state():
    """Each test gets a clean config registry and metrics system."""
    from hadoop_tpu.conf import ConfigRegistry
    from hadoop_tpu.dfs.protocol import datatransfer
    from hadoop_tpu.metrics import metrics_system
    yield
    ConfigRegistry.reset_for_tests()
    metrics_system().reset_for_tests()
    datatransfer.set_default_security(None)
    from hadoop_tpu.security.ugi import UserGroupInformation
    UserGroupInformation._login_user = None
    from hadoop_tpu.tracing.collector import span_collector
    span_collector().reset_for_tests()
    from hadoop_tpu.tracing.tracer import global_tracer
    global_tracer().set_sample_rate(1.0)
    from hadoop_tpu.obs.comm import comm_runtime
    comm_runtime().reset_for_tests()
    from hadoop_tpu.obs.hbm import hbm_ledger
    hbm_ledger().reset_for_tests()
