"""Test harness configuration.

Multi-chip code paths are tested on a virtual 8-device CPU mesh (the
minicluster philosophy of the reference — real protocols, simulated fleet;
ref: MiniDFSCluster.java:157): JAX must see these flags before first import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import logging

import pytest

logging.basicConfig(level=logging.INFO)


@pytest.fixture(autouse=True)
def _reset_global_state():
    """Each test gets a clean config registry and metrics system."""
    from hadoop_tpu.conf import ConfigRegistry
    from hadoop_tpu.metrics import metrics_system
    yield
    ConfigRegistry.reset_for_tests()
    metrics_system().reset_for_tests()
