"""tpulint tests: every checker against known-bad and known-good
fixtures, the suppression/baseline workflow, and — the tier-1 gate — a
self-run asserting the shipped tree is clean against the committed
baseline (the findbugs-in-CI lane of the reference)."""

import os
import subprocess
import sys
import textwrap

import pytest

from hadoop_tpu.analysis import (GuardedByChecker, JitDisciplineChecker,
                                 LockOrderChecker, RetryHygieneChecker,
                                 SilentSwallowChecker, TimeoutChecker,
                                 all_checkers)
from hadoop_tpu.analysis.core import (load_baseline, run_lint,
                                      split_baselined, write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "hadoop_tpu")


def lint_source(tmp_path, source, checkers, name="fixture.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return run_lint([str(f)], checkers=checkers, root=str(tmp_path))


def ids_of(findings):
    return [f.checker for f in findings]


# ------------------------------------------------------------ guarded-by

BAD_GUARDED = """
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._free = []  # guarded-by: _lock

        def take(self):
            with self._lock:
                return self._free.pop()

        def peek(self):
            return self._free[0]      # BAD: no lock held
"""


def test_unguarded_field_is_flagged(tmp_path):
    findings = lint_source(tmp_path, BAD_GUARDED, [GuardedByChecker()])
    assert ids_of(findings) == ["lock/guarded-by"]
    assert "Pool._free" in findings[0].message
    # the finding lands on the unguarded access, not the guarded one
    assert "BAD" in (tmp_path / "fixture.py").read_text().splitlines()[
        findings[0].line - 1]


def test_guarded_access_and_init_are_clean(tmp_path):
    findings = lint_source(tmp_path, """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._free = [1]  # guarded-by: _lock
                self._free.append(2)   # __init__ is exempt

            def take(self):
                with self._lock:
                    return self._free.pop()
    """, [GuardedByChecker()])
    assert findings == []


def test_holds_annotation_covers_locked_helpers(tmp_path):
    findings = lint_source(tmp_path, """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._free = []  # guarded-by: _lock

            def take(self):
                with self._lock:
                    return self._take_locked()

            def _take_locked(self):  # lint: holds=_lock
                return self._free.pop()
    """, [GuardedByChecker()])
    assert findings == []


def test_rw_lock_scopes_count_as_held(tmp_path):
    findings = lint_source(tmp_path, """
        import threading

        class NS:
            def __init__(self):
                self.lock = threading.RLock()
                self._dirs = {}  # guarded-by: lock

            def read(self, p):
                with self.lock.read():
                    return self._dirs.get(p)
    """, [GuardedByChecker()])
    assert findings == []


# ------------------------------------------------------------ lock order

CYCLE = """
    import threading

    class A:
        def __init__(self):
            self.l1 = threading.Lock()
            self.l2 = threading.Lock()

        def forward(self):
            with self.l1:
                with self.l2:
                    return 1

        def backward(self):
            with self.l2:
                with self.l1:
                    return 2
"""


def test_lock_order_cycle_is_detected(tmp_path):
    findings = lint_source(tmp_path, CYCLE, [LockOrderChecker()])
    assert ids_of(findings) == ["lock/order-cycle"]
    assert "A.l1" in findings[0].message and "A.l2" in findings[0].message


def test_consistent_lock_order_is_clean(tmp_path):
    findings = lint_source(tmp_path, """
        import threading

        class A:
            def __init__(self):
                self.l1 = threading.Lock()
                self.l2 = threading.Lock()

            def forward(self):
                with self.l1:
                    with self.l2:
                        return 1

            def also_forward(self):
                with self.l1:
                    with self.l2:
                        return 2
    """, [LockOrderChecker()])
    assert findings == []


def test_lock_order_cycle_through_a_call_is_detected(tmp_path):
    """The deadlock hides one call deep: forward() nests l1→l2 lexically,
    backward() holds l2 and CALLS a helper that takes l1."""
    findings = lint_source(tmp_path, """
        import threading

        class A:
            def __init__(self):
                self.l1 = threading.Lock()
                self.l2 = threading.Lock()

            def forward(self):
                with self.l1:
                    with self.l2:
                        return 1

            def helper(self):
                with self.l1:
                    return 3

            def backward(self):
                with self.l2:
                    return self.helper()
    """, [LockOrderChecker()])
    assert ids_of(findings) == ["lock/order-cycle"]


# ---------------------------------------------------------- jit checkers

def test_traced_branch_is_flagged(tmp_path):
    findings = lint_source(tmp_path, """
        import jax
        import jax.numpy as jnp

        def step(x):
            if x > 0:              # BAD: branch on a traced value
                return x + 1
            return x - 1

        step_fn = jax.jit(step)
    """, [JitDisciplineChecker()])
    assert ids_of(findings) == ["jit/traced-branch"]


def test_shape_branch_and_config_branch_are_clean(tmp_path):
    findings = lint_source(tmp_path, """
        import jax
        import jax.numpy as jnp

        USE_BIAS = True

        def step(x, b):
            if x.shape[0] > 4:     # static: shapes are trace-time
                x = x * 2
            if USE_BIAS:           # static: Python config
                x = x + 1
            if b is None:          # static: identity check
                return x
            return x + b

        step_fn = jax.jit(step)
    """, [JitDisciplineChecker()])
    assert findings == []


def test_host_sync_is_flagged_through_a_callee(tmp_path):
    """Reachability: the sync hides in a helper the jitted fn calls."""
    findings = lint_source(tmp_path, """
        import jax
        import numpy as np

        def helper(v):
            return float(v.item())     # BAD: host sync on traced value

        def step(x):
            return helper(x) + 1

        step_fn = jax.jit(step)
    """, [JitDisciplineChecker()])
    assert "jit/host-sync" in ids_of(findings)


def test_np_asarray_on_traced_is_flagged(tmp_path):
    findings = lint_source(tmp_path, """
        import jax
        import numpy as np

        def step(x):
            host = np.asarray(x)       # BAD: device→host copy
            return host.sum()

        step_fn = jax.jit(step)
    """, [JitDisciplineChecker()])
    assert "jit/host-sync" in ids_of(findings)


def test_partial_bound_params_stay_static(tmp_path):
    """partial()-bound arguments are Python constants at jit time — a
    branch on one must NOT be flagged (the device_shuffle pattern)."""
    findings = lint_source(tmp_path, """
        from functools import partial

        import jax

        def body(x, mode):
            if mode == "sum":      # static: bound by partial below
                return x + x
            return x * x

        prog = jax.jit(partial(body, mode="sum"))
    """, [JitDisciplineChecker()])
    assert findings == []


def test_loop_over_traced_value_is_flagged(tmp_path):
    findings = lint_source(tmp_path, """
        import jax

        def step(x, n):
            acc = x
            for _ in range(n):     # BAD: traced trip count
                acc = acc + 1
            return acc

        step_fn = jax.jit(step)
    """, [JitDisciplineChecker()])
    assert ids_of(findings) == ["jit/traced-branch"]


def test_iterating_leaf_containers_is_clean(tmp_path):
    """Static-length containers of tracers (tree_flatten output, zip of
    leaf lists) are trace-time Python — iterating them, testing their
    truthiness, and keying dicts on their metadata must NOT flag (the
    bucketed-collective idiom, parallel/overlap.py)."""
    findings = lint_source(tmp_path, """
        import jax
        import jax.numpy as jnp

        def reduce_tree(tree, axes_tree):
            flat, treedef = jax.tree_util.tree_flatten(tree)
            axes_flat = treedef.flatten_up_to(axes_tree)
            out = []
            for g, axes in zip(flat, axes_flat):   # OK: static length
                axes = tuple(axes)
                if not axes:                       # OK: static tuple
                    out.append(g)
                    continue
                out.append(jax.lax.psum(g, axes))
            buf = jnp.concatenate([o.reshape(-1) for o in out])
            return treedef.unflatten(out), buf

        prog = jax.jit(reduce_tree)
    """, [JitDisciplineChecker()])
    assert findings == []


def test_branch_on_container_element_is_still_flagged(tmp_path):
    """Container precision must not hide the real bug: branching on an
    ELEMENT of a leaf container is still a traced branch."""
    findings = lint_source(tmp_path, """
        import jax

        def worst(tree):
            flat, _ = jax.tree_util.tree_flatten(tree)
            for g in flat:
                if g > 0:          # BAD: branch on a traced leaf
                    return g
            return flat[0]

        prog = jax.jit(worst)
    """, [JitDisciplineChecker()])
    assert ids_of(findings) == ["jit/traced-branch"]


# ------------------------------------------------------ blocking-in-step

def test_blocking_in_step_loop_is_flagged(tmp_path):
    from hadoop_tpu.analysis import StepBlockingChecker
    findings = lint_source(tmp_path, """
        def train(self, n_steps):
            for _ in range(n_steps):
                params, opt, m = self.step_fn(params, opt, tok, tgt)
                loss = float(m["loss"])        # BAD: per-step host sync
                self.fs.write_all("/log", b"x")  # BAD: blocking IO
                self.writer.join(5.0)          # BAD: thread join
    """, [StepBlockingChecker()])
    assert sorted(ids_of(findings)) == ["jit/blocking-in-step"] * 3


def test_blocking_outside_step_loop_is_clean(tmp_path):
    from hadoop_tpu.analysis import StepBlockingChecker
    findings = lint_source(tmp_path, """
        def train(self, n_steps):
            for _ in range(n_steps):
                params, opt, m = self.step_fn(params, opt, tok, tgt)
            # after the loop: syncs are fine
            loss = float(m["loss"])
            self.fs.write_all("/log", b"x")
            self.writer.join(5.0)

        def not_a_step_loop(rows):
            out = []
            for r in rows:                  # no step_fn call inside
                out.append(float(r))
            return ", ".join(out)           # str.join stays exempt
    """, [StepBlockingChecker()])
    assert findings == []


def test_blocking_in_step_annotation_suppresses(tmp_path):
    from hadoop_tpu.analysis import StepBlockingChecker
    findings = lint_source(tmp_path, """
        def train(self, n_steps):
            for _ in range(n_steps):
                params, opt, m = self.step_fn(params, opt, tok, tgt)
                if len(pending) > 16:  # deliberate backpressure sync
                    v = float(  # lint: disable=jit/blocking-in-step
                        pending.popleft())
    """, [StepBlockingChecker()])
    assert findings == []


def test_step_loop_from_make_train_step_assignment(tmp_path):
    from hadoop_tpu.analysis import StepBlockingChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.parallel.train import make_train_step

        def bench(cfg, plan, mesh, params, opt, tok, tgt):
            step = make_train_step(cfg, plan, mesh)
            while True:
                params, opt, m = step(params, opt, tok, tgt)
                print(m["loss"].item())        # BAD: per-step sync
    """, [StepBlockingChecker()])
    assert ids_of(findings) == ["jit/blocking-in-step"]


def test_step_loop_from_jit_bound_names(tmp_path):
    """The serving engine's device-resident step helpers are names
    bound from ``jax.jit(...)`` — at module level (_SET_SLOT-style) or
    as a self attribute (self._step_fn) — and a loop dispatching them
    is a step loop: blocking calls inside it undo the device-resident
    win exactly like in a trainer loop."""
    from hadoop_tpu.analysis import StepBlockingChecker
    findings = lint_source(tmp_path, """
        import jax

        _MOVER = jax.jit(lambda s, i: s)

        class Engine:
            def __init__(self):
                self._step_fn = jax.jit(self._impl)

            def drive(self, state, events, n):
                for ev in events:
                    state = _MOVER(state, ev)
                    self.log.write(float(ev.seq))   # BAD: host sync
                while n:
                    state, out = self._step_fn(state)
                    self.fs.append("/t", out)       # BAD: blocking IO
                    n -= 1
                return state

            def cold(self, state, events):
                # no jit-bound callable in this loop: syncs are fine
                for ev in events:
                    self.log.write(float(ev.seq))

            def warm(self, batches):
                # _MOVER is NAME-bound: an unrelated ATTRIBUTE call
                # spelled the same must not mark a step loop
                for b in batches:
                    self.log.write(float(self.other._MOVER(b)))
    """, [StepBlockingChecker()])
    assert sorted(ids_of(findings)) == ["jit/blocking-in-step"] * 2


# ---------------------------------------------------------- rpc checkers

def test_timeoutless_socket_is_flagged(tmp_path):
    findings = lint_source(tmp_path, """
        import socket

        def dial(addr):
            return socket.create_connection(addr)   # BAD: no timeout
    """, [TimeoutChecker()])
    assert ids_of(findings) == ["rpc/no-timeout"]


def test_socket_with_timeout_is_clean(tmp_path):
    findings = lint_source(tmp_path, """
        import socket

        def dial(addr):
            s = socket.socket()
            s.settimeout(5.0)
            s.connect(addr)
            return socket.create_connection(addr, timeout=5.0)
    """, [TimeoutChecker()])
    assert findings == []


def test_raw_connect_without_settimeout_is_flagged(tmp_path):
    findings = lint_source(tmp_path, """
        import socket

        def dial(addr):
            s = socket.socket()
            s.connect(addr)      # BAD: blocking connect, no settimeout
            return s
    """, [TimeoutChecker()])
    assert ids_of(findings) == ["rpc/no-timeout"]


def test_settimeout_none_is_flagged(tmp_path):
    findings = lint_source(tmp_path, """
        def unbound(sock):
            sock.settimeout(None)    # BAD: unbounds the live connection
    """, [TimeoutChecker()])
    assert ids_of(findings) == ["rpc/timeout-cleared"]


def test_constant_sleep_retry_is_flagged(tmp_path):
    findings = lint_source(tmp_path, """
        import time

        def fetch(op):
            for _ in range(5):
                try:
                    return op()
                except OSError:
                    time.sleep(0.5)      # BAD: lockstep retries
    """, [RetryHygieneChecker()])
    assert ids_of(findings) == ["rpc/retry-no-backoff"]


def test_jittered_backoff_retry_is_clean(tmp_path):
    findings = lint_source(tmp_path, """
        import time

        from hadoop_tpu.util.misc import backoff_delay

        def fetch(op):
            for attempt in range(5):
                try:
                    return op()
                except OSError:
                    time.sleep(backoff_delay(0.5, attempt))
    """, [RetryHygieneChecker()])
    assert findings == []


def test_silent_broad_swallow_is_flagged(tmp_path):
    findings = lint_source(tmp_path, """
        def quiet(op):
            try:
                op()
            except Exception:
                pass
    """, [SilentSwallowChecker()])
    assert ids_of(findings) == ["rpc/silent-swallow"]


def test_narrow_or_logged_excepts_are_clean(tmp_path):
    findings = lint_source(tmp_path, """
        import logging

        log = logging.getLogger(__name__)

        def quiet(op):
            try:
                op()
            except OSError:
                pass                      # narrow: fine
            try:
                op()
            except Exception as e:        # broad but leaves a breadcrumb
                log.debug("op failed: %s", e)
    """, [SilentSwallowChecker()])
    assert findings == []


# ------------------------------------------------ trace span discipline

def test_span_never_finished_is_flagged(tmp_path):
    from hadoop_tpu.analysis import SpanFinishChecker
    findings = lint_source(tmp_path, """
        def handler(tracer, work):
            sp = tracer.span("op")     # BAD: never finished
            sp.add_kv("k", "v")
            return work()
    """, [SpanFinishChecker()])
    assert ids_of(findings) == ["trace/span-not-finished"]


def test_span_bare_call_is_flagged(tmp_path):
    from hadoop_tpu.analysis import SpanFinishChecker
    findings = lint_source(tmp_path, """
        def handler(tracer):
            tracer.span("op")          # BAD: dropped on the floor
    """, [SpanFinishChecker()])
    assert ids_of(findings) == ["trace/span-not-finished"]


def test_span_exception_edge_leak_is_flagged(tmp_path):
    from hadoop_tpu.analysis import SpanFinishChecker
    findings = lint_source(tmp_path, """
        def handler(tracer, work):
            sp = tracer.span("op")
            result = work()            # raises past the finish below
            sp.finish()
            return result
    """, [SpanFinishChecker()])
    assert ids_of(findings) == ["trace/span-not-finished"]
    assert "exception edge" in findings[0].message


def test_span_good_shapes_are_clean(tmp_path):
    from hadoop_tpu.analysis import SpanFinishChecker
    findings = lint_source(tmp_path, """
        def ctx_manager(tracer, work):
            with tracer.span("op") as sp:
                sp.add_kv("k", "v")
                return work()

        def named_ctx_manager(tracer, work):
            sp = tracer.span("op")
            with sp:
                return work()

        def fire_and_forget(tracer):
            tracer.span("marker").finish()

        def try_finally(tracer, work):
            sp = tracer.span("op")
            try:
                return work()
            finally:
                sp.finish()

        def annotate_then_finish(tracer, n):
            sp = tracer.span("op")
            sp.add_kv("n", str(n))     # span methods + safe builtins
            sp.finish()                # can't raise past the finish

        def escapes(tracer, sink):
            sp = tracer.span("op")     # finished by the sink
            sink(sp)

        def conditional_cm(tracer, ctx, work):
            import contextlib
            cm = (tracer.span("op") if ctx else contextlib.nullcontext())
            with cm:
                return work()
    """, [SpanFinishChecker()])
    assert findings == []


# ------------------------------------------- metrics /prom discipline

def test_duplicate_prom_family_is_flagged(tmp_path):
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def a(reg):
            reg.gauge("queue_depth", "waiting")

        def b(reg2):
            reg2.counter("queue_depth", "BAD: merges as a counter "
                         "family elsewhere")  # still distinct: _total
            reg2.quantiles("queue_depth", "BAD: same family, summary")
    """, [PromFamilyChecker()])
    assert ids_of(findings) == ["metrics/duplicate-family"]
    # counter mints queue_depth_total (no clash); quantiles mints
    # queue_depth (clashes with the gauge)


def test_same_kind_shared_family_is_clean(tmp_path):
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def a(reg):
            for tier in ("host", "dfs"):
                reg.histogram(f"kv_fetch_seconds_{tier}", "fetch",
                              prom_name="kv_fetch_seconds",
                              prom_labels={"tier": tier})

        def b(reg2):
            reg2.histogram("kv_fetch_seconds_x", "another source",
                           prom_name="kv_fetch_seconds",
                           prom_labels={"tier": "x"})
    """, [PromFamilyChecker()])
    assert findings == []


def test_unbounded_prom_label_is_flagged(tmp_path):
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def per_user_series(reg, request):
            reg.histogram("op_seconds", "BAD: label from request data",
                          prom_labels={"user": request.user})

        def per_port_series(reg, port):
            reg.histogram("op2_seconds", "BAD: label from a parameter",
                          prom_labels={"port": f"{port}"})
    """, [PromFamilyChecker()])
    assert ids_of(findings) == ["metrics/unbounded-label",
                                "metrics/unbounded-label"]


def test_bounded_literal_labels_are_clean(tmp_path):
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def tiers(reg):
            hists = {t: reg.histogram(f"h_{t}", "ok",
                                      prom_name="h",
                                      prom_labels={"tier": t})
                     for t in ("host", "dfs")}
            for lane in ["a", "b"]:
                reg.histogram(f"lane_{lane}", "ok", prom_name="lane",
                              prom_labels={"lane": lane,
                                           "static": "x"})
            return hists
    """, [PromFamilyChecker()])
    assert findings == []


def test_labeled_counter_shared_family_is_clean(tmp_path):
    """Counters/gauges honor the prom_name override (the runtime comm
    ledger's htpu_comm_* families, the HBM ledger's htpu_hbm_bytes):
    same kind under one shared family across sites is the DESIGN."""
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def sites(reg):
            for s in ("bucket.psum", "tp.psum", "other"):
                reg.counter("comm_payload_bytes_" + s, "bytes",
                            prom_name="comm_payload_bytes",
                            prom_labels={"site": s})
                reg.histogram("comm_seconds_" + s, "wall",
                              prom_name="comm_seconds",
                              prom_labels={"site": s})

        def components(reg2):
            for c in ("weights", "kv_pool"):
                reg2.register_callback_gauge(
                    "hbm_bytes_" + c, lambda: 0,
                    prom_name="hbm_bytes",
                    prom_labels={"component": c})
    """, [PromFamilyChecker()])
    assert findings == []


def test_labeled_counter_family_kind_conflict_is_flagged(tmp_path):
    """A prom_name override joins the duplicate-family ledger: a gauge
    registering under a family another module minted as a counter is
    the silently-dropped-exposition bug, caught at the second site."""
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def a(reg):
            reg.counter("comm_payload_bytes_x", "ok",
                        prom_name="comm_payload_bytes",
                        prom_labels={"site": "x"})

        def b(reg2):
            reg2.gauge("whatever_unique_name", "BAD: the scraper sees "
                       "family comm_payload_bytes_total as a gauge",
                       prom_name="comm_payload_bytes_total",
                       prom_labels={"site": "y"})
    """, [PromFamilyChecker()])
    assert ids_of(findings) == ["metrics/duplicate-family"]


def test_unbounded_counter_label_is_flagged(tmp_path):
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def per_site_series(reg, site):
            reg.counter("comm_bytes_" + site, "BAD: label from a "
                        "parameter", prom_name="comm_bytes",
                        prom_labels={"site": site})
    """, [PromFamilyChecker()])
    assert ids_of(findings) == ["metrics/unbounded-label"]


def test_bounded_slo_class_outcome_labels_are_clean(tmp_path):
    """The SLO scoreboard idiom (serving/metrics.py): a dict
    comprehension with TWO generators over inline literal tuples
    binds both the class and the outcome as provably bounded — 12
    same-kind registrations share one prom family without a flag."""
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def slo(reg):
            hists = {c: reg.histogram(f"slo_ttft_seconds_{c}", "ttft",
                                      prom_name="slo_ttft_seconds",
                                      prom_labels={"class": c})
                     for c in ("p0", "p1", "p2", "p3")}
            counters = {(c, o): reg.counter(
                            f"slo_requests_{c}_{o}", "outcomes",
                            prom_name="slo_requests",
                            prom_labels={"class": c, "outcome": o})
                        for c in ("p0", "p1", "p2", "p3")
                        for o in ("ok", "shed", "failed")}
            return hists, counters
    """, [PromFamilyChecker()])
    assert findings == []


def test_unbounded_tenant_class_label_is_flagged(tmp_path):
    """The failure the bounded p0..p3 ladder exists to prevent: a
    class set flowing in from data (a conf string, a tenant name)
    would mint unbounded /prom series."""
    from hadoop_tpu.analysis import PromFamilyChecker
    findings = lint_source(tmp_path, """
        def slo(reg, classes):
            for c in classes:
                reg.counter("slo_requests_" + c,
                            "BAD: class set from a parameter",
                            prom_name="slo_requests",
                            prom_labels={"class": c})
    """, [PromFamilyChecker()])
    assert ids_of(findings) == ["metrics/unbounded-label"]


# -------------------------------------------- suppression + baseline

def test_line_suppression(tmp_path):
    findings = lint_source(tmp_path, """
        def quiet(op):
            try:
                op()
            except Exception:  # lint: disable=rpc/silent-swallow
                pass
    """, [SilentSwallowChecker()])
    assert findings == []


def test_file_suppression(tmp_path):
    findings = lint_source(tmp_path, """
        # lint: disable-file=rpc/silent-swallow

        def quiet(op):
            try:
                op()
            except Exception:
                pass
    """, [SilentSwallowChecker()])
    assert findings == []


def test_baseline_roundtrip(tmp_path):
    findings = lint_source(tmp_path, """
        def quiet(op):
            try:
                op()
            except Exception:
                pass
    """, [SilentSwallowChecker()])
    assert len(findings) == 1
    bl = tmp_path / "baseline"
    write_baseline(str(bl), findings)
    keys = load_baseline(str(bl))
    new, old = split_baselined(findings, keys)
    assert new == [] and len(old) == 1
    # an un-baselined finding still surfaces
    new2, _ = split_baselined(findings, set())
    assert len(new2) == 1


# --------------------------------------------------- the tier-1 gate

def test_shipped_tree_is_lint_clean():
    """Self-run: the full package against the committed baseline. A
    regression anywhere in hadoop_tpu/ fails this test."""
    findings = run_lint([PKG], checkers=all_checkers(), root=REPO)
    baseline = load_baseline(os.path.join(REPO, "LINT_BASELINE"))
    new, _ = split_baselined(findings, baseline)
    assert new == [], "unbaselined lint findings:\n" + \
        "\n".join(f.render() for f in new)


def test_cli_lint_gate():
    """`hadoop-tpu lint --baseline LINT_BASELINE` exits 0 on the shipped
    tree (the command CI shells)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hadoop-tpu"), "lint",
         "--baseline", os.path.join(REPO, "LINT_BASELINE")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_lint_fails_on_seeded_bad_tree(tmp_path):
    (tmp_path / "bad.py").write_text(textwrap.dedent("""
        import threading

        class D:
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()

            def one(self):
                with self.a:
                    with self.b:
                        pass

            def two(self):
                with self.b:
                    with self.a:
                        pass
    """))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hadoop-tpu"), "lint",
         "--no-baseline", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1
    assert "lock/order-cycle" in proc.stdout


# ------------------------------------------------- parity/relaxed-gated

def test_unguarded_lowp_entry_points_are_flagged(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.parallel.lowp.quant import psum_quantized as pq

        def reduce_bucket(buf, rq):
            return pq(buf, ("dp",), rq)                       # BAD

        def reduce_scatter(buf, ctx):
            from hadoop_tpu.parallel.lowp.quant import \\
                psum_scatter_quantized
            return psum_scatter_quantized(buf, "tp", None)    # BAD

        def project(x, w, ctx):
            from hadoop_tpu.ops.collective_matmul import \\
                chunked_matmul_reduce
            return chunked_matmul_reduce(x, w, ctx)           # BAD
    """, [RelaxedGateChecker()])
    assert len(findings) == 3
    assert all(f.checker == "parity/relaxed-gated" for f in findings)
    assert "relaxed-parity guard" in findings[0].message


def test_relaxed_guarded_entry_points_are_clean(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        def reduce_bucket(buf, rq, relaxed):
            from hadoop_tpu.parallel.lowp.quant import psum_quantized
            if relaxed is not None:
                return psum_quantized(buf, ("dp",), rq)
            return buf

        def reduce_tp(y, ctx):
            from hadoop_tpu.parallel.lowp.quant import \\
                psum_scatter_quantized
            if ctx.relaxed_codec is not None:
                return psum_scatter_quantized(y, "tp", None)
            return y

        def project(x, w, ctx):
            from hadoop_tpu.ops.collective_matmul import \\
                chunked_matmul_reduce
            return chunked_matmul_reduce(x, w, ctx) \\
                if ctx.relaxed_chunk_matmul else x

        def plumbing(conf):
            # tier plumbing is not a quantized path: never flagged
            from hadoop_tpu.parallel.lowp import parity_from_conf
            return parity_from_conf(conf)

        def kw_guard(x, rq, tier_matches):
            # a keyword ARG naming the tier is a guard too
            from hadoop_tpu.parallel.lowp.quant import psum_quantized
            if tier_matches(relaxed=True):
                return psum_quantized(x, ("dp",), rq)
            return x
    """, [RelaxedGateChecker()])
    assert findings == []


def test_unguarded_weightplane_entry_points_are_flagged(tmp_path):
    """The serving weight plane's entry points (qdot/qrows/qhead and
    the quantize-at-load seam) are relaxed-tier entry points too:
    unguarded calls would quantize resident weights for every
    serving.parity=bitwise user."""
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.serving.weightplane import qdot, quantized_load

        def project(x, w):
            return qdot(x, w)                                 # BAD

        def head(params, h, cfg):
            from hadoop_tpu.serving.weightplane import qhead
            return qhead(params, h, cfg)                      # BAD

        def load(fs, d, cfg, w):
            return quantized_load(fs, d, cfg, w)              # BAD
    """, [RelaxedGateChecker()])
    assert len(findings) == 3
    assert all(f.checker == "parity/relaxed-gated" for f in findings)


def test_guarded_weightplane_entry_points_are_clean(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.serving.weightplane import (qdot, qrows,
                                                    weightplane_from_conf)

        class Engine:
            def _wdot(self, x, w):
                if self._relaxed_weights:
                    return qdot(x, w)
                return x @ w

            def embed(self, params, tokens, dtype):
                if self._relaxed_weights and self._q_embed:
                    return qrows(params["embed"], tokens, dtype)
                return params["embed"][tokens]

        def plumbing(conf):
            # tier plumbing is not a quantized path: never flagged
            return weightplane_from_conf(conf)
    """, [RelaxedGateChecker()])
    assert findings == []


def test_unguarded_moe_entry_points_are_flagged(tmp_path):
    """The MoE expert-serving entry points — the expert-batched int8
    matmul (``qedot``) and the quantized all2all payload legs
    (``moe_dispatch_quantized``/``moe_combine_quantized``) — are
    relaxed-tier entry points: an unguarded call would quantize every
    bitwise MoE replica's expert math or exchange, including through a
    renamed import."""
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.serving.weightplane import qedot
        from hadoop_tpu.parallel.lowp.quant import \\
            moe_dispatch_quantized

        def expert_ffn(xe, lp):
            return qedot(xe, lp["w_gate"])                    # BAD

        def dispatch(xe):
            return moe_dispatch_quantized(xe)                 # BAD

        def combine(ye, ax):
            from hadoop_tpu.parallel.lowp.quant import \\
                moe_combine_quantized as mc
            return mc(ye, ax)                                 # BAD
    """, [RelaxedGateChecker()])
    assert len(findings) == 3
    assert all(f.checker == "parity/relaxed-gated" for f in findings)


def test_guarded_moe_entry_points_are_clean(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.parallel.lowp.quant import (
            moe_combine_quantized, moe_dispatch_quantized)
        from hadoop_tpu.serving.weightplane import qedot

        class Engine:
            def _moe_mlp(self, xe, lp):
                if self._relaxed_weights:
                    ye = qedot(xe, lp["w_gate"])
                else:
                    ye = xe
                if self._relaxed_weights and self._codec != "none":
                    xe = moe_dispatch_quantized(xe)
                    ye = moe_combine_quantized(ye)
                return ye
    """, [RelaxedGateChecker()])
    assert findings == []


def test_unguarded_qslice_calls_are_flagged(tmp_path):
    """``qslice`` is the layer-sliced twin of ``qdot`` (the longctx
    fused decode path's per-layer weight route) — same entry-point
    contract, including through a renamed import."""
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.serving.weightplane import qslice

        def layer_weight(layers, l):
            return qslice(layers["wq"], l)                    # BAD

        def renamed(layers, l):
            from hadoop_tpu.serving.weightplane import qslice as qs
            return qs(layers["wo"], l)                        # BAD
    """, [RelaxedGateChecker()])
    assert len(findings) == 2
    assert all(f.checker == "parity/relaxed-gated" for f in findings)


def test_guarded_qslice_calls_are_clean(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.serving.weightplane import qdot, qslice

        class FusedStep:
            def _lw(self, layers, name, l):
                if self._relaxed_qweights:
                    return qslice(layers[name], l)
                return layers[name][l]

            def _mm(self, x, w, relaxed):
                return qdot(x, w) if relaxed else x @ w
    """, [RelaxedGateChecker()])
    assert findings == []


def test_unguarded_syncpolicy_entry_points_are_flagged(tmp_path):
    """The partially-synchronized sync schedule's entry points
    (parallel/lowp/syncpolicy.py) are relaxed-tier entry points: an
    unguarded call would skip/stale TP activation syncs — rank-
    divergent activations — for every parallel.parity=bitwise user."""
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.parallel.lowp.syncpolicy import \\
            scheduled_row_reduce

        def reduce(y, ctx, entry):
            return scheduled_row_reduce(y, ctx, entry)        # BAD

        def skip(y, ctx):
            from hadoop_tpu.parallel.lowp.syncpolicy import \\
                skip_row_reduce
            return skip_row_reduce(y, ctx)                    # BAD

        def stale(y, ctx, corr):
            from hadoop_tpu.parallel.lowp.syncpolicy import \\
                stale_row_reduce
            return stale_row_reduce(y, ctx, corr)             # BAD
    """, [RelaxedGateChecker()])
    assert len(findings) == 3
    assert all(f.checker == "parity/relaxed-gated" for f in findings)


def test_guarded_syncpolicy_entry_points_are_clean(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        def reduce(y, ctx, relaxed_sync):
            from hadoop_tpu.parallel.lowp.syncpolicy import \\
                scheduled_row_reduce
            if relaxed_sync is not None and relaxed_sync.mode != "sync":
                return scheduled_row_reduce(y, ctx, relaxed_sync)
            return y

        def plumbing(conf, n_layers):
            # schedule parsing is tier plumbing, never flagged
            from hadoop_tpu.parallel.lowp.syncpolicy import \\
                resolve_schedule
            return resolve_schedule("periodic:2", n_layers)
    """, [RelaxedGateChecker()])
    assert findings == []


def test_lowp_package_itself_is_exempt(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    pkg = tmp_path / "hadoop_tpu" / "parallel" / "lowp"
    pkg.mkdir(parents=True)
    for p in (tmp_path / "hadoop_tpu", tmp_path / "hadoop_tpu" /
              "parallel", pkg):
        (p / "__init__.py").write_text("")
    (pkg / "quant.py").write_text(textwrap.dedent("""
        def psum_quantized(x, axes, rq):
            return x

        def helper(x, rq):
            return psum_quantized(x, (), rq)   # definition site: exempt
    """))
    findings = run_lint([str(tmp_path)], checkers=[RelaxedGateChecker()],
                        root=str(tmp_path))
    assert findings == []


def test_unguarded_longctx_entry_points_are_flagged(tmp_path):
    """The long-context plane's entry points are relaxed-tier entry
    points: an unguarded call would run CP-reassociated softmax (not
    bitwise) for every serving.parity=bitwise user."""
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.serving.longctx import longctx_plane_from_conf

        def build(conf, cfg, engine):
            return longctx_plane_from_conf(conf, cfg, engine)  # BAD

        def admit(plane, prompt, sampling):
            return plane.longctx_submit(prompt, sampling)      # BAD

        def prefill(pre, tokens):
            return pre.cp_prefill(tokens)                      # BAD

        def decode(dec, tokens, first, sampling, deliver):
            return dec.paged_decode(tokens, first, sampling,   # BAD
                                    deliver=deliver)
    """, [RelaxedGateChecker()])
    assert len(findings) == 4
    assert all(f.checker == "parity/relaxed-gated" for f in findings)


def test_guarded_longctx_entry_points_are_clean(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = lint_source(tmp_path, """
        from hadoop_tpu.serving.longctx import longctx_plane_from_conf

        class Engine:
            def submit(self, prompt, sampling):
                if self._relaxed_longctx is not None and \\
                        len(prompt) >= self._relaxed_longctx.min_tokens:
                    return self._relaxed_longctx.longctx_submit(
                        prompt, sampling)
                return self._fused(prompt, sampling)

        def wire(conf, cfg, engine, weights):
            if weights.relaxed:
                engine.attach_longctx(
                    longctx_plane_from_conf(conf, cfg, engine))

        def plumbing(plane):
            # tier plumbing / observability is not a quantized path
            return plane.stats()
    """, [RelaxedGateChecker()])
    assert findings == []


def test_longctx_package_itself_is_exempt(tmp_path):
    from hadoop_tpu.analysis import RelaxedGateChecker
    pkg = tmp_path / "hadoop_tpu" / "serving" / "longctx"
    pkg.mkdir(parents=True)
    for p in (tmp_path / "hadoop_tpu", tmp_path / "hadoop_tpu" /
              "serving", pkg):
        (p / "__init__.py").write_text("")
    (pkg / "plane.py").write_text(textwrap.dedent("""
        def longctx_submit(prompt):
            return prompt

        def serve(req):
            return longctx_submit(req)   # definition site: exempt
    """))
    findings = run_lint([str(tmp_path)], checkers=[RelaxedGateChecker()],
                        root=str(tmp_path))
    assert findings == []


def test_shipped_tree_has_no_unguarded_relaxed_entry_points():
    """The real consumers (overlap.py, collective_matmul.py, train.py)
    stay behind their guards — the tier-1 self-run of the contract."""
    from hadoop_tpu.analysis import RelaxedGateChecker
    findings = run_lint([os.path.join(REPO, "hadoop_tpu")],
                        checkers=[RelaxedGateChecker()])
    assert findings == [], [f.render() for f in findings]


# ------------------------------------------------- conf discipline

def _conf_findings(tmp_path, source, readme=None, name="fixture.py"):
    from hadoop_tpu.analysis import ConfDisciplineChecker
    if readme is not None:
        (tmp_path / "README.md").write_text(textwrap.dedent(readme))
    return lint_source(tmp_path, source, [ConfDisciplineChecker()],
                       name=name)


def test_conf_default_drift_is_flagged(tmp_path):
    findings = _conf_findings(tmp_path, """
        def a(conf):
            return conf.get_int("dfs.x.limit", 4)

        def b(conf):
            return conf.get_int("dfs.x.limit", 8)   # BAD: drifted default
    """, readme="docs: `dfs.x.limit`\n")
    assert ids_of(findings) == ["conf/default-drift"]
    assert "dfs.x.limit" in findings[0].message


def test_conf_shared_default_is_clean(tmp_path):
    findings = _conf_findings(tmp_path, """
        LIMIT = "dfs.x.limit"
        LIMIT_DEFAULT = 4

        def a(conf):
            return conf.get_int(LIMIT, LIMIT_DEFAULT)

        def b(conf):
            return conf.get_int(LIMIT, LIMIT_DEFAULT)
    """, readme="docs: `dfs.x.limit`\n")
    assert findings == []


def test_conf_typo_cluster_is_flagged(tmp_path):
    findings = _conf_findings(tmp_path, """
        def a(conf):
            return conf.get("dfs.pool.interval", "")

        def b(conf):
            return conf.get("dfs.pool.intervall", "")  # BAD: near-miss
    """, readme="docs: `dfs.pool.interval` `dfs.pool.intervall`\n")
    assert ids_of(findings) == ["conf/typo-cluster"]
    assert "dfs.pool.intervall" in findings[0].message


def test_conf_separator_split_is_flagged(tmp_path):
    findings = _conf_findings(tmp_path, """
        def a(conf):
            return conf.get("yarn.store.dir", "")

        def b(conf):
            return conf.get("yarn.store-dir", "")  # BAD: -/. split
    """, readme="docs: `yarn.store.dir` `yarn.store-dir`\n")
    assert ids_of(findings) == ["conf/typo-cluster"]


def test_conf_undocumented_key_is_flagged(tmp_path):
    findings = _conf_findings(tmp_path, """
        def a(conf):
            return conf.get_bool("ipc.backoff.enable", False)
    """, readme="this README never mentions the key\n")
    assert ids_of(findings) == ["conf/undocumented-key"]
    assert "ipc.backoff.enable" in findings[0].message


def test_conf_documented_key_is_clean(tmp_path):
    findings = _conf_findings(tmp_path, """
        def a(conf):
            return conf.get_bool("ipc.backoff.enable", False)
    """, readme="Set `ipc.backoff.enable` to shed load.\n")
    assert findings == []


def test_conf_stale_doc_key_is_flagged(tmp_path):
    findings = _conf_findings(tmp_path, """
        def a(conf):
            return conf.get("dfs.real.key", "")
    """, readme="""
        <!-- conf-keys:begin -->
        Conf keys: `dfs.real.key`, `dfs.ghost.key`.
        <!-- conf-keys:end -->
    """)
    assert ids_of(findings) == ["conf/stale-doc-key"]
    assert "dfs.ghost.key" in findings[0].message
    assert findings[0].path == "README.md"


def test_conf_doc_outside_marked_region_is_not_stale_checked(tmp_path):
    # prose mentions (span names, examples) outside the marked tables
    # never count as doc claims
    findings = _conf_findings(tmp_path, """
        def a(conf):
            return conf.get("dfs.real.key", "")
    """, readme="""
        The `dfs.real.key` lever; prose also says `serving.some.span`.
    """)
    assert findings == []


def test_conf_scan_resolves_indirection(tmp_path):
    """Registry extraction round-trip: shared constants, class attrs,
    helper-threaded keys, bounded rule loops, and f-string families all
    resolve statically."""
    from hadoop_tpu.analysis import confscan
    from hadoop_tpu.analysis.core import load_project
    (tmp_path / "mod.py").write_text(textwrap.dedent("""
        KEY = "x.alpha"
        KEY_DEFAULT = 5

        class Reader:
            K = "x.class.key"

            def __init__(self, conf):
                self.v = conf.get(self.K, "d")

        def read_time(conf, key, dv=3.0):
            return conf.get_time_seconds(key, dv)

        def build(conf, scheme):
            a = conf.get_int(KEY, KEY_DEFAULT)
            b = read_time(conf, "x.timeout")
            for k, d in (("x.l1", 1), ("x.l2", 2)):
                conf.get_int(k, d)
            return conf.get(f"x.{scheme}.endpoint", "")
    """))
    project, errs = load_project([str(tmp_path)], root=str(tmp_path))
    assert errs == []
    scan = confscan.scan_project(project)
    assert scan.unresolved == []
    by_key = {r.key: r for r in scan.reads}
    assert by_key["x.alpha"].defaults == ("5",)
    assert by_key["x.alpha"].rtype == "int"
    assert by_key["x.class.key"].defaults == ("'d'",)
    assert by_key["x.timeout"].rtype == "time"
    assert by_key["x.timeout"].defaults == ("3.0",)
    assert by_key["x.l1"].defaults == ("1",)
    assert by_key["x.l2"].defaults == ("2",)
    assert by_key["x.*.endpoint"].is_pattern


def test_conf_scan_full_coverage_on_shipped_tree():
    """The acceptance bar: every conf read site in the tree resolves
    statically — the registry covers 100% of them."""
    from hadoop_tpu.analysis import confscan
    from hadoop_tpu.analysis.core import load_project
    project, _ = load_project([PKG], root=REPO)
    scan = confscan.scan_project(project)
    assert scan.unresolved == [], scan.unresolved
    assert len(scan.reads) > 300  # the fleet's lever space is large


def test_shipped_registry_matches_tree():
    """The committed registry regenerates to itself (the gate CI runs)."""
    from hadoop_tpu.analysis import confscan
    ok, diff = confscan.check_registry(REPO)
    assert ok, "\n".join(diff[:60])


def test_registry_gate_fails_on_stale_registry(tmp_path):
    """--check-conf-registry exits 1 with a diff on a deliberately
    stale registry; --write-conf-registry repairs it."""
    from hadoop_tpu.analysis import confscan
    pkg = tmp_path / "hadoop_tpu"
    (pkg / "conf").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "conf" / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent("""
        def a(conf):
            return conf.get_int("dfs.x.limit", 4)
    """))
    (pkg / "conf" / "registry.py").write_text("KEYS = {}\n")  # stale
    (tmp_path / "README.md").write_text(
        "Levers: `dfs.x.limit`.\n\n"
        + confscan.README_BEGIN + "\n" + confscan.README_END + "\n")
    ok, diff = confscan.check_registry(str(tmp_path))
    assert not ok and diff
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "hadoop-tpu"), "lint",
         "--check-conf-registry", str(pkg)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1
    assert "STALE" in proc.stdout
    changed = confscan.write_registry(str(tmp_path))
    assert "hadoop_tpu/conf/registry.py" in changed
    ok2, diff2 = confscan.check_registry(str(tmp_path))
    assert ok2, "\n".join(diff2[:40])
