"""Async checkpointing: crash safety, fencing, and the vpp host reorder.

The contract under test (parallel/checkpoint.py + trainer.save):

- an interval save blocks the caller only for the host snapshot; the
  DFS write rides a background writer fenced at the next save /
  restore / train-exit;
- a writer killed mid-write leaves a manifest-less directory that
  ``try_restore`` never sees (the previous complete checkpoint wins)
  and that the next retention sweep removes;
- a failed write surfaces exactly once, at the next fence;
- interleaved (vpp) plans reorder the stacked layer axis to LOGICAL
  order on the HOST, off the device step path, producing the same
  bytes the old device-side reorder wrote.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.models import get_config
from hadoop_tpu.parallel import MeshPlan
from hadoop_tpu.parallel.checkpoint import (AsyncCheckpointWriter,
                                            latest_step, list_checkpoints,
                                            load_checkpoint,
                                            snapshot_tree, write_snapshot)
from hadoop_tpu.testing.minicluster import MiniDFSCluster

BATCH = 8

requires_vma = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="multichip train step needs jax vma tracking (jax.typeof)")


@pytest.fixture(scope="module")
def cluster():
    with MiniDFSCluster(num_datanodes=3) as c:
        yield c


@pytest.fixture(scope="module")
def fs(cluster):
    return cluster.get_filesystem()


@pytest.fixture(scope="module")
def token_file(fs):
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 256, 200_000, dtype=np.uint16)
    fs.mkdirs("/adata")
    fs.write_all("/adata/tokens.bin", toks.tobytes())
    return "/adata/tokens.bin"


class _FailingFS:
    """Delegating FileSystem wrapper whose write_all starts raising
    after ``allow`` more calls once armed — the 'kill the writer
    mid-write' fault."""

    def __init__(self, inner):
        self._inner = inner
        self._armed = False
        self._allow = 0
        self.failures = 0

    def arm(self, allow: int) -> None:
        self._armed, self._allow = True, allow

    def disarm(self) -> None:
        self._armed = False

    def write_all(self, path, data):
        if self._armed:
            if self._allow <= 0:
                self.failures += 1
                raise IOError("injected mid-write crash")
            self._allow -= 1
        return self._inner.write_all(path, data)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _trainer(fs, token_file, ckpt_dir, **kw):
    from hadoop_tpu.parallel.trainer import Trainer
    cfg = get_config("tiny")
    kw.setdefault("plan", MeshPlan(dp=8))
    plan = kw.pop("plan")
    return Trainer(cfg, plan, fs, token_file, ckpt_dir, batch=BATCH,
                   lr=1e-2, ckpt_interval=kw.pop("interval", 0), **kw)


# ----------------------------------------------------------- writer unit

def test_writer_runs_in_background_and_fences():
    w = AsyncCheckpointWriter()
    gate = threading.Event()
    done = threading.Event()

    def job():
        gate.wait(10.0)
        done.set()

    w.submit(job)
    assert w.in_flight
    assert not done.is_set()
    gate.set()
    w.wait()
    assert done.is_set() and not w.in_flight


def test_writer_error_surfaces_exactly_once_at_fence():
    w = AsyncCheckpointWriter()

    def boom():
        raise IOError("dfs fell over")

    w.submit(boom)
    with pytest.raises(IOError, match="dfs fell over"):
        w.wait()
    w.wait()  # cleared: does not raise twice


def test_writer_submit_fences_previous_and_keeps_order():
    w = AsyncCheckpointWriter()
    order = []
    gate = threading.Event()

    def first():
        gate.wait(10.0)
        order.append(1)

    def second():
        order.append(2)

    w.submit(first)
    release = threading.Timer(0.05, gate.set)
    release.start()
    w.submit(second)   # must fence job 1 before starting job 2
    w.wait()
    assert order == [1, 2]


# ------------------------------------------------------- trainer saves

def test_async_save_blocks_only_for_snapshot(fs, token_file):
    """save(wait=False) returns while the (slowed) DFS write is still
    in flight; wait_for_checkpoint() fences it durable."""
    t = _trainer(fs, token_file, "/ackpt/async")
    t.step = 3
    gate = threading.Event()
    orig = fs.write_all

    def slow_write_all(path, data):
        gate.wait(10.0)
        return orig(path, data)

    fs.write_all = slow_write_all
    try:
        t0 = time.monotonic()
        t.save(wait=False)
        returned_after = time.monotonic() - t0
        assert t._ckpt_writer.in_flight
        assert latest_step(fs, "/ackpt/async") is None  # not durable yet
        gate.set()
        t.wait_for_checkpoint()
    finally:
        fs.write_all = orig
        gate.set()
    assert latest_step(fs, "/ackpt/async") == 3
    # the blocking part (fence+snapshot of a tiny model) is far from
    # the gated write; generous bound only guards gross regressions
    assert returned_after < 5.0


def test_writer_crash_leaves_previous_checkpoint_winning(fs, token_file):
    ffs = _FailingFS(fs)
    t = _trainer(ffs, token_file, "/ackpt/crash")
    t.step = 5
    t.save()                     # durable baseline at step 5

    ffs.arm(allow=2)             # die after 2 shard writes, no manifest
    t.step = 7
    t.save(wait=False)
    with pytest.raises(IOError, match="injected"):
        t.wait_for_checkpoint()  # the fence surfaces the failure
    ffs.disarm()

    # the torn step-7 dir has no manifest: invisible to restore
    assert latest_step(fs, "/ackpt/crash") == 5
    t2 = _trainer(fs, token_file, "/ackpt/crash")
    assert t2.try_restore()
    assert t2.step == 5
    # the next successful save's retention sweep removes the orphan
    t2.step = 9
    t2.save()
    assert list_checkpoints(fs, "/ackpt/crash") == [5, 9]
    assert not fs.exists("/ackpt/crash/step_000000000007")


def test_explicit_save_is_durable_on_return(fs, token_file):
    t = _trainer(fs, token_file, "/ackpt/durable")
    t.step = 11
    t.save()
    assert not t._ckpt_writer.in_flight
    assert latest_step(fs, "/ackpt/durable") == 11


def test_sync_mode_never_spawns_writer(fs, token_file):
    t = _trainer(fs, token_file, "/ackpt/sync", async_ckpt=False)
    t.step = 2
    t.save(wait=False)           # async off: wait flag is irrelevant
    assert not t._ckpt_writer.in_flight
    assert latest_step(fs, "/ackpt/sync") == 2


def test_snapshot_is_isolated_from_later_updates(fs):
    """The snapshot copies shard bytes: mutating (rebinding) the live
    tree after submit must not change what lands on disk."""
    tree = {"w": jnp.arange(8, dtype=jnp.float32)}
    snap = snapshot_tree(tree)
    tree["w"] = tree["w"] * 100.0
    write_snapshot(fs, "/ackpt/iso", 1, snap)
    like = {"w": np.zeros(8, np.float32)}
    out, _ = load_checkpoint(fs, "/ackpt/iso", like)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(8, dtype=np.float32))


def test_vpp_host_reorder_matches_device_reorder(fs, token_file):
    """An interleaved-plan save must persist LOGICAL layer order — the
    host-side snapshot permutation produces exactly what the old
    device-side logical_layer_order wrote."""
    from hadoop_tpu.parallel.train import logical_layer_order
    t = _trainer(fs, token_file, "/ackpt/vpp",
                 plan=MeshPlan(dp=2, pp=2, vpp=2))
    t.step = 1
    t.save()
    expect = logical_layer_order(t.params, t.cfg, t.plan)
    like = {"params": jax.tree_util.tree_map(np.asarray,
                                             jax.device_get(t.params)),
            "opt": jax.tree_util.tree_map(np.asarray,
                                          jax.device_get(t.opt)),
            "data_pos": np.zeros(2, np.int32)}
    out, step = load_checkpoint(fs, "/ackpt/vpp", like)
    assert step == 1
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(out["params"]),
            jax.tree_util.tree_leaves_with_path(expect)):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(jax.device_get(b)),
            err_msg=str(pa))
    # and the moments permuted with the params (non-zero1 plans)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(out["opt"].mu),
            jax.tree_util.tree_leaves_with_path(
                logical_layer_order(t.opt.mu, t.cfg, t.plan))):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(jax.device_get(b)),
            err_msg=str(pa))


def test_train_exit_fence_raises_write_failure(fs, token_file):
    """A failed ASYNC interval write must surface from train() itself
    (the exit fence), not vanish: the regression was exc_info() being
    consulted inside the except block, where it reports the just-caught
    write error and never looks 'clean'. The step_fn is stubbed so the
    loop runs without the multichip trace."""
    ffs = _FailingFS(fs)
    t = _trainer(ffs, token_file, "/ackpt/fence", interval=2)
    t.step_fn = lambda p, o, tok, tgt: (p, o, {"loss": jnp.zeros(())})
    ffs.arm(allow=1)             # interval save at step 2 dies mid-write
    with pytest.raises(IOError, match="injected"):
        t.train(2)
    ffs.disarm()
    # surfaced exactly once: the next fence is clean
    t.wait_for_checkpoint()


def test_step_exception_not_masked_by_write_failure(fs, token_file):
    """When a STEP raises, a concurrent write failure is logged, not
    allowed to replace the real error."""
    ffs = _FailingFS(fs)
    t = _trainer(ffs, token_file, "/ackpt/fence2", interval=1)
    calls = {"n": 0}

    def step_fn(p, o, tok, tgt):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("step blew up")
        return p, o, {"loss": jnp.zeros(())}

    t.step_fn = step_fn
    ffs.arm(allow=1)             # the step-1 interval save dies too
    with pytest.raises(RuntimeError, match="step blew up"):
        t.train(2)
    ffs.disarm()


@requires_vma
def test_interval_crash_resumes_bit_exact_with_inflight(fs, token_file):
    """Kill the ASYNC interval save's writer mid-write during train();
    the run must surface the failure at the train-exit fence, restore
    must land on the previous complete checkpoint, and resume must
    continue the reference loss curve bit-exactly (cursor semantics
    preserved with prefetched batches in flight)."""
    ref = _trainer(fs, token_file, "/ackpt/ref",
                   plan=MeshPlan(dp=2, tp=2))
    ref_losses = ref.train(6)

    a = _trainer(fs, token_file, "/ackpt/mid",
                 plan=MeshPlan(dp=2, tp=2), interval=2)
    a.train(2)                   # durable step-2 checkpoint
    a.wait_for_checkpoint()
    ffs = _FailingFS(fs)
    a.fs = ffs
    ffs.arm(allow=1)
    with pytest.raises(IOError, match="injected"):
        a.train(2)               # interval save at step 4 dies; fence
        a.wait_for_checkpoint()  # (whichever fence fires first raises)
    ffs.disarm()

    b = _trainer(fs, token_file, "/ackpt/mid",
                 plan=MeshPlan(dp=2, tp=2))
    assert b.try_restore()
    assert b.step == 2
    b_losses = b.train(4)
    np.testing.assert_allclose(b_losses, ref_losses[2:], rtol=1e-6)
