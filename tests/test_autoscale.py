"""Elastic serving fleet: autoscaler policy/signals, door QoS,
drain-aware scale-in, registry heartbeat staleness, router 429 edges.

Policy tests drive ``Autoscaler._decide`` on synthetic snapshots (the
pure half of the control loop); the drain and scale-in tests run real
engines + doors so the protocol is exercised end-to-end in-process —
the subprocess/CLI variant lives in ``benchmarks/serve_bench.py
--storm``.
"""

import json
import math
import http.client
import threading
import time

import jax
import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.models.config import get_config
from hadoop_tpu.models.decoder import init_params
from hadoop_tpu.serving.autoscale import (Autoscaler, FleetActuator,
                                          histogram_p99, parse_prom)
from hadoop_tpu.serving.autoscale.signals import (FleetSnapshot,
                                                  ReplicaSample)
from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
from hadoop_tpu.serving.qos import (DecayCostScheduler,
                                    FairAdmissionQueue, QoSGate)
from hadoop_tpu.serving.server import ServingServer


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("tiny")
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def _post_json(port, path, payload, timeout=60.0):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload).encode())
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, (json.loads(body) if body else {}), \
            resp.getheader("Retry-After")
    finally:
        conn.close()


# ----------------------------------------------------------- signal math

def test_parse_prom_and_histogram_p99():
    text = """# HELP htpu_x_total things
# TYPE htpu_x_total counter
htpu_x_total{source="a"} 5
htpu_h_bucket{source="a",le="0.01"} 50
htpu_h_bucket{source="a",le="0.1"} 99
htpu_h_bucket{source="a",le="+Inf"} 100
htpu_h_count{source="a"} 100
htpu_gauge 2.5
garbage line that must not crash the parser
"""
    fams = parse_prom(text)
    assert fams["htpu_x_total"] == [({"source": "a"}, 5.0)]
    assert fams["htpu_gauge"] == [({}, 2.5)]
    buckets = {float(lab["le"]) if lab["le"] != "+Inf" else math.inf: v
               for lab, v in fams["htpu_h_bucket"]}
    # p50 inside the first bucket, p99 exactly at the 0.1 edge, and the
    # overflow bucket never interpolates past the last finite bound
    assert histogram_p99(buckets, q=0.99) == pytest.approx(0.1)
    assert histogram_p99(buckets, q=0.995) == pytest.approx(0.1)
    assert histogram_p99(buckets, q=0.25) == pytest.approx(0.005)
    assert histogram_p99({}) is None
    assert histogram_p99({0.01: 0.0, math.inf: 0.0}) is None


def _sample(path="/services/serving/s/r0", role="mixed", ok=True,
            queue=0, active=0, slots=4, backlog=0, cached=0,
            load_seconds=0.0):
    return ReplicaSample(path=path, host="127.0.0.1", port=1, role=role,
                        ok=ok, queue_depth=queue, active=active,
                        slots=slots, prefill_backlog=backlog,
                        cached_blocks=cached,
                        load_seconds=load_seconds)


def test_snapshot_pools_and_utilization():
    snap = FleetSnapshot(at=0.0, samples=[
        _sample("/s/d0", active=4),
        _sample("/s/d1", active=0),
        _sample("/s/p0", role="prefill", backlog=100),
    ])
    assert {s.path for s in snap.pool("decode")} == {"/s/d0", "/s/d1"}
    assert [s.path for s in snap.pool("prefill")] == ["/s/p0"]
    assert snap.utilization("decode") == pytest.approx(0.5)
    assert snap.mean_prefill_backlog("prefill") == pytest.approx(100)
    # a draining replica belongs to no pool (mid-retirement)
    snap.samples[0].draining = True
    assert [s.path for s in snap.pool("decode")] == ["/s/d1"]


# ----------------------------------------------------------------- policy

def _mk_scaler(**over):
    conf = Configuration(load_defaults=False)
    conf.set("serving.autoscale.breach.polls", "2")
    conf.set("serving.autoscale.idle.polls", "2")
    conf.set("serving.autoscale.cooldown", "0s")
    conf.set("serving.autoscale.ttft.p99.slo", "1s")
    for k, v in over.items():
        conf.set(k, v)
    # dead registry address: these tests drive _decide directly
    return Autoscaler(conf, ("127.0.0.1", 1), "svc")


def test_grow_needs_consecutive_breaches_then_cooldown():
    sc = _mk_scaler(**{"serving.autoscale.cooldown": "60s"})
    hot = FleetSnapshot(at=0.0, samples=[_sample(queue=9)],
                        ttft_p99_s=5.0, ttft_samples=10)
    assert sc._decide("decode", hot) is None          # breach 1 of 2
    d = sc._decide("decode", hot)
    assert d is not None and d.action == "grow" and d.target == 2
    assert "ttft" in d.reason
    # cooldown holds the pool even though the breach persists
    assert sc._decide("decode", hot) is None
    assert sc._decide("decode", hot) is None


def test_breach_counter_resets_on_a_quiet_poll():
    sc = _mk_scaler()
    hot = FleetSnapshot(at=0.0, samples=[_sample(queue=9)])
    calm = FleetSnapshot(at=0.0, samples=[_sample()])
    assert sc._decide("decode", hot) is None
    sc._decide("decode", calm)                        # breach resets
    assert sc._decide("decode", hot) is None          # back to 1 of 2
    assert sc._decide("decode", hot).action == "grow"


def test_shed_signal_triggers_growth():
    sc = _mk_scaler(**{"serving.autoscale.breach.polls": "1"})
    snap = FleetSnapshot(at=0.0, samples=[_sample()], shed_delta=3)
    d = sc._decide("decode", snap)
    assert d.action == "grow" and "shed" in d.reason


def test_cold_start_lead_grows_before_saturation():
    # same 75% utilization: instant-loading replicas hold (under the
    # 0.85 high-water mark), replicas that take 30s to come up
    # (horizon 60s, lead cap 0.3 → effective mark 0.55) grow NOW
    sc = _mk_scaler(**{"serving.autoscale.breach.polls": "1",
                       "serving.autoscale.util.high": "0.85"})
    cold_fast = FleetSnapshot(at=0.0, samples=[
        _sample(active=3, slots=4, load_seconds=0.1) for _ in range(2)])
    assert sc._decide("decode", cold_fast) is None
    cold_slow = FleetSnapshot(at=0.0, samples=[
        _sample(f"/s/r{i}", active=3, slots=4, load_seconds=30.0)
        for i in range(2)])
    d = sc._decide("decode", cold_slow)
    assert d is not None and d.action == "grow"
    assert "cold-start lead" in d.reason


def test_scale_in_needs_idle_polls_and_picks_cheapest_victim():
    sc = _mk_scaler()
    quiet = FleetSnapshot(at=0.0, samples=[
        _sample("/s/r0", active=1, cached=50),
        _sample("/s/r1", active=0, cached=40),
        _sample("/s/r2", active=0, cached=3),
    ])
    assert sc._decide("decode", quiet) is None        # idle 1 of 2
    d = sc._decide("decode", quiet)
    assert d is not None and d.action == "shrink" and d.target == 2
    # least loaded, then least cache-resident: r2's drain costs least
    assert d.victim == "/s/r2"


def test_scale_in_never_shrinks_below_min():
    sc = _mk_scaler(**{"serving.autoscale.min": "1",
                       "serving.autoscale.idle.polls": "1"})
    quiet = FleetSnapshot(at=0.0, samples=[_sample()])
    assert sc._decide("decode", quiet) is None


def test_pool_below_min_floor_is_restored_without_a_breach():
    # a crashed replica whose record TTL-expired: the pool is empty and
    # quiet — no signal ever breaches, the floor must grow it anyway
    sc = _mk_scaler(**{"serving.autoscale.min": "2"})
    quiet = FleetSnapshot(at=0.0, samples=[_sample()])
    d = sc._decide("decode", quiet)
    assert d is not None and d.action == "grow" and d.target == 2
    assert "floor" in d.reason


def test_scale_in_skips_pools_with_only_min_healthy_replicas():
    # one working + one wedged replica: n=2 > min=1, but retiring the
    # healthy one would leave a fleet of corpses
    sc = _mk_scaler(**{"serving.autoscale.idle.polls": "1"})
    snap = FleetSnapshot(at=0.0, samples=[
        _sample("/s/ok"),
        _sample("/s/wedged", ok=False),
    ])
    assert sc._decide("decode", snap) is None


def test_prefill_pool_sized_independently():
    sc = _mk_scaler(**{"serving.autoscale.breach.polls": "1",
                       "serving.autoscale.backlog.high": "64"})
    snap = FleetSnapshot(at=0.0, samples=[
        _sample("/s/d0", queue=0),
        _sample("/s/p0", role="prefill", backlog=500),
    ])
    d = sc._decide("prefill", snap)
    assert d is not None and d.role == "prefill" and d.action == "grow"
    assert sc._decide("decode", snap) is None
    # a fleet with no prefill replicas and prefill.min=0 has no
    # prefill pool to manage at all
    sc2 = _mk_scaler()
    snap2 = FleetSnapshot(at=0.0, samples=[_sample(backlog=500)])
    assert sc2._decide("prefill", snap2) is None


# -------------------------------------------------------------- door QoS

def test_decay_cost_scheduler_levels_by_share():
    conf = Configuration(load_defaults=False)
    conf.set("serving.qos.decay.period", "3600s")   # no decay in-test
    sched = DecayCostScheduler(4, conf)
    sched.charge("heavy", 900)
    sched.charge("light", 100)
    assert sched.share_of("heavy") == pytest.approx(0.9)
    assert sched.level_of("heavy") == 3               # >= 1/2 share
    assert sched.level_of("light") == 0               # < 1/8 share
    assert sched.num_tenants == 2
    sched.stop()


class _Req:
    def __init__(self, tenant):
        self.tenant = tenant


def test_fair_admission_queue_wrr_and_urgent_lane():
    class _FixedSched:
        num_levels = 4

        def level_of(self, tenant):
            return 3 if tenant == "heavy" else 0

    q = FairAdmissionQueue(_FixedSched())
    h1, h2, h3 = _Req("heavy"), _Req("heavy"), _Req("heavy")
    light = _Req("light")
    for r in (h1, h2, h3, light):
        q.append(r)
    assert len(q) == 4
    # peek == pop (the engine peeks, allocates, then pops)
    assert q[0] is light                 # level 0 outranks the backlog
    assert q.popleft() is light
    # heavy backlog still drains (weighted RR, never starved)
    assert q.popleft() is h1
    # a preempted request re-queues at the absolute front, regardless
    # of its tenant's level (preemption order is the engine's contract)
    pre = _Req("heavy")
    q.appendleft(pre)
    assert q[0] is pre
    assert q.popleft() is pre
    assert q.popleft() is h2
    assert q.popleft() is h3
    assert len(q) == 0
    with pytest.raises(IndexError):
        q.popleft()


def test_qos_gate_sheds_over_share_only_under_overload():
    class _Eng:
        queue_depth = 0

    conf = Configuration(load_defaults=False)
    conf.set("serving.qos.decay.period", "3600s")
    conf.set("serving.qos.shed.queue.depth", "4")
    conf.set("serving.qos.queue.max", "10")
    eng = _Eng()
    gate = QoSGate(conf, eng)
    gate.sched.charge("heavy", 900)
    gate.sched.charge("light", 100)
    # no overload: even the heavy tenant queues
    ok, _, _ = gate.admit("heavy", 10)
    assert ok
    # overload: heavy sheds with a level-scaled Retry-After, light rides
    eng.queue_depth = 5
    ok, retry_after, level = gate.admit("heavy", 10)
    assert not ok and level > 0 and retry_after >= gate.retry_after_s
    ok, _, _ = gate.admit("light", 10)
    assert ok
    # past the hard cap everyone sheds
    eng.queue_depth = 10
    ok, _, _ = gate.admit("light", 10)
    assert not ok
    assert gate.stats()["sheds"] == 2
    gate.stop()


def test_qos_single_tenant_is_never_fairness_shed():
    class _Eng:
        queue_depth = 100

    conf = Configuration(load_defaults=False)
    conf.set("serving.qos.decay.period", "3600s")
    conf.set("serving.qos.shed.queue.depth", "4")
    conf.set("serving.qos.queue.max", "1000")
    gate = QoSGate(conf, _Eng())
    # the only tenant owns 100% share — there is no one to be fair to
    for _ in range(5):
        ok, _, _ = gate.admit("solo", 50)
        assert ok
    gate.stop()


def test_door_sheds_heavy_tenant_with_retry_after(tiny_model):
    """Door-level 429: the engine is never started, so admitted
    requests park in the queue; once the queue is past the shed line a
    second tenant over its share gets 429 + Retry-After while the
    light tenant is still admitted (408 on its own timeout — admitted,
    not shed)."""
    params, cfg = tiny_model
    conf = Configuration(load_defaults=False)
    conf.set("serving.qos.decay.period", "3600s")
    conf.set("serving.qos.shed.queue.depth", "2")
    # two tenants in the whole test: over-share means majority share
    conf.set("serving.qos.thresholds", "0.5,0.7,0.9")
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32)
    gate = QoSGate(conf, eng)
    srv = ServingServer(eng, conf, qos=gate)
    srv.start()
    try:
        results = {}

        def ask(i, user):
            results[i] = _post_json(
                srv.port, f"/v1/generate?user.name={user}",
                {"tokens": [1, 2], "max_new_tokens": 4,
                 "timeout": 1.5})

        # one light probe seeds the second tenant, then the heavy
        # tenant parks requests past the shed line
        t0 = threading.Thread(target=ask, args=("light0", "light"))
        t0.start()
        parked = [threading.Thread(target=ask, args=(f"h{i}", "heavy"))
                  for i in range(3)]
        for t in parked:
            t.start()
        deadline = time.monotonic() + 10
        # once the parked queue crosses the shed line, further heavy
        # arrivals (including some of the parked threads) shed. The
        # gate only sheds with >= 2 TRACKED tenants, so also wait for
        # the light probe's charge to land — two parked heavies alone
        # satisfy the depth check, and if light loses the thread-start
        # race the next heavy is (correctly) admitted, parking for the
        # server's full 60s default timeout.
        while (eng.queue_depth < 2 or gate.sched.num_tenants < 2) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.queue_depth >= 2 and gate.sched.num_tenants >= 2
        status, body, retry_after = _post_json(
            srv.port, "/v1/generate?user.name=heavy",
            {"tokens": [1, 2], "max_new_tokens": 4, "timeout": 5})
        assert status == 429, body
        assert "ServerTooBusy" in str(body)
        assert retry_after is not None and float(retry_after) > 0
        # the light tenant is still ADMITTED under the same overload
        status, body, _ = _post_json(
            srv.port, "/v1/generate?user.name=light",
            {"tokens": [1, 2], "max_new_tokens": 4, "timeout": 0.3})
        assert status == 408, body      # parked then timed out — never
        #                                 shed
        for t in [t0] + parked:
            t.join(timeout=30)
        assert gate.stats()["sheds"] >= 1
        assert gate.stats()["sheds_by_tenant"].get("heavy", 0) >= 1
        assert "light" not in gate.stats()["sheds_by_tenant"]
    finally:
        srv.stop()


# ------------------------------------------- router edges (satellite 2)

def test_router_429_retries_on_another_replica_408_fails_fast(
        tiny_model):
    from hadoop_tpu.registry import (RegistryClient, RegistryServer,
                                     ServiceRecord)
    from hadoop_tpu.serving.router import (ReplicaRequestError,
                                           ServingRouter, replica_path)
    params, cfg = tiny_model
    conf = Configuration(load_defaults=False)
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    servers, engines = [], []
    try:
        # replica 0 sheds EVERYTHING (a gate stub); replica 1 serves
        class _AlwaysShed:
            retry_after_s = 0.05

            @staticmethod
            def cost_of(tokens, max_new):
                return 1.0

            def admit(self, tenant, cost):
                return False, 0.05, 3

            def stats(self):
                return {}

            def stop(self):
                pass

        for i in range(2):
            eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                               max_context=32)
            srv = ServingServer(eng, Configuration(load_defaults=False),
                                qos=_AlwaysShed() if i == 0 else None)
            eng.start()
            srv.start()
            engines.append(eng)
            servers.append(srv)
        reg_addr = ("127.0.0.1", reg_srv.port)
        rc = RegistryClient(reg_addr, conf)
        for i, srv in enumerate(servers):
            rc.register(ServiceRecord(
                replica_path("edges", f"r{i}"),
                {"http": f"127.0.0.1:{srv.port}"},
                {"state": "serving"}), ttl_s=60.0, auto_renew=False)
        router = ServingRouter(reg_addr, "edges", conf, cache_ttl_s=0.0)
        # every request succeeds: 429s from r0 fail over to r1
        for _ in range(8):
            out = router.generate({"tokens": [3, 4, 5],
                                   "max_new_tokens": 3})
            assert len(out["tokens"]) == 3
        assert engines[0].tokens_generated == 0
        assert engines[1].tokens_generated > 0
        # 408 stays fail-fast: r1's engine is stopped so the request
        # parks and times out — the router must NOT replay it
        engines[1].stop()
        rc.unregister(replica_path("edges", "r0"))
        with pytest.raises(ReplicaRequestError) as ei:
            router.generate({"tokens": [3, 4, 5], "max_new_tokens": 3,
                             "timeout": 0.3})
        assert ei.value.status == 408
        router.close()
        rc.close()
    finally:
        for srv in servers:
            srv.stop()
        reg_srv.stop()


# ------------------------- registry heartbeat + staleness (satellite 1)

def test_registry_ttl_evicts_dead_record_and_stale_hb_is_skipped():
    from hadoop_tpu.registry import (HEARTBEAT_ATTR, RegistryServer,
                                     ServiceRecord, record_is_stale)
    from hadoop_tpu.serving.router import ServingRouter, replica_path
    conf = Configuration(load_defaults=False)
    conf.set("registry.sweep.interval", "0.1s")
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    try:
        # a replica that died without deregistering: registered with a
        # short TTL and never renewed — the sweep evicts it
        reg_srv.put(ServiceRecord(replica_path("ttl", "dead"),
                                  {"http": "127.0.0.1:1"},
                                  {"state": "serving"}), ttl_s=0.3)
        assert len(reg_srv.list("/services/serving/ttl")) == 1
        deadline = time.monotonic() + 5
        while reg_srv.list("/services/serving/ttl") and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert reg_srv.list("/services/serving/ttl") == []

        # heartbeat staleness: the record still SITS in the registry
        # (long lease) but its owner stopped stamping — consumers skip
        # it instead of retrying into a corpse
        stale = ServiceRecord(
            replica_path("hb", "stale"), {"http": "127.0.0.1:1"},
            {"state": "serving",
             HEARTBEAT_ATTR: f"{time.time() - 100:.3f}"})
        fresh = ServiceRecord(
            replica_path("hb", "fresh"), {"http": "127.0.0.1:2"},
            {"state": "serving", HEARTBEAT_ATTR: f"{time.time():.3f}"})
        legacy = ServiceRecord(       # no heartbeat attr: never stale
            replica_path("hb", "legacy"), {"http": "127.0.0.1:3"},
            {"state": "serving"})
        assert record_is_stale(stale, 10.0)
        assert not record_is_stale(fresh, 10.0)
        assert not record_is_stale(legacy, 10.0)
        for r in (stale, fresh, legacy):
            reg_srv.put(r, ttl_s=3600.0)
        router = ServingRouter(("127.0.0.1", reg_srv.port), "hb", conf,
                               cache_ttl_s=0.0)
        live = {r.path for r in router.replicas(refresh=True)}
        assert live == {replica_path("hb", "fresh"),
                        replica_path("hb", "legacy")}
        router.close()
    finally:
        reg_srv.stop()


def test_replica_heartbeat_keeps_record_alive_and_fresh(tmp_path,
                                                        tiny_model):
    """A live replica outlives many record TTLs through its heartbeat
    (which also refreshes live-load attributes); once it stops beating
    — death without deregistration — the sweep evicts the record."""
    from hadoop_tpu.fs import LocalFileSystem
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    from hadoop_tpu.registry import HEARTBEAT_ATTR, RegistryServer
    from hadoop_tpu.serving.service import ServingReplica
    params, cfg = tiny_model
    save_checkpoint(LocalFileSystem(), f"{tmp_path}/ckpt", 2,
                    {"params": params, "opt": {}})
    conf = Configuration(load_defaults=False)
    conf.set("registry.sweep.interval", "0.1s")
    conf.set("serving.registry.record.ttl", "0.6s")
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    try:
        replica = ServingReplica(
            conf, name="hb-live", checkpoint=f"file://{tmp_path}/ckpt",
            preset="tiny", registry_addr=("127.0.0.1", reg_srv.port),
            instance="i0")
        replica.start()
        time.sleep(1.5)                 # two+ TTLs worth of beats
        recs = reg_srv.list("/services/serving/hb-live")
        assert len(recs) == 1
        attrs = recs[0].attributes
        assert time.time() - float(attrs[HEARTBEAT_ATTR]) < 1.0
        assert "queue_depth" in attrs   # live load rides the beat
        # simulate a hard death: beats stop, nothing deregisters
        replica._stopped.set()
        deadline = time.monotonic() + 5
        while reg_srv.list("/services/serving/hb-live") and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert reg_srv.list("/services/serving/hb-live") == []
        replica.server.stop()
    finally:
        reg_srv.stop()


# ------------------------------------- drain protocol (satellite 3)

def test_drain_persists_prefixes_completes_inflight_survivor_recovers(
        tmp_path, tiny_model):
    """Scale-in under an active shared-prefix workload: the victim
    finishes every in-flight request (zero failures), force-persists
    its resident prefixes to the DFS tier, and a fresh replica over the
    same store serves the next shared-prefix request with
    ``hits_dfs > 0`` instead of re-prefilling."""
    from hadoop_tpu.fs import LocalFileSystem
    params, cfg = tiny_model
    fs = LocalFileSystem()
    head = [5, 9, 2, 7, 1, 8, 3, 6]                  # 2 full blocks

    def mk():
        return DecodeEngine(params, cfg, max_batch=4, block_size=4,
                            max_context=48, prefill_chunk=4,
                            kv_store_fs=fs,
                            kv_store_dir=f"{tmp_path}/kv",
                            kv_dfs_min_refs=100)     # hotness never
        #   crosses the threshold — only the DRAIN persists anything

    eng1 = mk()
    srv1 = ServingServer(eng1, Configuration(load_defaults=False))
    eng1.start()
    srv1.start()
    results = {}

    def ask(i, tail, max_new):
        results[i] = _post_json(srv1.port, "/v1/generate",
                                {"tokens": head + tail,
                                 "max_new_tokens": max_new,
                                 "timeout": 60.0})

    threads = [threading.Thread(target=ask, args=(i, [10 + i], 12))
               for i in range(3)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 30
    while eng1.num_active < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert eng1.num_active >= 1          # the workload is in flight
    # the autoscaler's door-drain, mid-workload
    status, body, _ = _post_json(srv1.port, "/v1/admin/drain", {})
    assert status == 202 and body["draining"] is True
    for t in threads:
        t.join(timeout=60)
    # every in-flight request completed — zero failures
    for i in range(3):
        status, body, _ = results[i]
        assert status == 200, body
        assert len(body["tokens"]) == 12
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        h = _post_json(srv1.port, "/v1/generate", {"tokens": [1]})[0]
        if h == 503:
            break
        time.sleep(0.05)
    assert _post_json(srv1.port, "/v1/generate", {"tokens": [1]})[0] \
        == 503                           # drained: new work refused
    # wait for the async drain (persist included) to finish
    deadline = time.monotonic() + 60
    while eng1.kvstore.stats()["dfs_persists"] == 0 and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    persisted = eng1.kvstore.stats()["dfs_persists"]
    assert persisted > 0, "drain persisted nothing to the DFS tier"
    srv1.stop()
    # the survivor: cold HBM, same DFS store — the shared head comes
    # back from the DataNodes, not from a re-prefill
    eng2 = mk()
    out = eng2.generate([head + [42]],
                        SamplingParams(max_new_tokens=4))
    assert len(out[0]) == 4
    st = eng2.kvstore.stats()
    assert st["hits_dfs"] >= 2           # both head blocks recovered
    eng2.stop()


# ------------------------------- autoscaler scale-in, end to end

def test_autoscaler_scale_in_drains_victim_via_door(tiny_model):
    """poll() → shrink decision → POST /v1/admin/drain on the
    affinity-cheapest victim → watch /v1/health → retire through the
    actuator. Runs against real doors + the real registry."""
    from hadoop_tpu.registry import (HEARTBEAT_ATTR, RegistryClient,
                                     RegistryServer, ServiceRecord)
    from hadoop_tpu.serving.router import replica_path
    params, cfg = tiny_model
    conf = Configuration(load_defaults=False)
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    servers, engines = [], []
    retired = []

    class _Act(FleetActuator):
        def scale_out(self, role, target):
            raise AssertionError("quiet fleet must never grow")

        def retire(self, sample, target):
            retired.append((sample.path, target))

    try:
        for i in range(2):
            eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                               max_context=32)
            srv = ServingServer(eng, Configuration(load_defaults=False))
            eng.start()
            srv.start()
            engines.append(eng)
            servers.append(srv)
        reg_addr = ("127.0.0.1", reg_srv.port)
        rc = RegistryClient(reg_addr, conf)
        for i, srv in enumerate(servers):
            rc.register(ServiceRecord(
                replica_path("shrinkme", f"r{i}"),
                {"http": f"127.0.0.1:{srv.port}"},
                {"state": "serving",
                 HEARTBEAT_ATTR: f"{time.time():.3f}"}),
                ttl_s=3600.0, auto_renew=False)
        # seed a prefix on r0 so the victim choice (fewest cached
        # blocks) deterministically lands on r1
        engines[0].generate([[5, 9, 2, 7, 1, 8, 3, 6, 1]],
                            SamplingParams(max_new_tokens=2))
        as_conf = Configuration(load_defaults=False)
        as_conf.set("serving.autoscale.idle.polls", "1")
        as_conf.set("serving.autoscale.cooldown", "0s")
        as_conf.set("serving.autoscale.drain.timeout", "30s")
        as_conf.set("serving.registry.record.ttl", "3600s")
        scaler = Autoscaler(as_conf, reg_addr, "shrinkme",
                            actuator=_Act())
        decisions = scaler.poll()
        assert [d.action for d in decisions] == ["shrink"]
        assert decisions[0].victim == replica_path("shrinkme", "r1")
        deadline = time.monotonic() + 30
        while not retired and time.monotonic() < deadline:
            time.sleep(0.05)
        assert retired == [(replica_path("shrinkme", "r1"), 1)]
        # the victim is draining: refuses new work, r0 untouched
        status, _, _ = _post_json(servers[1].port, "/v1/generate",
                                  {"tokens": [1]})
        assert status == 503
        status, _, _ = _post_json(servers[0].port, "/v1/generate",
                                  {"tokens": [1, 2],
                                   "max_new_tokens": 2})
        assert status == 200
        # while a drain is pending the pool must not shrink again
        # (the victim reads as draining, pool size 1 == min)
        assert scaler.poll() == []
        scaler.stop()
        rc.close()
    finally:
        for srv in servers:
            srv.stop()
        reg_srv.stop()
