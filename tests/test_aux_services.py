"""Metrics sinks, HTTP auth filter, service registry, disk checker.
Ref: metrics2/sink/{FileSink,StatsDSink}.java, hadoop-auth
AuthenticationFilter.java, hadoop-registry, util/DiskChecker.java."""

import json
import os
import socket
import time
import urllib.error
import urllib.request

import pytest

from hadoop_tpu.conf import Configuration


# ------------------------------------------------------------------ sinks


def test_file_sink_and_publisher(tmp_path):
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.sinks import FileSink, SinkPublisher
    reg = metrics_system().source("sinktest")
    c = reg.counter("things")
    c.incr(41)
    path = str(tmp_path / "metrics.jsonl")
    pub = SinkPublisher(period_s=999).add_sink(FileSink(path))
    c.incr()
    pub.publish_once()
    pub.stop()
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert lines
    assert lines[0]["metrics"]["sinktest"]["things"] == 42


def test_statsd_sink_datagrams():
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.sinks import SinkPublisher, StatsDSink
    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(5.0)
    port = rx.getsockname()[1]
    reg = metrics_system().source("statsdtest")
    reg.counter("pkts").incr(7)
    pub = SinkPublisher(period_s=999).add_sink(
        StatsDSink("127.0.0.1", port))
    pub.publish_once()
    got = []
    try:
        for _ in range(200):
            got.append(rx.recv(4096).decode())
            if any("statsdtest.pkts:7|g" in g for g in got):
                break
    except socket.timeout:
        pass
    assert any("statsdtest.pkts:7|g" in g for g in got), got[:5]


def test_failing_sink_isolated(tmp_path):
    from hadoop_tpu.metrics.sinks import (CallbackSink, FileSink,
                                          SinkPublisher)
    boom = CallbackSink(lambda ts, s: (_ for _ in ()).throw(IOError("x")))
    path = str(tmp_path / "ok.jsonl")
    pub = SinkPublisher(period_s=999).add_sink(boom).add_sink(
        FileSink(path))
    pub.publish_once()
    assert open(path).read().strip()


# ------------------------------------------------------------------- auth


def test_http_auth_pseudo_and_cookie():
    from hadoop_tpu.http.server import HttpServer
    from hadoop_tpu.security.http_auth import AuthFilter
    http = HttpServer(Configuration(load_defaults=False),
                      ("127.0.0.1", 0), daemon_name="authtest")
    filt = AuthFilter(b"secret")
    http.add_handler("/prot", filt.wrap(
        lambda q, b: (200, {"user": q["__user__"]})))
    http.start()
    try:
        base = f"http://127.0.0.1:{http.port}/prot"
        # no auth → 401
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base)
        assert exc.value.code == 401
        # pseudo auth → 200 + signed cookie
        resp = urllib.request.urlopen(f"{base}?user.name=alice")
        assert json.loads(resp.read())["user"] == "alice"
        cookie = resp.headers.get("Set-Cookie", "")
        assert cookie.startswith("hadoop.auth=")
        # cookie replays without user.name
        req = urllib.request.Request(
            base, headers={"Cookie": cookie.split(";")[0]})
        assert json.loads(urllib.request.urlopen(req).read())[
            "user"] == "alice"
        # tampered cookie → 401
        bad = cookie.split(";")[0][:-4] + "beef"
        req = urllib.request.Request(base, headers={"Cookie": bad})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 401
    finally:
        http.stop()


def test_auth_token_expiry():
    from hadoop_tpu.security.http_auth import AuthenticationToken
    tok = AuthenticationToken("bob", time.time() - 1)
    signed = tok.sign(b"s")
    assert AuthenticationToken.verify(signed, b"s") is None
    tok2 = AuthenticationToken("bob", time.time() + 60)
    got = AuthenticationToken.verify(tok2.sign(b"s"), b"s")
    assert got is not None and got.user == "bob"
    assert AuthenticationToken.verify(tok2.sign(b"s"), b"other") is None


# --------------------------------------------------------------- registry


def test_registry_register_resolve_expire():
    from hadoop_tpu.registry import (RegistryClient, RegistryServer,
                                     ServiceRecord)
    conf = Configuration(load_defaults=False)
    conf.set("registry.sweep.interval", "0.2s")
    srv = RegistryServer(conf)
    srv.init(conf)
    srv.start()
    try:
        c = RegistryClient(("127.0.0.1", srv.port), conf)
        c.register(ServiceRecord("/services/nn/active",
                                 {"rpc": "127.0.0.1:9000"},
                                 {"role": "active"}), ttl_s=5.0)
        c.register(ServiceRecord("/services/rm",
                                 {"rpc": "127.0.0.1:9001"},
                                 ephemeral=False), ttl_s=1.0)
        got = c.resolve("/services/nn/active")
        assert got.endpoints["rpc"] == "127.0.0.1:9000"
        assert got.attributes["role"] == "active"
        assert len(c.list("/services")) == 2
        # a second client whose owner dies (no renewal) expires
        c2 = RegistryClient(("127.0.0.1", srv.port), conf)
        c2.register(ServiceRecord("/services/ephemeral", {"x": "y"}),
                    ttl_s=0.4, auto_renew=False)
        assert c2.resolve("/services/ephemeral") is not None
        c2.close()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if c.resolve("/services/ephemeral") is None:
                break
            time.sleep(0.1)
        assert c.resolve("/services/ephemeral") is None
        # persistent record survives with no renewal
        time.sleep(0.6)
        assert c.resolve("/services/rm") is not None
        c.close()
    finally:
        srv.stop()


def test_registry_reregisters_after_server_state_loss():
    """A renewal that finds its record gone (registry restarted and
    lost ephemeral state, or the sweep beat a late renewal) must
    RECREATE the record — the ZK-ephemeral-recreate analog. Before the
    fix the client renewed into the void forever and the service
    silently vanished from the registry."""
    from hadoop_tpu.registry import (RegistryClient, RegistryServer,
                                     ServiceRecord)
    conf = Configuration(load_defaults=False)
    srv = RegistryServer(conf)
    srv.init(conf)
    srv.start()
    try:
        c = RegistryClient(("127.0.0.1", srv.port), conf)
        c.register(ServiceRecord("/services/am", {"rpc": "h:1"}),
                   ttl_s=30.0)
        assert c.resolve("/services/am") is not None
        # simulate registry state loss
        with srv._lock:
            srv._entries.clear()
        assert c.resolve("/services/am") is None
        c._renew_once()
        got = c.resolve("/services/am")
        assert got is not None and got.endpoints["rpc"] == "h:1"
        c.close()
    finally:
        srv.stop()


def test_lz4_corrupt_size_word_rejected_without_allocation():
    """An lz4 blob whose size prefix claims gigabytes must be rejected
    as corrupt, not allocated (a 12-byte hostile blob could otherwise
    demand a 4 GB buffer before decompression even starts)."""
    import struct as _struct

    from hadoop_tpu.io.codecs import Lz4Codec
    if not Lz4Codec.available():
        pytest.skip("liblz4 not present")
    codec = Lz4Codec()
    rt = codec.decompress(codec.compress(b"payload" * 100))
    assert rt == b"payload" * 100
    evil = _struct.pack("<I", 0xFFFFFFF0) + b"\x00" * 8
    with pytest.raises(IOError):
        codec.decompress(evil)


# ----------------------------------------------------------- disk checker


def test_check_dir(tmp_path):
    from hadoop_tpu.util.misc import check_dir
    d = str(tmp_path / "vol0")
    check_dir(d)                      # created + probed
    assert os.path.isdir(d)
    with pytest.raises(OSError):
        check_dir(d, min_free_bytes=1 << 60)  # absurd floor
    ro = tmp_path / "ro"
    ro.mkdir()
    os.chmod(ro, 0o500)
    try:
        if os.geteuid() != 0:  # root bypasses mode bits
            with pytest.raises(OSError):
                check_dir(str(ro))
    finally:
        os.chmod(ro, 0o700)


# ------------------------------------------------- native container-executor


def test_native_executor_launches_and_limits(tmp_path):
    from hadoop_tpu.yarn.nm import NativeExecutor
    try:
        ex = NativeExecutor(nofile=64)
    except FileNotFoundError:
        pytest.skip("native toolchain unavailable")
    wd = tmp_path / "c1"
    wd.mkdir()
    import sys
    proc = ex.launch(str(wd), [sys.executable, "-c",
                               "import resource,sys;"
                               "print('hello from container');"
                               "print(resource.getrlimit("
                               "resource.RLIMIT_NOFILE)[0])"], {})
    assert proc.wait(timeout=30) == 0
    out = (wd / "stdout").read_text()
    assert "hello from container" in out
    assert "64" in out            # rlimit applied before user code
    # exit code propagation
    p2 = ex.launch(str(wd), [sys.executable, "-c", "raise SystemExit(7)"],
                   {})
    assert p2.wait(timeout=30) == 7


def test_native_executor_runs_wordcount_job(tmp_path):
    """Whole MR job with every container through the native launcher."""
    from hadoop_tpu.examples.wordcount import make_job
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    conf = Configuration(load_defaults=False)
    conf.set("yarn.nodemanager.container-executor.class", "native")
    with MiniMRYarnCluster(num_nodes=2, conf=conf,
                           base_dir=str(tmp_path / "c")) as cluster:
        from hadoop_tpu.yarn.nm import NativeExecutor
        assert all(isinstance(nm.executor, NativeExecutor)
                   for nm in cluster.yarn.node_agents)
        fs = cluster.get_filesystem()
        fs.mkdirs("/ne-in")
        fs.write_all("/ne-in/x.txt", b"n m n\n")
        job = make_job(cluster.rm_addr, cluster.default_fs, "/ne-in",
                       "/ne-out")
        assert job.wait_for_completion(), job.diagnostics


# ----------------------------------------------------------------- httpfs


def test_httpfs_gateway(tmp_path):
    from hadoop_tpu.dfs.httpfs import HttpFSServer
    from hadoop_tpu.testing.minicluster import MiniDFSCluster
    with MiniDFSCluster(num_datanodes=2,
                        base_dir=str(tmp_path / "dfs")) as cluster:
        conf = Configuration(load_defaults=False)
        srv = HttpFSServer(conf, cluster.default_fs)
        srv.init(conf)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}/webhdfs/v1"
            auth = "user.name=root"
            # unauthenticated → 401
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"{base}/?op=LISTSTATUS")
            assert exc.value.code == 401
            # authenticated as a non-superuser: a write into the
            # root-owned tree is 403 — the gateway doAs-es the caller
            # on the NameNode, not its own process identity
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/gw/nope?op=MKDIRS&user.name=tester",
                    method="PUT"))
            assert exc.value.code == 403
            # mkdirs + create + open + liststatus + delete
            req = urllib.request.Request(
                f"{base}/gw/dir?op=MKDIRS&{auth}", method="PUT")
            assert json.loads(urllib.request.urlopen(req).read())["boolean"]
            req = urllib.request.Request(
                f"{base}/gw/dir/f.bin?op=CREATE&{auth}",
                data=b"payload-123", method="PUT")
            assert urllib.request.urlopen(req).status == 201
            got = urllib.request.urlopen(
                f"{base}/gw/dir/f.bin?op=OPEN&{auth}").read()
            assert got == b"payload-123"
            ls = json.loads(urllib.request.urlopen(
                f"{base}/gw/dir?op=LISTSTATUS&{auth}").read())
            names = [s["pathSuffix"]
                     for s in ls["FileStatuses"]["FileStatus"]]
            assert names == ["f.bin"]
            st = json.loads(urllib.request.urlopen(
                f"{base}/gw/dir/f.bin?op=GETFILESTATUS&{auth}").read())
            assert st["FileStatus"]["length"] == 11
            req = urllib.request.Request(
                f"{base}/gw/dir?op=DELETE&recursive=true&{auth}",
                method="DELETE")
            assert json.loads(urllib.request.urlopen(req).read())["boolean"]
            # the gateway's writes are visible through the native client
            fs = cluster.get_filesystem()
            assert not fs.exists("/gw/dir")
        finally:
            srv.stop()


# ----------------------------------------------------- shared cache (SCM)


def test_shared_cache_upload_use_cleanup(tmp_path):
    from hadoop_tpu.testing.minicluster import MiniDFSCluster
    from hadoop_tpu.yarn.sharedcache import (SharedCacheClient,
                                             SharedCacheManager)
    with MiniDFSCluster(num_datanodes=2,
                        base_dir=str(tmp_path / "dfs")) as cluster:
        conf = Configuration(load_defaults=False)
        conf.set("yarn.sharedcache.cleaner.resource-ttl", "0.3s")
        conf.set("yarn.sharedcache.cleaner.period", "0.2s")
        scm = SharedCacheManager(conf, cluster.default_fs)
        scm.init(conf)
        scm.start()
        try:
            art = tmp_path / "lib.bin"
            art.write_bytes(os.urandom(50_000))
            c = SharedCacheClient(("127.0.0.1", scm.port),
                                  cluster.default_fs, conf)
            # first use uploads
            p1 = c.use(str(art), "app_1")
            fs = cluster.get_filesystem()
            assert fs.exists(p1)
            assert fs.get_file_status(p1).length == 50_000
            # second app hits the cache (no second copy)
            p2 = c.use(str(art), "app_2")
            assert p2 == p1
            assert scm.stats()["entries"] == 1
            # releases + TTL -> cleaner evicts, file removed from DFS
            c.release("app_1")
            c.release("app_2")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if scm.stats()["entries"] == 0 and not fs.exists(p1):
                    break
                time.sleep(0.1)
            assert scm.stats()["entries"] == 0
            assert not fs.exists(p1)
            # re-upload after eviction works
            p3 = c.use(str(art), "app_3")
            assert fs.exists(p3)
            c.close()
        finally:
            scm.stop()


def test_shared_cache_survives_restart(tmp_path):
    from hadoop_tpu.testing.minicluster import MiniDFSCluster
    from hadoop_tpu.yarn.sharedcache import (SharedCacheClient,
                                             SharedCacheManager)
    with MiniDFSCluster(num_datanodes=2,
                        base_dir=str(tmp_path / "dfs")) as cluster:
        conf = Configuration(load_defaults=False)
        scm = SharedCacheManager(conf, cluster.default_fs)
        scm.init(conf)
        scm.start()
        art = tmp_path / "model.bin"
        art.write_bytes(b"weights" * 1000)
        c = SharedCacheClient(("127.0.0.1", scm.port),
                              cluster.default_fs, conf)
        p1 = c.use(str(art), "app_1")
        c.close()
        scm.stop()
        # a fresh SCM recovers the store by scanning
        scm2 = SharedCacheManager(conf, cluster.default_fs)
        scm2.init(conf)
        scm2.start()
        try:
            assert scm2.stats()["entries"] == 1
            c2 = SharedCacheClient(("127.0.0.1", scm2.port),
                                   cluster.default_fs, conf)
            assert c2.use(str(art), "app_9") == p1  # hit, no re-upload
            c2.close()
        finally:
            scm2.stop()


# --------------------------------------------------------- oom-listener


def test_oom_listener_binary(tmp_path):
    """The watcher binary builds and validates its inputs; the v2 polling
    arm is exercised against a synthetic memory.events file (real cgroup
    registration needs root — ref: oom-listener/test's same split)."""
    import subprocess
    import sys
    binary = os.path.join(os.path.dirname(os.path.abspath(
        __import__("hadoop_tpu.native", fromlist=["x"]).__file__)),
        "htpu-oom-listener")
    if not os.path.exists(binary):
        pytest.skip("native toolchain unavailable")
    assert subprocess.run([binary]).returncode == 2          # usage
    assert subprocess.run([binary, "/nonexistent"]).returncode == 2
    # synthetic v2 cgroup dir: oom_kill increments are reported
    cg = tmp_path / "cg"
    cg.mkdir()
    (cg / "memory.events").write_text("low 0\noom 0\noom_kill 0\n")
    proc = subprocess.Popen([binary, str(cg)], stdout=subprocess.PIPE,
                            text=True)
    try:
        time.sleep(0.5)
        (cg / "memory.events").write_text("low 0\noom 1\noom_kill 1\n")
        line = proc.stdout.readline().strip()
        assert line.startswith("oom ")
        # cgroup removal -> clean exit
        (cg / "memory.events").unlink()
        import shutil
        shutil.rmtree(cg)
        assert proc.wait(timeout=10) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


def test_timeline_v2_per_app_collectors(tmp_path):
    """ATSv2-style collector lifecycle on the NM: a collector appears
    with an app's first container, gathers container events, and stops
    when the RM reports the app finished (ref:
    PerNodeTimelineCollectorsAuxService + TimelineCollector)."""
    import time

    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.examples.distributed_shell import submit
    from hadoop_tpu.testing.minicluster import MiniYARNCluster
    from hadoop_tpu.yarn.client import YarnClient

    conf = Configuration(load_defaults=False)
    conf.set("yarn.timeline-service.enabled", "true")
    with MiniYARNCluster(num_nodes=1, conf=conf,
                         base_dir=str(tmp_path)) as cluster:
        nm = cluster.node_agents[0]
        assert nm.timeline is not None
        yc = YarnClient(cluster.rm_addr, Configuration(other=cluster.conf))
        try:
            app_id = submit(cluster.rm_addr, ["bash", "-c", "exit 0"],
                            n=1, conf=Configuration(other=cluster.conf))
            # collector exists while the app runs or shortly after
            deadline = time.monotonic() + 30
            seen_active = False
            while time.monotonic() < deadline:
                if nm.timeline.has_collector(str(app_id)):
                    seen_active = True
                    break
                time.sleep(0.1)
            assert seen_active, "collector never started for the app"
            yc.wait_for_completion(app_id, timeout=60)
            # RM heartbeat reports the finished app → collector stops
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if not nm.timeline.has_collector(str(app_id)):
                    break
                time.sleep(0.2)
            assert not nm.timeline.has_collector(str(app_id)), \
                "collector not stopped after app finished"
            # the store holds this app's container lifecycle events
            events = nm.timeline.store.events("YARN_CONTAINER")
            mine = [e for e in events
                    if e["info"].get("app_id") == str(app_id)]
            assert any(e["event"] == "CREATED" for e in mine)
            assert any(e["event"] == "FINISHED" for e in mine)
        finally:
            yc.close()


# ------------------------------------------------------ timeline store backends

def test_sqlite_timeline_store_contract_parity(tmp_path):
    """The sqlite backend (external-DB analog, ref: ATSv2 HBase / v1
    leveldb timeline stores) answers every query identically to the
    JSONL baseline — same events, same order, same entity fold."""
    from hadoop_tpu.yarn.timeline import SqliteTimelineStore, TimelineStore

    a = TimelineStore(str(tmp_path / "jl"))
    b = SqliteTimelineStore(str(tmp_path / "sq"))
    for st in (a, b):
        st.put_event("YARN_APPLICATION", "app_1", "SUBMITTED",
                     name="etl", user="u")
        st.put_event("YARN_CONTAINER", "c_1", "CREATED", app_id="app_1")
        st.put_event("YARN_CONTAINER", "c_1", "FINISHED",
                     app_id="app_1", mb_seconds=12.5)
        st.put_event("YARN_APPLICATION", "app_1", "FINISHED",
                     state="FINISHED", diagnostics="")

    def strip_ts(recs):
        return [{k: v for k, v in r.items() if k != "ts"} for r in recs]

    assert strip_ts(a.events()) == strip_ts(b.events())
    assert strip_ts(a.events("YARN_CONTAINER")) == \
        strip_ts(b.events("YARN_CONTAINER"))
    assert strip_ts(a.events("YARN_CONTAINER", "c_1")) == \
        strip_ts(b.events("YARN_CONTAINER", "c_1"))
    assert a.events("YARN_CONTAINER", "absent") == \
        b.events("YARN_CONTAINER", "absent") == []
    assert a.entities("YARN_APPLICATION") == b.entities("YARN_APPLICATION")


def test_sqlite_timeline_store_cross_connection_visibility(tmp_path):
    """WAL mode: a second, independently-opened store on the same
    directory (the reader daemon's view) sees the writer's events —
    including ones written after the reader opened."""
    from hadoop_tpu.yarn.timeline import SqliteTimelineStore

    writer = SqliteTimelineStore(str(tmp_path))
    writer.put_event("T", "e1", "ONE")
    reader = SqliteTimelineStore(str(tmp_path))
    assert [r["event"] for r in reader.events("T", "e1")] == ["ONE"]
    writer.put_event("T", "e1", "TWO")  # after the reader opened
    assert [r["event"] for r in reader.events("T", "e1")] == ["ONE", "TWO"]
    reader.close()
    writer.close()


def test_timeline_store_auto_detection(tmp_path):
    """make_store("auto") must open whatever format the writer left on
    disk — a reader pointed at a sqlite store must not silently return
    zero events through a jsonl lens (and vice versa)."""
    from hadoop_tpu.yarn.timeline import (SqliteTimelineStore,
                                          TimelineStore, make_store)

    sq_dir, jl_dir, empty = (str(tmp_path / d) for d in ("s", "j", "e"))
    SqliteTimelineStore(sq_dir).put_event("T", "x", "E")
    TimelineStore(jl_dir).put_event("T", "x", "E")
    assert isinstance(make_store(sq_dir, "auto"), SqliteTimelineStore)
    assert isinstance(make_store(jl_dir, "auto"), TimelineStore)
    assert isinstance(make_store(empty, "auto"), TimelineStore)
    assert [r["event"] for r in make_store(sq_dir, "auto").events()] == ["E"]
    with pytest.raises(ValueError):
        make_store(str(tmp_path / "z"), "leveldb")


def test_reader_opened_before_writer_binds_late(tmp_path):
    """A reader brought up against a still-empty store directory must
    not bind the jsonl default forever: once the writer creates the
    sqlite store, the reader's next query sees it."""
    from hadoop_tpu.yarn.timeline import SqliteTimelineStore, _AutoStoreView

    view = _AutoStoreView(str(tmp_path))   # directory exists, no store yet
    assert view.events() == []
    writer = SqliteTimelineStore(str(tmp_path))
    writer.put_event("T", "x", "E")
    assert [r["event"] for r in view.events()] == ["E"]
    view.close()
    writer.close()
