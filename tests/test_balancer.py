"""Balancer, Mover, decommission completion, maintenance mode.

Mirrors the reference tests (ref: hadoop-hdfs TestBalancer.java,
TestMover.java, TestDecommission.java, TestMaintenanceState.java).
"""

import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.balancer import Balancer, Mover
from hadoop_tpu.dfs.protocol.records import DatanodeInfo
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf


def _conf():
    conf = fast_conf()
    conf.set("dfs.blocksize", str(64 * 1024))
    conf.set("dfs.replication", "1")
    # Small fixed capacity so utilization deltas are visible (all mini-DNs
    # share one host volume otherwise).
    conf.set("dfs.datanode.capacity", "2m")
    return conf


def test_balancer_spreads_blocks(tmp_path):
    """Start with 2 DNs, load them, add 2 empty DNs; the balancer should
    move blocks onto the newcomers."""
    with MiniDFSCluster(num_datanodes=2, conf=_conf(),
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        for i in range(6):
            with fs.create(f"/load/f{i}") as out:
                out.write(os.urandom(64 * 1024))
        # Two empty newcomers.
        cluster.num_datanodes = 4
        cluster._start_datanode(2)
        cluster._start_datanode(3)
        cluster.wait_active()
        # The balancer plans from heartbeat-reported usage; writes now
        # complete faster than the next heartbeat (immediate IBRs), so
        # wait until the loaded DNs' non-zero dfs_used has actually
        # reached the NN before asking for a plan.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            dm = cluster.namenode.fsn.bm.dn_manager
            loaded = [dm.get(cluster.datanodes[i].uuid) for i in (0, 1)]
            # replication may be 1: it's enough that SOME loaded DN's
            # non-zero usage has reached the NN via heartbeat
            if any(n is not None and n.dfs_used > 0 for n in loaded):
                break
            time.sleep(0.1)
        bal = Balancer(cluster.nn_addr, cluster.conf, threshold=0.02)
        try:
            stats = bal.run()
        finally:
            bal.close()
        assert stats["blocks_moved"] > 0
        # The newcomers now hold replicas.
        deadline = time.monotonic() + 20
        ok = False
        while time.monotonic() < deadline and not ok:
            fsn = cluster.namenode.fsn
            new_nodes = [cluster.datanodes[2].uuid, cluster.datanodes[3].uuid]
            held = sum(len(fsn.bm.dn_manager.get(u).blocks)
                       for u in new_nodes)
            ok = held > 0
            time.sleep(0.2)
        assert ok, "no blocks landed on the new datanodes"
        # Data still fully readable after moves + excess pruning.
        for i in range(6):
            with fs.open(f"/load/f{i}") as f:
                assert len(f.read()) == 64 * 1024


def test_mover_satisfies_cold_policy(tmp_path):
    with MiniDFSCluster(num_datanodes=3, conf=_conf(),
                        base_dir=str(tmp_path),
                        storage_types=["DISK", "DISK", "ARCHIVE"]
                        ) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fs.mkdirs("/archive")
        with fs.create("/archive/old.dat") as out:
            out.write(os.urandom(100 * 1024))
        fs.set_storage_policy("/archive", "COLD")
        mover = Mover(cluster.nn_addr, cluster.conf)
        try:
            stats = mover.run("/archive")
        finally:
            mover.close()
        assert stats["replicas_moved"] > 0
        # Replicas now live on the ARCHIVE node only.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            info = fs.client.get_block_locations("/archive/old.dat")
            types = {DatanodeInfo.from_wire(d).storage_type
                     for b in info["blocks"] for d in b["locs"]}
            if types == {"ARCHIVE"}:
                break
            time.sleep(0.2)
        assert types == {"ARCHIVE"}, types
        with fs.open("/archive/old.dat") as f:
            assert len(f.read()) == 100 * 1024


def test_decommission_completes_and_data_survives(tmp_path):
    conf = fast_conf()
    conf.set("dfs.blocksize", str(64 * 1024))
    conf.set("dfs.replication", "2")
    with MiniDFSCluster(num_datanodes=4, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        payload = os.urandom(150 * 1024)
        with fs.create("/dc/data") as out:
            out.write(payload)
        victim = cluster.datanodes[0]
        fs.client.nn.decommission_datanode(victim.uuid)
        fsn = cluster.namenode.fsn
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            node = fsn.bm.dn_manager.get(victim.uuid)
            if node.state == DatanodeInfo.STATE_DECOMMISSIONED:
                break
            time.sleep(0.2)
        assert node.state == DatanodeInfo.STATE_DECOMMISSIONED, node.state
        # Safe to stop it now.
        cluster.kill_datanode(0)
        with fs.open("/dc/data") as f:
            assert f.read() == payload


def test_maintenance_mode_roundtrip(tmp_path):
    conf = fast_conf()
    conf.set("dfs.replication", "2")
    with MiniDFSCluster(num_datanodes=3, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        with fs.create("/mm/f") as out:
            out.write(b"z" * 50_000)
        victim = cluster.datanodes[1]
        fs.client.nn.start_maintenance(victim.uuid)
        fsn = cluster.namenode.fsn
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            node = fsn.bm.dn_manager.get(victim.uuid)
            if node.state == DatanodeInfo.STATE_IN_MAINTENANCE:
                break
            time.sleep(0.2)
        assert node.state == DatanodeInfo.STATE_IN_MAINTENANCE
        fs.client.nn.stop_maintenance(victim.uuid)
        assert fsn.bm.dn_manager.get(victim.uuid).state == \
            DatanodeInfo.STATE_LIVE
        with fs.open("/mm/f") as f:
            assert f.read() == b"z" * 50_000


def test_sps_satisfies_policy_inside_namenode(tmp_path):
    """satisfyStoragePolicy(path) migrates replicas without any external
    mover process (ref: TestStoragePolicySatisfier.java — the in-NN SPS
    moves misplaced replicas via heartbeat transfer commands)."""
    with MiniDFSCluster(num_datanodes=3, conf=_conf(),
                        base_dir=str(tmp_path),
                        storage_types=["DISK", "DISK", "ARCHIVE"]
                        ) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fs.mkdirs("/cold")
        with fs.create("/cold/old.dat") as out:
            out.write(os.urandom(100 * 1024))
        fs.set_storage_policy("/cold", "COLD")
        # Marker xattr set synchronously by the RPC.
        assert fs.client.nn.satisfy_storage_policy("/cold")
        nn = cluster.namenode
        assert nn.fsn.get_xattrs("/cold").get("system.hdfs.sps") == b"1"
        # The redundancy-monitor sweep drives the moves to completion.
        deadline = time.monotonic() + 20
        types = set()
        while time.monotonic() < deadline:
            info = fs.client.get_block_locations("/cold/old.dat")
            types = {DatanodeInfo.from_wire(d).storage_type
                     for b in info["blocks"] for d in b["locs"]}
            if types == {"ARCHIVE"} and \
                    "system.hdfs.sps" not in nn.fsn.get_xattrs("/cold"):
                break
            time.sleep(0.2)
        assert types == {"ARCHIVE"}, types
        # Marker removed once satisfied — restart discovers nothing.
        assert "system.hdfs.sps" not in nn.fsn.get_xattrs("/cold")
        with fs.open("/cold/old.dat") as f:
            assert len(f.read()) == 100 * 1024


def test_diskbalancer_evens_volumes(tmp_path):
    """Intra-node rebalancing: skew replicas onto one volume, then
    DiskBalancer.plan/execute spreads them within threshold (ref:
    hadoop-hdfs server/diskbalancer TestDiskBalancer.java)."""
    from hadoop_tpu.dfs.datanode.volumes import DiskBalancer, VolumeSet

    conf = fast_conf()
    conf.set("dfs.blocksize", str(64 * 1024))
    conf.set("dfs.replication", "1")
    conf.set("dfs.datanode.volumes", "3")
    conf.set("dfs.datanode.capacity", "6m")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        dn = cluster.datanodes[0]
        assert isinstance(dn.store, VolumeSet)
        fs = cluster.get_filesystem()
        with fs.create("/skew.dat") as out:
            out.write(os.urandom(512 * 1024))  # 8 blocks
        # Skew: force everything onto volume 0.
        vs = dn.store
        for b in vs.all_finalized():
            src = vs._vol_of(b.block_id)
            if src is not vs.volumes[0]:
                # move directly via the mover primitive
                assert vs.move_replica(b.block_id, 0)
        per_vol = [len(v.all_finalized()) for v in vs.volumes]
        assert per_vol[1] == per_vol[2] == 0, per_vol

        db = DiskBalancer(vs)
        rpt = db.report()
        assert max(s["density"] for s in rpt["volumes"]) > 0.05
        moves = db.plan(threshold=0.02)
        assert moves
        result = db.execute(moves)
        assert result["failed"] == 0 and result["moved"] == len(moves)
        per_vol = [len(v.all_finalized()) for v in vs.volumes]
        assert all(n > 0 for n in per_vol), per_vol
        # Every byte still readable through the normal DFS read path.
        with fs.open("/skew.dat") as f:
            assert len(f.read()) == 512 * 1024


def test_balancer_runs_with_block_tokens_enabled(tmp_path):
    """On a token-secured cluster the balancer mints its own access
    tokens from NN-exported master keys (ref: NamenodeProtocol
    .getBlockKeys feeding the Balancer's KeyManager) — a regression
    here crashed at construction because the RPC was only registered on
    DatanodeProtocol (review finding)."""
    conf = _conf()
    conf.set("dfs.block.access.token.enable", "true")
    with MiniDFSCluster(num_datanodes=2, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        for i in range(6):
            with fs.create(f"/load/f{i}") as out:
                out.write(os.urandom(64 * 1024))
        cluster.num_datanodes = 4
        cluster._start_datanode(2)
        cluster._start_datanode(3)
        cluster.wait_active()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            dm = cluster.namenode.fsn.bm.dn_manager
            loaded = [dm.get(cluster.datanodes[i].uuid) for i in (0, 1)]
            if any(n is not None and n.dfs_used > 0 for n in loaded):
                break
            time.sleep(0.1)
        bal = Balancer(cluster.nn_addr, cluster.conf, threshold=0.02)
        try:
            stats = bal.run()
        finally:
            bal.close()
        # moves happened THROUGH the tokened data plane
        assert stats["blocks_moved"] > 0
        for i in range(6):
            with fs.open(f"/load/f{i}") as f:
                assert len(f.read()) == 64 * 1024
