"""Balancer, Mover, decommission completion, maintenance mode.

Mirrors the reference tests (ref: hadoop-hdfs TestBalancer.java,
TestMover.java, TestDecommission.java, TestMaintenanceState.java).
"""

import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.balancer import Balancer, Mover
from hadoop_tpu.dfs.protocol.records import DatanodeInfo
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf


def _conf():
    conf = fast_conf()
    conf.set("dfs.blocksize", str(64 * 1024))
    conf.set("dfs.replication", "1")
    # Small fixed capacity so utilization deltas are visible (all mini-DNs
    # share one host volume otherwise).
    conf.set("dfs.datanode.capacity", "2m")
    return conf


def test_balancer_spreads_blocks(tmp_path):
    """Start with 2 DNs, load them, add 2 empty DNs; the balancer should
    move blocks onto the newcomers."""
    with MiniDFSCluster(num_datanodes=2, conf=_conf(),
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        for i in range(6):
            with fs.create(f"/load/f{i}") as out:
                out.write(os.urandom(64 * 1024))
        # Two empty newcomers.
        cluster.num_datanodes = 4
        cluster._start_datanode(2)
        cluster._start_datanode(3)
        cluster.wait_active()
        bal = Balancer(cluster.nn_addr, cluster.conf, threshold=0.02)
        try:
            stats = bal.run()
        finally:
            bal.close()
        assert stats["blocks_moved"] > 0
        # The newcomers now hold replicas.
        deadline = time.monotonic() + 20
        ok = False
        while time.monotonic() < deadline and not ok:
            fsn = cluster.namenode.fsn
            new_nodes = [cluster.datanodes[2].uuid, cluster.datanodes[3].uuid]
            held = sum(len(fsn.bm.dn_manager.get(u).blocks)
                       for u in new_nodes)
            ok = held > 0
            time.sleep(0.2)
        assert ok, "no blocks landed on the new datanodes"
        # Data still fully readable after moves + excess pruning.
        for i in range(6):
            with fs.open(f"/load/f{i}") as f:
                assert len(f.read()) == 64 * 1024


def test_mover_satisfies_cold_policy(tmp_path):
    with MiniDFSCluster(num_datanodes=3, conf=_conf(),
                        base_dir=str(tmp_path),
                        storage_types=["DISK", "DISK", "ARCHIVE"]
                        ) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fs.mkdirs("/archive")
        with fs.create("/archive/old.dat") as out:
            out.write(os.urandom(100 * 1024))
        fs.set_storage_policy("/archive", "COLD")
        mover = Mover(cluster.nn_addr, cluster.conf)
        try:
            stats = mover.run("/archive")
        finally:
            mover.close()
        assert stats["replicas_moved"] > 0
        # Replicas now live on the ARCHIVE node only.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            info = fs.client.get_block_locations("/archive/old.dat")
            types = {DatanodeInfo.from_wire(d).storage_type
                     for b in info["blocks"] for d in b["locs"]}
            if types == {"ARCHIVE"}:
                break
            time.sleep(0.2)
        assert types == {"ARCHIVE"}, types
        with fs.open("/archive/old.dat") as f:
            assert len(f.read()) == 100 * 1024


def test_decommission_completes_and_data_survives(tmp_path):
    conf = fast_conf()
    conf.set("dfs.blocksize", str(64 * 1024))
    conf.set("dfs.replication", "2")
    with MiniDFSCluster(num_datanodes=4, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        payload = os.urandom(150 * 1024)
        with fs.create("/dc/data") as out:
            out.write(payload)
        victim = cluster.datanodes[0]
        fs.client.nn.decommission_datanode(victim.uuid)
        fsn = cluster.namenode.fsn
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            node = fsn.bm.dn_manager.get(victim.uuid)
            if node.state == DatanodeInfo.STATE_DECOMMISSIONED:
                break
            time.sleep(0.2)
        assert node.state == DatanodeInfo.STATE_DECOMMISSIONED, node.state
        # Safe to stop it now.
        cluster.kill_datanode(0)
        with fs.open("/dc/data") as f:
            assert f.read() == payload


def test_maintenance_mode_roundtrip(tmp_path):
    conf = fast_conf()
    conf.set("dfs.replication", "2")
    with MiniDFSCluster(num_datanodes=3, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        with fs.create("/mm/f") as out:
            out.write(b"z" * 50_000)
        victim = cluster.datanodes[1]
        fs.client.nn.start_maintenance(victim.uuid)
        fsn = cluster.namenode.fsn
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            node = fsn.bm.dn_manager.get(victim.uuid)
            if node.state == DatanodeInfo.STATE_IN_MAINTENANCE:
                break
            time.sleep(0.2)
        assert node.state == DatanodeInfo.STATE_IN_MAINTENANCE
        fs.client.nn.stop_maintenance(victim.uuid)
        assert fsn.bm.dn_manager.get(victim.uuid).state == \
            DatanodeInfo.STATE_LIVE
        with fs.open("/mm/f") as f:
            assert f.read() == b"z" * 50_000
