"""Centralized cache: directives pin blocks in DN memory.
Ref: namenode/CacheManager.java + CacheReplicationMonitor.java +
fsdataset/impl/FsDatasetCache.java; LocatedBlock cachedLocations."""

import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.testing.minicluster import MiniDFSCluster


@pytest.fixture(scope="module")
def cluster():
    conf = Configuration(load_defaults=False)
    conf.set("dfs.namenode.redundancy.interval", "0.2s")
    with MiniDFSCluster(num_datanodes=3, conf=conf) as c:
        yield c


@pytest.fixture(scope="module")
def fs(cluster):
    return cluster.get_filesystem()


def _cached_uuids(fs, path):
    locs = fs.client.get_block_locations(path)
    return [lb.get("cach", []) for lb in locs["blocks"]]


def test_directive_pins_and_serves_from_memory(cluster, fs):
    data = os.urandom(400_000)
    fs.write_all("/hot.bin", data)
    did = fs.add_cache_directive("/hot.bin")
    assert did >= 1
    assert fs.list_cache_directives() == {did: "/hot.bin"}
    # the cache monitor + DN round trip pins a replica
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        cached = _cached_uuids(fs, "/hot.bin")
        if cached and all(c for c in cached):
            break
        time.sleep(0.2)
    assert cached and all(len(c) == 1 for c in cached), cached
    # data still reads correctly (served from the pinned copy when the
    # reader hits the caching node)
    assert fs.read_all("/hot.bin") == data
    # the caching DN really holds it in memory
    cached_uuid = cached[0][0]
    dn = next(d for d in cluster.datanodes
              if d is not None and d.uuid == cached_uuid)
    assert dn.store.cached_ids()


def test_remove_directive_uncaches(cluster, fs):
    fs.write_all("/warm.bin", os.urandom(100_000))
    did = fs.add_cache_directive("/warm.bin")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if all(_cached_uuids(fs, "/warm.bin")):
            break
        time.sleep(0.2)
    assert fs.remove_cache_directive(did)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if not any(any(c) for c in _cached_uuids(fs, "/warm.bin")):
            break
        time.sleep(0.2)
    assert not any(any(c) for c in _cached_uuids(fs, "/warm.bin"))
    assert not fs.remove_cache_directive(did)  # already gone


def test_directives_survive_restart(cluster, fs):
    fs.write_all("/pin.bin", b"z" * 50_000)
    did = fs.add_cache_directive("/pin.bin")
    cluster.restart_namenode()
    fs2 = cluster.get_filesystem()
    assert did in fs2.list_cache_directives()
    assert fs2.list_cache_directives()[did] == "/pin.bin"
