"""CLI: fs shell, dfsadmin, fsck against a live minicluster.

Mirrors the reference CLI tests (ref: hadoop-hdfs TestDFSShell.java,
TestDFSAdmin.java, TestFsck.java — driven through the command classes
with captured output rather than forked processes).
"""

import io
import os

import pytest

from hadoop_tpu.cli.dfsadmin import DFSAdmin, Fsck
from hadoop_tpu.cli.main import main, parse_generic_options
from hadoop_tpu.cli.shell import FsShell
from hadoop_tpu.conf import Configuration
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf


@pytest.fixture(scope="module")
def cluster():
    with MiniDFSCluster(num_datanodes=3) as c:
        c.wait_active()
        yield c


@pytest.fixture
def conf(cluster):
    conf = fast_conf(cluster.conf)
    conf.set("fs.defaultFS",
             f"htpu://127.0.0.1:{cluster.namenode.port}")
    return conf


@pytest.fixture
def shell(conf):
    out = io.StringIO()
    sh = FsShell(conf, out=out)
    sh.captured = out  # type: ignore[attr-defined]
    yield sh
    sh.close()


def test_mkdir_put_ls_cat_get(shell, tmp_path):
    local = tmp_path / "in.txt"
    local.write_bytes(b"hello cli world\n")
    assert shell.run(["-mkdir", "-p", "/clitest"]) == 0
    assert shell.run(["-put", str(local), "/clitest/in.txt"]) == 0
    assert shell.run(["-ls", "/clitest"]) == 0
    listing = shell.captured.getvalue()
    assert "/clitest/in.txt" in listing and "Found 1 items" in listing
    shell.captured.truncate(0), shell.captured.seek(0)
    assert shell.run(["-cat", "/clitest/in.txt"]) == 0
    assert shell.captured.getvalue() == "hello cli world\n"
    dest = tmp_path / "out.txt"
    assert shell.run(["-get", "/clitest/in.txt", str(dest)]) == 0
    assert dest.read_bytes() == b"hello cli world\n"


def test_rm_with_trash_and_skiptrash(shell, conf):
    conf.set("fs.trash.interval", "1h")
    shell.run(["-mkdir", "/trashy"])
    shell.run(["-touchz", "/trashy/a.txt"])
    assert shell.run(["-rm", "/trashy/a.txt"]) == 0
    assert "to trash" in shell.captured.getvalue()
    assert shell.run(["-test", "-e",
                      "/user/root/.Trash/Current/trashy/a.txt"]) in (0, 1)
    shell.run(["-touchz", "/trashy/b.txt"])
    assert shell.run(["-rm", "-skipTrash", "/trashy/b.txt"]) == 0
    assert shell.run(["-test", "-e", "/trashy/b.txt"]) == 1


def test_mv_cp_count_du_setrep(shell):
    shell.run(["-mkdir", "/mvcp"])
    shell.run(["-touchz", "/mvcp/one"])
    assert shell.run(["-cp", "/mvcp/one", "/mvcp/two"]) == 0
    assert shell.run(["-mv", "/mvcp/two", "/mvcp/three"]) == 0
    assert shell.run(["-test", "-e", "/mvcp/three"]) == 0
    assert shell.run(["-count", "/mvcp"]) == 0
    assert shell.run(["-du", "/mvcp"]) == 0
    assert shell.run(["-setrep", "2", "/mvcp/one"]) == 0


def test_xattr_and_snapshot_commands(shell):
    shell.run(["-mkdir", "/cliattr"])
    assert shell.run(["-setfattr", "-n", "user.k", "-v", "v1",
                      "/cliattr"]) == 0
    shell.captured.truncate(0), shell.captured.seek(0)
    assert shell.run(["-getfattr", "/cliattr"]) == 0
    assert 'user.k="v1"' in shell.captured.getvalue()
    assert shell.run(["-setfacl", "-m", "user:bob:rw-", "/cliattr"]) == 0
    shell.captured.truncate(0), shell.captured.seek(0)
    assert shell.run(["-getfacl", "/cliattr"]) == 0
    assert "user:bob:rw-" in shell.captured.getvalue()


def test_dfsadmin_report_safemode_quota(conf):
    out = io.StringIO()
    admin = DFSAdmin(conf, out=out)
    try:
        assert admin.run(["-report"]) == 0
        text = out.getvalue()
        assert "Datanodes (3)" in text
        assert admin.run(["-safemode", "get"]) == 0
        assert "Safe mode is OFF" in out.getvalue()
        assert admin.run(["-setQuota", "100", "/"]) == 0
        assert admin.run(["-clrQuota", "/"]) == 0
        assert admin.run(["-listECPolicies"]) == 0
        assert "RS-6-3-64k" in out.getvalue()
    finally:
        admin.close()


def test_fsck_healthy_and_missing(cluster, conf):
    fs = cluster.get_filesystem()
    with fs.create("/fsck/good.bin") as f:
        f.write(os.urandom(100_000))
    out = io.StringIO()
    fsck = Fsck(conf, out=out)
    try:
        assert fsck.run(["/fsck"]) == 0
        assert "Status: HEALTHY" in out.getvalue()
    finally:
        fsck.close()


def test_generic_options_and_version(capsys):
    conf = Configuration(load_defaults=False)
    rest = parse_generic_options(
        conf, ["-D", "a.b=c", "-Dx.y=z", "-fs", "htpu://h:1", "-ls", "/"])
    assert conf.get("a.b") == "c"
    assert conf.get("x.y") == "z"
    assert conf.get("fs.defaultFS") == "htpu://h:1"
    assert rest == ["-ls", "/"]
    assert main(["version"]) == 0
    assert "hadoop-tpu" in capsys.readouterr().out


def test_cli_dispatches_tools(capsys):
    from hadoop_tpu.cli.main import main
    assert main(["help"]) == 0
    assert "distcp" in capsys.readouterr().out
    assert main(["sls", "--nodes", "5", "--apps", "2",
                 "--containers", "3", "--ticks", "100"]) == 0
    out = capsys.readouterr().out
    import json
    assert json.loads(out.strip().splitlines()[-1])["unfinished_apps"] == 0
    assert main(["nope"]) == 1


def test_cli_job_control(tmp_path, capsys):
    from hadoop_tpu.cli.main import main
    from hadoop_tpu.examples.wordcount import make_job
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    with MiniMRYarnCluster(num_nodes=2,
                           base_dir=str(tmp_path / "c")) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/ji")
        fs.write_all("/ji/x.txt", b"a b\n")
        job = make_job(cluster.rm_addr, cluster.default_fs, "/ji", "/jo")
        assert job.wait_for_completion()
        rm = f"127.0.0.1:{cluster.yarn.rm.port}"
        assert main(["job", "-Dyarn.resourcemanager.address=" + rm,
                     "-list"]) == 0
        out = capsys.readouterr().out
        assert "FINISHED" in out
        app_id = out.split()[0]
        assert main(["job", "-Dyarn.resourcemanager.address=" + rm,
                     "-status", app_id]) == 0
        assert "FINISHED" in capsys.readouterr().out
