"""Configuration tests (behavioral parity targets:
ref hadoop-common/src/test/java/org/apache/hadoop/conf/TestConfiguration.java)."""

import pytest

from hadoop_tpu.conf import Configuration, ConfigRegistry
from hadoop_tpu.conf.configuration import DeprecationDelta


def test_basic_get_set():
    c = Configuration(load_defaults=False)
    c.set("a.b", "hello")
    assert c.get("a.b") == "hello"
    assert c.get("missing") is None
    assert c.get("missing", "dflt") == "dflt"
    assert "a.b" in c and "missing" not in c


def test_typed_getters():
    c = Configuration(load_defaults=False)
    c.set("i", "42")
    c.set("hex", "0x10")
    c.set("f", "2.5")
    c.set("b1", "true")
    c.set("b2", "off")
    c.set("sz", "64m")
    c.set("t1", "30s")
    c.set("t2", "5m")
    c.set("t3", "100ms")
    c.set("lst", "a, b ,c")
    c.set("rng", "2000-2002,2010")
    assert c.get_int("i") == 42
    assert c.get_int("hex") == 16
    assert c.get_int("nope", 7) == 7
    assert c.get_float("f") == 2.5
    assert c.get_bool("b1") is True
    assert c.get_bool("b2") is False
    assert c.get_size_bytes("sz") == 64 * 1024 * 1024
    assert c.get_time_seconds("t1") == 30.0
    assert c.get_time_seconds("t2") == 300.0
    assert abs(c.get_time_seconds("t3") - 0.1) < 1e-9
    assert c.get_list("lst") == ["a", "b", "c"]
    assert c.get_range("rng") == [2000, 2001, 2002, 2010]


def test_variable_expansion():
    c = Configuration(load_defaults=False)
    c.set("base.dir", "/data")
    c.set("log.dir", "${base.dir}/logs")
    c.set("deep", "${log.dir}/app")
    assert c.get("log.dir") == "/data/logs"
    assert c.get("deep") == "/data/logs/app"
    c.set("unresolved", "${nope}/x")
    assert c.get("unresolved") == "${nope}/x"


def test_env_expansion(monkeypatch):
    monkeypatch.setenv("HTPU_TEST_HOME", "/opt/htpu")
    c = Configuration(load_defaults=False)
    c.set("home", "${env.HTPU_TEST_HOME}/bin")
    assert c.get("home") == "/opt/htpu/bin"


def test_self_recursion_bounded():
    c = Configuration(load_defaults=False)
    c.set("x", "${x}")
    assert c.get("x") == "${x}"  # bounded at MAX_SUBST_DEPTH, no hang


def test_deprecation():
    ConfigRegistry.add_deprecations([DeprecationDelta("old.key", ["new.key"])])
    c = Configuration(load_defaults=False)
    c.set("old.key", "v1")  # writes through to new.key
    assert c.get("new.key") == "v1"
    assert c.get("old.key") == "v1"
    c.set("new.key", "v2")
    assert c.get("old.key") == "v2"


def test_final_properties(tmp_path):
    site = tmp_path / "site.conf"
    site.write_text("locked.key = base !final\nfree.key = f1\n")
    c = Configuration(load_defaults=False)
    c.add_resource(str(site))
    assert c.get("locked.key") == "base"
    override = tmp_path / "override.conf"
    override.write_text("locked.key = hacked\nfree.key = f2\n")
    c.add_resource(str(override))
    assert c.get("locked.key") == "base"  # final wins
    assert c.get("free.key") == "f2"


def test_flat_and_json_resources(tmp_path):
    flat = tmp_path / "a.conf"
    flat.write_text("# comment\nk1 = v1\nk2=  v2\n")
    js = tmp_path / "b.json"
    js.write_text('{"k3": "v3", "k4": 4}')
    c = Configuration(load_defaults=False)
    c.add_resource(str(flat))
    c.add_resource(str(js))
    assert c.get("k1") == "v1"
    assert c.get("k2") == "v2"
    assert c.get("k3") == "v3"
    assert c.get_int("k4") == 4
    assert c.get_property_source("k1") == str(flat)


def test_default_resources():
    ConfigRegistry.add_default_resource({"framework.default": "yes"})
    c = Configuration()
    assert c.get("framework.default") == "yes"


def test_prefix_and_copy():
    c = Configuration(load_defaults=False)
    c.set("dfs.block.size", "128m")
    c.set("dfs.replication", "3")
    c.set("yarn.memory", "8g")
    assert c.get_by_prefix("dfs.") == {"block.size": "128m", "replication": "3"}
    c2 = c.copy()
    c2.set("dfs.replication", "5")
    assert c.get("dfs.replication") == "3"


def test_reconfigure_listener():
    seen = []
    c = Configuration(load_defaults=False)
    c.set("k", "v0")
    c.register_reconfigure_listener(lambda k, old, new: seen.append((k, old, new)))
    c.set("k", "v1")
    assert seen == [("k", "v0", "v1")]


def test_get_class():
    c = Configuration(load_defaults=False)
    c.set("impl", "hadoop_tpu.conf.configuration.Configuration")
    assert c.get_class("impl") is Configuration


def test_get_int_garbage_is_loud():
    c = Configuration(load_defaults=False)
    c.set("i", "not-a-number")
    with pytest.raises(ValueError) as exc:
        c.get_int("i")
    assert "i" in str(exc.value) and "not-a-number" in str(exc.value)


def test_get_bool_garbage_is_loud():
    c = Configuration(load_defaults=False)
    c.set("b", "yeah")
    with pytest.raises(ValueError) as exc:
        c.get_bool("b")
    assert "b" in str(exc.value) and "yeah" in str(exc.value)


def test_get_bool_accepted_literals():
    c = Configuration(load_defaults=False)
    for raw in ("true", "YES", "On", "1"):
        c.set("b", raw)
        assert c.get_bool("b") is True, raw
    for raw in ("false", "NO", "Off", "0"):
        c.set("b", raw)
        assert c.get_bool("b") is False, raw
    c.set("b", "")
    assert c.get_bool("b", True) is True  # empty = unset, default wins


def test_strict_mode_warns_on_unknown_key(caplog):
    import logging
    c = Configuration(load_defaults=False)
    c.set("conf.strict.keys", "true")
    with caplog.at_level(logging.WARNING, logger="hadoop_tpu.conf"):
        c.set("dfs.blocksize.typo-key", "1")  # not in the registry
        c.set("dfs.blocksize", "64m")         # registered: silent
        c.set("fs.htpu.endpoint", "x")        # pattern fs.*.endpoint: silent
        c.set("dfs.blocksize.typo-key", "2")  # warn-once per key
    warned = [r for r in caplog.records if "registry" in r.getMessage()]
    assert len(warned) == 1
    assert "dfs.blocksize.typo-key" in warned[0].getMessage()


def test_strict_mode_off_is_silent(caplog):
    import logging
    c = Configuration(load_defaults=False)
    with caplog.at_level(logging.WARNING, logger="hadoop_tpu.conf"):
        c.set("total.garbage.key", "1")
    assert [r for r in caplog.records if "registry" in r.getMessage()] == []


def test_shipped_deprecations_survive_registry_reset():
    """conftest resets ConfigRegistry per test; the shipped deltas
    (data.dirs -> data.dir, store-dir -> store.dir) must come back."""
    ConfigRegistry.reset_for_tests()
    c = Configuration(load_defaults=False)
    c.set("dfs.datanode.data.dirs", "/a,/b")
    assert c.get("dfs.datanode.data.dir") == "/a,/b"
    c.set("yarn.timeline-service.store-dir", "/tl")
    assert c.get("yarn.timeline-service.store.dir") == "/tl"
