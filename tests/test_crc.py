"""CRC32C / DataChecksum tests (parity target: ref
hadoop-common/src/test/java/org/apache/hadoop/util/TestDataChecksum.java)."""

import struct

import pytest

from hadoop_tpu.util.crc import ChecksumError, DataChecksum, crc32c


def test_known_vectors():
    # RFC 3720 (iSCSI) CRC32C test vectors.
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA
    assert crc32c(bytes([0xFF] * 32)) == 0x62A8AB43


def test_incremental():
    data = b"hello world, this is a longer buffer" * 10
    whole = crc32c(data)
    part = crc32c(data[10:], crc32c(data[:10]))
    assert whole == part


def test_chunked_checksums_roundtrip():
    cs = DataChecksum(bytes_per_chunk=512)
    data = bytes(range(256)) * 10  # 2560 bytes = 5 chunks
    sums = cs.checksums_for(data)
    assert len(sums) == 5 * 4
    cs.verify(data, sums)  # no raise


def test_corruption_detected_with_position():
    cs = DataChecksum(bytes_per_chunk=512)
    data = bytearray(b"\xab" * 2048)
    sums = cs.checksums_for(bytes(data))
    data[1030] ^= 0xFF  # corrupt chunk 2
    with pytest.raises(ChecksumError) as ei:
        cs.verify(bytes(data), sums, base_pos=0)
    assert ei.value.pos == 1024


def test_header_roundtrip():
    cs = DataChecksum(bytes_per_chunk=4096)
    hdr = cs.header()
    assert len(hdr) == DataChecksum.HEADER_LEN
    cs2 = DataChecksum.from_header(hdr)
    assert cs2.bytes_per_chunk == 4096
    assert cs2.type == DataChecksum.TYPE_CRC32C


def test_null_checksum():
    cs = DataChecksum(bytes_per_chunk=512, ctype=DataChecksum.TYPE_NULL)
    assert cs.checksums_for(b"data") == b""
    cs.verify(b"data", b"")  # no raise


def test_partial_last_chunk():
    cs = DataChecksum(bytes_per_chunk=512)
    data = b"z" * 700  # 1 full + 1 partial chunk
    sums = cs.checksums_for(data)
    assert len(sums) == 8
    cs.verify(data, sums)
    bad = bytearray(data)
    bad[600] ^= 1
    with pytest.raises(ChecksumError):
        cs.verify(bytes(bad), sums)
