"""Device-resident shuffle: the lax.all_to_all exchange (SURVEY §5.8).

Runs on the forced 8-device CPU mesh (conftest). Parity oracle is the
HOST shuffle semantics: same partition function, same per-partition
record multisets, same grouped totals — computed in plain numpy.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hadoop_tpu.mapreduce.device_shuffle import (device_group_reduce,
                                                 device_shuffle,
                                                 device_terasort,
                                                 hash_partitioner)


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8])
    assert devs.size == 8, "conftest must force 8 CPU devices"
    return Mesh(devs, ("x",))


def _shard(mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P("x")))


def _host_partition(keys, n):
    """The same hash the device uses, in numpy — the host-shuffle oracle."""
    h = keys.astype(np.uint32) * np.uint32(0x9E3779B1)
    h ^= h >> np.uint32(15)
    return (h % np.uint32(n)).astype(np.int32)


def test_device_shuffle_parity_with_host_partitioning(mesh):
    """Every record lands on the device its hash names, nothing lost,
    nothing invented — the ShuffleHandler/Fetcher contract."""
    rng = np.random.default_rng(7)
    n = 8 * 512
    keys = rng.integers(0, 10_000, size=n).astype(np.int32)
    vals = rng.integers(0, 100, size=n).astype(np.int32)

    res = device_shuffle(mesh, "x", _shard(mesh, jnp.asarray(keys)),
                         _shard(mesh, jnp.asarray(vals)),
                         capacity_factor=3.0)
    assert int(res.dropped.sum()) == 0

    out_k = np.asarray(res.keys).reshape(8, -1)
    out_v = np.asarray(res.values).reshape(8, -1)
    out_m = np.asarray(res.valid).reshape(8, -1)

    want_dest = _host_partition(keys, 8)
    for d in range(8):
        got = sorted(zip(out_k[d][out_m[d]].tolist(),
                         out_v[d][out_m[d]].tolist()))
        want = sorted(zip(keys[want_dest == d].tolist(),
                          vals[want_dest == d].tolist()))
        assert got == want, f"partition {d} mismatch"


def test_device_shuffle_detects_overflow(mesh):
    """Skew past the capacity factor must be REPORTED, never silent:
    all records hash to one destination, capacity can't hold them."""
    n = 8 * 64
    keys = jnp.full((n,), 42, jnp.int32)  # one destination for everything
    vals = jnp.arange(n, dtype=jnp.int32)
    res = device_shuffle(mesh, "x", _shard(mesh, keys),
                         _shard(mesh, vals), capacity_factor=1.0)
    n_valid = int(np.asarray(res.valid).sum())
    n_dropped = int(np.asarray(res.dropped).sum())
    assert n_dropped > 0
    assert n_valid + n_dropped == n  # conservation: every record accounted


def test_device_terasort_global_order(mesh):
    """TeraSort acceptance: after sample→range-partition→exchange→sort,
    concatenating the devices' valid runs IS the sorted input (the
    TeraValidate check)."""
    rng = np.random.default_rng(11)
    n = 8 * 1024
    keys = rng.integers(-2**31, 2**31 - 2, size=n).astype(np.int32)
    vals = np.arange(n).astype(np.int32)

    res = device_terasort(mesh, "x", _shard(mesh, jnp.asarray(keys)),
                          _shard(mesh, jnp.asarray(vals)),
                          capacity_factor=3.0)
    assert int(res.dropped.sum()) == 0
    out_k = np.asarray(res.keys).reshape(8, -1)
    out_m = np.asarray(res.valid).reshape(8, -1)
    runs = [out_k[d][out_m[d]] for d in range(8)]
    for d, run in enumerate(runs):
        assert np.all(np.diff(run) >= 0), f"device {d} run not sorted"
    for d in range(7):
        if runs[d].size and runs[d + 1].size:
            assert runs[d][-1] <= runs[d + 1][0], "global order broken"
    glued = np.concatenate(runs)
    np.testing.assert_array_equal(glued, np.sort(keys))


def test_device_group_reduce_wordcount_parity(mesh):
    """The numeric wordcount: per-key sums across the mesh equal the
    host reducer's output; each key reported exactly once."""
    rng = np.random.default_rng(3)
    n = 8 * 256
    keys = rng.integers(0, 50, size=n).astype(np.int32)  # heavy dupes
    vals = rng.integers(1, 10, size=n).astype(np.int32)

    res = device_group_reduce(mesh, "x", _shard(mesh, jnp.asarray(keys)),
                              _shard(mesh, jnp.asarray(vals)),
                              capacity_factor=16.0)  # 50 keys / 8 devs: skew
    assert int(res.dropped.sum()) == 0
    out_k = np.asarray(res.keys)
    out_v = np.asarray(res.values)
    out_m = np.asarray(res.valid)

    got = {int(k): int(v) for k, v in zip(out_k[out_m], out_v[out_m])}
    want = {}
    for k, v in zip(keys.tolist(), vals.tolist()):
        want[k] = want.get(k, 0) + v
    assert got == want
    assert len(out_k[out_m]) == len(set(out_k[out_m].tolist()))


def test_device_shuffle_values_can_be_vectors(mesh):
    """Values needn't be scalars — a [n, d] payload (e.g. embedding
    rows) rides the same exchange."""
    rng = np.random.default_rng(5)
    n = 8 * 128
    keys = rng.integers(0, 1000, size=n).astype(np.int32)
    vals = rng.standard_normal((n, 16)).astype(np.float32)
    res = device_shuffle(mesh, "x", _shard(mesh, jnp.asarray(keys)),
                         _shard(mesh, jnp.asarray(vals)),
                         capacity_factor=3.0)
    assert int(res.dropped.sum()) == 0
    out_k = np.asarray(res.keys)
    out_v = np.asarray(res.values)
    out_m = np.asarray(res.valid)
    # reattach: every surviving (key, payload) pair exists in the input
    want = {}
    for k, v in zip(keys.tolist(), vals):
        want.setdefault(k, []).append(v)
    for k, v in zip(out_k[out_m].tolist(), out_v[out_m]):
        assert any(np.allclose(v, w) for w in want[k])
    assert int(out_m.sum()) == n


def test_device_shuffle_extreme_skew_and_tiny_shards(mesh):
    """Degenerate shapes: a single record per device, and 90%-skewed
    keys with a big capacity factor — conservation holds throughout."""
    keys = jnp.arange(8, dtype=jnp.int32)
    vals = jnp.arange(8, dtype=jnp.int32) * 10
    res = device_shuffle(mesh, "x", _shard(mesh, keys), _shard(mesh, vals),
                         capacity_factor=8.0)
    assert int(res.dropped.sum()) == 0
    assert int(np.asarray(res.valid).sum()) == 8

    rng = np.random.default_rng(2)
    n = 8 * 256
    skewed = np.where(rng.random(n) < 0.9, 7,
                      rng.integers(0, 1000, size=n)).astype(np.int32)
    vals = rng.integers(0, 5, size=n).astype(np.int32)
    res = device_shuffle(mesh, "x", _shard(mesh, jnp.asarray(skewed)),
                         _shard(mesh, jnp.asarray(vals)),
                         capacity_factor=16.0)
    n_valid = int(np.asarray(res.valid).sum())
    n_drop = int(np.asarray(res.dropped).sum())
    assert n_valid + n_drop == n
    if n_drop == 0:
        got = np.asarray(res.values)[np.asarray(res.valid)].sum()
        assert int(got) == int(vals.sum())


def test_repeat_shuffles_reuse_compiled_programs(mesh):
    """Iterative jobs must not retrace per call: the exchange program is
    cached on its static signature, and fresh range split points ride in
    as a traced argument instead of forcing a recompile (review
    finding: shard_map+jit were rebuilt per invocation)."""
    from hadoop_tpu.parallel.collectives import _PROGRAM_CACHE

    keys = _shard(mesh, jnp.arange(256, dtype=jnp.int32))
    vals = _shard(mesh, jnp.ones((256,), jnp.int32))
    _PROGRAM_CACHE.clear()
    device_shuffle(mesh, "x", keys, vals)
    n_after_first = len(_PROGRAM_CACHE)
    assert n_after_first >= 1
    for _ in range(3):
        device_shuffle(mesh, "x", keys, vals)
    assert len(_PROGRAM_CACHE) == n_after_first

    # terasort: two programs (sample + exchange); repeated sorts with
    # DIFFERENT data (⇒ different split points) still reuse them.
    # capacity_factor=8: contiguous shards are maximal skew (each shard
    # range-partitions to ONE destination), which is the point — the
    # split points differ wildly between the two sorts yet the program
    # is reused.
    _PROGRAM_CACHE.clear()
    device_terasort(mesh, "x", keys, vals, capacity_factor=8.0)
    n_after_sort = len(_PROGRAM_CACHE)
    other = _shard(mesh, jnp.arange(256, dtype=jnp.int32)[::-1].copy())
    res = device_terasort(mesh, "x", other, vals, capacity_factor=8.0)
    assert len(_PROGRAM_CACHE) == n_after_sort
    assert int(res.dropped.sum()) == 0

    # group-reduce adds its segment-reduce program once
    _PROGRAM_CACHE.clear()
    device_group_reduce(mesh, "x", keys % 7, vals)
    n_after_gr = len(_PROGRAM_CACHE)
    device_group_reduce(mesh, "x", keys % 7, vals)
    assert len(_PROGRAM_CACHE) == n_after_gr
