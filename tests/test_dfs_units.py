"""DFS component unit tests: inodes, edit log, image, leases, block manager,
block store. (Parity targets: ref TestINodeFile, TestEditLog, TestFSImage,
TestLeaseManager, TestBlockManager, TestFsDatasetImpl.)"""

import os

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.namenode.blockmanager import BlockManager
from hadoop_tpu.dfs.namenode.editlog import (OP_MKDIR, FSEditLog,
                                             FileJournalManager)
from hadoop_tpu.dfs.namenode.fsimage import FSImage
from hadoop_tpu.dfs.namenode.inodes import FSDirectory, INodeFile
from hadoop_tpu.dfs.namenode.lease import LeaseManager
from hadoop_tpu.dfs.datanode.blockstore import BlockStore, Replica
from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo
from hadoop_tpu.util.crc import DataChecksum


# ------------------------------------------------------------------ inodes


def test_fsdirectory_basic():
    d = FSDirectory()
    d.mkdirs("/a/b/c")
    assert d.exists("/a/b/c")
    assert d.get_inode("/a/b").is_dir
    f = d.add_file("/a/b/f.txt", replication=3, block_size=1024)
    assert not f.is_dir
    assert d.get_inode("/a/b/f.txt") is f
    with pytest.raises(FileExistsError):
        d.add_file("/a/b/f.txt", 3, 1024)
    listing = d.listing("/a/b")
    assert [s.path for s in listing] == ["/a/b/c", "/a/b/f.txt"]


def test_fsdirectory_delete_rename():
    d = FSDirectory()
    d.add_file("/x/f1", 3, 1024)
    d.add_file("/x/f2", 3, 1024)
    with pytest.raises(OSError):
        d.delete("/x", recursive=False)
    d.rename("/x/f1", "/y/")  # /y doesn't exist → parent missing
    # ^ rename to /y/: components ["y"], parent of "/y/" is root, dst=/y
    assert d.exists("/y")
    d.mkdirs("/z")
    d.rename("/x/f2", "/z")  # into existing dir → /z/f2
    assert d.exists("/z/f2")
    assert d.delete("/z", recursive=True) is not None
    assert not d.exists("/z/f2")


def test_rename_under_self_rejected():
    d = FSDirectory()
    d.mkdirs("/a/b")
    with pytest.raises(ValueError):
        d.rename("/a", "/a/b/c")


# ---------------------------------------------------------------- edit log


def test_editlog_roundtrip(tmp_path):
    jm = FileJournalManager(str(tmp_path / "edits"))
    elog = FSEditLog(jm)
    elog.open_for_write(0)
    txids = [elog.log_edit(OP_MKDIR, {"p": f"/d{i}"}) for i in range(10)]
    elog.log_sync()
    assert txids == list(range(1, 11))
    elog.close()
    recs = list(jm.read_edits(1))
    assert len(recs) == 10
    assert recs[0]["p"] == "/d0"
    assert recs[-1]["t"] == 10
    # Finalized segment exists.
    segs = jm.segments()
    assert segs == [(1, 10, str(tmp_path / "edits" / "edits_1-10"))]


def test_editlog_torn_tail_tolerated(tmp_path):
    jm = FileJournalManager(str(tmp_path / "edits"))
    elog = FSEditLog(jm)
    elog.open_for_write(0)
    for i in range(5):
        elog.log_edit(OP_MKDIR, {"p": f"/d{i}"})
    elog.log_sync()
    # Simulate crash: truncate the in-progress segment mid-frame.
    seg = os.path.join(str(tmp_path / "edits"), "edits_inprogress_1")
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 3)
    jm2 = FileJournalManager(str(tmp_path / "edits"))
    recs = list(jm2.read_edits(1))
    assert len(recs) == 4  # last record torn away, rest intact


def test_editlog_group_commit_batches(tmp_path):
    import threading
    jm = FileJournalManager(str(tmp_path / "edits"))
    elog = FSEditLog(jm)
    elog.open_for_write(0)

    def writer(i):
        t = elog.log_edit(OP_MKDIR, {"p": f"/t{i}"})
        elog.log_sync(t)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert elog.synced_txid == 32
    elog.close()
    assert len(list(jm.read_edits(1))) == 32


def test_editlog_roll(tmp_path):
    jm = FileJournalManager(str(tmp_path / "edits"))
    elog = FSEditLog(jm)
    elog.open_for_write(0)
    elog.log_edit(OP_MKDIR, {"p": "/a"})
    first_new = elog.roll()
    assert first_new == 2
    elog.log_edit(OP_MKDIR, {"p": "/b"})
    elog.close()
    firsts = [s[0] for s in jm.segments()]
    assert firsts == [1, 2]
    assert [r["p"] for r in jm.read_edits(1)] == ["/a", "/b"]


# ------------------------------------------------------------------ fsimage


def test_fsimage_roundtrip(tmp_path):
    d = FSDirectory()
    d.mkdirs("/data/sub")
    f = d.add_file("/data/file", 2, 4096, owner="alice")
    f.blocks = [Block(101, 1000, 500), Block(102, 1001, 300)]
    img = FSImage(str(tmp_path / "img"))
    img.save(d, txid=42, extra={"gen_stamp": 1001})
    loaded = img.load()
    assert loaded is not None
    txid, d2, extra = loaded
    assert txid == 42
    assert extra["gen_stamp"] == 1001
    f2 = d2.get_inode("/data/file")
    assert isinstance(f2, INodeFile)
    assert f2.owner == "alice"
    assert [b.block_id for b in f2.blocks] == [101, 102]
    assert f2.length() == 800
    assert d2.exists("/data/sub")


def test_fsimage_corruption_detected(tmp_path):
    d = FSDirectory()
    img = FSImage(str(tmp_path / "img"))
    path = img.save(d, txid=1, extra={})
    with open(path, "r+b") as f:
        f.seek(10)
        f.write(b"\xde\xad")
    with pytest.raises(IOError, match="corrupt"):
        img.load()


# ------------------------------------------------------------------- leases


def test_lease_lifecycle():
    lm = LeaseManager(soft_limit_s=0.2, hard_limit_s=0.5)
    lm.add_lease("client1", "/f1")
    assert lm.holder_of("/f1") == "client1"
    assert not lm.is_soft_expired("/f1")
    import time
    time.sleep(0.25)
    assert lm.is_soft_expired("/f1")
    lm.renew_lease("client1")
    assert not lm.is_soft_expired("/f1")
    time.sleep(0.55)
    assert lm.hard_expired_paths() == ["/f1"]
    lm.remove_lease("client1", "/f1")
    assert lm.holder_of("/f1") is None
    assert lm.num_leases() == 0


# ------------------------------------------------------------ block manager


def _register(bm, n):
    nodes = []
    for i in range(n):
        info = DatanodeInfo(f"uuid-{i}", "127.0.0.1", 5000 + i)
        nodes.append(bm.dn_manager.register(info))
    return nodes


def test_block_manager_replication_tracking():
    conf = Configuration(load_defaults=False)
    bm = BlockManager(conf)
    bm.safemode.leave(force=True)
    nodes = _register(bm, 3)
    blk = Block(1, 100, 1024)
    info = bm.add_block_collection(blk, None, 3)
    info.under_construction = False
    for node in nodes:
        bm.add_stored_block(blk, node.uuid)
    assert bm.get(1).live_replicas() == 3
    assert bm.under_replicated_count() == 0
    # Lose a node → under-replicated.
    nodes[0].state = DatanodeInfo.STATE_DEAD
    bm.node_died(nodes[0])
    assert bm.get(1).live_replicas() == 2
    assert bm.under_replicated_count() == 1
    # Only 2 nodes remain live and both already hold replicas → no target.
    assert bm.compute_reconstruction_work() == 0


def test_block_manager_schedules_reconstruction():
    conf = Configuration(load_defaults=False)
    bm = BlockManager(conf)
    bm.safemode.leave(force=True)
    nodes = _register(bm, 4)
    blk = Block(1, 100, 1024)
    info = bm.add_block_collection(blk, None, 3)
    info.under_construction = False
    for node in nodes[:3]:
        bm.add_stored_block(blk, node.uuid)
    nodes[0].state = DatanodeInfo.STATE_DEAD
    bm.node_died(nodes[0])
    assert bm.compute_reconstruction_work() == 1
    queued = [n for n in nodes[1:3] if n.transfer_queue]
    assert len(queued) == 1
    _, targets = queued[0].transfer_queue[0]
    assert targets[0].uuid == nodes[3].uuid


def test_block_manager_excess_replicas_pruned():
    conf = Configuration(load_defaults=False)
    bm = BlockManager(conf)
    bm.safemode.leave(force=True)
    nodes = _register(bm, 4)
    blk = Block(1, 100, 1024)
    info = bm.add_block_collection(blk, None, 2)  # want 2
    info.under_construction = False
    for node in nodes:
        bm.add_stored_block(blk, node.uuid)  # have 4
    assert bm.get(1).live_replicas() == 2
    invalidations = sum(len(n.invalidate_queue) for n in nodes)
    assert invalidations == 2


def test_block_manager_stale_genstamp_is_corrupt():
    conf = Configuration(load_defaults=False)
    bm = BlockManager(conf)
    bm.safemode.leave(force=True)
    nodes = _register(bm, 2)
    blk = Block(1, gen_stamp=200, num_bytes=100)
    info = bm.add_block_collection(blk, None, 2)
    info.under_construction = False
    bm.add_stored_block(Block(1, 200, 100), nodes[0].uuid)
    bm.add_stored_block(Block(1, 150, 80), nodes[1].uuid)  # stale
    assert bm.get(1).live_replicas() == 1
    assert nodes[1].invalidate_queue  # stale replica queued for deletion


def test_safemode_threshold():
    conf = Configuration(load_defaults=False)
    bm = BlockManager(conf)
    nodes = _register(bm, 1)
    blocks = [Block(i, 100, 10) for i in range(10)]
    for b in blocks:
        bi = bm.add_block_collection(b, None, 1)
        bi.under_construction = False
    bm.safemode.set_block_total(10)
    assert bm.safemode.is_on()
    for b in blocks[:9]:
        bm.add_stored_block(b, nodes[0].uuid)
    assert bm.safemode.is_on()  # 9/10 < 99.9%
    bm.add_stored_block(blocks[9], nodes[0].uuid)
    assert not bm.safemode.is_on()


def test_heartbeat_commands_roundtrip():
    conf = Configuration(load_defaults=False)
    bm = BlockManager(conf)
    nodes = _register(bm, 1)
    nodes[0].invalidate_queue.append(Block(7, 1, 0))
    cmds = bm.dn_manager.handle_heartbeat("uuid-0", 100, 10, 90, 0)
    assert len(cmds) == 1
    assert cmds[0].action == "invalidate"
    assert cmds[0].blocks[0].block_id == 7
    # Queue drained.
    assert bm.dn_manager.handle_heartbeat("uuid-0", 100, 10, 90, 0) == []
    # Unknown node → reregister.
    cmds = bm.dn_manager.handle_heartbeat("ghost", 1, 1, 1, 0)
    assert cmds[0].action == "reregister"


# --------------------------------------------------------------- blockstore


def test_blockstore_write_read_roundtrip(tmp_path):
    store = BlockStore(str(tmp_path / "bs"))
    cs = DataChecksum(512)
    blk = Block(42, 1000)
    rep = store.create_rbw(blk, cs)
    data = os.urandom(3000)
    for off in range(0, len(data), 1024):
        chunk = data[off:off + 1024]
        rep.write_packet(chunk, cs.checksums_for(chunk))
    final = store.finalize(rep)
    assert final.num_bytes == 3000
    assert final.state == Replica.FINALIZED
    # Read back whole + ranges, verifying checksums.
    got = bytearray()
    for pos, d, sums in store.read_chunks(Block(42, 1000, 3000), 0, 3000):
        cs.verify(d, sums, base_pos=pos)
        got += d
    assert bytes(got) == data


def test_blockstore_range_read_chunk_aligned(tmp_path):
    store = BlockStore(str(tmp_path / "bs"))
    cs = DataChecksum(512)
    blk = Block(1, 5)
    rep = store.create_rbw(blk, cs)
    data = bytes(range(256)) * 8  # 2048
    rep.write_packet(data, cs.checksums_for(data))
    store.finalize(rep)
    # Ask for bytes 700..900; reader gets chunk-aligned data covering it.
    runs = list(store.read_chunks(Block(1, 5, 2048), 700, 200))
    start = runs[0][0]
    assert start == 512  # aligned down
    total = b"".join(r[1] for r in runs)
    assert data[700:900] in total


def test_blockstore_survives_restart(tmp_path):
    store = BlockStore(str(tmp_path / "bs"))
    cs = DataChecksum(512)
    rep = store.create_rbw(Block(9, 77), cs)
    rep.write_packet(b"abc", cs.checksums_for(b"abc"))
    store.finalize(rep)
    store2 = BlockStore(str(tmp_path / "bs"))
    r = store2.get_replica(9)
    assert r is not None and r.gen_stamp == 77 and r.num_bytes == 3
    assert [b.block_id for b in store2.all_finalized()] == [9]


def test_blockstore_genstamp_update(tmp_path):
    store = BlockStore(str(tmp_path / "bs"))
    cs = DataChecksum(512)
    rep = store.create_rbw(Block(5, 10), cs)
    rep.write_packet(b"x", cs.checksums_for(b"x"))
    store.finalize(rep)
    store.update_gen_stamp(5, 20)
    assert store.get_replica(5).gen_stamp == 20
    store2 = BlockStore(str(tmp_path / "bs"))
    assert store2.get_replica(5).gen_stamp == 20


def test_blockstore_invalidate(tmp_path):
    store = BlockStore(str(tmp_path / "bs"))
    cs = DataChecksum(512)
    rep = store.create_rbw(Block(3, 1), cs)
    rep.write_packet(b"zz", cs.checksums_for(b"zz"))
    store.finalize(rep)
    assert store.invalidate(Block(3, 1))
    assert store.get_replica(3) is None
    assert not store.invalidate(Block(3, 1))


def test_editlog_torn_tail_truncated_before_append(tmp_path):
    """Regression: a torn in-progress segment must be truncated on reopen,
    or edits appended after the torn frame are unreachable on replay."""
    jm = FileJournalManager(str(tmp_path / "edits"))
    elog = FSEditLog(jm)
    elog.open_for_write(0)
    for i in range(3):
        elog.log_edit(OP_MKDIR, {"p": f"/a{i}"})
    elog.log_sync()
    # Crash: torn frame at the tail of the in-progress segment.
    seg = str(tmp_path / "edits" / "edits_inprogress_1")
    with open(seg, "ab") as f:
        f.write(b"\x00\x00\x01\x00partial-frame")
    # Restart: reopen the same segment and write more durable edits.
    jm2 = FileJournalManager(str(tmp_path / "edits"))
    elog2 = FSEditLog(jm2)
    elog2.open_for_write(3)
    elog2.log_edit(OP_MKDIR, {"p": "/after-crash"})
    elog2.log_sync()
    elog2.close()
    # Second restart must see ALL four edits.
    jm3 = FileJournalManager(str(tmp_path / "edits"))
    paths = [r["p"] for r in jm3.read_edits(1)]
    assert paths == ["/a0", "/a1", "/a2", "/after-crash"]


def test_editlog_roll_races_concurrent_writers(tmp_path):
    """Regression: roll() must not lose or misplace edits logged
    concurrently by other threads."""
    import threading
    jm = FileJournalManager(str(tmp_path / "edits"))
    elog = FSEditLog(jm)
    elog.open_for_write(0)
    stop = threading.Event()
    errors = []

    def writer(tid):
        i = 0
        while not stop.is_set():
            try:
                t = elog.log_edit(OP_MKDIR, {"p": f"/w{tid}-{i}"})
                elog.log_sync(t)
                i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    import time as _t
    for _ in range(10):
        elog.roll()
        _t.sleep(0.01)
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    elog.close()
    jm2 = FileJournalManager(str(tmp_path / "edits"))
    recs = list(jm2.read_edits(1))
    txids = [r["t"] for r in recs]
    assert txids == list(range(1, elog.last_txid + 1))  # no gaps, no loss


def test_lease_rename_subtree_and_actual_dst():
    """Regression: leases must follow directory renames and into-dir moves."""
    lm = LeaseManager()
    lm.add_lease("c1", "/d/open1")
    lm.add_lease("c1", "/d/sub/open2")
    lm.add_lease("c2", "/other")
    lm.rename_path("/d", "/d2")
    assert lm.holder_of("/d/open1") is None
    assert lm.holder_of("/d2/open1") == "c1"
    assert lm.holder_of("/d2/sub/open2") == "c1"
    assert lm.holder_of("/other") == "c2"


def test_lease_remove_under():
    lm = LeaseManager()
    lm.add_lease("c1", "/gone/f1")
    lm.add_lease("c1", "/gone/deep/f2")
    lm.add_lease("c1", "/keep/f3")
    lm.remove_under("/gone")
    assert lm.holder_of("/gone/f1") is None
    assert lm.holder_of("/gone/deep/f2") is None
    assert lm.holder_of("/keep/f3") == "c1"


def test_blockstore_finalize_existing_rbw(tmp_path):
    """Regression: block recovery finalizes a partial rbw replica at its
    on-disk length."""
    store = BlockStore(str(tmp_path / "bs"))
    cs = DataChecksum(512)
    rep = store.create_rbw(Block(11, 100), cs)
    rep.write_packet(b"x" * 700, cs.checksums_for(b"x" * 700))
    rep.fsync()
    rep.close()  # interrupted write: rbw retained, never finalized
    store.update_gen_stamp(11, 101)
    final = store.finalize_existing(11)
    assert final.state == Replica.FINALIZED
    assert final.num_bytes == 700
    assert final.gen_stamp == 101
    assert [b.block_id for b in store.all_finalized()] == [11]


def test_standby_postpones_unknown_block_reports():
    """A standby whose edit tail lags the DNs must QUEUE received-reports
    for unknown blocks, not invalidate the replicas (ref: BlockManager
    .PendingDataNodeMessages; the round-5 immediate-IBR change makes the
    race routine). Replay happens when the block appears or on
    transition to active."""
    conf = Configuration(load_defaults=False)
    bm = BlockManager(conf)
    bm.safemode.leave(force=True)
    (node,) = _register(bm, 1)
    bm.postpone_unknown = True

    blk = Block(77, 100, 4096)
    bm.add_stored_block(blk, node.uuid)           # namespace doesn't know it
    assert not node.invalidate_queue, "standby must not invalidate"
    assert bm._postponed_count == 1

    info = bm.add_block_collection(blk, None, 1)  # edit tail catches up
    info.under_construction = False
    assert bm._postponed_count == 0
    assert bm.get(77).live_replicas() == 1        # replayed

    # Unknown at activation time → really deletable: drained with
    # postponement off, replica invalidated.
    bm.postpone_unknown = True
    bm.add_stored_block(Block(88, 100, 4096), node.uuid)
    assert bm._postponed_count == 1
    bm.process_all_postponed()
    assert bm._postponed_count == 0 and not bm.postpone_unknown
    assert any(b.block_id == 88 for b in node.invalidate_queue)


def test_fjm_start_segment_truncates_torn_tail_directly(tmp_path):
    """FileJournalManager.start_segment on a segment with a torn tail
    (the QJM's crash path — no FSEditLog pre-recovery in front of it)
    must truncate and continue, not die on the warning line (review
    finding: an undefined logger name made this path raise NameError)."""
    import os

    import struct

    from hadoop_tpu.io.wire import pack

    d = str(tmp_path / "edits")
    jm = FileJournalManager(d)
    jm.start_segment(1)
    rec = pack({"t": 1, "op": "mkdir", "p": "/a"})
    jm.journal(struct.pack(">I", len(rec)) + rec, 1, 1)
    jm.sync()
    jm.close()
    with open(os.path.join(d, "edits_inprogress_1"), "ab") as f:
        f.write(b"\x00\x00\x01\x00partial")
    jm2 = FileJournalManager(d)
    jm2.start_segment(1)  # must truncate the torn frame, not raise
    jm2.close()
    assert [r for r in jm2.read_edits(1)]  # intact prefix readable


def test_pending_recovery_pinned_to_inode_identity(tmp_path):
    """An in-flight lease recovery must not act on a path that now names
    a DIFFERENT file (delete + overwrite-create while recovery waited),
    and must follow renames (review findings: the sweep force-closed a
    new writer's file; renamed recoveries were stranded)."""
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fsn = cluster.namenode.fsn

        out = fs.create("/f")
        out.write(b"x" * 100)
        out.flush()
        old_inode = fsn.fsdir.get_inode("/f")
        # recovery of A's file is "in flight"
        fsn._pending_recovery["/f"] = old_inode

        # B replaces the file and starts writing
        out.close()
        out2 = fs.create("/f", overwrite=True)
        out2.write(b"y" * 50)
        out2.flush()
        new_inode = fsn.fsdir.get_inode("/f")
        assert new_inode is not old_inode
        assert new_inode.under_construction

        fsn.check_pending_recoveries()
        # B's live file untouched; the stale entry is gone
        assert fsn.fsdir.get_inode("/f").under_construction
        assert "/f" not in fsn._pending_recovery
        out2.close()

        # rename migrates a pending-recovery key with the file
        out3 = fs.create("/r1")
        out3.write(b"z" * 10)
        out3.flush()
        fsn._pending_recovery["/r1"] = fsn.fsdir.get_inode("/r1")
        out3.close()  # closing does not consult the map; entry remains
        fs.rename("/r1", "/r2")
        assert "/r1" not in fsn._pending_recovery
        assert "/r2" in fsn._pending_recovery
        fsn._pending_recovery.pop("/r2", None)


def test_is_hard_expired_point_check(tmp_path):
    """The sweep's under-lock re-verification: a fresh/renewed lease is
    NOT hard-expired; an unleased path is fair game (review finding:
    the sweep acted on a stale snapshot)."""
    from hadoop_tpu.dfs.namenode.lease import LeaseManager

    lm = LeaseManager(soft_limit_s=0.05, hard_limit_s=0.1)
    lm.add_lease("clientA", "/f")
    assert not lm.is_hard_expired("/f")     # fresh
    import time as _t
    _t.sleep(0.12)
    assert lm.is_hard_expired("/f")         # aged out
    lm.renew_lease("clientA")
    assert not lm.is_hard_expired("/f")     # renewal rescues it
    lm.remove_lease("clientA", "/f")
    assert lm.is_hard_expired("/f")         # nothing protects the path


def test_finalize_existing_truncates_to_checksummed_prefix(tmp_path):
    """Crash alignment: data flushed past the meta's checksums must be
    truncated at promotion, not finalized as a replica whose tail fails
    every read (review finding)."""
    import os

    from hadoop_tpu.dfs.datanode.blockstore import BlockStore
    from hadoop_tpu.dfs.protocol.records import Block
    from hadoop_tpu.util.crc import DataChecksum

    store = BlockStore(str(tmp_path / "data"))
    blk = Block(7001, 1, 0)
    w = store.create_rbw(blk, DataChecksum(512))
    payload = b"A" * 2048
    w.write_packet(payload, DataChecksum(512).checksums_for(payload))
    w.fsync()
    # crash: data file grows past what the meta covers
    data_path = w.data_path
    with open(data_path, "ab") as f:
        f.write(b"B" * 700)  # unchecksummed tail
    w.steal()
    rep = store.finalize_existing(blk.block_id)
    assert rep.num_bytes == 2048  # truncated to the verified prefix
    assert os.path.getsize(store._path(rep.state, blk.block_id)) == 2048
    # and the finalized replica reads back clean end to end
    _, _, checksum, _ = store.open_for_read(Block(7001, 1, 2048))
    for _pos, data, sums in store.read_chunks(Block(7001, 1, 2048), 0,
                                              2048):
        checksum.verify(data, sums, base_pos=_pos)


def test_nn_restart_past_torn_fsimage_md5(tmp_path):
    """A crash artifact (empty/torn .md5 side file) must not block NN
    startup: empty digests are skipped and a truly corrupt newest image
    falls back to an older retained one (review finding)."""
    import os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as c:
        c.wait_active()
        fs = c.get_filesystem()
        fs.write_all("/f1", b"one")
        c.namenode.fsn.save_namespace()
        name_dir = c.namenode.fsn.image.dir
        images = sorted(p for p in os.listdir(name_dir)
                        if p.startswith("fsimage_")
                        and not p.endswith(".md5"))
        with open(os.path.join(name_dir, images[-1] + ".md5"), "w"):
            pass  # torn side file
        fs.write_all("/f2", b"two")
        c.restart_namenode()
        fs2 = c.get_filesystem()
        assert fs2.read_all("/f1") == b"one"
        assert fs2.read_all("/f2") == b"two"
