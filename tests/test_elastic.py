"""Elastic training plane: reshard-on-restore math + controller policy.

The contracts under test (parallel/elastic/):

- ZeRO-1 moment leaves convert EXACTLY between plan layouts through the
  global param-shaped intermediate — including non-power-of-two shrinks
  (dp 8→6) and padded slices — and same-plan conversion is the
  untouched-object passthrough (the bit-identical restore path);
- ``resolve_restore`` classifies manifests: same-plan, reshard, legacy
  (pre-plan-block → DeprecationWarning), and pp/vpp changes are refused
  loudly;
- the controller's streak policy: demote exactly once per flagged
  streak, evict on dead/flagged thresholds onto the largest healthy
  sub-mesh, hysteresis after a resume, evicted ranks never re-evicted;
- the retention sweep leaves an auditable (path, reason) breadcrumb per
  removal.
"""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs.filesystem import LocalFileSystem
from hadoop_tpu.parallel import MeshPlan
from hadoop_tpu.parallel.checkpoint import (_retain, list_checkpoints,
                                            read_manifest, snapshot_tree,
                                            write_snapshot)
from hadoop_tpu.parallel.elastic import ElasticConfig, elastic_from_conf
from hadoop_tpu.parallel.elastic.controller import (ElasticController,
                                                    pick_shrunken_plan)
from hadoop_tpu.parallel.elastic.reshard import (MANIFEST_FORMAT,
                                                 check_reshardable,
                                                 global_to_zero1_state,
                                                 manifest_meta,
                                                 plan_from_meta,
                                                 reshard_opt_state,
                                                 reshard_zero1_leaf,
                                                 resolve_restore,
                                                 zero1_state_to_global)
from hadoop_tpu.parallel.optimizer import AdamWState

requires_vma = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="multichip train step needs jax vma tracking (jax.typeof)")


# ---------------------------------------------------- reshard layout math

def test_zero1_roundtrip_replicated_leaf():
    plan = MeshPlan(dp=8)
    g = np.arange(48, dtype=np.float32).reshape(12, 4)
    state = global_to_zero1_state(g, P(), plan)
    assert state.shape == (8, 6)          # z=8, K=48/8
    back = zero1_state_to_global(state, P(), g.shape, plan)
    np.testing.assert_array_equal(back, g)


def test_zero1_roundtrip_with_padding():
    # local size 10 over z=8 pads to K=2 per slice; the pad tail must
    # stay zero and never leak into the reassembled global array
    plan = MeshPlan(dp=8)
    g = np.arange(10, dtype=np.float32)
    state = global_to_zero1_state(g, P(), plan)
    assert state.shape == (8, 2)
    assert state.sum() == g.sum()         # pad contributed nothing
    back = zero1_state_to_global(state, P(), g.shape, plan)
    np.testing.assert_array_equal(back, g)


def test_reshard_dp8_to_dp6_non_power_of_two():
    plan_a, plan_b = MeshPlan(dp=8), MeshPlan(dp=6)
    g = np.random.default_rng(0).normal(
        size=(12, 5)).astype(np.float32)   # 60 elements: pads under dp=8
    state_a = global_to_zero1_state(g, P(), plan_a)
    state_b = reshard_zero1_leaf(state_a, P(), g.shape, plan_a, plan_b)
    assert state_b.shape == (6, 10)
    np.testing.assert_array_equal(
        zero1_state_to_global(state_b, P(), g.shape, plan_b), g)


def test_reshard_sharded_leaf_across_dp():
    # a tp-sharded leaf: spec axes lead the state shape, dp slices the
    # per-shard flattened remainder
    spec = P("tp", None)
    plan_a, plan_b = MeshPlan(dp=4, tp=2), MeshPlan(dp=2, tp=2)
    g = np.random.default_rng(1).normal(size=(8, 6)).astype(np.float32)
    state_a = global_to_zero1_state(g, spec, plan_a)
    assert state_a.shape == (2, 4, 6)     # (tp, dp, K=24/4)
    state_b = reshard_zero1_leaf(state_a, spec, g.shape, plan_a, plan_b)
    assert state_b.shape == (2, 2, 12)
    np.testing.assert_array_equal(
        zero1_state_to_global(state_b, spec, g.shape, plan_b), g)


def test_reshard_tuple_axis_leaf():
    # stage-stacked + tp dims share one array dim via a tuple spec
    spec = P(("pp", "tp"))
    plan_a = MeshPlan(dp=2, pp=2, tp=2)
    plan_b = MeshPlan(dp=1, pp=2, tp=2)   # dp shrink, pp unchanged
    g = np.random.default_rng(2).normal(size=(8, 4)).astype(np.float32)
    state_a = global_to_zero1_state(g, spec, plan_a)
    assert state_a.shape == (2, 2, 2, 4)  # (pp, tp, dp, K=8/2)
    state_b = reshard_zero1_leaf(state_a, spec, g.shape, plan_a, plan_b)
    np.testing.assert_array_equal(
        zero1_state_to_global(state_b, spec, g.shape, plan_b), g)


def test_zero1_state_shape_mismatch_refused():
    with pytest.raises(ValueError, match="does not match plan layout"):
        zero1_state_to_global(np.zeros((4, 2), np.float32), P(),
                              (12,), MeshPlan(dp=8))


def test_reshard_opt_state_same_plan_is_passthrough():
    # THE bit-identical contract: same plan + same zero1 flag returns
    # the object untouched (no copy, no float round-trip)
    plan = MeshPlan(dp=4)
    g = np.ones((8,), np.float32)
    state = global_to_zero1_state(g, P(), plan)
    opt = AdamWState(count=np.int32(7), mu={"w": state},
                     nu={"w": state})
    out = reshard_opt_state(opt, {"w": g}, {"w": P()}, plan, plan,
                            zero1_a=True, zero1_b=True)
    assert out is opt


def test_reshard_opt_state_zero1_to_plain_and_back():
    plan = MeshPlan(dp=4)
    g = np.random.default_rng(3).normal(size=(8, 3)).astype(np.float32)
    z = global_to_zero1_state(g, P(), plan)
    opt_z = AdamWState(count=np.int32(2), mu={"w": z}, nu={"w": z})
    shapes, specs = {"w": g}, {"w": P()}
    # zero1 → plain: moments land global
    opt_p = reshard_opt_state(opt_z, shapes, specs, plan, plan,
                              zero1_a=True, zero1_b=False)
    np.testing.assert_array_equal(opt_p.mu["w"], g)
    # plain → zero1: back to slices
    opt_z2 = reshard_opt_state(opt_p, shapes, specs, plan, plan,
                               zero1_a=False, zero1_b=True)
    np.testing.assert_array_equal(opt_z2.nu["w"], z)


# ------------------------------------------------------ restore classify

def test_resolve_restore_same_plan():
    plan = MeshPlan(dp=2)
    manifest = {"meta": manifest_meta(plan, zero1=True)}
    assert resolve_restore(manifest, plan, True) == \
        ("same-plan", plan, True)


def test_resolve_restore_cross_plan():
    saved = MeshPlan(dp=4)
    manifest = {"meta": manifest_meta(saved, zero1=True)}
    mode, got_plan, got_z1 = resolve_restore(manifest, MeshPlan(dp=2),
                                             True)
    assert (mode, got_plan, got_z1) == ("reshard", saved, True)
    # a zero1-flag flip alone also reshards (layouts differ)
    mode, _, _ = resolve_restore(manifest, saved, False)
    assert mode == "reshard"


def test_resolve_restore_refuses_pp_change():
    manifest = {"meta": manifest_meta(MeshPlan(dp=2, pp=2), zero1=False)}
    with pytest.raises(ValueError, match="pipeline stage count"):
        resolve_restore(manifest, MeshPlan(dp=2, pp=1), False)
    with pytest.raises(ValueError, match="pipeline stage count"):
        check_reshardable(MeshPlan(pp=2, vpp=2, dp=2),
                          MeshPlan(pp=2, vpp=1, dp=2))


def test_resolve_restore_legacy_manifest_warns():
    with pytest.warns(DeprecationWarning, match="no plan block"):
        mode, plan, z1 = resolve_restore({"step": 3, "leaves": {}},
                                         MeshPlan(dp=2), True)
    assert (mode, plan, z1) == ("legacy", None, True)


def test_plan_from_meta_unknown_format_refused():
    meta = manifest_meta(MeshPlan(dp=2), zero1=False)
    assert plan_from_meta(meta) == MeshPlan(dp=2)
    assert meta["format"] == MANIFEST_FORMAT
    with pytest.raises(ValueError, match="unknown checkpoint meta"):
        plan_from_meta(dict(meta, format="htpu-ckpt-plan-99"))


def test_manifest_meta_rides_written_checkpoint(tmp_path):
    fs = LocalFileSystem()
    base = str(tmp_path / "ck")
    plan = MeshPlan(dp=2)
    write_snapshot(fs, base, 5, snapshot_tree({"w": np.ones(4)}),
                   meta=manifest_meta(plan, zero1=True))
    mode, saved, z1 = resolve_restore(read_manifest(fs, base, 5),
                                      plan, True)
    assert (mode, saved, z1) == ("same-plan", plan, True)


# ------------------------------------------------------ retention sweep

def test_retention_sweep_breadcrumbs(tmp_path):
    fs = LocalFileSystem()
    base = str(tmp_path / "ck")
    snap = snapshot_tree({"w": np.arange(4.0)})
    for s in (1, 2, 3):
        write_snapshot(fs, base, s, snap, keep=10)
    # a crashed publish: step dir with shards but no manifest
    orphan = f"{base}/step_{9:012d}"
    fs.mkdirs(orphan)
    fs.write_all(f"{orphan}/shard_000000.bin", b"xx")
    swept = dict(_retain(fs, base, keep=2))
    assert swept == {f"{base}/step_{1:012d}": "retention",
                     orphan: "crash-mid-write"}
    assert list_checkpoints(fs, base) == [2, 3]


# ------------------------------------------------------- shrink planning

def test_pick_shrunken_plan_non_power_of_two():
    assert pick_shrunken_plan(MeshPlan(dp=4), healthy=3, batch=12,
                              min_dp=1) == MeshPlan(dp=3)


def test_pick_shrunken_plan_respects_batch_divisibility():
    # 8 % 3 != 0 → falls through to dp=2
    assert pick_shrunken_plan(MeshPlan(dp=4), healthy=3, batch=8,
                              min_dp=1) == MeshPlan(dp=2)


def test_pick_shrunken_plan_respects_min_dp():
    assert pick_shrunken_plan(MeshPlan(dp=4), healthy=2, batch=12,
                              min_dp=3) is None


def test_pick_shrunken_plan_with_ep():
    got = pick_shrunken_plan(MeshPlan(dp=4, ep=2), healthy=2, batch=8,
                             min_dp=1)
    assert got == MeshPlan(dp=2, ep=2)    # batch % (dp' * ep) == 0


# --------------------------------------------------------- controller

class FakeTrainer:
    """Duck-typed ElasticController trainer contract."""

    def __init__(self, plan, batch=12, restore_step=30):
        self.plan = plan
        self.batch = batch
        self.step = 40
        self.restore_step = restore_step
        self.saves = []
        self.applied = []

    def save(self, wait=None):
        self.saves.append((self.step, wait))

    def apply_plan(self, plan):
        self.applied.append(plan)
        self.plan = plan
        self.step = self.restore_step
        return True


def doctor_report(flagged=(), dead=(), n=4):
    ranks = {f"rank-{r}": {"ok": f"rank-{r}" not in dead, "rank": r}
             for r in range(n)}
    return {"trainers": {
        "flagged": {name: {"signals": ["trainer.step_wall"]}
                    for name in flagged},
        "ranks": ranks}}


def _controller(trainer, reports, **cfg_kw):
    kw = dict(enabled=True, poll_steps=1, min_dp=1, demote_windows=2,
              evict_windows=10, dead_windows=2, cooldown_polls=0)
    kw.update(cfg_kw)
    feed = list(reports)
    return ElasticController(trainer, ElasticConfig(**kw),
                             poll_fn=lambda: feed.pop(0))


def test_controller_requires_poll_fn():
    with pytest.raises(ValueError, match="poll_fn"):
        ElasticController(FakeTrainer(MeshPlan(dp=4)),
                          ElasticConfig(enabled=True), poll_fn=None)


def test_demote_fires_once_per_streak():
    tr = FakeTrainer(MeshPlan(dp=4))
    flagged = doctor_report(flagged=["rank-1"])
    clear = doctor_report()
    ctl = _controller(tr, [flagged, flagged, flagged, clear,
                           flagged, flagged])
    for step in range(1, 4):
        assert ctl.on_step(step) is False
    # streak hit demote_windows=2 at poll 2; polls 3+ must not re-save
    assert tr.saves == [(40, False)]
    assert [e["decision"] for e in ctl.events] == ["demote"]
    ctl.on_step(4)                        # flag clears → streak resets
    ctl.on_step(5)
    assert ctl.on_step(6) is False        # fresh streak → second demote
    assert len(tr.saves) == 2


def test_dead_rank_evicts_and_reshards():
    tr = FakeTrainer(MeshPlan(dp=4))
    dead = doctor_report(dead=["rank-2"])
    ctl = _controller(tr, [dead] * 6, dead_windows=1, cooldown_polls=0)
    assert ctl.on_step(1) is True         # dead_windows=1 → immediate
    assert ctl.pending
    assert tr.applied == []               # decision only; no actuation
    assert ctl.on_step(2) is True         # pending short-circuits polls
    assert ctl.resume() is True
    assert tr.applied == [MeshPlan(dp=3)]  # healthy=3, 12 % 3 == 0
    assert not ctl.pending
    ev = {e["decision"]: e for e in ctl.events}
    assert ev["evict"]["ranks"] == ["rank-2"]
    assert ev["evict"]["plan_to"]["dp"] == 3
    assert ev["resume"]["lost_steps"] == 10   # step 40 → restored 30
    assert ev["resume"]["restored"] is True
    # the dead rank's roster row lingers — it must never evict again
    for step in (3, 4, 5):
        assert ctl.on_step(step) is False
    assert len([e for e in ctl.events
                if e["decision"] == "evict"]) == 1


def test_flagged_streak_evicts_at_threshold():
    tr = FakeTrainer(MeshPlan(dp=4))
    flagged = doctor_report(flagged=["rank-0"])
    ctl = _controller(tr, [flagged] * 5, demote_windows=2,
                      evict_windows=4)
    got = [ctl.on_step(s) for s in range(1, 5)]
    assert got == [False, False, False, True]
    assert tr.saves == [(40, False)]      # the demote at streak 2
    assert ctl.resume() is True
    assert tr.applied == [MeshPlan(dp=3)]


def test_cooldown_hysteresis_after_resume():
    tr = FakeTrainer(MeshPlan(dp=4))
    first_dead = doctor_report(dead=["rank-3"])
    then_dead = doctor_report(dead=["rank-3", "rank-1"])
    ctl = _controller(tr, [first_dead] + [then_dead] * 4,
                      dead_windows=1, cooldown_polls=2)
    assert ctl.on_step(1) is True
    ctl.resume()
    # rank-1 dies during cooldown: streak builds but decisions wait
    assert ctl.on_step(2) is False
    assert ctl.on_step(3) is False
    assert ctl.on_step(4) is True         # cooldown spent → evict
    ctl.resume()
    assert [p.dp for p in tr.applied] == [3, 2]


def test_evict_infeasible_raises():
    tr = FakeTrainer(MeshPlan(dp=2), batch=12)
    dead = doctor_report(dead=["rank-1"], n=2)
    ctl = _controller(tr, [dead], dead_windows=1, min_dp=2)
    with pytest.raises(RuntimeError, match="no dp in"):
        ctl.on_step(1)
    assert [e["decision"] for e in ctl.events] == ["evict-infeasible"]


def test_poll_failure_is_not_fatal():
    tr = FakeTrainer(MeshPlan(dp=4))

    def boom():
        raise OSError("doctor unreachable")

    ctl = ElasticController(tr, ElasticConfig(enabled=True),
                            poll_fn=boom)
    assert ctl.on_step(1) is False
    assert ctl.events == []


def test_controller_report_shape():
    tr = FakeTrainer(MeshPlan(dp=4))
    ctl = _controller(tr, [doctor_report(flagged=["rank-1"])])
    ctl.on_step(1)
    rep = ctl.report()
    assert rep["enabled"] is True
    assert rep["config"] == dataclasses.asdict(ctl.cfg)
    assert rep["plan"]["dp"] == 4
    assert rep["flagged_streaks"] == {"rank-1": 1}
    assert rep["evicted_ranks"] == []
    assert rep["events"] == []


# ------------------------------------------------------------- config

def test_elastic_config_validation():
    with pytest.raises(ValueError, match="must exceed"):
        ElasticConfig(demote_windows=3, evict_windows=3)
    with pytest.raises(ValueError, match="poll.steps"):
        ElasticConfig(poll_steps=0)
    with pytest.raises(ValueError, match="min-dp"):
        ElasticConfig(min_dp=0)


def test_elastic_from_conf():
    conf = Configuration(load_defaults=False)
    conf.set("elastic.enabled", "true")
    conf.set("elastic.poll.steps", "5")
    conf.set("elastic.min-dp", "2")
    conf.set("elastic.evict.windows", "7")
    got = elastic_from_conf(conf)
    assert got == ElasticConfig(enabled=True, poll_steps=5, min_dp=2,
                                evict_windows=7)
    assert elastic_from_conf(None) == ElasticConfig()


# ------------------------------------------------- trainer integration

@requires_vma
def test_trainer_same_plan_restore_bit_identical(tmp_path):
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel.trainer import Trainer
    fs = LocalFileSystem()
    cfg = get_config("tiny", max_seq=32)
    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, 50_000, dtype=np.uint16)
    data = str(tmp_path / "toks.bin")
    fs.write_all(data, toks.tobytes())
    ck = str(tmp_path / "ck")
    plan = MeshPlan(dp=4)
    tr = Trainer(cfg, plan, fs, data, ck, batch=8, zero1=True,
                 ckpt_interval=0)
    tr.train(3)
    tr.save()
    tr2 = Trainer(cfg, plan, fs, data, ck, batch=8, zero1=True,
                  ckpt_interval=0)
    assert tr2.try_restore() and tr2.step == 3
    for a, b in zip(jax.tree_util.tree_leaves((tr.params, tr.opt)),
                    jax.tree_util.tree_leaves((tr2.params, tr2.opt))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tr.close()
    tr2.close()


@requires_vma
def test_trainer_reshard_restore_across_plans(tmp_path):
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel.mesh import param_specs
    from hadoop_tpu.parallel.trainer import Trainer
    fs = LocalFileSystem()
    cfg = get_config("tiny", max_seq=32)
    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, 50_000, dtype=np.uint16)
    data = str(tmp_path / "toks.bin")
    fs.write_all(data, toks.tobytes())
    ck = str(tmp_path / "ck")
    plan_a, plan_b = MeshPlan(dp=4), MeshPlan(dp=2)
    tr = Trainer(cfg, plan_a, fs, data, ck, batch=8, zero1=True,
                 ckpt_interval=0)
    tr.train(3)
    tr.save()
    tr2 = Trainer(cfg, plan_b, fs, data, ck, batch=8, zero1=True,
                  ckpt_interval=0)
    assert tr2.try_restore() and tr2.step == 3
    # params restore to the same global values under either plan
    for a, b in zip(jax.tree_util.tree_leaves(tr.params),
                    jax.tree_util.tree_leaves(tr2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # moments agree exactly through their global layouts
    specs = param_specs(cfg, plan_a)
    flat = zip(
        jax.tree_util.tree_leaves_with_path(tr.opt.mu),
        jax.tree_util.tree_leaves(tr2.opt.mu),
        jax.tree_util.tree_leaves(tr.params),
        jax.tree_util.tree_leaves(specs))
    for (_, ma), mb, p, spec in flat:
        ga = zero1_state_to_global(np.asarray(ma), spec,
                                   np.shape(p), plan_a)
        gb = zero1_state_to_global(np.asarray(mb), spec,
                                   np.shape(p), plan_b)
        np.testing.assert_array_equal(ga, gb)
    tr.close()
    tr2.close()
