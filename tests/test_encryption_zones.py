"""Encryption zones: transparent encryption at rest via the KMS.

Ref: HDFS TDE — FSDirEncryptionZoneOp (zone create + per-file EDEK),
HdfsKMSUtil (client-side EDEK→DEK), CryptoInput/OutputStream wrapping;
acceptance mirrors TestEncryptionZones: data readable through the zone,
ciphertext on disk, unauthorized clients locked out."""

import glob
import os

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.crypto.kms import KMSKeyProvider, KMSServer
from hadoop_tpu.testing.minicluster import MiniDFSCluster


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ez")
    kms_conf = Configuration(load_defaults=False)
    kms_conf.set("kms.key.provider.path", str(tmp / "keys.json"))
    kms = KMSServer(kms_conf)
    kms.init(kms_conf)
    kms.start()
    KMSKeyProvider(f"127.0.0.1:{kms.port}").create_key("zone-key", 128)

    conf = Configuration(load_defaults=False)
    conf.set("dfs.encryption.key.provider.uri",
             f"kms://127.0.0.1:{kms.port}")
    cluster = MiniDFSCluster(num_datanodes=2, conf=conf,
                             base_dir=str(tmp / "dfs"))
    cluster.start()
    yield kms, cluster
    cluster.shutdown()
    kms.stop()


def test_zone_roundtrip_and_ciphertext_on_disk(stack):
    kms, cluster = stack
    fs = cluster.get_filesystem()
    fs.mkdirs("/secure")
    fs.create_encryption_zone("/secure", "zone-key")
    data = (b"attack at dawn " * 5000)[:64_000]
    with fs.create("/secure/plan.txt") as out:
        out.write(data)
    # transparent read-back
    with fs.open("/secure/plan.txt") as f:
        assert f.read() == data
    # positioned read decrypts mid-stream
    with fs.open("/secure/plan.txt") as f:
        f.seek(31_337)
        assert f.read(100) == data[31_337:31_437]
    # ON DISK it is ciphertext
    raw = b""
    for path in glob.glob(os.path.join(
            cluster.base_dir, "data*", "current", "finalized", "blk_*")):
        if not path.endswith(".meta"):
            raw += open(path, "rb").read()
    assert b"attack at dawn" not in raw
    # files outside the zone stay plaintext
    fs.write_all("/plain.txt", b"not secret")
    assert fs.read_all("/plain.txt") == b"not secret"
    assert fs.get_encryption_info("/plain.txt") is None
    info = fs.get_encryption_info("/secure/plan.txt")
    assert info["key"] == "zone-key" and info["edek"]


def test_client_without_kms_cannot_read(stack):
    kms, cluster = stack
    from hadoop_tpu.dfs.client.filesystem import DistributedFileSystem
    blind_conf = Configuration(load_defaults=False)  # no KMS uri
    blind = DistributedFileSystem([cluster.nn_addr], blind_conf)
    try:
        # metadata visible, content not decryptable
        assert blind.get_file_status("/secure/plan.txt").length > 0
        with blind.open("/secure/plan.txt") as f:
            assert f.read(100) != b"attack at dawn "[:100]
    finally:
        blind.close()


def test_zone_constraints(stack):
    kms, cluster = stack
    fs = cluster.get_filesystem()
    fs.mkdirs("/notempty/sub")
    with pytest.raises(OSError):
        fs.create_encryption_zone("/notempty", "zone-key")
    with pytest.raises(Exception):
        fs.create_encryption_zone("/secure", "no-such-key")
    fs.mkdirs("/secure/inner")
    with pytest.raises(OSError):  # no nested zones
        fs.create_encryption_zone("/secure/inner", "zone-key")


def test_zone_survives_namenode_restart(stack):
    kms, cluster = stack
    fs = cluster.get_filesystem()
    data = os.urandom(10_000)
    with fs.create("/secure/persist.bin") as out:
        out.write(data)
    cluster.restart_namenode()
    fs2 = cluster.get_filesystem()
    with fs2.open("/secure/persist.bin") as f:
        assert f.read() == data
    # new files in the zone still get EDEKs after replay
    with fs2.create("/secure/after.bin") as out:
        out.write(b"post-restart")
    assert fs2.get_encryption_info("/secure/after.bin") is not None


def test_list_encryption_zones(stack):
    kms, cluster = stack
    fs = cluster.get_filesystem()
    zones = fs.list_encryption_zones()
    assert zones.get("/secure") == "zone-key"
