"""Erasure coding: rawcoders, policies, striped IO on a minicluster.

Mirrors the reference's EC test posture (ref:
hadoop-common io/erasurecode/rawcoder/TestRSRawCoder.java;
hadoop-hdfs TestDFSStripedOutputStream.java,
TestDFSStripedInputStream.java, TestReconstructStripedFile.java):
coder correctness for every loss pattern, striped write/read roundtrip,
decode-on-read with a dead datanode, and background reconstruction.
"""

import itertools
import os
import time

import pytest

from hadoop_tpu.io import erasurecode as ec


# ------------------------------------------------------------- raw coders

@pytest.mark.parametrize("k,m", [(3, 2), (6, 3)])
def test_rs_coder_all_loss_patterns(k, m):
    coder = ec.RSRawCoder(k, m)
    cell = 512
    data = [os.urandom(cell) for _ in range(k)]
    parity = coder.encode(data)
    full = data + parity
    for lost in itertools.combinations(range(k + m), m):
        shards = [None if i in lost else full[i] for i in range(k + m)]
        assert coder.decode(shards) == full


def test_rs_numpy_matches_native():
    # Force the numpy path and compare against whatever encode() produced
    # (native when available): both must emit identical parity.
    k, m, cell = 6, 3, 256
    data = [os.urandom(cell) for _ in range(k)]
    coder = ec.RSRawCoder(k, m)
    parity = coder.encode(data)
    import numpy as np
    mat = ec._cauchy_parity_matrix(k, m)
    stacked = np.stack([np.frombuffer(c, np.uint8) for c in data])
    ref = ec._gf_matmul(mat, stacked)
    assert [ref[i].tobytes() for i in range(m)] == parity


def test_xor_coder_roundtrip():
    coder = ec.XORRawCoder(2, 1)
    data = [os.urandom(128), os.urandom(128)]
    parity = coder.encode(data)
    for lost in range(3):
        shards = [None if i == lost else (data + parity)[i] for i in range(3)]
        assert coder.decode(shards) == data + parity


def test_unit_length_accounting():
    p = ec.get_policy("RS-3-2-64k")
    cell = p.cell_size
    # 2 full stripes + 1.5 cells
    logical = 2 * 3 * cell + cell + cell // 2
    lens = [ec.unit_length(logical, p, i) for i in range(5)]
    assert lens[0] == 3 * cell
    assert lens[1] == 2 * cell + cell // 2
    assert lens[2] == 2 * cell
    assert lens[3] == lens[4] == 3 * cell  # parity tracks longest column
    assert sum(lens[:3]) == logical


def test_striped_id_scheme():
    gid = ec.STRIPED_ID_BASE + 32
    assert ec.is_striped_id(gid)
    assert not ec.is_striped_id(1 << 31)
    assert ec.group_id_of(gid + 7) == gid
    assert ec.unit_index_of(gid + 7) == 7


# ------------------------------------------------------------ minicluster

@pytest.fixture
def ec_cluster(tmp_path):
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    conf = fast_conf()
    conf.set("dfs.blocksize", str(256 * 1024))  # small groups → multi-group
    cluster = MiniDFSCluster(num_datanodes=5, base_dir=str(tmp_path),
                             conf=conf).start()
    cluster.wait_active()
    yield cluster
    cluster.shutdown()


def _fs(cluster):
    return cluster.get_filesystem()


def test_striped_write_read_roundtrip(ec_cluster):
    fs = _fs(ec_cluster)
    fs.mkdirs("/ec")
    fs.client.set_ec_policy("/ec", "RS-3-2-64k")
    assert fs.client.get_ec_policy("/ec") == "RS-3-2-64k"
    # Spans multiple stripes + a partial tail cell; > one block group.
    payload = os.urandom(900 * 1024 + 12345)
    with fs.create("/ec/striped.bin") as out:
        out.write(payload)
    st = fs.get_file_status("/ec/striped.bin")
    assert st.ec_policy == "RS-3-2-64k"
    assert st.length == len(payload)
    with fs.open("/ec/striped.bin") as f:
        assert f.read() == payload


def test_striped_read_with_dead_datanode_decodes(ec_cluster):
    fs = _fs(ec_cluster)
    fs.mkdirs("/ec2")
    fs.client.set_ec_policy("/ec2", "RS-3-2-64k")
    payload = os.urandom(400 * 1024)
    with fs.create("/ec2/f.bin") as out:
        out.write(payload)
    # Kill one datanode holding a unit; the read must decode around it.
    ec_cluster.kill_datanode(0)
    with fs.open("/ec2/f.bin") as f:
        assert f.read() == payload


def test_striped_reconstruction_after_loss(ec_cluster):
    fs = _fs(ec_cluster)
    fs.mkdirs("/ec3")
    fs.client.set_ec_policy("/ec3", "RS-3-2-64k")
    payload = os.urandom(300 * 1024)
    with fs.create("/ec3/f.bin") as out:
        out.write(payload)
    fsn = ec_cluster.namenode.fsn
    gid = next(bid for bid in fsn.bm._blocks
               if ec.is_striped_id(bid))
    info = fsn.bm.get(gid)
    assert set(info.live_units()) == {0, 1, 2, 3, 4} or \
        len(info.live_units()) == 5
    ec_cluster.kill_datanode(1)
    # Pump the redundancy monitor synchronously (deterministic under
    # full-suite load) instead of racing the background thread; the DN
    # heartbeats still pick up + execute the scheduled work.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if len(info.live_units()) == 5:
            break
        ec_cluster.namenode.redundancy_pass()
        time.sleep(0.3)
    assert len(info.live_units()) == 5, (
        f"units never reconstructed: {sorted(info.live_units())}")
    with fs.open("/ec3/f.bin") as f:
        assert f.read() == payload


def test_striped_lease_recovery_closes_abandoned_file(ec_cluster):
    """A client that dies mid-EC-write must not wedge the file: lease
    recovery issues unit-level RECOVER commands and derives the group
    length from the finalized unit lengths (ref: recoverLeaseInternal +
    commitBlockSynchronization for striped groups)."""
    fs = _fs(ec_cluster)
    fs.mkdirs("/ec5")
    fs.client.set_ec_policy("/ec5", "RS-3-2-64k")
    payload = os.urandom(200 * 1024)
    out = fs.create("/ec5/abandoned.bin")
    out.write(payload)
    # Simulate client death: unit sockets vanish, no complete() RPC.
    for w in out._writers:
        if w is not None:
            w.close()
    fsn = ec_cluster.namenode.fsn
    # Force lease expiry rather than waiting out the hard limit.
    fsn.leases.soft_limit_s = fsn.leases.hard_limit_s = 0.0
    deadline = time.monotonic() + 60
    closed = False
    while time.monotonic() < deadline:
        fsn.check_leases()
        inode = fsn.fsdir.get_inode("/ec5/abandoned.bin")
        if inode is not None and not inode.under_construction:
            closed = True
            break
        time.sleep(0.3)
    assert closed, "lease recovery never closed the striped file"
    st = fs.get_file_status("/ec5/abandoned.bin")
    # All full stripes the writers pushed before death are recoverable;
    # the tail may be truncated at a stripe boundary but never beyond.
    assert st.length >= 0
    if st.length:
        with fs.open("/ec5/abandoned.bin") as f:
            data = f.read()
        assert data == payload[:len(data)]


def test_ec_policy_inherited_and_image_persisted(ec_cluster):
    fs = _fs(ec_cluster)
    fs.mkdirs("/ec4/sub")
    fs.client.set_ec_policy("/ec4", "XOR-2-1-64k")
    with fs.create("/ec4/sub/f.bin") as out:
        out.write(b"x" * 100_000)
    st = fs.get_file_status("/ec4/sub/f.bin")
    assert st.ec_policy == "XOR-2-1-64k"
    # Survives a namenode restart (image + edits replay).
    ec_cluster.namenode.fsn.save_namespace()
    ec_cluster.restart_namenode()
    ec_cluster.wait_active()
    fs2 = _fs(ec_cluster)
    assert fs2.get_file_status("/ec4/sub/f.bin").ec_policy == "XOR-2-1-64k"
    with fs2.open("/ec4/sub/f.bin") as f:
        assert f.read() == b"x" * 100_000


# ------------------------------------------------- device-resident RS coding

def test_device_rs_encode_bit_identical_with_host_coders():
    """The jitted VPU bit-ops encoder (ops/ec_device, SURVEY §5.8's
    device-side EC) produces byte-identical parity to the host GF
    coder for every supported schema — wire parity: a DN's C++ coder
    can reconstruct what a device program encoded."""
    import os as _os

    from hadoop_tpu.io.erasurecode import RSRawCoder
    from hadoop_tpu.ops.ec_device import encode_cells

    for k, m in ((3, 2), (6, 3), (10, 4)):
        cells = [_os.urandom(8192) for _ in range(k)]
        host = RSRawCoder(k, m).encode(cells)
        dev = encode_cells(k, m, cells)
        assert dev == host, f"RS({k},{m}) parity mismatch"

    # odd (non-word-aligned) cell lengths round-trip too
    cells = [_os.urandom(1021) for _ in range(3)]
    assert encode_cells(3, 2, cells) == RSRawCoder(3, 2).encode(cells)


def test_device_rs_decode_reconstructs_erasures():
    """Device-side reconstruction inverts the Cauchy system for any
    erasure pattern up to m losses, matching the original data."""
    import os as _os

    from hadoop_tpu.io.erasurecode import RSRawCoder
    from hadoop_tpu.ops.ec_device import decode_cells, encode_cells

    k, m = 6, 3
    data = [_os.urandom(4096) for _ in range(k)]
    parity = encode_cells(k, m, data)
    shards = list(data) + parity

    # lose two data units and one parity unit
    lost = dict(enumerate(shards))
    for i in (1, 4, k + 2):
        lost[i] = None
    out = decode_cells(k, m, [lost[i] for i in range(k + m)])
    assert out == data

    # parity-only survival of data unit 0 (all-parity heavy pattern)
    lost2 = dict(enumerate(shards))
    for i in (0, 2, 5):
        lost2[i] = None
    assert decode_cells(k, m, [lost2[i] for i in range(k + m)]) == data

    # host coder decodes device-written parity (cross-backend; the host
    # decode contract returns all k+m shards — data half must match)
    host_out = RSRawCoder(k, m).decode([lost[i] for i in range(k + m)])
    assert host_out[:k] == data
