"""Fault-injection tests at exact IO points — the reference's load-bearing
test strategy (SURVEY §4.3; ref: DataNodeFaultInjector.java call site
DataXceiver.java:848, DFSClientFaultInjector.java,
qjournal/server/JournalFaultInjector.java). Each test installs an
injector subclass, drives a real minicluster through the failure, and
asserts the RECOVERY behavior — reverting the recovery code makes these
fail.
"""

import os
import threading
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.client.streams import DFSClientFaultInjector, \
    PipelineError
from hadoop_tpu.dfs.datanode.datanode import DataNodeFaultInjector
from hadoop_tpu.dfs.namenode.editlog import EditLogFaultInjector
from hadoop_tpu.dfs.qjournal import JournalFaultInjector
from hadoop_tpu.testing.minicluster import MiniDFSCluster, MiniQJMHACluster


@pytest.fixture(autouse=True)
def _reset_injectors():
    yield
    DFSClientFaultInjector.set(None)
    DataNodeFaultInjector.set(None)
    JournalFaultInjector.set(None)
    EditLogFaultInjector.set(None)


@pytest.fixture()
def cluster():
    with MiniDFSCluster(num_datanodes=4) as c:
        yield c


def test_pipeline_recovers_from_midblock_send_failure(cluster):
    """The client's whole-block recovery: a pipeline that dies mid-block
    is rebuilt (excluding the suspect) and the block replayed — the file
    lands intact. Ref: DataStreamer error paths / nextBlockOutputStream
    retry loop."""
    fs = cluster.get_filesystem()

    class Inj(DFSClientFaultInjector):
        def __init__(self):
            self.fired = False

        def before_send_packet(self, block, seq):
            if seq == 2 and not self.fired:
                self.fired = True
                raise PipelineError("injected mid-block failure")

    inj = Inj()
    DFSClientFaultInjector.set(inj)
    data = os.urandom(3 * 1024 * 1024 + 777)  # several packets, spans blocks
    with fs.create("/fi/midblock.bin") as out:
        out.write(data)
    assert inj.fired
    DFSClientFaultInjector.set(None)
    with fs.open("/fi/midblock.bin") as f:
        assert f.read() == data


def test_pipeline_survives_datanode_death_midwrite(cluster):
    """Kill a DN while a stream is mid-write: the client's recovery
    replaces the pipeline and the file lands intact. Ref: writeBlock's
    firstBadLink + DataStreamer's excludedNodes."""
    fs = cluster.get_filesystem()
    data = os.urandom(2 * 1024 * 1024)
    stream = fs.create("/fi/dnloss.bin", replication=3)
    stream.write(data[:512 * 1024])
    cluster.datanodes[0].stop()
    stream.write(data[512 * 1024:])
    stream.close()
    with fs.open("/fi/dnloss.bin") as f:
        assert f.read() == data


@pytest.fixture()
def ha_cluster():
    with MiniQJMHACluster(num_journalnodes=3, num_namenodes=2,
                          num_datanodes=3) as c:
        yield c


def test_journal_fault_on_minority_is_tolerated(ha_cluster):
    """One JN failing appends does not stop the namespace — quorum (2/3)
    acks carry the edit log. Ref: QuorumJournalManager's quorum calls."""
    fs = ha_cluster.get_filesystem()
    victim = ha_cluster.journalnodes[0].port

    class Inj(JournalFaultInjector):
        def before_journal(self, jn_port, first_txid):
            if jn_port == victim:
                raise IOError("injected journal failure")

    JournalFaultInjector.set(Inj())
    for i in range(5):
        fs.mkdirs(f"/fi/minority{i}")
    JournalFaultInjector.set(None)
    assert fs.exists("/fi/minority4")


def test_journal_fault_on_majority_fails_writes(ha_cluster):
    """Two of three JNs failing appends must surface as a namespace write
    failure (no silent data loss past quorum)."""
    fs = ha_cluster.get_filesystem()
    victims = {jn.port for jn in ha_cluster.journalnodes[:2]}

    class Inj(JournalFaultInjector):
        def before_journal(self, jn_port, first_txid):
            if jn_port in victims:
                raise IOError("injected journal failure")

    JournalFaultInjector.set(Inj())
    try:
        with pytest.raises(Exception):
            fs.mkdirs("/fi/majority")
    finally:
        JournalFaultInjector.set(None)
    # cluster recovers once the fault clears
    fs.mkdirs("/fi/after")
    assert fs.exists("/fi/after")


def test_read_corruption_injected_on_wire_fails_over(cluster):
    """corrupt_read_packet: a DN returning flipped bytes is detected by
    the client CRC check, reported, and the read fails over to a healthy
    replica. (The wire-corruption twin of the on-disk corruption test in
    test_minidfs.) Ref: BlockSender / DFSInputStream retry."""
    conf = Configuration(other=cluster.conf)
    conf.set("dfs.client.read.shortcircuit", "false")  # force the DN path
    fs = cluster.get_filesystem()
    fs.client.conf.set("dfs.client.read.shortcircuit", "false")
    data = os.urandom(300_000)
    fs.write_all("/fi/corrupt.bin", data)

    class Inj(DataNodeFaultInjector):
        def __init__(self):
            self.fired = 0

        def corrupt_read_packet(self, block, data_b, sums):
            if self.fired == 0:
                self.fired += 1
                bad = bytearray(data_b)
                bad[0] ^= 0xFF
                return bytes(bad), sums
            return data_b, sums

    inj = Inj()
    DataNodeFaultInjector.set(inj)
    try:
        with fs.open("/fi/corrupt.bin") as f:
            assert f.read() == data
        assert inj.fired == 1
    finally:
        DataNodeFaultInjector.set(None)
        fs.client.conf.set("dfs.client.read.shortcircuit", "true")


def test_editlog_sync_failure_surfaces_and_recovers(cluster):
    """An IO failure at the group-commit point surfaces to the caller;
    once the fault clears the namespace keeps working and a restart
    replays a consistent log. Ref: FSEditLog.logSync abort semantics."""
    fs = cluster.get_filesystem()

    class Inj(EditLogFaultInjector):
        def __init__(self):
            self.armed = True

        def before_sync(self, txid):
            if self.armed:
                raise IOError("injected sync failure")

    inj = Inj()
    fs.mkdirs("/fi/pre")      # healthy baseline
    EditLogFaultInjector.set(inj)
    try:
        with pytest.raises(Exception):
            fs.mkdirs("/fi/duringfault")
    finally:
        inj.armed = False
        EditLogFaultInjector.set(None)
    fs.mkdirs("/fi/post")
    assert fs.exists("/fi/pre") and fs.exists("/fi/post")
