"""Flash attention kernel parity vs the jnp reference path.

Mirrors the reference's native-vs-pure parity posture (ref: nativetask's
TestGlibc/kvtest combinatorial checks, hadoop-common
TestNativeCrc32 against the pure-Java implementation): the fused kernel
must agree with the portable implementation on values AND gradients.
Runs the Pallas kernels in interpreter mode on CPU; the same code path
compiles for TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.ops.attention import causal_attention
from hadoop_tpu.ops.flash import flash_attention, supported


def _mk(b, s, hq, hkv, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d), dtype)
    k = jax.random.normal(ks[1], (b, s, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("b,s,hq,hkv,d,bq,bk", [
    (1, 256, 2, 2, 64, 128, 128),     # MHA, multi-block
    (2, 256, 4, 2, 64, 128, 128),     # GQA 2:1
    (1, 384, 4, 1, 64, 128, 128),     # MQA, non-power-of-two blocks count
    (1, 256, 2, 2, 128, 256, 128),    # uneven bq/bk, d=128
    (1, 128, 2, 1, 64, 128, 128),     # single block (degenerate loop)
])
def test_flash_forward_matches_reference(b, s, hq, hkv, d, bq, bk):
    q, k, v = _mk(b, s, hq, hkv, d)
    ref = causal_attention(q, k, v)
    got = flash_attention(q, k, v, block_q=bq, block_k=bk, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("b,s,hq,hkv,d,bq,bk", [
    (1, 256, 2, 2, 64, 128, 128),
    (2, 256, 4, 2, 64, 128, 128),
    (1, 256, 2, 2, 128, 128, 256),
])
def test_flash_grads_match_reference(b, s, hq, hkv, d, bq, bk):
    q, k, v = _mk(b, s, hq, hkv, d, seed=7)

    def loss_ref(q, k, v):
        out = causal_attention(q, k, v)
        return jnp.sum(out * jnp.cos(out))  # non-trivial cotangent

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, block_q=bq, block_k=bk,
                              interpret=True)
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_ref, g_got):
        np.testing.assert_allclose(
            np.asarray(b_), np.asarray(a), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch")


def test_flash_bf16_close_to_f32_reference():
    q, k, v = _mk(1, 256, 4, 2, 64, seed=3)
    ref = causal_attention(q, k, v)
    got = flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                          v.astype(jnp.bfloat16), block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


def test_supported_predicate():
    assert supported((2, 2048, 16, 64), (2, 2048, 8, 64), 0, 0)
    assert not supported((2, 2048, 16, 64), (2, 1024, 8, 64), 0, 0)  # Sq!=Skv
    assert not supported((2, 2000, 16, 64), (2, 2000, 8, 64), 0, 0)  # S%128
    assert not supported((2, 2048, 16, 80), (2, 2048, 8, 80), 0, 0)  # d%64
    assert not supported((2, 2048, 16, 64), (2, 2048, 8, 64), 5, 0)  # offset
    assert not supported((2, 2048, 16, 64), (2, 2048, 8, 64),
                         jnp.array(0), 0)  # traced offset


def test_flash_under_remat_and_scan():
    """The bench path wraps attention in jax.checkpoint inside lax.scan —
    the custom-vjp kernel must survive that composition."""
    q, k, v = _mk(1, 128, 2, 2, 64, seed=11)

    def layer(x, _):
        out = flash_attention(x, k, v, interpret=True)
        return out, None

    def loss(q):
        body = jax.checkpoint(layer)
        y, _ = jax.lax.scan(body, q, jnp.arange(2))
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()


# ----------------------------------------------------- ring partials


@pytest.mark.parametrize("causal,sq,skv", [
    (True, 256, 256),     # diagonal chunk
    (False, 256, 256),    # fully-visible chunk
    (False, 128, 384),    # unequal lengths (ring shard vs rotated chunk)
])
def test_flash_partial_matches_reference(causal, sq, skv):
    from hadoop_tpu.ops.flash import _partial_ref, flash_attention_partial
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (2, sq, 4, 64), jnp.float32)
    k = jax.random.normal(ks[1], (2, skv, 2, 64), jnp.float32)
    v = jax.random.normal(ks[2], (2, skv, 2, 64), jnp.float32)
    scale = 0.125
    got_o, got_l = flash_attention_partial(q, k, v, scale, causal, True)
    ref_o, ref_l = _partial_ref(q, k, v, scale, causal)
    np.testing.assert_allclose(np.asarray(got_o), np.asarray(ref_o),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(got_l), np.asarray(ref_l),
                               atol=2e-5, rtol=2e-5)


def test_flash_partial_grads_via_reference_vjp():
    from hadoop_tpu.ops.attention import merge_attention
    from hadoop_tpu.ops.flash import _partial_ref, flash_attention_partial
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (1, 128, 2, 64), jnp.float32)
    k = jax.random.normal(ks[1], (1, 128, 2, 64), jnp.float32)
    v = jax.random.normal(ks[2], (1, 128, 2, 64), jnp.float32)

    def loss_fused(q, k, v):
        o1, l1 = flash_attention_partial(q, k, v, 0.125, True, True)
        o2, l2 = flash_attention_partial(q, k, v, 0.125, False, True)
        o, _ = merge_attention(o1, l1, o2, l2)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o1, l1 = _partial_ref(q, k, v, 0.125, True)
        o2, l2 = _partial_ref(q, k, v, 0.125, False)
        o, _ = merge_attention(o1, l1, o2, l2)
        return jnp.sum(o * o)

    got = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   atol=3e-5, rtol=3e-5)


def test_ring_attention_flash_path_matches_jnp_path():
    """The fused-partial ring must agree with the chunk/merge ring on an
    8-device CPU mesh (interpret-mode partials)."""
    from unittest import mock

    import hadoop_tpu.ops.flash as flash_mod
    from hadoop_tpu.parallel.ring_attention import ring_attention
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("sp",))
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    B, S, HQ, HKV, D = 2, 512, 4, 2, 64
    q = jax.random.normal(ks[0], (B, S, HQ, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, HKV, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, HKV, D), jnp.float32)

    real_partial = flash_mod.flash_attention_partial

    def interp_partial(q, k, v, scale, causal, interpret=False):
        return real_partial(q, k, v, scale, causal, True)

    def run(impl):
        def body(q, k, v):
            return ring_attention(q, k, v, "sp", 4, impl=impl)
        m = jax.shard_map(body, mesh=mesh,
                          in_specs=(P(None, "sp"),) * 3,
                          out_specs=P(None, "sp"))
        return jax.jit(m)(q, k, v)

    ref = run("ref")
    with mock.patch.object(flash_mod, "flash_attention_partial",
                           interp_partial):
        got = run("flash")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
