"""HA: quorum journal semantics, failover, tailing, observer reads.

Mirrors the reference's HA test posture (ref: hadoop-hdfs
TestQuorumJournalManager.java, TestEditLogTailer.java,
TestStandbyCheckpoints.java, TestFailoverWithBlockTokensEnabled /
TestHASafeMode, TestObserverNode.java): quorum commit + epoch fencing at
the journal layer, end-to-end automatic failover with a live client, and
consistent observer reads.
"""

import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.qjournal import (FencedError, JournalNode,
                                     QuorumJournalManager, QuorumLease)
from hadoop_tpu.testing.minicluster import MiniQJMHACluster, fast_conf


# ------------------------------------------------------------ journal layer

@pytest.fixture
def jns(tmp_path):
    conf = fast_conf()
    nodes = []
    for i in range(3):
        jn = JournalNode(conf, storage_dir=str(tmp_path / f"jn{i}"))
        jn.init(conf)
        jn.start()
        nodes.append(jn)
    yield nodes
    for jn in nodes:
        jn.stop()


def _addrs(jns):
    return [("127.0.0.1", j.port) for j in jns]


def _write(qjm, first, recs):
    import struct
    from hadoop_tpu.io.wire import pack
    blob = bytearray()
    for r in recs:
        data = pack(r)
        blob += struct.pack(">I", len(data)) + data
    qjm.journal(bytes(blob), first, len(recs))
    qjm.sync()


def test_quorum_write_and_read(jns):
    qjm = QuorumJournalManager(_addrs(jns))
    assert qjm.recover() == 0
    qjm.start_segment(1)
    _write(qjm, 1, [{"t": 1, "op": "mkdir", "p": "/a"},
                    {"t": 2, "op": "mkdir", "p": "/b"}])
    got = list(qjm.read_edits(1))
    assert [r["t"] for r in got] == [1, 2]
    qjm.finalize_segment(1, 2)
    qjm.close()


def test_epoch_fencing_rejects_deposed_writer(jns):
    w1 = QuorumJournalManager(_addrs(jns))
    w1.recover()
    w1.start_segment(1)
    _write(w1, 1, [{"t": 1, "op": "mkdir", "p": "/a"}])
    # A second writer takes over → w1 is fenced on its next quorum call.
    w2 = QuorumJournalManager(_addrs(jns))
    assert w2.recover() == 1
    with pytest.raises((FencedError, IOError)):
        _write(w1, 2, [{"t": 2, "op": "mkdir", "p": "/b"}])
    w2.start_segment(2)
    _write(w2, 2, [{"t": 2, "op": "mkdir", "p": "/c"}])
    assert [r["t"] for r in w2.read_edits(1)] == [1, 2]
    w1.close()
    w2.close()


def test_recovery_survives_one_jn_down(jns):
    qjm = QuorumJournalManager(_addrs(jns))
    qjm.recover()
    qjm.start_segment(1)
    _write(qjm, 1, [{"t": 1, "op": "mkdir", "p": "/a"}])
    jns[0].stop()  # majority (2/3) still up
    _write(qjm, 2, [{"t": 2, "op": "mkdir", "p": "/b"}])
    w2 = QuorumJournalManager(_addrs(jns))
    assert w2.recover() == 2
    assert [r["t"] for r in w2.read_edits(1)] == [1, 2]
    qjm.close()
    w2.close()


def _blob(recs):
    import struct
    from hadoop_tpu.io.wire import pack
    out = bytearray()
    for r in recs:
        data = pack(r)
        out += struct.pack(">I", len(data)) + data
    return bytes(out)


def test_recovery_syncs_laggard_past_fetch_cap(jns, tmp_path):
    """A JN lagging by more records than one get_edits call can carry must
    be fully caught up — never given a finalized segment with holes (ref:
    JournalNodeSyncer transfers whole segments; regression for the
    partial-sync-then-finalize bug)."""
    conf = fast_conf()
    qjm = QuorumJournalManager(_addrs(jns))
    qjm.recover()
    qjm.start_segment(1)
    _write(qjm, 1, [{"t": t, "op": "mkdir", "p": f"/d{t}"}
                    for t in range(1, 4)])
    jns[2].stop()
    _write(qjm, 4, [{"t": t, "op": "mkdir", "p": f"/d{t}"}
                    for t in range(4, 121)])
    qjm.close()
    # Restart the laggard (same storage, fresh port).
    jn2 = JournalNode(conf, storage_dir=jns[2].storage_dir)
    jn2.init(conf)
    jn2.start()
    try:
        addrs = _addrs(jns[:2]) + [("127.0.0.1", jn2.port)]
        w2 = QuorumJournalManager(addrs)
        w2._fetch_batch = 10   # force many fetch round-trips
        assert w2.recover() == 120
        w2.close()
        # The laggard itself must now hold every txid, contiguously.
        got = [r["t"] for r in jn2.get_journal("ns").fjm.read_edits(1)]
        assert got == list(range(1, 121))
        # And the quorum must be able to serve the whole tail even with
        # the most advanced original JN gone.
        jns[0].stop()
        reader = QuorumJournalManager(
            [("127.0.0.1", jns[1].port), ("127.0.0.1", jn2.port)])
        assert [r["t"] for r in reader.read_edits(1)] == list(range(1, 121))
        reader.close()
    finally:
        jn2.stop()


def test_stale_divergent_record_cannot_shadow_quorum(jns):
    """A JN that slept through a recovery and kept a deposed writer's
    divergent record for a txid must not have its copy served to tailers
    over the quorum's adopted copy (ref: acceptRecovery's rewrite; the
    read path prefers the highest segment epoch)."""
    from hadoop_tpu.dfs.qjournal import JournalProtocol
    conf = fast_conf()
    w1 = QuorumJournalManager(_addrs(jns))
    w1.recover()
    w1.start_segment(1)
    _write(w1, 1, [{"t": 1, "op": "mkdir", "p": "/a"}])
    # The deposed writer got txid 2 onto ONE journal only (no quorum ack).
    JournalProtocol(jns[2]).journal(
        "ns", w1.epoch, _blob([{"t": 2, "op": "mkdir", "p": "/stale"}]),
        2, 1, 2)
    jns[2].stop()
    # New writer recovers without that JN and rewrites txid 2.
    w2 = QuorumJournalManager(_addrs(jns[:2]) + [("127.0.0.1", 1)])
    assert w2.recover() == 1
    w2.start_segment(2)
    _write(w2, 2, [{"t": 2, "op": "mkdir", "p": "/new"}])
    w2.close()
    w1.close()
    # The stale JN resurfaces; a tailer reading the quorum must see the
    # adopted content for txid 2, not the deposed writer's.
    jn2 = JournalNode(conf, storage_dir=jns[2].storage_dir)
    jn2.init(conf)
    jn2.start()
    try:
        reader = QuorumJournalManager(
            _addrs(jns[:2]) + [("127.0.0.1", jn2.port)])
        got = list(reader.read_edits(1))
        assert [r["t"] for r in got] == [1, 2]
        assert got[1]["p"] == "/new"
        assert "_e" not in got[1]
        reader.close()
    finally:
        jn2.stop()


def test_uncommitted_mixed_epoch_copies_do_not_fake_quorum(jns):
    """A lone newest-epoch proposal plus an unrelated stale-epoch copy of
    the same txid must not count as a served majority: tailers apply a
    txid only when it is at/below the piggybacked commit point or a
    majority holds it AT the same epoch (ref: committedTxnId gating in
    getJournaledEdits)."""
    from hadoop_tpu.dfs.qjournal import JournalProtocol
    w1 = QuorumJournalManager(_addrs(jns))
    w1.recover()
    w1.start_segment(1)
    _write(w1, 1, [{"t": 1, "op": "mkdir", "p": "/a"}])
    # Deposed writer leaves an uncommitted txid 2 on jn2 only, and jn2
    # then sleeps through the next recovery.
    JournalProtocol(jns[2]).journal(
        "ns", w1.epoch, _blob([{"t": 2, "op": "mkdir", "p": "/stale"}]),
        2, 1, 2)
    jns[2].stop()
    w1.close()
    # New writer recovers without jn2 (adopts tail=1), then dies after
    # landing its own txid 2 on ONE journal without a quorum ack.
    conf = fast_conf()
    w2 = QuorumJournalManager(_addrs(jns[:2]) + [("127.0.0.1", 1)])
    assert w2.recover() == 1
    w2.start_segment(2)
    JournalProtocol(jns[0]).journal(
        "ns", w2.epoch, _blob([{"t": 2, "op": "mkdir", "p": "/new"}]),
        2, 1, 2)
    w2.close()
    # jn2 resurfaces with its stale copy.
    jn2 = JournalNode(conf, storage_dir=jns[2].storage_dir)
    jn2.init(conf)
    jn2.start()
    addrs = _addrs(jns[:2]) + [("127.0.0.1", jn2.port)]
    try:
        # Tailers must stop at txid 1: txid 2 has one copy at epoch 2 and
        # one stale copy at epoch 1 — no same-epoch majority, no commit
        # point covering it.
        reader = QuorumJournalManager(addrs)
        assert [r["t"] for r in reader.read_edits(1)] == [1]
        reader.close()
        # The next recovery adopts the newest-epoch proposal; only then is
        # txid 2 committed and served — with the adopted content.
        w3 = QuorumJournalManager(addrs)
        assert w3.recover() == 2
        got = list(w3.read_edits(1))
        assert [r["t"] for r in got] == [1, 2]
        assert got[1]["p"] == "/new"
        w3.close()
    finally:
        jn2.stop()


def test_journal_refuses_gap_creating_segment(jns):
    """A JN that missed txids must refuse to open a later segment: the
    newest-epoch stamp on an empty tail would outrank complete peers at
    the next recovery's adoption and destroy committed edits (review
    finding; ref: the reference's startLogSegment txid continuity
    checks)."""
    from hadoop_tpu.dfs.qjournal import JournalProtocol
    w1 = QuorumJournalManager(_addrs(jns))
    w1.recover()
    w1.start_segment(1)
    _write(w1, 1, [{"t": t, "op": "mkdir", "p": f"/d{t}"}
                   for t in (1, 2, 3)])
    w1.finalize_segment(1, 3)
    p0 = JournalProtocol(jns[0])
    with pytest.raises(IOError, match="gap"):
        p0.start_segment("ns", w1.epoch, 8)  # 4..7 never existed
    w1.close()


def test_recovery_refuses_tail_with_holes(jns):
    """If the adopted tail cannot be fully reconstructed from responders,
    recovery must fail rather than adopt a log with missing txids (ref:
    the reference never finalizes a segment it hasn't fully transferred).
    The API refuses to create gaps, so the hole is disk damage: the
    middle segment file vanishes from every JN."""
    import glob
    import os
    w1 = QuorumJournalManager(_addrs(jns))
    w1.recover()
    w1.start_segment(1)
    _write(w1, 1, [{"t": t, "op": "mkdir", "p": f"/d{t}"}
                   for t in (1, 2, 3)])
    w1.finalize_segment(1, 3)
    w1.start_segment(4)
    _write(w1, 4, [{"t": t, "op": "mkdir", "p": f"/d{t}"}
                   for t in (4, 5, 6, 7)])
    w1.finalize_segment(4, 7)
    w1.start_segment(8)
    _write(w1, 8, [{"t": t, "op": "mkdir", "p": f"/d{t}"}
                   for t in (8, 9, 10)])
    w1.close()
    for jn in jns:
        for p in glob.glob(os.path.join(jn.storage_dir, "ns",
                                        "edits_4-7")):
            os.remove(p)  # txids 4..7 gone everywhere
    w2 = QuorumJournalManager(_addrs(jns))
    with pytest.raises(IOError):
        w2.recover()
    w2.close()


def test_recovery_adoption_respects_committed_floor(jns):
    """A responder whose accept failed can carry the newest promise while
    missing committed txids; adoption must skip it for a peer that holds
    everything the writer quorum-acked (review finding: the old rule
    keyed on (tail_epoch, last) alone could adopt the short tail and
    destroy acked edits)."""
    w1 = QuorumJournalManager(_addrs(jns))
    w1.recover()
    w1.start_segment(1)
    _write(w1, 1, [{"t": t, "op": "mkdir", "p": f"/d{t}"}
                   for t in (1, 2, 3)])
    # jn0 misses the second batch: stop it, write 4..6 on {jn1, jn2}
    # (quorum ack ⇒ committed), restart jn0.
    store0 = jns[0].storage_dir
    jns[0].stop()
    _write(w1, 4, [{"t": t, "op": "mkdir", "p": f"/d{t}"}
                   for t in (4, 5, 6)])
    w1.close()
    from hadoop_tpu.dfs.qjournal import JournalNode
    from hadoop_tpu.testing.minicluster import fast_conf
    jn0 = JournalNode(fast_conf(), storage_dir=store0)
    jn0.init(fast_conf())
    jn0.start()
    try:
        # New writer recovers: jn0's tail (last=3) is SHORT of the
        # committed floor (6) — adoption must come from jn1/jn2, and the
        # recovered log must retain every acked txid.
        w2 = QuorumJournalManager(_addrs([jn0, jns[1], jns[2]]))
        assert w2.recover() == 6
        seen = [r["t"] for r in w2.read_edits(1)]
        assert seen == [1, 2, 3, 4, 5, 6]
        w2.close()
    finally:
        jn0.stop()


def test_quorum_lease_single_winner(jns):
    a = QuorumLease(_addrs(jns), holder="nn1", ttl_s=2.0)
    b = QuorumLease(_addrs(jns), holder="nn2", ttl_s=2.0)
    try:
        assert a.try_acquire()
        assert not b.try_acquire()
        a.release()
        assert b.try_acquire()
    finally:
        a.close()
        b.close()


# ------------------------------------------------------------- HA cluster

@pytest.fixture
def ha_cluster(tmp_path):
    cluster = MiniQJMHACluster(num_journalnodes=3, num_namenodes=2,
                               num_datanodes=3,
                               base_dir=str(tmp_path)).start()
    cluster.wait_active()
    yield cluster
    cluster.shutdown()


def test_automatic_election_and_standby_rejects(ha_cluster):
    idx = ha_cluster.wait_active()
    states = [nn.ha_state for nn in ha_cluster.namenodes]
    assert states.count("active") == 1
    assert states.count("standby") == 1
    # Standby rejects reads AND writes with StandbyError.
    from hadoop_tpu.ipc import Client, get_proxy
    from hadoop_tpu.ipc.errors import StandbyError
    standby = ha_cluster.namenodes[1 - idx]
    client = Client(fast_conf())
    try:
        proxy = get_proxy("ClientProtocol", ("127.0.0.1", standby.port),
                          client=client)
        with pytest.raises(StandbyError):
            proxy.mkdirs("/nope")
        with pytest.raises(StandbyError):
            proxy.listing("/")
    finally:
        client.stop()


def test_standby_tails_edits(ha_cluster):
    idx = ha_cluster.wait_active()
    fs = ha_cluster.get_filesystem()
    fs.mkdirs("/tailed/dir")
    with fs.create("/tailed/f.txt") as out:
        out.write(b"hello standby")
    standby = ha_cluster.namenodes[1 - idx]
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if standby.fsn.fsdir.exists("/tailed/f.txt"):
            break
        time.sleep(0.1)
    inode = standby.fsn.fsdir.get_inode("/tailed/f.txt")
    assert inode is not None, "standby never tailed the create"
    assert inode.length() == len(b"hello standby")


def test_failover_on_active_crash_client_continues(ha_cluster):
    ha_cluster.wait_active()
    fs = ha_cluster.get_filesystem()
    with fs.create("/ha/before.txt") as out:
        out.write(b"written before failover")
    old_idx = ha_cluster.kill_active()
    # The survivor should win the lease and promote itself.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if ha_cluster.active_index() is not None:
            break
        time.sleep(0.1)
    new_idx = ha_cluster.active_index()
    assert new_idx is not None and new_idx != old_idx
    # Same client keeps working: reads of old data and fresh writes.
    with fs.open("/ha/before.txt") as f:
        assert f.read() == b"written before failover"
    with fs.create("/ha/after.txt") as out:
        out.write(b"written after failover")
    with fs.open("/ha/after.txt") as f:
        assert f.read() == b"written after failover"


def test_demoted_active_is_fenced(ha_cluster):
    idx = ha_cluster.wait_active()
    active = ha_cluster.namenodes[idx]
    fs = ha_cluster.get_filesystem()
    fs.mkdirs("/fence")
    # Force a manual demotion + promotion of the peer.
    standby = ha_cluster.namenodes[1 - idx]
    active.transition_to_standby()
    standby.transition_to_active()
    assert standby.ha_state == "active"
    # The old active's journal epoch is stale; direct writes via its
    # namesystem must fail at the quorum.
    with pytest.raises(Exception):
        active.fsn.mkdirs("/fence/stale-write")
    # The cluster still works through the new active.
    fs.mkdirs("/fence/ok")
    assert fs.get_file_status("/fence/ok").is_dir


def test_demote_then_repromote_same_node(ha_cluster):
    """A demoted active must keep tailing through the same quorum journal
    and be fully re-promotable (exercises close_segment keeping the QJM
    alive rather than shutting its pools)."""
    idx = ha_cluster.wait_active()
    a, b = ha_cluster.namenodes[idx], ha_cluster.namenodes[1 - idx]
    fs = ha_cluster.get_filesystem()
    fs.mkdirs("/flip/one")
    a.transition_to_standby()
    b.transition_to_active()
    fs.mkdirs("/flip/two")
    # The demoted node tails the new active's write...
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if a.fsn.fsdir.exists("/flip/two"):
            break
        time.sleep(0.1)
    assert a.fsn.fsdir.exists("/flip/two"), "demoted NN stopped tailing"
    # ...and comes back as a working active.
    b.transition_to_standby()
    a.transition_to_active()
    fs.mkdirs("/flip/three")
    assert a.fsn.fsdir.exists("/flip/three")
    for p in ("/flip/one", "/flip/two", "/flip/three"):
        assert fs.get_file_status(p).is_dir


@pytest.fixture
def observer_cluster(tmp_path):
    cluster = MiniQJMHACluster(num_journalnodes=3, num_namenodes=2,
                               num_datanodes=3, num_observers=1,
                               base_dir=str(tmp_path)).start()
    cluster.wait_active()
    yield cluster
    cluster.shutdown()


def test_observer_serves_aligned_reads(observer_cluster):
    cluster = observer_cluster
    observer = cluster.namenodes[2]
    assert observer.ha_state == "observer"
    fs = cluster.get_filesystem(observer_reads=True)
    with fs.create("/obs/data.txt") as out:
        out.write(b"observed")
    # The read goes to the observer (msync seeded the state id, so the
    # observer waits until it has tailed the create before answering).
    st = fs.get_file_status("/obs/data.txt")
    assert st.length == len(b"observed")
    with fs.open("/obs/data.txt") as f:
        assert f.read() == b"observed"
    # Sanity: the observer really has the file (it tailed it).
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if observer.fsn.fsdir.exists("/obs/data.txt"):
            break
        time.sleep(0.1)
    assert observer.fsn.fsdir.exists("/obs/data.txt")
