"""Hedged reads: a slow DataNode must not stall reads.

Mirrors the reference's TestPread.testHedgedPreadDFSBasic /
testMaxOutHedgedReadPool (ref: hadoop-hdfs TestPread.java): with the
hedged pool enabled, a read whose first replica is slow completes from
another replica, and the hedged metrics move.

Determinism: the slow replica BLOCKS on an event the test only sets
after the read has returned — there is no wall-clock sleep to race and
no elapsed-time assertion to flake under full-suite load (VERDICT
round-5 weak #1: the old 30s-sleep/20s-bound version still depended on
the hedge beating a timer on a loaded core). If the hedge never fired,
the read would hang on the blocked replica and the test would fail by
timeout, not by a margin.
"""

import os
import threading

import pytest

from hadoop_tpu.dfs.datanode.datanode import DataNodeFaultInjector
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf


class _BlockFirstReplica(DataNodeFaultInjector):
    """Block the FIRST read attempt (whichever replica the client
    picks) on an event; the hedge that follows is served at full
    speed. ``release()`` unblocks the stalled replica thread so it can
    run to completion (losers are abandoned, not joined)."""

    def __init__(self):
        self.hits = 0
        self._lock = threading.Lock()
        self._gate = threading.Event()
        self.blocked = threading.Event()

    def before_read_block(self, block, port: int = 0) -> None:
        with self._lock:
            self.hits += 1
            first = self.hits == 1
        if first:
            self.blocked.set()
            # generous ceiling so an aborted test run cannot leak a
            # forever-parked xceiver thread; the PASSING path never
            # waits on it
            self._gate.wait(timeout=120.0)

    def release(self) -> None:
        self._gate.set()


@pytest.fixture()
def cluster(tmp_path):
    conf = fast_conf()
    conf.set("dfs.replication", "2")
    conf.set("dfs.client.read.shortcircuit", "false")  # force TCP reads
    conf.set("dfs.client.hedged.read.threadpool.size", "4")
    # the threshold only delays the hedge's START; correctness no
    # longer depends on any upper time bound
    conf.set("dfs.client.hedged.read.threshold", "0.05")
    with MiniDFSCluster(num_datanodes=2, conf=conf,
                        base_dir=str(tmp_path)) as c:
        c.wait_active()
        yield c


def test_slow_replica_does_not_stall_read(cluster):
    fs = cluster.get_filesystem()
    payload = os.urandom(100_000)
    fs.write_all("/hedge.bin", payload)

    injector = _BlockFirstReplica()
    DataNodeFaultInjector.set(injector)
    try:
        # the first replica thread parks on the gate; the ONLY way this
        # read returns the payload is the hedge completing from the
        # second replica
        assert fs.read_all("/hedge.bin") == payload
        assert injector.blocked.is_set(), \
            "first replica was never attempted"
        assert injector.hits >= 2, "hedge never reached the second replica"
        assert fs.client.hedged_reads >= 1
        assert fs.client.hedged_wins >= 1
    finally:
        injector.release()  # let the parked loser thread finish
        DataNodeFaultInjector.set(None)


def test_hedged_read_correct_when_all_healthy(cluster):
    fs = cluster.get_filesystem()
    payload = os.urandom(50_000)
    fs.write_all("/hedge2.bin", payload)
    assert fs.read_all("/hedge2.bin") == payload
    with fs.open("/hedge2.bin") as f:
        assert f.pread(10_000, 256) == payload[10_000:10_256]
