"""Hedged reads: a slow DataNode must not stall reads.

Mirrors the reference's TestPread.testHedgedPreadDFSBasic /
testMaxOutHedgedReadPool (ref: hadoop-hdfs TestPread.java): with the
hedged pool enabled, a read whose first replica is slow completes from
another replica in ~threshold time, and the hedged metrics move.
"""

import os
import threading
import time

import pytest

from hadoop_tpu.dfs.datanode.datanode import DataNodeFaultInjector
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf


class _SlowFirstReplica(DataNodeFaultInjector):
    """Delay the FIRST read attempt (whichever replica the client
    picks); the hedge that follows is served at full speed."""

    def __init__(self, delay_s: float):
        self.delay_s = delay_s
        self.hits = 0
        self._lock = threading.Lock()

    def before_read_block(self, block, port: int = 0) -> None:
        with self._lock:
            self.hits += 1
            first = self.hits == 1
        if first:
            time.sleep(self.delay_s)


@pytest.fixture()
def cluster(tmp_path):
    conf = fast_conf()
    conf.set("dfs.replication", "2")
    conf.set("dfs.client.read.shortcircuit", "false")  # force TCP reads
    conf.set("dfs.client.hedged.read.threadpool.size", "4")
    conf.set("dfs.client.hedged.read.threshold", "0.15")
    with MiniDFSCluster(num_datanodes=2, conf=conf,
                        base_dir=str(tmp_path)) as c:
        c.wait_active()
        yield c


def test_slow_replica_does_not_stall_read(cluster):
    fs = cluster.get_filesystem()
    payload = os.urandom(100_000)
    fs.write_all("/hedge.bin", payload)

    injector = _SlowFirstReplica(delay_s=30.0)
    DataNodeFaultInjector.set(injector)
    try:
        t0 = time.monotonic()
        assert fs.read_all("/hedge.bin") == payload
        elapsed = time.monotonic() - t0
        # Unhedged this takes >= delay_s (30s); hedged it finishes around
        # the 0.15s threshold + transfer time. The sleeping replica thread
        # is abandoned, not joined, so the big delay costs no wall time in
        # the passing case — it only widens the pass/fail gap so the
        # decision stays unambiguous even when the whole suite shares one
        # loaded core (this test once flaked at an 8s-delay/6s-bound
        # margin while a 1B-parameter bench ran beside it).
        assert elapsed < 20.0, f"read took {elapsed:.2f}s — hedge did not fire"
        assert injector.hits >= 2, "hedge never reached the second replica"
        assert fs.client.hedged_reads >= 1
        assert fs.client.hedged_wins >= 1
    finally:
        DataNodeFaultInjector.set(None)


def test_hedged_read_correct_when_all_healthy(cluster):
    fs = cluster.get_filesystem()
    payload = os.urandom(50_000)
    fs.write_all("/hedge2.bin", payload)
    assert fs.read_all("/hedge2.bin") == payload
    with fs.open("/hedge2.bin") as f:
        assert f.pread(10_000, 256) == payload[10_000:10_256]
