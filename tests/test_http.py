"""HTTP surface: admin servlets, WebHDFS REST, RM web status.

Mirrors the reference tests (ref: hadoop-common TestHttpServer.java,
hadoop-hdfs TestWebHDFS.java, yarn TestRMWebServices)."""

import json
import urllib.request

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.http import HttpServer
from hadoop_tpu.testing.minicluster import (MiniDFSCluster,
                                            MiniYARNCluster, fast_conf)


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        body = r.read()
        ctype = r.headers.get("Content-Type", "")
        return (r.status, json.loads(body) if "json" in ctype else body)


def _req(url: str, method: str, data: bytes = b""):
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read() or b"{}")


def test_standard_servlets():
    conf = Configuration(load_defaults=False)
    conf.set("test.key", "test.value")
    srv = HttpServer(conf, daemon_name="unit")
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        st, health = _get(f"{base}/health")
        assert st == 200 and health["status"] == "alive"
        st, beans = _get(f"{base}/jmx")
        assert st == 200 and "beans" in beans
        st, cfg = _get(f"{base}/conf")
        assert cfg.get("test.key") == "test.value"
        st, stacks = _get(f"{base}/stacks")
        assert b"Thread" in stacks
        with pytest.raises(urllib.error.HTTPError):
            _get(f"{base}/nope")
    finally:
        srv.stop()


@pytest.fixture(scope="module")
def cluster():
    with MiniDFSCluster(num_datanodes=3) as c:
        c.wait_active()
        yield c


def test_webhdfs_roundtrip(cluster):
    base = (f"http://127.0.0.1:{cluster.namenode.http.port}"
            f"/webhdfs/v1")
    # no user.name → the unprivileged dr.who default: a write into the
    # root-owned tree must be DENIED (the REST door honors the same
    # permission model as RPC; ref: NamenodeWebHdfsMethods ugi.doAs)
    with pytest.raises(urllib.error.HTTPError) as denied:
        _req(f"{base}/web/anon?op=MKDIRS", "PUT")
    assert denied.value.code == 403  # AccessControlException → Forbidden
    assert "AccessControlError" in denied.value.read().decode()
    st, _ = _req(f"{base}/web/dir?op=MKDIRS&user.name=root", "PUT")
    assert st == 200
    payload = b"webhdfs payload bytes"
    st, _ = _req(f"{base}/web/dir/f.bin?op=CREATE&user.name=root",
                 "PUT", payload)
    assert st == 201
    st, info = _get(f"{base}/web/dir/f.bin?op=GETFILESTATUS")
    assert info["FileStatus"]["length"] == len(payload)
    assert info["FileStatus"]["type"] == "FILE"
    st, data = _get(f"{base}/web/dir/f.bin?op=OPEN")
    assert data == payload
    st, data = _get(f"{base}/web/dir/f.bin?op=OPEN&offset=8&length=7")
    assert data == payload[8:15]
    st, ls = _get(f"{base}/web/dir?op=LISTSTATUS")
    names = [e["pathSuffix"] for e in ls["FileStatuses"]["FileStatus"]]
    assert names == ["f.bin"]
    st, cs = _get(f"{base}/web?op=GETCONTENTSUMMARY")
    assert cs["ContentSummary"]["fileCount"] == 1
    st, _ = _req(f"{base}/web/dir/f.bin?op=RENAME&"
                 f"destination=/web/dir/g.bin&user.name=root", "PUT")
    st, _ = _req(f"{base}/web/dir/g.bin?op=DELETE&user.name=root",
                 "DELETE")
    st, ls = _get(f"{base}/web/dir?op=LISTSTATUS")
    assert ls["FileStatuses"]["FileStatus"] == []


def test_webhdfs_errors(cluster):
    base = (f"http://127.0.0.1:{cluster.namenode.http.port}"
            f"/webhdfs/v1")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(f"{base}/no/such/file?op=GETFILESTATUS")
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(f"{base}/x?op=BOGUS", "PUT")
    assert ei.value.code == 400


def test_namenode_jmx_has_metrics():
    # Own cluster: the autouse conftest fixture resets the process-global
    # metrics system between tests, so module-scoped sources vanish.
    with MiniDFSCluster(num_datanodes=1) as c:
        c.wait_active()
        base = f"http://127.0.0.1:{c.namenode.http.port}"
        st, beans = _get(f"{base}/jmx?qry=namenode")
        names = [b["name"] for b in beans["beans"]]
        assert any("namenode" in n for n in names)


def test_rm_web_status():
    with MiniYARNCluster(num_nodes=2) as yc:
        yc.wait_nodes()
        base = f"http://127.0.0.1:{yc.rm.http.port}"
        st, info = _get(f"{base}/ws/v1/cluster/info")
        assert info["num_node_managers"] == 2
        st, nodes = _get(f"{base}/ws/v1/cluster/nodes")
        assert len(nodes["nodes"]) == 2
        st, apps = _get(f"{base}/ws/v1/cluster/apps")
        assert apps["apps"] == []


def test_daemon_web_ui_pages(tmp_path):
    """The daemons' human pages (ref: the RM webapp + dfshealth.html):
    HTML renders with live numbers from both masters."""
    import urllib.request

    from hadoop_tpu.testing.minicluster import (MiniDFSCluster,
                                                MiniYARNCluster, fast_conf)

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path / "dfs")) as dfs:
        dfs.wait_active()
        dfs.get_filesystem().write_all("/ui.bin", b"x" * 10_000)
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{dfs.namenode.http.port}/dfshealth"
        ).read().decode()
        assert "NameNode" in page and "Datanodes (1)" in page
        assert "active" in page.lower()

    with MiniYARNCluster(num_nodes=2,
                         base_dir=str(tmp_path / "yarn")) as yarn:
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{yarn.rm.http.port}/cluster"
        ).read().decode()
        assert "ResourceManager" in page and "Nodes (2)" in page


def test_webhdfs_percent_encoded_paths_and_streaming(tmp_path):
    """REST contract: percent-encoded paths decode ('a%20b' names
    'a b'), and OPEN streams chunked so big files never materialize in
    the NameNode process (review findings)."""
    import http.client
    import os as _os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as c:
        c.wait_active()
        port = c.namenode.http.port
        payload = _os.urandom(300_000)

        conn = http.client.HTTPConnection("127.0.0.1", port)
        conn.request("PUT", "/webhdfs/v1/dir/a%20b?op=CREATE&user.name=root",
                     body=payload)
        assert conn.getresponse().read() and True
        # the native client sees the DECODED name
        fs = c.get_filesystem()
        assert fs.read_all("/dir/a b") == payload

        conn.request("GET", "/webhdfs/v1/dir/a%20b?op=OPEN")
        resp = conn.getresponse()
        assert resp.status == 200
        body = resp.read()  # http.client de-chunks transparently
        assert body == payload
        conn.close()


def test_ifile_rejects_sentinel_colliding_keys(monkeypatch):
    """A key whose length-vint would alias the EOF marker is refused at
    write time — read-side it would silently truncate the segment
    (review finding)."""
    import pytest as _p

    from hadoop_tpu.mapreduce import ifile

    monkeypatch.setattr(ifile, "_MAX_KEY_LEN", 64)
    with _p.raises(ValueError, match="key"):
        ifile.encode_records([(b"k" * 64, b"v")])
    with _p.raises(ValueError, match="key"):
        ifile.write_partitioned_streams("/dev/null",
                                        [iter([(b"k" * 64, b"v")])])


def test_ws_conf_lever_table():
    """/ws/v1/conf: the registry joined with the live conf — overridden
    keys diffed out, lever annotations attached, set-but-unregistered
    keys surfaced, credentials redacted (same rule as /conf)."""
    conf = Configuration(load_defaults=False)
    conf.set("dfs.blocksize", "64m")            # registered override
    conf.set("serving.max.lanes", "32")         # registered, has a lever
    conf.set("totally.unknown.key", "x")        # not in the registry
    conf.set("serving.http.auth.secret", "s3"); # registered + redacted
    srv = HttpServer(conf, daemon_name="unit")
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        st, table = _get(f"{base}/ws/v1/conf")
        assert st == 200
        assert table["registry_keys"] > 300
        rows = {r["key"]: r for r in table["keys"]}
        assert rows["dfs.blocksize"]["source"] == "set"
        assert rows["dfs.blocksize"]["effective"] == "64m"
        assert rows["dfs.blocksize"]["type"] == "size"
        # unset keys report their registry default, no effective value
        assert rows["dfs.replication"]["source"] == "default"
        assert rows["dfs.replication"]["effective"] is None
        lever = rows["serving.max.lanes"]["lever"]
        assert lever["guard"] == "capacity" and lever["range"] == [1, 256]
        assert rows["serving.http.auth.secret"]["effective"] == "<redacted>"
        assert "dfs.blocksize" in table["overridden"]
        unreg = {u["key"] for u in table["unregistered"]}
        assert unreg == {"totally.unknown.key"}
        # ?diff=1 keeps only the overridden rows
        st, diff = _get(f"{base}/ws/v1/conf?diff=1")
        assert {r["key"] for r in diff["keys"]} == set(table["overridden"])
    finally:
        srv.stop()
