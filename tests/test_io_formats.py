"""Tests for codecs + SequenceFile/MapFile (ref test model:
hadoop-common/src/test .../io/TestSequenceFile.java, compress/TestCodec.java)."""

import io

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs.filesystem import LocalFileSystem
from hadoop_tpu.io import sequencefile as sf
from hadoop_tpu.io.codecs import CodecFactory, ZstdCodec


@pytest.mark.parametrize("name", ["zlib", "gzip", "bzip2", "lzma"])
def test_codec_roundtrip(name):
    codec = CodecFactory.get(name)
    data = b"the quick brown fox " * 1000
    comp = codec.compress(data)
    assert len(comp) < len(data)
    assert codec.decompress(comp) == data


def test_zstd_if_available():
    if not ZstdCodec.available():
        pytest.skip("libzstd not present")
    codec = CodecFactory.get("zstd")
    data = b"abc" * 10000
    assert codec.decompress(codec.compress(data)) == data


def test_codec_by_extension():
    assert CodecFactory.by_extension("/a/b.gz").name == "gzip"
    assert CodecFactory.by_extension("/a/b.txt") is None


def test_streaming_codec_faces():
    codec = CodecFactory.get("zlib")
    sink = io.BytesIO()
    out = codec.wrap_output(_NoClose(sink))
    payload = b"0123456789" * 100000
    out.write(payload)
    out.close()
    src = codec.wrap_input(io.BytesIO(sink.getvalue()))
    got = src.read()
    assert got == payload


class _NoClose:
    def __init__(self, inner):
        self._inner = inner

    def write(self, b):
        return self._inner.write(b)

    def close(self):
        pass


records = [(f"key{i:05d}".encode(), b"value" * (i % 7) + str(i).encode())
           for i in range(2000)]


@pytest.mark.parametrize("compression,codec", [
    (sf.NONE, "zlib"), (sf.RECORD, "zlib"), (sf.BLOCK, "zlib"),
    (sf.BLOCK, "bzip2"),
])
def test_sequencefile_roundtrip(compression, codec):
    sink = io.BytesIO()
    w = sf.Writer(_NoClose(sink), compression=compression, codec=codec,
                  metadata={"who": "test"})
    for k, v in records:
        w.append(k, v)
    w.close()
    r = sf.Reader(io.BytesIO(sink.getvalue()))
    assert r.compression == compression
    assert r.metadata == {"who": "test"}
    assert list(r) == records


def test_sequencefile_detects_bad_magic():
    with pytest.raises(IOError):
        sf.Reader(io.BytesIO(b"JUNKJUNKJUNK"))


def test_mapfile(tmp_path):
    fs = LocalFileSystem(Configuration(load_defaults=False))
    path = str(tmp_path / "map")
    w = sf.MapFileWriter(fs, path)
    for k, v in records:
        w.append(k, v)
    w.close()
    r = sf.MapFileReader(fs, path)
    assert r.get(b"key00123") == records[123][1]
    assert r.get(b"nope") is None
    with pytest.raises(ValueError):
        w2 = sf.MapFileWriter(fs, str(tmp_path / "m2"))
        w2.append(b"b", b"")
        w2.append(b"a", b"")


def test_lz4_snappy_codecs_roundtrip_and_reject_garbage():
    """Native lz4/snappy bindings (ref: the reference's bundled lz4.c /
    snappy JNI glue): roundtrip integrity, incompressible data safety,
    and garbage rejection instead of junk output."""
    import os as _os

    import pytest as _pytest

    from hadoop_tpu.io.codecs import CodecFactory, Lz4Codec, SnappyCodec
    assert Lz4Codec.available() and SnappyCodec.available()
    for name in ("lz4", "snappy"):
        codec = CodecFactory.get(name)
        for payload in (b"", b"a", b"abc" * 50_000,
                        _os.urandom(256 * 1024)):
            assert codec.decompress(codec.compress(payload)) == payload
        with _pytest.raises(IOError):
            codec.decompress(b"\xff\xfe\xfd\xfc" * 10)


def test_spill_codec_policy():
    """Spill compression is off by default (like the reference); when a
    job opts in without naming a codec, the CLIENT resolves the lz4
    default into the job conf at submission (Job.submit) so every task
    sees the same name — task-side resolution is conf-driven only."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.io.codecs import Lz4Codec
    from hadoop_tpu.mapreduce.job import Job
    from hadoop_tpu.mapreduce.task_runner import _spill_codec

    conf = Configuration(load_defaults=False)
    assert _spill_codec(conf) is None            # off by default (ref)
    conf.set("mapreduce.map.output.compress", "true")
    # tasks never probe the host: absent a resolved codec they use the
    # deterministic zlib fallback
    assert _spill_codec(conf) == "zlib"
    conf.set("mapreduce.map.output.compress.codec", "zstd")
    assert _spill_codec(conf) == "zstd"
    conf.set("mapreduce.map.output.compress", "false")
    assert _spill_codec(conf) is None

    # the submission-side default: compress on, no codec named → the
    # client picks lz4 when IT has the library
    job = Job(("127.0.0.1", 1), "file:///tmp") \
        .set("mapreduce.map.output.compress", "true")
    try:
        job.submit()
    except Exception:
        pass  # no cluster: only the conf resolution step matters here
    assert job.conf.get("mapreduce.map.output.compress.codec") == \
        ("lz4" if Lz4Codec.available() else "zlib")


class _DribbleStream:
    """Returns at most ``k`` bytes per read — a remote-FS-style stream."""

    def __init__(self, data: bytes, k: int = 3):
        self._d = data
        self._off = 0
        self._k = k

    def read(self, n: int = -1) -> bytes:
        if self._off >= len(self._d):
            return b""
        take = min(self._k, n if n >= 0 else self._k,
                   len(self._d) - self._off)
        out = self._d[self._off:self._off + take]
        self._off += take
        return out

    def close(self):
        pass


def test_codec_stream_survives_short_reads():
    """Block-codec framing over a stream that dribbles bytes: full
    payload back, no silent truncation (review finding — a short header
    read was treated as clean EOF)."""
    import io as _io

    from hadoop_tpu.io.codecs import CodecFactory

    codec = CodecFactory.get("zlib")
    payload = b"0123456789abcdef" * 500
    sink = _io.BytesIO()
    sink.close = lambda: None  # keep the buffer readable
    out = codec.wrap_output(sink)
    out.write(payload)
    out.close()
    framed = sink.getvalue()

    got = codec.wrap_input(_DribbleStream(framed)).read(-1)
    assert got == payload

    # an actually-truncated stream errors instead of returning a prefix
    import pytest as _p
    with _p.raises(IOError, match="truncated"):
        codec.wrap_input(_DribbleStream(framed[:-5])).read(-1)


def test_sequencefile_reader_survives_short_reads(tmp_path):
    """Reader header/sync parsing over a dribbling stream (review
    finding — single unchecked read() truncated the sync marker and
    every sync check then failed on a valid file)."""
    import io as _io

    from hadoop_tpu.io.sequencefile import BLOCK, Reader, Writer

    sink = _io.BytesIO()
    sink.close = lambda: None
    w = Writer(sink, compression=BLOCK, codec="zlib")
    recs = [(f"k{i:04d}".encode(), f"v{i}".encode() * 10)
            for i in range(200)]
    for k, v in recs:
        w.append(k, v)
    w.close()
    data = sink.getvalue()

    rd = Reader(_DribbleStream(data, k=7))
    assert list(rd) == recs


def test_stdlib_codec_truncation_rejected():
    """The bounded decompress path must reject a truncated stream (the
    old one-shot functions raised; silently returning a partial block
    would corrupt reads)."""
    import pytest

    from hadoop_tpu.io.codecs import Bzip2Codec, GzipCodec, ZlibCodec

    for codec in (ZlibCodec(), GzipCodec(), Bzip2Codec()):
        blob = codec.compress(b"x" * 50_000)
        assert codec.decompress(blob) == b"x" * 50_000
        with pytest.raises(IOError):
            codec.decompress(blob[: len(blob) // 2])


def test_sequencefile_corrupt_block_length_rejected():
    """A corrupt BLOCK length word must be refused before the reader
    tries to buffer it (a flipped bit could otherwise demand a 4 GB
    read)."""
    import io as _io
    import struct as _struct

    from hadoop_tpu.io import sequencefile as sf

    buf = _io.BytesIO()
    w = sf.Writer(buf, compression=sf.BLOCK, codec="zlib")
    w.append(b"k", b"v")
    w._flush_block()
    data = bytearray(buf.getvalue())
    # find the block's length word (follows the first post-header sync
    # escape) and corrupt it to claim ~3 GB
    idx = data.index(_struct.pack(">I", sf.SYNC_ESCAPE), 5)
    plen_off = idx + 4 + 16
    data[plen_off:plen_off + 4] = _struct.pack(">I", 3 << 30)
    r = sf.Reader(_io.BytesIO(bytes(data)))
    with pytest.raises(IOError, match="corrupt file"):
        next(iter(r))
