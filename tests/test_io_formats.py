"""Tests for codecs + SequenceFile/MapFile (ref test model:
hadoop-common/src/test .../io/TestSequenceFile.java, compress/TestCodec.java)."""

import io

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs.filesystem import LocalFileSystem
from hadoop_tpu.io import sequencefile as sf
from hadoop_tpu.io.codecs import CodecFactory, ZstdCodec


@pytest.mark.parametrize("name", ["zlib", "gzip", "bzip2", "lzma"])
def test_codec_roundtrip(name):
    codec = CodecFactory.get(name)
    data = b"the quick brown fox " * 1000
    comp = codec.compress(data)
    assert len(comp) < len(data)
    assert codec.decompress(comp) == data


def test_zstd_if_available():
    if not ZstdCodec.available():
        pytest.skip("libzstd not present")
    codec = CodecFactory.get("zstd")
    data = b"abc" * 10000
    assert codec.decompress(codec.compress(data)) == data


def test_codec_by_extension():
    assert CodecFactory.by_extension("/a/b.gz").name == "gzip"
    assert CodecFactory.by_extension("/a/b.txt") is None


def test_streaming_codec_faces():
    codec = CodecFactory.get("zlib")
    sink = io.BytesIO()
    out = codec.wrap_output(_NoClose(sink))
    payload = b"0123456789" * 100000
    out.write(payload)
    out.close()
    src = codec.wrap_input(io.BytesIO(sink.getvalue()))
    got = src.read()
    assert got == payload


class _NoClose:
    def __init__(self, inner):
        self._inner = inner

    def write(self, b):
        return self._inner.write(b)

    def close(self):
        pass


records = [(f"key{i:05d}".encode(), b"value" * (i % 7) + str(i).encode())
           for i in range(2000)]


@pytest.mark.parametrize("compression,codec", [
    (sf.NONE, "zlib"), (sf.RECORD, "zlib"), (sf.BLOCK, "zlib"),
    (sf.BLOCK, "bzip2"),
])
def test_sequencefile_roundtrip(compression, codec):
    sink = io.BytesIO()
    w = sf.Writer(_NoClose(sink), compression=compression, codec=codec,
                  metadata={"who": "test"})
    for k, v in records:
        w.append(k, v)
    w.close()
    r = sf.Reader(io.BytesIO(sink.getvalue()))
    assert r.compression == compression
    assert r.metadata == {"who": "test"}
    assert list(r) == records


def test_sequencefile_detects_bad_magic():
    with pytest.raises(IOError):
        sf.Reader(io.BytesIO(b"JUNKJUNKJUNK"))


def test_mapfile(tmp_path):
    fs = LocalFileSystem(Configuration(load_defaults=False))
    path = str(tmp_path / "map")
    w = sf.MapFileWriter(fs, path)
    for k, v in records:
        w.append(k, v)
    w.close()
    r = sf.MapFileReader(fs, path)
    assert r.get(b"key00123") == records[123][1]
    assert r.get(b"nope") is None
    with pytest.raises(ValueError):
        w2 = sf.MapFileWriter(fs, str(tmp_path / "m2"))
        w2.append(b"b", b"")
        w2.append(b"a", b"")
