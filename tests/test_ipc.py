"""RPC layer tests (parity targets: ref
hadoop-common/src/test/java/org/apache/hadoop/ipc/TestRPC.java,
TestFairCallQueue.java, TestDecayRpcScheduler.java, TestRetryCache.java)."""

import threading
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import (Client, DecayRpcScheduler, FairCallQueue,
                            RemoteError, RetryCache, RetryInvocationHandler,
                            RetryPolicies, RpcError, Server,
                            StaticFailoverProxyProvider, current_call,
                            get_proxy, idempotent)
from hadoop_tpu.ipc.errors import StandbyError
from hadoop_tpu.security.ugi import (AccessControlError, SecretManager,
                                     UserGroupInformation)
from hadoop_tpu.tracing.tracer import global_tracer


class EchoProtocol:
    """Test protocol."""

    @idempotent
    def echo(self, x):
        return x

    @idempotent
    def add(self, a, b):
        return a + b

    def whoami(self):
        ctx = current_call()
        return {"user": ctx.user.user_name,
                "real": ctx.user.real_user.user_name if ctx.user.real_user else None}

    def boom(self):
        raise ValueError("deliberate failure")

    def access_denied(self):
        raise AccessControlError("not allowed")

    @idempotent
    def slow(self, seconds):
        time.sleep(seconds)
        return "done"

    @idempotent
    def big(self, n):
        return b"x" * n


@pytest.fixture
def server():
    conf = Configuration(load_defaults=False)
    # grant the impersonation used by the proxy-user tests (real
    # 'scheduler' may act as anyone from anywhere)
    conf.set("hadoop.proxyuser.scheduler.users", "*")
    conf.set("hadoop.proxyuser.scheduler.hosts", "*")
    srv = Server(conf, num_handlers=3, name="test")
    srv.register_protocol("EchoProtocol", EchoProtocol())
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture
def client():
    c = Client()
    yield c
    c.stop()


def test_roundtrip(server, client):
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    assert proxy.echo("hello") == "hello"
    assert proxy.add(2, 3) == 5
    assert proxy.echo({"nested": [1, b"bytes", None]}) == {"nested": [1, b"bytes", None]}


def test_large_payload(server, client):
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    assert len(proxy.big(4 * 1024 * 1024)) == 4 * 1024 * 1024


def test_remote_exception_resolution(server, client):
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    with pytest.raises(ValueError, match="deliberate failure"):
        proxy.boom()
    with pytest.raises(AccessControlError):
        proxy.access_denied()


def test_unknown_method(server, client):
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    with pytest.raises((AttributeError, RemoteError)):
        proxy.no_such_method()


def test_user_propagation(server, client):
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    ugi = UserGroupInformation.create_remote_user("alice")
    result = ugi.do_as(proxy.whoami)
    assert result["user"] == "alice"


def test_proxy_user(server, client):
    real = UserGroupInformation.create_remote_user("scheduler")
    proxy_ugi = UserGroupInformation.create_proxy_user("enduser", real)
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client,
                      user=proxy_ugi)
    result = proxy.whoami()
    assert result == {"user": "enduser", "real": "scheduler"}


def test_concurrent_calls_multiplexed(server, client):
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    results = []
    errs = []

    def worker(i):
        try:
            results.append(proxy.add(i, i))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(20)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert sorted(results) == [2 * i for i in range(20)]


def test_timeout():
    srv = Server(num_handlers=1, name="slow")
    srv.register_protocol("EchoProtocol", EchoProtocol())
    srv.start()
    c = Client()
    try:
        proxy = get_proxy(EchoProtocol, ("127.0.0.1", srv.port), client=c,
                          timeout=0.3)
        from hadoop_tpu.ipc import RpcTimeoutError
        with pytest.raises(RpcTimeoutError):
            proxy.slow(2.0)
    finally:
        c.stop()
        srv.stop()


def test_connection_refused():
    c = Client()
    try:
        proxy = get_proxy(EchoProtocol, ("127.0.0.1", 1), client=c)
        with pytest.raises(RpcError):
            proxy.echo("x")
    finally:
        c.stop()


def test_token_auth():
    sm = SecretManager(kind="test-token")
    srv = Server(num_handlers=2, name="secure", secret_manager=sm)
    srv.register_protocol("EchoProtocol", EchoProtocol())
    srv.start()
    c = Client(token_kind="test-token")
    try:
        ugi = UserGroupInformation.create_remote_user("bob")
        ugi.add_token(sm.create_token("bob"))
        proxy = get_proxy(EchoProtocol, ("127.0.0.1", srv.port), client=c,
                          user=ugi)
        assert proxy.whoami()["user"] == "bob"
    finally:
        c.stop()
        srv.stop()


def test_bad_token_rejected():
    sm = SecretManager(kind="test-token")
    other_sm = SecretManager(kind="test-token")
    srv = Server(num_handlers=2, name="secure2", secret_manager=sm)
    srv.register_protocol("EchoProtocol", EchoProtocol())
    srv.start()
    c = Client(token_kind="test-token")
    try:
        ugi = UserGroupInformation.create_remote_user("mallory")
        ugi.add_token(other_sm.create_token("mallory"))  # wrong key
        proxy = get_proxy(EchoProtocol, ("127.0.0.1", srv.port), client=c,
                          user=ugi)
        with pytest.raises((RpcError, AccessControlError)):
            proxy.whoami()
    finally:
        c.stop()
        srv.stop()


def test_trace_propagation(server, client):
    tracer = global_tracer()
    before = len(tracer.finished)
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    with tracer.span("client-op") as sp:
        trace_id = sp.trace_id
        proxy.echo("traced")
    spans = tracer.finished[before:]
    server_spans = [s for s in spans if s.name == "test.echo"]
    assert server_spans, "server should emit a span"
    assert server_spans[0].trace_id == trace_id  # same trace across the wire


def test_state_alignment(client):
    state = {"txid": 7}
    srv = Server(num_handlers=1, name="aligned",
                 state_provider=lambda: state["txid"])
    srv.register_protocol("EchoProtocol", EchoProtocol())
    srv.start()
    try:
        proxy = get_proxy(EchoProtocol, ("127.0.0.1", srv.port), client=client)
        proxy.echo(1)
        conn = next(iter(client._conns.values()))
        assert conn.last_state_id == 7
        state["txid"] = 9
        proxy.echo(2)
        assert conn.last_state_id == 9
    finally:
        srv.stop()


# ---------------------------------------------------------------------- QoS


def test_fair_call_queue_priorities():
    q = FairCallQueue(num_levels=2, capacity=100)
    for i in range(10):
        q.put_nowait(f"hog{i}", 1)
    q.put_nowait("light0", 0)
    q.put_nowait("light1", 0)
    first_four = [q.get(timeout=1) for _ in range(4)]
    # Weighted RR must service level-0 items promptly despite the hog backlog.
    assert "light0" in first_four and "light1" in first_four
    # All items eventually drain.
    rest = [q.get(timeout=1) for _ in range(8)]
    assert len(rest) == 8


def test_decay_scheduler_prioritizes_light_users():
    conf = Configuration(load_defaults=False)
    conf.set("ipc.decay-scheduler.period", "3600s")  # no decay during test
    sched = DecayRpcScheduler(num_levels=4, conf=conf)
    try:
        for _ in range(1000):
            sched.priority("hog")
        light = sched.priority("light")
        hog = sched.priority("hog")
        assert hog > light  # heavy user demoted
        assert light == 0
    finally:
        sched.stop()


def test_retry_cache_replay():
    cache = RetryCache(ttl_s=60)
    executions = []

    def mutate(client_id, call_id):
        entry = cache.wait_for_completion(client_id, call_id)
        if entry.done:
            return entry.payload
        executions.append(1)
        result = f"result-{len(executions)}"
        cache.complete(entry, True, result)
        return result

    r1 = mutate(b"c1", 5)
    r2 = mutate(b"c1", 5)  # retried call — must not re-execute
    assert r1 == r2 == "result-1"
    assert len(executions) == 1
    r3 = mutate(b"c1", 6)  # different call id executes
    assert r3 == "result-2"


def test_retry_cache_failed_execution_retries():
    cache = RetryCache()
    entry = cache.wait_for_completion(b"c", 1)
    cache.complete(entry, False)
    entry2 = cache.wait_for_completion(b"c", 1)
    assert not entry2.done  # failure evicted; retry re-executes


# ------------------------------------------------------------ retry/failover


class FlakyProxy:
    def __init__(self, fail_times, exc_factory):
        self.fail_times = fail_times
        self.exc_factory = exc_factory
        self.calls = 0

    def _is_idempotent(self, name):
        return True

    def _set_retry_count(self, n):
        pass

    def op(self):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise self.exc_factory()
        return "ok"


def test_retry_handler_retries_then_succeeds():
    from hadoop_tpu.ipc.errors import RetriableError
    proxy = FlakyProxy(2, lambda: RetriableError("busy"))
    provider = StaticFailoverProxyProvider(lambda addr: proxy, [("a", 1)])
    handler = RetryInvocationHandler(
        provider, RetryPolicies.failover_on_network_exception(delay_s=0.01))
    assert handler.op() == "ok"
    assert proxy.calls == 3


def test_failover_on_standby():
    active = FlakyProxy(0, lambda: None)
    standby_calls = []

    class StandbyProxy:
        def _is_idempotent(self, name):
            return True

        def _set_retry_count(self, n):
            pass

        def op(self):
            standby_calls.append(1)
            raise StandbyError("standby")

    proxies = {("standby", 1): StandbyProxy(), ("active", 2): active}
    provider = StaticFailoverProxyProvider(
        lambda addr: proxies[addr], [("standby", 1), ("active", 2)])
    handler = RetryInvocationHandler(
        provider, RetryPolicies.failover_on_network_exception(delay_s=0.01))
    assert handler.op() == "ok"
    assert len(standby_calls) == 1


def test_server_metrics(server, client):
    from hadoop_tpu.metrics import metrics_system
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    for i in range(5):
        proxy.echo(i)
    # Counters tick in the handler's finally, after the response is written —
    # poll briefly instead of racing it.
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        snap = metrics_system().snapshot_all()["rpc.test"]
        if snap["rpc_processing_calls"] >= 5:
            break
        time.sleep(0.02)
    assert snap["rpc_processing_calls"] >= 5
    assert snap["rpc_processing_time_num_ops"] >= 5


def test_malformed_frame_does_not_kill_reader(server, client):
    """Regression: a structurally-bad (non-dict) frame must drop only that
    connection; the reader thread keeps serving others."""
    import socket as _socket
    import struct as _struct
    from hadoop_tpu.io.wire import pack as _pack

    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=client)
    assert proxy.echo("before") == "before"

    s = _socket.create_connection(("127.0.0.1", server.port))
    hdr = _pack({"magic": "htpu1", "user": "evil"})
    s.sendall(_struct.pack(">I", len(hdr)) + hdr)
    bad = _pack(12345)  # valid wirepack, not a record
    s.sendall(_struct.pack(">I", len(bad)) + bad)
    time.sleep(0.3)
    s.close()

    # Existing multiplexed connection must still work.
    assert proxy.echo("after") == "after"
    # And brand-new connections must still be accepted and served.
    c2 = Client()
    try:
        p2 = get_proxy(EchoProtocol, ("127.0.0.1", server.port), client=c2)
        assert p2.echo("fresh") == "fresh"
    finally:
        c2.stop()


def test_token_auth_preserves_proxy_user():
    """Regression: under TOKEN auth the effective user must ride on top of the
    token owner as a proxy user, not be silently replaced by it."""
    sm = SecretManager(kind="test-token")
    conf = Configuration(load_defaults=False)
    conf.set("hadoop.proxyuser.scheduler.users", "enduser")
    conf.set("hadoop.proxyuser.scheduler.hosts", "*")
    srv = Server(conf, num_handlers=2, name="secure3", secret_manager=sm)
    srv.register_protocol("EchoProtocol", EchoProtocol())
    srv.start()
    c = Client(token_kind="test-token")
    try:
        real = UserGroupInformation.create_remote_user("scheduler")
        ugi = UserGroupInformation.create_proxy_user("enduser", real)
        ugi.add_token(sm.create_token("scheduler"))
        proxy = get_proxy(EchoProtocol, ("127.0.0.1", srv.port), client=c,
                          user=ugi)
        assert proxy.whoami() == {"user": "enduser", "real": "scheduler"}
    finally:
        c.stop()
        srv.stop()


def test_remote_app_errors_do_not_failover():
    """Regression: a deterministic remote error (e.g. AccessControlError) must
    fail fast, not bounce across HA nodes."""
    from hadoop_tpu.ipc.errors import resolve_exception

    e = resolve_exception(
        "hadoop_tpu.security.ugi.AccessControlError", "denied")
    policy = RetryPolicies.failover_on_network_exception(delay_s=0.01)
    action = policy.should_retry(e, 0, 0, idempotent=True)
    from hadoop_tpu.ipc.retry import RetryAction
    assert action.action == RetryAction.FAIL


def test_retry_cache_timeout_is_retriable():
    from hadoop_tpu.ipc.errors import RetriableError
    cache = RetryCache()
    owner = cache.wait_for_completion(b"c", 1)
    assert not owner.done  # we own it and never complete it
    with pytest.raises(RetriableError):
        cache.wait_for_completion(b"c", 1, timeout=0.1)


# ------------------------------------------------- multi-process server

def _mp_factory(conf):
    """Per-worker protocol: reports the serving pid (module-level so
    forked workers import it by path)."""
    import os as _os

    class WhoProtocol:
        def whoserves(self):
            return _os.getpid()

        def echo(self, x):
            return x
    return {"WhoProtocol": WhoProtocol()}


def test_multiprocess_server_distributes_and_survives_worker_death():
    """SO_REUSEPORT worker pool (ref: Server.java scales handlers with
    threads; CPython scales with processes): connections spread across
    workers, and killing one worker leaves the port serving."""
    import os
    import signal as _signal

    from hadoop_tpu.ipc.mpserver import MultiProcessServer

    srv = MultiProcessServer(factory="tests.test_ipc:_mp_factory",
                             num_workers=3, num_handlers=2,
                             name="mp-test")
    srv.start()
    try:
        pids = set()
        # each Client = fresh connection; the kernel hashes by 4-tuple,
        # so a handful of distinct source ports reaches >1 worker
        for _ in range(12):
            c = Client()
            try:
                pid = get_proxy("WhoProtocol", ("127.0.0.1", srv.port),
                                client=c).whoserves()
                pids.add(pid)
            finally:
                c.stop()
        assert len(pids) >= 2, f"all connections on one worker: {pids}"
        assert os.getpid() not in pids  # served by CHILDREN

        # kill one worker: remaining listeners keep the port alive
        victim = srv._procs[0]
        os.kill(victim.pid, _signal.SIGKILL)
        victim.join(timeout=5)
        ok = 0
        for _ in range(8):
            c = Client()
            try:
                if get_proxy("WhoProtocol", ("127.0.0.1", srv.port),
                             client=c).echo(7) == 7:
                    ok += 1
            finally:
                c.stop()
        assert ok == 8
        assert srv.alive_workers() == 2
    finally:
        srv.stop()
    assert srv.alive_workers() == 0


def test_retry_cache_never_evicts_inflight_entries():
    """Capacity pressure may only shed COMPLETED entries: evicting an
    in-flight one would let its retry become a second concurrent
    executor of a non-idempotent op (review finding)."""
    cache = RetryCache(ttl_s=600, max_entries=4)
    inflight = [cache.wait_for_completion(b"c", i, timeout=0.01)
                for i in range(3)]
    done = cache.wait_for_completion(b"c", 99, timeout=0.01)
    cache.complete(done, True, "payload")
    # 5th insert at capacity: the completed entry goes, in-flight stay
    cache.wait_for_completion(b"c", 100, timeout=0.01)
    assert cache.size() == 4
    import pytest as _p

    from hadoop_tpu.ipc.errors import RetriableError
    for i in range(3):
        with _p.raises(RetriableError):
            # still in flight — retries must NOT become owners
            cache.wait_for_completion(b"c", i, timeout=0.01)
    for e in inflight:
        cache.complete(e, True)


# ---------------------------------------------------------- read timeout


def test_read_timeout_fails_calls_against_stalled_server():
    """A server that accepts the connection and then goes silent must not
    block a caller for its full (possibly huge) per-call timeout:
    ipc.client.read.timeout bounds the silence (regression for the old
    settimeout(None)-after-connect behaviour)."""
    import socket as _socket

    from hadoop_tpu.ipc.errors import RpcTimeoutError

    lsock = _socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    accepted = []

    def stall():
        conn, _ = lsock.accept()
        accepted.append(conn)  # read nothing, answer nothing — just hang

    t = threading.Thread(target=stall, daemon=True)
    t.start()
    conf = Configuration(load_defaults=False)
    conf.set("ipc.client.read.timeout", "0.4")
    c = Client(conf)
    try:
        t0 = time.monotonic()
        with pytest.raises(RpcTimeoutError, match="read.timeout"):
            # per-call timeout far beyond what the test tolerates: only
            # the read timeout can fail this fast
            c.call(("127.0.0.1", port), "EchoProtocol", "echo",
                   ("hi",), timeout=60.0)
        assert time.monotonic() - t0 < 10.0
    finally:
        c.stop()
        for conn in accepted:
            conn.close()
        lsock.close()


def test_read_timeout_spares_slow_but_alive_server(server, client):
    """Inbound bytes reset the clock: a handler that takes longer than
    the read timeout but whose connection stays live must still complete
    (the timeout measures silence, not latency... while pings and other
    call responses flow, only TOTAL silence kills the connection)."""
    proxy = get_proxy(EchoProtocol, ("127.0.0.1", server.port),
                      client=client)
    # an early fast call proves the path; the slow call then outlives
    # the default read timeout tick without the connection dying
    assert proxy.echo("warm") == "warm"
    assert proxy.slow(0.3) == "done"
