"""JobHistory + AM recovery.

Acceptance (VERDICT r2 item 6): kill the AM mid-job after the maps are
done; the relaunched attempt recovers completed maps from the durable
event log and the rerun skips them (each map has exactly ONE finished
event). Plus the history server's REST surface over the done-dir.
Ref: hadoop-mapreduce-client-hs, MRAppMaster.java:180 recovery.
"""

import json
import os
import signal
import time
import urllib.request

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.mapreduce import history
from hadoop_tpu.testing.minicluster import MiniMRYarnCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniMRYarnCluster(num_nodes=2) as c:
        yield c


@pytest.fixture(scope="module")
def fs(cluster):
    return cluster.get_filesystem()


# ------------------------------------------------------------- unit level


def test_event_log_roundtrip_and_recovery_digest(fs):
    w = history.JobHistoryWriter(fs, "/hist/unit")
    w.event(history.JOB_SUBMITTED, job_id="j1", name="t")
    w.flush()
    w.event(history.TASK_FINISHED, task_id="j1_m_0", task_type="map",
            shuffle_addr="h:1", counters={})
    w.event(history.TASK_FINISHED, task_id="j1_r_0", task_type="reduce",
            shuffle_addr="", counters={})
    w.flush()
    evs = list(history.read_events(fs, "/hist/unit"))
    assert [e["type"] for e in evs] == [
        history.JOB_SUBMITTED, history.TASK_FINISHED, history.TASK_FINISHED]
    dig = history.recover_completed_tasks(fs, "/hist/unit")
    assert dig["submitted"] and dig["finished"] is None
    assert set(dig["tasks"]) == {"j1_m_0", "j1_r_0"}
    # a new writer (AM attempt 2) continues the sequence
    w2 = history.JobHistoryWriter(fs, "/hist/unit")
    w2.event(history.JOB_FINISHED, job_id="j1", state="SUCCEEDED")
    w2.flush()
    dig = history.recover_completed_tasks(fs, "/hist/unit")
    assert dig["finished"]["state"] == "SUCCEEDED"


# ---------------------------------------------------------------- e2e


from hadoop_tpu.testing.mr_helpers import SlowGateReducer  # noqa: E402


def _find_am_proc(cluster):
    for nm in cluster.yarn.node_agents:
        for rc in list(nm.containers.values()):
            if rc.proc is not None and rc.proc.poll() is None and \
                    any("appmaster" in c for c in rc.ctx.commands):
                return rc.proc
    return None


def test_am_crash_recovery_skips_finished_maps(cluster, fs, tmp_path):
    from hadoop_tpu.examples.wordcount import TokenizerMapper
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref

    fs.mkdirs("/jh-in")
    for i in range(3):
        fs.write_all(f"/jh-in/f{i}.txt", (f"alpha beta gamma {i}\n" * 50)
                     .encode())
    gate = str(tmp_path / "gate")
    open(gate, "w").close()

    job = (Job(cluster.rm_addr, cluster.default_fs, name="jh-recovery")
           .set_mapper(TokenizerMapper)
           .set_reducer(class_ref(SlowGateReducer))
           .add_input_path("/jh-in")
           .set_output_path("/jh-out")
           .set_num_reduces(1)
           .set("test.reduce.gate", gate)
           .set("mapreduce.job.reduce.slowstart.completedmaps", "1.0"))
    job.submit()
    staging_hist = f"/tmp/staging/{job.job_id}/history"

    # wait until every map has a durable TASK_FINISHED event
    deadline = time.monotonic() + 60
    n_maps = None
    while time.monotonic() < deadline:
        evs = list(history.read_events(fs, staging_hist))
        maps_done = [e for e in evs
                     if e["type"] == history.TASK_FINISHED
                     and e["task_type"] == "map"]
        n_maps = len(maps_done)
        if n_maps >= 3:
            break
        time.sleep(0.2)
    assert n_maps and n_maps >= 3, "maps never finished"

    # kill the AM attempt 1 (reduce is gated, so the job is mid-flight)
    am = _find_am_proc(cluster)
    assert am is not None, "AM process not found"
    am.send_signal(signal.SIGKILL)
    time.sleep(0.5)
    os.remove(gate)  # open the reduce gate for attempt 2

    ok = job.wait_for_completion(timeout=120)
    assert ok, f"job failed: {job.diagnostics}" 
    # each map finished exactly once — the relaunched AM recovered them
    evs = list(history.read_events(
        fs, f"/mr-history/done/{job.job_id}"))
    finished_maps = [e["task_id"] for e in evs
                     if e["type"] == history.TASK_FINISHED
                     and e["task_type"] == "map"]
    assert len(finished_maps) == len(set(finished_maps)) == 3
    assert any(e["type"] == history.JOB_FINISHED
               and e["state"] == "SUCCEEDED" for e in evs)
    out = b"".join(fs.read_all(s.path)
                   for s in fs.list_status("/jh-out")
                   if "part-" in s.path)
    assert b"alpha\t150" in out


def test_history_server_rest(cluster, fs):
    from hadoop_tpu.mapreduce.historyserver import JobHistoryServer
    conf = Configuration(load_defaults=False)
    jhs = JobHistoryServer(conf, cluster.default_fs)
    jhs.init(conf)
    jhs.start()
    try:
        base = f"http://127.0.0.1:{jhs.port}/ws/v1/history/mapreduce/jobs"
        jobs = json.loads(urllib.request.urlopen(base).read())
        ids = [j["id"] for j in jobs["jobs"]["job"]]
        assert ids, "no jobs in done-dir"
        jid = ids[0]
        one = json.loads(urllib.request.urlopen(f"{base}/{jid}").read())
        assert one["job"]["state"] == "SUCCEEDED"
        tasks = json.loads(
            urllib.request.urlopen(f"{base}/{jid}/tasks").read())
        assert len(tasks["tasks"]["task"]) >= 4  # 3 maps + 1 reduce
        counters = json.loads(
            urllib.request.urlopen(f"{base}/{jid}/counters").read())
        assert "TaskCounter" in counters["jobCounters"]
        # 404 for unknown job
        try:
            urllib.request.urlopen(f"{base}/job_nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        jhs.stop()
