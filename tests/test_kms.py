"""KMS server + client provider + ACLs + crypto-stream integration.
Ref: hadoop-common-project/hadoop-kms (KMS.java, KMSClientProvider.java,
KMSACLs.java, TestKMS.java's server-roundtrip posture)."""

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.crypto.kms import KMSKeyProvider, KMSServer


@pytest.fixture()
def kms(tmp_path):
    conf = Configuration(load_defaults=False)
    conf.set("kms.key.provider.path", str(tmp_path / "keys.json"))
    srv = KMSServer(conf)
    srv.init(conf)
    srv.start()
    yield srv
    srv.stop()


def test_kms_key_lifecycle_over_rest(kms):
    p = KMSKeyProvider(f"127.0.0.1:{kms.port}")
    kv = p.create_key("zone1", 128)
    assert kv.name == "zone1" and len(kv.material) == 16
    assert p.get_keys() == ["zone1"]
    cur = p.get_current_key("zone1")
    assert cur.material == kv.material
    rolled = p.roll_key("zone1")
    assert rolled.version != kv.version
    assert p.get_current_key("zone1").material == rolled.material
    p.delete_key("zone1")
    assert p.get_keys() == []


def test_kms_eek_generate_decrypt(kms):
    p = KMSKeyProvider(f"127.0.0.1:{kms.port}")
    p.create_key("ez", 128)
    ekv = p.generate_encrypted_key("ez")
    dek = p.decrypt_encrypted_key(ekv)
    assert len(dek) == 16
    # the EDEK is not the DEK (it's wrapped)
    assert ekv.edek != dek
    # a second generate gives a different DEK
    assert p.decrypt_encrypted_key(p.generate_encrypted_key("ez")) != dek


def test_kms_acls_enforced(tmp_path):
    conf = Configuration(load_defaults=False)
    conf.set("kms.key.provider.path", str(tmp_path / "k.json"))
    conf.set("kms.acl.CREATE", "admin")
    conf.set("kms.acl.DECRYPT_EEK", "worker")
    srv = KMSServer(conf)
    srv.init(conf)
    srv.start()
    try:
        admin = KMSKeyProvider(f"127.0.0.1:{srv.port}", user="admin")
        worker = KMSKeyProvider(f"127.0.0.1:{srv.port}", user="worker")
        with pytest.raises(PermissionError):
            worker.create_key("x")
        admin.create_key("x")
        ekv = admin.generate_encrypted_key("x")
        with pytest.raises(PermissionError):
            admin.decrypt_encrypted_key(ekv)   # admin lacks DECRYPT_EEK
        assert len(worker.decrypt_encrypted_key(ekv)) == 16
    finally:
        srv.stop()


def test_kms_backed_crypto_stream(kms, tmp_path):
    """The client provider plugs into the same seam the AES-CTR streams
    use — encrypt with a KMS-held key, decrypt after a roll (old version
    still resolvable through the EDEK's version pin)."""
    import io

    from hadoop_tpu.crypto.streams import CryptoInputStream, \
        CryptoOutputStream
    p = KMSKeyProvider(f"127.0.0.1:{kms.port}")
    p.create_key("files", 128)
    ekv = p.generate_encrypted_key("files")
    dek = p.decrypt_encrypted_key(ekv)
    data = b"secret payload " * 1000
    buf = io.BytesIO()
    out = CryptoOutputStream(buf, dek, ekv.iv)
    out.write(data)
    out.flush()
    blob = buf.getvalue()
    assert blob != data and len(blob) == len(data)
    back = CryptoInputStream(io.BytesIO(blob), dek, ekv.iv)
    assert back.read(len(data)) == data


def test_keys_kms_client_provider_speaks_server_protocol(kms):
    """The KeyProviderFactory-dispatch client (keys.make_provider
    'kms://...') must interoperate with the in-repo KMS daemon: eek_op
    routing, nested edek material, /_roll path (review finding — it
    spoke a different dialect and every envelope op 404'd)."""
    from hadoop_tpu.crypto.keys import make_provider

    p = make_provider(f"kms://http@127.0.0.1:{kms.port}")
    kv = p.create_key("zonek", 128)
    assert kv.name == "zonek" and len(kv.material) == 16
    assert p.get_current_key("zonek").version == kv.version
    rolled = p.roll_key("zonek")
    assert rolled.version != kv.version
    ekv = p.generate_encrypted_key("zonek")
    dek = p.decrypt_encrypted_key(ekv)
    assert len(dek) == 16
    # the decrypted DEK re-encrypts consistently under the zone key
    assert "zonek" in p.get_keys()
