"""Tiered fleet-wide KV cache: HBM radix → host-RAM ring → DFS store.

What must hold for the tiers to be invisible to correctness:

- a demote → promote round trip is BIT-EXACT (raw codec) — a prompt
  whose blocks took a detour through the host ring or the DFS store
  decodes to exactly the tokens a cold prefill produces;
- only zero-ref pages ever demote — an active decode can never lose KV
  under itself;
- the DFS tier is fleet-wide: a DIFFERENT engine instance (fresh HBM,
  fresh host ring — a restarted replica) maps a persisted prefix with
  zero prefill steps for the cached span;
- eviction interleaved with a cold-tier fetch-admission cannot corrupt
  either side;
- the prefill/decode disaggregation handoff (prefill_to_store on one
  engine, decode on another) matches single-replica decode exactly.
"""

import json
import http.client
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import LocalFileSystem
from hadoop_tpu.models.config import get_config
from hadoop_tpu.models.decoder import forward, init_params
from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
from hadoop_tpu.serving.kvstore import (CODECS, HostTier, decode_block,
                                        encode_block)
from hadoop_tpu.serving.metrics import ServingMetrics


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("tiny")
    return init_params(jax.random.PRNGKey(0), cfg), cfg


_REF_P = 48
_ref_fwd_cache = {}


def _reference_greedy(params, cfg, prompt, max_new):
    fwd = _ref_fwd_cache.get(id(cfg))
    if fwd is None:
        fwd = jax.jit(lambda p, t: forward(p, t, cfg))
        _ref_fwd_cache[id(cfg)] = fwd
    seq = list(prompt)
    for _ in range(max_new):
        padded = seq + [0] * (_REF_P - len(seq))
        logits = fwd(params, jnp.asarray([padded]))
        seq.append(int(jnp.argmax(logits[0, len(seq) - 1])))
    return seq[len(prompt):]


def _drive(eng, req):
    while not req.done.is_set():
        eng.step()
    return req.wait(0)


# ------------------------------------------------------------------ codec

def test_codec_raw_roundtrip_bit_exact():
    rng = np.random.default_rng(0)
    shape = (2, 4, 3, 8)
    k = rng.standard_normal(shape).astype(np.float32)
    v = rng.standard_normal(shape).astype(np.float32)
    k2, v2, hdr = decode_block(encode_block(k, v, "raw"),
                               shape=shape, dtype=np.float32)
    assert hdr["codec"] == "raw"
    assert np.array_equal(k, k2) and np.array_equal(v, v2)


def test_codec_int8_roundtrip_allclose_and_smaller():
    rng = np.random.default_rng(1)
    shape = (3, 4, 2, 8)
    k = rng.standard_normal(shape).astype(np.float32) * 3.0
    v = rng.standard_normal(shape).astype(np.float32) * 0.1
    raw = encode_block(k, v, "raw")
    q = encode_block(k, v, "int8")
    assert len(q) < len(raw) / 2          # ~4x on f32 minus the header
    k2, v2, hdr = decode_block(q, shape=shape, dtype=np.float32)
    assert hdr["codec"] == "int8"
    # symmetric per-layer int8: error bounded by half a step (amax/127)
    for orig, deq in ((k, k2), (v, v2)):
        step = np.abs(orig).max(axis=(1, 2, 3), keepdims=True) / 127.0
        assert np.all(np.abs(orig - deq) <= step * 0.51 + 1e-7)


def test_codec_is_a_block_property_not_a_reader_config():
    """Mixed fleets during a codec rollout: the header records which
    codec WROTE the block, so any reader decodes it."""
    rng = np.random.default_rng(2)
    shape = (2, 4, 2, 4)
    k = rng.standard_normal(shape).astype(np.float32)
    v = rng.standard_normal(shape).astype(np.float32)
    for codec in CODECS:
        k2, _, hdr = decode_block(encode_block(k, v, codec),
                                  shape=shape, dtype=np.float32)
        assert hdr["codec"] == codec
        assert np.allclose(k, k2, atol=float(np.abs(k).max()) / 120)


def test_codec_shape_dtype_mismatch_is_loud():
    k = np.zeros((2, 4, 2, 4), np.float32)
    data = encode_block(k, k, "raw")
    with pytest.raises(ValueError, match="shape"):
        decode_block(data, shape=(2, 4, 2, 8), dtype=np.float32)
    with pytest.raises(ValueError, match="dtype"):
        decode_block(data, shape=(2, 4, 2, 4), dtype=np.float16)
    with pytest.raises(ValueError):
        decode_block(data[:10], shape=(2, 4, 2, 4), dtype=np.float32)
    with pytest.raises(ValueError):
        encode_block(k, k, "zstd")


# -------------------------------------------------------------- host tier

def test_host_tier_ring_wrap_evicts_oldest():
    shape = (1, 2, 1, 2)
    tier = HostTier(shape, np.float32, budget_bytes=3 * 2 * 4 * 4)
    assert tier.capacity == 3
    mk = lambda i: (np.full(shape, i, np.float32),
                    np.full(shape, -i, np.float32))
    for i in range(4):                       # 4 puts into 3 slots
        assert tier.put(bytes([i]), *mk(i))
    assert tier.get(bytes([0])) is None      # oldest fell off the ring
    for i in (1, 2, 3):
        k, v = tier.get(bytes([i]))
        assert float(k[0, 0, 0, 0]) == i and float(v[0, 0, 0, 0]) == -i
    assert len(tier) == 3
    # get() hands back copies: mutating them must not poison the ring
    k, _ = tier.get(bytes([2]))
    k[:] = 99
    assert float(tier.get(bytes([2]))[0][0, 0, 0, 0]) == 2
    assert HostTier(shape, np.float32, budget_bytes=1).put(b"x", *mk(0)) \
        is False                             # budget below one block


def test_host_tier_int8_codec_roundtrip_and_items():
    """serving.kv.codec=int8 on the host ring: payloads round-trip
    allclose (same contract as a DFS round trip under the codec) and
    the drain path's items() decodes every resident block."""
    shape = (2, 4, 2, 4)
    tier = HostTier(shape, np.float32, budget_bytes=1 << 20,
                    codec="int8")
    rng = np.random.default_rng(0)
    blocks = {bytes([i]): (rng.normal(size=shape).astype(np.float32),
                           rng.normal(size=shape).astype(np.float32))
              for i in range(3)}
    for d, (k, v) in blocks.items():
        assert tier.put(d, k, v)
    for d, (k, v) in blocks.items():
        gk, gv = tier.get(d)
        assert gk.dtype == np.float32 and gk.shape == shape
        np.testing.assert_allclose(gk, k, atol=2.5 / 127 * np.abs(
            k).max())
        np.testing.assert_allclose(gv, v, atol=2.5 / 127 * np.abs(
            v).max())
    got = dict((d, kv) for d, *kv in
               ((d, k, v) for d, k, v in tier.items()))
    assert set(got) == set(blocks)
    # all-zero block decodes exactly zero (scale-of-zeros edge)
    z = np.zeros(shape, np.float32)
    tier.put(b"z", z, z)
    gk, gv = tier.get(b"z")
    assert (gk == 0).all() and (gv == 0).all()


def test_host_tier_int8_codec_quadruples_f32_capacity():
    """The compounding satellite: the same serving.kv.host.bytes budget
    holds ~4× the blocks of an f32 engine under the int8 codec (the
    scale plane costs a sliver below exactly 4×)."""
    shape = (2, 8, 2, 8)
    budget = 64 * 1024
    raw = HostTier(shape, np.float32, budget_bytes=budget)
    q = HostTier(shape, np.float32, budget_bytes=budget, codec="int8")
    assert q.capacity >= 3 * raw.capacity            # ~3.9× here
    assert q.capacity * q.block_bytes <= budget
    with pytest.raises(ValueError, match="codec"):
        HostTier(shape, np.float32, budget_bytes=budget, codec="zstd")


def test_tiered_int8_demote_promote_allclose():
    """End-to-end through TieredKVCache: with serving.kv.codec=int8 the
    demote path quantizes into the ring and a host get dequantizes
    back allclose in the engine dtype."""
    from hadoop_tpu.serving.kvstore import BlockPool, TieredKVCache
    shape = (2, 4, 2, 4)
    pool = BlockPool(8, block_size=4)
    store = {}
    rng = np.random.default_rng(1)

    def extract(block):
        return store[block]

    kv = TieredKVCache(pool, layers=2, kv_heads=2, head_dim=4,
                       dtype=np.float32, host_bytes=1 << 20,
                       codec="int8", extract=extract)
    assert kv.host is not None and kv.host.codec == "int8"
    # simulate a demotion: radix-owned page whose payload we control
    toks = list(range(4))
    kv.radix.insert(toks, [3])
    node = kv.radix.node_for_block(3)
    payload = (rng.normal(size=shape).astype(np.float32),
               rng.normal(size=shape).astype(np.float32))
    store[3] = payload
    kv.demote(node)
    got = kv.host.get(node.digest)
    assert got is not None
    np.testing.assert_allclose(got[0], payload[0],
                               atol=2.5 / 127 * np.abs(
                                   payload[0]).max())
    assert kv.demotions == 1


# -------------------------------------------- demote/promote round trips

def test_demote_promote_roundtrip_bit_exact(tiny_model):
    """A prompt whose cached blocks were evicted HBM → host ring and
    recovered at re-admission decodes bit-identically to its cold run,
    and the recovery is visible as host-tier hits (not re-prefill)."""
    params, cfg = tiny_model
    head = [5, 9, 2, 7, 1, 8, 3, 6, 4, 2, 9, 1]          # 3 full blocks
    pa = head + [11, 12]
    ref = _reference_greedy(params, cfg, pa, 6)
    # pool of 7 usable pages; the host ring holds the whole working set
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, num_blocks=8,
                       kv_host_bytes=1 << 30, metrics=ServingMetrics())
    assert _drive(eng, eng.submit(
        pa, SamplingParams(max_new_tokens=6))) == ref     # cold
    # flood the pool with unrelated prompts so pa's zero-ref cached
    # pages are evicted — demoting them into the host ring on the way
    for flood in ([77, 66, 55, 44, 33, 22, 88, 99, 12, 13, 14, 15],
                  [31, 41, 59, 26, 53, 58, 97, 93, 23, 84, 62, 64]):
        _drive(eng, eng.submit(flood + [1, 2], SamplingParams(
            max_new_tokens=6)))
    assert eng.kvstore.demotions >= 3
    assert eng.prefix_cache.match(pa) == []               # gone from HBM
    # re-admission recovers the head from the ring instead of prefilling
    req = eng.submit(pa, SamplingParams(max_new_tokens=6))
    assert _drive(eng, req) == ref                        # bit-exact
    assert eng.kvstore.hits["host"] >= 3
    assert req.prefix_tokens_reused >= 12


def test_zero_ref_only_demotion_under_active_decode(tiny_model):
    """An ACTIVE request's pages are pinned (refcount > 0): pool
    pressure may evict and demote only zero-ref cache, and the active
    stream still decodes exactly."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, num_blocks=8,
                       kv_host_bytes=1 << 30, metrics=ServingMetrics())
    ref_a = _reference_greedy(params, cfg, [1, 2, 3, 4], 20)
    ref_b = _reference_greedy(params, cfg, [9, 9, 9, 9], 16)
    a = eng.submit([1, 2, 3, 4], SamplingParams(max_new_tokens=20))
    b = eng.submit([9, 9, 9, 9], SamplingParams(max_new_tokens=16))
    while not (a.done.is_set() and b.done.is_set()):
        eng.step()
        # invariant mid-flight: every demoted digest belongs to a page
        # that was zero-ref at demotion time — active tables never
        # overlap the host ring's source pages
        for slot, req in enumerate(eng._slots):
            if req is not None:
                for blk in req._blocks:
                    assert eng.pool.refcount(blk) >= 1
    assert a.wait(0) == ref_a
    assert b.wait(0) == ref_b


# ------------------------------------------------------------- DFS tier

def test_dfs_tier_hit_from_a_different_engine(tmp_path, tiny_model):
    """The fleet-wide property: engine A persists a hot shared prefix
    through the write pipeline; engine B — a different instance with
    cold HBM and no host ring (a restarted replica) — maps it from the
    DFS store with zero prefill steps for the cached span."""
    params, cfg = tiny_model
    fs = LocalFileSystem()
    kvdir = f"{tmp_path}/kvcache"
    head = [5, 9, 2, 7, 1, 8, 3, 6, 4, 2, 9, 1]          # 3 full blocks
    pa = head + [11, 12]
    ref = _reference_greedy(params, cfg, pa, 6)

    def mk(min_refs):
        # chunk < cached span so skipped prefill shows up in the step
        # count (one chunk per engine step)
        return DecodeEngine(params, cfg, max_batch=2, block_size=4,
                            max_context=32, prefill_chunk=4,
                            kv_store_fs=fs, kv_store_dir=kvdir,
                            kv_dfs_min_refs=min_refs,
                            metrics=ServingMetrics())

    # min-refs gates persistence on cross-request HOTNESS: after one
    # cold run nothing is durable; a second request re-matching the
    # prefix crosses the threshold and triggers the background persist
    a = mk(min_refs=1)
    assert _drive(a, a.submit(pa, SamplingParams(max_new_tokens=6))) \
        == ref
    assert a.kvstore.stats()["dfs_persists"] == 0
    assert _drive(a, a.submit(pa, SamplingParams(max_new_tokens=6))) \
        == ref
    assert a.kvstore.flush(30.0)
    assert a.kvstore.stats()["dfs_persists"] == 3
    files = []
    for d in fs.list_status(kvdir):
        files += [s.path for s in fs.list_status(d.path)]
    assert len([f for f in files if f.endswith(".kvb")]) == 3

    # a DIFFERENT engine instance: every full block of the head comes
    # off the DataNodes; only the tail (and the last prompt token)
    # prefills — fewer engine steps than the same run cold
    cold = mk(min_refs=1)
    cold.kvstore.dfs = None          # cache-off arm for the step count
    s0 = cold.steps
    assert _drive(cold, cold.submit(
        pa, SamplingParams(max_new_tokens=6))) == ref
    cold_steps = cold.steps - s0

    b = mk(min_refs=1)
    req = b.submit(pa, SamplingParams(max_new_tokens=6))
    assert _drive(b, req) == ref                         # exact
    assert b.kvstore.hits["dfs"] == 3
    assert req.prefix_tokens_reused == 12                # the whole head
    assert b.steps < cold_steps


def test_dfs_min_refs_threshold(tmp_path, tiny_model):
    """serving.kv.dfs.min-refs=2: one re-match is not hot enough, the
    second is."""
    params, cfg = tiny_model
    fs = LocalFileSystem()
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, kv_store_fs=fs,
                       kv_store_dir=f"{tmp_path}/kv", kv_dfs_min_refs=2,
                       metrics=ServingMetrics())
    pa = [5, 9, 2, 7, 1, 8, 3, 6] + [11]                 # 2 full blocks
    for expect in (0, 0, 2):           # cold, hits=1, hits=2 -> persist
        _drive(eng, eng.submit(pa, SamplingParams(max_new_tokens=4)))
        assert eng.kvstore.flush(30.0)
        assert eng.kvstore.stats()["dfs_persists"] == expect


def test_mid_fetch_eviction_safety(tmp_path, tiny_model):
    """Admission that recovers blocks from a cold tier while its OWN
    allocation evicts (and demotes) other zero-ref pages: both streams
    of payloads stay intact — the recovered prompt decodes exactly and
    the evicted one recovers from the ring next."""
    params, cfg = tiny_model
    pa = [5, 9, 2, 7, 1, 8, 3, 6, 4, 2, 9, 1] + [11, 12]
    pb = [77, 66, 55, 44, 33, 22, 88, 99, 12, 13, 14, 15] + [1, 2]
    ref_a = _reference_greedy(params, cfg, pa, 6)
    ref_b = _reference_greedy(params, cfg, pb, 6)
    # 7 usable pages: either prompt's working set is 4 — caching both
    # heads (3+3) plus a live tail can't fit, so every re-admission
    # must evict the other's cache while injecting its own cold hits
    eng = DecodeEngine(params, cfg, max_batch=1, block_size=4,
                       max_context=32, num_blocks=8,
                       kv_host_bytes=1 << 30, metrics=ServingMetrics())
    assert _drive(eng, eng.submit(pa, SamplingParams(
        max_new_tokens=6))) == ref_a
    assert _drive(eng, eng.submit(pb, SamplingParams(
        max_new_tokens=6))) == ref_b
    for _ in range(3):                 # ping-pong: fetch + evict each way
        assert _drive(eng, eng.submit(pa, SamplingParams(
            max_new_tokens=6))) == ref_a
        assert _drive(eng, eng.submit(pb, SamplingParams(
            max_new_tokens=6))) == ref_b
    assert eng.kvstore.hits["host"] >= 6
    assert eng.kvstore.demotions >= 6


# ------------------------------------------------------- disaggregation

def test_disaggregated_handoff_exact_match(tmp_path, tiny_model):
    """prefill_to_store on one engine, decode on another: the decode
    replica's output is bit-identical to a single-replica decode, with
    the whole full-block span served from the store."""
    params, cfg = tiny_model
    fs = LocalFileSystem()
    kvdir = f"{tmp_path}/kvcache"
    prompt = list(range(7, 21))                          # 3 full blocks

    def mk():
        return DecodeEngine(params, cfg, max_batch=4, block_size=4,
                            max_context=48, kv_store_fs=fs,
                            kv_store_dir=kvdir, kv_dfs_min_refs=1,
                            metrics=ServingMetrics())

    solo = mk()
    ref = _drive(solo, solo.submit(prompt,
                                   SamplingParams(max_new_tokens=8)))
    p_eng = mk()
    assert p_eng.prefill_to_store(prompt) == 12          # durable now
    d_eng = mk()
    req = d_eng.submit(prompt, SamplingParams(max_new_tokens=8))
    assert _drive(d_eng, req) == ref
    assert d_eng.kvstore.hits["dfs"] == 3
    assert req.prefix_tokens_reused == 12
    # no DFS tier -> the handoff API refuses loudly
    plain = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                         max_context=48)
    with pytest.raises(ValueError, match="dfs"):
        plain.prefill_to_store(prompt)


def test_prefill_http_door_and_role_records(tmp_path, tiny_model):
    """/v1/prefill persists and reports the span; a replica without the
    DFS tier answers 400 (the router's fall-back-to-cold signal)."""
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    fs = LocalFileSystem()
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=48, kv_store_fs=fs,
                       kv_store_dir=f"{tmp_path}/kv",
                       metrics=ServingMetrics())
    srv = ServingServer(eng, Configuration(load_defaults=False))
    eng.start()
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=60)
        conn.request("POST", "/v1/prefill", body=json.dumps(
            {"tokens": list(range(7, 21))}).encode())
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 200, body
        assert body["persisted_tokens"] == 12
    finally:
        srv.stop()
    plain = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                         max_context=48)
    srv2 = ServingServer(plain, Configuration(load_defaults=False))
    plain.start()
    srv2.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv2.port,
                                          timeout=60)
        conn.request("POST", "/v1/prefill", body=json.dumps(
            {"tokens": [1, 2, 3]}).encode())
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        assert resp.status == 400, body
    finally:
        srv2.stop()


def test_router_offloads_long_prompts_to_prefill_role(tmp_path,
                                                      tiny_model):
    """Role-aware routing end to end: a long prompt is first shipped to
    the role=prefill replica (KV lands on the shared store), then
    decoded on the role=decode replica, which maps the handoff blocks
    instead of re-prefilling. Short prompts skip the handoff, and a
    fleet with no prefill replicas behaves exactly as before."""
    from hadoop_tpu.registry import (RegistryClient, RegistryServer,
                                     ServiceRecord)
    from hadoop_tpu.serving.router import ServingRouter, replica_path
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    fs = LocalFileSystem()
    kvdir = f"{tmp_path}/kvcache"
    conf = Configuration(load_defaults=False)
    conf.set("serving.router.prefill.min.tokens", "12")
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    engines, servers = [], []
    try:
        for _ in range(2):
            eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                               max_context=48, kv_store_fs=fs,
                               kv_store_dir=kvdir, kv_dfs_min_refs=1)
            srv = ServingServer(eng, Configuration(load_defaults=False))
            eng.start()
            srv.start()
            engines.append(eng)
            servers.append(srv)
        reg_addr = ("127.0.0.1", reg_srv.port)
        rc = RegistryClient(reg_addr, conf)
        for i, role in enumerate(("prefill", "decode")):
            rc.register(ServiceRecord(
                replica_path("disagg", f"r{i}"),
                {"http": f"127.0.0.1:{servers[i].port}"},
                {"state": "serving", "role": role}),
                ttl_s=30.0, auto_renew=False)
        router = ServingRouter(reg_addr, "disagg", conf, cache_ttl_s=0.0)
        prompt = list(range(7, 21))                      # 14 >= 12
        ref = _reference_greedy(params, cfg, prompt, 6)
        out = router.generate({"tokens": prompt, "max_new_tokens": 6})
        assert out["tokens"] == ref
        assert router.prefill_offloaded == 1
        # the decode replica mapped the handoff instead of prefilling
        assert engines[1].kvstore.hits["dfs"] == 3
        # and the decode itself ran on the decode-role replica
        assert engines[1].tokens_generated >= 6
        # short prompt: no handoff
        out = router.generate({"tokens": [3, 4, 5],
                               "max_new_tokens": 4})
        assert out["tokens"] == _reference_greedy(params, cfg,
                                                  [3, 4, 5], 4)
        assert router.prefill_offloaded == 1
        router.close()
        rc.close()
    finally:
        for srv in servers:
            srv.stop()
        reg_srv.stop()


# ----------------------------------------------------------- telemetry

def test_prom_exposition_has_tier_labels(tiny_model):
    """kv_fetch_seconds publishes as ONE family with tier labels, and
    the per-tier hit counters surface on /prom."""
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.prom import render_prom
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, num_blocks=8,
                       kv_host_bytes=1 << 30, metrics=ServingMetrics())
    pa = [5, 9, 2, 7, 1, 8, 3, 6, 4, 2, 9, 1, 11, 12]
    _drive(eng, eng.submit(pa, SamplingParams(max_new_tokens=4)))
    _drive(eng, eng.submit([7] * 12 + [1, 2],
                           SamplingParams(max_new_tokens=4)))
    _drive(eng, eng.submit(pa, SamplingParams(max_new_tokens=4)))
    text = render_prom(metrics_system())
    assert 'kv_fetch_seconds_bucket{' in text
    assert 'tier="host"' in text
    # ONE family declaration even with two labelled series
    assert text.count("# TYPE htpu_kv_fetch_seconds histogram") == 1
    for name in ("kv_hits_hbm", "kv_hits_host", "kv_hits_dfs",
                 "kv_demotions", "kv_promotions"):
        assert f"htpu_{name}" in text


# ---------------------------------------- chain ingest + fetch window

def _bare_tiered(fetch_window=4, host_bytes=0):
    """A TieredKVCache with tiny payload shapes and no engine behind
    it — the chain surfaces (ingest/read) need no device pool."""
    from hadoop_tpu.serving.kvstore import BlockPool
    from hadoop_tpu.serving.kvstore.tiered import TieredKVCache
    pool = BlockPool(4, 4)
    return TieredKVCache(pool, layers=1, kv_heads=1, head_dim=2,
                         dtype=np.float32, host_bytes=host_bytes,
                         fetch_window=fetch_window)


def _chain_payload(i):
    k = np.full((1, 4, 1, 2), float(i), np.float32)
    return k, -k


def test_ingest_chain_roundtrips_through_read_chain():
    """Streamed ingest (the longctx prefill sink) and read_chain (the
    working-set decode source) agree on digests and payloads."""
    kv = _bare_tiered(host_bytes=1 << 20)
    tokens = list(range(40))                      # 10 full blocks
    n = kv.ingest_chain(tokens, (_chain_payload(i) for i in range(10)))
    assert n == 10
    assert kv.stats()["chain_ingested"] == 10
    hits = kv.read_chain(tokens, 10)
    assert len(hits) == 10
    for i, h in enumerate(hits):
        np.testing.assert_array_equal(h.k, _chain_payload(i)[0])
    assert kv.hits["host"] == 10
    # a DIFFERENT token chain misses (digest chaining, not position)
    assert kv.read_chain([9] * 40, 10) == []


def test_ingest_chain_digests_match_the_radix_scheme():
    """One keying for both writers: blocks streamed by ingest_chain
    carry exactly the digests a radix insert of the same tokens would
    — the interop that lets a normal admission map a longctx chain."""
    from hadoop_tpu.serving.kvstore.radix import chain_digest
    kv = _bare_tiered(host_bytes=1 << 20)
    tokens = list(range(12))                      # 3 full blocks
    kv.ingest_chain(tokens, (_chain_payload(i) for i in range(3)))
    digest = kv.chain_salt
    for i in range(3):
        digest = chain_digest(digest, tuple(tokens[i * 4:(i + 1) * 4]))
    assert kv.host.get(digest) is not None
    assert kv.radix.root_digest == kv.chain_salt


class _CountingDFS:
    """Digest-keyed in-memory stand-in for the DFS tier that counts
    individual reads (the per-block DataNode round trips)."""

    def __init__(self, store):
        self.store = store
        self.reads = 0

    def get(self, digest):
        self.reads += 1
        return self.store.get(digest)


def test_fetch_window_pages_long_chains_in_window_round_trips():
    """The serving.kv.fetch.window regression: a 1000-block contiguous
    chain pages in with O(chain/window) speculative window reads, not
    O(chain) serial round trips."""
    from hadoop_tpu.serving.kvstore.radix import chain_digest
    from hadoop_tpu.serving.kvstore.tiered import TieredKVCache

    class Counting(TieredKVCache):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            self.window_reads = 0

        def _dfs_read_window(self, digests, idx):
            self.window_reads += 1
            return super()._dfs_read_window(digests, idx)

    chain = 1000
    tokens = list(range(chain * 4))
    payload = _chain_payload(1)

    def mk(window):
        from hadoop_tpu.serving.kvstore import BlockPool
        kv = Counting(BlockPool(4, 4), layers=1, kv_heads=1,
                      head_dim=2, dtype=np.float32,
                      fetch_window=window)
        store = {}
        digest = kv.chain_salt
        for i in range(chain):
            digest = chain_digest(digest,
                                  tuple(tokens[i * 4:(i + 1) * 4]))
            store[digest] = payload
        kv.dfs = _CountingDFS(store)
        return kv

    kv = mk(50)
    hits = kv.read_chain(tokens, chain)
    assert len(hits) == chain
    assert kv.window_reads == chain // 50          # 20, not 1000
    assert kv.dfs.reads == chain                   # every block once

    kv1 = mk(1)
    assert len(kv1.read_chain(tokens, chain)) == chain
    assert kv1.window_reads == chain               # the old O(chain)


def test_fetch_window_is_conf_keyed_through_the_engine(tiny_model):
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, kv_host_bytes=1 << 20,
                       kv_fetch_window=17)
    assert eng.kvstore.fetch_window == 17
    assert eng.kvstore.stats()["fetch_window"] == 17
    eng.stop()
