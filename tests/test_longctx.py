"""Long-context serving plane (serving/longctx).

The contract: a prompt too big for one chip's KV pool prefills as a CP
job across the virtual mesh, its KV streams into the cold tiers, and
working-set decode reproduces the single-chip ``decoder.forward``
greedy tokens EXACTLY at small shapes — with the A-B guard rejecting a
deliberately broken ring hop, every longctx shape compiling exactly
once, and the engine's fused-step path untouched beside it.

CP tests are capability-gated like the seed parallel suite: they skip
when the shard_map context-parallel machinery is unavailable on the
installed jax (the non-CP pieces — paging, validation, routing, the
router capacity gate — run everywhere).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.models.config import get_config
from hadoop_tpu.models.decoder import forward, init_params
from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
from hadoop_tpu.serving.metrics import ServingMetrics


def _cp_supported() -> bool:
    """One 2-device ring probe: CP tests skip (not fail) on jax builds
    where the shard_map machinery can't run — the same capability the
    seed parallel suite depends on."""
    try:
        from functools import partial

        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        from hadoop_tpu.parallel.ring_attention import ring_attention
        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        q = jnp.ones((1, 4, 2, 4), jnp.float32)

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
        def ring(q, k, v):
            return ring_attention(q, k, v, "sp", 2)

        np.asarray(ring(q, q, q))
        return True
    except Exception:  # noqa: BLE001 — any failure means "not on this
        # jax"; the skip reason is the gate, not the traceback
        return False


cp_only = pytest.mark.skipif(not _cp_supported(),
                             reason="shard_map CP machinery "
                                    "unavailable on this jax build")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("tiny", max_seq=512)
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def _reference_greedy(params, cfg, prompt, n):
    ctx = list(prompt)
    out = []
    for _ in range(n):
        lg = forward(params, jnp.asarray(ctx, jnp.int32)[None, :],
                     cfg)[0, -1]
        tok = int(jnp.argmax(lg))
        out.append(tok)
        ctx.append(tok)
    return out


def _prompt(cfg, n, seed=1):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, size=n).tolist()


def _mk_plane(params, cfg, engine, **kw):
    from hadoop_tpu.serving.longctx import LongContextPlane
    kw.setdefault("block_size", engine.block_size)
    kw.setdefault("min_tokens", 100)
    kw.setdefault("max_tokens", 256)
    kw.setdefault("sp", 4)
    kw.setdefault("window_blocks", 3)
    kw.setdefault("tail_tokens", 64)
    kw.setdefault("metrics", engine.metrics)
    return LongContextPlane(params, cfg, engine.kvstore, **kw)


# ------------------------------------------------------- plan / topology

@pytest.mark.parametrize("shape", [(2, 4), (4, 4), (2, 2, 2),
                                   (2, 2, 4), (4, 4, 4), (2, 3, 4)])
def test_ring_order_snakes_through_the_grid(shape):
    """TASP placement: consecutive CP ranks must be physical neighbors
    — on every coordinate grid (2D and the 3D torus-slice shapes) the
    snake order makes every hop one step on one axis."""
    import itertools

    from hadoop_tpu.serving.longctx import ring_order

    class Dev:
        def __init__(self, i, coords):
            self.id = i
            self.coords = coords

    coords = list(itertools.product(*[range(s) for s in shape]))
    devs = [Dev(i, c) for i, c in enumerate(coords)]
    rng = np.random.default_rng(3)
    shuffled = [devs[i] for i in rng.permutation(len(devs))]
    ordered = ring_order(shuffled)
    for a, b in zip(ordered, ordered[1:]):
        dist = sum(abs(x - y) for x, y in zip(a.coords, b.coords))
        assert dist == 1, (
            f"non-neighbor hop {a.coords}->{b.coords} on grid {shape}")


def test_ring_order_without_coords_is_id_order():
    from hadoop_tpu.serving.longctx import ring_order

    class Dev:
        def __init__(self, i):
            self.id = i
            self.coords = None

    devs = [Dev(i) for i in (3, 0, 2, 1)]
    assert [d.id for d in ring_order(devs)] == [0, 1, 2, 3]


def test_choose_sp_mode_validates_and_falls_back(tiny_model):
    from hadoop_tpu.serving.longctx import choose_sp_mode
    _, cfg = tiny_model
    assert choose_sp_mode(cfg, 2, "ulysses") == "ulysses"
    # tiny has 2 kv heads: ulysses over 4 ranks is impossible — loud
    # fallback, not a refused workload
    assert choose_sp_mode(cfg, 4, "ulysses") == "ring"
    with pytest.raises(ValueError):
        choose_sp_mode(cfg, 2, "diagonal")


# ------------------------------------------------------ CP prefill parity

@cp_only
@pytest.mark.parametrize("sp,mode", [(4, "ring"), (2, "ulysses")])
def test_cp_prefill_exact_match(tiny_model, sp, mode):
    """Small-shape A-B: CP last-token logits vs single-chip
    ``decoder.forward`` — exact guard (tight atol + greedy argmax
    identity), for both CP strategies."""
    from hadoop_tpu.serving.longctx import (ContextParallelPrefiller,
                                            run_prefill_ab)
    params, cfg = tiny_model
    prompt = _prompt(cfg, 150)
    pre = ContextParallelPrefiller(params, cfg, block_size=8,
                                   pad_tokens=160, sp=sp, sp_mode=mode)
    report = run_prefill_ab(params, cfg, prompt, pre, mode="exact")
    assert report["accepted"] and report["argmax_agree"]
    assert report["sp_mode"] == mode


@cp_only
def test_cp_prefill_pinned_shape_compiles_once(tiny_model):
    """Different prompt lengths ride ONE padded executable — the
    compile-once contract of the longctx plane."""
    from hadoop_tpu.serving.longctx import ContextParallelPrefiller
    params, cfg = tiny_model
    pre = ContextParallelPrefiller(params, cfg, block_size=8,
                                   pad_tokens=200, sp=4)
    for n in (110, 150, 197):
        res = pre.cp_prefill(_prompt(cfg, n, seed=n))
        list(res.blocks)      # drain the stream
    assert pre.prefill_compiles == 1
    assert pre.head_compiles == 1


@cp_only
def test_guard_rejects_broken_ring_hop(tiny_model, monkeypatch):
    """A deliberately corrupted ring hop (one rank's attention output
    scaled) must be REJECTED by the exact guard — the A-B machinery is
    what stands between a silent CP bug and served logits."""
    import hadoop_tpu.parallel.ring_attention as ra
    from hadoop_tpu.parallel.lowp.guard import ParityGuardError
    from hadoop_tpu.serving.longctx import (ContextParallelPrefiller,
                                            run_prefill_ab)
    params, cfg = tiny_model
    orig = ra.ring_attention

    def broken(q, k, v, axis_name, axis_size, impl="auto"):
        out = orig(q, k, v, axis_name, axis_size, impl)
        rank = jax.lax.axis_index(axis_name)
        return out * jnp.where(rank == 1, 1.5, 1.0)

    monkeypatch.setattr(ra, "ring_attention", broken)
    pre = ContextParallelPrefiller(params, cfg, block_size=8,
                                   pad_tokens=160, sp=4)
    with pytest.raises(ParityGuardError):
        run_prefill_ab(params, cfg, _prompt(cfg, 150), pre,
                       mode="exact")


# ------------------------------------------------------------ end to end

@cp_only
def test_longctx_end_to_end_matches_single_chip(tiny_model):
    """The whole lane: submit through the ENGINE (routing seam), CP
    prefill, KV streamed to the host ring, working-set decode — greedy
    tokens identical to repeated single-chip forward."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, prefill_chunk=8,
                       kv_host_bytes=1 << 22, metrics=ServingMetrics())
    plane = _mk_plane(params, cfg, eng)
    eng.attach_longctx(plane)
    try:
        prompt = _prompt(cfg, 150)
        req = eng.submit(prompt, SamplingParams(max_new_tokens=6))
        toks = req.wait(180)
        assert toks == _reference_greedy(params, cfg, prompt, 6)
        # the fused step never ran: the monster prompt was the plane's
        assert eng.steps == 0
        st = plane.stats()
        assert st["requests"] == 1
        assert st["blocks_streamed"] == len(prompt) // 8
        kv = eng.kvstore.stats()
        assert kv["chain_ingested"] == len(prompt) // 8
        assert kv["hits_host"] >= len(prompt) // 8
        # working set stays a window+tail, far under the full context
        full_ctx_bytes = (len(prompt) * 2 * cfg.n_layers *
                          cfg.n_kv_heads * cfg.head_dim * 4)
        assert plane.decoder.hbm_working_set_bytes < full_ctx_bytes
        assert st["window_fetches"] > 0
    finally:
        eng.stop()


@cp_only
def test_streamed_chain_feeds_the_radix_path(tiny_model):
    """Interop: a SHORT prompt that is a prefix of a served monster
    prompt maps the longctx-streamed chain through the normal radix
    admission (fetch_cold promotions) — one digest scheme, two
    consumers."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, prefill_chunk=8,
                       kv_host_bytes=1 << 22, metrics=ServingMetrics())
    plane = _mk_plane(params, cfg, eng)
    eng.attach_longctx(plane)
    try:
        prompt = _prompt(cfg, 150)
        eng.submit(prompt, SamplingParams(max_new_tokens=2)).wait(180)
        short = prompt[:24]
        req = eng.submit(short, SamplingParams(max_new_tokens=3))
        while not req.done.is_set():
            eng.step()
        assert req.wait(0) == _reference_greedy(params, cfg, short, 3)
        assert eng.kvstore.promotions > 0
    finally:
        eng.stop()


@cp_only
def test_short_prompts_keep_the_fused_step(tiny_model):
    """Routing seam: below min_tokens the request rides the fused step
    exactly as before (compile-once intact), at/above it the plane
    serves without touching the step."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, prefill_chunk=8,
                       kv_host_bytes=1 << 20, metrics=ServingMetrics())
    plane = _mk_plane(params, cfg, eng, min_tokens=100)
    eng.attach_longctx(plane)
    try:
        short = _prompt(cfg, 20)
        req = eng.submit(short, SamplingParams(max_new_tokens=3))
        while not req.done.is_set():
            eng.step()
        assert req.wait(0) == _reference_greedy(params, cfg, short, 3)
        assert eng.decode_compiles == 1
        assert eng.prefill_compiles == 1
        long_req = eng.submit(_prompt(cfg, 120),
                              SamplingParams(max_new_tokens=2))
        long_req.wait(180)
        assert eng.decode_compiles == 1      # untouched by the plane
        assert eng.prefill_compiles == 1
    finally:
        eng.stop()


@cp_only
def test_engine_drain_finishes_longctx_request(tiny_model):
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 20,
                       metrics=ServingMetrics())
    plane = _mk_plane(params, cfg, eng)
    eng.attach_longctx(plane)
    req = eng.submit(_prompt(cfg, 120), SamplingParams(max_new_tokens=2))
    eng.stop(drain=True, timeout=180.0)
    assert req.done.is_set()
    assert req.state == "FINISHED"
    assert len(req.out_tokens) == 2


# ------------------------------------------ pipelined decode (fused path)

def _prefill_chain(params, cfg, eng, prompt):
    """CP prefill at sp=1 + stream the chain into the engine's tiers —
    the decoder-level fixtures' shared setup (the plane does exactly
    this per request)."""
    from hadoop_tpu.serving.longctx import ContextParallelPrefiller
    pre = ContextParallelPrefiller(params, cfg, block_size=8,
                                   pad_tokens=160, sp=1)
    res = pre.cp_prefill(prompt)
    eng.kvstore.ingest_chain(prompt, res.blocks)
    return res


def _run_decoder(params, cfg, eng, prompt, res, sampling, **kw):
    from hadoop_tpu.serving.longctx.decode import WorkingSetDecoder
    dec = WorkingSetDecoder(params, cfg, eng.kvstore, block_size=8,
                            window_blocks=3, tail_tokens=64, **kw)
    out = []
    dec.paged_decode(prompt, int(np.argmax(res.last_logits)), sampling,
                     tail_k=res.tail_k, tail_v=res.tail_v,
                     deliver=out.append, seed=11,
                     rng=np.random.default_rng(11))
    return out, dec


@cp_only
def test_pipelined_decode_is_token_identical_to_legacy(tiny_model):
    """The fused path's A-B vs the pre-pipelining loop it replaced:
    same chain, same tail, same sampler stream — identical tokens,
    greedy AND stochastic (the pipelined host-sampler fallback draws
    the legacy loop's exact rng stream; the in-graph device sampler is
    greedy-identical by construction). Alongside: the per-token budgets
    the pipelining exists for, audited on the real counters —
    dispatches <= 2 per (token, window) + head, and host->HBM
    transfers counted per (layer, slab), O(chain) instead of the
    legacy loop's O(layers x chain) window slices."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 22,
                       metrics=ServingMetrics())
    try:
        prompt = _prompt(cfg, 150)
        res = _prefill_chain(params, cfg, eng, prompt)
        greedy = SamplingParams(max_new_tokens=6)
        legacy, dl = _run_decoder(params, cfg, eng, prompt, res,
                                  greedy, pipeline=False)
        fused, df = _run_decoder(params, cfg, eng, prompt, res, greedy)
        host, _ = _run_decoder(params, cfg, eng, prompt, res, greedy,
                               sampler="host")
        assert fused == legacy == host and len(fused) == 5
        # stochastic A-B rides the host sampler on both arms
        sp = SamplingParams(max_new_tokens=6, temperature=0.8, top_k=5)
        a, _ = _run_decoder(params, cfg, eng, prompt, res, sp,
                            pipeline=False)
        b, _ = _run_decoder(params, cfg, eng, prompt, res, sp,
                            sampler="host")
        assert a == b
        # ---- budgets (chain = 18 full blocks = 144 tokens)
        chain = (len(prompt) // 8) * 8
        n_win = -(-chain // df.win)
        assert df.dispatches_per_token <= 2 * n_win + 1
        assert df.dispatches < dl.dispatches
        # fetches: one per (layer, slab) on the fused path — the slab
        # IS the transfer unit — one per (layer, window) SLICE legacy
        n_slabs = -(-chain // (df.fetch_windows * df.win))
        assert df.window_fetches == cfg.n_layers * n_slabs * 5
        assert dl.window_fetches == cfg.n_layers * n_win * 5
        assert df.window_fetches < dl.window_fetches
    finally:
        eng.stop()


@cp_only
def test_fused_family_compiles_once_across_tokens(tiny_model):
    """Compile-once on the fused family: a multi-token paged decode —
    across two decoder INSTANCES and both samplers — traces each of
    fstart/fadvance/fwin/ffinish/fhead exactly once (the module-level
    jit cache is per layout family, not per decoder)."""
    from hadoop_tpu.serving.longctx.decode import trace_counts
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 22,
                       metrics=ServingMetrics())
    try:
        prompt = _prompt(cfg, 150)
        res = _prefill_chain(params, cfg, eng, prompt)
        greedy = SamplingParams(max_new_tokens=5)
        _, dec = _run_decoder(params, cfg, eng, prompt, res, greedy)
        _run_decoder(params, cfg, eng, prompt, res, greedy,
                     sampler="host")
        fam = dec._fused.family
        tc = trace_counts()
        for piece in ("fstart", "fadvance", "fwin", "ffinish", "fhead"):
            assert tc[f"{piece}@{fam}"] == 1, (piece, tc)
    finally:
        eng.stop()


@cp_only
def test_int8_longctx_serves_and_guard_accepts(tiny_model):
    """int8-resident CP weights: the plane serves straight off the
    quantized tree (no dequantized second copy), the weight A-B guard
    accepts the arm, and a zeroed payload is REJECTED — the guard is
    falsifiable, not a rubber stamp."""
    from hadoop_tpu.serving.longctx import LongContextPlane
    from hadoop_tpu.serving.weightplane import (WeightPlaneConfig,
                                                dequantize_params,
                                                quantize_params,
                                                run_weight_ab)
    params, cfg = tiny_model
    wp = WeightPlaneConfig(tier="relaxed", quant_embed=True,
                           quant_head=True)
    qparams, rep = quantize_params(params, cfg, wp)
    assert rep["leaves_quantized"] > 0
    ab = run_weight_ab(cfg, params, qparams, wp=wp)
    assert ab["accepted"], ab
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 22,
                       metrics=ServingMetrics())
    plane = LongContextPlane(qparams, cfg, eng.kvstore, block_size=8,
                             min_tokens=100, max_tokens=256, sp=1,
                             window_blocks=3, tail_tokens=64,
                             metrics=eng.metrics)
    try:
        prompt = _prompt(cfg, 150)
        req = plane.longctx_submit(prompt,
                                   SamplingParams(max_new_tokens=4))
        toks = req.wait(180)
        # greedy off the int8 plane == greedy off the dequantized
        # reconstruction (numerically what qdot contracts against)
        assert toks == _reference_greedy(
            dequantize_params(qparams, cfg), cfg, prompt, 4)
        st = plane.stats()
        assert st["int8_weights"] is True
        assert st["dequantized_view_bytes"] == 0
    finally:
        plane.stop()
        eng.stop()
    # falsifiability: zero one layer matmul's payload -> rejected
    broken = dict(qparams)
    broken["layers"] = dict(qparams["layers"])
    wq = qparams["layers"]["wq"]
    broken["layers"]["wq"] = {"q": np.zeros_like(wq["q"]),
                              "s": wq["s"]}
    assert not run_weight_ab(cfg, params, broken, wp=wp)["accepted"]
    # the legacy loop cannot serve a quantized tree: loud, not wrong
    from hadoop_tpu.serving.longctx.decode import WorkingSetDecoder
    with pytest.raises(ValueError, match="pipeline"):
        WorkingSetDecoder(qparams, cfg, eng.kvstore, block_size=8,
                          pipeline=False)


def test_hbm_ledger_reflects_decode_double_buffer(tiny_model):
    """Live HBM ledger: the pipelined decoder's window component is
    BOTH in-flight slabs of the double buffer (2x one window at the
    default slab depth), the in-graph sampler registers its device
    state, /v1/health surfaces the same split, and stop() unregisters
    every owner — a stopped plane never haunts /prom."""
    from hadoop_tpu.obs.hbm import hbm_ledger
    from hadoop_tpu.serving.longctx.decode import WorkingSetDecoder
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 20,
                       metrics=ServingMetrics())
    plane = _mk_plane(params, cfg, eng, sp=1)
    eng.attach_longctx(plane)
    try:
        dec = plane.decoder
        assert dec.fetch_windows == cfg.n_layers
        # slab depth = n_layers => one slab costs exactly one window
        # of per-token working-set bytes; the double buffer costs two
        assert dec.hbm_window_bytes == 2 * dec.win * dec._per_tok_bytes
        assert dec.hbm_working_set_bytes == (
            dec.hbm_window_bytes + dec.tail_cap * dec._per_tok_bytes
            + dec.sampler_state_bytes)
        comps = hbm_ledger().report()["components"]
        assert comps["longctx_window"] == dec.hbm_window_bytes
        assert comps["longctx_tail"] == \
            dec.tail_cap * dec._per_tok_bytes
        assert comps["longctx_sampler"] == dec.sampler_state_bytes > 0
        from hadoop_tpu.conf import Configuration
        from hadoop_tpu.serving.server import ServingServer
        srv = ServingServer(eng, Configuration(load_defaults=False))
        _, health = srv._health({}, b"")
        assert health["hbm"]["components"]["longctx_window"] == \
            dec.hbm_window_bytes
        # the legacy loop keeps the pre-pipelining accounting: one
        # window in flight, no device sampler state
        dl = WorkingSetDecoder(params, cfg, eng.kvstore, block_size=8,
                               window_blocks=3, tail_tokens=64,
                               pipeline=False)
        assert dl.hbm_window_bytes == dl.win * dl._per_tok_bytes
        assert dl.sampler_state_bytes == 0
    finally:
        eng.stop()
    comps = hbm_ledger().report()["components"]
    assert "longctx_window" not in comps
    assert "longctx_sampler" not in comps


def test_plane_from_conf_reads_decode_pipeline_keys(tiny_model):
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.serving.longctx import longctx_plane_from_conf
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 20,
                       metrics=ServingMetrics())
    try:
        conf = Configuration(load_defaults=False)
        conf.set("serving.parity", "relaxed")
        conf.set("serving.longctx.min.tokens", "100")
        conf.set("serving.longctx.chips", "1")
        conf.set("serving.longctx.decode.pipeline", "false")
        conf.set("serving.longctx.decode.sampler", "host")
        plane = longctx_plane_from_conf(conf, cfg, eng)
        assert plane.decoder.pipeline is False
        assert plane.decoder.sampler == "host"
        plane.stop()
        conf.set("serving.longctx.decode.pipeline", "true")
        conf.set("serving.longctx.decode.fetch.windows", "2")
        plane = longctx_plane_from_conf(conf, cfg, eng)
        assert plane.decoder.pipeline is True
        assert plane.decoder.fetch_windows == 2
        plane.stop()
        conf.set("serving.longctx.decode.sampler", "bogus")
        with pytest.raises(ValueError, match="sampler"):
            longctx_plane_from_conf(conf, cfg, eng)
    finally:
        eng.stop()


# ------------------------------------------------------------ validation

def test_longctx_submit_validation(tiny_model):
    """Requests the plane can NEVER serve fail loudly at submit (the
    door's 400), not as a wedged worker."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 20,
                       metrics=ServingMetrics())
    plane = _mk_plane(params, cfg, eng, sp=1, tail_tokens=16)
    eng.attach_longctx(plane)
    try:
        with pytest.raises(ValueError, match="max.tokens"):
            eng.submit(_prompt(cfg, 300),
                       SamplingParams(max_new_tokens=2))
        with pytest.raises(ValueError, match="tail"):
            eng.submit(_prompt(cfg, 120),
                       SamplingParams(max_new_tokens=32))
    finally:
        eng.stop()


def test_host_ring_too_small_for_chain_is_loud(tiny_model):
    params, cfg = tiny_model
    # a ring that holds ~4 blocks cannot hold a 15-block chain and
    # there is no DFS tier behind it — reject at the door
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64,
                       kv_host_bytes=4 * 2 * cfg.n_layers * 8 *
                       cfg.n_kv_heads * cfg.head_dim * 4,
                       metrics=ServingMetrics())
    plane = _mk_plane(params, cfg, eng, sp=1)
    eng.attach_longctx(plane)
    try:
        with pytest.raises(ValueError, match="host-ring|host.ring|ring"):
            eng.submit(_prompt(cfg, 130),
                       SamplingParams(max_new_tokens=2))
    finally:
        eng.stop()


def test_plane_requires_cold_tier(tiny_model):
    from hadoop_tpu.serving.longctx import LongContextPlane
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64)
    try:
        with pytest.raises(ValueError, match="cold|host|dfs"):
            LongContextPlane(params, cfg, eng.kvstore, block_size=8,
                             min_tokens=100)
    finally:
        eng.stop()


def test_plane_from_conf_requires_relaxed_parity(tiny_model):
    """The tier gate: under the bitwise default the plane must be
    unconstructable — CP softmax reassociation is not bitwise."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.serving.longctx import longctx_plane_from_conf
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 20,
                       metrics=ServingMetrics())
    try:
        conf = Configuration(load_defaults=False)
        with pytest.raises(ValueError, match="relaxed"):
            longctx_plane_from_conf(conf, cfg, eng)
        conf.set("serving.parity", "relaxed")
        conf.set("serving.longctx.min.tokens", "100")
        conf.set("serving.longctx.chips", "2")
        plane = longctx_plane_from_conf(conf, cfg, eng)
        assert plane.min_tokens == 100
        assert plane.prefiller.sp == 2
        plane.stop()
    finally:
        eng.stop()


def test_health_exposes_longctx_stats(tiny_model):
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                       max_context=64, kv_host_bytes=1 << 20,
                       metrics=ServingMetrics())
    plane = _mk_plane(params, cfg, eng, sp=1)
    eng.attach_longctx(plane)
    srv = ServingServer(eng, Configuration(load_defaults=False))
    try:
        status, health = srv._health({}, b"")
        assert status == 200
        assert health["longctx"]["enabled"] is True
        assert health["longctx"]["chips"] == 1
    finally:
        eng.stop()
    # a bitwise replica reports the plane absent
    plain = DecodeEngine(params, cfg, max_batch=2, block_size=8,
                         max_context=64)
    assert plain.longctx_stats() == {"enabled": False}
    plain.stop()


# ------------------------------------------- router prefill capacity gate

def _rec(path, role, **attrs):
    from hadoop_tpu.registry import ServiceRecord
    a = {"state": "serving", "role": role}
    a.update({k: str(v) for k, v in attrs.items()})
    return ServiceRecord(path, {"http": "127.0.0.1:9"}, a)


def _router(conf=None):
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.serving.router import ServingRouter
    conf = conf or Configuration(load_defaults=False)
    conf.set("serving.router.prefill.min.tokens", "8")
    return ServingRouter(("127.0.0.1", 1), "svc", conf)


def test_router_skips_undersized_prefill_replica(monkeypatch):
    """The capacity gate: a monster prompt is never OFFERED to a
    prefill replica whose advertised HBM pool cannot hold its paged
    working set — loud skip with a counter, not a handoff failure.
    (The host ring backs demotions, not admissions, so it does NOT
    count toward prefill capacity.)"""
    r = _router()
    # pool of 4 blocks x 4 tokens = 16 tokens; a fat host ring must
    # not make a 100-token prompt look admittable
    small = _rec("/services/serving/svc/small", "prefill",
                 kv_block_bytes=1024, kv_block_size=4, kv_hbm_blocks=4,
                 kv_host_bytes=1 << 30)
    dec = _rec("/services/serving/svc/dec", "decode")
    monkeypatch.setattr(r, "replicas",
                        lambda refresh=False: [small, dec])
    posts = []
    monkeypatch.setattr(r, "_post",
                        lambda *a, **k: posts.append(a) or {})
    shipped = r._maybe_offload_prefill(
        {"tokens": list(range(100))}, None)
    assert shipped is False
    assert r.prefill_capacity_skips == 1
    assert posts == []
    r.close()


def test_router_offloads_to_the_replica_that_fits(monkeypatch):
    r = _router()
    small = _rec("/services/serving/svc/small", "prefill",
                 kv_block_bytes=1024, kv_block_size=4, kv_hbm_blocks=4,
                 kv_host_bytes=0)
    big = _rec("/services/serving/svc/big", "prefill",
               kv_block_bytes=1024, kv_block_size=4, kv_hbm_blocks=64,
               kv_host_bytes=0)
    dec = _rec("/services/serving/svc/dec", "decode")
    monkeypatch.setattr(r, "replicas",
                        lambda refresh=False: [small, big, dec])
    posts = []
    monkeypatch.setattr(
        r, "_post",
        lambda rec, *a, **k: posts.append(rec.path) or
        {"persisted_tokens": 100})
    assert r._maybe_offload_prefill({"tokens": list(range(100))},
                                    None) is True
    assert posts == ["/services/serving/svc/big"]
    assert r.prefill_capacity_skips == 1
    r.close()


def test_router_longctx_replica_is_never_capacity_skipped(monkeypatch):
    """A replica advertising the long-context plane + DFS streams
    monster prompts into the cold tiers — its tiny HBM pool must not
    disqualify it (that pool is exactly what longctx works around)."""
    r = _router()
    lcx = _rec("/services/serving/svc/lcx", "prefill",
               kv_block_bytes=1024, kv_block_size=4, kv_hbm_blocks=4,
               kv_host_bytes=0, longctx=1, kv_dfs=1)
    dec = _rec("/services/serving/svc/dec", "decode")
    monkeypatch.setattr(r, "replicas",
                        lambda refresh=False: [lcx, dec])
    posts = []
    monkeypatch.setattr(
        r, "_post",
        lambda rec, *a, **k: posts.append(rec.path) or
        {"persisted_tokens": 100000})
    assert r._maybe_offload_prefill({"tokens": list(range(100000))},
                                    None) is True
    assert posts == ["/services/serving/svc/lcx"]
    assert r.prefill_capacity_skips == 0
    r.close()


def test_router_respects_longctx_pinned_budget(monkeypatch):
    """...but only up to the plane's advertised pinned prompt budget:
    past serving.longctx.max.tokens the replica's door rejects, so the
    gate must skip rather than burn a doomed handoff."""
    r = _router()
    lcx = _rec("/services/serving/svc/lcx", "prefill",
               kv_block_bytes=1024, kv_block_size=4, kv_hbm_blocks=4,
               longctx=1, kv_dfs=1, longctx_max_tokens=4096)
    dec = _rec("/services/serving/svc/dec", "decode")
    monkeypatch.setattr(r, "replicas",
                        lambda refresh=False: [lcx, dec])
    posts = []
    monkeypatch.setattr(r, "_post",
                        lambda *a, **k: posts.append(a) or {})
    assert r._maybe_offload_prefill({"tokens": list(range(5000))},
                                    None) is False
    assert posts == []
    assert r.prefill_capacity_skips == 1
    r.close()


def test_router_keeps_legacy_records_eligible(monkeypatch):
    """Records without capacity attributes (hand-registered,
    mid-upgrade) must stay eligible — a stricter router cannot starve
    an older fleet."""
    r = _router()
    legacy = _rec("/services/serving/svc/old", "prefill")
    dec = _rec("/services/serving/svc/dec", "decode")
    monkeypatch.setattr(r, "replicas",
                        lambda refresh=False: [legacy, dec])
    posts = []
    monkeypatch.setattr(
        r, "_post",
        lambda rec, *a, **k: posts.append(rec.path) or
        {"persisted_tokens": 8})
    assert r._maybe_offload_prefill({"tokens": list(range(50))},
                                    None) is True
    assert posts and r.prefill_capacity_skips == 0
    r.close()
