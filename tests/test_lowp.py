"""Relaxed-parity plane: quantizer numerics, guard math, tier gating.

Three layers of coverage, mirroring test_overlap.py's structure:

- Primitive tests run the quantized collectives inside a bare
  shard_map against their exact forms and bound the error (SQNR /
  allclose) — plus the edge cases a codec must not mangle: all-zero
  groups decode exactly zero, denormals flush finite, integer buckets
  stay exact, and a mismatched payload header is a loud error.
- Tier-gating tests prove the contract tpulint enforces lexically:
  with the bitwise tier (the default) NO lowp entry point is
  reachable — poisoned quantizers don't fire — and the chunked
  collective matmul only compiles under the relaxed tier.
- Full-step A-B tests run the real train step relaxed vs bitwise
  (dp2×tp2+sp over ≥50 steps, zero1 dp8 over ≥50 steps) through the
  loss-curve guard, asserting acceptance AND the ≥2× quantized
  payload-byte contract. vma-gated like the seed parallel suite.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from hadoop_tpu.parallel.lowp import (BITWISE_PARITY, RELAXED_PARITY,
                                      ParityConfig, parity_from_conf)
from hadoop_tpu.parallel.lowp.guard import (ParityGuardError,
                                            allclose_guard,
                                            loss_curve_report)
from hadoop_tpu.parallel.lowp.quant import (RelaxedQuant, capture_comm,
                                            decode_payload,
                                            encode_payload,
                                            psum_of_scatter_quantized,
                                            psum_quantized,
                                            psum_scatter_quantized)

requires_vma = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="multichip train step needs jax vma tracking "
           "(jax.typeof); same gap that fails the seed parallel suite "
           "on this jax")


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("x",))


def _smap(f, mesh, in_specs, out_specs):
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


def _rq(codec="int8", group=64):
    return RelaxedQuant(codec=codec, group=group,
                        mesh_axis_sizes={"x": 4})


def _sqnr_db(ref, got):
    ref = np.asarray(ref, np.float64)
    err = ref - np.asarray(got, np.float64)
    return 10 * np.log10(np.sum(ref ** 2) / max(np.sum(err ** 2), 1e-30))


# ------------------------------------------------------ quantized psum

@pytest.mark.parametrize("codec", ["int8", "fp8"])
def test_psum_quantized_allclose_with_sqnr_bound(codec):
    mesh = _mesh()
    # mixed magnitudes per group stress the shared-scale design
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 200), jnp.float32) \
        * jnp.array([1e-3, 1.0, 50.0, 1e3])[:, None]
    ref = jax.jit(_smap(lambda t: jax.lax.psum(t, ("x",)), mesh,
                        (P("x", None),), P("x", None)))(x)
    got = jax.jit(_smap(lambda t: psum_quantized(t, ("x",), _rq(codec)),
                        mesh, (P("x", None),), P("x", None)))(x)
    ref, got = np.asarray(ref), np.asarray(got)
    # int8 at 4-rank headroom keeps ~5 bits; 20 dB is a loose floor
    # (measured ~28 dB int8, ~30 dB fp8 on this workload)
    assert _sqnr_db(ref, got) > 20.0
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.1


def test_psum_quantized_single_rank_is_exact_passthrough():
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32), jnp.float32)
    rq = RelaxedQuant(codec="int8", mesh_axis_sizes={"x": 1})
    got = jax.jit(_smap(lambda t: psum_quantized(t, (), rq), mesh,
                        (P("x", None),), P("x", None)))(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(x))


def test_quantized_zeros_decode_exactly_zero():
    mesh = _mesh()
    got = jax.jit(_smap(lambda t: psum_quantized(t, ("x",), _rq()),
                        mesh, (P("x", None),), P("x", None)))(
        jnp.zeros((4, 64), jnp.float32))
    assert (np.asarray(got) == 0).all()


def test_quantized_denormals_flush_finite():
    # group amax below the scale floor: values flush to exact zero
    # instead of dividing by a denormal scale into inf/nan
    mesh = _mesh()
    got = jax.jit(_smap(lambda t: psum_quantized(t, ("x",), _rq()),
                        mesh, (P("x", None),), P("x", None)))(
        jnp.full((4, 64), 1e-38, jnp.float32))
    got = np.asarray(got)
    assert np.isfinite(got).all()


def test_integer_buckets_stay_exact_on_relaxed_tier():
    from hadoop_tpu.parallel.overlap import bucketed_psum
    mesh = _mesh()
    tree = {"i": jnp.arange(8, dtype=jnp.int32).reshape(4, 2)}
    axes = {"i": ("x",)}

    def run(t):
        return bucketed_psum(t, axes, 1 << 20, relaxed=_rq())
    got = jax.jit(_smap(run, mesh, ({"i": P("x", None)},),
                        {"i": P("x", None)}))(tree)
    ref = jax.jit(_smap(
        lambda t: {"i": jax.lax.psum(t["i"], ("x",))}, mesh,
        ({"i": P("x", None)},), {"i": P("x", None)}))(tree)
    np.testing.assert_array_equal(np.asarray(got["i"]),
                                  np.asarray(ref["i"]))


def test_wire_widens_past_int8_headroom():
    """127 // n hits zero at n >= 128 — the wire must widen to int16
    (still 2x under f32) instead of letting the int8 accumulator wrap,
    and refuse outright past the int16 range."""
    from hadoop_tpu.parallel.lowp.quant import _wire_for
    assert _wire_for(4) == (jnp.int8, 31)
    assert _wire_for(127) == (jnp.int8, 1)
    wire, qmax = _wire_for(256)
    assert wire == jnp.int16 and qmax == 32767 // 256
    assert qmax * 256 <= 32767          # the no-wrap invariant
    with pytest.raises(ValueError, match="int16 wire"):
        _wire_for(40000)


def test_relaxed_parity_requires_overlap_pass():
    """relaxed with the overlap pass disabled must be a loud error —
    silently building the bitwise graph would label bench rows and
    A-B arms 'relaxed' while measuring the bitwise tier."""
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.overlap import OVERLAP_OFF
    from hadoop_tpu.parallel.train import make_train_step
    cfg = get_config("tiny")
    plan = MeshPlan(dp=2)
    mesh = make_mesh(plan)
    with pytest.raises(ValueError, match="overlap"):
        make_train_step(cfg, plan, mesh, overlap=OVERLAP_OFF,
                        parity=RELAXED_PARITY)


# --------------------------------------------------- quantized scatter

def test_psum_scatter_quantized_group_matches_reference():
    mesh = _mesh()
    y = jax.random.normal(jax.random.PRNGKey(1), (16, 100), jnp.float32)

    def sc_ref(t):           # psum + this rank's row of the [Z,K] bucket
        full = jax.lax.psum(t, ("x",))
        i = jax.lax.axis_index("x")
        return jax.lax.dynamic_slice_in_dim(full, i, 1, 0).reshape(-1)

    a = jax.jit(_smap(sc_ref, mesh, (P("x", None),), P("x")))(y)
    b = jax.jit(_smap(lambda t: psum_scatter_quantized(t, "x", _rq()),
                      mesh, (P("x", None),), P("x")))(y)
    assert _sqnr_db(np.asarray(a), np.asarray(b)) > 20.0


def test_psum_scatter_quantized_tensor_scale_dim1():
    # the megatron-SP activation shape: scatter the SEQUENCE dim (1)
    mesh = _mesh()
    z = jax.random.normal(jax.random.PRNGKey(2), (8, 8, 16), jnp.float32)

    def sct_ref(t):
        full = jax.lax.psum(t, ("x",))
        i = jax.lax.axis_index("x")
        return jax.lax.dynamic_slice_in_dim(full, i * 2, 2, 1)

    def sct_q(t):
        return psum_scatter_quantized(t, "x", _rq(), scatter_dimension=1,
                                      scale="tensor")

    a = jax.jit(_smap(sct_ref, mesh, (P("x",),), P("x", None, None)))(z)
    b = jax.jit(_smap(sct_q, mesh, (P("x",),), P("x", None, None)))(z)
    assert _sqnr_db(np.asarray(a), np.asarray(b)) > 20.0


def test_psum_scatter_quantized_group_rejects_bad_layout():
    with pytest.raises(ValueError, match=r"\[Z, K\] bucket layout"):
        psum_scatter_quantized(jnp.zeros((2, 3, 4)), "x", _rq())


@pytest.mark.parametrize("codec", ["int8", "fp8"])
def test_psum_of_scatter_quantized_full_range(codec):
    """The ZeRO-1 gather wire: disjoint contributions quantize at full
    range — int8 must land well above the headroom'd psum's SQNR."""
    mesh = _mesh()
    rows = jax.random.normal(jax.random.PRNGKey(3), (4, 150),
                             jnp.float32)

    def g_ref(t):
        t = t.reshape(-1)
        i = jax.lax.axis_index("x")
        buf = jnp.zeros((4, 150), t.dtype)
        buf = jax.lax.dynamic_update_slice(
            buf, t[None, :], (i, jnp.zeros((), jnp.int32)))
        return jax.lax.psum(buf, ("x",))

    def g_q(t):
        t = t.reshape(-1)
        i = jax.lax.axis_index("x")
        return psum_of_scatter_quantized(t, 4, i, ("x",),
                                         _rq(codec))[:, :150]

    a = jax.jit(_smap(g_ref, mesh, (P("x", None),), P(None, None)))(rows)
    b = jax.jit(_smap(g_q, mesh, (P("x", None),), P(None, None)))(rows)
    sqnr = _sqnr_db(np.asarray(a), np.asarray(b))
    assert sqnr > (25.0 if codec == "fp8" else 40.0)


# ------------------------------------------- straight-through backward

def test_quantized_psum_gradient_is_exact_transpose():
    """The STE contract: rint/clip have measure-zero gradients, so a
    naively differentiated quantized collective returns ZERO cotangents
    and training silently stalls. The backward must be the exact
    psum's transpose — the cotangent flows through untouched."""
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 64), jnp.float32)

    def f(t):
        return jnp.sum(psum_quantized(t, ("x",), _rq(),
                                      scale="tensor") * 3.0)

    g = jax.jit(_smap(lambda t: jax.grad(f)(t), mesh,
                      (P("x", None),), P("x", None)))(x)
    assert (np.asarray(g) == 3.0).all()


def test_quantized_scatter_gradient_is_allgather_transpose():
    mesh = _mesh()
    z = jax.random.normal(jax.random.PRNGKey(2), (8, 8, 16), jnp.float32)

    def f(t):
        return jnp.sum(psum_scatter_quantized(
            t, "x", _rq(), scatter_dimension=1, scale="tensor") * 2.0)

    g = jax.jit(_smap(lambda t: jax.grad(f)(t), mesh,
                      (P("x",),), P("x",)))(z)
    assert (np.asarray(g) == 2.0).all()


def test_relaxed_project_gradients_flow_nonzero():
    """End-to-end through the quantized chunked projection: gradients
    must be finite and nonzero (the stall the STE exists to prevent)."""
    from hadoop_tpu.ops.collective_matmul import row_parallel_project
    mesh = _mesh()
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 24), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 32),
                          jnp.float32)
    ctx = _ctx(relaxed_chunk_matmul=True, relaxed_codec="int8")

    def loss(w_, x_):
        return jnp.mean(row_parallel_project(x_, w_, ctx) ** 2)

    g = np.asarray(jax.jit(_smap(
        lambda ww, xx: jax.grad(loss)(ww, xx), mesh,
        (P("x", None), P(None, None, "x")), P("x", None)))(w, x))
    assert np.isfinite(g).all() and np.abs(g).max() > 0


# --------------------------------------------------------- comm ledger

def test_comm_ledger_proves_payload_reduction():
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 256), jnp.float32)
    fn = _smap(lambda t: psum_quantized(t, ("x",), _rq()), mesh,
               (P("x", None),), P("x", None))
    with capture_comm() as led:
        jax.jit(fn)(x)
    assert led.sites and led.payload_bytes > 0
    # f32 → int8 + per-64 f32 scales: 4 bytes → ~1.06 bytes per element
    assert led.ratio >= 2.0
    assert led.report()["ratio"] == round(led.ratio, 3)
    # recording is scoped to the capture
    before = led.payload_bytes
    jax.jit(_smap(lambda t: psum_quantized(t, ("x",), _rq(group=32)),
                  mesh, (P("x", None),), P("x", None)))(x)
    assert led.payload_bytes == before


# -------------------------------------------------- host payload codec

@pytest.mark.parametrize("codec", ["int8", "fp8"])
def test_payload_roundtrip(codec):
    x = np.random.default_rng(0).normal(size=(7, 33)).astype(np.float32)
    out, header = decode_payload(encode_payload(x, codec=codec))
    assert header["codec"] == codec
    assert out.shape == x.shape and out.dtype == x.dtype
    assert _sqnr_db(x, out) > 25.0
    # quantized payload is strictly smaller than the raw array past
    # the fixed header (the point of the wire codec)
    assert len(encode_payload(x, codec=codec)) < x.nbytes + 200


def test_payload_header_mismatches_are_loud():
    x = np.ones((4, 8), np.float32)
    blob = encode_payload(x, codec="int8")
    with pytest.raises(ValueError, match="codec"):
        decode_payload(blob, codec="fp8")
    with pytest.raises(ValueError, match="shape"):
        decode_payload(blob, shape=(8, 4))
    with pytest.raises(ValueError, match="dtype"):
        decode_payload(blob, dtype=np.float64)
    with pytest.raises(ValueError, match="truncated"):
        decode_payload(blob[:-3])
    with pytest.raises(ValueError, match="truncated"):
        decode_payload(b"\x00\x01")
    with pytest.raises(ValueError, match="codec"):
        encode_payload(x, codec="int4")


# ------------------------------------------------ chunked matmul tier

def _ctx(**kw):
    from hadoop_tpu.models.decoder import ParallelCtx
    return ParallelCtx(tp_axis="x", tp_size=4, tp_overlap_chunks=4, **kw)


def _project(ctx, x, w, bias, mesh, out_specs=P()):
    from hadoop_tpu.ops.collective_matmul import row_parallel_project
    ins = (P(None, None, "x"), P("x", None), P())
    return np.asarray(jax.jit(_smap(
        lambda x_, w_, b_: row_parallel_project(x_, w_, ctx, bias=b_),
        mesh, ins, out_specs))(x, w, bias))


def test_chunked_matmul_forward_value_exact_backward_reassociates():
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32),
                          jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 24), jnp.float32)
    bias = jax.random.normal(jax.random.PRNGKey(2), (24,), jnp.float32)
    a = _project(_ctx(), x, w, bias, mesh)
    b = _project(_ctx(relaxed_chunk_matmul=True), x, w, bias, mesh)
    # forward: disjoint row chunks of the same product — bitwise
    np.testing.assert_array_equal(a, b)

    from hadoop_tpu.ops.collective_matmul import row_parallel_project

    def gw(ctx):
        def loss(w_, x_):
            return jnp.sum(
                row_parallel_project(x_, w_, ctx, bias=bias) ** 2)
        return np.asarray(jax.jit(_smap(
            lambda ww, xx: jax.grad(loss)(ww, xx), mesh,
            (P("x", None), P(None, None, "x")), P("x", None)))(w, x))

    ga, gb = gw(_ctx()), gw(_ctx(relaxed_chunk_matmul=True))
    # backward: the weight-grad contraction reassociates — allclose,
    # and NOT bitwise (the measured fact that parks this transform in
    # the relaxed tier; if it ever comes back bitwise the chunking
    # silently stopped happening)
    np.testing.assert_allclose(ga, gb, rtol=1e-5, atol=1e-5)
    assert not (ga == gb).all()


def test_chunked_matmul_megatron_sp_forward_value_exact():
    mesh = _mesh()
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32),
                          jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 24), jnp.float32)
    bias = jax.random.normal(jax.random.PRNGKey(2), (24,), jnp.float32)
    a = _project(_ctx(megatron_sp=True), x, w, bias, mesh,
                 out_specs=P(None, "x", None))
    b = _project(_ctx(megatron_sp=True, relaxed_chunk_matmul=True),
                 x, w, bias, mesh, out_specs=P(None, "x", None))
    np.testing.assert_array_equal(a, b)


def test_bitwise_tier_never_reaches_lowp_entry_points(monkeypatch):
    """The gating contract: with relaxed off, poisoned quantizers must
    never fire — through the bucketed collectives OR the tp reduce."""
    import hadoop_tpu.parallel.lowp.quant as quant
    from hadoop_tpu.ops.collective_matmul import row_parallel_project
    from hadoop_tpu.parallel.overlap import bucketed_psum

    def boom(*a, **k):
        raise AssertionError("lowp entry point reached on bitwise tier")

    monkeypatch.setattr(quant, "psum_quantized", boom)
    monkeypatch.setattr(quant, "psum_scatter_quantized", boom)
    monkeypatch.setattr(quant, "psum_of_scatter_quantized", boom)
    mesh = _mesh()
    tree = {"a": jax.random.normal(jax.random.PRNGKey(0), (33,))}
    got = jax.jit(_smap(
        lambda t: bucketed_psum(t, {"a": ("x",)}, 1 << 20),
        mesh, ({"a": P()},), {"a": P()}))(tree)
    assert np.isfinite(np.asarray(got["a"])).all()
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 8), jnp.float32)
    ctx = _ctx()
    out = jax.jit(_smap(
        lambda x_, w_: row_parallel_project(x_, w_, ctx), mesh,
        (P(None, None, "x"), P("x", None)), P()))(x, w)
    assert np.isfinite(np.asarray(out)).all()
    # and the relaxed tier DOES reach them (the poison fires at trace)
    rq = _rq()
    with pytest.raises(AssertionError, match="bitwise tier"):
        jax.jit(_smap(
            lambda t: bucketed_psum(t, {"a": ("x",)}, 1 << 20,
                                    relaxed=rq),
            mesh, ({"a": P()},), {"a": P()}))(tree)


# ----------------------------------------------------------- guard math

def test_loss_curve_report_accepts_close_curves():
    b = [5.0 - 0.05 * i for i in range(50)]
    r = [x * 1.02 for x in b]
    rep = loss_curve_report(b, r, rel_tol=0.25)
    assert rep["accepted"] and rep["max_rel_div"] < 0.03


def test_loss_curve_report_rejects_divergence_nonfinite_and_flat():
    b = [5.0 - 0.05 * i for i in range(50)]
    rep = loss_curve_report(b, [x * 2.0 for x in b], rel_tol=0.25)
    assert not rep.get("accepted") and "max_rel_div" in rep["reason"]
    rep = loss_curve_report(b, b[:-1] + [float("nan")], rel_tol=0.25)
    assert not rep.get("accepted") and rep["reason"] == "non-finite loss"
    rep = loss_curve_report(b, list(b[:1]) * 50, rel_tol=10.0)
    assert not rep.get("accepted") and "did not learn" in rep["reason"]
    rep = loss_curve_report(b, b[:10], rel_tol=0.25)
    assert not rep.get("accepted") and "length" in rep["reason"]


def test_allclose_guard_reports_and_raises():
    rep = allclose_guard("ok", [1.0, 2.0], [1.0, 2.0 + 1e-7])
    assert rep["max_abs"] < 1e-6
    with pytest.raises(ParityGuardError, match="max_abs"):
        allclose_guard("bad", np.ones(4), np.ones(4) * 1.5)
    with pytest.raises(ParityGuardError, match="arity"):
        allclose_guard("arity", [np.ones(2)], [np.ones(2), np.ones(2)])


# ----------------------------------------------------------------- conf

def test_parity_from_conf_defaults_and_overrides():
    from hadoop_tpu.conf import Configuration
    assert parity_from_conf(None) == BITWISE_PARITY
    conf = Configuration(load_defaults=False)
    assert parity_from_conf(conf) == ParityConfig()
    assert not parity_from_conf(conf).relaxed
    conf.set("parallel.parity", "relaxed")
    conf.set("parallel.lowp.codec", "fp8")
    conf.set("parallel.lowp.quant.buckets", "false")
    conf.set("parallel.lowp.quant.group", "256")
    conf.set("parallel.lowp.guard.steps", "20")
    conf.set("parallel.lowp.guard.rel-tol", "0.1")
    got = parity_from_conf(conf)
    assert got == ParityConfig(tier="relaxed", codec="fp8",
                               quant_buckets=False, group=256,
                               guard_steps=20, guard_rel_tol=0.1)
    assert got.relaxed


def test_parity_config_rejects_unknown_tier_and_codec():
    with pytest.raises(ValueError, match="parallel.parity"):
        ParityConfig(tier="fast-and-loose")
    with pytest.raises(ValueError, match="codec"):
        ParityConfig(codec="int4")
    with pytest.raises(ValueError, match="codec"):
        RelaxedQuant(codec="int4")


# ---------------------------------- partially synchronized activations


def test_sync_schedule_parsing_and_merge():
    from hadoop_tpu.parallel.lowp.syncpolicy import resolve_schedule
    assert resolve_schedule("full", 4) == ("sync",) * 4
    assert resolve_schedule("none", 4) == ("skip",) * 4
    assert resolve_schedule("none", 4, off_mode="stale") == ("stale",) * 4
    assert resolve_schedule("periodic:2", 4) == \
        ("sync", "skip", "sync", "skip")
    assert resolve_schedule("periodic:3", 7) == \
        ("sync", "skip", "skip", "sync", "skip", "skip", "sync")
    # periodic:1 ≡ full by construction
    assert resolve_schedule("periodic:1", 6) == ("sync",) * 6
    # layers: overrides merge with (and win over) the periodic base
    assert resolve_schedule("periodic:2+layers:1=sync,2=stale", 4) == \
        ("sync", "sync", "stale", "skip")
    assert resolve_schedule("layers:*=skip+layers:0=sync", 3) == \
        ("sync", "skip", "skip")
    # later clauses refine earlier IN SPEC ORDER: a trailing wildcard
    # really does force the whole stack
    assert resolve_schedule("layers:0=sync+layers:*=skip", 3) == \
        ("skip",) * 3


def test_sync_guard_tolerance_picked_on_resolved_schedule():
    """The loose schedule tolerance applies only when the RESOLVED
    schedule actually turns a sync off — periodic:1 / layers:*=sync /
    tp=1 build the exact full graph and keep the strict quantization
    bar."""
    from hadoop_tpu.parallel.lowp.guard import guard_rel_tol_for
    strict = RELAXED_PARITY.guard_rel_tol
    loose = RELAXED_PARITY.sync_guard_rel_tol
    assert guard_rel_tol_for(RELAXED_PARITY, 4, tp=2) == strict
    p1 = ParityConfig(tier="relaxed", relaxed_sync="periodic:1")
    assert guard_rel_tol_for(p1, 4, tp=2) == strict
    allsync = ParityConfig(tier="relaxed", relaxed_sync="layers:*=sync")
    assert guard_rel_tol_for(allsync, 4, tp=2) == strict
    p2 = ParityConfig(tier="relaxed", relaxed_sync="periodic:2")
    assert guard_rel_tol_for(p2, 4, tp=2) == loose
    assert guard_rel_tol_for(p2, 4, tp=1) == strict   # no tp, no sync


def test_sync_schedule_malformed_specs_raise_loud():
    from hadoop_tpu.parallel.lowp.syncpolicy import resolve_schedule
    for bad in ("", "sometimes", "periodic:", "periodic:x", "periodic:0",
                "layers:", "layers:1", "layers:1=never", "layers:x=skip",
                "layers:-1=skip", "full+none", "periodic:2+periodic:3"):
        with pytest.raises(ValueError, match="parallel.lowp.sync"):
            resolve_schedule(bad, 4)
    with pytest.raises(ValueError, match="out of range"):
        resolve_schedule("layers:9=skip", 4)
    with pytest.raises(ValueError, match="parallel.lowp.sync.mode"):
        resolve_schedule("periodic:2", 4, off_mode="maybe")
    # ParityConfig validates the grammar at config time
    with pytest.raises(ValueError, match="parallel.lowp.sync"):
        ParityConfig(relaxed_sync="periodic:zero")
    with pytest.raises(ValueError, match="parallel.lowp.sync.mode"):
        ParityConfig(relaxed_sync_mode="defer")


def test_sync_schedule_tp1_plans_forced_full_by_construction():
    """A plan without a tp axis has no sync to schedule: plan.ctx
    drops the schedule entirely (None == full), so tp=1 relaxed runs
    build the exact same graph whatever the conf says."""
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel import MeshPlan
    cfg = get_config("tiny")
    ctx = MeshPlan(dp=2).ctx(cfg, relaxed_sync=("skip",) * cfg.n_layers)
    assert ctx.relaxed_sync is None
    ctx2 = MeshPlan(dp=2, tp=2).ctx(
        cfg, relaxed_sync=("skip",) * cfg.n_layers)
    assert ctx2.relaxed_sync == ("skip",) * cfg.n_layers


def test_sync_schedule_policy_roundtrips_conf_and_bench_json():
    """The satellite pin: parallel.lowp.sync.* conf keys land on
    ParityConfig, and dataclasses.asdict carries them into bench JSON
    (the self-describing tier policy dict profile_train records)."""
    import dataclasses
    import json

    from hadoop_tpu.conf import Configuration
    conf = Configuration(load_defaults=False)
    conf.set("parallel.parity", "relaxed")
    conf.set("parallel.lowp.sync.schedule", "periodic:2+layers:0=stale")
    conf.set("parallel.lowp.sync.mode", "stale")
    got = parity_from_conf(conf)
    assert got.relaxed_sync == "periodic:2+layers:0=stale"
    assert got.relaxed_sync_mode == "stale"
    row = json.loads(json.dumps(dataclasses.asdict(got)))
    assert row["relaxed_sync"] == "periodic:2+layers:0=stale"
    assert row["relaxed_sync_mode"] == "stale"
    # defaults: schedule full, mode skip
    assert BITWISE_PARITY.relaxed_sync == "full"
    assert BITWISE_PARITY.relaxed_sync_mode == "skip"


def _tp_mesh_and_model(tp=2):
    from hadoop_tpu.models import get_config
    from hadoop_tpu.models.decoder import init_params
    from hadoop_tpu.parallel.mesh import (MeshPlan, make_mesh,
                                          param_specs)
    plan = MeshPlan(tp=tp)
    mesh = make_mesh(plan)
    cfg = get_config("tiny", max_seq=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    return plan, mesh, cfg, params, param_specs(cfg, plan), tokens


def _scheduled_forward(sched_spec, key, off_mode="skip"):
    """Trace + dispatch a tp=2 decoder forward under a sync schedule,
    through the REAL runtime dispatch seam; returns (out, profile)."""
    from hadoop_tpu.models.decoder import ParallelCtx, forward_hidden
    from hadoop_tpu.obs.comm import comm_runtime
    from hadoop_tpu.parallel.lowp.syncpolicy import resolve_schedule
    plan, mesh, cfg, params, specs, tokens = _tp_mesh_and_model()
    sched = resolve_schedule(sched_spec, cfg.n_layers, off_mode) \
        if sched_spec else None
    ctx = ParallelCtx(tp_axis="tp", tp_size=2, relaxed_sync=sched)
    fn = _smap(lambda p, t: forward_hidden(p, t, cfg, ctx), mesh,
               (specs, P(None, None)), P(None, None, None))
    rt = comm_runtime()
    with rt.step(key):
        out = jax.jit(fn)(params, tokens)
        out.block_until_ready()
    return np.asarray(out), rt.profile(key)


def test_periodic1_is_full_collective_count_identical():
    """periodic:1 ≡ full: bitwise-identical outputs AND an identical
    per-step ledger profile (payload/reference/executions), pinned at
    the dispatch seam."""
    full, prof_full = _scheduled_forward(None, "sync.t1.full")
    p1, prof_p1 = _scheduled_forward("periodic:1", "sync.t1.p1")
    np.testing.assert_array_equal(full, p1)
    assert prof_full == prof_p1
    assert prof_full["tp.psum"][2] > 0


def test_sync_schedule_runtime_ledger_proves_execution_drop():
    """The core ledger proof on the live dispatch seam: at periodic:2
    the scheduled tp sites execute HALF the collectives and move half
    the payload bytes per step (>=1.8x contract), while the reference
    bytes — what full would have moved — stay identical, and the
    skipped share records payload 0."""
    full, prof_full = _scheduled_forward(None, "sync.t2.full")
    p2, prof_p2 = _scheduled_forward("periodic:2", "sync.t2.p2")
    fp, fr, fe = prof_full["tp.psum"]
    sp_, sr, se = prof_p2["tp.psum"]
    assert fe > 0 and fp == fr          # full: every byte on the wire
    assert fr == sr                     # same reference work per step
    assert fe / max(se, 1) >= 1.8       # executions drop on schedule
    assert fp / max(sp_, 1) >= 1.8      # payload bytes drop with them
    assert sp_ * 2 == fr                # the skipped half moved ZERO
    assert se * 2 == fe                 # exactly on the periodic:2 beat
    # the schedule changes values (it is a relaxed transform), finitely
    assert not (full == p2).all() and np.isfinite(p2).all()


def test_skip_reduce_gradient_is_exact_collective_transpose():
    """The ISSUE-10 lesson applied to skips: a skipped forward sync
    must not zero the backward. skip's backward IS the exact psum's
    transpose (cotangent flows untouched); the megatron-SP skip's
    backward is the exact reduce-scatter's transpose (all_gather)."""
    from hadoop_tpu.models.decoder import ParallelCtx
    from hadoop_tpu.parallel.lowp.syncpolicy import skip_row_reduce
    mesh = _mesh()
    ctx = ParallelCtx(tp_axis="x", tp_size=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16), jnp.float32)

    def f(t):
        return jnp.sum(skip_row_reduce(t, ctx) * 3.0)

    g = jax.jit(_smap(lambda t: jax.grad(f)(t), mesh,
                      (P(None, None, "x"),), P(None, None, "x")))(x)
    assert (np.asarray(g) == 3.0).all()

    ctx_sp = ParallelCtx(tp_axis="x", tp_size=4, megatron_sp=True)

    def fsp(t):
        return jnp.sum(skip_row_reduce(t, ctx_sp) * 2.0)

    gsp = jax.jit(_smap(lambda t: jax.grad(fsp)(t), mesh,
                        (P(None, None, "x"),), P(None, None, "x")))(x)
    # transpose of the scatter is the all_gather of the cotangent:
    # every position receives its (constant) cotangent — nonzero
    assert (np.asarray(gsp) == 2.0).all()


def test_skip_reduce_forward_is_scaled_local_partial():
    """Forward semantics: skip == the rank's local partial scaled by
    tp (each partial is a 1/tp-magnitude sample of the row-parallel
    sum — the bare partial is a systematic bias, measured 67.6
    max_rel_div bare vs 1.45 scaled on the 50-step A-B), its own
    sequence block of it under megatron-SP; no collective executed."""
    from hadoop_tpu.models.decoder import ParallelCtx
    from hadoop_tpu.parallel.lowp.quant import capture_comm
    from hadoop_tpu.parallel.lowp.syncpolicy import skip_row_reduce
    mesh = _mesh()
    ctx = ParallelCtx(tp_axis="x", tp_size=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16), jnp.float32)
    with capture_comm() as led:
        got = jax.jit(_smap(lambda t: skip_row_reduce(t, ctx), mesh,
                            (P("x", None, None),),
                            P("x", None, None)))(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(x) * 4.0)
    assert led.executions == 0 and led.payload_bytes == 0
    assert led.reference_bytes > 0
    # megatron-SP: every rank holds the same partial (replicated in),
    # rank i keeps ITS OWN sequence block — reassembling the blocks
    # over the scatter dim reproduces the scaled partial, no psum
    ctx_sp = ParallelCtx(tp_axis="x", tp_size=4, megatron_sp=True)
    got_sp = jax.jit(_smap(lambda t: skip_row_reduce(t, ctx_sp), mesh,
                           (P(None, None, None),),
                           P(None, "x", None)))(x)
    np.testing.assert_array_equal(np.asarray(got_sp),
                                  np.asarray(x) * 4.0)


def test_stale_reduce_consumes_prev_correction_and_defers_collective():
    """Stale semantics at the seam: step 1 (zero correction) == skip
    (the tp-scaled local partial); the emitted correction is
    exact - scaled-local (the gain is absorbed); applying it makes the
    next same-input step EXACT; bytes ride the tp.stale site while
    the critical-path site records payload 0 / executions 0."""
    from hadoop_tpu.models.decoder import ParallelCtx
    from hadoop_tpu.parallel.lowp.quant import capture_comm
    from hadoop_tpu.parallel.lowp.syncpolicy import stale_row_reduce
    mesh = _mesh()
    ctx = ParallelCtx(tp_axis="x", tp_size=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 16), jnp.float32)

    def step(t, corr):
        return stale_row_reduce(t, ctx, corr)

    zeros = jnp.zeros_like(x)
    with capture_comm() as led:
        out1, corr1 = jax.jit(_smap(
            step, mesh, (P("x", None, None), P("x", None, None)),
            (P("x", None, None), P("x", None, None))))(x, zeros)
    # step 1 with no correction behaves as skip (scaled local partial)
    np.testing.assert_array_equal(np.asarray(out1),
                                  np.asarray(x) * 4.0)
    local_bytes = x.nbytes // 4          # the per-rank shard the seam sees
    per = led.per_site
    assert per["tp.psum"] == [0, local_bytes, 0]    # critical path: off
    assert per["tp.stale"][2] == 1                  # deferred collective
    assert per["tp.stale"][0] == local_bytes
    # step 2 with step 1's correction reproduces the EXACT psum
    exact = jax.jit(_smap(lambda t: jax.lax.psum(t, ("x",)), mesh,
                          (P("x", None, None),), P("x", None, None)))(x)
    out2, _ = jax.jit(_smap(
        step, mesh, (P("x", None, None), P("x", None, None)),
        (P("x", None, None), P("x", None, None))))(x, corr1)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(exact),
                               rtol=1e-6, atol=1e-6)
    # a mis-shaped correction is a loud trace-time error, never a
    # silent broadcast
    with pytest.raises(ValueError, match="correction shape"):
        jax.jit(_smap(
            step, mesh, (P("x", None, None), P(None, None, None)),
            (P("x", None, None), P("x", None, None))))(
            x, jnp.zeros((2, 8, 16), jnp.float32))


def test_scheduled_layers_gradients_flow_nonzero():
    """End-to-end through an all-skip layer stack: parameter gradients
    must be finite and nonzero (the stall the straight-through
    backward exists to prevent)."""
    from hadoop_tpu.models.decoder import ParallelCtx, run_layers
    from hadoop_tpu.ops import rope_frequencies
    from hadoop_tpu.parallel.lowp.syncpolicy import resolve_schedule
    plan, mesh, cfg, params, specs, _ = _tp_mesh_and_model()
    sched = resolve_schedule("none", cfg.n_layers)
    ctx = ParallelCtx(tp_axis="tp", tp_size=2, relaxed_sync=sched)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq,
                                cfg.rope_theta)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, cfg.d_model),
                          jnp.float32)

    def loss(layers, xx):
        return jnp.mean(
            run_layers(xx, layers, cfg, ctx, cos, sin) ** 2)

    g = jax.jit(_smap(
        lambda lp, xx: jax.grad(loss)(lp, xx), mesh,
        (specs["layers"], P(None, None, None)), specs["layers"]))(
        params["layers"], x)
    for path, leaf in jax.tree_util.tree_leaves_with_path(
            jax.device_get(g)):
        a = np.asarray(leaf)
        assert np.isfinite(a).all(), path
        assert np.abs(a).max() > 0, path


def test_sync_schedule_machinery_unreachable_on_bitwise(monkeypatch):
    """Static + dynamic gating: the bitwise tier never resolves a
    schedule (even with the conf keys set) and never reaches the
    syncpolicy reduce seam; a relaxed ctx with a schedule hits it at
    trace time."""
    import hadoop_tpu.parallel.lowp.syncpolicy as sp
    from hadoop_tpu.models import get_config
    from hadoop_tpu.models.decoder import ParallelCtx, forward_hidden
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.train import make_train_step

    def boom(*a, **k):
        raise AssertionError("syncpolicy reached on bitwise tier")

    monkeypatch.setattr(sp, "resolve_schedule", boom)
    cfg = get_config("tiny")
    plan = MeshPlan(dp=2, tp=2, megatron_sp=True)
    mesh = make_mesh(plan)
    # bitwise tier with the schedule CONF set: never resolved
    make_train_step(cfg, plan, mesh, donate=False,
                    parity=ParityConfig(tier="bitwise",
                                        relaxed_sync="periodic:2"))
    # relaxed tier resolves it at build time (the poison fires)
    with pytest.raises(AssertionError, match="bitwise tier"):
        make_train_step(cfg, plan, mesh, donate=False,
                        parity=ParityConfig(tier="relaxed",
                                            relaxed_sync="periodic:2"))
    monkeypatch.undo()
    monkeypatch.setattr(sp, "scheduled_row_reduce", boom)
    plan1, mesh1, cfg1, params, specs, tokens = _tp_mesh_and_model()
    # a ctx WITHOUT a schedule never touches the seam
    ctx = ParallelCtx(tp_axis="tp", tp_size=2)
    out = jax.jit(_smap(
        lambda p, t: forward_hidden(p, t, cfg1, ctx), mesh1,
        (specs, P(None, None)), P(None, None, None)))(params, tokens)
    assert np.isfinite(np.asarray(out)).all()
    # a scheduled relaxed ctx reaches it at trace time
    ctx_s = ParallelCtx(tp_axis="tp", tp_size=2,
                        relaxed_sync=("sync", "skip", "sync", "skip"))
    with pytest.raises(AssertionError, match="bitwise tier"):
        jax.jit(_smap(
            lambda p, t: forward_hidden(p, t, cfg1, ctx_s), mesh1,
            (specs, P(None, None)), P(None, None, None)))(params, tokens)


def test_sync_schedule_refuses_pipeline_plans_and_missing_state():
    """Loud edges: a non-full schedule on a pp plan is refused at
    train-step build (per-stage layer slices cannot index a global
    schedule), and a stale schedule without sync_state is refused at
    the layer loop."""
    from hadoop_tpu.models import get_config
    from hadoop_tpu.models.decoder import ParallelCtx, run_layers
    from hadoop_tpu.ops import rope_frequencies
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.train import make_train_step
    cfg = get_config("tiny")
    plan = MeshPlan(dp=2, tp=2, pp=2)
    mesh = make_mesh(plan)
    with pytest.raises(ValueError, match="pp"):
        make_train_step(cfg, plan, mesh, donate=False,
                        n_microbatches=2,
                        parity=ParityConfig(tier="relaxed",
                                            relaxed_sync="periodic:2"))
    ctx = ParallelCtx(tp_axis="tp", tp_size=2,
                      relaxed_sync=("stale",) * cfg.n_layers)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq,
                                cfg.rope_theta)
    layers = {"w": jnp.zeros((cfg.n_layers, 2))}
    with pytest.raises(ValueError, match="sync_state"):
        run_layers(jnp.zeros((1, 8, 4)), layers, cfg, ctx, cos, sin)
    # and a schedule whose length disagrees with the traced stack
    ctx_bad = ParallelCtx(tp_axis="tp", tp_size=2,
                          relaxed_sync=("skip",) * (cfg.n_layers + 1))
    with pytest.raises(ValueError, match="schedule names"):
        run_layers(jnp.zeros((1, 8, 4)), layers, cfg, ctx_bad, cos, sin)


# ------------------------------------------------- full-step A-B (vma)

@requires_vma
def test_relaxed_dp2_tp2_passes_loss_curve_guard_50_steps():
    """Acceptance rung: quantized tp reduces + chunked collective
    matmul, 50 steps, bounded trajectory divergence."""
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.lowp.guard import run_loss_ab
    rep = run_loss_ab(MeshPlan(dp=2, tp=2, megatron_sp=True), steps=50)
    assert rep["accepted"], rep.get("reason")
    assert rep["comm"]["sites"] > 0          # quantized tp reduces fired
    assert rep["relaxed_final"] < rep["relaxed_first"]


@requires_vma
def test_relaxed_zero1_dp8_guard_and_comm_contract_50_steps():
    """Acceptance rung: quantized ZeRO-1 reassembly, 50 steps, with the
    ≥2× collective-payload-byte reduction the ledger proves."""
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.lowp.guard import run_loss_ab
    rep = run_loss_ab(MeshPlan(dp=8), zero1=True, steps=50)
    assert rep["accepted"], rep.get("reason")
    assert rep["comm"]["ratio"] >= 2.0


@requires_vma
def test_relaxed_pp_grad_buckets_comm_contract():
    """Quantized gradient buckets ride the manual-schedule reduce; the
    payload contract holds there too."""
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.lowp.guard import run_loss_ab
    rep = run_loss_ab(MeshPlan(dp=2, pp=2), steps=12, n_microbatches=2)
    assert rep["accepted"], rep.get("reason")
    assert rep["comm"]["ratio"] >= 2.0


@requires_vma
def test_bitwise_parity_is_byte_identical_to_parity_unset():
    """parallel.parity=bitwise must build EXACTLY the unset graph:
    identical losses and parameters, bit for bit."""
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.train import (init_sharded,
                                           make_data_sharding,
                                           make_train_step)
    cfg = get_config("tiny")
    plan = MeshPlan(dp=2, tp=2, megatron_sp=True)
    mesh = make_mesh(plan)
    ds = make_data_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                           cfg.vocab_size, dtype=jnp.int32), ds)
    targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)
    out = {}
    for label, par in (("unset", None), ("bitwise", BITWISE_PARITY)):
        step = make_train_step(cfg, plan, mesh, lr=1e-2, donate=False,
                               parity=par)
        params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan,
                                   mesh)
        losses = []
        for _ in range(3):
            params, opt, m = step(params, opt, tokens, targets)
            losses.append(float(m["loss"]))
        out[label] = (losses, jax.tree_util.tree_map(
            np.asarray, jax.device_get(params)))
    assert out["unset"][0] == out["bitwise"][0]
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(out["unset"][1]),
            jax.tree_util.tree_leaves_with_path(out["bitwise"][1])):
        np.testing.assert_array_equal(a, b, err_msg=str(pa))


@requires_vma
def test_sync_schedule_periodic2_guard_and_ledger_50_steps():
    """Acceptance rung: partially synchronized activations at
    periodic:2 on dp2×tp2+sp — the 50-step loss-curve guard must
    accept, and the ledger must show the scheduled tp sites executing
    >=1.8x fewer collectives (and moving >=1.8x fewer payload bytes)
    per step than the full-schedule relaxed twin."""
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.lowp.guard import run_loss_ab

    def tp_sites(rep):
        per = rep["comm"].get("per_site", {})
        e = sum(v["executions"] for s, v in per.items()
                if s in ("tp.psum", "tp.scatter"))
        p = sum(v["payload_bytes"] for s, v in per.items()
                if s in ("tp.psum", "tp.scatter"))
        return e, p

    plan = MeshPlan(dp=2, tp=2, megatron_sp=True)
    rep_full = run_loss_ab(plan, steps=50)
    rep_sync = run_loss_ab(plan, steps=50,
                           bitwise_losses=rep_full["bitwise_losses"],
                           parity=ParityConfig(
                               tier="relaxed",
                               relaxed_sync="periodic:2"))
    assert rep_sync["accepted"], rep_sync.get("reason")
    assert rep_sync["sync_schedule"] == "periodic:2"
    fe, fp = tp_sites(rep_full)
    se, sp_ = tp_sites(rep_sync)
    assert fe > 0 and fe / max(se, 1) >= 1.8
    assert fp / max(sp_, 1) >= 1.8
    assert rep_sync["relaxed_final"] < rep_sync["relaxed_first"]


@requires_vma
def test_sync_schedule_all_skipped_rejects():
    """Falsifiability: a schedule that skips EVERY tp sync must be
    REJECTED by the loss-curve guard — otherwise the guard is not
    measuring anything and every acceptance above is vacuous."""
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.lowp.guard import run_loss_ab
    rep = run_loss_ab(MeshPlan(dp=2, tp=2, megatron_sp=True), steps=50,
                      parity=ParityConfig(tier="relaxed",
                                          relaxed_sync="none"))
    assert not rep.get("accepted"), (
        "all-layers-skipped schedule was ACCEPTED: "
        f"max_rel_div={rep.get('max_rel_div')}")


@requires_vma
def test_sync_schedule_stale_mode_guard_50_steps():
    """The stale mode: scheduled-off layers consume the previous
    step's reduced correction instead of skipping outright — the
    guard must accept, and the deferred bytes must show up under the
    tp.stale site while the critical-path tp sites record zero
    executions for the staled share."""
    from hadoop_tpu.parallel import MeshPlan
    from hadoop_tpu.parallel.lowp.guard import run_loss_ab
    rep = run_loss_ab(
        MeshPlan(dp=2, tp=2, megatron_sp=True), steps=50,
        parity=ParityConfig(tier="relaxed", relaxed_sync="periodic:2",
                            relaxed_sync_mode="stale"))
    assert rep["accepted"], rep.get("reason")
    per = rep["comm"].get("per_site", {})
    assert per.get("tp.stale", {}).get("executions", 0) > 0
    assert rep["relaxed_final"] < rep["relaxed_first"]


@requires_vma
def test_bitwise_with_sync_conf_is_byte_identical_full_step():
    """A step built with parity=bitwise while the sync-schedule conf
    keys are set must be bit-identical to parity-unset — the schedule
    machinery is unreachable on the default tier."""
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.train import (init_sharded,
                                           make_data_sharding,
                                           make_train_step)
    cfg = get_config("tiny")
    plan = MeshPlan(dp=2, tp=2, megatron_sp=True)
    mesh = make_mesh(plan)
    ds = make_data_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                           cfg.vocab_size, dtype=jnp.int32), ds)
    targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)
    out = {}
    for label, par in (
            ("unset", None),
            ("bitwise+sched", ParityConfig(tier="bitwise",
                                           relaxed_sync="periodic:2"))):
        step = make_train_step(cfg, plan, mesh, lr=1e-2, donate=False,
                               parity=par)
        params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan,
                                   mesh)
        losses = []
        for _ in range(3):
            params, opt, m = step(params, opt, tokens, targets)
            losses.append(float(m["loss"]))
        out[label] = losses
    assert out["unset"] == out["bitwise+sched"]


@requires_vma
def test_chunked_matmul_compiles_only_under_relaxed(monkeypatch):
    """A poisoned chunked_matmul_reduce: the bitwise step never touches
    it, the relaxed step hits it at trace time."""
    import hadoop_tpu.ops.collective_matmul as cm
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel import MeshPlan, make_mesh
    from hadoop_tpu.parallel.train import (init_sharded,
                                           make_data_sharding,
                                           make_train_step)

    def boom(*a, **k):
        raise AssertionError("chunked matmul reached on bitwise tier")

    monkeypatch.setattr(cm, "chunked_matmul_reduce", boom)
    cfg = get_config("tiny")
    plan = MeshPlan(dp=2, tp=2, megatron_sp=True)
    mesh = make_mesh(plan)
    ds = make_data_sharding(mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                           cfg.vocab_size, dtype=jnp.int32), ds)
    targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)
    step = make_train_step(cfg, plan, mesh, donate=False,
                           parity=BITWISE_PARITY)
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh)
    params, opt, m = step(params, opt, tokens, targets)   # no poison
    assert np.isfinite(float(m["loss"]))
    step_r = make_train_step(cfg, plan, mesh, donate=False,
                             parity=RELAXED_PARITY)
    with pytest.raises(AssertionError, match="bitwise tier"):
        step_r(params, opt, tokens, targets)
