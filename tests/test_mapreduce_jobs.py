"""Whole-job MapReduce integration tests on the mini MR cluster.

Model: the reference's TestMRJobs / terasort acceptance suite (ref:
hadoop-mapreduce-client-jobclient/src/test/.../v2/TestMRJobs.java on
MiniMRYarnCluster.java:63) — real RM, node agents, DFS, AM, task containers
and shuffle, one process. TeraGen→TeraSort→TeraValidate is the SURVEY §7
minimum-slice smoke test.
"""

import collections

import pytest

from hadoop_tpu.examples import terasort, wordcount
from hadoop_tpu.testing.minicluster import MiniMRYarnCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniMRYarnCluster(num_nodes=3) as c:
        yield c


def test_wordcount_end_to_end(cluster):
    fs = cluster.get_filesystem()
    words = ["alpha", "beta", "gamma", "delta"]
    lines = []
    expected = collections.Counter()
    for i in range(300):
        w = words[i % len(words)]
        lines.append(f"{w} {w} {words[(i + 1) % len(words)]}")
        expected[w] += 2
        expected[words[(i + 1) % len(words)]] += 1
    fs.mkdirs("/wc/in")
    fs.write_all("/wc/in/part0", "\n".join(lines[:150]).encode() + b"\n")
    fs.write_all("/wc/in/part1", "\n".join(lines[150:]).encode() + b"\n")

    job = wordcount.make_job(cluster.rm_addr, cluster.default_fs,
                             "/wc/in", "/wc/out", num_reduces=2)
    job.set("mapreduce.task.timeout", "60")
    assert job.wait_for_completion(timeout=240), job.diagnostics

    assert fs.exists("/wc/out/_SUCCESS")
    got = {}
    for st in fs.list_status("/wc/out"):
        if "part-" not in st.path:
            continue
        for line in fs.read_all(st.path).decode().splitlines():
            word, count = line.split("\t")
            got[word] = int(count)
    assert got == dict(expected)
    # counters flowed back through the AM report
    tc = job.counters.get("TaskCounter", {})
    assert tc.get("MAP_INPUT_RECORDS") == 300
    assert tc.get("REDUCE_OUTPUT_RECORDS") == len(expected)
    # combiner collapsed the per-word streams
    assert tc.get("COMBINE_INPUT_RECORDS", 0) > tc.get(
        "COMBINE_OUTPUT_RECORDS", 0)


def test_terasort_end_to_end(cluster):
    fs = cluster.get_filesystem()
    n = 20_000  # 2 MB of 100-byte records
    terasort.teragen(fs, "/tera/in", n, num_files=3)

    job = terasort.make_terasort_job(
        cluster.rm_addr, cluster.default_fs, "/tera/in", "/tera/out",
        num_reduces=3, split_mb=1)
    job.set("mapreduce.task.timeout", "60")
    assert job.wait_for_completion(timeout=240), job.diagnostics

    total, errors = terasort.teravalidate(fs, "/tera/out")
    assert errors == []
    assert total == n


def test_failed_job_reports_diagnostics(cluster):
    fs = cluster.get_filesystem()
    fs.mkdirs("/bad/in")
    fs.write_all("/bad/in/part0", b"some input\n")
    from hadoop_tpu.mapreduce import Job
    job = (Job(cluster.rm_addr, cluster.default_fs, name="boom")
           .set_mapper("tests.test_mapreduce_jobs:CrashingMapper")
           .add_input_path("/bad/in")
           .set_output_path("/bad/out")
           .set_num_reduces(1))
    job.set("mapreduce.map.maxattempts", "2")
    job.set("mapreduce.task.timeout", "60")
    assert not job.wait_for_completion(timeout=240)
    assert any("boom!" in d for d in job.diagnostics), job.diagnostics


from hadoop_tpu.mapreduce.api import Mapper  # noqa: E402


class CrashingMapper(Mapper):
    def map(self, key, value, ctx):
        raise RuntimeError("boom!")


def test_uber_mode_runs_job_inside_am(tmp_path):
    """Small jobs run inside the AM — exactly one container (the AM
    itself) is ever launched. Ref: mapreduce.job.ubertask.enable +
    MRAppMaster.makeUberDecision / LocalContainerLauncher."""
    import glob as _glob

    from hadoop_tpu.examples.wordcount import make_job
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    with MiniMRYarnCluster(num_nodes=2,
                           base_dir=str(tmp_path / "c")) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/uin")
        fs.write_all("/uin/a.txt", b"x y x\nz x\n")
        job = make_job(cluster.rm_addr, cluster.default_fs, "/uin",
                       "/uout")
        job.set_num_reduces(1)  # uber allows at most maxreduces=1
        job.set("mapreduce.job.ubertask.enable", "true")
        assert job.wait_for_completion(), job.diagnostics
        out = b"".join(fs.read_all(s.path)
                       for s in fs.list_status("/uout")
                       if "part-" in s.path)
        rows = dict(l.split(b"\t") for l in out.splitlines() if l)
        assert rows[b"x"] == b"3" and rows[b"z"] == b"1"
        containers = _glob.glob(str(tmp_path / "c" / "yarn" / "nm*" /
                                    "container_*"))
        # at most the AM's own container (which the NM may have already
        # cleaned up after job completion) — never task containers
        assert len(containers) <= 1, containers
