"""Unit tests for the MapReduce engine's pieces (no cluster).

Model: the reference's pure-logic MR tests (ref:
hadoop-mapreduce-client-core/src/test — TestIFile, TestMapOutputBuffer-style
collector tests, TestTextInputFormat split/realign cases).
"""

import os
import threading

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs.filesystem import LocalFileSystem
from hadoop_tpu.mapreduce import ifile, shuffle
from hadoop_tpu.mapreduce.api import (Counters, FileSplit,
                                      FixedLengthInputFormat, Partitioner,
                                      TextInputFormat)
from hadoop_tpu.mapreduce.sorter import (MapOutputCollector, group_by_key,
                                         make_combiner, merge_sorted_runs)


# ------------------------------------------------------------------- ifile


@pytest.mark.parametrize("codec", [None, "zlib", "bz2"])
def test_ifile_roundtrip(codec):
    records = [(f"k{i:04d}".encode(), (b"v" * (i % 50)) + str(i).encode())
               for i in range(500)]
    stored = ifile.encode_records(records, codec)
    assert list(ifile.decode_records(stored, codec)) == records


def test_ifile_detects_corruption():
    stored = bytearray(ifile.encode_records([(b"a", b"b")], None))
    stored[0] ^= 0xFF
    with pytest.raises(IOError):
        list(ifile.decode_records(bytes(stored), None))


def test_partitioned_write_and_range_read(tmp_path):
    runs = [[(b"a", b"1")], [(b"b", b"2"), (b"c", b"3")], []]
    path = str(tmp_path / "file.out")
    index = ifile.write_partitioned(path, runs)
    for p, expect in enumerate(runs):
        assert ifile.read_partition(path, index, p) == expect
    # index round-trips through bytes
    idx2 = ifile.SpillIndex.from_bytes(index.to_bytes())
    assert idx2.entries == index.entries


# ------------------------------------------------------------------ sorter


def test_collector_sorts_and_partitions(tmp_path):
    c = Counters()
    coll = MapOutputCollector(4, Partitioner().partition,
                              str(tmp_path / "spill"), c)
    import random
    rng = random.Random(7)
    data = [(f"key{rng.randrange(1000):04d}".encode(), b"x")
            for _ in range(2000)]
    for k, v in data:
        coll.collect(k, v)
    out = str(tmp_path / "file.out")
    index = coll.close(out)
    seen = 0
    part = Partitioner()
    for p in range(4):
        records = ifile.read_partition(out, index, p)
        keys = [k for k, _ in records]
        assert keys == sorted(keys)
        assert all(part.partition(k, 4) == p for k in keys)
        seen += len(records)
    assert seen == len(data)
    assert c.get(Counters.MAP_OUTPUT_RECORDS) == len(data)


def test_collector_spills_and_merges(tmp_path):
    c = Counters()
    coll = MapOutputCollector(2, Partitioner().partition,
                              str(tmp_path / "spill"), c,
                              sort_mb=0.001)  # ~1KB → many spills
    for i in range(500):
        coll.collect(f"k{i % 97:03d}".encode(), b"v" * 20)
    out = str(tmp_path / "file.out")
    index = coll.close(out)
    assert c.get(Counters.SPILLED_RECORDS) >= 500
    total = sum(len(ifile.read_partition(out, index, p)) for p in range(2))
    assert total == 500
    for p in range(2):
        keys = [k for k, _ in ifile.read_partition(out, index, p)]
        assert keys == sorted(keys)


def test_combiner_runs_at_spill(tmp_path):
    from hadoop_tpu.examples.wordcount import IntSumReducer
    c = Counters()
    combiner = make_combiner(IntSumReducer, {}, c)
    coll = MapOutputCollector(1, Partitioner().partition,
                              str(tmp_path / "spill"), c, combiner=combiner)
    for _ in range(100):
        coll.collect(b"w", b"1")
    out = str(tmp_path / "file.out")
    index = coll.close(out)
    records = ifile.read_partition(out, index, 0)
    assert records == [(b"w", b"100")]


def test_group_by_key_partial_consumption():
    stream = iter([(b"a", b"1"), (b"a", b"2"), (b"b", b"3"), (b"c", b"4")])
    groups = []
    for key, values in group_by_key(stream):
        groups.append((key, next(values)))  # consume only first value
    assert groups == [(b"a", b"1"), (b"b", b"3"), (b"c", b"4")]


def test_merge_sorted_runs():
    runs = [[(b"a", b"1"), (b"c", b"2")], [(b"b", b"3")], []]
    assert [k for k, _ in merge_sorted_runs(runs)] == [b"a", b"b", b"c"]


# ------------------------------------------------------------ input formats


def test_text_input_format_split_realignment(tmp_path):
    """Every line read exactly once regardless of split boundaries.
    Ref: LineRecordReader.java:126 skip-first-partial-line rule."""
    lines = [f"line-{i:03d}".encode() for i in range(100)]
    f = tmp_path / "input.txt"
    f.write_bytes(b"\n".join(lines) + b"\n")
    fs = LocalFileSystem(Configuration(load_defaults=False))
    fmt = TextInputFormat()
    size = f.stat().st_size
    for split_size in (17, 64, 1000, size):
        conf = {TextInputFormat.SPLIT_SIZE_KEY: str(split_size)}
        splits = fmt.get_splits(fs, [str(f)], conf)
        got = []
        for s in splits:
            got.extend(v for _, v in fmt.read(fs, s, conf))
        assert got == lines, f"split_size={split_size}"


def test_fixed_length_format(tmp_path):
    rec = 20
    rows = [bytes([65 + i % 26]) * rec for i in range(50)]
    f = tmp_path / "fixed.bin"
    f.write_bytes(b"".join(rows))
    fs = LocalFileSystem(Configuration(load_defaults=False))
    fmt = FixedLengthInputFormat()
    conf = {FixedLengthInputFormat.RECORD_LENGTH_KEY: str(rec),
            "mapreduce.input.fixedlength.key.length": "4",
            fmt.SPLIT_SIZE_KEY: "64"}
    splits = fmt.get_splits(fs, [str(f)], conf)
    assert len(splits) > 1
    got = [k + v for s in splits for k, v in fmt.read(fs, s, conf)]
    assert got == rows


# ----------------------------------------------------------------- shuffle


def test_shuffle_service_serves_and_fetches(tmp_path):
    svc = shuffle.ShuffleService(None, str(tmp_path))
    svc.start()
    try:
        runs = [[(b"a", b"1")], [(b"b", b"2")]]
        out, idx = shuffle.map_output_paths(svc.shuffle_dir, "job1", "m0")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        index = ifile.write_partitioned(out, runs)
        with open(idx, "wb") as f:
            f.write(index.to_bytes())

        c = Counters()
        merger = shuffle.MergeManager(str(tmp_path / "merge"), None, c)
        fetcher = shuffle.Fetcher(1, "job1", merger, num_threads=2)
        fetcher.add_events([("m0", f"127.0.0.1:{svc.port}")])
        fetcher.finish()
        assert list(merger.merged_iterator()) == [(b"b", b"2")]
        assert c.get(Counters.SHUFFLED_BYTES) > 0

        # purge removes the job dir
        shuffle.purge_job(("127.0.0.1", svc.port), "job1")
        assert not os.path.exists(os.path.dirname(out))
    finally:
        svc.stop()


def test_shuffle_service_verifies_job_token(tmp_path):
    """A job whose secret was registered (container service_data →
    initialize_app, ref: ShuffleHandler.verifyRequest) gets every
    request MAC-checked: unsigned or wrongly-signed fetch/locate/purge
    are refused, correctly signed ones succeed — an unauthenticated
    local process can no longer read another job's map outputs or
    purge its shuffle dir."""
    import json as _json

    svc = shuffle.ShuffleService(None, str(tmp_path))
    svc.start()
    try:
        secret = "deadbeef" * 8
        svc.initialize_app({shuffle.SHUFFLE_SERVICE_KEY: _json.dumps(
            {"job": "sec-job", "secret": secret})})
        runs = [[(b"a", b"1")]]
        out, idx = shuffle.map_output_paths(svc.shuffle_dir, "sec-job",
                                            "m0")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        index = ifile.write_partitioned(out, runs)
        with open(idx, "wb") as f:
            f.write(index.to_bytes())

        addr = ("127.0.0.1", svc.port)
        base = {"job": "sec-job", "map": "m0", "partition": 0}
        # unsigned and badly-signed fetches refused
        assert not shuffle._request(addr, dict(base))["ok"]
        assert not shuffle._request(addr, dict(base),
                                    secret="wrong" * 13)["ok"]
        assert not shuffle._request(
            addr, dict(base, op="locate"), secret=None)["ok"]
        # signed fetch succeeds
        resp = shuffle._request(addr, dict(base), secret=secret)
        assert resp["ok"] and resp["data"]
        # unsigned purge refused — the dir survives
        shuffle.purge_job(addr, "sec-job")
        assert os.path.exists(os.path.dirname(out))
        # signed purge removes it
        shuffle.purge_job(addr, "sec-job", secret=secret)
        assert not os.path.exists(os.path.dirname(out))
        # an unrelated job with no registered secret stays open-mode
        out2, idx2 = shuffle.map_output_paths(svc.shuffle_dir, "open-job",
                                              "m0")
        os.makedirs(os.path.dirname(out2), exist_ok=True)
        with open(idx2, "wb") as f:
            f.write(ifile.write_partitioned(out2, runs).to_bytes())
        assert shuffle._request(
            addr, {"job": "open-job", "map": "m0", "partition": 0})["ok"]
    finally:
        svc.stop()


def test_fetcher_retries_then_fails(tmp_path):
    svc = shuffle.ShuffleService(None, str(tmp_path))
    svc.start()
    try:
        c = Counters()
        merger = shuffle.MergeManager(str(tmp_path / "merge"), None, c)
        fetcher = shuffle.Fetcher(0, "nope", merger, num_threads=1,
                                  max_retries=2)
        fetcher.add_events([("m-missing", f"127.0.0.1:{svc.port}")])
        with pytest.raises(shuffle.ShuffleError):
            fetcher.finish()
    finally:
        svc.stop()


def test_merge_manager_disk_spill(tmp_path):
    c = Counters()
    merger = shuffle.MergeManager(str(tmp_path / "m"), None, c,
                                  mem_limit=200)
    for i in range(10):
        merger.add_segment(ifile.encode_records(
            [(f"k{i:02d}".encode(), b"v" * 30)]))
    keys = [k for k, _ in merger.merged_iterator()]
    assert keys == sorted(keys) and len(keys) == 10
    assert len(merger._disk_runs) >= 1


def test_spill_codec_is_conf_driven_not_host_probed():
    """Tasks must read the codec NAME from the job conf (resolved once
    at submission) — a per-host liblz4 probe would let map and reduce
    tasks on heterogeneous hosts disagree about the shuffle wire format
    (review finding)."""
    from hadoop_tpu.mapreduce.task_runner import _spill_codec

    assert _spill_codec({}) is None
    assert _spill_codec({"mapreduce.map.output.compress": "false"}) is None
    # compress on + explicit codec: honored verbatim
    assert _spill_codec({"mapreduce.map.output.compress": "true",
                         "mapreduce.map.output.compress.codec": "lz4"}) \
        == "lz4"
    # compress on + no codec in conf (job predates client resolution):
    # the deterministic zlib fallback, NEVER a host-dependent answer
    assert _spill_codec({"mapreduce.map.output.compress": "true"}) == "zlib"


def test_fetcher_records_nonio_failures_for_retry():
    """A corrupt segment raises zlib.error/ValueError from the merger —
    that must hit the retry/error accounting, not silently kill the
    worker and idle the reduce to the shuffle timeout (review
    finding). failed() exposes the terminal state to the poll loop."""
    import threading
    import time as _t

    from hadoop_tpu.mapreduce.shuffle import Fetcher, ShuffleError

    class _BoomMerger:
        def add_segment(self, stored):
            raise ValueError("corrupt segment")

    # a server that always answers OK with junk data
    import socketserver
    import struct as _struct

    from hadoop_tpu.io.wire import pack

    class _H(socketserver.BaseRequestHandler):
        def handle(self):
            self.request.recv(1 << 16)
            body = pack({"ok": True, "data": b"junk"})
            self.request.sendall(_struct.pack(">I", len(body)) + body)

    srv = socketserver.ThreadingTCPServer(("127.1.2.3", 0), _H)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        port = srv.server_address[1]
        # 127.1.2.3 is loopback-but-not-local-hostname: the remote lane
        fetcher2 = Fetcher(0, "job_x", _BoomMerger(), max_retries=2,
                           num_threads=1)
        fetcher2.add_events([("m_0", f"127.1.2.3:{port}")])
        deadline = _t.monotonic() + 10
        while _t.monotonic() < deadline and not fetcher2.failed():
            _t.sleep(0.05)
        assert fetcher2.failed(), "ValueError never reached error state"
        import pytest as _p
        with _p.raises(ShuffleError, match="corrupt segment"):
            fetcher2.finish()
    finally:
        srv.shutdown()
        srv.server_close()


def test_umbilical_get_job_never_leaks_shuffle_secret():
    """The umbilical is an open local RPC surface; the shuffle token
    must ride the container-private launch env instead (review finding:
    serving it from get_job() let any local process sign fetches for
    the job the token protects)."""
    from hadoop_tpu.mapreduce.appmaster import TaskUmbilicalProtocol

    class _FakeAM:
        job = {"job_id": "j", "shuffle_secret": "s3cr3t", "conf": {}}

    served = TaskUmbilicalProtocol(_FakeAM()).get_job()
    assert "shuffle_secret" not in served
    assert served["job_id"] == "j"


def test_shuffle_secrets_survive_service_restart(tmp_path):
    """An NM restart must not flip surviving protected outputs into
    open mode (review finding): secrets persist as 0600 files beside
    the shuffle dir and reload on start. A later registration with a
    DIFFERENT secret must not replace the original binding (hijack via
    the open container-launch surface)."""
    import json as _json

    secret = "feedface" * 8
    svc1 = shuffle.ShuffleService(None, str(tmp_path))
    svc1.start()
    svc1.initialize_app({shuffle.SHUFFLE_SERVICE_KEY: _json.dumps(
        {"job": "pj", "secret": secret})})
    out, idx = shuffle.map_output_paths(svc1.shuffle_dir, "pj", "m0")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(idx, "wb") as f:
        f.write(ifile.write_partitioned(out, [[(b"k", b"v")]]).to_bytes())
    svc1.stop()

    svc2 = shuffle.ShuffleService(None, str(tmp_path))
    svc2.start()
    try:
        addr = ("127.0.0.1", svc2.port)
        req = {"job": "pj", "map": "m0", "partition": 0}
        assert not shuffle._request(addr, dict(req))["ok"]       # still closed
        assert shuffle._request(addr, dict(req), secret=secret)["ok"]
        # hijack attempt: a different secret must not replace the binding
        svc2.initialize_app({shuffle.SHUFFLE_SERVICE_KEY: _json.dumps(
            {"job": "pj", "secret": "attacker" * 8})})
        assert not shuffle._request(addr, dict(req),
                                    secret="attacker" * 8)["ok"]
        assert shuffle._request(addr, dict(req), secret=secret)["ok"]
    finally:
        svc2.stop()


def test_shuffle_rejects_path_traversal_names(tmp_path):
    """'../<other-job>/m0' must not reach another job's outputs through
    a no-secret job's open mode, and a traversal purge must not delete
    the persisted-secrets dir (review finding)."""
    import json as _json

    svc = shuffle.ShuffleService(None, str(tmp_path))
    svc.start()
    try:
        secret = "cafebabe" * 8
        svc.initialize_app({shuffle.SHUFFLE_SERVICE_KEY: _json.dumps(
            {"job": "prot", "secret": secret})})
        out, idx = shuffle.map_output_paths(svc.shuffle_dir, "prot", "m0")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(idx, "wb") as f:
            f.write(ifile.write_partitioned(
                out, [[(b"k", b"v")]]).to_bytes())
        addr = ("127.0.0.1", svc.port)
        for req in (
                {"job": "zzz", "map": "../prot/m0", "partition": 0},
                {"job": "../shuffle/prot", "map": "m0", "partition": 0},
                {"op": "purge", "job": "../shuffle/prot"},
                {"op": "purge", "job": ".secrets"},
        ):
            resp = shuffle._request(addr, req)
            assert not resp["ok"], req
        assert os.path.exists(out)
        assert os.path.exists(os.path.join(svc._secrets_dir, "prot"))
        # unsafe registration refused entirely
        svc.initialize_app({shuffle.SHUFFLE_SERVICE_KEY: _json.dumps(
            {"job": "../../evil", "secret": "x" * 64})})
        assert not os.path.exists(str(tmp_path / ".." / "evil"))
    finally:
        svc.stop()


def test_shuffle_mac_binds_all_request_fields():
    """A MAC minted for one request must not authorize another: op,
    job, map, and partition are all bound, so a captured fetch MAC
    cannot be replayed as a purge (or against another segment)."""
    base = {"job": "j1", "map": "m0", "partition": 0}
    secret = "s" * 64
    mac = shuffle.request_mac(secret, base)
    assert shuffle.request_mac(secret, dict(base, op="purge")) != mac
    assert shuffle.request_mac(secret, dict(base, map="m1")) != mac
    assert shuffle.request_mac(secret, dict(base, partition=1)) != mac
    assert shuffle.request_mac(secret, dict(base, job="j2")) != mac
    assert shuffle.request_mac("x" * 64, base) != mac
    # deterministic for the same request
    assert shuffle.request_mac(secret, dict(base)) == mac
