"""End-to-end DFS tests on the in-process minicluster.

Parity targets: ref TestDistributedFileSystem, TestReplication,
TestFileCreation, TestDataTransferProtocol, TestFsck-adjacent flows — real
NN + 3 DNs, real RPC + streaming protocols, one process.
"""

import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.testing.minicluster import MiniDFSCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniDFSCluster(num_datanodes=3) as c:
        yield c


@pytest.fixture(scope="module")
def fs(cluster):
    return cluster.get_filesystem()


def test_write_read_roundtrip(cluster, fs):
    data = os.urandom(300_000)  # < 1 block
    with fs.create("/roundtrip.bin") as out:
        out.write(data)
    with fs.open("/roundtrip.bin") as f:
        assert f.read() == data
    st = fs.get_file_status("/roundtrip.bin")
    assert st.length == len(data)
    assert not st.is_dir


def test_multi_block_file(cluster, fs):
    # 1 MB blocks (fast_conf) → 3.5 MB = 4 blocks.
    data = os.urandom(3 * 1024 * 1024 + 512 * 1024)
    with fs.create("/big.bin") as out:
        # Write in odd-sized chunks to exercise packet buffering.
        for off in range(0, len(data), 97_531):
            out.write(data[off:off + 97_531])
    with fs.open("/big.bin") as f:
        got = f.read()
    assert got == data
    locs = cluster.get_filesystem().client.get_block_locations("/big.bin")
    assert len(locs["blocks"]) == 4


def test_replication_factor_honored(cluster, fs):
    with fs.create("/rep.bin", replication=2) as out:
        out.write(b"hello replication")
    time.sleep(0.3)  # let incremental reports land
    locs = fs.client.get_block_locations("/rep.bin")
    assert len(locs["blocks"]) == 1
    assert len(locs["blocks"][0]["locs"]) == 2


def test_empty_file(cluster, fs):
    with fs.create("/empty") as out:
        pass
    st = fs.get_file_status("/empty")
    assert st.length == 0
    with fs.open("/empty") as f:
        assert f.read() == b""


def test_mkdirs_listing_delete(cluster, fs):
    fs.mkdirs("/dir/sub")
    fs.write_all("/dir/a.txt", b"aaa")
    fs.write_all("/dir/b.txt", b"bbb")
    names = [s.path for s in fs.list_status("/dir")]
    assert names == ["/dir/a.txt", "/dir/b.txt", "/dir/sub"]
    assert fs.delete("/dir", recursive=True)
    assert not fs.exists("/dir")


def test_rename(cluster, fs):
    fs.write_all("/src.txt", b"content")
    fs.rename("/src.txt", "/dst.txt")
    assert not fs.exists("/src.txt")
    assert fs.read_all("/dst.txt") == b"content"


def test_overwrite_semantics(cluster, fs):
    fs.write_all("/ow.txt", b"v1")
    with pytest.raises(FileExistsError):
        with fs.create("/ow.txt", overwrite=False) as out:
            out.write(b"nope")
    fs.write_all("/ow.txt", b"v2", overwrite=True)
    assert fs.read_all("/ow.txt") == b"v2"


def test_seek_and_pread(cluster, fs):
    data = bytes(range(256)) * 5000  # 1.28 MB, crosses a block boundary
    fs.write_all("/seek.bin", data)
    with fs.open("/seek.bin") as f:
        f.seek(1000)
        assert f.read(100) == data[1000:1100]
        assert f.pread(1024 * 1024 - 50, 100) == \
            data[1024 * 1024 - 50:1024 * 1024 + 50]  # spans block edge
        f.seek(0)
        assert f.read(10) == data[:10]


def test_concurrent_writers_distinct_files(cluster, fs):
    import threading
    payload = {i: os.urandom(200_000) for i in range(6)}
    errs = []

    def write(i):
        try:
            fs.write_all(f"/conc/f{i}", payload[i])
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=write, args=(i,)) for i in payload]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    for i, data in payload.items():
        assert fs.read_all(f"/conc/f{i}") == data


def test_single_writer_enforced(cluster, fs):
    from hadoop_tpu.dfs.protocol.records import AlreadyBeingCreatedError
    out = fs.create("/locked.txt")
    out.write(b"partial")
    other = cluster.get_filesystem()
    try:
        with pytest.raises((AlreadyBeingCreatedError, FileExistsError)):
            other.create("/locked.txt", overwrite=True)
    finally:
        out.close()
        other.close()


def test_read_failover_on_dead_datanode(cluster, fs):
    """Kill a DN holding a replica; reads must fail over to survivors."""
    data = os.urandom(400_000)
    fs.write_all("/failover.bin", data)
    time.sleep(0.3)
    locs = fs.client.get_block_locations("/failover.bin")
    holder_uuids = {l["u"] for l in locs["blocks"][0]["locs"]}
    victim_idx = next(i for i, dn in enumerate(cluster.datanodes)
                      if dn is not None and dn.uuid in holder_uuids)
    cluster.kill_datanode(victim_idx)
    try:
        with fs.open("/failover.bin") as f:
            assert f.read() == data
    finally:
        cluster.restart_datanode(victim_idx)
        cluster.wait_active()


def test_re_replication_after_datanode_death(cluster, fs):
    """The RedundancyMonitor must restore replication after a DN dies."""
    data = os.urandom(100_000)
    fs.write_all("/heal.bin", data, overwrite=True)
    time.sleep(0.3)
    locs = fs.client.get_block_locations("/heal.bin")
    block_id = locs["blocks"][0]["b"]["id"]
    holders = {l["u"] for l in locs["blocks"][0]["locs"]}
    assert len(holders) == 3
    victim_idx = next(i for i, dn in enumerate(cluster.datanodes)
                      if dn is not None and dn.uuid in holders)
    victim_uuid = cluster.datanodes[victim_idx].uuid
    cluster.kill_datanode(victim_idx)
    # Not possible to reach 3 replicas with 2 nodes; bring up a fresh 4th DN.
    cluster.num_datanodes += 1
    cluster._start_datanode(len(cluster.datanodes))
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            info = cluster.namenode.fsn.bm.get(block_id)
            live = {u for u in info.locations if u != victim_uuid}
            if len(live) >= 3:
                break
            time.sleep(0.2)
        else:
            pytest.fail(f"block never re-replicated: {info.locations}")
        with fs.open("/heal.bin") as f:
            assert f.read() == data
    finally:
        cluster.restart_datanode(victim_idx)
        cluster.wait_active()


def test_corrupt_replica_detected_and_avoided(cluster, fs):
    data = os.urandom(50_000)
    fs.write_all("/corrupt.bin", data, overwrite=True)
    time.sleep(0.3)
    locs = fs.client.get_block_locations("/corrupt.bin")
    block_id = locs["blocks"][0]["b"]["id"]
    holders = [l["u"] for l in locs["blocks"][0]["locs"]]
    dn_idx = next(i for i, dn in enumerate(cluster.datanodes)
                  if dn is not None and dn.uuid == holders[0])
    assert cluster.corrupt_replica(block_id, dn_idx)
    # Fresh reader (no cached dead-node state): must transparently survive.
    fs2 = cluster.get_filesystem()
    with fs2.open("/corrupt.bin") as f:
        assert f.read() == data


def test_namenode_restart_preserves_namespace(cluster, fs):
    data = os.urandom(150_000)
    fs.write_all("/persist/f.bin", data, overwrite=True)
    fs.mkdirs("/persist/dir")
    cluster.restart_namenode()
    cluster.wait_active()
    fs2 = cluster.get_filesystem()
    assert fs2.exists("/persist/f.bin")
    assert fs2.exists("/persist/dir")
    assert fs2.read_all("/persist/f.bin") == data


def test_namenode_restart_after_checkpoint(cluster, fs):
    fs.write_all("/ckpt/a.bin", b"before checkpoint", overwrite=True)
    fs.client.nn.save_namespace()
    fs.write_all("/ckpt/b.bin", b"after checkpoint", overwrite=True)
    cluster.restart_namenode()
    cluster.wait_active()
    fs2 = cluster.get_filesystem()
    assert fs2.read_all("/ckpt/a.bin") == b"before checkpoint"
    assert fs2.read_all("/ckpt/b.bin") == b"after checkpoint"


def test_lease_recovery_on_abandoned_writer(cluster, fs):
    """A writer that vanishes must not lock the file forever — and flushed
    data must survive via block recovery (rbw replicas finalized at their
    length; ref: internalReleaseLease → block recovery)."""
    payload = b"some data that will be recovered"
    out = fs.create("/abandoned.txt")
    out.write(payload)
    out.flush()
    # Simulate writer death: stop renewing (kill the renewer + client ref).
    fs.client._renewer_stop.set()
    deadline = time.monotonic() + 20
    fs2 = cluster.get_filesystem()
    recovered = False
    while time.monotonic() < deadline:
        try:
            if fs2.client.nn.recover_lease("/abandoned.txt", "taker"):
                recovered = True
                break
        except Exception:
            pass
        time.sleep(0.3)
    assert recovered
    assert fs2.read_all("/abandoned.txt") == payload  # flushed bytes durable
    # Restart the renewer thread machinery for later tests.
    fs.client._renewer_stop = None
    fs.client._open_files = 0


def test_datanode_report_and_stats(cluster, fs):
    stats = fs.client.nn.get_stats()
    assert stats["live_datanodes"] >= 3
    assert not stats["safemode"]
    report = fs.client.nn.get_datanode_report("live")
    assert len(report) >= 3
    assert all(r["st"] == "live" for r in report)


def test_short_circuit_local_read(cluster, fs):
    """Same-host reads take the direct-file path (ref:
    ShortCircuitCache.java:72 / BlockReaderFactory.java:354-381)."""
    from hadoop_tpu.dfs.client.shortcircuit import ShortCircuitCache
    data = os.urandom(2 * 1024 * 1024 + 12345)  # spans blocks
    with fs.create("/sc.bin") as out:
        out.write(data)
    cache = ShortCircuitCache.get()
    hits0, reqs0 = cache.hits, cache.requests
    with fs.open("/sc.bin") as f:
        assert f.read() == data
    assert cache.hits > hits0          # local path actually taken
    assert cache.requests > reqs0


def test_short_circuit_disabled_by_conf(cluster, fs):
    from hadoop_tpu.dfs.client.streams import DFSInputStream
    data = os.urandom(10_000)
    fs.write_all("/sc3.bin", data)
    # flag plumbed through the stream (TCP path still correct)
    s = DFSInputStream(fs.client, "/sc3.bin")
    assert s._short_circuit_ok  # default on
    fs.client.conf.set("dfs.client.read.shortcircuit", "false")
    try:
        s2 = DFSInputStream(fs.client, "/sc3.bin")
        assert not s2._short_circuit_ok
        assert s2.read() == data  # remote path works
    finally:
        fs.client.conf.set("dfs.client.read.shortcircuit", "true")


def test_short_circuit_fallback_when_replica_moved(cluster, fs):
    """A stale cached path falls back to TCP instead of failing."""
    from hadoop_tpu.dfs.client import shortcircuit as scmod
    data = os.urandom(100_000)
    with fs.create("/sc2.bin") as out:
        out.write(data)
    cache = scmod.ShortCircuitCache.get()
    with fs.open("/sc2.bin") as f:
        assert f.read(10) == data[:10]
    # poison every cached slot's data fd; next read must still succeed
    import os as _os
    with cache._lock:
        for slot in cache._slots.values():
            _os.close(slot.data_fd)
            slot.data_fd = -1  # EBADF on pread; close() is a no-op
    with fs.open("/sc2.bin") as f:
        assert f.read() == data


def test_unaligned_flush_mid_write(cluster, fs):
    """hflush at a non-chunk-aligned offset must not corrupt checksums:
    the DN re-covers the straddling chunk when the next packet arrives
    (ref: BlockReceiver partial-chunk handling)."""
    a, b, c = os.urandom(1000), os.urandom(50_001), os.urandom(700)
    with fs.create("/unaligned_flush.bin") as out:
        out.write(a)
        out.flush()          # 1000 % 512 != 0 → partial trailing chunk
        out.write(b)
        out.flush()
        out.write(c)
    assert fs.read_all("/unaligned_flush.bin") == a + b + c


def test_short_circuit_fds_survive_dn_restart(cluster, fs):
    """A cached fd grant outlives the granting DN: finalized block bytes
    at a genstamp are immutable, so the open descriptors stay correct
    across a DN restart (the reference's slot invalidation exists to
    reclaim space, not for correctness) — and after the restart, NEW
    grants flow through the recreated domain socket."""
    from hadoop_tpu.dfs.client.shortcircuit import ShortCircuitCache
    data = os.urandom(500_000)
    with fs.create("/scr.bin") as out:
        out.write(data)
    cache = ShortCircuitCache.get()
    hits0 = cache.hits
    with fs.open("/scr.bin") as f:
        assert f.read() == data        # populate fd slots
    assert cache.hits > hits0

    cluster.restart_datanode(0)
    cluster.wait_active()

    # cached fds still serve the immutable bytes
    hits1 = cache.hits
    with fs.open("/scr.bin") as f:
        assert f.read() == data
    assert cache.hits > hits1

    # and a fresh file gets NEW grants via the recreated socket
    data2 = os.urandom(100_000)
    fs.write_all("/scr2.bin", data2)
    reqs = cache.requests
    assert fs.read_all("/scr2.bin") == data2
    assert cache.requests > reqs


def test_domain_socket_concurrent_grants_and_bad_peers(cluster, fs):
    """The fd-passing server under load: N threads grab grants for
    different blocks concurrently while garbage peers poke the socket —
    every legitimate read stays correct (slot refcounting + per-conn
    isolation)."""
    import socket as _socket
    import threading

    data = {}
    for i in range(4):
        data[i] = os.urandom(300_000)
        fs.write_all(f"/dsc/f{i}", data[i])

    from hadoop_tpu.dfs.client.shortcircuit import ShortCircuitCache
    cache = ShortCircuitCache.get()
    dn = cluster.datanodes[0]
    sock_path = dn.domain_server.path
    errs = []

    def garbage():
        try:
            s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            s.connect(sock_path)
            s.sendall(b"\x00\x00\x00\x05junk!")
            s.close()
        except OSError:
            pass

    def reader(i):
        try:
            for _ in range(5):
                with fs.open(f"/dsc/f{i}") as f:
                    assert f.read() == data[i]
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=reader, args=(i,)) for i in data]
    threads += [threading.Thread(target=garbage) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    assert cache.hits > 0


def test_non_default_bytes_per_checksum_roundtrip(tmp_path):
    """dfs.bytes-per-checksum != 512: the replica meta stores the
    writer's chunking and the read setup reply echoes it, so readers
    verify with the WRITER's bpc instead of assuming the default
    (review finding: clients hard-coded 512 and failed every block
    written with another chunk size)."""
    import os as _os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.bytes-per-checksum", "2048")
    # force the remote (TCP) read path so the bpc rides the setup reply
    conf.set("dfs.client.read.shortcircuit", "false")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as c:
        c.wait_active()
        fs = c.get_filesystem()
        payload = _os.urandom(300_001)  # odd size: partial last chunk
        fs.write_all("/bpc.bin", payload)
        assert fs.read_all("/bpc.bin") == payload


def test_remote_reads_on_multivolume_datanode(tmp_path):
    """OP_READ_BLOCK against a multi-volume DN: the VolumeSet must
    accept the xceiver's eager-open handle (review finding — a
    signature mismatch made every remote read on multi-volume DNs die
    with TypeError before the setup reply)."""
    import os as _os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.datanode.volumes", "3")
    conf.set("dfs.client.read.shortcircuit", "false")  # force TCP reads
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as c:
        c.wait_active()
        fs = c.get_filesystem()
        payload = _os.urandom(200_000)
        fs.write_all("/mv.bin", payload)
        assert fs.read_all("/mv.bin") == payload
