"""End-to-end YARN tests: real RM + node agents + subprocess containers.
(Parity targets: ref TestDistributedShell, MiniYARNCluster-based RM/NM
integration tests.)"""

import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.examples.distributed_shell import submit
from hadoop_tpu.testing.minicluster import MiniYARNCluster
from hadoop_tpu.yarn.client import YarnClient
from hadoop_tpu.yarn.records import (ApplicationSubmissionContext, AppState,
                                     ContainerLaunchContext, Resource)


@pytest.fixture(scope="module")
def cluster():
    with MiniYARNCluster(num_nodes=2) as c:
        yield c


@pytest.fixture(scope="module")
def yc(cluster):
    client = YarnClient(cluster.rm_addr,
                        Configuration(other=cluster.conf))
    yield client
    client.close()


def test_cluster_registration(cluster, yc):
    metrics = yc.cluster_metrics()
    assert metrics["num_node_managers"] == 2
    total = Resource.from_wire(metrics["total_resource"])
    assert total.memory_mb == 2 * 4096
    nodes = yc.nodes()
    assert len(nodes) == 2


def test_distributed_shell_end_to_end(cluster, yc, tmp_path):
    """Canonical acceptance: AM + 3 task containers, all real processes."""
    marker_dir = str(tmp_path)
    app_id = submit(
        cluster.rm_addr,
        ["bash", "-c",
         f"echo task-$HTPU_SHELL_INDEX > {marker_dir}/out-$HTPU_SHELL_INDEX"],
        n=3, resource=Resource(256, 1),
        conf=Configuration(other=cluster.conf))
    report = yc.wait_for_completion(app_id, timeout=60)
    assert report.state == AppState.FINISHED, report.diagnostics
    files = sorted(os.listdir(marker_dir))
    assert files == ["out-0", "out-1", "out-2"]
    assert open(os.path.join(marker_dir, "out-1")).read().strip() == "task-1"


def test_failing_command_fails_app(cluster, yc):
    app_id = submit(cluster.rm_addr, ["bash", "-c", "exit 3"], n=1,
                    conf=Configuration(other=cluster.conf))
    report = yc.wait_for_completion(app_id, timeout=60)
    # The AM observes the nonzero container exit and unregisters FAILED;
    # the app as a whole records the failure.
    assert report.state in (AppState.FAILED, AppState.FINISHED)
    assert report.final_status == AppState.FAILED or \
        "failed" in report.diagnostics


def test_kill_application(cluster, yc):
    app_id = submit(cluster.rm_addr, ["sleep", "300"], n=1,
                    conf=Configuration(other=cluster.conf))
    # Let it reach RUNNING, then kill.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if yc.application_report(app_id).state == AppState.RUNNING:
            break
        time.sleep(0.1)
    yc.kill_application(app_id)
    report = yc.wait_for_completion(app_id, timeout=30)
    assert report.state == AppState.KILLED


def test_am_failure_retries_then_fails(cluster, yc):
    """An AM that crashes is retried up to max_attempts, then the app fails.
    Ref: RMAppImpl attempt-retry transitions."""
    app_id_obj, _ = YarnClient(
        cluster.rm_addr, Configuration(other=cluster.conf)
    ).create_application()
    ctx = ApplicationSubmissionContext(
        app_id_obj, "crashy-am",
        ContainerLaunchContext(["bash", "-c", "exit 7"]),
        am_resource=Resource(128, 1), max_attempts=2)
    yc.rm.submit_application(ctx.to_wire())
    report = yc.wait_for_completion(app_id_obj, timeout=60)
    assert report.state == AppState.FAILED
    assert report.attempt_no == 2
    assert "exited 7" in report.diagnostics or "attempts" in report.diagnostics


def test_tpu_chip_isolation(cluster, yc, tmp_path):
    """Containers get disjoint HTPU_TPU_CHIPS assignments."""
    with MiniYARNCluster(num_nodes=1,
                         node_resource={"tpu_chips": 4}) as tpu_cluster:
        marker = str(tmp_path / "chips")
        os.makedirs(marker, exist_ok=True)
        app_id = submit(
            tpu_cluster.rm_addr,
            ["bash", "-c",
             f"echo $HTPU_TPU_CHIPS > {marker}/$HTPU_CONTAINER_ID"],
            n=2, resource=Resource(128, 1, 2),
            conf=Configuration(other=tpu_cluster.conf))
        client = YarnClient(tpu_cluster.rm_addr,
                            Configuration(other=tpu_cluster.conf))
        try:
            report = client.wait_for_completion(app_id, timeout=60)
            assert report.state == AppState.FINISHED, report.diagnostics
        finally:
            client.close()
        seen = set()
        for name in os.listdir(marker):
            chips = open(os.path.join(marker, name)).read().strip()
            chip_set = set(chips.split(","))
            assert len(chip_set) == 2
            assert not (seen & chip_set), "chip double-assignment"
            seen |= chip_set
        assert len(seen) == 4


def test_rm_restart_recovers_finished_state(cluster, yc, tmp_path):
    marker = str(tmp_path / "done")
    app_id = submit(cluster.rm_addr, ["bash", "-c", f"touch {marker}"], n=1,
                    conf=Configuration(other=cluster.conf))
    report = yc.wait_for_completion(app_id, timeout=60)
    assert report.state == AppState.FINISHED
    # State store has the outcome on disk.
    store = cluster.rm.state_store.load_all()
    entry = [d for d in store if d["state"] == AppState.FINISHED]
    assert entry, store
