"""Misc tools: resource estimator, datajoin, fedbalance, stream sink,
API annotations.

Mirrors the reference's smaller tool modules (ref:
hadoop-resourceestimator TestLpSolver; hadoop-datajoin TestDataJoin —
a real MR join job; hadoop-federation-balance TestFedBalance — a real
mount move; hadoop-kafka TestKafkaMetrics; hadoop-annotations).
"""

import json
import socket
import threading

import pytest

from hadoop_tpu.conf import Configuration


def test_resource_estimator_sizes_reservation():
    from hadoop_tpu.tools.resourceestimator import (estimate,
                                                    make_reservation)
    runs = [{"containers": c, "mb": 1024,
             "task_ms": {"mean": 40_000, "max": 60_000 + i * 1000}}
            for i, c in enumerate([8, 10, 9, 12, 8])]
    est = estimate(runs)
    assert est["containers"] >= 12          # p90 with headroom
    assert est["mb"] >= 1024
    assert est["duration_ms"] >= 60_000
    res = make_reservation("nightly", est, start=1000.0)
    assert res.num_containers == est["containers"]
    assert res.deadline > res.start
    with pytest.raises(ValueError):
        estimate([])


def test_resource_estimate_admits_into_scheduler():
    """The estimator's output is directly admissible by the capacity
    scheduler's ReservationSystem (the reference's end-to-end story)."""
    from hadoop_tpu.tools.resourceestimator import (estimate,
                                                    make_reservation)
    from hadoop_tpu.yarn.records import (ApplicationId, ContainerId,
                                         NodeId, Resource)
    from hadoop_tpu.yarn.scheduler import CapacityScheduler

    def cid(attempt_id, seq):
        parts = attempt_id.rsplit("_", 1)
        return ContainerId(ApplicationId.parse(parts[0]), int(parts[1]),
                           seq)

    conf = Configuration(load_defaults=False)
    conf.set("yarn.scheduler.capacity.root.queues", "default")
    sched = CapacityScheduler(conf, cid, now_fn=lambda: 0.0)
    sched.add_node(NodeId("h1", 1), Resource(65536, 64), "h1:1")
    est = estimate([{"containers": 4, "mb": 1024,
                     "task_ms": {"mean": 30_000}}])
    sched.submit_reservation(
        make_reservation("etl", est, start=0.0, deadline=100.0))
    assert "etl" in sched.reservations


def test_datajoin_mr_job(tmp_path):
    """Reduce-side join over two real inputs on a live MR cluster
    (ref: TestDataJoin)."""
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.tools.datajoin import JoinMapper, JoinReducer

    with MiniMRYarnCluster(num_nodes=1,
                           base_dir=str(tmp_path)) as cluster:
        fs = cluster.get_filesystem()
        fs.write_all("/join/users.tsv",
                     b"u1\talice\nu2\tbob\nu3\tcarol\n")
        fs.write_all("/join/orders.tsv",
                     b"u1\tbook\nu1\tpen\nu3\tlamp\nu9\tghost\n")
        job = (Job(cluster.rm_addr, cluster.default_fs, name="datajoin")
               .set_mapper(class_ref(JoinMapper))
               .set_reducer(class_ref(JoinReducer))
               .add_input_path("/join/users.tsv")
               .add_input_path("/join/orders.tsv")
               .set_output_path("/join-out")
               .set_num_reduces(1))
        assert job.wait_for_completion()
        out = b"".join(fs.read_all(p) for p in fs.glob("/join-out/part-*"))
        # u1 joins twice (two orders), u3 once, u2/u9 unmatched
        assert out.count(b"alice") == 2
        assert out.count(b"carol") == 1
        assert b"bob" not in out and b"ghost" not in out


def test_stream_sink_emits_ndjson_records():
    from hadoop_tpu.metrics.sinks import StreamSink
    received = []
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def consumer():
        conn, _ = srv.accept()
        buf = b""
        while b"\n" not in buf:
            chunk = conn.recv(65536)
            if not chunk:
                break
            buf += chunk
        received.append(buf)
        conn.close()

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    sink = StreamSink("127.0.0.1", srv.getsockname()[1], topic="tm")
    sink.put_snapshot(123.0, {"rpc.test": {"calls": 7}})
    t.join(timeout=5.0)
    sink.close()
    srv.close()
    rec = json.loads(received[0].splitlines()[0])
    assert rec["topic"] == "tm"
    assert rec["source"] == "rpc.test"
    assert rec["metrics"]["calls"] == 7


def test_api_annotations_registry():
    import hadoop_tpu.fs.filesystem  # noqa: F401 — registers annotations
    from hadoop_tpu.fs.filesystem import FileSystem
    from hadoop_tpu.util.annotations import api_report
    assert FileSystem._api_audience == "Public"
    assert FileSystem._api_stability == "Stable"
    rep = {r["name"]: r for r in api_report()}
    assert rep["hadoop_tpu.fs.filesystem.FileSystem"]["audience"] == \
        "Public"


def test_fedbalance_moves_mount_between_nameservices(tmp_path):
    """FedBalance: distcp the subtree, repoint the mount, retire the
    source (ref: hadoop-federation-balance's three procedures)."""
    from hadoop_tpu.dfs.client.filesystem import DistributedFileSystem
    from hadoop_tpu.dfs.router import Router
    from hadoop_tpu.testing.minicluster import (MiniDFSCluster,
                                                MiniMRYarnCluster,
                                                fast_conf)
    from hadoop_tpu.tools.fedbalance import fedbalance

    dconf = fast_conf()
    dconf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=dconf,
                        base_dir=str(tmp_path / "ns1")) as ns1, \
            MiniDFSCluster(num_datanodes=1, conf=dconf,
                           base_dir=str(tmp_path / "ns2")) as ns2, \
            MiniMRYarnCluster(num_nodes=1,
                              base_dir=str(tmp_path / "mr")) as mr:
        ns1.wait_active()
        ns2.wait_active()
        rconf = Configuration(load_defaults=False)
        rconf.set("dfs.federation.ns.ns1",
                  f"127.0.0.1:{ns1.namenode.port}")
        rconf.set("dfs.federation.ns.ns2",
                  f"127.0.0.1:{ns2.namenode.port}")
        router = Router(rconf, state_dir=str(tmp_path / "router"))
        router.init(rconf)
        router.start()
        try:
            router.mounts.add("/data", "ns1", "/warm")
            f1 = ns1.get_filesystem()
            f1.write_all("/warm/a.bin", b"A" * 5000)
            f1.write_all("/warm/sub/b.bin", b"B" * 3000)

            report = fedbalance(router, mr.rm_addr, mr.default_fs,
                                "/data", "ns2", "/migrated")
            assert report["to"] == ["ns2", "/migrated"]
            # mount now points at ns2, data readable through the router
            rfs = DistributedFileSystem([("127.0.0.1", router.port)],
                                        Configuration(load_defaults=False))
            try:
                assert rfs.read_all("/data/a.bin") == b"A" * 5000
                assert rfs.read_all("/data/sub/b.bin") == b"B" * 3000
            finally:
                rfs.close()
            # landed on ns2; source retired
            assert ns2.get_filesystem().read_all(
                "/migrated/a.bin") == b"A" * 5000
            assert not ns1.get_filesystem().exists("/warm/a.bin")
        finally:
            router.stop()


def test_fs2img_provided_storage(tmp_path):
    """fs2img mounts an external tree as PROVIDED storage: namespace +
    alias map on the NN, reads served by DNs range-reading the external
    store, nothing copied (ref: hadoop-fs2img + HDFS-9806 provided
    volumes). Survives an NN restart (alias map rides the image)."""
    import os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    from hadoop_tpu.tools.fs2img import mount_tree

    # external data: a local tree
    ext = tmp_path / "external"
    (ext / "sub").mkdir(parents=True)
    big = os.urandom(3 * 1024 * 1024)  # spans multiple 1MB blocks
    (ext / "big.bin").write_bytes(big)
    (ext / "sub" / "small.txt").write_bytes(b"provided bytes")

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path / "dfs")) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        report = mount_tree(fs, f"file://{ext}", "/provided")
        assert report["files"] == 2
        # reads flow DN → external file, CRC'd like any replica
        assert fs.read_all("/provided/sub/small.txt") == b"provided bytes"
        assert fs.read_all("/provided/big.bin") == big
        with fs.open("/provided/big.bin") as f:
            assert f.pread(2_000_000, 64) == big[2_000_000:2_000_064]
        st = fs.get_file_status("/provided/big.bin")
        assert st.length == len(big)
        # no local replicas were created for provided blocks
        locs = fs.client.get_block_locations("/provided/big.bin")
        assert locs["blocks"], "provided blocks must have locations"

        # namespace + alias map survive an NN restart via the image
        cluster.namenode.fsn.save_namespace()
        cluster.restart_namenode()
        cluster.wait_active()
        fs2 = cluster.get_filesystem()
        assert fs2.read_all("/provided/sub/small.txt") == b"provided bytes"


def test_pipes_cpp_wordcount_job(tmp_path):
    """A C++ pipes binary (native/src/pipes.hh API) runs as a real MR
    job — map and reduce phases both execute compiled C++ (ref:
    hadoop-pipes Submitter + its wordcount example)."""
    import pytest

    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.tools.pipes import (example_wordcount_binary,
                                        pipes_job)

    prog = example_wordcount_binary()
    if prog is None:
        pytest.skip("pipes example binary not built")
    with MiniMRYarnCluster(num_nodes=1,
                           base_dir=str(tmp_path)) as cluster:
        fs = cluster.get_filesystem()
        fs.write_all("/pin/a.txt",
                     b"the quick fox\nand the lazy dog and the fox\n")
        job = pipes_job(cluster.rm_addr, cluster.default_fs,
                        "/pin", "/pout", program=prog)
        assert job.wait_for_completion()
        out = b"".join(fs.read_all(p) for p in fs.glob("/pout/part-*"))
        counts = dict(line.split(b"\t") for line in out.splitlines())
        assert counts[b"the"] == b"3"
        assert counts[b"fox"] == b"2"
        assert counts[b"dog"] == b"1"


def test_reference_trace_dialects_convert_and_replay():
    """Migration story: traces written by the REFERENCE tooling (the
    SLS input json and rumen LoggedJob streams) convert into the
    canonical trace and drive the real scheduler via SLS (ref:
    SLSRunner's input modes + RumenToSLSConverter)."""
    import json as _json

    from hadoop_tpu.tools.rumen import load_reference_trace
    from hadoop_tpu.tools.sls import SyntheticTrace, run

    # SLS dialect: a stream of two job objects (jackson MappingIterator
    # shape — concatenated, not an array)
    sls_text = _json.dumps({
        "am.type": "mapreduce", "job.start.ms": 0,
        "job.end.ms": 9000, "job.queue.name": "q1", "job.id": "job_1",
        "job.user": "alice",
        "job.tasks": [
            {"container.host": "/r/n1", "container.start.ms": 1000,
             "container.end.ms": 5000, "container.type": "map"},
            {"container.host": "/r/n2", "container.start.ms": 1000,
             "container.end.ms": 8000, "container.type": "reduce"},
        ]}) + "\n" + _json.dumps({
        "am.type": "mapreduce", "job.start.ms": 4000,
        "job.queue.name": "q2", "job.id": "job_2", "job.user": "bob",
        "job.tasks": [
            {"container.start.ms": 5000, "container.end.ms": 6000,
             "container.type": "map"}]})
    jobs = load_reference_trace(sls_text)
    assert [j["job_id"] for j in jobs] == ["job_1", "job_2"]
    assert jobs[0]["containers"] == 2 and jobs[0]["reduces"] == 1
    assert jobs[0]["arrival"] == 0 and jobs[1]["arrival"] == 4
    assert jobs[0]["task_ms"]["mean"] == (4000 + 7000) // 2

    # rumen LoggedJob dialect (the keys RumenToSLSConverter reads)
    rumen_text = _json.dumps({
        "jobID": "job_201601010000_0001", "submitTime": 100000,
        "finishTime": 160000, "queue": "prod", "user": "carol",
        "mapTasks": [
            {"attempts": [{"startTime": 101000, "finishTime": 103000,
                           "hostName": "/r/n1"}]},
            {"attempts": [{"startTime": 101000, "finishTime": 105000,
                           "hostName": "/r/n2"}]}],
        "reduceTasks": [
            {"attempts": [{"startTime": 106000, "finishTime": 109000,
                           "hostName": "/r/n1"}]}]})
    rjobs = load_reference_trace(rumen_text)
    assert rjobs[0]["containers"] == 3
    assert rjobs[0]["maps"] == 2 and rjobs[0]["reduces"] == 1
    assert rjobs[0]["queue"] == "prod"

    # app ids derive from job ids, so merged traces don't collide
    merged = jobs + rjobs
    assert len({j["app"] for j in merged}) == len(merged)

    # converted traces drive the real scheduler end-to-end (the sim's
    # default capacity config has one queue; the dialect queues were
    # asserted above)
    trace = SyntheticTrace.__new__(SyntheticTrace)
    trace.jobs = [dict(j, queue="default") for j in merged]
    report = run(num_nodes=4, num_apps=0, scheduler="capacity",
                 ticks=200, trace=trace)
    assert report["containers_allocated"] == \
        sum(j["containers"] for j in trace.jobs)


def test_datajoin_same_basename_directory_inputs(tmp_path):
    """Two DIRECTORY inputs whose part files share basenames must join
    as distinct sources (review finding: basename-only tags collapsed
    both sides and the inner join silently emitted nothing)."""
    from hadoop_tpu.mapreduce import Job
    from hadoop_tpu.mapreduce.api import class_ref
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.tools.datajoin import JoinMapper, JoinReducer

    with MiniMRYarnCluster(num_nodes=1,
                           base_dir=str(tmp_path)) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/a")
        fs.mkdirs("/b")
        fs.write_all("/a/part-00000", b"k1\tleftA\nk2\tleftB\n")
        fs.write_all("/b/part-00000", b"k1\trightA\nk3\trightC\n")
        job = (Job(cluster.rm_addr, cluster.default_fs, name="dj2")
               .set_mapper(class_ref(JoinMapper))
               .set_reducer(class_ref(JoinReducer))
               .add_input_path("/a")
               .add_input_path("/b")
               .set_output_path("/j2-out")
               .set_num_reduces(1))
        assert job.wait_for_completion()
        out = b"".join(fs.read_all(p) for p in fs.glob("/j2-out/part-*"))
        assert b"leftA" in out and b"rightA" in out, out
        assert b"leftB" not in out  # unmatched key drops (inner join)
