"""Single-device model-core tests (tiny configs, CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.models import get_config, init_params, forward, count_params
from hadoop_tpu.models.decoder import SINGLE
from hadoop_tpu.ops import softmax_cross_entropy, causal_attention
from hadoop_tpu.ops.attention import chunk_attention, merge_attention


@pytest.mark.parametrize("preset", ["tiny", "tiny-moe", "tiny-gpt2"])
def test_forward_shapes(preset):
    cfg = get_config(preset)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))
    assert count_params(params) > 0


def test_causality():
    """Changing a future token must not change earlier logits."""
    cfg = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                cfg.vocab_size)
    logits_a = forward(params, tokens, cfg)
    tokens_b = tokens.at[0, 10].set((tokens[0, 10] + 1) % cfg.vocab_size)
    logits_b = forward(params, tokens_b, cfg)
    np.testing.assert_allclose(logits_a[0, :10], logits_b[0, :10],
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(logits_a[0, 10:], logits_b[0, 10:])


def test_loss_decreases():
    cfg = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    def loss_fn(p):
        return softmax_cross_entropy(forward(p, tokens, cfg), targets)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    l0, g = grad_fn(params)
    for _ in range(5):
        params = jax.tree_util.tree_map(lambda p, gg: p - 0.05 * gg,
                                        params, g)
        l1, g = grad_fn(params)
    assert float(l1) < float(l0)


def test_chunked_attention_matches_full():
    """online-softmax chunk merge == monolithic attention (ring invariant)."""
    rng = jax.random.PRNGKey(0)
    b, s, h, d = 2, 32, 4, 16
    q, k, v = (jax.random.normal(kk, (b, s, h, d))
               for kk in jax.random.split(rng, 3))
    full = causal_attention(q, k, v)

    scale = 1.0 / (d ** 0.5)
    n_chunks = 4
    cs = s // n_chunks
    pos = jnp.arange(s)
    out = jnp.zeros((b, s, h, d), jnp.float32)
    lse = jnp.full((b, s, h), -jnp.inf, jnp.float32)
    for i in range(n_chunks):
        kc = k[:, i * cs:(i + 1) * cs]
        vc = v[:, i * cs:(i + 1) * cs]
        o_i, l_i = chunk_attention(q, kc, vc, scale, pos,
                                   pos[i * cs:(i + 1) * cs])
        out, lse = merge_attention(out, lse, o_i, l_i)
    np.testing.assert_allclose(np.asarray(full), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_moe_routing_mass():
    """Combine weights per token sum to ~1 when capacity is ample."""
    from hadoop_tpu.models.moe import route
    cfg = get_config("tiny-moe", capacity_factor=4.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (64, cfg.d_model))
    w = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.d_model, cfg.n_experts))
    dispatch, combine = route(x, w, cfg)
    mass = np.asarray(jnp.sum(combine, axis=(1, 2)))
    np.testing.assert_allclose(mass, np.ones_like(mass), atol=1e-5)
    # no expert slot double-booked
    slot_fill = np.asarray(jnp.sum(dispatch, axis=0))
    assert slot_fill.max() <= 1.0 + 1e-6
