"""Expert-parallel MoE serving (serving/engine.py ``_moe_mlp`` + the
weight plane's expert stacks).

Pins the contracts the workload class ships under:

- the per-tensor policy table covers the expert stacks (int8
  per-expert, router stays f32) and the streamed quantize-at-load path
  is bit-identical to the in-memory application on an MoE checkpoint;
- capacity semantics at the serving seam: a top_k = n_experts
  degenerate config matches the dense path, dropped tokens pass the
  residual through EXACTLY (all-zero MLP contribution);
- the fused step stays compile-once per shape with routing enabled —
  capacity padding keeps shapes static;
- the relaxed tier's all2all payload quantization is measured on the
  comm ledger (``moe.dispatch``/``moe.combine``, >= 2x byte cut,
  honest per-step executions) and gated by the logits A-B guard, which
  must also REJECT a zeroed expert payload (falsifiability);
- expert placement is observable: the ``moe_experts`` HBM component,
  the ``htpu_hbm_bytes`` gauge, and the weight-plane/health fields.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.models.config import get_config
from hadoop_tpu.models.decoder import init_params
from hadoop_tpu.models.moe import capacity, route
from hadoop_tpu.serving import weightplane as wp
from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams


@pytest.fixture(scope="module")
def moe_model():
    cfg = get_config("tiny-moe")
    return init_params(jax.random.PRNGKey(0), cfg), cfg


MOE_POLICY = wp.WeightPlaneConfig(tier="relaxed", group=16)
# MoE guard thresholds: near-tie routing flips spike single positions'
# logits, so the rel-err bound is wide and the argmax-agreement
# dimension carries the systematic-damage check (the falsifier test
# below proves the pair still discriminates)
MOE_AGREE, MOE_REL = 0.9, 3.0


# ------------------------------------------------ weight-plane coverage

def test_policy_quantizes_expert_stacks_router_stays_f32(moe_model):
    params, cfg = moe_model
    qp, rep = wp.quantize_params(params, cfg, MOE_POLICY)
    layers = qp["layers"]
    for k in sorted(wp.EXPERT_STACKS):
        assert wp.is_qtensor(layers[k]), k
        # per-expert grouping: leading [L, E] dims survive on payload
        # AND scales — a scale can never pair with another expert's q
        L, E = cfg.n_layers, cfg.n_experts
        assert layers[k]["q"].shape[:2] == (L, E)
        assert layers[k]["s"].shape[:2] == (L, E)
    # the router is value-critical and byte-irrelevant: stays f32
    assert not wp.is_qtensor(layers["router"])
    assert layers["router"].dtype == jnp.float32
    # 4 attn matmuls + 3 expert stacks
    assert rep["leaves_quantized"] == 7
    assert rep["moe_experts"] == cfg.n_experts
    # measured expert bytes: the int8 stacks are ~4x under f32
    eb_f32 = wp.expert_weight_bytes(params, cfg)
    eb_int8 = wp.expert_weight_bytes(qp, cfg)
    assert rep["expert_bytes"] == eb_int8
    assert eb_f32 > 3 * eb_int8 > 0
    # dense configs report zero (the component is MoE-only)
    dense_cfg = get_config("tiny")
    dense = init_params(jax.random.PRNGKey(0), dense_cfg)
    assert wp.expert_weight_bytes(dense, dense_cfg) == 0


def test_dequantize_round_trips_expert_stacks(moe_model):
    """dequantize_params restores the expert stacks' shapes/axes —
    run_weight_ab's reference forward depends on this."""
    params, cfg = moe_model
    qp, _ = wp.quantize_params(params, cfg, MOE_POLICY)
    back = wp.dequantize_params(qp, cfg)
    for k in sorted(wp.EXPERT_STACKS):
        a, b = params["layers"][k], back["layers"][k]
        assert a.shape == b.shape
        assert np.allclose(np.asarray(a), np.asarray(b), atol=2e-2)


def test_expert_shard_count_rules():
    # auto: the largest divisor of n_experts that fits the devices
    assert wp.expert_shard_count(8, 0, 4) == 4
    assert wp.expert_shard_count(8, 0, 3) == 2
    assert wp.expert_shard_count(4, 0, 1) == 1
    assert wp.expert_shard_count(0, 0, 8) == 1     # dense: no shards
    # explicit: must divide the experts and fit the devices — loudly
    assert wp.expert_shard_count(8, 2, 4) == 2
    with pytest.raises(ValueError, match="divide"):
        wp.expert_shard_count(8, 3, 4)
    with pytest.raises(ValueError, match="device"):
        wp.expert_shard_count(8, 8, 4)


def test_streamed_moe_load_bit_identical(tmp_path, moe_model):
    """Quantize-at-load on an MoE checkpoint: the expert stacks stream
    through the same per-leaf transform and land BIT-identical to the
    in-memory policy application."""
    from hadoop_tpu.fs import LocalFileSystem
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    params, cfg = moe_model
    fs = LocalFileSystem()
    save_checkpoint(fs, f"{tmp_path}/ckpt", 3,
                    {"params": params, "opt": {}})
    qp_mem, _ = wp.quantize_params(params, cfg, MOE_POLICY)
    qp_load, step, report = wp.quantized_load(
        fs, f"{tmp_path}/ckpt", cfg, MOE_POLICY, io_workers=4)
    assert step == 3
    assert report["expert_bytes"] == wp.expert_weight_bytes(qp_mem, cfg)
    a = jax.tree_util.tree_leaves(qp_mem)
    b = jax.tree_util.tree_leaves(qp_load)
    assert len(a) == len(b)
    assert all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(a, b))
    # and the streamed tree serves through the routed step
    eng = DecodeEngine(qp_load, cfg, max_batch=2, block_size=4,
                       max_context=64)
    assert len(eng.generate([[1, 2, 3]],
                            SamplingParams(max_new_tokens=3))[0]) == 3


# ----------------------------------------- capacity semantics at serving

def test_topk_equals_experts_matches_dense_path(moe_model):
    """top_k = n_experts with identical experts degenerates to ONE
    dense SwiGLU MLP (renormalized gates sum to 1), so the routed
    engine must match a dense engine built from expert 0's weights —
    same embed/attention tree, same greedy tokens."""
    params, cfg = moe_model
    deg_cfg = dataclasses.replace(cfg, top_k=cfg.n_experts)
    layers = dict(params["layers"])
    for k in sorted(wp.EXPERT_STACKS):
        w = layers[k]
        layers[k] = jnp.broadcast_to(w[:, :1], w.shape)
    moe_params = dict(params)
    moe_params["layers"] = layers

    dense_cfg = dataclasses.replace(cfg, n_experts=0)
    dense_layers = {k: (v[:, 0] if k in wp.EXPERT_STACKS else v)
                    for k, v in layers.items() if k != "router"}
    dense_params = dict(params)
    dense_params["layers"] = dense_layers

    prompts = [[7, 3, 11, 5], [2, 9]]
    sp = SamplingParams(max_new_tokens=6)
    eng_moe = DecodeEngine(moe_params, deg_cfg, max_batch=2,
                           block_size=4, max_context=64)
    eng_dense = DecodeEngine(dense_params, dense_cfg, max_batch=2,
                             block_size=4, max_context=64)
    assert eng_moe.generate(prompts, sp) == eng_dense.generate(prompts,
                                                               sp)


def test_dropped_token_residual_passthrough_exact(moe_model):
    """Tokens past every routed expert's capacity contribute EXACTLY
    zero MLP output (all-zero combine row -> exact 0.0 from the
    combine einsum), i.e. the residual passes through bit-for-bit.
    Routing is forced: every token picks experts 0 and 1, so with
    T=8, k=2, E=4, cf=1.25 the capacity is C=5 and tokens 5..7 drop."""
    params, cfg = moe_model
    D, E = cfg.d_model, cfg.n_experts
    assert capacity(8, cfg) == 5
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=64)
    lp = {k: v[0] for k, v in params["layers"].items()}
    # router: every token's logits are [big, 0, 0, 0] -> top-2 picks
    # experts 0 and 1 (top_k tie-break is by index, deterministic)
    router = np.zeros((D, E), np.float32)
    router[0, 0] = 1.0
    lp["router"] = jnp.asarray(router)
    x = jnp.tile(jnp.eye(1, D, 0, dtype=jnp.float32) * 5.0, (8, 1))
    y = eng._moe_mlp(x, lp)
    assert y.shape == (8, D)
    y = np.asarray(y)
    # kept rows produce a real MLP contribution...
    assert np.abs(y[:5]).max() > 0
    # ...dropped rows are EXACTLY zero — not small, zero
    assert np.array_equal(y[5:], np.zeros_like(y[5:]))
    # the same rule the engine/bench observability publishes
    assert eng.weight_plane()["expert_capacity"] == \
        capacity(eng.max_batch * (eng.spec_k + 1), cfg)
    # sanity on the forced routing itself
    dispatch, combine = route(x, lp["router"], cfg)
    assert float(jnp.sum(combine[5:])) == 0.0
    assert float(jnp.sum(dispatch[:5])) > 0


def test_compile_once_with_moe_enabled(moe_model):
    """Routing must not add shape families: both arms (bitwise f32 and
    relaxed int8) compile exactly one decode-only and one fused-prefill
    program across a mixed workload, and the relaxed arm replays
    deterministically."""
    params, cfg = moe_model
    qp, _ = wp.quantize_params(params, cfg, MOE_POLICY)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, cfg.vocab_size, size=n).tolist()
               for n in (9, 4, 17, 6)]
    sp = SamplingParams(max_new_tokens=6)
    for p in (params, qp):
        eng = DecodeEngine(p, cfg, max_batch=2, block_size=4,
                           max_context=64)
        outs = eng.generate(prompts, sp)
        assert all(len(o) == 6 for o in outs)
        assert eng.decode_compiles == 1, eng.decode_compiles
        assert eng.prefill_compiles == 1, eng.prefill_compiles
    eng2 = DecodeEngine(qp, cfg, max_batch=2, block_size=4,
                        max_context=64)
    assert eng2.generate(prompts, sp) == outs


# ------------------------------------------- relaxed tier: a2a + guard

def test_comm_ledger_records_quantized_a2a(moe_model):
    """The relaxed engine's dispatch/combine legs land on the comm
    ledger at the bounded MoE sites with >= 2x byte cut and honest
    per-step executions (comm_scale x the scan length, both shapes)."""
    from hadoop_tpu.parallel.lowp.quant import capture_comm
    params, cfg = moe_model
    qp, _ = wp.quantize_params(params, cfg, MOE_POLICY)
    eng = DecodeEngine(qp, cfg, max_batch=2, block_size=4,
                       max_context=64)
    with capture_comm() as led:
        eng.generate([[5, 1, 4, 2, 8, 3]],
                     SamplingParams(max_new_tokens=4))
    assert set(led.per_site) == {"moe.dispatch", "moe.combine"}
    for site, (payload, reference, execs) in led.per_site.items():
        assert 0 < payload < reference, site
        # two shape families traced, n_layers legs each per step
        assert execs == 2 * cfg.n_layers, (site, execs)
    assert led.ratio >= 2.0, led.ratio
    # bitwise serving records NOTHING at the MoE sites (the guard the
    # lint enforces lexically, proven dynamically here)
    eng32 = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                         max_context=64)
    with capture_comm() as led32:
        eng32.generate([[5, 1, 4]], SamplingParams(max_new_tokens=3))
    assert led32.per_site == {}


def test_a2a_codec_none_serves_without_payload_quant(moe_model):
    """serving.moe.a2a.codec=none: the relaxed engine still serves the
    int8 expert stacks but exchanges f32 payloads — zero MoE comm
    sites; an unknown codec fails loudly at construction."""
    from hadoop_tpu.parallel.lowp.quant import capture_comm
    params, cfg = moe_model
    qp, _ = wp.quantize_params(params, cfg, MOE_POLICY)
    eng = DecodeEngine(qp, cfg, max_batch=2, block_size=4,
                       max_context=64, moe_a2a_codec="none")
    with capture_comm() as led:
        out = eng.generate([[5, 1, 4]], SamplingParams(max_new_tokens=3))
    assert len(out[0]) == 3
    assert led.per_site == {}
    with pytest.raises(ValueError, match="codec"):
        DecodeEngine(qp, cfg, max_batch=2, block_size=4,
                     max_context=64, moe_a2a_codec="fp4")


def test_moe_guard_accepts_and_falsifier_rejects(moe_model):
    """Acceptance rides run_weight_ab at the MoE thresholds; the SAME
    thresholds must reject a zeroed expert payload (w_down int8 bytes
    zeroed, scales kept) — falsifiability of the acceptance."""
    params, cfg = moe_model
    qp, _ = wp.quantize_params(params, cfg, MOE_POLICY)
    report = wp.run_weight_ab(cfg, params, qp, wp=MOE_POLICY,
                              min_agree=MOE_AGREE, rel_tol=MOE_REL)
    assert report["accepted"], report
    assert report["greedy_agree"] >= MOE_AGREE
    broken = dict(qp)
    broken["layers"] = dict(qp["layers"])
    wd = qp["layers"]["w_down"]
    broken["layers"]["w_down"] = {"q": jnp.zeros_like(wd["q"]),
                                  "s": wd["s"]}
    falsifier = wp.run_weight_ab(cfg, params, broken, wp=MOE_POLICY,
                                 min_agree=MOE_AGREE, rel_tol=MOE_REL)
    assert not falsifier["accepted"], falsifier


def test_capacity_factor_override_widens_slots(moe_model):
    """serving.moe.capacity.factor overrides the checkpoint config's
    padding at the engine door (0 = keep the model's)."""
    params, cfg = moe_model
    e_default = DecodeEngine(params, cfg, max_batch=8, block_size=4,
                             max_context=64)
    e_wide = DecodeEngine(params, cfg, max_batch=8, block_size=4,
                          max_context=64, moe_capacity_factor=4.0)
    c_def = e_default.weight_plane()["expert_capacity"]
    c_wide = e_wide.weight_plane()["expert_capacity"]
    assert c_wide > c_def
    assert c_def == capacity(8, cfg)
    assert c_wide == capacity(
        8, dataclasses.replace(cfg, capacity_factor=4.0))


# --------------------------------------------------------- observability

def test_moe_experts_hbm_component_and_gauge(moe_model):
    """Resident expert bytes ride the live HBM ledger as the
    ``moe_experts`` component (beside, not inside, the dense weights
    remainder), surface as the htpu_hbm_bytes gauge, and unregister at
    stop()."""
    import re

    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.prom import render_prom
    from hadoop_tpu.obs.hbm import HBM_COMPONENTS, hbm_ledger
    params, cfg = moe_model
    qp, _ = wp.quantize_params(params, cfg, MOE_POLICY)
    eng = DecodeEngine(qp, cfg, max_batch=2, block_size=4,
                       num_blocks=9, max_context=32)
    comps, errors = hbm_ledger().component_bytes()
    assert errors == 0
    assert comps["moe_experts"] == eng.expert_bytes > 0
    # the dense remainder excludes the expert stacks — no double count
    assert comps["weights"] == eng.weight_bytes - eng.expert_bytes
    assert comps["kv_pool"] == 9 * eng.block_nbytes
    text = render_prom(metrics_system())
    gauge = [ln for ln in text.splitlines()
             if 'component="moe_experts"' in ln
             and ln.startswith("htpu_hbm_bytes")]
    assert gauge and float(gauge[0].rsplit(" ", 1)[1]) == \
        eng.expert_bytes
    comp_labels = set(re.findall(
        r'htpu_hbm_bytes\{[^}]*component="([^"]+)"', text))
    assert comp_labels <= set(HBM_COMPONENTS)
    eng.stop()
    comps, _ = hbm_ledger().component_bytes()
    assert "moe_experts" not in comps and "weights" not in comps


def test_health_and_registry_surface_expert_placement(tmp_path,
                                                      moe_model):
    """/v1/health's weights block carries expert count/shards/bytes
    next to weight_dtype, and the replica's registry record advertises
    the same placement for the autoscaler."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.fs import LocalFileSystem
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    from hadoop_tpu.registry import RegistryServer
    from hadoop_tpu.serving.service import ServingReplica
    params, cfg = moe_model
    save_checkpoint(LocalFileSystem(), f"{tmp_path}/ckpt", 1,
                    {"params": params, "opt": {}})
    conf = Configuration(load_defaults=False)
    conf.set("serving.parity", "relaxed")
    conf.set("serving.weights.group", "16")
    conf.set("serving.max.batch", "2")
    conf.set("serving.kv.block.size", "4")
    conf.set("serving.max.context", "64")
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    try:
        replica = ServingReplica(
            conf, name="moe", checkpoint=f"file://{tmp_path}/ckpt",
            preset="tiny-moe",
            registry_addr=("127.0.0.1", reg_srv.port), instance="i0")
        replica.start()
        try:
            eng = replica.engine
            status, health = replica.server._health({}, b"")
            assert status == 200
            weights = health["weights"]
            assert weights["parity"] == "relaxed"
            assert weights["experts"] == cfg.n_experts
            # auto placement: under the test harness's virtual CPU
            # devices the expert dim actually splits (1 on one chip)
            shards = wp.expert_shard_count(cfg.n_experts, 0,
                                           jax.local_device_count())
            assert weights["expert_shards"] == shards >= 1
            assert weights["expert_bytes"] == eng.expert_bytes > 0
            assert weights["expert_capacity"] > 0
            assert weights["a2a_codec"] == "int8"
            rec = reg_srv.list("/services/serving/moe")[0]
            assert rec.attributes["weight_dtype"] == "int8"
            assert rec.attributes["experts"] == str(cfg.n_experts)
            assert rec.attributes["expert_shards"] == str(shards)
            assert rec.attributes["expert_bytes"] == \
                str(eng.expert_bytes)
        finally:
            replica.drain_and_stop(timeout=15)
    finally:
        reg_srv.stop()
