"""Namespace features: snapshots, quotas, xattrs, ACLs, storage policy,
trash, concat, truncate.

Mirrors the reference's feature tests (ref: hadoop-hdfs
TestSnapshot.java, TestQuota.java, TestXAttrWithSnapshot /
FSXAttrBaseTest.java, TestAcl, TestStoragePolicy, TestTrash.java,
TestHDFSConcat.java, TestFileTruncate.java)."""

import os
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.protocol.records import QuotaExceededError
from hadoop_tpu.fs.trash import Trash
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf


@pytest.fixture(scope="module")
def cluster():
    conf = fast_conf()
    conf.set("dfs.blocksize", str(128 * 1024))
    with MiniDFSCluster(num_datanodes=3, conf=conf) as c:
        c.wait_active()
        yield c


@pytest.fixture
def fs(cluster):
    return cluster.get_filesystem()


def _write(fs, path, data):
    with fs.create(path, overwrite=True) as out:
        out.write(data)


# -------------------------------------------------------------- snapshots

def test_snapshot_preserves_deleted_file(fs):
    fs.mkdirs("/snap1/sub")
    _write(fs, "/snap1/sub/keep.txt", b"version one")
    fs.allow_snapshot("/snap1")
    spath = fs.create_snapshot("/snap1", "s1")
    assert spath == "/snap1/.snapshot/s1"
    # Delete the live file; the snapshot copy must still be readable.
    fs.delete("/snap1/sub/keep.txt")
    with pytest.raises(FileNotFoundError):
        fs.get_file_status("/snap1/sub/keep.txt")
    st = fs.get_file_status("/snap1/.snapshot/s1/sub/keep.txt")
    assert st.length == len(b"version one")
    with fs.open("/snap1/.snapshot/s1/sub/keep.txt") as f:
        assert f.read() == b"version one"


def test_snapshot_diff_and_rename(fs):
    fs.mkdirs("/snap2")
    _write(fs, "/snap2/a.txt", b"a")
    fs.allow_snapshot("/snap2")
    fs.create_snapshot("/snap2", "before")
    _write(fs, "/snap2/b.txt", b"b")
    fs.delete("/snap2/a.txt")
    diff = fs.snapshot_diff("/snap2", "before", "")
    assert "/snap2/b.txt" in diff["created"]
    assert "/snap2/a.txt" in diff["deleted"]
    assert diff["modified"] == []
    fs.rename_snapshot("/snap2", "before", "renamed")
    assert fs.get_file_status("/snap2/.snapshot/renamed/a.txt")
    fs.delete_snapshot("/snap2", "renamed")
    with pytest.raises(FileNotFoundError):
        fs.get_file_status("/snap2/.snapshot/renamed/a.txt")


def test_snapshot_listing(fs):
    fs.mkdirs("/snap3")
    fs.allow_snapshot("/snap3")
    fs.create_snapshot("/snap3", "x")
    fs.create_snapshot("/snap3", "y")
    names = sorted(st.path.rsplit("/", 1)[-1]
                   for st in fs.list_status("/snap3/.snapshot"))
    assert names == ["x", "y"]


def test_snapshot_survives_nn_restart(cluster, fs):
    fs.mkdirs("/snap4")
    _write(fs, "/snap4/f.txt", b"persisted")
    fs.allow_snapshot("/snap4")
    fs.create_snapshot("/snap4", "keeper")
    fs.delete("/snap4/f.txt")
    cluster.namenode.fsn.save_namespace()
    cluster.restart_namenode()
    cluster.wait_active()
    fs2 = cluster.get_filesystem()
    with fs2.open("/snap4/.snapshot/keeper/f.txt") as f:
        assert f.read() == b"persisted"


# ----------------------------------------------------------------- quotas

def test_namespace_quota_enforced(fs):
    fs.mkdirs("/q1")
    fs.set_quota("/q1", ns_quota=3)  # dir itself + 2 children
    _write(fs, "/q1/a", b"x")
    _write(fs, "/q1/b", b"x")
    with pytest.raises(QuotaExceededError):
        _write(fs, "/q1/c", b"x")
    # Clearing the quota unblocks.
    fs.set_quota("/q1", ns_quota=-1)
    _write(fs, "/q1/c", b"x")


def test_space_quota_enforced(fs):
    fs.mkdirs("/q2")
    # One 128k block × 3 replicas fits; a second block does not.
    fs.set_quota("/q2", space_quota=int(128 * 1024 * 3.5))
    _write(fs, "/q2/one", os.urandom(100 * 1024))
    with pytest.raises((QuotaExceededError, IOError)):
        _write(fs, "/q2/two", os.urandom(200 * 1024))


def test_content_summary_reflects_quota_usage(fs):
    fs.mkdirs("/q3/deep")
    _write(fs, "/q3/deep/f", b"12345")
    cs = fs.content_summary("/q3")
    assert cs["files"] == 1 and cs["length"] == 5


# ------------------------------------------------------------ xattrs/acls

def test_xattr_roundtrip_and_persistence(cluster, fs):
    fs.mkdirs("/x1")
    fs.set_xattr("/x1", "user.purpose", b"tpu-training-data")
    fs.set_xattr("/x1", "user.owner-team", b"infra")
    assert fs.get_xattrs("/x1")["user.purpose"] == b"tpu-training-data"
    fs.remove_xattr("/x1", "user.owner-team")
    assert "user.owner-team" not in fs.get_xattrs("/x1")
    with pytest.raises(ValueError):
        fs.set_xattr("/x1", "nonamespace", b"v")
    cluster.restart_namenode()
    cluster.wait_active()
    fs2 = cluster.get_filesystem()
    assert fs2.get_xattrs("/x1")["user.purpose"] == b"tpu-training-data"


def test_acl_roundtrip(fs):
    fs.mkdirs("/a1")
    entries = ["user:alice:rw-", "group:infra:r--"]
    fs.set_acl("/a1", entries)
    assert fs.get_acl("/a1") == entries
    with pytest.raises(ValueError):
        fs.set_acl("/a1", ["garbage"])


# --------------------------------------------------------- storage policy

def test_storage_policy_inheritance(fs):
    fs.mkdirs("/sp1/child")
    assert fs.get_storage_policy("/sp1/child") == "HOT"
    fs.set_storage_policy("/sp1", "COLD")
    assert fs.get_storage_policy("/sp1/child") == "COLD"
    fs.set_storage_policy("/sp1/child", "ALL_SSD")
    assert fs.get_storage_policy("/sp1/child") == "ALL_SSD"
    with pytest.raises(ValueError):
        fs.set_storage_policy("/sp1", "NOT_A_POLICY")


# ------------------------------------------------------------------ trash

def test_trash_move_and_expunge(fs):
    _write(fs, "/tr/doomed.txt", b"recoverable")
    trash = Trash(fs, interval_s=3600.0)
    loc = trash.move_to_trash("/tr/doomed.txt")
    assert "/.Trash/Current/tr/doomed.txt" in loc
    with fs.open(loc) as f:
        assert f.read() == b"recoverable"
    # Roll a checkpoint, then expunge immediately → all gone.
    trash.checkpoint()
    removed = trash.expunge(immediately=True)
    assert removed
    with pytest.raises(FileNotFoundError):
        fs.get_file_status(loc)


def test_trash_expunges_collision_suffixed_checkpoints(fs):
    """Two checkpoints in one wall-clock second produce a '<stamp>-N'
    name; those must expire on the same schedule as bare stamps (review
    finding: the expunge pattern only knew \\d{12}, so suffixed
    checkpoints leaked forever)."""
    trash = Trash(fs, interval_s=3600.0)
    root = trash._trash_root()
    first = second = ""
    for _ in range(5):  # the pair is ~ms apart; straddling a second
        fs.mkdirs(f"{root}/Current")          # boundary twice is ~never
        first = trash.checkpoint()
        fs.mkdirs(f"{root}/Current")
        second = trash.checkpoint()
        if "-" in second.rsplit("/", 1)[-1]:
            break
        trash.expunge(immediately=True)
    assert "-" in second.rsplit("/", 1)[-1], (first, second)
    removed = trash.expunge(immediately=True)
    assert first in removed and second in removed


def test_trash_sibling_of_root_is_trashable(fs):
    """A path sharing the trash root's name as a string prefix but NOT a
    component prefix (/user/u/.TrashOld vs /user/u/.Trash) must be
    movable to trash (ref: TrashPolicyDefault's path containment check)."""
    trash = Trash(fs, interval_s=3600.0)
    root = trash._trash_root()
    sibling = root + "Old"
    _write(fs, sibling + "/f.txt", b"x")
    loc = trash.move_to_trash(sibling)
    assert "/.Trash/Current" in loc
    # And the root itself still refuses.
    fs.mkdirs(root + "/Current")
    with pytest.raises(ValueError):
        trash.move_to_trash(root)


# --------------------------------------------------------- concat/truncate

def test_concat_merges_blocks(fs):
    _write(fs, "/cc/a", os.urandom(130 * 1024))   # > 1 block
    _write(fs, "/cc/b", os.urandom(50 * 1024))
    with fs.open("/cc/a") as f:
        a = f.read()
    with fs.open("/cc/b") as f:
        b = f.read()
    fs.concat("/cc/a", ["/cc/b"])
    with pytest.raises(FileNotFoundError):
        fs.get_file_status("/cc/b")
    st = fs.get_file_status("/cc/a")
    assert st.length == len(a) + len(b)
    with fs.open("/cc/a") as f:
        assert f.read() == a + b


def test_quota_enforced_on_nested_creates(fs):
    fs.mkdirs("/q4")
    fs.set_quota("/q4", ns_quota=3)
    with pytest.raises(QuotaExceededError):
        fs.mkdirs("/q4/a/b/c")  # would add 3 inodes under a quota of 3(-1)


def test_delete_of_snapshottable_dir_refused(fs):
    fs.mkdirs("/sd1")
    _write(fs, "/sd1/f", b"x")
    fs.allow_snapshot("/sd1")
    fs.create_snapshot("/sd1", "s")
    with pytest.raises(OSError):
        fs.delete("/sd1", recursive=True)
    fs.delete_snapshot("/sd1", "s")
    assert fs.delete("/sd1", recursive=True)


def test_concat_rejects_self_and_duplicates(fs):
    _write(fs, "/cc2/t", b"target")
    _write(fs, "/cc2/s", b"source")
    with pytest.raises(ValueError):
        fs.concat("/cc2/t", ["/cc2/t"])
    with pytest.raises(ValueError):
        fs.concat("/cc2/t", ["/cc2/s", "/cc2/s"])
    with fs.open("/cc2/t") as f:
        assert f.read() == b"target"  # unharmed by the rejections


def test_truncate_refused_when_snapshotted(fs):
    fs.mkdirs("/sd2")
    _write(fs, "/sd2/f", os.urandom(200 * 1024))
    fs.allow_snapshot("/sd2")
    fs.create_snapshot("/sd2", "pin")
    with pytest.raises(OSError):
        fs.truncate("/sd2/f", 10)
    with fs.open("/sd2/.snapshot/pin/f") as f:
        assert len(f.read()) == 200 * 1024


def test_truncate_drops_and_trims(fs):
    data = os.urandom(300 * 1024)  # 3 blocks at 128k
    _write(fs, "/tt/f", data)
    assert fs.truncate("/tt/f", 150 * 1024)
    st = fs.get_file_status("/tt/f")
    assert st.length == 150 * 1024
    with fs.open("/tt/f") as f:
        assert f.read() == data[:150 * 1024]
    with pytest.raises(ValueError):
        fs.truncate("/tt/f", 10**9)


def test_dot_and_dotdot_path_components_rejected(fs, cluster):
    """'.'/'..' are invalid COMPONENT names on name-CREATING ops (ref:
    DFSUtil.isValidName, validated at the write boundary): the
    namespace walks literally, so a directory literally named '..'
    would make POSIX-normalizing clients and prefix-based rules (trash
    containment, encryption zones, mounts) address a different node
    than the one stored (probe finding: mkdirs('/a/../b') created a
    literal '..' child). Read/delete paths stay permissive so a tree
    holding a pre-fix literal node can still be cleaned up."""
    for bad in ("/a/../b", "/a/./b", "/..", "/."):
        with pytest.raises((ValueError, OSError)):
            fs.mkdirs(bad)
        with pytest.raises((ValueError, OSError, FileNotFoundError)):
            fs.write_all(bad + "/f", b"x")
    fs.mkdirs("/renbase")
    fs.write_all("/renbase/f", b"x")
    with pytest.raises((ValueError, OSError)):
        fs.rename("/renbase/f", "/renbase/../escape")
    # cleanup escape hatch: a literal legacy node (fabricated below the
    # validation boundary) is still deletable by path
    fsn = cluster.namenode.fsn
    with fsn.lock.write():
        fsn.fsdir.mkdirs("/renbase/..", owner="root")
    assert fs.delete("/renbase/..", recursive=True)
