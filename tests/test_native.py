"""Native library: build, CRC parity with pure-Python, RS recoverability.

Mirrors the reference's native test posture (ref:
hadoop-common/src/main/native/gtest, TestNativeCrc32.java,
rawcoder/TestRSRawCoder.java): native and pure paths must agree
bit-for-bit, and RS must recover from every loss pattern up to m.
"""

import itertools
import os
import random

import pytest

from hadoop_tpu import native as nat
from hadoop_tpu.util import crc as crcmod


requires_native = pytest.mark.skipif(
    not nat.available(), reason="native toolchain unavailable")


@requires_native
def test_crc32c_known_vector():
    assert nat.crc32c(0, b"123456789") == 0xE3069283


@requires_native
def test_crc32c_native_matches_python():
    rng = random.Random(7)
    for n in (0, 1, 7, 8, 9, 511, 512, 513, 4096):
        data = bytes(rng.randrange(256) for _ in range(n))
        assert nat.crc32c(0, data) == crcmod._crc32c_py(0, data)


@requires_native
def test_chunked_roundtrip_and_first_bad_chunk():
    data = os.urandom(16 * 512 + 100)
    sums = nat.crc32c_chunked(data, 512)
    assert nat.crc32c_verify(data, 512, sums) == -1
    bad = bytearray(data)
    bad[7 * 512 + 3] ^= 0xFF
    assert nat.crc32c_verify(bytes(bad), 512, sums) == 7


@requires_native
@pytest.mark.parametrize("k,m", [(3, 2), (6, 3), (10, 4)])
def test_rs_recovers_every_loss_pattern(k, m):
    cell = 256
    data = os.urandom(k * cell)
    parity = nat.rs_encode(k, m, cell, data)
    full = data + parity
    for lost in itertools.combinations(range(k + m), m):
        shards = bytearray(full)
        present = [i not in lost for i in range(k + m)]
        for i in lost:
            shards[i * cell:(i + 1) * cell] = b"\0" * cell
        assert nat.rs_decode(k, m, cell, bytes(shards), present) == full


@requires_native
def test_rs_too_many_losses_raises():
    cell = 64
    data = os.urandom(3 * cell)
    parity = nat.rs_encode(3, 2, cell, data)
    present = [False, False, False, True, True]
    with pytest.raises(ValueError):
        nat.rs_decode(3, 2, cell, data + parity, present)


@requires_native
def test_xor_parity():
    d = os.urandom(128)
    p = nat.xor_encode(2, 64, d)
    assert p == bytes(a ^ b for a, b in zip(d[:64], d[64:]))


@requires_native
def test_sort_kv_matches_python_sort():
    rng = random.Random(13)
    keys = [os.urandom(rng.randint(0, 24)) for _ in range(1000)]
    parts = [rng.randint(0, 9) for _ in range(1000)]
    offs, o = [], 0
    for k in keys:
        offs.append(o)
        o += len(k)
    idx = nat.sort_kv(b"".join(keys), offs, [len(k) for k in keys], parts)
    assert [(parts[i], keys[i]) for i in idx] == sorted(
        zip(parts, keys), key=lambda t: (t[0], t[1]))


def test_datachecksum_verify_uses_available_backend():
    # Exercises whichever backend is live; content checks are backend-blind.
    cs = crcmod.DataChecksum(512)
    data = os.urandom(3000)
    sums = cs.checksums_for(data)
    cs.verify(data, sums)
    bad = bytearray(data)
    bad[1500] ^= 1
    with pytest.raises(crcmod.ChecksumError) as ei:
        cs.verify(bytes(bad), sums)
    assert ei.value.pos == 1024


def test_native_io_fadvise_and_sync_range(tmp_path):
    """NativeIO page-cache hints succeed against a real fd (ref:
    NativeIO.c posix_fadvise/sync_file_range bindings)."""
    from hadoop_tpu import native
    if not native.available():
        import pytest
        pytest.skip("native library unavailable")
    p = tmp_path / "f.bin"
    with open(p, "wb") as f:
        f.write(b"z" * 65536)
        f.flush()
        assert native.fadvise(f.fileno(), 0, 65536,
                              native.FADV_SEQUENTIAL)
        assert native.sync_file_range(f.fileno(), 0, 65536)
        assert native.sync_file_range(f.fileno(), 0, 65536, wait=True)
        assert native.fadvise(f.fileno(), 0, 65536, native.FADV_DONTNEED)
    # bad fd reports failure instead of raising
    assert not native.fadvise(999999, 0, 1, native.FADV_DONTNEED)


def test_libhtpufs_c_client_against_live_cluster(tmp_path):
    """libhtpufs (the libhdfs slot): the C library speaks to a live
    NameNode's WebHDFS gateway with its OWN sockets/HTTP/JSON — ctypes
    here only drives the test; no Python runs inside the client path
    (ref: hadoop-hdfs-native-client libhdfs API shape)."""
    import ctypes
    import os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    so = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                      "hadoop_tpu", "native", "libhtpufs.so")
    if not os.path.exists(so):
        import pytest
        pytest.skip("libhtpufs.so not built")
    lib = ctypes.CDLL(so)
    lib.htpufs_connect.restype = ctypes.c_void_p
    lib.htpufs_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.htpufs_disconnect.argtypes = [ctypes.c_void_p]
    lib.htpufs_exists.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.htpufs_mkdirs.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.htpufs_get_file_size.restype = ctypes.c_int64
    lib.htpufs_get_file_size.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.htpufs_write_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.c_int]
    lib.htpufs_pread.restype = ctypes.c_int64
    lib.htpufs_pread.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int64, ctypes.c_char_p,
                                 ctypes.c_int64]
    lib.htpufs_rename.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p]
    lib.htpufs_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int]
    lib.htpufs_list.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p)),
        ctypes.POINTER(ctypes.c_int)]
    lib.htpufs_free_listing.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
    lib.htpufs_last_error.restype = ctypes.c_char_p
    lib.htpufs_last_error.argtypes = [ctypes.c_void_p]

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        port = cluster.namenode.http.port
        fs = lib.htpufs_connect(b"127.0.0.1", port)
        assert fs
        try:
            assert lib.htpufs_mkdirs(fs, b"/c/dir") == 0
            payload = os.urandom(70_000)
            assert lib.htpufs_write_file(fs, b"/c/dir/f.bin", payload,
                                         len(payload), 1) == 0, \
                lib.htpufs_last_error(fs)
            assert lib.htpufs_exists(fs, b"/c/dir/f.bin") == 1
            assert lib.htpufs_get_file_size(fs, b"/c/dir/f.bin") == \
                len(payload)
            buf = ctypes.create_string_buffer(len(payload))
            n = lib.htpufs_pread(fs, b"/c/dir/f.bin", 0, buf,
                                 len(payload))
            assert n == len(payload)
            assert buf.raw[:n] == payload
            # ranged read
            n = lib.htpufs_pread(fs, b"/c/dir/f.bin", 1000, buf, 64)
            assert n == 64 and buf.raw[:64] == payload[1000:1064]
            assert lib.htpufs_rename(fs, b"/c/dir/f.bin",
                                     b"/c/dir/g.bin") == 0
            names = ctypes.POINTER(ctypes.c_char_p)()
            cnt = ctypes.c_int()
            assert lib.htpufs_list(fs, b"/c/dir", ctypes.byref(names),
                                   ctypes.byref(cnt)) == 0
            got = {names[i].decode() for i in range(cnt.value)}
            lib.htpufs_free_listing(names, cnt.value)
            assert "g.bin" in got
            assert lib.htpufs_delete(fs, b"/c/dir", 1) == 0
            assert lib.htpufs_exists(fs, b"/c/dir/g.bin") == 0
        finally:
            lib.htpufs_disconnect(fs)


def test_htpufast_async_cpp_client_reads_real_cluster(tmp_path):
    """The libhdfs++ analog (ref: libhdfspp/lib/{rpc,reader,connection}):
    the C++ client resolves a path over REAL NameNode RPC (wirepack
    frames), streams every block from the DNs over the REAL
    datatransfer protocol with per-chunk CRC32C verification, all block
    streams concurrently under epoll — no Python in the data path."""
    import ctypes
    import os as _os

    from hadoop_tpu import native as _nat
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    lib = _nat.get_lib()
    if lib is None or not hasattr(lib, "htpufast_read_file"):
        import pytest as _pytest
        _pytest.skip("native library unavailable")
    lib.htpufast_open.restype = ctypes.c_void_p
    lib.htpufast_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_char_p]
    lib.htpufast_close.argtypes = [ctypes.c_void_p]
    lib.htpufast_error.restype = ctypes.c_char_p
    lib.htpufast_error.argtypes = [ctypes.c_void_p]
    lib.htpufast_file_length.restype = ctypes.c_int64
    lib.htpufast_file_length.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.htpufast_read_file.restype = ctypes.c_int64
    lib.htpufast_read_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_uint8),
                                       ctypes.c_int64]

    conf = fast_conf()
    conf.set("dfs.replication", "2")
    with MiniDFSCluster(num_datanodes=2, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        # multi-block file (1 MB blocks): concurrency is real
        payload = _os.urandom(3 * 1024 * 1024 + 12345)
        fs.write_all("/fast.bin", payload)
        import time as _time
        _time.sleep(0.2)  # let IBRs land everywhere

        h = lib.htpufast_open(b"127.0.0.1", cluster.namenode.port, b"root")
        try:
            n = lib.htpufast_file_length(h, b"/fast.bin")
            assert n == len(payload), lib.htpufast_error(h)
            buf = (ctypes.c_uint8 * n)()
            got = lib.htpufast_read_file(h, b"/fast.bin", buf, n)
            assert got == n, lib.htpufast_error(h)
            assert bytes(buf) == payload

            # missing file surfaces as an error, not junk
            assert lib.htpufast_file_length(h, b"/nope") == -1
            assert b"no such file" in lib.htpufast_error(h)
        finally:
            lib.htpufast_close(h)


def test_htpufast_respects_block_tokens(tmp_path):
    """On a token-enabled cluster the C++ client passes the NN-minted
    token through OP_READ_BLOCK — and reads succeed (the DN would
    refuse a token-less stream)."""
    import ctypes
    import os as _os

    from hadoop_tpu import native as _nat
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    lib = _nat.get_lib()
    if lib is None or not hasattr(lib, "htpufast_read_file"):
        import pytest as _pytest
        _pytest.skip("native library unavailable")
    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.block.access.token.enable", "true")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        payload = _os.urandom(600_000)
        fs.write_all("/tokfast.bin", payload)
        h = lib.htpufast_open(b"127.0.0.1", cluster.namenode.port, b"root")
        try:
            n = lib.htpufast_file_length(h, b"/tokfast.bin")
            buf = (ctypes.c_uint8 * n)()
            got = lib.htpufast_read_file(h, b"/tokfast.bin", buf, n)
            assert got == n, lib.htpufast_error(h)
            assert bytes(buf) == payload
        finally:
            lib.htpufast_close(h)


def test_fuse_dfs_mount_end_to_end(tmp_path):
    """fuse-dfs (ref: hadoop-hdfs-native-client fuse-dfs): mount the
    namespace through the FUSE daemon and drive it with PLAIN POSIX
    tools — ls/cat/cp/mkdir/mv/rm — against a live cluster."""
    import os as _os
    import shutil as _shutil
    import subprocess as _subprocess
    import time as _time

    import pytest as _pytest

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    binary = _os.path.join(_os.path.dirname(__file__), _os.pardir,
                           "hadoop_tpu", "native", "htpu-fuse-dfs")
    if not _os.path.exists(binary) or not _os.path.exists("/dev/fuse"):
        _pytest.skip("fuse-dfs binary or /dev/fuse unavailable")

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    mnt = str(tmp_path / "mnt")
    _os.makedirs(mnt)
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path / "c")) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fs.mkdirs("/fusedir")
        fs.write_all("/fusedir/hello.txt", b"hello from dfs\n")

        proc = _subprocess.Popen(
            [binary, "127.0.0.1", str(cluster.namenode.http.port), mnt,
             "-f"],
            stdout=_subprocess.DEVNULL, stderr=_subprocess.PIPE)
        try:
            deadline = _time.monotonic() + 10
            mounted = False
            while _time.monotonic() < deadline:
                if _os.path.isdir(f"{mnt}/fusedir"):
                    mounted = True
                    break
                if proc.poll() is not None:
                    _pytest.fail("fuse daemon died: "
                                 f"{proc.stderr.read().decode()[-400:]}")
                _time.sleep(0.2)
            assert mounted, "mount never became visible"

            # read through the kernel
            with open(f"{mnt}/fusedir/hello.txt", "rb") as f:
                assert f.read() == b"hello from dfs\n"
            assert sorted(_os.listdir(f"{mnt}/fusedir")) == ["hello.txt"]

            # write through the kernel → visible in the DFS
            with open(f"{mnt}/fusedir/new.bin", "wb") as f:
                f.write(b"x" * 70_000)
            assert fs.read_all("/fusedir/new.bin") == b"x" * 70_000

            # mkdir / rename / rm via POSIX
            _os.makedirs(f"{mnt}/fusedir/sub")
            assert fs.exists("/fusedir/sub")
            _os.rename(f"{mnt}/fusedir/new.bin", f"{mnt}/fusedir/moved.bin")
            assert fs.exists("/fusedir/moved.bin")
            _os.remove(f"{mnt}/fusedir/moved.bin")
            assert not fs.exists("/fusedir/moved.bin")
            # stat sizes agree
            st = _os.stat(f"{mnt}/fusedir/hello.txt")
            assert st.st_size == len(b"hello from dfs\n")
        finally:
            _subprocess.run(["fusermount", "-u", mnt],
                            stdout=_subprocess.DEVNULL,
                            stderr=_subprocess.DEVNULL)
            try:
                proc.wait(timeout=5)
            except _subprocess.TimeoutExpired:
                proc.kill()


def test_htpufast_verifies_with_writer_bytes_per_checksum(tmp_path):
    """Blocks written with a non-default dfs.bytes-per-checksum must
    CRC-verify in the C++ client: the read setup reply carries the
    writer's chunking and htpufast uses it instead of assuming 512
    (review finding — a fixed 512 failed every such block)."""
    import ctypes
    import os as _os

    from hadoop_tpu import native as _nat
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    lib = _nat.get_lib()
    if lib is None or not hasattr(lib, "htpufast_read_file"):
        import pytest as _pytest
        _pytest.skip("native library unavailable")
    lib.htpufast_open.restype = ctypes.c_void_p
    lib.htpufast_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_char_p]
    lib.htpufast_close.argtypes = [ctypes.c_void_p]
    lib.htpufast_error.restype = ctypes.c_char_p
    lib.htpufast_error.argtypes = [ctypes.c_void_p]
    lib.htpufast_file_length.restype = ctypes.c_int64
    lib.htpufast_file_length.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.htpufast_read_file.restype = ctypes.c_int64
    lib.htpufast_read_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_uint8),
                                       ctypes.c_int64]

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.bytes-per-checksum", "4096")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        payload = _os.urandom(200_123)  # partial tail chunk at 4096 too
        fs.write_all("/bpc4k.bin", payload)
        import time as _time
        _time.sleep(0.2)

        h = lib.htpufast_open(b"127.0.0.1", cluster.namenode.port, b"root")
        try:
            n = lib.htpufast_file_length(h, b"/bpc4k.bin")
            assert n == len(payload), lib.htpufast_error(h)
            buf = (ctypes.c_uint8 * n)()
            got = lib.htpufast_read_file(h, b"/bpc4k.bin", buf, n)
            assert got == n, lib.htpufast_error(h)
            assert bytes(buf) == payload
        finally:
            lib.htpufast_close(h)
