"""Native batch collector / merger — parity with the Python engine.

Strategy mirrors the reference's nativetask tests (ref:
hadoop-mapreduce-client-nativetask/src/test — kv/combiner/compress tests
compare native output against the Java collector's): every native result
is checked against the pure-Python path on the same records.
"""

import random
import struct

import pytest

from hadoop_tpu import native as nat
from hadoop_tpu.mapreduce import batch, ifile
from hadoop_tpu.mapreduce.api import Counters, Partitioner
from hadoop_tpu.mapreduce.sorter import MapOutputCollector

pytestmark = pytest.mark.skipif(not nat.available(),
                                reason="native library not built")


def _records(n, seed=7):
    rng = random.Random(seed)
    recs = []
    for i in range(n):
        k = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 20)))
        v = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 40)))
        recs.append((k, v))
    return recs


def _read_all(path, index, nparts):
    out = {}
    for p in range(nparts):
        out[p] = ifile.read_partition(path, index, p)
    return out


def test_native_vs_python_collector_hash(tmp_path):
    recs = _records(5000)
    packed = batch.pack_records(recs)

    cn = MapOutputCollector(4, Partitioner().partition,
                            str(tmp_path / "n"), Counters(),
                            partitioner=Partitioner())
    assert cn._native is not None
    cn.collect_batch(packed)
    idx_n = cn.close(str(tmp_path / "n.out"))

    cp = MapOutputCollector(4, Partitioner().partition,
                            str(tmp_path / "p"), Counters())
    assert cp._native is None
    for k, v in recs:
        cp.collect(k, v)
    idx_p = cp.close(str(tmp_path / "p.out"))

    assert _read_all(str(tmp_path / "n.out"), idx_n, 4) == \
        _read_all(str(tmp_path / "p.out"), idx_p, 4)


def test_native_collector_spills(tmp_path):
    recs = _records(3000, seed=3)
    c = MapOutputCollector(3, Partitioner().partition, str(tmp_path / "s"),
                           Counters(), sort_mb=0.01,
                           partitioner=Partitioner())
    assert c._native is not None
    for i in range(0, len(recs), 100):
        c.collect_batch(batch.pack_records(recs[i:i + 100]))
    idx = c.close(str(tmp_path / "s.out"))
    got = _read_all(str(tmp_path / "s.out"), idx, 3)
    assert sum(len(v) for v in got.values()) == 3000
    p = Partitioner()
    for part, rs in got.items():
        keys = [k for k, _ in rs]
        assert keys == sorted(keys)  # equal keys stay stable by spill order
        assert all(p.partition(k, 3) == part for k in keys)


def test_custom_partitioner_stays_python(tmp_path):
    class Custom(Partitioner):
        def partition(self, key, n):
            return 0
    c = MapOutputCollector(2, Custom().partition, str(tmp_path / "c"),
                           Counters(), partitioner=Custom())
    assert c._native is None


def test_per_record_collect_via_native(tmp_path):
    recs = _records(500, seed=11)
    c = MapOutputCollector(2, Partitioner().partition, str(tmp_path / "r"),
                           Counters(), partitioner=Partitioner())
    for k, v in recs:
        c.collect(k, v)
    idx = c.close(str(tmp_path / "r.out"))
    got = _read_all(str(tmp_path / "r.out"), idx, 2)
    assert sum(len(v) for v in got.values()) == 500


def test_merge_segments_matches_heapq():
    recs = _records(2000, seed=5)
    runs = [sorted(recs[i::4]) for i in range(4)]
    segs = [ifile.encode_records(r) for r in runs]
    merged = nat.merge_segments(segs)
    got = list(batch.iter_records(merged))
    import heapq
    want = list(heapq.merge(*runs, key=lambda kv: kv[0]))
    assert got == want


def test_merge_segments_bad_crc():
    seg = bytearray(ifile.encode_records([(b"k", b"v")]))
    seg[-1] ^= 0xFF
    with pytest.raises(IOError):
        nat.merge_segments([bytes(seg)])


def test_pack_unpack_fixed_roundtrip():
    raw = bytes(range(256)) * 100  # 25600 bytes of 10+90 rows
    packed = batch.pack_fixed(raw[:25600], 10, 90)
    assert batch.fast_count(packed) == 256
    assert batch.unpack_fixed(packed, 10, 90) == raw[:25600]
    assert batch.probe_fixed(packed) == (10, 90)
    recs = list(batch.iter_records(packed))
    assert len(recs) == 256
    assert recs[0] == (raw[:10], raw[10:100])


def test_unpack_fixed_rejects_mixed():
    # two records whose sizes coincide in total length but differ per-record
    packed = batch.pack_records([(b"aa", b"bbbb"), (b"aaa", b"bbb")])
    assert batch.unpack_fixed(packed, 2, 4) is None


def test_range_partitioner_native_parity(tmp_path):
    from hadoop_tpu.examples.terasort import TotalOrderPartitioner
    tp = TotalOrderPartitioner()
    tp._cuts = [struct.pack(">I", 100), struct.pack(">I", 2000)]
    recs = [(struct.pack(">I", i * 7 % 3000), b"x") for i in range(500)]
    c = MapOutputCollector(3, tp.partition, str(tmp_path / "t"),
                           Counters(), partitioner=tp)
    assert c._native is not None
    c.collect_batch(batch.pack_records(recs))
    idx = c.close(str(tmp_path / "t.out"))
    got = _read_all(str(tmp_path / "t.out"), idx, 3)
    for part, rs in got.items():
        for k, _ in rs:
            assert tp.partition(k, 3) == part
    assert sum(len(v) for v in got.values()) == 500
