"""NFS gateway: ONC RPC plumbing + NFSv3 procedures over a live DFS.

Mirrors the reference tests (ref: hadoop-hdfs-nfs TestRpcProgramNfs3.java
drives the program with hand-built XDR; TestPortmap.java checks the
embedded portmapper) — every call here crosses a real TCP socket.
"""

import os

import pytest

from hadoop_tpu.nfs import NfsGateway, SimpleRpcClient
from hadoop_tpu.nfs.oncrpc import IPPROTO_TCP
from hadoop_tpu.nfs.xdr import XdrEncoder
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

NFS_PROGRAM = 100003
MOUNT_PROGRAM = 100005
PORTMAP_PROGRAM = 100000


@pytest.fixture()
def gateway(tmp_path):
    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        gw = NfsGateway(cluster.get_filesystem(), export="/")
        gw.start()
        try:
            yield gw
        finally:
            gw.stop()


def _mount(gw):
    c = SimpleRpcClient("127.0.0.1", gw.port, MOUNT_PROGRAM, 3)
    x = c.call(1, XdrEncoder().string("/").getvalue())
    assert x.u32() == 0
    fh = x.opaque()
    c.close()
    return fh


def test_portmap_and_mount(gateway):
    pm = SimpleRpcClient("127.0.0.1", gateway.port, PORTMAP_PROGRAM, 2)
    args = XdrEncoder().u32(NFS_PROGRAM).u32(3).u32(IPPROTO_TCP).u32(0)
    x = pm.call(3, args.getvalue())       # GETPORT
    assert x.u32() == gateway.port
    pm.close()
    fh = _mount(gateway)
    assert len(fh) == 8


def test_nfs3_file_lifecycle(gateway):
    root = _mount(gateway)
    nfs = SimpleRpcClient("127.0.0.1", gateway.port, NFS_PROGRAM, 3)

    # MKDIR /data
    args = XdrEncoder().opaque(root).string("data")
    args.boolean(False).boolean(False).boolean(False).boolean(False)
    args.u32(0).u32(0)    # don't-set atime/mtime
    x = nfs.call(9, args.getvalue())
    assert x.u32() == 0
    assert x.boolean()
    dir_fh = x.opaque()

    # CREATE /data/hello (UNCHECKED + empty sattr)
    args = XdrEncoder().opaque(dir_fh).string("hello").u32(0)
    x = nfs.call(8, args.getvalue())
    assert x.u32() == 0
    assert x.boolean()
    file_fh = x.opaque()

    # WRITE: two in-order chunks plus one retransmit
    payload = os.urandom(100_000)
    half = len(payload) // 2
    for off, chunk in ((0, payload[:half]), (half, payload[half:]),
                       (0, payload[:half])):   # retransmit of chunk 1
        args = XdrEncoder().opaque(file_fh).u64(off)
        args.u32(len(chunk)).u32(2).opaque(chunk)   # FILE_SYNC
        x = nfs.call(7, args.getvalue())
        assert x.u32() == 0, f"WRITE at {off} failed"

    # COMMIT finalizes the stream
    args = XdrEncoder().opaque(file_fh).u64(0).u32(0)
    x = nfs.call(21, args.getvalue())
    assert x.u32() == 0

    # GETATTR reflects the final size
    x = nfs.call(1, XdrEncoder().opaque(file_fh).getvalue())
    assert x.u32() == 0
    assert x.u32() == 1          # NF3REG
    x.u32(); x.u32(); x.u32(); x.u32()   # mode nlink uid gid
    assert x.u64() == len(payload)

    # READ it back in two chunks through the gateway
    got = b""
    for off in (0, half):
        args = XdrEncoder().opaque(file_fh).u64(off).u32(half)
        x = nfs.call(6, args.getvalue())
        assert x.u32() == 0
        x.boolean() and x.opaque_fixed(84)   # skip post_op_attr fattr3
        n = x.u32()
        x.boolean()      # eof
        got += x.opaque()[:n]
    assert got == payload

    # LOOKUP + READDIRPLUS see it
    args = XdrEncoder().opaque(dir_fh).string("hello")
    x = nfs.call(3, args.getvalue())
    assert x.u32() == 0
    args = XdrEncoder().opaque(dir_fh).u64(0).opaque_fixed(b"\0" * 8)
    args.u32(4096).u32(1 << 20)
    x = nfs.call(17, args.getvalue())
    assert x.u32() == 0

    # RENAME and REMOVE
    args = XdrEncoder().opaque(dir_fh).string("hello")
    args.opaque(dir_fh).string("world")
    x = nfs.call(14, args.getvalue())
    assert x.u32() == 0
    args = XdrEncoder().opaque(dir_fh).string("world")
    x = nfs.call(12, args.getvalue())
    assert x.u32() == 0
    args = XdrEncoder().opaque(dir_fh).string("world")
    x = nfs.call(3, args.getvalue())
    assert x.u32() == 2          # NFS3ERR_NOENT
    nfs.close()


def test_out_of_order_writes_reassembled(gateway):
    """The OpenFileCtx parks ahead-of-cursor writes until the gap fills
    (ref: OpenFileCtx.nonSequentialWriteInMemory)."""
    root = _mount(gateway)
    nfs = SimpleRpcClient("127.0.0.1", gateway.port, NFS_PROGRAM, 3)
    args = XdrEncoder().opaque(root).string("ooo").u32(0)
    x = nfs.call(8, args.getvalue())
    assert x.u32() == 0
    x.boolean()
    fh = x.opaque()

    a, b, c = os.urandom(1000), os.urandom(1000), os.urandom(1000)
    # Send middle chunk first, then the tail, then the head.
    for off, chunk in ((1000, b), (2000, c), (0, a)):
        args = XdrEncoder().opaque(fh).u64(off)
        args.u32(len(chunk)).u32(2).opaque(chunk)
        x = nfs.call(7, args.getvalue())
        assert x.u32() == 0
    args = XdrEncoder().opaque(fh).u64(0).u32(0)
    assert nfs.call(21, args.getvalue()).u32() == 0   # COMMIT

    args = XdrEncoder().opaque(fh).u64(0).u32(3000)
    x = nfs.call(6, args.getvalue())
    assert x.u32() == 0
    x.boolean() and x.opaque_fixed(84)
    n = x.u32()
    x.boolean()
    assert x.opaque()[:n] == a + b + c
    nfs.close()


def test_write_retransmit_with_tail(gateway):
    """A Linux client re-sending a whole dirty page whose tail extends
    past the gateway cursor must not lose the tail (ref:
    OpenFileCtx.processOverWrite rejects imperfect overwrites; here the
    unseen suffix is appended instead of silently dropped)."""
    root = _mount(gateway)
    nfs = SimpleRpcClient("127.0.0.1", gateway.port, NFS_PROGRAM, 3)
    args = XdrEncoder().opaque(root).string("page").u32(0)
    x = nfs.call(8, args.getvalue())
    assert x.u32() == 0
    assert x.boolean()
    fh = x.opaque()

    page = os.urandom(4096)
    # write first 2K, then retransmit the whole 4K page at offset 0
    for off, chunk in ((0, page[:2048]), (0, page)):
        args = XdrEncoder().opaque(fh).u64(off)
        args.u32(len(chunk)).u32(0).opaque(chunk)   # UNSTABLE
        x = nfs.call(7, args.getvalue())
        assert x.u32() == 0

    args = XdrEncoder().opaque(fh).u64(0).u32(0)
    assert nfs.call(21, args.getvalue()).u32() == 0   # COMMIT

    x = nfs.call(1, XdrEncoder().opaque(fh).getvalue())
    assert x.u32() == 0
    assert x.u32() == 1
    x.u32(); x.u32(); x.u32(); x.u32()
    assert x.u64() == 4096          # tail bytes 2048-4096 not dropped
    nfs.close()


def test_commit_mid_transfer_keeps_stream_writable(gateway):
    """COMMIT durability-syncs but must NOT close the write stream:
    Linux clients fsync mid-copy and keep writing (review finding —
    the close made every later WRITE fail and truncated the file)."""
    root_fh = _mount(gateway)
    fs = gateway.nfs3.fs
    nfs = SimpleRpcClient("127.0.0.1", gateway.port, NFS_PROGRAM, 3)

    def call(proc, args):
        return nfs.call(proc, args.getvalue())

    # CREATE /c.bin
    args = XdrEncoder().opaque(root_fh).string("c.bin").u32(0)
    x = call(8, args)
    assert x.u32() == 0
    assert x.boolean()
    fh = x.opaque()

    half1, half2 = b"A" * 1000, b"B" * 1000
    args = XdrEncoder().opaque(fh).u64(0).u32(len(half1)).u32(0)
    args.opaque(half1)
    assert call(7, args).u32() == 0
    # COMMIT mid-transfer
    args = XdrEncoder().opaque(fh).u64(0).u32(0)
    assert call(21, args).u32() == 0
    # ...and the client keeps writing at the next offset
    args = XdrEncoder().opaque(fh).u64(len(half1)).u32(len(half2)).u32(0)
    args.opaque(half2)
    assert call(7, args).u32() == 0, "WRITE after COMMIT must succeed"
    # a reader from another client finalizes (close-to-open): the
    # gateway READ closes the write context and serves the bytes
    got = b""
    for off in (0, 1000):
        args = XdrEncoder().opaque(fh).u64(off).u32(1000)
        x = call(6, args)
        assert x.u32() == 0
        x.boolean() and x.opaque_fixed(84)
        n = x.u32()
        x.boolean()
        got += x.opaque()[:n]
    assert got == half1 + half2
    assert fs.read_all("/c.bin") == half1 + half2  # durable in the DFS
    nfs.close()


def test_readdir_honors_reply_budget(gateway):
    """READDIRPLUS pages by the client's maxcount instead of encoding
    the whole directory into one oversized reply (review finding)."""
    root_fh = _mount(gateway)
    fs = gateway.nfs3.fs
    nfs = SimpleRpcClient("127.0.0.1", gateway.port, NFS_PROGRAM, 3)

    fs.mkdirs("/big")
    for i in range(120):
        fs.write_all(f"/big/f{i:04d}", b"x")

    def call(proc, args):
        return nfs.call(proc, args.getvalue())

    # resolve /big
    x = call(3, XdrEncoder().opaque(root_fh).string("big"))
    assert x.u32() == 0
    big_fh = x.opaque()

    names, cookie, rounds = [], 0, 0
    while True:
        rounds += 1
        args = XdrEncoder().opaque(big_fh).u64(cookie)
        args.opaque_fixed(b"\0" * 8).u32(1024).u32(2048)  # small budget
        x = call(17, args)
        assert x.u32() == 0
        x.boolean() and x.opaque_fixed(84)
        x.opaque_fixed(8)  # cookieverf
        while x.boolean():
            x.u64()
            names.append(x.string())
            cookie = x.u64()
            x.boolean() and x.opaque_fixed(84)
            x.boolean() and x.opaque()
        if x.boolean():   # eof
            break
        assert rounds < 200
    assert rounds > 1, "a 120-entry dir must take multiple rounds at 2KB"
    assert len(names) == 120
    nfs.close()


def test_nfs_executes_as_the_auth_sys_caller(gateway):
    """The gateway doAs-es the AUTH_SYS credential's uid, not its own
    process user (ref: the reference NFS gateway's IdUserGroup uid
    mapping): an unmapped non-root uid cannot read a 0600 root-owned
    file or create in a root-owned 0755 dir through the NFS door."""
    fs = gateway.nfs3.fs
    fs.mkdirs("/nfssec")
    fs.write_all("/nfssec/secret.bin", b"top")
    fs.set_permission("/nfssec/secret.bin", 0o600)
    fs.set_permission("/nfssec", 0o755)

    root = _mount(gateway)
    nfs = SimpleRpcClient("127.0.0.1", gateway.port, NFS_PROGRAM, 3)
    # LOOKUP the dir + file as uid 0 (root → superuser, still allowed)
    x = nfs.call(3, XdrEncoder().opaque(root).string("nfssec").getvalue())
    assert x.u32() == 0
    dir_fh = x.opaque()
    x = nfs.call(3, XdrEncoder().opaque(dir_fh).string("secret.bin")
                 .getvalue())
    assert x.u32() == 0
    file_fh = x.opaque()

    # READ as unmapped uid 54321 → denied (nonzero NFS status)
    args = XdrEncoder().opaque(file_fh).u64(0).u32(16)
    x = nfs.call(6, args.getvalue(), uid=54321)
    assert x.u32() != 0, "0600 file readable by arbitrary NFS uid"
    # READ as root works
    x = nfs.call(6, args.getvalue())
    assert x.u32() == 0

    # CREATE in the root-owned 755 dir as uid 54321 → denied
    args = XdrEncoder().opaque(dir_fh).string("intruder").u32(0)
    x = nfs.call(8, args.getvalue(), uid=54321)
    assert x.u32() != 0, "root-owned dir writable by arbitrary NFS uid"


def test_open_write_context_is_owner_bound(gateway):
    """An in-flight write stream belongs to the principal that opened
    it: a different AUTH_SYS uid writing at the cursor must get
    NFS3ERR_ACCES, not have its bytes land in the other user's file
    through the already-open stream (review finding)."""
    root = _mount(gateway)
    nfs = SimpleRpcClient("127.0.0.1", gateway.port, NFS_PROGRAM, 3)
    x = nfs.call(9, XdrEncoder().opaque(root).string("wctx")
                 .boolean(False).boolean(False).boolean(False)
                 .boolean(False).u32(0).u32(0).getvalue())
    assert x.u32() == 0 and x.boolean()
    dir_fh = x.opaque()
    x = nfs.call(8, XdrEncoder().opaque(dir_fh).string("f").u32(0)
                 .getvalue())
    assert x.u32() == 0 and x.boolean()
    fh = x.opaque()
    # owner writes the first chunk
    w = XdrEncoder().opaque(fh).u64(0).u32(4).u32(2).opaque(b"mine")
    assert nfs.call(7, w.getvalue()).u32() == 0
    # a different uid tries to append at the cursor → ACCES (13)
    w2 = XdrEncoder().opaque(fh).u64(4).u32(4).u32(2).opaque(b"evil")
    assert nfs.call(7, w2.getvalue(), uid=54321).u32() == 13
    # and COMMIT by the intruder is refused too
    c = XdrEncoder().opaque(fh).u64(0).u32(0)
    assert nfs.call(21, c.getvalue(), uid=54321).u32() == 13


def test_access_group_bits_use_gateway_groups_config(tmp_path):
    """ACCESS resolves the caller's groups through the gateway's single
    Groups(conf) instance, so the cluster's configured static mapping
    applies (ADVICE round 5: a fresh conf-less Groups() per call lost
    the static mapping and defeated the TTL cache)."""
    conf = fast_conf()
    conf.set("dfs.replication", "1")
    # the unmapped-uid principal (uid-54321) belongs to a static group
    conf.set("hadoop.security.group.mapping.static.mapping",
             "uid-54321=nfsreaders")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fs.write_all("/groupread.bin", b"data")
        fs.set_permission("/groupread.bin", 0o640)
        # owner is someone else entirely: only the GROUP bits can grant
        fs.set_owner("/groupread.bin", "alice", "nfsreaders")
        gw = NfsGateway(fs, export="/", conf=conf)
        gw.start()
        try:
            # every ACCESS must consult the gateway's single Groups
            # instance (not construct a fresh one)
            consulted = []
            orig_groups_for = gw.nfs3.groups.groups_for
            gw.nfs3.groups.groups_for = \
                lambda u: (consulted.append(u) or orig_groups_for(u))
            root = _mount(gw)
            nfs = SimpleRpcClient("127.0.0.1", gw.port, NFS_PROGRAM, 3)
            x = nfs.call(3, XdrEncoder().opaque(root)
                         .string("groupread.bin").getvalue())
            assert x.u32() == 0
            fh = x.opaque()
            # ACCESS as unmapped uid 54321 -> "uid-54321" -> static
            # group nfsreaders -> group bits (r--) grant READ
            x = nfs.call(4, XdrEncoder().opaque(fh).u32(0x3f).getvalue(),
                         uid=54321)
            assert x.u32() == 0
            x.boolean() and x.opaque_fixed(84)   # post_op_attr
            granted = x.u32()
            assert granted & 0x01, \
                "static-mapped group bits must grant ACC_READ"
            assert not granted & 0x04, "group r-- must not grant MODIFY"
            assert consulted == ["uid-54321"], \
                "ACCESS bypassed the gateway's Groups instance"
            nfs.close()
        finally:
            gw.stop()


def test_read_auth_open_ioerror_maps_to_nfs3err_io(gateway):
    """A transient IOError from READ's eager authorization open of an
    in-flight file must come back as NFS3ERR_IO, not escape as a
    generic RPC system error (ADVICE round 5)."""
    root = _mount(gateway)
    nfs = SimpleRpcClient("127.0.0.1", gateway.port, NFS_PROGRAM, 3)
    x = nfs.call(8, XdrEncoder().opaque(root).string("inflight").u32(0)
                 .getvalue())
    assert x.u32() == 0 and x.boolean()
    fh = x.opaque()
    w = XdrEncoder().opaque(fh).u64(0).u32(4).u32(2).opaque(b"data")
    assert nfs.call(7, w.getvalue()).u32() == 0

    orig_open = gateway.nfs3.fs.open
    def flaky_open(path, *a, **kw):
        raise IOError("transient NN/DN failure")
    gateway.nfs3.fs.open = flaky_open
    try:
        r = XdrEncoder().opaque(fh).u64(0).u32(4)
        x = nfs.call(6, r.getvalue())
        assert x.u32() == 5, "expected NFS3ERR_IO resfail"
    finally:
        gateway.nfs3.fs.open = orig_open
    # the stream was NOT finalized by the failed read; the owner can
    # still read through the recovered fs (close-to-open finalize)
    x = nfs.call(6, XdrEncoder().opaque(fh).u64(0).u32(4).getvalue())
    assert x.u32() == 0
    x.boolean() and x.opaque_fixed(84)
    n = x.u32()
    x.boolean()
    assert x.opaque()[:n] == b"data"
    nfs.close()
