"""Object-store connector: FS semantics, streams, committer, distcp.

Mirrors the reference's hadoop-aws test strategy (ref: ITestS3A*
contract tests driven against a store endpoint; ITestCommitOperations
for the magic committer; TestDistCpWithS3 for cross-store copies) —
every test here crosses real HTTP sockets to the in-process fake
store (testing/fakestore.py).
"""

import os

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.fs import FileSystem
from hadoop_tpu.fs.objectstore import (ObjectStoreCommitter,
                                       ObjectStoreFileSystem)
from hadoop_tpu.testing.fakestore import FakeObjectStore


@pytest.fixture()
def store():
    with FakeObjectStore() as s:
        yield s


@pytest.fixture()
def fs(store):
    f = FileSystem.get(f"htps://{store.endpoint}/bkt", Configuration())
    assert isinstance(f, ObjectStoreFileSystem)
    yield f
    f.close()


def test_write_read_roundtrip(fs):
    data = os.urandom(100_000)
    fs.write_all("/bkt/dir/a.bin", data)
    assert fs.read_all("/bkt/dir/a.bin") == data
    st = fs.get_file_status("/bkt/dir/a.bin")
    assert not st.is_dir and st.length == len(data)


def test_object_invisible_until_close(fs):
    out = fs.create("/bkt/late.bin")
    out.write(b"x" * 1000)
    assert not fs.exists("/bkt/late.bin")  # ref: S3A visibility-at-close
    out.close()
    assert fs.exists("/bkt/late.bin")


def test_multipart_write(store, fs):
    fs.part_size = 4096  # force multiple parts
    data = os.urandom(3 * 4096 + 123)
    fs.write_all("/bkt/mp.bin", data)
    assert fs.read_all("/bkt/mp.bin") == data
    assert store.pending_uploads() == 0  # completed, not leaked


def test_range_reads_and_seek(fs):
    data = bytes(range(256)) * 1000
    fs.write_all("/bkt/seek.bin", data)
    with fs.open("/bkt/seek.bin") as f:
        assert f.read(10) == data[:10]
        f.seek(100_000)
        assert f.read(16) == data[100_000:100_016]
        f.seek(-8, 2)
        assert f.read() == data[-8:]
        assert f.pread(5000, 64) == data[5000:5064]


def test_listing_directories_and_pagination(fs):
    fs.list_page = 7  # force pagination
    for i in range(25):
        fs.write_all(f"/bkt/pag/f{i:03d}", b"x")
    fs.mkdirs("/bkt/pag/sub")
    fs.write_all("/bkt/pag/sub/inner", b"y")
    sts = fs.list_status("/bkt/pag")
    names = [s.path.rsplit("/", 1)[-1] for s in sts]
    assert len([s for s in sts if not s.is_dir]) == 25
    subs = [s for s in sts if s.is_dir]
    assert len(subs) == 1 and subs[0].path.endswith("/pag/sub")
    assert "f000" in names and "f024" in names
    # implicit directory (no marker) is still a directory
    fs.write_all("/bkt/imp/deep/file", b"z")
    assert fs.get_file_status("/bkt/imp").is_dir
    assert fs.get_file_status("/bkt/imp/deep").is_dir


def test_mkdirs_delete(fs):
    fs.mkdirs("/bkt/d1/d2")
    assert fs.get_file_status("/bkt/d1/d2").is_dir
    fs.write_all("/bkt/d1/d2/f", b"data")
    with pytest.raises(OSError):
        fs.delete("/bkt/d1/d2", recursive=False)
    assert fs.delete("/bkt/d1/d2", recursive=True)
    assert not fs.exists("/bkt/d1/d2/f")
    assert not fs.delete("/bkt/never-existed")


def test_rename_file_and_tree(fs):
    fs.write_all("/bkt/r/a", b"A")
    fs.write_all("/bkt/r/sub/b", b"B")
    assert fs.rename("/bkt/r", "/bkt/moved")
    assert fs.read_all("/bkt/moved/a") == b"A"
    assert fs.read_all("/bkt/moved/sub/b") == b"B"
    assert not fs.exists("/bkt/r/a")
    # file rename into an existing directory
    fs.write_all("/bkt/single", b"S")
    fs.mkdirs("/bkt/into")
    assert fs.rename("/bkt/single", "/bkt/into")
    assert fs.read_all("/bkt/into/single") == b"S"


def test_committer_atomic_visibility(store, fs):
    """Task output is invisible until job commit, then appears atomically
    (ref: the magic committer's deferred multipart completion)."""
    fs.part_size = 4096
    committer = ObjectStoreCommitter(fs, "/bkt/out")
    writers = []
    for t in range(3):
        w = committer.task_writer(f"task_{t}", f"part-{t:05d}")
        w.write(os.urandom(10_000))
        writers.append(w)
        committer.commit_task(f"task_{t}", [w])
    # data uploaded but NOT visible; uploads parked
    assert not fs.exists("/bkt/out/part-00000")
    assert store.pending_uploads() == 3
    n = committer.commit_job()
    assert n == 3
    for t in range(3):
        assert fs.get_file_status(f"/bkt/out/part-{t:05d}").length \
            == 10_000
    assert fs.exists("/bkt/out/_SUCCESS")
    assert store.pending_uploads() == 0


def test_committer_abort_leaves_nothing(store, fs):
    committer = ObjectStoreCommitter(fs, "/bkt/ab")
    w = committer.task_writer("t0", "part-00000")
    w.write(b"never seen")
    committer.commit_task("t0", [w])
    committer.abort_job()
    assert store.pending_uploads() == 0
    assert not fs.exists("/bkt/ab/part-00000")


def test_distcp_dfs_to_store_and_back(store, tmp_path):
    """distcp DFS↔store both directions over a live MR cluster (ref:
    using hadoop-distcp against s3a:// targets)."""
    from hadoop_tpu.testing.minicluster import MiniMRYarnCluster
    from hadoop_tpu.tools.distcp import distcp

    with MiniMRYarnCluster(num_nodes=1,
                           base_dir=str(tmp_path)) as cluster:
        dfs = cluster.get_filesystem()
        payloads = {f"/src/f{i}": os.urandom(20_000 + i) for i in range(3)}
        for p, data in payloads.items():
            dfs.write_all(p, data)
        store_uri = f"htps://{store.endpoint}/bkt"

        counters = distcp(cluster.rm_addr, cluster.default_fs,
                          f"{cluster.default_fs}/src",
                          f"{store_uri}/mirror")
        sfs = FileSystem.get(store_uri, Configuration())
        for p, data in payloads.items():
            name = p.rsplit("/", 1)[-1]
            assert sfs.read_all(f"/bkt/mirror/{name}") == data

        # and back again into a fresh DFS directory
        distcp(cluster.rm_addr, cluster.default_fs,
               f"{store_uri}/mirror", f"{cluster.default_fs}/back")
        for p, data in payloads.items():
            name = p.rsplit("/", 1)[-1]
            assert dfs.read_all(f"/back/{name}") == data


def test_trailing_slash_is_directory(fs):
    fs.mkdirs("/bkt/ts")
    fs.write_all("/bkt/ts/child", b"c")
    st = fs.get_file_status("/bkt/ts/")
    assert st.is_dir
    with pytest.raises(IsADirectoryError):
        fs.open("/bkt/ts/")
    with pytest.raises(OSError):
        fs.delete("/bkt/ts/", recursive=False)
    assert fs.exists("/bkt/ts/child")
