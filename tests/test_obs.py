"""Fleet doctor: cross-daemon trace assembly, median/MAD slow-node
detection (with the NN placement loop), histogram exemplars resolving
through the doctor, and the satellite servlets (/ws/v1/stacks JSON,
/ws/v1/top, NN audit log).

Determinism rule (the ISSUE's hard constraint): detection decisions run
on INJECTED latencies only — tests feed the per-peer trackers synthetic
samples and assert on flag sets, never on wall-clock elapsed time.
"""

import http.client
import json
import logging
import re
import threading
import time

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.obs.assemble import (Endpoint, FleetTraceStore,
                                     assemble_tree, parse_endpoint_list)
from hadoop_tpu.obs.detect import (RollingStat, SlowNodeDetector,
                                   mad_outliers, median)
from hadoop_tpu.obs.peers import PeerLatencyTracker
from hadoop_tpu.obs import top as obs_top
from hadoop_tpu.tracing.collector import span_collector
from hadoop_tpu.tracing.tracer import global_tracer


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10.0)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get_json(port, path):
    status, body = _get(port, path)
    assert status == 200, body
    return json.loads(body)


# ------------------------------------------------------- detection math


def test_median_and_mad_outliers():
    assert median([3.0, 1.0, 2.0]) == 2.0
    assert median([1.0, 2.0, 3.0, 4.0]) == 2.5
    vals = {"a": 0.010, "b": 0.012, "c": 0.011, "d": 0.150}
    flagged = mad_outliers(vals)
    assert list(flagged) == ["d"]
    ev = flagged["d"]
    assert ev["value"] == 0.15 and ev["peers"] == 4
    assert ev["threshold"] < 0.15


def test_outliers_need_peers_and_spread():
    # below min_peers: nobody can be an outlier among too few
    assert mad_outliers({"a": 0.01, "b": 9.9}, min_peers=3) == {}
    # a tight healthy fleet (microseconds of spread, all below the
    # absolute floor) flags nobody
    tight = {f"n{i}": 0.0010 + i * 1e-6 for i in range(5)}
    assert mad_outliers(tight, abs_floor=0.002) == {}
    # ratio guard: statistically "outlying" but only 10% slower
    near = {"a": 1.000, "b": 1.000, "c": 1.000, "d": 1.100}
    assert mad_outliers(near, ratio=1.5) == {}


def test_detector_hysteresis_flags_and_recovers():
    det = SlowNodeDetector(history=5, min_windows=3, min_peers=3)
    slow = {"a": 0.01, "b": 0.011, "c": 0.012, "sick": 0.2}
    clean = {"a": 0.01, "b": 0.011, "c": 0.012, "sick": 0.011}
    det.observe(slow)
    det.observe(slow)
    assert det.report() == {}          # 2 of 3 required windows
    det.observe(slow)
    rep = det.report()
    assert list(rep) == ["sick"]
    assert rep["sick"]["windows_flagged"] == 3
    # recovery: clean windows push the slow ones out of history
    for _ in range(5):
        det.observe(clean)
    assert det.report() == {}


def test_rolling_stat_window_bound():
    rs = RollingStat(window=4)
    for v in (1.0, 2.0, 3.0, 4.0, 5.0, 6.0):
        rs.record(v)
    s = rs.summary()
    assert s["n"] == 4 and s["mean"] == pytest.approx(4.5)
    assert s["median"] == pytest.approx(4.5)


def test_peer_tracker_bounds_and_self_stats():
    tr = PeerLatencyTracker(window=8, max_peers=3)
    for i in range(5):          # 5 peers through a 3-peer budget
        tr.record(f"peer{i}", 0.01 * (i + 1))
    assert len(tr.summary()) == 3
    tr.record_self_read(0.002)
    tr.record_self_write(0.004)
    rep = tr.to_report("node-x")
    assert rep["node"] == "node-x"
    assert rep["self"]["read"]["n"] == 1
    assert rep["self"]["write"]["mean"] == pytest.approx(0.004)
    # self stats never leak into the peer map
    assert all(not p.startswith("__") for p in rep["peers"])


def test_peer_tracker_summary_safe_under_concurrent_records():
    """A doctor scrape (/ws/v1/peers -> summary) racing a responder
    thread's record() must never die with deque-mutated-during-
    iteration — summaries read under the tracker lock."""
    tr = PeerLatencyTracker(window=64)
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            tr.record(f"p{i % 8}", 0.001)
            tr.record_self_read(0.001)
            i += 1

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        for _ in range(300):
            tr.summary()
            tr.self_summary()
            tr.to_report("n")
    finally:
        stop.set()
        t.join(5.0)


def test_peer_tracker_never_evicts_self_stats():
    """A read-quiet node forwarding writes to many peers must keep its
    own service-time signal: the reserved self entries are not eviction
    candidates even as the idle-longest members."""
    tr = PeerLatencyTracker(window=8, max_peers=4)
    tr.record_self_read(0.002)       # oldest entries by last_at
    tr.record_self_write(0.003)
    for i in range(10):              # churn well past the budget
        tr.record(f"peer{i}", 0.01)
    rep = tr.to_report("n")
    assert rep["self"]["read"] is not None
    assert rep["self"]["write"] is not None
    assert len(rep["peers"]) <= 4


# ------------------------------------------------------- tree assembly


def _span(tid, sid, parent, start, end, name, daemon="d"):
    return {"trace_id": tid, "span_id": sid, "parent_id": parent,
            "start": start, "end": end, "name": name, "daemon": daemon}


def test_assemble_tree_nesting_self_time_and_orphans():
    spans = [
        _span(7, 1, None, 0.0, 1.0, "root", "client"),
        _span(7, 2, 1, 0.1, 0.9, "nn.op", "nn"),
        _span(7, 3, 2, 0.2, 0.8, "dn.read", "dn"),
        # parent 99 never arrived (its daemon died): adopted as a root
        _span(7, 4, 99, 0.3, 0.4, "orphan", "gone"),
    ]
    t = assemble_tree(7, spans)
    assert t["num_spans"] == 4 and t["roots"] == 2
    root = t["tree"][0]
    assert root["name"] == "root"
    assert root["children"][0]["name"] == "nn.op"
    assert root["children"][0]["children"][0]["name"] == "dn.read"
    # self time: root 1.0-0.8, nn 0.8-0.6, dn 0.6 — dn dominates
    crit = t["critical_path"]
    assert crit[0]["daemon"] == "dn"
    assert crit[0]["self_ms"] == pytest.approx(600.0)
    assert t["trace_id_hex"] == f"{7:016x}"


def test_parse_endpoint_list():
    eps = parse_endpoint_list("nn=1.2.3.4:80, :9090 ,dn=x:1")
    assert eps == [("nn", "1.2.3.4", 80), (":9090", "127.0.0.1", 9090),
                   ("dn", "x", 1)]


def test_trace_id_candidates_shared_and_consistent():
    """ONE reading of user-supplied trace ids, shared by the per-daemon
    /ws/v1/traces?trace_id= handler and the fleet endpoint (two drifted
    copies is exactly how ids end up resolving per-daemon but 404ing
    fleet-wide): ambiguous all-digit strings try both hex and decimal,
    0x forces hex, garbage is empty."""
    from hadoop_tpu.tracing.tracer import parse_trace_id_candidates
    assert parse_trace_id_candidates("ff") == [255]
    assert parse_trace_id_candidates("123") == [0x123, 123]
    assert parse_trace_id_candidates("0x123") == [0x123]
    assert parse_trace_id_candidates("zzz!") == []
    assert parse_trace_id_candidates("0") == [0]   # dedup across bases


# --------------------------------------------- trace store under churn


def _fake_trace_server(spans, slow=()):
    """A chassis HttpServer whose trace endpoints serve CONTROLLED
    spans (overriding the process-global collector handlers)."""
    from hadoop_tpu.http.server import HttpServer
    srv = HttpServer(Configuration(load_defaults=False), daemon_name="f")
    srv.add_handler("/ws/v1/traces",
                    lambda q, b: (200, {"spans": list(spans)}))
    srv.add_handler("/ws/v1/traces/slow",
                    lambda q, b: (200, {"traces": list(slow)}))
    srv.start()
    return srv


def test_store_merges_and_keeps_spans_of_departed_endpoint():
    """Kill a daemon mid-scrape: the spans it already contributed stay
    in the assembled trace; its endpoint bookkeeping is pruned once
    discovery drops it (FleetScraper precedent)."""
    a = _fake_trace_server([_span(5, 1, None, 0.0, 1.0, "client.op")])
    b = _fake_trace_server([_span(5, 2, 1, 0.2, 0.8, "dn.op")])
    store = FleetTraceStore(Configuration(load_defaults=False))
    ep_a = Endpoint("a", "127.0.0.1", a.port, "daemon")
    ep_b = Endpoint("b", "127.0.0.1", b.port, "datanode")
    try:
        store.scrape([ep_a, ep_b])
        t = store.assemble(5)
        assert t["num_spans"] == 2
        assert {s["daemon"] for s in _names(t)} == {"a", "b"}

        # b dies; still listed: scrape fails, spans kept, ok=False
        b.stop()
        store.scrape([ep_a, ep_b])
        st = store.status()
        assert st[ep_b.key]["ok"] is False and st[ep_b.key]["error"]
        assert store.assemble(5)["num_spans"] == 2

        # discovery drops b: bookkeeping pruned, spans STILL kept
        store.scrape([ep_a])
        st = store.status()
        assert ep_b.key not in st and ep_a.key in st
        t = store.assemble(5)
        assert t["num_spans"] == 2
        assert any(s["name"] == "dn.op" for s in _names(t))
    finally:
        a.stop()


def _names(tree):
    out = []

    def walk(n):
        out.append(n)
        for c in n["children"]:
            walk(c)
    for r in tree["tree"]:
        walk(r)
    return out


def test_store_bounds_traces_lru():
    conf = Configuration(load_defaults=False)
    conf.set("obs.doctor.max-traces", "3")
    srv = _fake_trace_server(
        [_span(t, t * 10, None, 0.0, 1.0, f"op{t}") for t in
         (1, 2, 3, 4, 5)])
    store = FleetTraceStore(conf)
    try:
        store.scrape([Endpoint("a", "127.0.0.1", srv.port)])
        held = store.trace_ids()
        assert len(held) == 3 and set(held) == {3, 4, 5}
    finally:
        srv.stop()


def test_store_targeted_fetch_pulls_flight_recorder():
    """A trace only the flight recorder retains resolves via the
    targeted fetch path (exemplar-resolution's fallback)."""
    slow_trace = {"trace_id": 11, "trigger": "x", "spans": [
        _span(11, 1, None, 0.0, 2.0, "slow.root")]}
    srv = _fake_trace_server([], slow=[slow_trace])
    store = FleetTraceStore(Configuration(load_defaults=False))
    try:
        ep = Endpoint("a", "127.0.0.1", srv.port)
        assert store.assemble(11) is None
        store.fetch_trace(11, [ep])
        t = store.assemble(11)
        assert t is not None and t["tree"][0]["name"] == "slow.root"
    finally:
        srv.stop()


# --------------------------------------------------- chassis servlets


def test_ws_stacks_json_servlet():
    from hadoop_tpu.http.server import HttpServer
    srv = HttpServer(Configuration(load_defaults=False),
                     daemon_name="stacky")
    srv.start()
    marker = threading.Event()
    t = threading.Thread(target=marker.wait, name="obs-marker-thread",
                         daemon=True)
    t.start()
    try:
        js = _get_json(srv.port, "/ws/v1/stacks")
        assert js["daemon"] == "stacky"
        byname = {th["name"]: th for th in js["threads"]}
        assert "obs-marker-thread" in byname
        th = byname["obs-marker-thread"]
        assert th["daemon"] is True and th["alive"] is True
        # frames carry file/line/func — the wait() frame is in there
        assert any(f["func"] == "wait" for f in th["stack"])
    finally:
        marker.set()
        srv.stop()


def test_prom_exemplars_opt_out():
    """Strict 0.0.4 consumers can disable exemplars per-scrape
    (?exemplars=0) or fleet-wide (metrics.prom.exemplars=false) — a
    stock Prometheus scraper rejects the OpenMetrics suffix."""
    from hadoop_tpu.http.server import HttpServer
    from hadoop_tpu.metrics import metrics_system
    h = metrics_system().source("exq").histogram("exq_seconds", "t")
    h.add(0.01, exemplar_trace=0xbeef)
    srv = HttpServer(Configuration(load_defaults=False), daemon_name="p")
    srv.start()
    try:
        _, body = _get(srv.port, "/prom")
        assert ' # {trace_id="' in body.decode()     # default: on
        _, body = _get(srv.port, "/prom?exemplars=0")
        assert " # " not in body.decode()
        assert "exq_seconds_bucket" in body.decode()  # data intact
    finally:
        srv.stop()
    conf = Configuration(load_defaults=False)
    conf.set("metrics.prom.exemplars", "false")
    srv = HttpServer(conf, daemon_name="p2")
    srv.start()
    try:
        _, body = _get(srv.port, "/prom")
        assert " # " not in body.decode()
        _, body = _get(srv.port, "/prom?exemplars=1")  # per-scrape wins
        assert ' # {trace_id="' in body.decode()
    finally:
        srv.stop()


def test_ws_top_reads_registered_decay_accounting():
    from hadoop_tpu.http.server import HttpServer
    obs_top.reset_for_tests()
    obs_top.register_top_source(
        "test.tenants",
        lambda: {"total": 100.0,
                 "tenants": {"heavy": 80.0, "light": 20.0}})
    srv = HttpServer(Configuration(load_defaults=False), daemon_name="t")
    srv.start()
    try:
        js = _get_json(srv.port, "/ws/v1/top?n=1")
        src = js["sources"]["test.tenants"]
        assert src["window"] == [
            {"key": "heavy", "cost": 80.0, "share": 0.8}]
        status, _ = _get(srv.port, "/ws/v1/top?n=zzz")
        assert status == 400
        # a raising source becomes an error entry, not a 500
        obs_top.register_top_source(
            "bad", lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        js = _get_json(srv.port, "/ws/v1/top")
        assert "RuntimeError" in js["sources"]["bad"]["error"]
    finally:
        srv.stop()
        obs_top.reset_for_tests()


def test_serving_qos_snapshot_shape_matches_top_contract():
    """The door's decay accounting is readable by /ws/v1/top as-is —
    the 'reuse ISSUE 8's accounting, no second counter' contract."""
    from hadoop_tpu.serving.qos import DecayCostScheduler
    sched = DecayCostScheduler(4, Configuration(load_defaults=False))
    try:
        sched.charge("tenant-a", 700.0)
        sched.charge("tenant-b", 300.0)
        obs_top.reset_for_tests()
        obs_top.register_top_source("serving.test.tenants",
                                    sched.snapshot)
        out = obs_top.top_n(5)["serving.test.tenants"]
        assert out["window"][0]["key"] == "tenant-a"
        assert out["window"][0]["share"] == pytest.approx(0.7)
    finally:
        sched.stop()
        obs_top.reset_for_tests()


# ------------------------------------------------- autoscaler victim


def test_autoscaler_prefers_sick_victim():
    from hadoop_tpu.serving.autoscale.controller import Autoscaler
    from hadoop_tpu.serving.autoscale.signals import ReplicaSample

    busy_sick = ReplicaSample(path="/s/r1", host="h", port=1, ok=True,
                              active=3, queue_depth=2, cached_blocks=9)
    idle_healthy = ReplicaSample(path="/s/r2", host="h", port=2,
                                 ok=True, active=0, queue_depth=0,
                                 cached_blocks=0)
    pick = Autoscaler._pick_victim  # unbound: no registry needed

    class Stub:
        _sick = {"/s/r1"}
    assert pick(Stub(), [busy_sick, idle_healthy]) is busy_sick

    class NoSick:
        _sick = set()
    assert pick(NoSick(), [busy_sick, idle_healthy]) is idle_healthy


def test_parse_prom_strips_exemplar_suffix():
    from hadoop_tpu.serving.autoscale.signals import parse_prom
    text = ('htpu_x_bucket{le="0.5"} 3 # {trace_id="00ab"} 0.4 1.7e9\n'
            'htpu_x_bucket{le="+Inf"} 3\n')
    fams = parse_prom(text)
    assert fams["htpu_x_bucket"][0] == ({"le": "0.5"}, 3.0)


# -------------------------------------------------------- miniDFS e2e


@pytest.fixture(scope="module")
def doctor_cluster(tmp_path_factory):
    """One 3-DN miniDFS + a FleetDoctor wired to it (and the NN audit
    log on) — shared by the e2e tests below."""
    from hadoop_tpu.obs.doctor import FleetDoctor
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    conf = fast_conf()
    conf.set("dfs.replication", "2")
    conf.set("dfs.client.read.shortcircuit", "false")
    conf.set("namenode.audit.enable", "true")
    base = str(tmp_path_factory.mktemp("doctor-e2e"))
    span_collector().reset_for_tests()
    with MiniDFSCluster(num_datanodes=3, conf=conf,
                        base_dir=base) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        # real traffic: pipelines populate the peer trackers and the
        # xceiver histograms (tiny and fast; detection never reads
        # these wall-clock numbers — the tests inject their own)
        for i in range(3):
            fs.write_all(f"/warm{i}.bin", b"x" * 100_000)
            fs.read_all(f"/warm{i}.bin")
        dconf = Configuration(load_defaults=False)
        dconf.set("obs.doctor.namenode.http",
                  f"127.0.0.1:{cluster.namenode.http.port}")
        dconf.set("dfs.namenode.rpc-address",
                  f"127.0.0.1:{cluster.namenode.port}")
        # determinism: the absolute floor sits far above anything the
        # real miniDFS traffic can produce (single-box microsecond-to-
        # millisecond noise), so ONLY the injected 250 ms latencies can
        # flag — the decision never reads wall-clock measurements
        dconf.set("obs.doctor.slow.floor.ms", "50")
        doctor = FleetDoctor(dconf)
        doctor.init(dconf)
        doctor.start()
        try:
            yield cluster, fs, doctor
        finally:
            doctor.stop()


def test_slow_datanode_flagged_deprioritized_and_exemplar_resolves(
        doctor_cluster):
    """THE acceptance path, in two phases sharing one live cluster
    (the conftest autouse reset wipes the process-global metrics
    system BETWEEN tests, so the /prom-exemplar phase must run in the
    same test as the traffic that mints it):

    1. one DN gets injected slow pipeline-ack latencies; within
       min_windows doctor polls it (and only it) is flagged at
       /ws/v1/fleet/doctor, and NN placement stops choosing it while
       healthy nodes can satisfy the pipeline;
    2. an exemplar trace id lifted off a DN's /prom histogram bucket
       resolves at the doctor into a full assembled cross-daemon trace.
    """
    cluster, fs, doctor = doctor_cluster
    uuids = [dn.uuid for dn in cluster.datanodes]
    sick = uuids[2]
    # injected latencies, never wall-clock: two healthy reporters each
    # measure the sick DN ~50x slower than each other
    for reporter in (0, 1):
        tracker = cluster.datanodes[reporter].xceiver.peer_tracker
        other = uuids[1 - reporter]
        for _ in range(16):
            tracker.record(sick, 0.250)
            tracker.record(other, 0.005)
    for _ in range(3):                    # bounded: min_windows polls
        report = doctor.poll_once()
    flagged = report["datanodes"]["flagged"]
    assert list(flagged) == [sick], flagged
    ev = flagged[sick]["signals"]["dn.pipeline_ack"]
    assert ev["windows_flagged"] >= 3
    # the report links the node's thread dump (and the link works)
    stacks_url = flagged[sick]["stacks"]
    port = int(stacks_url.rsplit(":", 1)[1].split("/", 1)[0])
    assert _get_json(port, "/ws/v1/stacks")["num_threads"] > 0

    # the doctor door serves the same verdict
    js = _get_json(doctor.port, "/ws/v1/fleet/doctor")
    assert list(js["datanodes"]["flagged"]) == [sick]

    # NN consumed the push: placement deprioritizes the flagged DN
    dm = cluster.namenode.fsn.bm.dn_manager
    assert sick in dm.slow_node_uuids()
    for _ in range(8):
        targets = dm.choose_targets(2, set())
        assert sick not in [t.uuid for t in targets]
    # ...but a pipeline WIDER than the healthy pool still places
    assert len(dm.choose_targets(3, set())) == 3
    # NN roster marks it for operators
    roster = _get_json(cluster.namenode.http.port, "/ws/v1/datanodes")
    assert {d["uuid"]: d["slow"] for d in roster["datanodes"]}[sick]

    # ---- phase 2: exemplar -> assembled cross-daemon trace
    tracer = global_tracer()
    with tracer.span("e2e.traced_read") as root:
        assert fs.read_all("/warm0.bin")
    # the xceiver's read histogram recorded inside the resumed span:
    # its bucket exemplar IS this trace
    found = None
    debug = []
    for dn in cluster.datanodes:
        _, body = _get(dn.http.port, "/prom")
        debug += [l for l in body.decode().splitlines()
                  if "read_block_seconds_bucket" in l and "#" in l]
        for m in re.finditer(
                r'htpu_read_block_seconds_bucket\{[^}]*\} \d+ '
                r'# \{trace_id="([0-9a-f]+)"\}', body.decode()):
            if int(m.group(1), 16) == root.trace_id:
                found = m.group(1)
        if found:
            break
    assert found, (f"no exemplar for trace {root.trace_id:016x}; "
                   f"saw {debug}")
    # the DECIMAL form (what span JSON prints) must resolve too — the
    # fleet endpoint tries the same candidate set per-daemon handlers do
    assert _get_json(doctor.port,
                     f"/ws/v1/fleet/traces/{root.trace_id}")
    assembled = _get_json(doctor.port, f"/ws/v1/fleet/traces/{found}")
    names = {s["name"] for s in _names(assembled)}
    assert "e2e.traced_read" in names            # client plane
    assert any(n.startswith("namenode.") for n in names)   # NN plane
    assert "dfs.xceiver.read_block" in names     # DN plane
    assert assembled["critical_path"], "no critical-path summary"
    # list endpoint knows it now; bad ids are rejected loudly
    listed = _get_json(doctor.port, "/ws/v1/fleet/traces")
    assert found in listed["traces"]
    status, _ = _get(doctor.port, "/ws/v1/fleet/traces/zzz!")
    assert status == 400
    status, _ = _get(doctor.port, f"/ws/v1/fleet/traces/{'f' * 16}")
    assert status == 404

    # ---- phase 3: recovery — the node stops being slow, and the NN
    # clears IMMEDIATELY on the doctor's next (empty) full report, not
    # after the TTL
    for reporter in (0, 1):
        tracker = cluster.datanodes[reporter].xceiver.peer_tracker
        for _ in range(tracker.window):     # flush the injected slowness
            tracker.record(sick, 0.004)
    for _ in range(5):                      # clean windows push out slow
        report = doctor.poll_once()
    assert report["datanodes"]["flagged"] == {}
    assert sick not in dm.slow_node_uuids(), \
        "recovered DN still deprioritized (empty report never pushed)"


def test_nn_audit_log_lines(doctor_cluster, caplog):
    """One structured tab-separated line per namespace op on the
    existing ``hadoop_tpu.audit`` plane — success lines gain status=ok
    + trace_id (joined to the telemetry plane), failed RPCs gain their
    own failure line from the facade auditor, and the whole stream
    stays dynamometer-parseable."""
    from hadoop_tpu.tools.dynamometer import parse_audit_line
    cluster, fs, doctor = doctor_cluster
    tracer = global_tracer()
    with caplog.at_level(logging.INFO, logger="hadoop_tpu.audit"):
        with tracer.span("audit.probe") as root:
            fs.mkdirs("/audited-dir")
        with pytest.raises(FileNotFoundError):
            fs.read_all("/no-such-file")
    lines = [r.getMessage() for r in caplog.records
             if r.name == "hadoop_tpu.audit"]
    mk = [parse_audit_line(l) for l in lines if "cmd=mkdirs" in l]
    assert mk, lines
    ev = mk[-1]
    assert ev["src"] == "/audited-dir" and ev["allowed"] == "true"
    assert ev["status"] == "ok"
    assert ev["trace_id"] == f"{root.trace_id:016x}"
    assert ev["ugi"] and ev["ip"]
    failed = [parse_audit_line(l) for l in lines if "failed" in l]
    assert any(e["src"] == "/no-such-file" and
               e["status"].startswith("failed(") and
               e["cmd"] == "get_block_locations" for e in failed), lines


def test_slow_node_push_reaches_every_configured_namenode(
        doctor_cluster):
    """HA posture: the doctor pushes its report to EVERY address in
    dfs.namenode.rpc-address (the DN's one-actor-per-NN precedent) and
    tolerates dead members — a push that only ever reached the first
    (possibly standby) NN would silently defeat placement
    deprioritization."""
    from hadoop_tpu.obs.doctor import FleetDoctor
    cluster, fs, doctor = doctor_cluster
    dconf = Configuration(load_defaults=False)
    # first address is a corpse; the real NN is second
    dconf.set("dfs.namenode.rpc-address",
              f"127.0.0.1:1,127.0.0.1:{cluster.namenode.port}")
    doc2 = FleetDoctor(dconf)
    doc2.init(dconf)             # no start(): push driven directly
    try:
        doc2._push_slow_nodes(["ha-probe-uuid"])
        dm = cluster.namenode.fsn.bm.dn_manager
        assert "ha-probe-uuid" in dm.slow_node_uuids()
        doc2._push_slow_nodes([])        # the full-report clear
        assert "ha-probe-uuid" not in dm.slow_node_uuids()
    finally:
        doc2.stop()


def test_discovery_skips_stale_registry_records():
    """Corpse replicas (heartbeat stamp older than the record TTL)
    cost bounded-timeout scrapes EVERY poll — discovery skips them,
    the router/autoscaler precedent."""
    from hadoop_tpu.obs.doctor import FleetDoctor
    from hadoop_tpu.registry.registry import (HEARTBEAT_ATTR,
                                              RegistryServer,
                                              ServiceRecord)
    conf = Configuration(load_defaults=False)
    reg = RegistryServer(conf)
    reg.init(conf)
    reg.start()
    try:
        reg.put(ServiceRecord(
            "/services/s/fresh", {"http": "127.0.0.1:1234"},
            {HEARTBEAT_ATTR: f"{time.time():.3f}"}), ttl_s=60.0)
        reg.put(ServiceRecord(
            "/services/s/corpse", {"http": "127.0.0.1:1235"},
            {HEARTBEAT_ATTR: f"{time.time() - 3600:.3f}"}), ttl_s=60.0)
        reg.put(ServiceRecord(           # pre-heartbeat publisher:
            "/services/s/legacy", {"http": "127.0.0.1:1236"},
            {}), ttl_s=60.0)             # never stale by contract
        dconf = Configuration(load_defaults=False)
        dconf.set("obs.doctor.registry", f"127.0.0.1:{reg.port}")
        dconf.set("obs.doctor.service", "/services/s")
        doc = FleetDoctor(dconf)
        doc.init(dconf)
        try:
            names = {e.name for e in doc.discover()}
            assert names == {"/services/s/fresh", "/services/s/legacy"}
        finally:
            doc.stop()
    finally:
        reg.stop()


def test_nn_top_shows_rpc_callers(doctor_cluster):
    """nntop over the NN's decay scheduler: the test user's calls rank
    on /ws/v1/top without any nntop-private counter."""
    cluster, fs, doctor = doctor_cluster
    for i in range(5):
        fs.exists("/warm0.bin")
    js = _get_json(cluster.namenode.http.port, "/ws/v1/top")
    nn_sources = [s for s in js["sources"]
                  if s.startswith("namenode.") and
                  s.endswith("rpc.callers")]
    assert nn_sources, js["sources"].keys()
    window = js["sources"][nn_sources[0]]["window"]
    assert window and window[0]["cost"] > 0


def test_audit_toggle(tmp_path):
    """namenode.audit.enable (default on, the seed's behavior) gates
    BOTH halves of the plane: the facade install and the success-line
    call sites."""
    from hadoop_tpu.dfs.namenode.audit import (AuditedClientProtocol,
                                               maybe_audited)
    from hadoop_tpu.dfs.namenode import fsnamesystem as fsn_mod
    conf = Configuration(load_defaults=False)
    sentinel = object()
    assert isinstance(maybe_audited(sentinel, conf),
                      AuditedClientProtocol)
    conf.set("namenode.audit.enable", "false")
    assert maybe_audited(sentinel, conf) is sentinel
    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r)
    fsn_mod.audit_log.addHandler(handler)
    try:
        fsn_mod.set_audit_enabled(False)
        fsn_mod.log_audit_event(True, "mkdirs", "/x")
        assert records == []
        fsn_mod.set_audit_enabled(True)
        fsn_mod.log_audit_event(True, "mkdirs", "/x")
        assert len(records) == 1
    finally:
        fsn_mod.audit_log.removeHandler(handler)
        fsn_mod.set_audit_enabled(True)


# -------------------------------------------- training flight recorder


def test_comm_runtime_capture_and_counters():
    """The dispatch seam: trace-time records bind to the step key, and
    every subsequent execution advances the per-site byte counters and
    latency histograms by the captured static profile."""
    from hadoop_tpu.obs.comm import comm_runtime, record_comm

    rt = comm_runtime()
    with rt.step("t.step"):
        record_comm("tp.psum", 100, 400)       # quantized: 4x bytes
        record_comm("tp.psum", 50, 200)        # two chunks, one site
        record_comm("not-a-real-site", 7, 7)   # unbounded-proof: other
        # a site a sync schedule scheduled OFF: reference intact,
        # payload 0, zero executed collectives (syncpolicy.py)
        record_comm("tp.scatter", 0, 640, executions=0)
    # second execution: jit cache hit, no fresh records, profile reused
    with rt.step("t.step"):
        pass
    prof = rt.profile("t.step")
    assert prof["tp.psum"] == (150, 600, 2)
    assert prof["other"] == (7, 7, 1)
    assert prof["tp.scatter"] == (0, 640, 0)
    rep = rt.report()
    assert rep["sites"]["tp.psum"]["payload_bytes"] == 300
    assert rep["sites"]["tp.psum"]["reference_bytes"] == 1200
    assert rep["sites"]["tp.psum"]["executions"] == 4
    assert rep["sites"]["tp.psum"]["observations"] == 2
    # the skipped site still observes per step but executes nothing —
    # how the ledger proves collective-execution counts drop
    assert rep["sites"]["tp.scatter"]["executions"] == 0
    assert rep["sites"]["tp.scatter"]["observations"] == 2
    assert rep["sites"]["tp.scatter"]["reference_bytes"] == 1280
    assert rep["steps"]["t.step"] == 2
    # records OUTSIDE any dispatch window are dropped (a bare test
    # trace is not a runtime step)
    record_comm("tp.psum", 999, 999)
    assert rt.report()["sites"]["tp.psum"]["payload_bytes"] == 300
    # a step that RAISED moved nothing: no observation recorded
    try:
        with rt.step("t.step"):
            raise RuntimeError("aborted step")
    except RuntimeError:
        pass
    assert rt.report()["steps"]["t.step"] == 2


def test_comm_runtime_conf_gate():
    """obs.comm.timing=false: the seam no-ops (no counters, no
    histograms) but the capture still binds profiles, so flipping the
    gate back on needs no retrace."""
    from hadoop_tpu.obs.comm import comm_runtime, record_comm

    rt = comm_runtime()
    conf = Configuration(load_defaults=False)
    conf.set("obs.comm.timing", "false")
    rt.configure(conf)
    with rt.step("gated.step"):
        record_comm("bucket.psum", 10, 40)
    assert rt.report()["sites"] == {}
    assert rt.profile("gated.step")["bucket.psum"] == (10, 40, 1)
    rt.set_enabled(True)
    with rt.step("gated.step"):
        pass
    assert rt.report()["sites"]["bucket.psum"]["payload_bytes"] == 10


def test_comm_prom_families_are_bounded_and_shared():
    """htpu_comm_* on /prom: ONE family per kind, site label values
    only from the bounded set, histogram exemplar captured under an
    active sampled span."""
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.prom import render_prom
    from hadoop_tpu.obs.comm import COMM_SITES, comm_runtime, record_comm

    rt = comm_runtime()
    with global_tracer().span("trainer.step") as root:
        with rt.step("p.step"):
            record_comm("zero1.gather", 64, 256)
    text = render_prom(metrics_system())
    assert text.count("# TYPE htpu_comm_seconds histogram") == 1
    assert text.count("# TYPE htpu_comm_payload_bytes_total counter") \
        == 1
    sites = set(re.findall(
        r'htpu_comm_payload_bytes_total\{[^}]*site="([^"]+)"', text))
    assert sites and sites <= set(COMM_SITES)
    # the slow-bucket exemplar names the step's trace
    assert f'trace_id="{root.trace_id:016x}"' in text


def test_hbm_ledger_components_and_family():
    """Component sums, provider error containment, unregister_prefix,
    and the single htpu_hbm_bytes family with bounded component
    labels."""
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.prom import render_prom
    from hadoop_tpu.obs.hbm import HBM_COMPONENTS, hbm_ledger

    led = hbm_ledger()
    led.register("e1.w", "weights", lambda: 1000)
    led.register("e1.kv", "kv_pool", lambda: 500)
    led.register("e1.kv2", "kv_pool", lambda: 250)     # sums per comp
    led.register("e1.bad", "opt_state", lambda: 1 / 0)  # contained
    led.register("e1.odd", "no-such-component", lambda: 9)  # -> other
    rep = led.report()
    assert rep["components"]["weights"] == 1000
    assert rep["components"]["kv_pool"] == 750
    assert rep["components"]["other"] == 9
    assert rep["errors"] == 1
    assert rep["total_bytes"] == sum(rep["components"].values())
    text = render_prom(metrics_system())
    assert text.count("# TYPE htpu_hbm_bytes gauge") == 1
    comps = set(re.findall(
        r'htpu_hbm_bytes\{[^}]*component="([^"]+)"', text))
    assert comps and comps <= set(HBM_COMPONENTS)
    assert 'component="weights"} 1000' in text
    led.unregister_prefix("e1.")
    assert led.report()["components"] == {}


def test_engine_hbm_components_sum_sanity(tiny_model=None):
    """The engine's registered components match its measured numbers:
    weights == engine.weight_bytes, kv_pool == num_blocks x
    block_nbytes; stop() removes them from the ledger."""
    import jax

    from hadoop_tpu.models.config import get_config
    from hadoop_tpu.models.decoder import init_params
    from hadoop_tpu.obs.hbm import hbm_ledger
    from hadoop_tpu.serving.engine import DecodeEngine

    cfg = get_config("tiny")
    params = init_params(jax.random.PRNGKey(0), cfg)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       num_blocks=9, max_context=32)
    comps, errors = hbm_ledger().component_bytes()
    assert errors == 0
    assert comps["weights"] == eng.weight_bytes
    assert comps["kv_pool"] == 9 * eng.block_nbytes
    rep = hbm_ledger().report()
    assert rep["total_bytes"] == sum(comps.values())
    # the CPU simulator reports no device stats — the ledger degrades
    # to accounted bytes (device is None), never an error
    eng.stop()
    comps, _ = hbm_ledger().component_bytes()
    assert "weights" not in comps and "kv_pool" not in comps


def test_trainer_step_metrics_rank_label():
    """Rank-labeled /prom families from the bounded label set: rank 3
    publishes rank="3"; a rank past the set shares "other"."""
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.prom import render_prom
    from hadoop_tpu.obs.trainer import TrainerStepMetrics, rank_label

    assert rank_label(3) == "3"
    assert rank_label(99) == "other"
    m = TrainerStepMetrics(rank=3)
    m.step_wall_hist.add(0.05)
    m.data_wait_hist.add(0.01)
    text = render_prom(metrics_system())
    assert re.search(
        r'htpu_trainer_step_wall_seconds_count\{[^}]*rank="3"', text)
    assert re.search(
        r'htpu_trainer_data_wait_seconds_count\{[^}]*rank="3"', text)
    # a RE-RANKED process must not keep publishing under the old label
    # (get_or_make alone would hand back the rank="3" histogram)
    m2 = TrainerStepMetrics(rank=5)
    m2.step_wall_hist.add(0.02)
    text = render_prom(metrics_system())
    assert re.search(
        r'htpu_trainer_step_wall_seconds_count\{[^}]*rank="5"', text)
    assert 'rank="3"' not in text


def test_trainer_telemetry_endpoint_shape():
    """/ws/v1/trainer serves the step anatomy as CUMULATIVE sums (the
    doctor diffs them), plus the comm and HBM ledger blocks."""
    from hadoop_tpu.obs.comm import comm_runtime, record_comm
    from hadoop_tpu.obs.hbm import hbm_ledger
    from hadoop_tpu.obs.trainer import (TrainerStepMetrics,
                                        TrainerTelemetry)

    m = TrainerStepMetrics(rank=1)
    m.steps.incr()
    m.step_wall.add(0.2)
    m.step_wall_hist.add(0.2)
    m.ckpt_snapshot.add(0.01)
    with comm_runtime().step("trainer.step"):
        record_comm("bucket.psum", 11, 44)
    hbm_ledger().register("t.params", "params", lambda: 4096)
    tt = TrainerTelemetry(rank=1, job="j", metrics=m)
    try:
        body = _get_json(tt.port, "/ws/v1/trainer")
        assert body["rank"] == 1 and body["job"] == "j"
        assert body["steps"] == 1
        assert body["step_wall"]["count"] == 1
        assert abs(body["step_wall"]["sum"] - 0.2) < 1e-9
        assert body["ckpt"]["snapshot"]["num_ops"] == 1
        assert body["comm"]["sites"]["bucket.psum"]["payload_bytes"] \
            == 11
        assert body["hbm"]["components"]["params"] == 4096
        # the chassis standard servlets ride along
        assert _get(tt.port, "/prom")[0] == 200
        assert _get_json(tt.port, "/ws/v1/stacks")["num_threads"] >= 1
    finally:
        tt.close()


class _FakeRank:
    """A controllable trainer endpoint: the test scripts the cumulative
    step_wall sums the doctor windows — detection runs on INJECTED
    numbers only (the determinism rule), never wall clocks."""

    def __init__(self, name):
        from hadoop_tpu.http import HttpServer
        self.name = name
        self.sum = 0.0
        self.count = 0
        self.http = HttpServer(Configuration(load_defaults=False),
                               daemon_name=name)
        self.http.add_handler("/ws/v1/trainer", self._h)
        self.http.start()

    def _h(self, query, body):
        return 200, {"rank": self.name, "job": "j",
                     "steps": self.count,
                     "step_wall": {"sum": self.sum,
                                   "count": self.count}}

    def advance(self, per_step, steps=10):
        self.sum += per_step * steps
        self.count += steps

    def stop(self):
        self.http.stop()


def _trainer_doctor(ranks):
    from hadoop_tpu.obs.doctor import FleetDoctor
    conf = Configuration(load_defaults=False)
    conf.set("obs.doctor.endpoints", ",".join(
        f"{r.name}=127.0.0.1:{r.http.port}" for r in ranks))
    # absolute floor far above box noise (values here are scripted
    # anyway — the doctor_smoke precedent)
    conf.set("obs.doctor.slow.floor.ms", "50")
    doctor = FleetDoctor(conf)
    doctor.init(conf)
    doctor.start()
    return doctor


def test_doctor_flags_straggler_rank_and_recovers():
    """Injected-latency straggler: exactly the slow rank flagged in <=3
    observation windows, a dead rank keeps its roster row with
    ok=False, and clean windows recover the flag without operator
    reset."""
    ranks = [_FakeRank(f"rank-{i}") for i in range(4)]
    doctor = _trainer_doctor(ranks)
    try:
        for r in ranks:
            r.advance(0.010)        # baseline poll: no diff yet
        doctor.poll_once()
        flagged = []
        windows = 0
        for windows in range(1, 4):
            for i, r in enumerate(ranks):
                r.advance(0.500 if i == 2 else 0.010)
            report = doctor.poll_once()
            flagged = sorted(report["trainers"]["flagged"])
            if flagged == ["rank-2"]:
                break
        assert flagged == ["rank-2"], report["trainers"]
        assert windows <= 3
        ev = report["trainers"]["flagged"]["rank-2"]
        assert ev["signals"]["trainer.step_wall"]["value"] > 0.05
        assert ev["stacks"].endswith("/ws/v1/stacks")
        rows = report["trainers"]["ranks"]
        assert len(rows) == 4 and all(v["ok"] for v in rows.values())
        # ---- recovery: the injection stops, hysteresis clears it
        for _ in range(5):
            for r in ranks:
                r.advance(0.010)
            report = doctor.poll_once()
            if not report["trainers"]["flagged"]:
                break
        assert report["trainers"]["flagged"] == {}
        # ---- a dead rank keeps its history with ok=False
        ranks[3].stop()
        for i, r in enumerate(ranks[:3]):
            r.advance(0.010)
        report = doctor.poll_once()
        rows = report["trainers"]["ranks"]
        dead = [v for v in rows.values()
                if v["endpoint"]["name"] == "rank-3"]
        assert dead and dead[0]["ok"] is False
        assert dead[0]["steps"] > 0          # contributed history kept
        alive = [v for v in rows.values()
                 if v["endpoint"]["name"] != "rank-3"]
        assert all(v["ok"] for v in alive)
    finally:
        doctor.stop()
        for r in ranks[:3]:
            r.stop()


def test_doctor_discovers_trainer_roster_and_skips_stale():
    """Trainer-job roster through the registry: a live heartbeat-
    stamped rank is discovered with kind=trainer; a corpse record
    (stale heartbeat) is SKIPPED by the record_is_stale precedent —
    no scrape timeouts burned on it."""
    from hadoop_tpu.obs.trainer import TrainerTelemetry
    from hadoop_tpu.registry import (HEARTBEAT_ATTR, RegistryServer,
                                     ServiceRecord)

    conf = Configuration(load_defaults=False)
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    tt = None
    doctor = None
    try:
        tconf = Configuration(load_defaults=False)
        tconf.set("obs.trainer.registry",
                  f"127.0.0.1:{reg_srv.port}")
        tt = TrainerTelemetry(tconf, rank=0, job="jobx")
        # a corpse: registered long ago, heartbeat stamp stale
        reg_srv.put(ServiceRecord(
            "/trainer-jobs/jobx/rank-9",
            endpoints={"http": "127.0.0.1:1"},
            attributes={HEARTBEAT_ATTR: f"{time.time() - 3600:.3f}"}),
            ttl_s=7200)
        from hadoop_tpu.obs.doctor import FleetDoctor
        dconf = Configuration(load_defaults=False)
        dconf.set("obs.doctor.registry", f"127.0.0.1:{reg_srv.port}")
        doctor = FleetDoctor(dconf)
        doctor.init(dconf)
        doctor.start()
        eps = doctor.discover()
        trainers = {e.name: e for e in eps if e.kind == "trainer"}
        assert "/trainer-jobs/jobx/rank-0" in trainers
        assert "/trainer-jobs/jobx/rank-9" not in trainers
        report = doctor.poll_once()
        rows = report["trainers"]["ranks"]
        assert any(v["endpoint"]["name"] == "/trainer-jobs/jobx/rank-0"
                   and v["ok"] for v in rows.values())
    finally:
        if doctor is not None:
            doctor.stop()
        if tt is not None:
            tt.close()
        reg_srv.stop()
