"""Communication-overlap pass: bit-exact parity and bucketing units.

Two layers of coverage:

- Primitive tests run the bucketed collectives inside a bare shard_map
  against their per-leaf forms and assert BITWISE equality — the
  property the whole pass rests on (concatenation/chunking must change
  the schedule, never the sums).
- Full-step A-B tests build the real train step with the pass on vs
  off (dp2, dp2×tp2(+sp), zero1 dp8 — the combinations the MULTICHIP
  dryrun runs) and assert bit-identical losses AND parameters. These
  need shard_map's varying-manual-axes tracking (jax.typeof().vma),
  which the training path requires anyway; on older jax they skip like
  the rest of the multichip suite fails at seed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from hadoop_tpu.parallel.overlap import (DEFAULT_OVERLAP, OVERLAP_OFF,
                                         OverlapConfig, _pack_buckets,
                                         bucketed_gather_slices,
                                         bucketed_psum,
                                         bucketed_psum_scatter,
                                         overlap_from_conf,
                                         zero1_slice_meta)

requires_vma = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="multichip train step needs jax vma tracking "
           "(jax.typeof); same gap that fails the seed parallel suite "
           "on this jax")


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("x",))


def _smap(f, mesh, in_specs, out_specs):
    """Version-portable shard_map with replication checking off (the
    primitive tests assert numerics, not spec inference)."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)


# --------------------------------------------------------------- packing

def test_pack_buckets_is_deterministic_and_size_bounded():
    sizes = [10, 20, 30, 5, 100, 1]
    buckets = _pack_buckets(sizes, itemsize=4, bucket_bytes=128)
    # in-order, every index exactly once
    assert [i for b in buckets for i in b] == list(range(len(sizes)))
    # no bucket over the cap unless it is a single oversized leaf
    for b in buckets:
        if len(b) > 1:
            assert sum(sizes[i] for i in b) * 4 <= 128
    # identical inputs → identical packing (the deterministic-order
    # contract the bit-exactness argument relies on)
    assert buckets == _pack_buckets(sizes, itemsize=4, bucket_bytes=128)


def test_pack_buckets_oversized_leaf_gets_own_bucket():
    buckets = _pack_buckets([1000, 2, 3], itemsize=4, bucket_bytes=64)
    assert buckets[0] == [0]
    assert buckets[1] == [1, 2]


def test_zero1_slice_meta_padding():
    z, k = zero1_slice_meta(np.zeros(10), ("x",), {"x": 4})
    assert (z, k) == (4, 3)          # 10 padded to 12 = 4*3
    z, k = zero1_slice_meta(np.zeros(8), (), {})
    assert (z, k) == (1, 8)


# ------------------------------------------------------------ collectives

def _tree():
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)
    return {
        "a": jax.random.normal(ks[0], (33,), jnp.float32),
        "b": jax.random.normal(ks[1], (17, 5), jnp.float32),
        "c": jax.random.normal(ks[2], (64,), jnp.float32),
        "d": jax.random.normal(ks[3], (7,)).astype(jnp.bfloat16),
    }


@pytest.mark.parametrize("bucket_bytes", [1, 256, 1 << 20])
def test_bucketed_psum_bitexact_vs_per_leaf(bucket_bytes):
    mesh = _mesh()
    tree = _tree()
    axes = {"a": ("x",), "b": ("x",), "c": (), "d": ("x",)}

    def per_leaf(t):
        return jax.tree_util.tree_map(
            lambda g, a: jax.lax.psum(g, tuple(a)) if a else g, t, axes)

    def bucketed(t):
        return bucketed_psum(t, axes, bucket_bytes)

    specs = jax.tree_util.tree_map(lambda _: P(), tree)
    args = (specs,)
    ref = jax.jit(_smap(per_leaf, mesh, args, specs))(tree)
    got = jax.jit(_smap(bucketed, mesh, args, specs))(tree)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ref),
            jax.tree_util.tree_leaves_with_path(got)):
        np.testing.assert_array_equal(
            np.asarray(a.astype(jnp.float32)),
            np.asarray(b.astype(jnp.float32)), err_msg=str(pa))


@pytest.mark.parametrize("bucket_bytes", [1, 1 << 20])
def test_bucketed_psum_scatter_matches_psum_plus_slice(bucket_bytes):
    mesh = _mesh()
    sizes = {"x": 4}
    tree = _tree()
    red = {k: ("x",) for k in tree}
    sc = {k: ("x",) for k in tree}

    def ref(t):
        def leaf(g):
            z, k = zero1_slice_meta(g, ("x",), sizes)
            full = jax.lax.psum(g, ("x",)).reshape(-1)
            pad = z * k - full.size
            if pad:
                full = jnp.pad(full, (0, pad))
            i = jax.lax.axis_index("x")
            return jax.lax.dynamic_slice(full, (i * k,), (k,))
        return jax.tree_util.tree_map(leaf, t)

    def scattered(t):
        return bucketed_psum_scatter(t, red, sc, sizes, bucket_bytes)

    in_specs = (jax.tree_util.tree_map(lambda _: P(), tree),)
    out_specs = jax.tree_util.tree_map(lambda _: P("x"), tree)
    a = jax.jit(_smap(ref, mesh, in_specs, out_specs))(tree)
    b = jax.jit(_smap(scattered, mesh, in_specs, out_specs))(tree)
    for (pa, x), (_, y) in zip(
            jax.tree_util.tree_leaves_with_path(a),
            jax.tree_util.tree_leaves_with_path(b)):
        np.testing.assert_array_equal(
            np.asarray(x.astype(jnp.float32)),
            np.asarray(y.astype(jnp.float32)), err_msg=str(pa))


@pytest.mark.parametrize("bucket_bytes", [1, 1 << 20])
def test_bucketed_gather_matches_per_leaf_gather(bucket_bytes):
    mesh = _mesh()
    sizes = {"x": 4}
    params = _tree()
    leaf_axes = {k: ("x",) for k in params}

    def slices_of(t):
        """Rank-dependent slices (deterministic): leaf slice layout."""
        def leaf(p):
            z, k = zero1_slice_meta(p, ("x",), sizes)
            flat = p.reshape(-1)
            pad = z * k - flat.size
            if pad:
                flat = jnp.pad(flat, (0, pad))
            i = jax.lax.axis_index("x")
            return jax.lax.dynamic_slice(flat, (i * k,), (k,))
        return jax.tree_util.tree_map(leaf, t)

    def per_leaf(t):
        sl = slices_of(t)

        def leaf(p, s):
            z, k = zero1_slice_meta(p, ("x",), sizes)
            i = jax.lax.axis_index("x")
            full = jnp.zeros((z * k,), s.dtype)
            full = jax.lax.dynamic_update_slice(full, s, (i * k,))
            full = jax.lax.psum(full, ("x",))
            return full[:p.size].reshape(p.shape)
        return jax.tree_util.tree_map(leaf, t, sl)

    def bucketed(t):
        return bucketed_gather_slices(slices_of(t), t, leaf_axes, sizes,
                                      bucket_bytes)

    specs = jax.tree_util.tree_map(lambda _: P(), params)
    a = jax.jit(_smap(per_leaf, mesh, (specs,), specs))(params)
    b = jax.jit(_smap(bucketed, mesh, (specs,), specs))(params)
    for (pa, x), (_, y) in zip(
            jax.tree_util.tree_leaves_with_path(a),
            jax.tree_util.tree_leaves_with_path(b)):
        np.testing.assert_array_equal(
            np.asarray(x.astype(jnp.float32)),
            np.asarray(y.astype(jnp.float32)), err_msg=str(pa))


@pytest.mark.parametrize("megatron_sp", [False, True])
@pytest.mark.parametrize("chunks", [2, 4])
def test_chunked_row_parallel_reduce_bitexact(megatron_sp, chunks):
    from hadoop_tpu.models.decoder import ParallelCtx
    from hadoop_tpu.ops.collective_matmul import reduce_row_parallel
    mesh = _mesh()
    y = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 32), jnp.float32)

    def run(n_chunks):
        ctx = ParallelCtx(tp_axis="x", tp_size=4,
                          megatron_sp=megatron_sp,
                          tp_overlap_chunks=n_chunks)
        out_spec = P(None, "x") if megatron_sp else P()
        prog = _smap(lambda t: reduce_row_parallel(t, ctx), mesh,
                     (P(),), out_spec)
        return np.asarray(jax.jit(prog)(y))

    np.testing.assert_array_equal(run(1), run(chunks))


# ----------------------------------------------------------------- conf

def test_overlap_from_conf_defaults_and_overrides():
    from hadoop_tpu.conf import Configuration
    assert overlap_from_conf(None) == DEFAULT_OVERLAP
    conf = Configuration(load_defaults=False)
    assert overlap_from_conf(conf) == OverlapConfig()
    conf.set("parallel.overlap.enabled", "false")
    conf.set("parallel.overlap.bucket.mb", "16")
    conf.set("parallel.overlap.tp.chunks", "8")
    conf.set("parallel.overlap.zero1.reduce-scatter", "false")
    got = overlap_from_conf(conf)
    assert got == OverlapConfig(enabled=False, bucket_mb=16, tp_chunks=8,
                                zero1_reduce_scatter=False)
    assert got.bucket_bytes == 16 << 20


# ------------------------------------------------------- full-step parity

def _run_plan_ab(plan, *, zero1=False, n_steps=3, optimizer="adamw",
                 n_microbatches=1):
    from hadoop_tpu.models import get_config
    from hadoop_tpu.parallel import make_mesh
    from hadoop_tpu.parallel.train import (init_sharded,
                                           make_data_sharding,
                                           make_train_step)
    cfg = get_config("tiny")
    mesh = make_mesh(plan)
    ds = make_data_sharding(mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    tokens = jax.device_put(tokens, ds)
    targets = jax.device_put(jnp.roll(tokens, -1, axis=1), ds)
    out = {}
    for label, ov in (("on", DEFAULT_OVERLAP), ("off", OVERLAP_OFF)):
        step = make_train_step(cfg, plan, mesh, lr=1e-2, donate=False,
                               optimizer=optimizer, zero1=zero1,
                               n_microbatches=n_microbatches,
                               overlap=ov)
        params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan,
                                   mesh, zero1=zero1)
        losses = []
        for _ in range(n_steps):
            params, opt, m = step(params, opt, tokens, targets)
            losses.append(float(m["loss"]))
        out[label] = (losses, jax.tree_util.tree_map(
            np.asarray, jax.device_get(params)))
    return out


def _assert_ab_bitexact(out):
    on_l, on_p = out["on"]
    off_l, off_p = out["off"]
    assert on_l == off_l, f"losses diverged: on={on_l} off={off_l}"
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(on_p),
            jax.tree_util.tree_leaves_with_path(off_p)):
        np.testing.assert_array_equal(a, b, err_msg=str(pa))


@requires_vma
def test_overlap_parity_dp2():
    from hadoop_tpu.parallel import MeshPlan
    _assert_ab_bitexact(_run_plan_ab(MeshPlan(dp=2)))


@requires_vma
def test_overlap_parity_dp2_tp2():
    from hadoop_tpu.parallel import MeshPlan
    _assert_ab_bitexact(_run_plan_ab(
        MeshPlan(dp=2, tp=2, megatron_sp=True)))


@requires_vma
def test_overlap_parity_zero1_dp8():
    from hadoop_tpu.parallel import MeshPlan
    _assert_ab_bitexact(_run_plan_ab(MeshPlan(dp=8), zero1=True))


@requires_vma
def test_overlap_zero1_manual_schedule_close():
    """zero1 under the manual 1F1B schedule reduce-scatters the grads;
    slice values are bitwise but the grad-NORM accumulates slice-wise,
    so the clip scale (and later losses) may move by an ulp — assert
    tight closeness, not bit equality (see parallel/overlap.py)."""
    from hadoop_tpu.parallel import MeshPlan
    out = _run_plan_ab(MeshPlan(dp=2, pp=2), zero1=True, n_steps=3,
                       n_microbatches=2)
    np.testing.assert_allclose(out["on"][0], out["off"][0], rtol=1e-6)
