"""Parallel-plan parity tests on the virtual 8-device CPU mesh.

The contract under test: every mesh plan computes the SAME training step
as the single-device reference — same loss, same updated parameters —
with the placement (tp psums, sp gathers, pp ppermute, ep all_to_all,
ring attention) being pure implementation detail. This is the compute
engine's minicluster pattern (ref: SURVEY.md §4 — real protocols,
simulated fleet).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.models import get_config, init_params
from hadoop_tpu.parallel import MeshPlan, make_mesh
from hadoop_tpu.parallel.train import (init_sharded, make_data_sharding,
                                       make_train_step)
from hadoop_tpu.parallel.optimizer import adamw_init

BATCH, SEQ = 8, 32


def _data(cfg, key=7):
    k1 = jax.random.PRNGKey(key)
    tokens = jax.random.randint(k1, (BATCH, SEQ), 0, cfg.vocab_size,
                                dtype=jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)
    return tokens, targets


def _run_plan(cfg, plan, n_steps=2, n_microbatches=1, optimizer="sgd",
              schedule="1f1b", zero1=False):
    mesh = make_mesh(plan)
    plan.validate(cfg, BATCH, SEQ, n_microbatches)
    step = make_train_step(cfg, plan, mesh, lr=1e-2,
                           n_microbatches=n_microbatches, donate=False,
                           optimizer=optimizer, pipeline_schedule=schedule,
                           zero1=zero1)
    params, opt = init_sharded(jax.random.PRNGKey(0), cfg, plan, mesh,
                               zero1=zero1 and optimizer == "adamw")
    ds = make_data_sharding(mesh)
    tokens, targets = _data(cfg)
    tokens = jax.device_put(tokens, ds)
    targets = jax.device_put(targets, ds)
    losses = []
    for _ in range(n_steps):
        params, opt, m = step(params, opt, tokens, targets)
        losses.append(float(m["loss"]))
    from hadoop_tpu.parallel.train import logical_layer_order
    params = logical_layer_order(params, cfg, plan)  # undo vpp placement
    gathered = jax.tree_util.tree_map(np.asarray, jax.device_get(params))
    return losses, gathered


def _assert_tree_close(a, b, rtol=2e-4, atol=2e-4):
    flat_a = jax.tree_util.tree_leaves_with_path(a)
    flat_b = dict(jax.tree_util.tree_leaves_with_path(b))
    for path, leaf in flat_a:
        other = flat_b[path]
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(other), rtol=rtol, atol=atol,
            err_msg=f"mismatch at {jax.tree_util.keystr(path)}")


@pytest.fixture(scope="module")
def reference_dense():
    cfg = get_config("tiny")
    return _run_plan(cfg, MeshPlan())


def test_single_device_plan_trains(reference_dense):
    losses, _ = reference_dense
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_adamw_trains():
    cfg = get_config("tiny")
    losses, _ = _run_plan(cfg, MeshPlan(), n_steps=5, optimizer="adamw")
    assert losses[-1] < losses[0]


def test_zero1_matches_replicated_adamw():
    """ZeRO-1 slice-partitioned AdamW == replicated AdamW, elementwise
    (same grads → same update; only the state layout differs)."""
    cfg = get_config("tiny")
    ref_losses, ref_params = _run_plan(cfg, MeshPlan(dp=8), n_steps=3,
                                       optimizer="adamw")
    z_losses, z_params = _run_plan(cfg, MeshPlan(dp=8), n_steps=3,
                                   optimizer="adamw", zero1=True)
    np.testing.assert_allclose(z_losses, ref_losses, rtol=1e-5)
    _assert_tree_close(z_params, ref_params, rtol=1e-5, atol=1e-6)


def test_zero1_with_model_parallelism():
    """ZeRO-1 composes with tp/pp: state for tp-sharded leaves partitions
    over dp only; replicated leaves over dp as well."""
    cfg = get_config("tiny")
    ref_losses, ref_params = _run_plan(cfg, MeshPlan(dp=2, pp=2, tp=2),
                                       n_steps=3, optimizer="adamw")
    z_losses, z_params = _run_plan(cfg, MeshPlan(dp=2, pp=2, tp=2),
                                   n_steps=3, optimizer="adamw", zero1=True)
    np.testing.assert_allclose(z_losses, ref_losses, rtol=1e-5)
    _assert_tree_close(z_params, ref_params, rtol=1e-5, atol=1e-6)


def test_zero1_state_memory_is_sharded():
    """Per-rank moment memory ÷ dp: the global ZeRO-1 state is ~the same
    total size as replicated state's PER-RANK size (i.e. dp ranks hold
    1/dp each instead of a copy each)."""
    from hadoop_tpu.parallel.train import zero1_layout
    cfg = get_config("tiny")
    plan = MeshPlan(dp=8)
    _, shapes, _, _ = zero1_layout(cfg, plan)
    total_state = sum(
        int(np.prod(s)) for s in jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda sh: int(np.prod(sh)), shapes,
                                   is_leaf=lambda x: isinstance(x, tuple))))
    n_params = sum(int(np.prod(p.shape)) for p in
                   jax.tree_util.tree_leaves(
                       init_params(jax.random.PRNGKey(0),
                                   get_config("tiny"))))
    # global state ≈ n_params (+ padding slack), NOT dp * n_params
    assert total_state < n_params * 1.1


def test_dp_tp_parity(reference_dense):
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(dp=2, tp=2))
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_dp_pp_tp_parity_1f1b(reference_dense):
    """pp runs the manual 1F1B schedule by default (parallel.pipeline)."""
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(dp=2, pp=2, tp=2),
                               n_microbatches=2)
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_dp_pp_tp_parity_gpipe(reference_dense):
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(dp=2, pp=2, tp=2),
                               n_microbatches=2, schedule="gpipe")
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_pp4_deep_pipeline_1f1b(reference_dense):
    """pp=4 with M=4: warmup/steady/drain phases all exercised."""
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(pp=4), n_microbatches=4)
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_sequence_parallel_parity(reference_dense):
    cfg = get_config("tiny")
    losses, params = _run_plan(
        cfg, MeshPlan(dp=2, pp=2, tp=2, megatron_sp=True),
        n_microbatches=2)
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_ring_attention_parity(reference_dense):
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(dp=2, sp=4))
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_moe_ep_parity():
    cfg = get_config("tiny-moe", capacity_factor=4.0)
    ref_losses, ref_params = _run_plan(cfg, MeshPlan())
    losses, params = _run_plan(cfg, MeshPlan(dp=2, ep=2, tp=2))
    assert ref_losses[-1] < ref_losses[0]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_plan_validation_rejects_bad_shapes():
    cfg = get_config("tiny")
    with pytest.raises(ValueError):
        MeshPlan(dp=2, tp=3).validate(cfg, BATCH, SEQ)
    # sp now composes with tp/pp; the remaining exclusions:
    with pytest.raises(ValueError):
        MeshPlan(sp=2, tp=2, megatron_sp=True)
    with pytest.raises(ValueError):
        MeshPlan(sp=2, ep=2)
    with pytest.raises(ValueError):
        MeshPlan(megatron_sp=True)


def test_interleaved_1f1b_parity(reference_dense):
    """Interleaved schedule (vpp=2 virtual stages/rank) computes the
    SAME step as the single-device reference (ref: Megatron-LM's
    virtual-pipeline interleave)."""
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(pp=2, vpp=2),
                               n_microbatches=4, schedule="interleaved")
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_interleaved_matches_plain_1f1b():
    """Same plan, both manual schedules — bitwise-equivalent math up to
    reduction order."""
    cfg = get_config("tiny")
    plain, plain_params = _run_plan(cfg, MeshPlan(pp=2),
                                    n_microbatches=4, schedule="1f1b")
    inter, inter_params = _run_plan(cfg, MeshPlan(pp=2, vpp=2),
                                    n_microbatches=4,
                                    schedule="interleaved")
    np.testing.assert_allclose(inter, plain, rtol=1e-4)
    _assert_tree_close(inter_params, plain_params)


def test_interleaved_with_dp_tp(reference_dense):
    """Interleaved composes with dp×tp on 8 devices."""
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(dp=2, pp=2, tp=2, vpp=2),
                               n_microbatches=4, schedule="interleaved")
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_interleaved_microbatch_divisibility():
    """M % pp != 0 is rejected (the reference imposes the same)."""
    cfg = get_config("tiny")
    with pytest.raises(ValueError, match="divisible by pp"):
        _run_plan(cfg, MeshPlan(pp=2, vpp=2), n_microbatches=1,
                  schedule="interleaved")


def test_ulysses_attention_parity(reference_dense):
    """All-to-all context parallelism computes the SAME step as the
    single-device reference (the DeepSpeed-Ulysses shape on
    lax.all_to_all; SURVEY §5.7's second SP strategy)."""
    cfg = get_config("tiny")
    # sp=2: tiny's GQA (4 q / 2 kv heads) splits both head counts
    losses, params = _run_plan(cfg, MeshPlan(dp=4, sp=2,
                                             sp_mode="ulysses"))
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_ulysses_matches_ring():
    """Both SP strategies are the same math on the same plan."""
    cfg = get_config("tiny")
    ring_losses, ring_params = _run_plan(cfg, MeshPlan(dp=4, sp=2))
    uly_losses, uly_params = _run_plan(cfg, MeshPlan(dp=4, sp=2,
                                                     sp_mode="ulysses"))
    np.testing.assert_allclose(uly_losses, ring_losses, rtol=1e-5)
    _assert_tree_close(uly_params, ring_params)


def test_ulysses_validation_rejects_indivisible_heads():
    cfg = get_config("tiny")  # n_heads must not divide by 3... use sp=8
    import pytest as _pytest
    bad_sp = 8 if cfg.n_heads % 8 != 0 else 16
    with _pytest.raises(ValueError, match="heads"):
        MeshPlan(dp=1, sp=bad_sp, sp_mode="ulysses").validate(
            cfg, BATCH, max(SEQ, bad_sp * 8))


def test_ring_composes_with_tp(reference_dense):
    """sp x tp: context parallelism with tensor-parallel weights in the
    same step (previously restricted to sp x dp)."""
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(dp=2, tp=2, sp=2))
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_ring_composes_with_pp(reference_dense):
    cfg = get_config("tiny")
    losses, params = _run_plan(cfg, MeshPlan(dp=2, pp=2, sp=2),
                               n_microbatches=2)
    ref_losses, ref_params = reference_dense
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    _assert_tree_close(params, ref_params)


def test_ulysses_composes_with_tp():
    # tp=2 halves head counts to 2q/1kv; sp=2 needs both divisible — 2/1
    # fails kv, so validate() must reject ulysses here and ring covers it
    with pytest.raises(ValueError, match="heads"):
        MeshPlan(dp=2, tp=2, sp=2, sp_mode="ulysses").validate(
            get_config("tiny"), BATCH, SEQ)
