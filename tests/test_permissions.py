"""Namespace permission ENFORCEMENT (ref test model: hadoop-hdfs
TestDFSPermission.java / FSPermissionChecker tests): the stored
owner/group/mode bits must gate reads, writes, traversal, and
admin ops for non-superusers — not just be recorded.
"""

import pytest

from hadoop_tpu.security.ugi import AccessControlError, UserGroupInformation
from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf


@pytest.fixture(scope="module")
def cluster():
    conf = fast_conf()
    # group membership is resolved SERVER-side (security/groups.py) —
    # a client asserting groups=["supergroup"] must get nothing from it
    conf.set("hadoop.security.group.mapping.static.mapping",
             "carol=eng;opsadmin=supergroup")
    with MiniDFSCluster(num_datanodes=2, conf=conf) as c:
        c.wait_active()
        yield c


@pytest.fixture(scope="module")
def root_fs(cluster):
    fs = cluster.get_filesystem()
    # world-writable scratch + a private tree, set up by the superuser
    fs.mkdirs("/open")
    fs.set_permission("/open", 0o777)
    fs.write_all("/open/readable.txt", b"anyone")
    fs.set_permission("/open/readable.txt", 0o644)
    fs.write_all("/open/secret.txt", b"root only")
    fs.set_permission("/open/secret.txt", 0o600)
    fs.mkdirs("/private")
    fs.set_permission("/private", 0o700)
    fs.write_all("/private/inner.txt", b"hidden")
    return fs


def _as(user, fn):
    return UserGroupInformation.create_remote_user(user).do_as(fn)


def test_mode_bits_gate_reads(cluster, root_fs):
    alice = UserGroupInformation.create_remote_user("alice")
    fs = alice.do_as(cluster.get_filesystem)
    assert alice.do_as(lambda: fs.read_all("/open/readable.txt")) == \
        b"anyone"
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs.read_all("/open/secret.txt"))


def test_traverse_gates_everything_below(cluster, root_fs):
    alice = UserGroupInformation.create_remote_user("alice")
    fs = alice.do_as(cluster.get_filesystem)
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs.read_all("/private/inner.txt"))
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs.list_status("/private"))


def test_parent_write_gates_create_and_delete(cluster, root_fs):
    alice = UserGroupInformation.create_remote_user("alice")
    fs = alice.do_as(cluster.get_filesystem)
    # /open is 777 → create allowed
    alice.do_as(lambda: fs.write_all("/open/alice.txt", b"hi"))
    assert alice.do_as(
        lambda: fs.read_all("/open/alice.txt")) == b"hi"
    # root-owned 755 dir → no write for alice
    root_fs.mkdirs("/rootdir")
    root_fs.set_permission("/rootdir", 0o755)
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs.write_all("/rootdir/nope.txt", b"x"))
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs.mkdirs("/rootdir/sub"))
    # delete requires WRITE on the PARENT, not the file
    root_fs.write_all("/rootdir/owned.txt", b"r")
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs.delete("/rootdir/owned.txt"))


def test_owner_and_superuser_gates_admin_ops(cluster, root_fs):
    alice = UserGroupInformation.create_remote_user("alice")
    fs = alice.do_as(cluster.get_filesystem)
    with pytest.raises(AccessControlError):
        alice.do_as(
            lambda: fs.set_permission("/open/readable.txt", 0o777))
    with pytest.raises(AccessControlError):
        alice.do_as(
            lambda: fs.set_owner("/open/readable.txt", "alice", "users"))
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs.client.nn.set_quota("/open", 10, -1))
    # alice CAN chmod her own file
    alice.do_as(lambda: fs.write_all("/open/mine.txt", b"m"))
    alice.do_as(lambda: fs.set_permission("/open/mine.txt", 0o600))
    # ...which root still reads (superuser bypass)
    assert root_fs.read_all("/open/mine.txt") == b"m"


def test_named_acl_entry_grants_access(cluster, root_fs):
    root_fs.write_all("/open/acl.txt", b"acl-gated")
    root_fs.set_permission("/open/acl.txt", 0o600)
    alice = UserGroupInformation.create_remote_user("alice")
    bob = UserGroupInformation.create_remote_user("bob")
    fs_a = alice.do_as(cluster.get_filesystem)
    fs_b = bob.do_as(cluster.get_filesystem)
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs_a.read_all("/open/acl.txt"))
    root_fs.set_acl("/open/acl.txt", ["user:alice:r--"])
    assert alice.do_as(
        lambda: fs_a.read_all("/open/acl.txt")) == b"acl-gated"
    with pytest.raises(AccessControlError):
        bob.do_as(lambda: fs_b.read_all("/open/acl.txt"))


def test_group_bits_apply(cluster, root_fs):
    root_fs.write_all("/open/grp.txt", b"group-readable")
    root_fs.set_permission("/open/grp.txt", 0o640)
    root_fs.set_owner("/open/grp.txt", "root", "eng")
    member = UserGroupInformation.create_remote_user("carol")
    outsider = UserGroupInformation.create_remote_user("dave")
    fs_m = member.do_as(cluster.get_filesystem)
    fs_o = outsider.do_as(cluster.get_filesystem)
    assert member.do_as(
        lambda: fs_m.read_all("/open/grp.txt")) == b"group-readable"
    with pytest.raises(AccessControlError):
        outsider.do_as(lambda: fs_o.read_all("/open/grp.txt"))


def test_supergroup_members_bypass_but_asserted_groups_do_not(
        cluster, root_fs):
    # opsadmin is in supergroup per the SERVER's static mapping
    admin = UserGroupInformation.create_remote_user("opsadmin")
    fs = admin.do_as(cluster.get_filesystem)
    assert admin.do_as(
        lambda: fs.read_all("/private/inner.txt")) == b"hidden"
    # mallory CLAIMS supergroup client-side; the server's mapping says
    # otherwise — asserted groups must carry no authority
    mallory = UserGroupInformation("mallory", groups=["supergroup"])
    fs_m = mallory.do_as(cluster.get_filesystem)
    with pytest.raises(AccessControlError):
        mallory.do_as(lambda: fs_m.read_all("/private/inner.txt"))


def test_enforcement_can_be_disabled(tmp_path):
    conf = fast_conf()
    conf.set("dfs.permissions.enabled", "false")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as c:
        c.wait_active()
        fs = c.get_filesystem()
        fs.write_all("/s.txt", b"x")
        fs.set_permission("/s.txt", 0o600)
        alice = UserGroupInformation.create_remote_user("alice")
        fs_a = alice.do_as(c.get_filesystem)
        assert alice.do_as(lambda: fs_a.read_all("/s.txt")) == b"x"


def test_sticky_bit_protects_entries_in_shared_dirs(cluster, root_fs):
    """1777 shared dirs (the staging-root shape): anyone may create,
    but only an entry's owner (or the dir owner) may delete/rename it
    (ref: FSPermissionChecker.checkStickyBit)."""
    root_fs.mkdirs("/shared")
    root_fs.set_permission("/shared", 0o1777)
    alice = UserGroupInformation.create_remote_user("alice")
    bob = UserGroupInformation.create_remote_user("bob")
    fs_a = alice.do_as(cluster.get_filesystem)
    fs_b = bob.do_as(cluster.get_filesystem)
    alice.do_as(lambda: fs_a.write_all("/shared/af.txt", b"a"))
    with pytest.raises(AccessControlError):
        bob.do_as(lambda: fs_b.delete("/shared/af.txt"))
    with pytest.raises(AccessControlError):
        bob.do_as(lambda: fs_b.rename("/shared/af.txt", "/shared/bf"))
    assert alice.do_as(lambda: fs_a.delete("/shared/af.txt"))
    # without sticky, parent-write suffices for anyone
    root_fs.set_permission("/shared", 0o777)
    alice.do_as(lambda: fs_a.write_all("/shared/af2.txt", b"a"))
    assert bob.do_as(lambda: fs_b.delete("/shared/af2.txt"))


def test_recursive_delete_requires_subtree_access(cluster, root_fs):
    """A 0700 subdir inside a world-writable dir must survive another
    user's recursive delete of it (ref: FSPermissionChecker
    checkSubAccess on recursive delete)."""
    alice = UserGroupInformation.create_remote_user("alice")
    bob = UserGroupInformation.create_remote_user("bob")
    fs_a = alice.do_as(cluster.get_filesystem)
    fs_b = bob.do_as(cluster.get_filesystem)
    alice.do_as(lambda: fs_a.mkdirs("/open/adir"))
    alice.do_as(lambda: fs_a.write_all("/open/adir/private.txt", b"p"))
    alice.do_as(lambda: fs_a.set_permission("/open/adir", 0o700))
    with pytest.raises(AccessControlError):
        bob.do_as(lambda: fs_b.delete("/open/adir", recursive=True))
    assert alice.do_as(
        lambda: fs_a.read_all("/open/adir/private.txt")) == b"p"


def test_reserved_xattr_namespaces_are_superuser_only(cluster, root_fs):
    alice = UserGroupInformation.create_remote_user("alice")
    fs_a = alice.do_as(cluster.get_filesystem)
    alice.do_as(lambda: fs_a.write_all("/open/x.txt", b"x"))
    alice.do_as(  # user namespace: fine on her own file
        lambda: fs_a.set_xattr("/open/x.txt", "user.tag", b"v"))
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs_a.set_xattr(
            "/open/x.txt", "system.crypto.edek", b"forged"))
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs_a.set_xattr(
            "/open/x.txt", "trusted.prov", b"forged"))


def test_webhdfs_rest_door_honors_permissions(cluster, root_fs):
    """The REST face executes as the pseudo-auth caller (doAs), not the
    NameNode process user: dr.who (no user.name) cannot read a 0600
    file — including via OPEN's lazy streamed body, which the HTTP
    server consumes after the handler's do_as scope ended — while
    user.name=alice works exactly where RPC-alice works."""
    import json as _json
    import urllib.error
    import urllib.request

    root_fs.write_all("/open/rest.txt", b"rest-gated")
    root_fs.set_permission("/open/rest.txt", 0o600)
    root_fs.set_acl("/open/rest.txt", ["user:alice:r--"])
    base = (f"http://127.0.0.1:{cluster.namenode.http.port}"
            f"/webhdfs/v1/open/rest.txt")
    # anonymous OPEN: denied (403), even though the body is streamed
    with pytest.raises(urllib.error.HTTPError) as denied:
        urllib.request.urlopen(f"{base}?op=OPEN").read()
    assert denied.value.code == 403
    # the ACL-granted identity reads it
    got = urllib.request.urlopen(
        f"{base}?op=OPEN&user.name=alice").read()
    assert got == b"rest-gated"
    # stat as anonymous works (644-style traverse on /open), but
    # a write as anonymous into a root-owned dir does not
    st = _json.loads(urllib.request.urlopen(
        f"{base}?op=GETFILESTATUS&user.name=alice").read())
    assert st["FileStatus"]["length"] == len(b"rest-gated")


def test_snapshot_paths_enforce_permissions(cluster, root_fs):
    """The checker's .snapshot traversal branch: captured subtrees carry
    the permissions they had at capture, and a non-owner is denied
    through the snapshot path exactly as through the live one."""
    root_fs.mkdirs("/snapperm")
    root_fs.set_permission("/snapperm", 0o755)
    root_fs.write_all("/snapperm/priv.txt", b"s")
    root_fs.set_permission("/snapperm/priv.txt", 0o600)
    root_fs.write_all("/snapperm/open.txt", b"o")
    root_fs.set_permission("/snapperm/open.txt", 0o644)
    root_fs.allow_snapshot("/snapperm")
    root_fs.create_snapshot("/snapperm", "s1")

    # flip the LIVE permissions after capture: the snapshot path must
    # keep answering with the CAPTURED bits, proving resolution goes
    # through the frozen copy rather than the live inode
    root_fs.set_permission("/snapperm/priv.txt", 0o644)
    root_fs.set_permission("/snapperm/open.txt", 0o600)

    alice = UserGroupInformation.create_remote_user("alice")
    fs_a = alice.do_as(cluster.get_filesystem)
    assert alice.do_as(
        lambda: fs_a.read_all("/snapperm/.snapshot/s1/open.txt")) == b"o"
    with pytest.raises(AccessControlError):
        alice.do_as(
            lambda: fs_a.read_all("/snapperm/.snapshot/s1/priv.txt"))
    # and the live paths answer with the NEW bits
    assert alice.do_as(
        lambda: fs_a.read_all("/snapperm/priv.txt")) == b"s"
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: fs_a.read_all("/snapperm/open.txt"))


def test_iter_as_caller_captures_identity_eagerly():
    """iter_as_caller must capture the caller's UGI when CALLED (inside
    the handler's do_as), not at first next() — a generator-function
    version would evaluate current_user() after do_as reset the
    context and silently run the stream as the daemon user (review
    finding on the WebHDFS OPEN path)."""
    from hadoop_tpu.dfs.webhdfs import iter_as_caller
    from hadoop_tpu.security.ugi import UserGroupInformation, current_user

    seen = []

    def producer():
        for _ in range(3):
            seen.append(current_user().user_name)
            yield b"x"

    alice = UserGroupInformation.create_remote_user("alice")
    wrapped = alice.do_as(lambda: iter_as_caller(producer()))
    # consumed OUTSIDE do_as — the capture must already have happened
    assert list(wrapped) == [b"x"] * 3
    assert seen == ["alice"] * 3


def test_group_mapping_static_precedence_and_isolation():
    """security/groups.py: the static conf mapping outranks OS lookup,
    unknown users resolve to no groups (never an error), and results
    are copies (a caller mutating the list must not poison the map)."""
    from hadoop_tpu.conf import Configuration
    from hadoop_tpu.security.groups import Groups

    conf = Configuration(load_defaults=False)
    conf.set("hadoop.security.group.mapping.static.mapping",
             "alice=eng,ops; bob=eng")
    g = Groups(conf)
    assert g.groups_for("alice") == ["eng", "ops"]
    assert g.groups_for("bob") == ["eng"]
    assert g.groups_for("no-such-user-xyz") == []
    got = g.groups_for("alice")
    got.append("supergroup")
    assert "supergroup" not in g.groups_for("alice")


def test_intermediate_file_component_is_not_found_not_denied(
        cluster, root_fs):
    """A path THROUGH a regular file (/open/secret.txt/sub) must resolve
    as target-not-found — not apply the target bits to the intermediate
    file inode and fail with AccessControlError (ADVICE round 5; the
    reference resolves this as an invalid path)."""
    alice = UserGroupInformation.create_remote_user("alice")
    fs = alice.do_as(cluster.get_filesystem)
    # secret.txt is 0600 root-owned: pre-fix this raised
    # AccessControlError from the READ check on the file inode
    with pytest.raises(FileNotFoundError):
        alice.do_as(lambda: fs.read_all("/open/secret.txt/sub"))
    with pytest.raises(FileNotFoundError):
        alice.do_as(lambda: fs.get_file_status("/open/secret.txt/sub"))


def test_owner_can_chgrp_to_own_group(cluster, root_fs):
    """Reference chgrp parity (FSDirAttrOp.setOwner): a file's owner may
    change its group to a group they belong to (server-resolved); owner
    changes stay superuser-only (ADVICE round 5)."""
    carol = UserGroupInformation.create_remote_user("carol")  # eng
    fs = carol.do_as(cluster.get_filesystem)
    carol.do_as(lambda: fs.write_all("/open/carol.txt", b"c"))
    # owner chgrp into her own (statically mapped) group: allowed
    carol.do_as(lambda: fs.set_owner("/open/carol.txt", "", "eng"))
    assert root_fs.get_file_status("/open/carol.txt").group == "eng"
    # a group she does NOT belong to: denied
    with pytest.raises(AccessControlError):
        carol.do_as(lambda: fs.set_owner("/open/carol.txt", "", "wheel"))
    # changing the OWNER is still superuser territory
    with pytest.raises(AccessControlError):
        carol.do_as(lambda: fs.set_owner("/open/carol.txt", "alice", ""))
    # a non-owner cannot chgrp someone else's file even to a group of
    # theirs
    alice = UserGroupInformation.create_remote_user("alice")
    afs = alice.do_as(cluster.get_filesystem)
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: afs.set_owner("/open/carol.txt", "", "eng"))
    # and the superuser chowns freely, as before
    root_fs.set_owner("/open/carol.txt", "alice", "users")
    st = root_fs.get_file_status("/open/carol.txt")
    assert (st.owner, st.group) == ("alice", "users")
    # set_owner on an untraversable path is denied at traversal and
    # must not leak the inode's existence or owner
    root_fs.mkdirs("/chgrp-locked")
    root_fs.set_permission("/chgrp-locked", 0o700)
    root_fs.write_all("/chgrp-locked/f", b"x")
    with pytest.raises(AccessControlError) as ei:
        carol.do_as(lambda: fs.set_owner("/chgrp-locked/f", "", "eng"))
    assert "is not the owner" not in str(ei.value)
