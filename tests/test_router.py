"""Router-based federation: one client namespace over two nameservices.
Ref: hadoop-hdfs-rbf federation/router/Router.java:82,
RouterRpcServer's ClientProtocol face, MountTableResolver, dfsrouteradmin."""

import os

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.dfs.client.filesystem import DistributedFileSystem
from hadoop_tpu.dfs.router import MountTable, Router
from hadoop_tpu.testing.minicluster import MiniDFSCluster


@pytest.fixture(scope="module")
def federation(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("rbf")
    ns1 = MiniDFSCluster(num_datanodes=2, base_dir=str(tmp / "ns1"))
    ns2 = MiniDFSCluster(num_datanodes=2, base_dir=str(tmp / "ns2"))
    ns1.start()
    ns2.start()
    conf = Configuration(load_defaults=False)
    conf.set("dfs.federation.ns.ns1",
             f"127.0.0.1:{ns1.namenode.port}")
    conf.set("dfs.federation.ns.ns2",
             f"127.0.0.1:{ns2.namenode.port}")
    router = Router(conf, state_dir=str(tmp / "router"))
    router.init(conf)
    router.start()
    router.mounts.add("/warm", "ns1", "/")
    router.mounts.add("/cold", "ns2", "/archive")
    yield router, ns1, ns2
    router.stop()
    ns1.shutdown()
    ns2.shutdown()


@pytest.fixture(scope="module")
def rfs(federation):
    router, _, _ = federation
    fs = DistributedFileSystem([("127.0.0.1", router.port)],
                               Configuration(load_defaults=False))
    yield fs
    fs.close()


def test_mount_table_resolution():
    mt = MountTable()
    mt.add("/a", "ns1", "/")
    mt.add("/a/deep", "ns2", "/d")
    assert mt.resolve("/a/x.txt") == ("ns1", "/x.txt", "/a")
    assert mt.resolve("/a/deep/y") == ("ns2", "/d/y", "/a/deep")
    assert mt.resolve("/other") is None
    assert mt.children_at("") == ["a"]
    assert mt.children_at("/a") == ["deep"]


def test_federated_read_write_through_router(federation, rfs):
    router, ns1, ns2 = federation
    rfs.mkdirs("/warm/data")
    with rfs.create("/warm/data/f.bin") as out:
        out.write(b"warm-bytes" * 1000)
    with rfs.create("/cold/old.bin") as out:
        out.write(b"cold-bytes")
    # data landed in the RIGHT backing nameservice, at remapped paths
    fs1 = ns1.get_filesystem()
    fs2 = ns2.get_filesystem()
    assert fs1.read_all("/data/f.bin") == b"warm-bytes" * 1000
    assert fs2.read_all("/archive/old.bin") == b"cold-bytes"
    assert not fs1.exists("/archive/old.bin")
    # reads through the router
    assert rfs.read_all("/warm/data/f.bin") == b"warm-bytes" * 1000
    assert rfs.read_all("/cold/old.bin") == b"cold-bytes"
    # listing paths come back ROUTER-side
    names = [s.path for s in rfs.list_status("/warm/data")]
    assert names == ["/warm/data/f.bin"]
    st = rfs.get_file_status("/cold/old.bin")
    assert st.path == "/cold/old.bin" and st.length == 10


def test_synthetic_root_listing(rfs):
    names = sorted(s.path for s in rfs.list_status("/"))
    assert names == ["/cold", "/warm"]
    assert all(s.is_dir for s in rfs.list_status("/"))
    st = rfs.get_file_status("/")
    assert st.is_dir


def test_rename_within_and_across_nameservices(federation, rfs):
    rfs.mkdirs("/warm/mv")
    rfs.write_all("/warm/mv/a.txt", b"x")
    assert rfs.rename("/warm/mv/a.txt", "/warm/mv/b.txt")
    assert rfs.read_all("/warm/mv/b.txt") == b"x"
    with pytest.raises(Exception):
        rfs.rename("/warm/mv/b.txt", "/cold/b.txt")  # crosses ns1 -> ns2


def test_no_mount_no_default_fails(rfs):
    with pytest.raises(Exception):
        rfs.mkdirs("/unmounted/x")


def test_router_admin_protocol(federation):
    router, _, _ = federation
    from hadoop_tpu.conf import Configuration as C
    from hadoop_tpu.ipc import Client, get_proxy
    client = Client(C(load_defaults=False))
    try:
        admin = get_proxy("RouterAdminProtocol",
                          ("127.0.0.1", router.port), client=client)
        assert admin.add_mount("/tmp-mount", "ns1", "/tmpdata")
        assert "/tmp-mount" in admin.list_mounts()
        with pytest.raises(Exception):
            admin.add_mount("/bad", "nope", "/")
        assert admin.remove_mount("/tmp-mount")
        assert "/tmp-mount" not in admin.list_mounts()
    finally:
        client.stop()


def test_mount_table_persists(federation, tmp_path):
    router, _, _ = federation
    mt2 = MountTable(os.path.join(router.state_dir, "mounts.json"))
    assert "/warm" in mt2.entries() and "/cold" in mt2.entries()


def test_quota_aggregation_across_namespaces(federation, rfs):
    """Mount quotas aggregate usage from BOTH nameservices: the router's
    content_summary above the mounts sums ns1+ns2, and a mount-level
    quota is enforced at the router (ref: RouterQuotaManager +
    RouterQuotaUpdateService)."""
    router, ns1, ns2 = federation
    rfs.mkdirs("/warm/qa")
    rfs.write_all("/warm/qa/a.bin", b"x" * 10_000)
    rfs.mkdirs("/cold/qb")
    rfs.write_all("/cold/qb/b.bin", b"y" * 20_000)

    # aggregated summary above the mounts spans both nameservices
    cs = rfs.client.nn.content_summary("/")
    assert cs["length"] >= 30_000
    assert cs["files"] >= 2

    # namespace quota on /warm: already at/above 2 inodes → next create
    # through the router is rejected
    router.set_mount_quota("/warm", nsquota=1)
    router.refresh_quota_usage()
    from hadoop_tpu.dfs.protocol.records import QuotaExceededError
    with pytest.raises((QuotaExceededError, IOError),
                       match="quota exceeded"):
        rfs.write_all("/warm/qa/more.bin", b"z")
    # /cold is unaffected
    rfs.write_all("/cold/qb/ok.bin", b"ok")
    # lift the quota; writes resume
    router.set_mount_quota("/warm", nsquota=-1, ssquota=-1)
    router.refresh_quota_usage()
    rfs.write_all("/warm/qa/more.bin", b"z")


def test_membership_state_store(federation):
    """The router heartbeats nameservice membership into its State
    Store (ref: NamenodeHeartbeatService → MembershipState records)."""
    import time
    router, ns1, ns2 = federation
    deadline = time.monotonic() + 15
    membership = {}
    while time.monotonic() < deadline:
        membership = router.store.load("membership")
        if {"ns1", "ns2"} <= set(membership):
            break
        time.sleep(0.3)
    assert {"ns1", "ns2"} <= set(membership)
    assert membership["ns1"]["state"] in ("active", "standby")
    assert membership["ns2"]["last_seen"] > 0


def test_quota_survives_router_restart(federation, tmp_path):
    """Quotas are State-Store records: a new Router over the same store
    dir sees them (ref: mount-table records persisting quota)."""
    router, ns1, ns2 = federation
    router.set_mount_quota("/cold", ssquota=1 << 40)
    conf = Configuration(load_defaults=False)
    conf.set("dfs.federation.ns.ns1", f"127.0.0.1:{ns1.namenode.port}")
    conf.set("dfs.federation.ns.ns2", f"127.0.0.1:{ns2.namenode.port}")
    r2 = Router(conf, state_dir=router.state_dir)
    try:
        assert r2.quotas.get("/cold", {}).get("ssquota") == 1 << 40
    finally:
        router.set_mount_quota("/cold", nsquota=-1, ssquota=-1)


def test_router_forwards_caller_identity(federation, rfs):
    """End-to-end identity lock through the router hop: the RPC
    server's do_as dispatch + per-call client user resolution must keep
    carrying the caller to the downstream NameNode (a refactor that
    pins the forwarding connection to the router's own user would pass
    every other router test — the data still flows — while silently
    bypassing downstream permission enforcement)."""
    from hadoop_tpu.security.ugi import (AccessControlError,
                                         UserGroupInformation)
    router, ns1, ns2 = federation
    fs1 = ns1.get_filesystem()
    fs1.mkdirs("/private")
    fs1.set_permission("/private", 0o700)
    fs1.write_all("/private/s.txt", b"locked")
    fs1.mkdirs("/pub")
    fs1.set_permission("/pub", 0o777)

    alice = UserGroupInformation.create_remote_user("alice")
    arfs = alice.do_as(lambda: DistributedFileSystem(
        [("127.0.0.1", router.port)],
        Configuration(load_defaults=False)))
    with pytest.raises(AccessControlError):
        alice.do_as(lambda: arfs.read_all("/warm/private/s.txt"))
    alice.do_as(lambda: arfs.write_all("/warm/pub/a.txt", b"hi"))
    # ...and the downstream file is OWNED by alice, not the router user
    assert fs1.get_file_status("/pub/a.txt").owner == "alice"
    # the superuser still reads through the router
    assert rfs.read_all("/warm/private/s.txt") == b"locked"


def test_secured_router_builds_proxy_chain(federation, monkeypatch):
    """A SECURED router forwards as effective=caller over real=router
    login (the caller has no SASL credentials at the router), and a
    secured router without a keytab login refuses to construct."""
    from hadoop_tpu.dfs.router import router as rmod
    from hadoop_tpu.security.ugi import UserGroupInformation

    router, _, _ = federation

    class _Ctx:
        user = UserGroupInformation.create_remote_user("alice")

    monkeypatch.setattr("hadoop_tpu.ipc.server.current_call",
                        lambda: _Ctx())
    monkeypatch.setattr(router, "secured", True)
    fwd = rmod._forwarding_ugi(router)
    assert fwd is not None
    assert fwd.user_name == "alice"
    assert fwd.real_user is not None and \
        fwd.real_user.user_name == \
        UserGroupInformation.get_login_user().user_name
    monkeypatch.setattr(router, "secured", False)
    assert rmod._forwarding_ugi(router) is None

    conf = Configuration(load_defaults=False)
    conf.set("hadoop.security.authentication", "sasl")
    with pytest.raises(ValueError, match="keytab"):
        Router(conf, state_dir="/tmp/htpu-router-secured-test")
