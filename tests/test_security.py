"""SASL-analog mutual auth + wire privacy.

Mirrors the reference's security tests (ref: hadoop-common
TestSaslRPC.java — every (client auth, server auth, QoP) combination
over live RPC; TestMiniKdc.java — principal provisioning). Handshake
units run the sessions directly; the live tests cross real sockets.
"""

import threading

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.ipc import Client, Server
from hadoop_tpu.ipc.errors import FatalRpcError
from hadoop_tpu.security.sasl import (MECH_SCRAM, MECH_TOKEN, QOP_PRIVACY,
                                      CredentialStore, SaslClientSession,
                                      SaslServerSession, WireCipher,
                                      scram_verifier)
from hadoop_tpu.security.ugi import (AccessControlError, SecretManager,
                                     UserGroupInformation)
from hadoop_tpu.testing.minikdc import MiniKdc


# ------------------------------------------------------------ handshake units

def _run_handshake(client, server):
    msg = client.initiate()
    challenge = server.step(msg)
    response = client.step(challenge)
    success = server.step(response)
    assert client.step(success) is None


def test_scram_mutual_auth_and_key_agreement():
    store = CredentialStore()
    store.add_principal("alice", b"s3cret")
    srv = SaslServerSession(store, required_qop=QOP_PRIVACY)
    cli = SaslClientSession(MECH_SCRAM, user="alice", password=b"s3cret",
                            qop=QOP_PRIVACY)
    _run_handshake(cli, srv)
    assert srv.complete and cli.complete
    assert srv.user == "alice"
    # Both sides derived the same wire keys: a frame wrapped by one is
    # unwrapped by the other, both directions.
    assert cli.cipher.unwrap(srv.cipher.wrap(b"from server")) \
        == b"from server"
    assert srv.cipher.unwrap(cli.cipher.wrap(b"from client")) \
        == b"from client"


def test_scram_wrong_password_rejected():
    store = CredentialStore()
    store.add_principal("alice", b"s3cret")
    srv = SaslServerSession(store)
    cli = SaslClientSession(MECH_SCRAM, user="alice", password=b"WRONG")
    challenge = srv.step(cli.initiate())
    with pytest.raises(AccessControlError, match="authentication failed"):
        srv.step(cli.step(challenge))
    assert not srv.complete


def test_scram_unknown_principal_rejected():
    srv = SaslServerSession(CredentialStore())
    cli = SaslClientSession(MECH_SCRAM, user="mallory", password=b"x")
    with pytest.raises(AccessControlError, match="unknown principal"):
        srv.step(cli.initiate())


def test_impostor_server_fails_mutual_proof():
    """A server that doesn't know the verifier cannot fake the server
    proof — the CLIENT aborts (the mutual leg; ref: SASL mutual auth)."""
    real = CredentialStore()
    real.add_principal("alice", b"s3cret")
    fake = CredentialStore()
    fake.add_principal("alice", b"guessed-wrong")
    srv = SaslServerSession(fake)
    cli = SaslClientSession(MECH_SCRAM, user="alice", password=b"s3cret")
    challenge = srv.step(cli.initiate())
    response = cli.step(challenge)
    # The impostor can't verify the proof either; but even if it blindly
    # forged a success, the client must reject the bad server proof.
    with pytest.raises(AccessControlError):
        success = srv.step(response)
        cli.step(success)


def test_token_mechanism_binds_verified_owner():
    sm = SecretManager("TEST_TOKEN")
    token = sm.create_token("bob")
    srv = SaslServerSession(None, secret_manager=sm)
    cli = SaslClientSession(MECH_TOKEN, token=token)
    _run_handshake(cli, srv)
    assert srv.user == "bob"
    assert srv.token_ident["owner"] == "bob"


def test_token_mechanism_forged_token_rejected():
    """A forged password fails the SCRAM proof exchange — the password
    itself never crosses the wire (the initiate carries only the
    identifier; the server recomputes the secret from its master key),
    so the rejection necessarily lands at the response step."""
    sm = SecretManager("TEST_TOKEN")
    token = sm.create_token("bob")
    token.password = b"\x00" * 32  # forged signature
    srv = SaslServerSession(None, secret_manager=sm)
    cli = SaslClientSession(MECH_TOKEN, token=token)
    challenge = srv.step(cli.initiate())
    with pytest.raises(AccessControlError):
        srv.step(cli.step(challenge))
    assert not srv.complete


def test_token_initiate_never_transmits_password():
    """Review finding: the old initiate shipped token.password in
    cleartext before any cipher existed, handing the credential to any
    eavesdropper."""
    sm = SecretManager("TEST_TOKEN")
    token = sm.create_token("carol")
    cli = SaslClientSession(MECH_TOKEN, token=token)
    from hadoop_tpu.io import pack
    wire = pack(cli.initiate())
    assert token.password not in wire
    # and the honest handshake still completes with mutual auth
    srv = SaslServerSession(None, secret_manager=sm)
    cli2 = SaslClientSession(MECH_TOKEN, token=sm.create_token("carol"))
    reply = srv.step(cli2.initiate())
    success = srv.step(cli2.step(reply))
    assert cli2.step(success) is None and cli2.complete
    assert srv.complete and srv.user == "carol"


def test_success_before_challenge_rejected():
    """Mutual-auth bypass (review finding): a forged success arriving
    before any challenge must be rejected, not compared against a
    guessable placeholder."""
    sm = SecretManager("TEST_TOKEN")
    cli = SaslClientSession(MECH_TOKEN, token=sm.create_token("bob"))
    cli.initiate()
    with pytest.raises(AccessControlError, match="before challenge"):
        cli.step({"state": "success", "server_proof": b"\x00"})


def test_wire_cipher_tamper_detection():
    ver = scram_verifier(b"pw")
    c2s, s2c = b"k" * 32, b"j" * 32
    a = WireCipher(c2s, s2c, is_client=True)
    b = WireCipher(c2s, s2c, is_client=False)
    orig = a.wrap(b"payload")
    rec = bytearray(orig)
    rec[-1] ^= 0xFF
    with pytest.raises(AccessControlError, match="decryption failed"):
        b.unwrap(bytes(rec))
    # a tampered frame does not advance the inbound counter: in-order
    # delivery of untampered records still works (in practice the
    # transports tear the connection down on the first failure)
    assert b.unwrap(orig) == b"payload"
    assert b.unwrap(a.wrap(b"two")) == b"two"


# --------------------------------------------------------------- live RPC

class _EchoService:
    def echo(self, x):
        return x

    def whoami(self):
        from hadoop_tpu.security.ugi import current_user
        u = current_user()
        return {"user": u.user_name, "auth": u.auth_method,
                "real": u.real_user.user_name if u.real_user else None}


def _secure_conf(kdc: MiniKdc, tmp_path, qop="authentication"):
    server_keytab = kdc.create_keytab(str(tmp_path / "server.keytab"))
    conf = Configuration(load_defaults=False)
    conf.set("hadoop.security.authentication", "sasl")
    conf.set("hadoop.rpc.protection", qop)
    conf.set("hadoop.security.server.keytab", server_keytab)
    return conf


@pytest.fixture()
def kdc(tmp_path):
    k = MiniKdc(str(tmp_path / "kdc"))
    k.create_principal("alice", b"alice-pw")
    return k


@pytest.mark.parametrize("qop", ["authentication", "privacy"])
def test_rpc_sasl_end_to_end(kdc, tmp_path, qop):
    conf = _secure_conf(kdc, tmp_path, qop)
    server = Server(conf, num_handlers=2, name=f"sasl-{qop}")
    server.register_protocol("Echo", _EchoService())
    server.start()
    try:
        ugi = UserGroupInformation.login_from_keytab(
            "alice", kdc.keytab_for("alice"))
        client = Client(conf)
        try:
            addr = ("127.0.0.1", server.port)
            payload = {"n": 42, "blob": b"\x00\x01" * 512}
            assert client.call(addr, "Echo", "echo", (payload,),
                               user=ugi) == payload
            who = client.call(addr, "Echo", "whoami", user=ugi)
            assert who["user"] == "alice"
            assert who["auth"] == UserGroupInformation.AUTH_KERBEROS
            # second call reuses the authenticated connection
            assert client.call(addr, "Echo", "echo", (7,), user=ugi) == 7
        finally:
            client.stop()
    finally:
        server.stop()


def test_rpc_privacy_bytes_are_encrypted(kdc, tmp_path):
    """Sniff the server-side frames: under privacy, a plaintext marker
    sent in a request must never appear on the wire."""
    import socket as _socket
    captured = []
    orig_recv = _socket.socket.recv

    conf = _secure_conf(kdc, tmp_path, "privacy")
    server = Server(conf, num_handlers=2, name="sasl-sniff")
    server.register_protocol("Echo", _EchoService())
    server.start()
    ugi = UserGroupInformation.login_from_keytab(
        "alice", kdc.keytab_for("alice"))
    client = Client(conf)
    marker = b"TOP-SECRET-MARKER-0123456789"

    def sniff_recv(sock, *a, **kw):
        data = orig_recv(sock, *a, **kw)
        captured.append(data)
        return data

    try:
        _socket.socket.recv = sniff_recv
        assert client.call(("127.0.0.1", server.port), "Echo", "echo",
                           (marker,), user=ugi) == marker
    finally:
        _socket.socket.recv = orig_recv
        client.stop()
        server.stop()
    joined = b"".join(captured)
    assert marker not in joined, "plaintext leaked on a privacy channel"
    assert joined, "sniffer captured nothing — test is vacuous"


def test_unauthenticated_client_rejected(kdc, tmp_path):
    """A SIMPLE client against a SASL-required server must be refused
    before any call dispatches (the negative test VERDICT asks for)."""
    conf = _secure_conf(kdc, tmp_path)
    server = Server(conf, num_handlers=2, name="sasl-neg")
    server.register_protocol("Echo", _EchoService())
    server.start()
    try:
        simple_conf = Configuration(load_defaults=False)
        client = Client(simple_conf)  # no sasl: sends a SIMPLE header
        try:
            with pytest.raises(FatalRpcError,
                               match="SIMPLE authentication is not"):
                client.call(("127.0.0.1", server.port), "Echo", "echo",
                            (1,))
        finally:
            client.stop()
    finally:
        server.stop()


def test_wrong_password_client_rejected(kdc, tmp_path):
    conf = _secure_conf(kdc, tmp_path)
    server = Server(conf, num_handlers=2, name="sasl-neg2")
    server.register_protocol("Echo", _EchoService())
    server.start()
    try:
        ugi = UserGroupInformation.create_remote_user("alice")
        ugi.sasl_password = b"not-the-password"
        client = Client(conf)
        try:
            with pytest.raises((FatalRpcError, AccessControlError)):
                client.call(("127.0.0.1", server.port), "Echo", "echo",
                            (1,), user=ugi)
        finally:
            client.stop()
    finally:
        server.stop()


def test_proxy_user_over_sasl(kdc, tmp_path):
    """Impersonation rides on the proven identity (ref: proxy users
    under Kerberos): effective user 'joe', real (authenticated) alice —
    and only because the proxy-user ACL grants it."""
    conf = _secure_conf(kdc, tmp_path)
    conf.set("hadoop.proxyuser.alice.users", "joe")
    conf.set("hadoop.proxyuser.alice.hosts", "*")
    server = Server(conf, num_handlers=2, name="sasl-proxy")
    server.register_protocol("Echo", _EchoService())
    server.start()
    try:
        real = UserGroupInformation.login_from_keytab(
            "alice", kdc.keytab_for("alice"))
        proxy = UserGroupInformation.create_proxy_user("joe", real)
        proxy.sasl_password = real.sasl_password
        client = Client(conf)
        try:
            who = client.call(("127.0.0.1", server.port), "Echo",
                              "whoami", user=proxy)
            assert who["user"] == "joe"
            assert who["real"] == "alice"
        finally:
            client.stop()
    finally:
        server.stop()


def test_proxy_user_without_acl_rejected(kdc, tmp_path):
    """An authenticated principal claiming a different effective user
    WITHOUT a hadoop.proxyuser ACL grant must be refused (ref:
    ProxyUsers.authorize — the round-4 impersonation hole)."""
    conf = _secure_conf(kdc, tmp_path)
    server = Server(conf, num_handlers=2, name="sasl-proxy-neg")
    server.register_protocol("Echo", _EchoService())
    server.start()
    try:
        real = UserGroupInformation.login_from_keytab(
            "alice", kdc.keytab_for("alice"))
        proxy = UserGroupInformation.create_proxy_user("hdfs-superuser",
                                                       real)
        proxy.sasl_password = real.sasl_password
        client = Client(conf)
        try:
            with pytest.raises((FatalRpcError, AccessControlError),
                               match="not configured as a proxy user"):
                client.call(("127.0.0.1", server.port), "Echo",
                            "whoami", user=proxy)
        finally:
            client.stop()
    finally:
        server.stop()


def test_proxy_user_acl_restricts_target_and_host(kdc, tmp_path):
    """ACL granting joe does not grant root; host lists are enforced."""
    conf = _secure_conf(kdc, tmp_path)
    conf.set("hadoop.proxyuser.alice.users", "joe")
    conf.set("hadoop.proxyuser.alice.hosts", "*")
    server = Server(conf, num_handlers=2, name="sasl-proxy-neg2")
    server.register_protocol("Echo", _EchoService())
    server.start()
    try:
        real = UserGroupInformation.login_from_keytab(
            "alice", kdc.keytab_for("alice"))
        proxy = UserGroupInformation.create_proxy_user("root", real)
        proxy.sasl_password = real.sasl_password
        client = Client(conf)
        try:
            with pytest.raises((FatalRpcError, AccessControlError),
                               match="not allowed to impersonate"):
                client.call(("127.0.0.1", server.port), "Echo",
                            "whoami", user=proxy)
        finally:
            client.stop()
    finally:
        server.stop()
    # host restriction: grant exists but only from another host
    from hadoop_tpu.security.proxyusers import ProxyUsers
    from hadoop_tpu.security.ugi import UserGroupInformation as U
    conf2 = Configuration(load_defaults=False)
    conf2.set("hadoop.proxyuser.alice.users", "joe")
    conf2.set("hadoop.proxyuser.alice.hosts", "10.0.0.9")
    pu = ProxyUsers(conf2)
    eff = U.create_proxy_user("joe", U.create_remote_user("alice"))
    with pytest.raises(AccessControlError, match="not allowed from host"):
        pu.authorize(eff, "127.0.0.1")
    pu.authorize(eff, "10.0.0.9")  # allowed from the listed host


def test_wire_cipher_replay_and_reorder_rejected():
    """A captured privacy-QoP record can be neither replayed nor
    delivered out of order (the advisor's round-4 finding: GCM tag
    alone binds content, not position)."""
    c2s, s2c = b"k" * 32, b"j" * 32
    a = WireCipher(c2s, s2c, is_client=True)
    b = WireCipher(c2s, s2c, is_client=False)
    r1, r2, r3 = a.wrap(b"one"), a.wrap(b"two"), a.wrap(b"three")
    assert b.unwrap(r1) == b"one"
    with pytest.raises(AccessControlError, match="out-of-order nonce"):
        b.unwrap(r1)  # replay
    assert b.unwrap(r2) == b"two"
    with pytest.raises(AccessControlError, match="out-of-order nonce"):
        # skipping ahead (dropping r3's predecessor) is also detected
        b.unwrap(a.wrap(b"five"))
    assert b.unwrap(r3) == b"three"


def test_dek_rpc_requires_privacy_channel_on_secured_cluster():
    """On hadoop.security.authentication=sasl, the NN refuses to serve
    data-encryption keys over a connection without privacy QoP (the
    advisor's round-4 finding: DEK over plaintext RPC is theater)."""
    from hadoop_tpu.dfs.namenode import namenode as nn_mod
    from hadoop_tpu.ipc.server import CallContext, _current_call

    class _FakeFsn:
        def __init__(self, auth):
            self.conf = Configuration(load_defaults=False)
            self.conf.set("hadoop.security.authentication", auth)

    def ctx(qop):
        return CallContext(
            user=UserGroupInformation.create_remote_user("alice"),
            client_id=b"", call_id=1, retry_count=0,
            address="127.0.0.1:1", protocol="ClientProtocol",
            method="get_data_encryption_key", client_state_id=-1,
            sasl_qop=qop)

    secured = _FakeFsn("sasl")
    tok = _current_call.set(ctx(None))
    try:
        with pytest.raises(AccessControlError, match="privacy"):
            nn_mod._check_dek_channel(secured)
    finally:
        _current_call.reset(tok)
    tok = _current_call.set(ctx("authentication"))
    try:
        with pytest.raises(AccessControlError, match="privacy"):
            nn_mod._check_dek_channel(secured)
    finally:
        _current_call.reset(tok)
    tok = _current_call.set(ctx("privacy"))
    try:
        nn_mod._check_dek_channel(secured)  # allowed
    finally:
        _current_call.reset(tok)
    # simple-auth (dev/test) cluster: warns, does not raise
    nn_mod._check_dek_channel(_FakeFsn("simple"))


# ------------------------------------------------- encrypted data transfer

def test_encrypted_data_transfer_end_to_end(tmp_path):
    """dfs.encrypt.data.transfer=true: write/read through a replication
    pipeline with every data socket SASL-authenticated + AES-GCM
    encrypted (ref: TestEncryptedTransfer.java)."""
    import os as _os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    conf = fast_conf()
    conf.set("dfs.replication", "2")
    conf.set("dfs.encrypt.data.transfer", "true")
    with MiniDFSCluster(num_datanodes=2, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        payload = _os.urandom(300_000)
        fs.write_all("/enc.bin", payload)
        assert fs.read_all("/enc.bin") == payload
        # positioned read exercises the read path's handshake too
        with fs.open("/enc.bin") as f:
            assert f.pread(1000, 64) == payload[1000:1064]


def test_encrypted_transfer_rejects_plaintext_peer(tmp_path):
    """A client that skips the handshake and sends a bare op frame must
    be refused by the DN (negative leg; ref: SaslDataTransferServer
    rejecting unprotected peers)."""
    import os as _os

    from hadoop_tpu.dfs.protocol import datatransfer as dt
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.encrypt.data.transfer", "true")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fs.write_all("/enc2.bin", _os.urandom(4096))
        locs = fs.client.get_block_locations("/enc2.bin")
        blk = locs["blocks"][0]
        addr = tuple(blk["locs"][0]["h"].rsplit(":", 1)) \
            if isinstance(blk["locs"][0], dict) and "h" in blk["locs"][0] \
            else None
        from hadoop_tpu.dfs.protocol.records import DatanodeInfo
        dn = DatanodeInfo.from_wire(blk["locs"][0])
        # Plain socket, straight to the op frame — no handshake.
        import socket as _socket
        sock = _socket.create_connection(dn.xfer_addr(), timeout=5.0)
        try:
            dt.send_frame(sock, {"op": dt.OP_READ_BLOCK,
                                 "b": blk["b"], "offset": 0,
                                 "length": 4096})
            reply = dt.recv_frame(sock)
            assert not reply.get("ok")
            assert "protection is required" in reply.get("em", "")
        finally:
            sock.close()


def test_fully_secured_minicluster(tmp_path):
    """The whole cluster under SASL: every RPC (client→NN, DN→NN) is
    mutually authenticated + encrypted, and block transfer is encrypted
    too (ref: a kerberized cluster with privacy QoP end to end)."""
    import getpass
    import os as _os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    kdc = MiniKdc(str(tmp_path / "kdc"))
    me = getpass.getuser()
    kdc.create_principal(me)
    server_keytab = kdc.create_keytab(str(tmp_path / "server.keytab"))
    client_keytab = kdc.create_keytab(str(tmp_path / "client.keytab"), me)

    conf = fast_conf()
    conf.set("dfs.replication", "2")
    conf.set("hadoop.security.authentication", "sasl")
    conf.set("hadoop.rpc.protection", "privacy")
    conf.set("hadoop.security.server.keytab", server_keytab)
    conf.set("hadoop.security.client.keytab", client_keytab)
    conf.set("dfs.encrypt.data.transfer", "true")
    with MiniDFSCluster(num_datanodes=2, conf=conf,
                        base_dir=str(tmp_path / "dfs")) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        payload = _os.urandom(200_000)
        fs.write_all("/secure/all.bin", payload)
        assert fs.read_all("/secure/all.bin") == payload
        st = fs.get_file_status("/secure/all.bin")
        assert st.length == len(payload)


def test_integrity_qop_macs_frames():
    """auth-int: frames are MAC'd + replay-protected but readable
    (ref: SASL auth-int wrap)."""
    from hadoop_tpu.security.sasl import (QOP_INTEGRITY,
                                          SaslClientSession,
                                          SaslServerSession)
    store = CredentialStore()
    store.add_principal("alice", b"pw")
    srv = SaslServerSession(store, required_qop=QOP_INTEGRITY)
    cli = SaslClientSession(MECH_SCRAM, user="alice", password=b"pw",
                            qop=QOP_INTEGRITY)
    _run_handshake(cli, srv)
    rec = cli.cipher.wrap(b"readable payload")
    assert b"readable payload" in rec          # not encrypted
    assert srv.cipher.unwrap(rec) == b"readable payload"
    # tamper detection
    bad = bytearray(cli.cipher.wrap(b"x"))
    bad[-1] ^= 1
    with pytest.raises(AccessControlError, match="integrity"):
        srv.cipher.unwrap(bytes(bad))
    # replay detection (counters advanced)
    r = cli.cipher.wrap(b"y")
    assert srv.cipher.unwrap(r) == b"y"
    with pytest.raises(AccessControlError):
        srv.cipher.unwrap(r)


def test_rpc_integrity_end_to_end(kdc, tmp_path):
    conf = _secure_conf(kdc, tmp_path, "integrity")
    server = Server(conf, num_handlers=2, name="sasl-int")
    server.register_protocol("Echo", _EchoService())
    server.start()
    try:
        ugi = UserGroupInformation.login_from_keytab(
            "alice", kdc.keytab_for("alice"))
        client = Client(conf)
        try:
            assert client.call(("127.0.0.1", server.port), "Echo",
                               "echo", ({"n": 9},), user=ugi) == {"n": 9}
        finally:
            client.stop()
    finally:
        server.stop()


# --------------------------------------------- block tokens + fd short-circuit

def test_short_circuit_fds_gated_on_block_token(tmp_path):
    """dfs.block.access.token.enable=true: the DN's AF_UNIX fd server
    refuses a request without (or with a forged) block token, and the
    normal client path — which carries the NN-minted token from
    LocatedBlock — works (ref: BlockTokenSecretManager.checkAccess
    gating requestShortCircuitFds; VERDICT r4 #4)."""
    import os as _os

    from hadoop_tpu.dfs.client.shortcircuit import (ShortCircuitCache,
                                                    ShortCircuitUnavailable)
    from hadoop_tpu.dfs.protocol.blocktoken import BlockTokenSecretManager
    from hadoop_tpu.dfs.protocol.records import Block
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.block.access.token.enable", "true")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        payload = _os.urandom(300_000)
        fs.write_all("/tok.bin", payload)
        cache = ShortCircuitCache.get()
        hits0 = cache.hits
        assert fs.read_all("/tok.bin") == payload   # tokened path works
        assert cache.hits > hits0

        locs = fs.client.get_block_locations("/tok.bin")
        blk = Block.from_wire(locs["blocks"][0]["b"])
        dn = cluster.datanodes[0]
        sock_path = dn.domain_server.path

        # no token → refused
        with pytest.raises(ShortCircuitUnavailable, match="token"):
            cache._request_fds(sock_path, blk, None)
        # forged token (wrong key) → refused
        forged = BlockTokenSecretManager().generate_token(
            "mallory", blk.block_id)
        with pytest.raises(ShortCircuitUnavailable,
                           match="key|signature|token"):
            cache._request_fds(sock_path, blk, forged)
        # token for a DIFFERENT block → refused
        other = locs["blocks"][0].get("tok")
        assert other is not None
        wrong_block = Block(blk.block_id + 999, blk.gen_stamp, 1)
        with pytest.raises(ShortCircuitUnavailable, match="block"):
            cache._request_fds(sock_path, wrong_block, other)


def test_block_tokens_gate_tcp_data_plane(tmp_path):
    """The TCP path enforces tokens too — otherwise the fd gate would be
    bypassed by the client's automatic TCP fallback (review finding):
    a bare OP_READ_BLOCK without a token is refused."""
    import os as _os

    from hadoop_tpu.dfs.protocol import datatransfer as dt
    from hadoop_tpu.dfs.protocol.records import Block, DatanodeInfo
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.block.access.token.enable", "true")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fs.write_all("/tcp-tok.bin", _os.urandom(50_000))
        locs = fs.client.get_block_locations("/tcp-tok.bin")
        blk = locs["blocks"][0]
        dn = DatanodeInfo.from_wire(blk["locs"][0])
        # no token → setup refused before any byte of data
        with pytest.raises(IOError, match="token"):
            dt.read_block_range(dn.xfer_addr(), blk["b"], 0, 1024)
        # the NN-minted token unlocks the same op
        data = dt.read_block_range(dn.xfer_addr(), blk["b"], 0, 1024,
                                   token=blk["tok"])
        assert len(data) == 1024


def test_block_tokens_with_erasure_coding(tmp_path):
    """Striped units carry unit ids but tokens name the group — the
    DN-side resolution must let a group token read any unit, and EC
    reconstruction (DN-minted tokens) must still heal."""
    import os as _os

    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.block.access.token.enable", "true")
    with MiniDFSCluster(num_datanodes=5, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        fs.mkdirs("/ec")
        fs.client.set_ec_policy("/ec", "RS-3-2-64k")
        payload = _os.urandom(500_000)
        fs.write_all("/ec/tok.bin", payload)
        assert fs.read_all("/ec/tok.bin") == payload


def test_block_token_import_tracks_exporter_key_ids():
    """A verification-side manager mints with the EXPORTER's newest key
    after import (balancer/DN-minted transfer tokens), and key rotation
    on the NN side must not strand importers on a stale counter
    (review finding: import_keys left _key_id at its local value)."""
    from hadoop_tpu.dfs.protocol.blocktoken import (MODE_COPY,
                                                    BlockTokenSecretManager)

    nn = BlockTokenSecretManager()
    dn = BlockTokenSecretManager.for_verification()
    dn.import_keys(nn.export_keys())
    tok = dn.generate_token("balancer", 42, modes=(MODE_COPY,))
    nn.check_access(tok, 42, MODE_COPY)  # NN-side keys verify it

    # rotate past the importer's original counter value
    for _ in range(3):
        nn._roll_key()
    dn.import_keys(nn.export_keys())
    tok2 = dn.generate_token("balancer", 43, modes=(MODE_COPY,))
    nn.check_access(tok2, 43, MODE_COPY)


def test_mint_without_keys_is_access_error():
    """An empty verification-side manager fails minting like an auth
    error, not a KeyError."""
    from hadoop_tpu.dfs.protocol.blocktoken import BlockTokenSecretManager
    from hadoop_tpu.security.ugi import AccessControlError

    dn = BlockTokenSecretManager.for_verification()
    with pytest.raises(AccessControlError, match="master key"):
        dn.generate_token("x", 1)


def test_truncated_fd_grant_degrades_to_unavailable(tmp_path):
    """A DN dying mid-reply on the fd channel must surface as
    ShortCircuitUnavailable (which the read path converts into TCP
    fallback), not a decode error that fails the whole read (review
    finding)."""
    import socket as _socket
    import struct as _struct
    import threading as _threading

    from hadoop_tpu.dfs.client.shortcircuit import (ShortCircuitCache,
                                                    ShortCircuitUnavailable)
    from hadoop_tpu.dfs.protocol.records import Block

    path = str(tmp_path / "dn.sock")
    srv = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
    srv.bind(path)
    srv.listen(1)

    def half_reply():
        conn, _ = srv.accept()
        try:
            conn.recv(1 << 16)  # swallow the request
            # claim a 64-byte frame, deliver 3 bytes, die
            conn.sendall(_struct.pack(">I", 64) + b"\x81\x01\x02")
        finally:
            conn.close()

    t = _threading.Thread(target=half_reply, daemon=True)
    t.start()
    cache = ShortCircuitCache()
    try:
        with pytest.raises(ShortCircuitUnavailable, match="truncated"):
            cache._request_fds(path, Block(1, 1, 10), None)
    finally:
        t.join(timeout=5)
        srv.close()


def test_block_token_master_keys_are_admin_only(tmp_path):
    """get_block_keys on ClientProtocol (the balancer's feed) hands out
    block-token MASTER keys — any client holding them can mint tokens
    for any block, so the RPC is restricted to cluster administrators
    (ref: NamenodeProtocol behind service ACLs; review finding)."""
    from hadoop_tpu.ipc import get_proxy
    from hadoop_tpu.security.ugi import (AccessControlError,
                                         UserGroupInformation)
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf

    conf = fast_conf()
    conf.set("dfs.replication", "1")
    conf.set("dfs.block.access.token.enable", "true")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        nn = get_proxy("ClientProtocol", cluster.nn_addr)
        # the NN's own user (what the balancer runs as) gets keys
        keys = nn.get_block_keys()
        assert keys and all("key" in k for k in keys)
        # an ordinary remote user does not
        mallory = UserGroupInformation.create_remote_user("mallory")
        evil = get_proxy("ClientProtocol", cluster.nn_addr, user=mallory)
        with pytest.raises(AccessControlError, match="administrator"):
            evil.get_block_keys()


def test_invalid_wire_bpc_fails_replica_not_read(tmp_path):
    """A peer replying bpc<=0 must fail like a replica IO error (the
    reader's failover path), never a ZeroDivisionError (review
    finding)."""
    from hadoop_tpu.dfs.protocol.datatransfer import checked_bpc

    assert checked_bpc({}) == 512
    assert checked_bpc({"bpc": 2048}) == 2048
    for bad in (0, -1, "512", 1 << 21, None):
        with pytest.raises(IOError, match="bytes-per-checksum"):
            checked_bpc({"bpc": bad})
