"""Service lifecycle tests (parity targets: ref
hadoop-common/src/test/java/org/apache/hadoop/service/TestServiceLifecycle.java,
TestCompositeService.java)."""

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.service import (AbstractService, CompositeService, ServiceState,
                                ServiceStateException)


class Recorder(AbstractService):
    def __init__(self, name, events, fail_in=None):
        super().__init__(name)
        self.events = events
        self.fail_in = fail_in

    def service_init(self, conf):
        if self.fail_in == "init":
            raise RuntimeError("init boom")
        self.events.append(f"{self.name}.init")

    def service_start(self):
        if self.fail_in == "start":
            raise RuntimeError("start boom")
        self.events.append(f"{self.name}.start")

    def service_stop(self):
        self.events.append(f"{self.name}.stop")


def test_lifecycle_order():
    ev = []
    s = Recorder("s", ev)
    conf = Configuration(load_defaults=False)
    assert s.state == ServiceState.NOTINITED
    s.init(conf)
    assert s.state == ServiceState.INITED
    s.start()
    assert s.state == ServiceState.STARTED
    s.stop()
    assert s.state == ServiceState.STOPPED
    assert ev == ["s.init", "s.start", "s.stop"]


def test_cannot_start_uninited():
    s = Recorder("s", [])
    with pytest.raises(ServiceStateException):
        s.start()


def test_stop_idempotent_from_any_state():
    ev = []
    s = Recorder("s", ev)
    s.stop()
    s.stop()
    assert s.state == ServiceState.STOPPED
    assert ev == ["s.stop"]


def test_start_failure_triggers_stop():
    ev = []
    s = Recorder("s", ev, fail_in="start")
    s.init(Configuration(load_defaults=False))
    with pytest.raises(RuntimeError):
        s.start()
    assert s.state == ServiceState.STOPPED
    assert s.failure_cause is not None
    assert ev == ["s.init", "s.stop"]


def test_composite_order_and_reverse_stop():
    ev = []
    parent = CompositeService("parent")
    parent.add_service(Recorder("a", ev))
    parent.add_service(Recorder("b", ev))
    conf = Configuration(load_defaults=False)
    parent.init(conf)
    parent.start()
    parent.stop()
    assert ev == ["a.init", "b.init", "a.start", "b.start", "b.stop", "a.stop"]


def test_composite_child_start_failure_stops_started_children():
    ev = []
    parent = CompositeService("parent")
    parent.add_service(Recorder("a", ev))
    parent.add_service(Recorder("bad", ev, fail_in="start"))
    parent.init(Configuration(load_defaults=False))
    with pytest.raises(RuntimeError):
        parent.start()
    assert parent.state == ServiceState.STOPPED
    assert "a.stop" in ev  # started child got torn down


def test_listeners():
    states = []
    s = Recorder("s", [])
    s.register_listener(lambda svc, st: states.append(st))
    s.init(Configuration(load_defaults=False))
    s.start()
    s.stop()
    assert states == [ServiceState.INITED, ServiceState.STARTED,
                      ServiceState.STOPPED]


def test_context_manager():
    ev = []
    with Recorder("s", ev) as s:
        s.init(Configuration(load_defaults=False))
        s.start()
    assert s.state == ServiceState.STOPPED
