"""Serving plane: continuous-batching decode engine + the full replica.

Engine tests pin the three properties that make the engine a real
serving core: paged-KV decode is EXACT (greedy tokens match a full
recompute through ``models.decoder.forward``), the two compiled
functions trace exactly once across an arbitrary workload, and the
paged pool admits/evicts under pressure without corrupting any stream.

The end-to-end test is the acceptance path of the subsystem: trainer
checkpoint → miniDFS → ``load_serving_params`` → replica HTTP door with
auth, streaming, mid-decode admission observable in the occupancy
metric, and graceful drain.
"""

import json
import http.client
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.models.config import get_config
from hadoop_tpu.models.decoder import forward, init_params
from hadoop_tpu.serving.engine import (BlockPool, DecodeEngine,
                                       PrefixCache, SamplingParams)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("tiny")
    return init_params(jax.random.PRNGKey(0), cfg), cfg


_REF_P = 48
_ref_fwd_cache = {}


def _reference_greedy(params, cfg, prompt, max_new):
    """Full forward recompute each step — the engine's ground truth.
    Sequences are padded to one fixed length so the reference forward
    compiles once per config (causal attention: the padded tail cannot
    influence logits at earlier positions)."""
    fwd = _ref_fwd_cache.get(id(cfg))
    if fwd is None:
        fwd = jax.jit(lambda p, t: forward(p, t, cfg))
        _ref_fwd_cache[id(cfg)] = fwd
    seq = list(prompt)
    for _ in range(max_new):
        padded = seq + [0] * (_REF_P - len(seq))
        logits = fwd(params, jnp.asarray([padded]))
        seq.append(int(jnp.argmax(logits[0, len(seq) - 1])))
    return seq[len(prompt):]


# -------------------------------------------------------------- block pool

def test_block_pool_alloc_free():
    pool = BlockPool(num_blocks=8, block_size=4)
    assert pool.num_usable == 7          # block 0 is scratch
    a = pool.alloc(3)
    b = pool.alloc(4)
    assert a is not None and b is not None
    assert BlockPool.SCRATCH not in a + b
    assert len(set(a + b)) == 7          # no page handed out twice
    assert pool.alloc(1) is None         # all-or-nothing exhaustion
    pool.free(a)
    assert pool.num_free == 3
    c = pool.alloc(3)
    assert sorted(c) == sorted(a)        # freed pages recycle
    with pytest.raises(ValueError):
        pool.free([BlockPool.SCRATCH])


def test_block_pool_refcounts_protect_shared_pages():
    pool = BlockPool(num_blocks=6, block_size=4)
    blocks = pool.alloc(2)
    assert all(pool.refcount(b) == 1 for b in blocks)
    pool.incref(blocks)                  # a second request maps them
    with pytest.raises(ValueError):      # still shared: free must refuse
        pool.free(blocks)
    assert pool.decref(blocks) == []     # first unmap: nothing hits zero
    zeros = pool.decref(blocks)          # second unmap: both unreferenced
    assert sorted(zeros) == sorted(blocks)
    pool.free(zeros)                     # only now may they recycle
    assert pool.num_free == 5
    with pytest.raises(ValueError):      # double-decref is a bug
        pool.decref(blocks)
    with pytest.raises(ValueError):
        pool.incref([BlockPool.SCRATCH])


def test_prefix_cache_radix_match_insert_evict():
    """Block-granular trie: longest full-block prefix match, first
    writer wins on insert, LRU zero-ref leaves evict first (a parent
    can only go after its children)."""
    cache = PrefixCache(block_size=2)
    ref = {10: 0, 11: 0, 12: 0, 13: 0}
    assert cache.match([1, 2, 3, 4]) == []
    assert cache.insert([1, 2, 3, 4], [10, 11]) == 2
    assert cache.match([1, 2, 3, 4, 5]) == [10, 11]   # partial tail cut
    assert cache.match([1, 2, 9, 9]) == [10]          # diverges mid-way
    assert cache.match([9, 2, 3, 4]) == []            # prefix is the key:
    # same block tokens under a different head must NOT match
    assert cache.insert([1, 2, 3, 4], [12, 13]) == 0  # dedup: first wins
    assert cache.match([1, 2, 3, 4]) == [10, 11]
    assert cache.insert([1, 2, 7, 8], [10, 12]) == 1  # sibling branch
    assert len(cache) == 3
    # 11 is the least-recently-touched leaf (12 was just inserted)
    assert cache.evict(1, ref.get) == [11]
    ref[12] = 1                                       # a request maps 12
    assert cache.evict(2, ref.get) == []   # leaf pinned, parent has kids
    ref[12] = 0
    assert cache.evict(2, ref.get) == [12, 10]        # leaf, then parent
    assert len(cache) == 0


# ------------------------------------------------------------------ engine

@pytest.mark.parametrize("preset", ["tiny", "tiny-gpt2"])
def test_paged_decode_matches_reference_forward(preset):
    """Greedy decode through the paged KV cache must produce exactly
    the tokens a full-context recompute produces — for both the
    rope/rmsnorm/swiglu and learned-pos/layernorm/gelu families."""
    cfg = get_config(preset)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = [3, 17, 42, 99, 5]
    ref = _reference_greedy(params, cfg, prompt, 8)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32)
    got = eng.generate([prompt], SamplingParams(max_new_tokens=8))[0]
    assert got == ref


def test_batched_requests_decode_independently(tiny_model):
    """Different-length requests in one batch each match their solo
    greedy reference — lanes must not bleed into each other."""
    params, cfg = tiny_model
    prompts = [[1, 2, 3], [5, 6, 7, 8, 9, 10, 11], [200]]
    refs = [_reference_greedy(params, cfg, p, 6) for p in prompts]
    eng = DecodeEngine(params, cfg, max_batch=4, block_size=4,
                       max_context=32)
    outs = eng.generate(prompts, SamplingParams(max_new_tokens=6))
    assert outs == refs


def test_mid_decode_admission_is_continuous(tiny_model):
    """A request admitted while another is mid-decode joins the running
    batch at a step boundary (occupancy 1 → 2) and neither stream is
    perturbed."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=4, block_size=4,
                       max_context=48)
    ref_a = _reference_greedy(params, cfg, [7, 8, 9], 10)
    ref_b = _reference_greedy(params, cfg, [42, 43], 5)
    a = eng.submit([7, 8, 9], SamplingParams(max_new_tokens=10))
    eng.step()                   # prefill A + first decode
    eng.step()
    assert eng.occupancy_log[-1] == 1
    b = eng.submit([42, 43], SamplingParams(max_new_tokens=5))
    while not (a.done.is_set() and b.done.is_set()):
        eng.step()
    assert max(eng.occupancy_log) == 2, "B never joined the batch"
    assert a.wait(0) == ref_a
    assert b.wait(0) == ref_b


def test_decode_compiles_exactly_once(tiny_model):
    """Any mix of prompt lengths, sampling params and admission orders
    rides two fixed-shape executables — no per-request retracing."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=3, block_size=4,
                       max_context=32)
    eng.generate([[1], [2, 3, 4, 5]], SamplingParams(max_new_tokens=3))
    eng.generate([[9, 8, 7]], SamplingParams(max_new_tokens=7,
                                             temperature=0.9, top_k=5))
    eng.generate([[4, 4], [5], [6, 6, 6]],
                 SamplingParams(max_new_tokens=2))
    assert eng.decode_compiles == 1
    assert eng.prefill_compiles == 1


def test_kv_pool_pressure_preempts_youngest_and_recovers(tiny_model):
    """When the pool runs dry the youngest request is evicted (pages
    freed, request requeued) and later resumes by recompute — both
    streams still match their solo greedy references."""
    params, cfg = tiny_model
    # usable pages: 7. A alone peaks at 6 pages, B at 5 — running
    # together they outgrow the pool and the younger (B) must yield.
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, num_blocks=8,
                       metrics=_metrics())
    ref_a = _reference_greedy(params, cfg, [1, 2, 3, 4], 20)
    ref_b = _reference_greedy(params, cfg, [9, 9, 9, 9], 16)
    a = eng.submit([1, 2, 3, 4], SamplingParams(max_new_tokens=20))
    b = eng.submit([9, 9, 9, 9], SamplingParams(max_new_tokens=16))
    while not (a.done.is_set() and b.done.is_set()):
        eng.step()
    assert b.preemptions >= 1, "pool pressure never evicted the youngest"
    assert eng.metrics.preemptions.value() >= 1
    assert a.wait(0) == ref_a
    assert b.wait(0) == ref_b
    # every page is either free or resident ref-zero prefix cache —
    # nothing is still mapped by a finished request
    cached = len(eng.prefix_cache)
    assert eng.pool.num_free + cached == eng.pool.num_usable
    assert all(eng.pool.refcount(b) == 0
               for b in range(1, eng.pool.num_blocks))


def test_submit_rejects_impossible_requests(tiny_model):
    """A request the pool can NEVER satisfy must fail fast at submit —
    parking it in the admission queue would wedge the queue forever."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=16, num_blocks=3)
    with pytest.raises(ValueError):
        eng.submit(list(range(20)), SamplingParams(max_new_tokens=1))
    with pytest.raises(ValueError):     # pool can never hold it
        eng.submit([1, 2], SamplingParams(max_new_tokens=12))
    assert eng.queue_depth == 0         # rejected, not parked
    with pytest.raises(ValueError):
        eng.submit([], SamplingParams())
    with pytest.raises(ValueError):     # prefill always emits one token
        eng.submit([1], SamplingParams(max_new_tokens=0))
    # the bound is pool capacity, not current availability: resident
    # prefix-cache blocks are evictable, so a feasible request must
    # still be accepted when the pool is momentarily full of cache
    eng2 = DecodeEngine(params, cfg, max_batch=1, block_size=4,
                        max_context=16, num_blocks=4)   # 3 usable pages
    eng2.generate([[1, 2, 3, 4, 5, 6]], SamplingParams(max_new_tokens=2))
    assert len(eng2.prefix_cache) > 0   # cache resident, pages not free
    with pytest.raises(ValueError):     # 13 tokens = 4 pages > 3 ever
        eng2.submit(list(range(9)), SamplingParams(max_new_tokens=4))
    out = eng2.generate([[9, 9, 9, 9, 9, 9, 9, 9]],
                        SamplingParams(max_new_tokens=4))
    assert len(out[0]) == 4             # feasible: cache evicted to fit


def test_engine_context_never_exceeds_model_max_seq(tiny_model):
    """Block-size rounding must never admit positions past the model's
    rope/pos-embed tables (silent clamping = wrong logits)."""
    params, cfg = tiny_model                   # cfg.max_seq == 128
    eng = DecodeEngine(params, cfg, max_batch=1, block_size=48)
    assert eng.s_max <= cfg.max_seq
    with pytest.raises(ValueError):
        DecodeEngine(params, cfg, max_batch=1, block_size=256)


def test_per_request_sampling_params(tiny_model):
    """top_k=1 at any temperature is argmax; free sampling stays in
    vocab range. Both ride the same compiled step as greedy lanes."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=3, block_size=4,
                       max_context=32)
    ref = _reference_greedy(params, cfg, [11, 12, 13], 6)
    greedy = eng.submit([11, 12, 13], SamplingParams(max_new_tokens=6))
    topk1 = eng.submit([11, 12, 13],
                       SamplingParams(max_new_tokens=6,
                                      temperature=1.0, top_k=1))
    free = eng.submit([50, 51], SamplingParams(max_new_tokens=6,
                                               temperature=1.2))
    while not all(r.done.is_set() for r in (greedy, topk1, free)):
        eng.step()
    assert greedy.wait(0) == ref
    assert topk1.wait(0) == ref
    assert all(0 <= t < cfg.vocab_size for t in free.wait(0))


def test_warm_prefix_cache_stays_exact_match(tiny_model):
    """The tentpole correctness pin: decode through REUSED KV blocks
    must produce exactly the tokens a cold full recompute produces —
    for a shared-head sibling and for an identical resubmit."""
    params, cfg = tiny_model
    head = [5, 9, 2, 7, 1, 8, 3, 6, 4, 2, 9, 1, 7, 3, 8, 5]   # 4 blocks
    pa, pb = head + [11, 12], head + [13]
    ref_a = _reference_greedy(params, cfg, pa, 8)
    ref_b = _reference_greedy(params, cfg, pb, 8)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=48, prefill_chunk=4,
                       metrics=_metrics())
    a = eng.submit(pa, SamplingParams(max_new_tokens=8))
    while not a.done.is_set():
        eng.step()
    assert a.wait(0) == ref_a                   # cold
    assert a.prefix_tokens_reused == 0
    assert len(eng.prefix_cache) >= 4           # head blocks resident
    b = eng.submit(pb, SamplingParams(max_new_tokens=8))
    while not b.done.is_set():
        eng.step()
    assert b.wait(0) == ref_b                   # warm sibling: exact
    assert b.prefix_tokens_reused == 16         # the whole head
    a2 = eng.submit(pa, SamplingParams(max_new_tokens=8))
    while not a2.done.is_set():
        eng.step()
    assert a2.wait(0) == ref_a                  # identical resubmit:
    # matched to the last full block, never the final prompt token
    # (its logits must be recomputed to sample the first output)
    assert a2.prefix_tokens_reused == 16
    stats = eng.cache_stats()
    assert stats["hit_rate"] > 0
    # engine-local counter, not the process-global metrics source
    # (other tests in this process share that counter object)
    assert eng.prefix_tokens_matched == 32
    assert eng.decode_compiles == 1 and eng.prefill_compiles == 1


def test_chunked_prefill_does_not_stall_running_decodes(tiny_model):
    """A long prompt prefills prefill_chunk tokens per step INSIDE the
    decode step: the running request keeps emitting one token every
    step of the newcomer's multi-chunk prefill (the head-of-line block
    the monolithic prefill used to cause), and both streams stay
    exact."""
    params, cfg = tiny_model
    long_prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3,
                   2, 3, 8, 4]                                  # 5 chunks
    ref_a = _reference_greedy(params, cfg, [7, 8, 9], 16)
    ref_b = _reference_greedy(params, cfg, long_prompt, 6)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=48, prefill_chunk=4)
    a = eng.submit([7, 8, 9], SamplingParams(max_new_tokens=16))
    eng.step()
    eng.step()
    a_before = len(a.out_tokens)
    b = eng.submit(long_prompt, SamplingParams(max_new_tokens=6))
    b_first_step = None
    for i in range(1, 30):
        eng.step()
        if b.out_tokens and b_first_step is None:
            b_first_step = i
            a_during = len(a.out_tokens) - a_before
            break
    assert b_first_step >= 5, "20-token prompt at chunk=4 must take " \
                              ">= 5 steps to its first token"
    # every prefill-chunk step also advanced A by one decode token
    assert a_during >= b_first_step - 1
    while not (a.done.is_set() and b.done.is_set()):
        eng.step()
    assert a.wait(0) == ref_a
    assert b.wait(0) == ref_b
    assert eng.decode_compiles == 1 and eng.prefill_compiles == 1


def test_preempting_a_sharer_never_frees_sibling_blocks(tiny_model):
    """Preemption x chunked prefill x prefix sharing: B maps A's cached
    head blocks; pool pressure then preempts B (the youngest). The
    shared pages must survive for A (its stream stays exact), and B's
    warm resubmit-by-recompute stays exact too."""
    params, cfg = tiny_model
    head = [5, 9, 2, 7, 1, 8, 3, 6]                   # 2 full blocks
    pa, pb = head + [1, 2], head + [3, 4]
    ref_a = _reference_greedy(params, cfg, pa, 14)
    ref_b = _reference_greedy(params, cfg, pb, 10)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, num_blocks=8, prefill_chunk=4,
                       metrics=_metrics())
    a = eng.submit(pa, SamplingParams(max_new_tokens=14))
    while a._prefill_pos is not None or not a.out_tokens:
        eng.step()                      # A's head is now cached
    b = eng.submit(pb, SamplingParams(max_new_tokens=10))
    while not (a.done.is_set() and b.done.is_set()):
        eng.step()
    assert b.prefix_tokens_reused >= 8, "B never mapped the shared head"
    assert b.preemptions >= 1, "pool pressure never evicted the youngest"
    assert a.wait(0) == ref_a           # sibling pages survived
    assert b.wait(0) == ref_b           # warm recompute resume: exact
    # every page is free or resident zero-ref cache; nothing leaked
    assert eng.pool.num_free + len(eng.prefix_cache) == \
        eng.pool.num_usable
    assert all(eng.pool.refcount(blk) == 0
               for blk in range(1, eng.pool.num_blocks))


def test_engine_shards_over_tp_mesh(tiny_model):
    """The same engine code runs with weights and KV heads sharded over
    a tp=2 mesh (virtual CPU devices) — greedy output is unchanged."""
    from hadoop_tpu.parallel.mesh import MeshPlan
    params, cfg = tiny_model
    ref = _reference_greedy(params, cfg, [5, 6, 7], 6)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, plan=MeshPlan(tp=2))
    got = eng.generate([[5, 6, 7]], SamplingParams(max_new_tokens=6))[0]
    assert got == ref


def _metrics():
    from hadoop_tpu.serving.metrics import ServingMetrics
    return ServingMetrics()


# ------------------------------------------------------------------ loader

def test_loader_reads_wrapped_and_bare_trees(tmp_path, tiny_model):
    from hadoop_tpu.fs import LocalFileSystem
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    from hadoop_tpu.serving.loader import load_serving_params
    params, cfg = tiny_model
    fs = LocalFileSystem()
    # the trainer's layout ({"params":..., "opt":...}) and a bare tree
    save_checkpoint(fs, f"{tmp_path}/wrapped", 3,
                    {"params": params, "opt": {"step": jnp.zeros(())}})
    save_checkpoint(fs, f"{tmp_path}/bare", 5, params)
    # sequential and concurrent shard fetch must load identical trees
    for io_workers in (1, 4):
        for base in ("wrapped", "bare"):
            got, step = load_serving_params(fs, f"{tmp_path}/{base}",
                                            cfg, io_workers=io_workers)
            assert step == (3 if base == "wrapped" else 5)
            for a, b in zip(jax.tree_util.tree_leaves(got),
                            jax.tree_util.tree_leaves(params)):
                assert jnp.allclose(a, b)


# ----------------------------------------------------------- http replica

def _post_json(port, path, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("POST", path, body=json.dumps(payload).encode())
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, (json.loads(body) if body else {})


def test_end_to_end_dfs_checkpoint_to_streaming_http(tmp_path,
                                                     tiny_model):
    """The acceptance path: checkpoint written to miniDFS is loaded by
    the replica; three concurrent different-length requests decode
    correctly with at least one admitted mid-decode (batch-occupancy
    observable); /v1/generate streams tokens and enforces auth; drain
    refuses new work and finishes what it holds."""
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    from hadoop_tpu.serving.loader import load_serving_params
    from hadoop_tpu.serving.server import ServingServer
    from hadoop_tpu.testing.minicluster import MiniDFSCluster, fast_conf
    params, cfg = tiny_model
    conf = fast_conf()
    conf.set("dfs.replication", "1")
    with MiniDFSCluster(num_datanodes=1, conf=conf,
                        base_dir=str(tmp_path)) as cluster:
        cluster.wait_active()
        fs = cluster.get_filesystem()
        save_checkpoint(fs, "/models/tiny", 7,
                        {"params": params, "opt": {"s": jnp.zeros(())}})
        loaded, step = load_serving_params(fs, "/models/tiny", cfg)
        assert step == 7

        conf.set("serving.http.auth.secret", "s3cr3t")
        eng = DecodeEngine(loaded, cfg, max_batch=4, block_size=4,
                           max_context=48, metrics=_metrics())
        srv = ServingServer(eng, conf)
        eng.start()
        srv.start()
        try:
            # auth enforced: no credential -> 401
            status, body = _post_json(srv.port, "/v1/generate",
                                      {"tokens": [1, 2]})
            assert status == 401
            assert "AuthenticationException" in str(body)

            prompts = [[7, 8, 9], [42, 43], [1, 2, 3, 4, 5, 6]]
            refs = [_reference_greedy(params, cfg, p, n)
                    for p, n in zip(prompts, (40, 8, 8))]
            results = {}

            def ask(i, prompt, max_new):
                status, body = _post_json(
                    srv.port, "/v1/generate?user.name=alice",
                    {"tokens": prompt, "max_new_tokens": max_new})
                results[i] = (status, body)

            # long request first; the others join while it decodes
            t0 = threading.Thread(target=ask, args=(0, prompts[0], 40))
            t0.start()
            deadline = time.monotonic() + 60
            while eng.num_active < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            ts = [threading.Thread(target=ask, args=(i, prompts[i], 8))
                  for i in (1, 2)]
            for t in ts:
                t.start()
            for t in [t0] + ts:
                t.join(timeout=120)
            for i in range(3):
                status, body = results[i]
                assert status == 200, body
                assert body["tokens"] == refs[i]
            # continuous batching observable: the occupancy metric saw
            # more than one request in the batch at once
            assert max(eng.occupancy_log) >= 2
            assert eng.metrics.ttft.snapshot()[
                "time_to_first_token_count"] == 3
            # cache observability rides the health door
            status, health = _post_json(srv.port, "/v1/health", {})
            assert status == 200
            assert health["prefix_cache"]["enabled"] is True
            assert health["prefix_cache"]["prefill_chunk"] >= 1

            # streaming: chunked JSON lines, one per token
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=60)
            conn.request("POST", "/v1/generate?user.name=alice",
                         body=json.dumps({"tokens": [7, 8, 9],
                                          "max_new_tokens": 4,
                                          "stream": True}).encode())
            resp = conn.getresponse()
            assert resp.status == 200
            lines = [json.loads(l) for l in resp.read().splitlines() if l]
            conn.close()
            assert [l["token"] for l in lines[:-1]] == refs[0][:4]
            assert lines[-1]["done"] is True

            # drain: in-flight work finishes, new work is refused
            srv.drain(timeout=30)
            status, body = _post_json(srv.port,
                                      "/v1/generate?user.name=alice",
                                      {"tokens": [1]})
            assert status == 503
            status, health = _post_json(srv.port, "/v1/health", {})
            assert health["status"] == "draining"
        finally:
            srv.stop()


def test_generate_timeout_returns_408_not_retriable(tiny_model):
    """A generation outliving the client timeout returns 408 (a 4xx the
    router fails fast on) instead of a 500 the router would replay on
    every replica — retry amplification under load."""
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32)
    srv = ServingServer(eng, Configuration(load_defaults=False))
    srv.start()          # engine scheduler NOT started: request parks
    try:
        status, body = _post_json(
            srv.port, "/v1/generate",
            {"tokens": [1, 2], "max_new_tokens": 4, "timeout": 0.2})
        assert status == 408
        assert "RequestTimedOutException" in str(body)
        status, body = _post_json(
            srv.port, "/v1/generate",
            {"tokens": [1, 2], "timeout": "abc"})
        assert status == 400         # malformed timeout is a 400 like
        assert "IllegalArgument" in str(body)   # every other bad field
    finally:
        srv.stop()


def test_router_power_of_two_and_drain(tiny_model):
    """Router resolves replicas from the registry, balances, retries
    past a draining replica, and sees drained replicas leave the
    candidate set."""
    from hadoop_tpu.registry import (RegistryClient, RegistryServer,
                                     ServiceRecord)
    from hadoop_tpu.serving.router import ServingRouter, replica_path
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    conf = Configuration(load_defaults=False)
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    engines, servers = [], []
    try:
        for _ in range(2):
            eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                               max_context=32)
            srv = ServingServer(eng, Configuration(load_defaults=False))
            eng.start()
            srv.start()
            engines.append(eng)
            servers.append(srv)
        reg_addr = ("127.0.0.1", reg_srv.port)
        rc = RegistryClient(reg_addr, conf)
        for i, srv in enumerate(servers):
            rc.register(ServiceRecord(
                replica_path("demo", f"r{i}"),
                {"http": f"127.0.0.1:{srv.port}"},
                {"state": "serving"}), ttl_s=30.0, auto_renew=False)
        # and one dead endpoint the retry policy must route around
        rc.register(ServiceRecord(replica_path("demo", "dead"),
                                  {"http": "127.0.0.1:1"},
                                  {"state": "serving"}),
                    ttl_s=30.0, auto_renew=False)
        router = ServingRouter(reg_addr, "demo", conf, cache_ttl_s=0.0)
        ref = _reference_greedy(params, cfg, [3, 4, 5], 4)
        for _ in range(6):
            out = router.generate({"tokens": [3, 4, 5],
                                   "max_new_tokens": 4})
            assert out["tokens"] == ref
        # drain replica 0: record flips, router keeps succeeding via 1
        servers[0].drain(timeout=10)
        rc.register(ServiceRecord(replica_path("demo", "r0"),
                                  {"http":
                                   f"127.0.0.1:{servers[0].port}"},
                                  {"state": "draining"}),
                    ttl_s=30.0, auto_renew=False)
        for _ in range(4):
            out = router.generate({"tokens": [3, 4, 5],
                                   "max_new_tokens": 4})
            assert out["tokens"] == ref
        live = router.replicas(refresh=True)
        assert {r.path for r in live} == {replica_path("demo", "r1"),
                                          replica_path("demo", "dead")}
        # deterministic 400s fail fast — no cross-replica retry storm
        from hadoop_tpu.serving.router import ReplicaRequestError
        with pytest.raises(ReplicaRequestError):
            router.generate({"tokens": []})
        # registry outage: the stale replica cache keeps serving
        router.replicas(refresh=True)
        reg_srv.stop()
        out = router.generate({"tokens": [3, 4, 5],
                               "max_new_tokens": 4})
        assert out["tokens"] == ref
        router.close()
        rc.close()
    finally:
        for srv in servers:
            srv.stop()
        reg_srv.stop()


def test_router_prefix_affinity_pins_shared_prefixes(tiny_model):
    """Requests sharing a prompt prefix rendezvous onto ONE replica
    (its prefix cache keeps earning hits across the fleet) and fail
    over when that replica drains."""
    from hadoop_tpu.registry import (RegistryClient, RegistryServer,
                                     ServiceRecord)
    from hadoop_tpu.serving.router import ServingRouter, replica_path
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    conf = Configuration(load_defaults=False)
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    engines, servers = [], []
    try:
        for _ in range(2):
            eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                               max_context=32)
            srv = ServingServer(eng, Configuration(load_defaults=False))
            eng.start()
            srv.start()
            engines.append(eng)
            servers.append(srv)
        reg_addr = ("127.0.0.1", reg_srv.port)
        rc = RegistryClient(reg_addr, conf)
        for i, srv in enumerate(servers):
            rc.register(ServiceRecord(
                replica_path("affine", f"r{i}"),
                {"http": f"127.0.0.1:{srv.port}"},
                {"state": "serving"}), ttl_s=30.0, auto_renew=False)
        router = ServingRouter(reg_addr, "affine", conf, cache_ttl_s=0.0)
        ref = _reference_greedy(params, cfg, [3, 4, 5], 4)
        for _ in range(6):
            out = router.generate({"tokens": [3, 4, 5],
                                   "max_new_tokens": 4})
            assert out["tokens"] == ref
        assert router.affinity_routed == 6
        # all six shared-prefix requests landed on one replica
        served = [e for e in engines if e.tokens_generated > 0]
        assert len(served) == 1
        # drain the pinned replica: affinity must fail over, not wedge
        pinned = engines.index(served[0])
        servers[pinned].drain(timeout=10)
        rc.register(ServiceRecord(
            replica_path("affine", f"r{pinned}"),
            {"http": f"127.0.0.1:{servers[pinned].port}"},
            {"state": "draining"}), ttl_s=30.0, auto_renew=False)
        out = router.generate({"tokens": [3, 4, 5],
                               "max_new_tokens": 4})
        assert out["tokens"] == ref
        assert engines[1 - pinned].tokens_generated > 0
        router.close()
        rc.close()
    finally:
        for srv in servers:
            srv.stop()
        reg_srv.stop()


def test_replica_lifecycle_with_registry(tmp_path, tiny_model):
    """ServingReplica end-to-end without YARN: file:// checkpoint,
    registry registration, router-routed generate, drain-and-stop
    leaves the registry clean. (The YARN service spec launches exactly
    this entry point per container.)"""
    from hadoop_tpu.fs import LocalFileSystem
    from hadoop_tpu.parallel.checkpoint import save_checkpoint
    from hadoop_tpu.registry import RegistryServer
    from hadoop_tpu.serving.router import ServingRouter
    from hadoop_tpu.serving.service import ServingReplica
    params, cfg = tiny_model
    save_checkpoint(LocalFileSystem(), f"{tmp_path}/ckpt", 2,
                    {"params": params, "opt": {}})
    conf = Configuration(load_defaults=False)
    reg_srv = RegistryServer(conf)
    reg_srv.init(conf)
    reg_srv.start()
    try:
        replica = ServingReplica(
            conf, name="lifecycle", checkpoint=f"file://{tmp_path}/ckpt",
            preset="tiny", registry_addr=("127.0.0.1", reg_srv.port),
            instance="i0")
        replica.start()
        router = ServingRouter(("127.0.0.1", reg_srv.port), "lifecycle",
                               conf)
        ref = _reference_greedy(params, cfg, [1, 2], 3)
        out = router.generate({"tokens": [1, 2], "max_new_tokens": 3})
        assert out["tokens"] == ref
        replica.drain_and_stop(timeout=15)
        assert router.replicas(refresh=True) == []
        router.close()
    finally:
        reg_srv.stop()


def test_serving_service_spec_packaging():
    """The YARN packaging: one replica component, restart ALWAYS, the
    replica entry point in the launch command, JSON-roundtrippable."""
    from hadoop_tpu.serving.service import serving_service_spec
    from hadoop_tpu.yarn.services import ServiceSpec
    spec = serving_service_spec(
        "llm", checkpoint="htpu://nn:8020/models/llm", preset="tiny",
        replicas=3, registry_addr="127.0.0.1:7777")
    rt = ServiceSpec.from_json(spec.to_json())
    assert rt.name == "llm"
    comp = rt.components[0]
    assert comp.number_of_containers == 3
    assert comp.restart_policy == "ALWAYS"
    assert "hadoop_tpu.serving.service" in comp.launch_command
    assert "--checkpoint" in comp.launch_command
