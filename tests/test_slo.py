"""Fleet SLO scoreboard: bounded tenant-class stamping at the serving
door, class-labeled ``htpu_slo_*`` families on ``/prom``, doctor-side
burn-rate/attainment math over injected cumulative counters, the
autoscaler's guarded grow signal, the ``htpu_build_info`` constant
gauge, and the BENCH_LOG scorecard/trend satellites.

Determinism rule (the ISSUE's hard constraint): every burn/attainment
verdict here is pure arithmetic over INJECTED counters pumped through
``observe``/``commit`` — no wall-clock reads feed an assertion.
"""

import http.client
import json
import re
import threading
import time

import jax
import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.models.config import get_config
from hadoop_tpu.models.decoder import init_params
from hadoop_tpu.obs.slo import (SLO_CLASSES, SloScoreboard,
                                parse_class_map, slo_class_of)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("tiny")
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10.0)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get_json(port, path):
    status, body = _get(port, path)
    assert status == 200, body
    return json.loads(body)


def _post_json(port, path, payload, timeout=60.0):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload).encode())
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, (json.loads(body) if body else {})
    finally:
        conn.close()


# ------------------------------------------------------ class stamping

def test_slo_class_of_clamps_into_the_bounded_set():
    assert slo_class_of(0) == "p0"
    assert slo_class_of(3) == "p3"
    # a deeper QoS ladder or a junk level must NOT mint a new label
    assert slo_class_of(17) == "p3"
    assert slo_class_of(-2) == "p0"
    assert all(slo_class_of(n) in SLO_CLASSES for n in range(-3, 9))


def test_parse_class_map_drops_unknown_classes():
    conf = Configuration(load_defaults=False)
    conf.set("obs.slo.class.map",
             " heavy = p3 , light=p0, weird=zz, =p1, bare")
    m = parse_class_map(conf)
    # the pinned identities land; an unknown class and malformed
    # entries are dropped — the label set stays bounded no matter
    # what the conf says
    assert m == {"heavy": "p3", "light": "p0"}
    assert parse_class_map(Configuration(load_defaults=False)) == {}


# ------------------------------------------------- injected-counter math

def _fams(outcomes, ttft=None, token=None):
    """Build a parse_prom-shaped family dict from per-class CUMULATIVE
    outcome counts and optional cumulative histogram buckets."""
    fams = {"htpu_slo_requests_total": [
        ({"class": c, "outcome": o}, float(v))
        for c, oc in outcomes.items() for o, v in oc.items()]}
    for name, hists in (("htpu_slo_ttft_seconds", ttft),
                        ("htpu_slo_token_seconds", token)):
        if not hists:
            continue
        fams[f"{name}_bucket"] = [
            ({"class": c, "le": str(le)}, float(v))
            for c, b in hists.items() for le, v in b.items()]
        fams[f"{name}_count"] = [
            ({"class": c}, float(max(b.values())))
            for c, b in hists.items()]
    return fams


def _board(**over):
    conf = Configuration(load_defaults=False)
    conf.set("obs.slo.window.fast", "2")
    conf.set("obs.slo.window.slow", "4")
    conf.set("obs.slo.burn.min-windows", "1")
    conf.set("obs.slo.burn.history", "3")
    for k, v in over.items():
        conf.set(k.replace("_", "."), v)
    return SloScoreboard(conf)


def test_burn_rate_and_attainment_over_injected_counters():
    sb = _board()
    # poll 1: both classes healthy (baseline)
    sb.observe("r0", _fams(
        {"p3": {"ok": 10}, "p0": {"ok": 10}},
        ttft={"p0": {0.1: 10, float("inf"): 10}}))
    rep = sb.commit(["r0"])
    assert rep["classes"]["p3"]["burning"] is False
    assert rep["classes"]["p0"]["availability"] == pytest.approx(1.0)
    # poll 2: the heavy class torches its budget (21 failures on 1 ok
    # delta); the light class stays perfect and fast
    sb.observe("r0", _fams(
        {"p3": {"ok": 11, "failed": 21}, "p0": {"ok": 12}},
        ttft={"p0": {0.1: 12, float("inf"): 12}}))
    rep = sb.commit(["r0"])
    p3, p0 = rep["classes"]["p3"], rep["classes"]["p0"]
    # fast window spans both polls: 11 ok / 32 total
    assert p3["availability"] == pytest.approx(11 / 32)
    budget = 1.0 - p3["targets"]["availability"]
    assert p3["burn_fast"] == pytest.approx(
        (1 - 11 / 32) / budget)
    assert p3["burn_fast"] >= 14.0 and p3["burn_slow"] >= 2.0
    assert p3["burning"] is True
    # the light class is green under the same overload: full
    # availability, p99 attained against the 2000 ms default target
    assert p0["availability"] == pytest.approx(1.0)
    assert p0["burning"] is False and p0["burn_fast"] == 0.0
    assert p0["ttft_p99_ms"] is not None
    assert p0["ttft_p99_ms"] <= p0["targets"]["ttft_p99_ms"]
    assert p0["ttft_attained"] is True
    assert rep["windows_seen"] == 2


def test_counter_reset_means_restart_not_negative_window():
    sb = _board(obs_slo_window_fast="1")
    sb.observe("r0", _fams({"p3": {"ok": 50}}))
    sb.commit(["r0"])
    # the replica restarted: cumulative counters fell. The whole new
    # history belongs to this window (FleetScraper rule) — never a
    # negative delta
    sb.observe("r0", _fams({"p3": {"ok": 5}}))
    rep = sb.commit(["r0"])
    assert rep["classes"]["p3"]["window"]["ok"] == pytest.approx(5.0)
    assert all(v >= 0 for v in
               rep["classes"]["p3"]["window"].values())


def test_departed_endpoint_is_pruned_then_rejoins_fresh():
    sb = _board(obs_slo_window_fast="1")
    sb.observe("a", _fams({"p0": {"ok": 100}}))
    sb.observe("b", _fams({"p0": {"ok": 40}}))
    sb.commit(["a", "b"])
    # b leaves the registry; its baseline must be forgotten
    sb.observe("a", _fams({"p0": {"ok": 101}}))
    rep = sb.commit(["a"])
    assert rep["classes"]["p0"]["window"]["ok"] == pytest.approx(1.0)
    # b's address returns with LOWER counters (a new replica on a
    # recycled port): fresh baseline, full value counted, no negatives
    sb.observe("a", _fams({"p0": {"ok": 102}}))
    sb.observe("b", _fams({"p0": {"ok": 3}}))
    rep = sb.commit(["a", "b"])
    assert rep["classes"]["p0"]["window"]["ok"] == pytest.approx(4.0)


def test_burn_hysteresis_flags_and_recovers():
    sb = _board(**{"obs_slo_burn_min-windows": "2",
                   "obs_slo_window_fast": "1",
                   "obs_slo_window_slow": "1"})
    burn = {"p3": {"ok": 0, "failed": 10}}
    ok = {"p3": {"ok": 10, "failed": 0}}
    cum = {"ok": 0, "failed": 0}

    def poll(shape):
        cum["ok"] += shape["p3"].get("ok", 0)
        cum["failed"] += shape["p3"].get("failed", 0)
        sb.observe("r0", _fams({"p3": dict(cum)}))
        return sb.commit(["r0"])

    # one burning poll is a spike, not a verdict (min-windows=2)
    assert poll(burn)["classes"]["p3"]["burning"] is False
    # the second consecutive burning poll flags
    assert poll(burn)["classes"]["p3"]["burning"] is True
    # clean polls age the flag out of the history deque (3 here) —
    # recovery without operator reset, the SlowNodeDetector precedent
    for _ in range(3):
        rep = poll(ok)
    assert rep["classes"]["p3"]["burning"] is False


def test_empty_fleet_commit_does_not_age_standing_verdicts():
    sb = _board(**{"obs_slo_burn_min-windows": "1",
                   "obs_slo_window_fast": "1",
                   "obs_slo_window_slow": "1"})
    sb.observe("r0", _fams({"p3": {"ok": 0, "failed": 10}}))
    rep = sb.commit(["r0"])
    assert rep["classes"]["p3"]["burning"] is True
    before = rep["windows_seen"]
    # nothing scraped and nobody known: NOT a window — a blind doctor
    # must not launder a burning class back to green
    rep = sb.commit([])
    assert rep["windows_seen"] == before
    assert rep["classes"]["p3"]["burning"] is True


# ------------------------------------------------- door -> /prom e2e

def test_door_stamps_bounded_class_labels_on_prom(tiny_model):
    """The e2e seam: a pinned tenant's 200 lands under its mapped
    class on /prom (ttft + outcome families), a QoS shed lands under
    the level-derived class, and the chassis carries the
    htpu_build_info constant gauge — all labels from the bounded
    p0..p3 set."""
    from hadoop_tpu.serving.engine import DecodeEngine
    from hadoop_tpu.serving.metrics import ServingMetrics
    from hadoop_tpu.serving.server import ServingServer
    params, cfg = tiny_model
    conf = Configuration(load_defaults=False)
    conf.set("obs.slo.class.map", "vip=p1")
    m = ServingMetrics()
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32, metrics=m)
    srv = ServingServer(eng, conf)
    eng.start()
    srv.start()
    try:
        ok_before = m.slo_requests[("p1", "ok")].value()
        status, body = _post_json(
            srv.port, "/v1/generate?user.name=vip",
            {"tokens": [1, 2, 3], "max_new_tokens": 4})
        assert status == 200, body
        assert m.slo_requests[("p1", "ok")].value() == ok_before + 1
        # a shedding gate stamps the shed with the ADMIT level's class
        class _AlwaysShed:
            @staticmethod
            def cost_of(tokens, max_new):
                return 1.0

            def admit(self, tenant, cost):
                return False, 0.05, 3

            def stats(self):
                return {}

            def stop(self):
                pass

        srv.qos = _AlwaysShed()
        shed_before = m.slo_requests[("p3", "shed")].value()
        status, body = _post_json(
            srv.port, "/v1/generate?user.name=batchjob",
            {"tokens": [1, 2], "max_new_tokens": 4})
        assert status == 429, body
        assert m.slo_requests[("p3", "shed")].value() \
            == shed_before + 1
        # ...and the families surface class-labeled on this door's own
        # /prom, next to the build-identity gauge
        text = _get(srv.port, "/prom")[1].decode()
        assert re.search(
            r'htpu_slo_requests_total\{[^}]*class="p1"[^}]*'
            r'outcome="ok"[^}]*\} \d+', text)
        assert re.search(
            r'htpu_slo_requests_total\{[^}]*class="p3"[^}]*'
            r'outcome="shed"[^}]*\} \d+', text)
        assert re.search(
            r'htpu_slo_ttft_seconds_bucket\{[^}]*class="p1"', text)
        assert re.search(
            r'htpu_build_info\{code_hash="[^"]+",jax="[^"]+"\} 1',
            text)
        # every emitted class label is from the bounded set
        for cls in re.findall(r'htpu_slo_\w+\{[^}]*class="([^"]+)"',
                              text):
            assert cls in SLO_CLASSES
    finally:
        srv.stop()


def test_build_info_constant_gauge_on_every_chassis():
    from hadoop_tpu.http.server import HttpServer
    from hadoop_tpu.obs.build import build_info, build_info_prom
    info = build_info()
    assert set(info) == {"code_hash", "jax"}
    assert info["code_hash"] and info["jax"]
    assert build_info() == info          # cached: one probe per process
    assert re.search(
        r'htpu_build_info\{code_hash="[^"]+",jax="[^"]+"\} 1\n',
        build_info_prom())
    # any daemon's chassis carries it — not just serving doors
    srv = HttpServer(Configuration(load_defaults=False),
                     daemon_name="anydaemon")
    srv.start()
    try:
        text = _get(srv.port, "/prom")[1].decode()
        assert f'htpu_build_info{{code_hash="{info["code_hash"]}"' \
            in text
    finally:
        srv.stop()


# ------------------------------------------- doctor + autoscaler seam

class _FakeReplica:
    """A scripted serving endpoint: the test sets the exact /prom text
    the doctor scrapes, so the scoreboard verdict is pure counter
    arithmetic (the _FakeRank precedent)."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        fake = self

        class _H(BaseHTTPRequestHandler):
            def do_GET(self):
                body = fake.text.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.text = ""
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def set_counts(self, counts):
        lines = ["# TYPE htpu_slo_requests_total counter"]
        for cls, oc in counts.items():
            for outcome, v in oc.items():
                lines.append(
                    f'htpu_slo_requests_total{{class="{cls}",'
                    f'outcome="{outcome}"}} {v}')
        self.text = "\n".join(lines) + "\n"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_doctor_scoreboard_flags_heavy_class_and_serves_slo_door():
    """The deterministic overload scenario end-to-end through the
    doctor: a registry-discovered replica's heavy class burns its
    budget and is flagged at /ws/v1/fleet/slo within min-windows
    polls; the light class stays green; the verdict is joined into
    /ws/v1/fleet/doctor."""
    from hadoop_tpu.obs.doctor import FleetDoctor
    from hadoop_tpu.registry import RegistryServer, ServiceRecord
    reg_srv = RegistryServer(Configuration(load_defaults=False))
    reg_srv.init(Configuration(load_defaults=False))
    reg_srv.start()
    rep = _FakeReplica()
    doctor = None
    try:
        reg_srv.put(ServiceRecord(
            "/services/serving/svc/r0",
            endpoints={"http": f"127.0.0.1:{rep.port}"}), ttl_s=3600)
        dconf = Configuration(load_defaults=False)
        dconf.set("obs.doctor.registry", f"127.0.0.1:{reg_srv.port}")
        dconf.set("obs.doctor.push.namenode", "false")
        dconf.set("obs.slo.window.fast", "2")
        dconf.set("obs.slo.window.slow", "8")
        dconf.set("obs.slo.burn.min-windows", "2")
        dconf.set("obs.slo.burn.history", "4")
        doctor = FleetDoctor(dconf)
        doctor.init(dconf)
        doctor.start()
        # poll 1: healthy baseline for both classes
        rep.set_counts({"p3": {"ok": 4}, "p0": {"ok": 5}})
        doctor.poll_once()
        # overload: the heavy class sheds 20 on 2 ok; light stays ok
        rep.set_counts({"p3": {"ok": 6, "shed": 20},
                        "p0": {"ok": 10}})
        doctor.poll_once()
        report = doctor.poll_once()       # 2nd flagged poll >= min
        slo = _get_json(doctor.port, "/ws/v1/fleet/slo")
        p3, p0 = slo["classes"]["p3"], slo["classes"]["p0"]
        assert p3["burning"] is True, p3
        assert p3["burn_fast"] >= 14.0 and p3["burn_slow"] >= 2.0
        assert p0["burning"] is False, p0
        assert p0["availability"] == pytest.approx(1.0)
        # the same verdict rides the main doctor report
        assert report["slo"]["classes"]["p3"]["burning"] is True
    finally:
        if doctor is not None:
            doctor.stop()
        rep.stop()
        reg_srv.stop()


def test_autoscaler_slo_burn_grow_signal_is_conf_guarded():
    from hadoop_tpu.serving.autoscale import Autoscaler
    from hadoop_tpu.serving.autoscale.signals import (FleetSnapshot,
                                                      ReplicaSample)

    def mk(enabled):
        conf = Configuration(load_defaults=False)
        conf.set("serving.autoscale.breach.polls", "1")
        conf.set("serving.autoscale.cooldown", "0s")
        conf.set("serving.autoscale.ttft.p99.slo", "1s")
        if enabled:
            conf.set("serving.autoscale.slo.burn", "true")
        return Autoscaler(conf, ("127.0.0.1", 1), "svc")

    calm = FleetSnapshot(at=0.0, samples=[ReplicaSample(
        path="/s/d0", host="127.0.0.1", port=1, role="mixed", ok=True,
        queue_depth=0, active=0, slots=4, prefill_backlog=0,
        cached_blocks=0, load_seconds=0.0)])
    burn = {"p3": {"burning": True, "burn_fast": 50.0,
                   "burn_slow": 9.0, "availability": 0.5}}
    # default OFF: a burning class alone must not grow the fleet
    sc = mk(enabled=False)
    sc._slo_burn = dict(burn)
    assert sc._decide("decode", calm) is None
    assert sc.status()["slo_burn"]["enabled"] is False
    # opted in: the doctor's verdict is a grow reason on its own
    sc = mk(enabled=True)
    sc._slo_burn = dict(burn)
    d = sc._decide("decode", calm)
    assert d is not None and d.action == "grow"
    assert "error-budget burn" in d.reason and "p3" in d.reason
    st = sc.status()
    assert st["slo_burn"]["enabled"] is True
    assert st["slo_burn"]["classes"]["p3"]["burning"] is True


# --------------------------------------- BENCH_LOG scorecard + sentinel

def test_scorecard_append_and_trend_sentinel(tmp_path):
    from benchmarks import bench_trend
    log = str(tmp_path / "BENCH_LOG.jsonl")
    slo = {"code": "abc1234",
           "classes": {"p3": {"burning": True, "availability": 0.5},
                       "p0": {"burning": False, "availability": 1.0}}}
    bench_trend.append_slo_scorecard(log, slo)
    with open(log) as f:
        rows = [json.loads(line) for line in f]
    assert rows[0]["metric"] == "slo_scorecard"
    assert rows[0]["burning"] == ["p3"]
    assert rows[0]["code"] == "abc1234"
    # scorecards pass through the suite sentinel untouched
    assert bench_trend.load_rows(log) == []
    # history + a regressed newest row: flagged, and --check exits 1
    with open(log, "a") as f:
        for mbs in (100.0, 110.0, 105.0, 40.0):
            f.write(json.dumps({
                "metric": "bench_suite", "quick": False,
                "key_metrics": {"dfsio.write_mb_s": mbs}}) + "\n")
    verdict = bench_trend.check(bench_trend.load_rows(log))
    assert verdict["regressions_count"] == 1
    assert verdict["regressions"][0]["metric"] == "dfsio.write_mb_s"
    assert verdict["regressions"][0]["direction"] == "higher"
    assert bench_trend.main(["--log", log, "--check"]) == 1
    # a recovered newest row passes the gate
    with open(log, "a") as f:
        f.write(json.dumps({
            "metric": "bench_suite", "quick": False,
            "key_metrics": {"dfsio.write_mb_s": 104.0}}) + "\n")
    assert bench_trend.main(["--log", log, "--check"]) == 0
