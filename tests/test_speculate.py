"""Speculative decoding + device-resident step state.

The exactness pins: speculation may only move WORK (fewer engine
steps), never tokens — greedy output through the speculation lane must
be token-for-token what the speculation-off engine (and the full
``models.decoder.forward`` recompute) produces, rejected drafts must
never reach the radix prefix cache, and multi-token bursts must respect
``max_new_tokens`` and ``stop_token`` exactly.

The perf pins: the steady-state decode loop transfers NOTHING
host→device (the state is device-resident; a ``jax.transfer_guard``
proves it), the two step shapes still compile exactly once each, and
``stop(drain=True)`` parks on the scheduler condition instead of
sleep-polling.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hadoop_tpu.models.config import get_config
from hadoop_tpu.models.decoder import forward, init_params
from hadoop_tpu.serving.engine import DecodeEngine, SamplingParams
from hadoop_tpu.serving.metrics import ServingMetrics
from hadoop_tpu.serving.speculate import NgramProposer


@pytest.fixture(scope="module")
def tiny_model():
    cfg = get_config("tiny")
    return init_params(jax.random.PRNGKey(0), cfg), cfg


_REF_P = 64
_ref_fwd_cache = {}


def _reference_greedy(params, cfg, prompt, max_new):
    """Full forward recompute each step — ground truth (padded to one
    fixed length so the reference compiles once; causal attention keeps
    the padded tail out of earlier logits)."""
    fwd = _ref_fwd_cache.get(id(cfg))
    if fwd is None:
        fwd = jax.jit(lambda p, t: forward(p, t, cfg))
        _ref_fwd_cache[id(cfg)] = fwd
    seq = list(prompt)
    for _ in range(max_new):
        padded = seq + [0] * (_REF_P - len(seq))
        logits = fwd(params, jnp.asarray([padded]))
        seq.append(int(jnp.argmax(logits[0, len(seq) - 1])))
    return seq[len(prompt):]


def _drive(eng, reqs):
    if not isinstance(reqs, list):
        reqs = [reqs]
    while not all(r.done.is_set() for r in reqs):
        eng.step()
    return [r.wait(0) for r in reqs]


def _motif_prompt(rng, cfg, motif_len=2, plen=16):
    m = rng.integers(0, cfg.vocab_size, size=motif_len).tolist()
    return (m * (-(-plen // motif_len)))[:plen]


# ----------------------------------------------------------- proposer

def test_ngram_proposer_chains_through_cycles():
    p = NgramProposer([1, 2, 3, 1, 2, 3, 1, 2], max_n=3)
    # tail (1, 2) last occurred ending at index 4; continuation chains
    # through the whole cycle as deep as k allows
    assert p.propose(6) == [3, 1, 2, 3, 1, 2]
    assert p.propose(2) == [3, 1]
    p.append(3)
    assert p.propose(3) == [1, 2, 3]


def test_ngram_proposer_never_matches_its_own_tip():
    # the tip trigram (7, 8, 9) exists nowhere earlier: no proposal —
    # a self-match would "predict" the token after the end of history
    p = NgramProposer([7, 8, 9])
    assert p.propose(4) == []
    # a single repeated token proposes itself (1-gram fallback)
    assert NgramProposer([5, 5]).propose(3) == [5, 5, 5]
    assert NgramProposer([]).propose(3) == []
    assert NgramProposer([1, 2]).propose(0) == []


def test_ngram_proposer_prefers_longer_context():
    # after [..., 1, 2] the 2-gram (1, 2) → 9 must beat the staler but
    # shorter 1-gram (2) → 7 evidence
    p = NgramProposer([2, 7, 1, 2, 9, 4, 1, 2], max_n=3)
    assert p.propose(1) == [9]


# ----------------------------------------------------- exact sampling

def test_speculative_greedy_matches_reference_and_off(tiny_model):
    """The tentpole pin: greedy decode through the speculation lane is
    token-for-token the full-recompute reference, accepts drafts, and
    still compiles exactly two shapes once each."""
    params, cfg = tiny_model
    rng = np.random.default_rng(3)
    prompt = _motif_prompt(rng, cfg)
    ref = _reference_greedy(params, cfg, prompt, 24)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=64, prefill_chunk=8, speculate_k=4)
    got = _drive(eng, eng.submit(
        prompt, SamplingParams(max_new_tokens=24)))[0]
    assert got == ref
    assert eng.spec_proposed > 0 and eng.spec_accepted > 0, \
        "a repetitive prompt must earn accepted drafts"
    off = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=64, prefill_chunk=8)
    assert _drive(off, off.submit(
        prompt, SamplingParams(max_new_tokens=24)))[0] == ref
    assert eng.steps < off.steps, \
        "accepted drafts must strictly reduce engine steps"
    assert eng.decode_compiles == 1 and eng.prefill_compiles == 1


def test_speculative_lanes_mix_with_sampled_lanes(tiny_model):
    """top_k=1 at temperature 1.0 is a point-mass target: rejection
    sampling degenerates to argmax equality, so the lane must emit
    exactly the greedy reference through the speculation path; a free
    temperature lane sharing the batch stays in-vocab."""
    params, cfg = tiny_model
    rng = np.random.default_rng(4)
    prompt = _motif_prompt(rng, cfg)
    ref = _reference_greedy(params, cfg, prompt, 12)
    eng = DecodeEngine(params, cfg, max_batch=3, block_size=4,
                       max_context=64, prefill_chunk=8, speculate_k=3)
    topk1 = eng.submit(prompt, SamplingParams(
        max_new_tokens=12, temperature=1.0, top_k=1))
    free = eng.submit(prompt[:6], SamplingParams(
        max_new_tokens=12, temperature=1.3))
    greedy = eng.submit(prompt, SamplingParams(max_new_tokens=12))
    outs = _drive(eng, [topk1, free, greedy])
    assert outs[0] == ref
    assert outs[2] == ref
    assert all(0 <= t < cfg.vocab_size for t in outs[1])
    assert len(outs[1]) == 12


# ------------------------------------------------- burst-delivery guard

def test_speculation_never_overshoots_max_new(tiny_model):
    """k > remaining budget: a lane accepting j drafts must deliver at
    most ``max_new_tokens - len(out_tokens)`` — the regression the
    in-step budget clamp (and the host-side burst guard) pins."""
    params, cfg = tiny_model
    rng = np.random.default_rng(3)
    prompt = _motif_prompt(rng, cfg)
    for max_new in (1, 2, 3, 5):
        ref = _reference_greedy(params, cfg, prompt, max_new)
        eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                           max_context=64, prefill_chunk=8,
                           speculate_k=4)
        got = _drive(eng, eng.submit(
            prompt, SamplingParams(max_new_tokens=max_new)))[0]
        assert got == ref, f"max_new={max_new}"
        assert len(got) == max_new


def test_speculation_stops_exactly_at_stop_token(tiny_model):
    """A stop_token hit mid-burst must cut delivery at the stop, never
    past it — token-for-token with the speculation-off engine."""
    params, cfg = tiny_model
    rng = np.random.default_rng(3)
    prompt = _motif_prompt(rng, cfg)
    ref = _reference_greedy(params, cfg, prompt, 24)
    # pick a token the greedy stream emits mid-flight so the stop
    # lands inside an accepted multi-token burst
    stop = ref[len(ref) // 2]
    want = ref[:ref.index(stop) + 1]
    for k in (0, 4):
        eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                           max_context=64, prefill_chunk=8,
                           speculate_k=k)
        got = _drive(eng, eng.submit(prompt, SamplingParams(
            max_new_tokens=24, stop_token=stop)))[0]
        assert got == want, f"speculate_k={k}"
        assert got[-1] == stop and stop not in got[:-1]


# ------------------------------------------- speculation x prefix cache

def test_rejected_drafts_never_enter_radix(tiny_model):
    """Pool pressure preempts a speculating request mid-flight; its
    re-prefill republishes prompt + ACCEPTED tokens into the radix.
    Every ``PrefixCache.insert`` must see only block-aligned prefixes
    of a request's true delivered stream — a rejected draft in the
    index would poison every future sharer — and the preemption must
    release draft pages exactly once (the pool invariants catch a
    double free)."""
    params, cfg = tiny_model
    rng = np.random.default_rng(3)
    pa = _motif_prompt(rng, cfg, plen=12)
    pb = _motif_prompt(rng, cfg, plen=12)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=48, num_blocks=10, prefill_chunk=8,
                       speculate_k=4, metrics=ServingMetrics())
    inserts = []
    real_insert = eng.prefix_cache.insert

    def spy(tokens, blocks):
        inserts.append(list(tokens))
        return real_insert(tokens, blocks)

    eng.prefix_cache.insert = spy
    ra = eng.submit(pa, SamplingParams(max_new_tokens=24))
    rb = eng.submit(pb, SamplingParams(max_new_tokens=20))
    outs = _drive(eng, [ra, rb])
    assert outs[0] == _reference_greedy(params, cfg, pa, 24)
    assert outs[1] == _reference_greedy(params, cfg, pb, 20)
    assert rb.preemptions + ra.preemptions >= 1, \
        "pool pressure never preempted a speculating lane"
    streams = [pa + outs[0], pb + outs[1]]
    for tokens in inserts:
        assert len(tokens) % eng.block_size == 0, \
            "insert saw a non-block-aligned span"
        assert any(tokens == s[:len(tokens)] for s in streams), \
            f"radix insert {tokens} is not a prefix of any " \
            f"accepted stream"
    # draft pages released exactly once: every page is free or
    # resident zero-ref cache, nothing leaked or double-freed
    assert eng.pool.num_free + len(eng.prefix_cache) == \
        eng.pool.num_usable
    assert all(eng.pool.refcount(b) == 0
               for b in range(1, eng.pool.num_blocks))


# --------------------------------------- device-resident state contract

def test_steady_state_decode_uploads_nothing(tiny_model):
    """The transfer-count probe: with the step state device-resident,
    a steady-state decode step performs ZERO host→device transfers —
    the eight per-step jnp.asarray uploads of the old engine are gone,
    replaced by event scatters at admission/finish/page-growth only.
    jax.transfer_guard turns any regression into a hard error."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=16,
                       max_context=64)
    req = eng.submit([1, 2, 3], SamplingParams(max_new_tokens=40))
    for _ in range(4):       # prefill, flip to decode, compile shapes
        eng.step()
    assert eng._active[0]
    before = len(req.out_tokens)
    with jax.transfer_guard_host_to_device("disallow"):
        for _ in range(8):   # no admission/finish/page event in here
            eng.step()
    assert len(req.out_tokens) == before + 8
    # the speculation lane keeps the contract on no-proposal steps:
    # the device-resident zero-draft twins dispatch, not an upload
    eng2 = DecodeEngine(params, cfg, max_batch=2, block_size=16,
                        max_context=64, speculate_k=4)
    req2 = eng2.submit(list(range(1, 8)),
                       SamplingParams(max_new_tokens=40))
    for _ in range(4):
        eng2.step()
    if not eng2._draft_lens.any():
        with jax.transfer_guard_host_to_device("disallow"):
            eng2.step()


def test_packed_bundle_reports_emission_and_finish(tiny_model):
    """The one device→host read per step carries everything the host
    needs: finished lanes retire without any extra scan."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32)
    ref = _reference_greedy(params, cfg, [9, 3, 7], 4)
    got = _drive(eng, eng.submit([9, 3, 7],
                                 SamplingParams(max_new_tokens=4)))[0]
    assert got == ref
    # slot fully cleared on the device side too: nothing decodes after
    assert not eng._active.any()
    assert eng.step() == 0


# ------------------------------------------------------------ lifecycle

def test_drain_stop_waits_on_condition_not_poll(tiny_model,
                                                monkeypatch):
    """stop(drain=True) parks on the scheduler condition and is
    notified on request completion — a time.sleep anywhere in the
    drain path fails the test."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32)
    req = eng.submit([4, 5, 6], SamplingParams(max_new_tokens=6))
    eng.start()

    def no_sleep(_):
        raise AssertionError("drain busy-waited via time.sleep")

    monkeypatch.setattr(time, "sleep", no_sleep)
    eng.stop(drain=True, timeout=60.0)
    assert req.done.is_set()
    assert req.wait(0) == _reference_greedy(params, cfg, [4, 5, 6], 6)


def test_failed_step_recovery_rebuilds_donated_state(tiny_model):
    """A step that fails AFTER consuming its donated device buffers
    (KV pools + step state) must not wedge the replica: the scheduler
    loop's handler rebuilds all of them before scattering lane-clear
    events, purges the HBM radix (its cached pages died with the
    pools), fails the in-flight requests, and the engine decodes fresh
    work correctly afterwards. (Simulated by deleting every donated
    buffer before raising — what a mid-execution device failure leaves
    behind.) The doomed prompt spans two full blocks so its prefix IS
    cached before the failure: replaying it afterwards must re-prefill
    exactly, not map a zeroed page the purged radix no longer knows."""
    params, cfg = tiny_model
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=32)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    real_step = eng._step_fn
    state = {"armed": True}

    def flaky_step(*a, **kw):
        # fail the first step AFTER prefill completed — by then the
        # prompt's two full blocks sit in the radix
        if state["armed"] and eng._active.any():
            state["armed"] = False
            for leaf in jax.tree_util.tree_leaves(
                    (eng._dstate, eng._kp, eng._vp)):
                leaf.delete()
            raise RuntimeError("injected device failure")
        return real_step(*a, **kw)

    eng._step_fn = flaky_step
    eng.start()
    doomed = eng.submit(prompt, SamplingParams(max_new_tokens=6))
    with pytest.raises(RuntimeError, match="decode failed"):
        doomed.wait(30.0)
    assert len(eng.prefix_cache) == 0, "dead pages survived as cache"
    # the thread survived; the rebuilt pools decode the SAME prompt
    # exactly (a stale radix entry would map zeroed K/V instead)
    fresh = eng.submit(prompt, SamplingParams(max_new_tokens=6))
    got = fresh.wait(30.0)
    eng.stop()
    assert got == _reference_greedy(params, cfg, prompt, 6)


# -------------------------------------------------------------- metrics

def test_spec_metrics_surface_on_prom(tiny_model):
    """spec_proposed/spec_accepted counters and the accepted-length
    histogram publish through /prom as one family each."""
    from hadoop_tpu.metrics import metrics_system
    from hadoop_tpu.metrics.prom import render_prom
    params, cfg = tiny_model
    rng = np.random.default_rng(3)
    eng = DecodeEngine(params, cfg, max_batch=2, block_size=4,
                       max_context=64, prefill_chunk=8, speculate_k=4,
                       metrics=ServingMetrics())
    _drive(eng, eng.submit(_motif_prompt(rng, cfg),
                           SamplingParams(max_new_tokens=24)))
    assert eng.spec_accepted > 0
    stats = eng.cache_stats()["speculate"]
    assert stats["proposed"] >= stats["accepted"] > 0
    assert stats["k"] == 4
    text = render_prom(metrics_system())
    assert "htpu_spec_proposed" in text
    assert "htpu_spec_accepted" in text
    assert text.count("# TYPE htpu_spec_accept_len histogram") == 1
