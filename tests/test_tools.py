"""L5 tools: distcp between two clusters, streaming with external
commands, map-only jobs. Ref: hadoop-tools/hadoop-distcp/DistCp.java:60,
hadoop-tools/hadoop-streaming."""

import os

import pytest

from hadoop_tpu.conf import Configuration
from hadoop_tpu.testing.minicluster import MiniDFSCluster, MiniMRYarnCluster


@pytest.fixture(scope="module")
def cluster():
    with MiniMRYarnCluster(num_nodes=2) as c:
        yield c


@pytest.fixture(scope="module")
def fs(cluster):
    return cluster.get_filesystem()


def test_distcp_between_clusters(cluster, fs):
    """The acceptance case: copy a tree from a SECOND dfs into this
    cluster's dfs via a map-only MR job, with CRC verification."""
    from hadoop_tpu.tools.distcp import distcp
    with MiniDFSCluster(num_datanodes=2) as src_cluster:
        src_fs = src_cluster.get_filesystem()
        payload = {}
        src_fs.mkdirs("/tree/sub")
        for name, size in (("/tree/a.bin", 100_000),
                           ("/tree/sub/b.bin", 300_000),
                           ("/tree/sub/c.txt", 5_000)):
            data = os.urandom(size)
            src_fs.write_all(name, data)
            payload[name] = data
        src_uri = f"{src_cluster.default_fs}/tree"
        dst_uri = f"{cluster.default_fs}/copied"

        counters = distcp(cluster.rm_addr, cluster.default_fs,
                          src_uri, dst_uri, num_maps=2)
        assert counters.get("DistCp", {}).get("COPIED") == 3
        for name, data in payload.items():
            rel = name[len("/tree"):]
            assert fs.read_all(f"/copied{rel}") == data

        # -update run: everything skips
        counters = distcp(cluster.rm_addr, cluster.default_fs,
                          src_uri, dst_uri, num_maps=2)
        assert counters.get("DistCp", {}).get("SKIPPED") == 3
        assert not counters.get("DistCp", {}).get("COPIED")


def test_streaming_sed_mapper_maponly(cluster, fs):
    from hadoop_tpu.tools.streaming import streaming_job
    fs.mkdirs("/stream-in")
    fs.write_all("/stream-in/x.txt",
                 b"foo one\nfoo two\nbar three\n")
    job = streaming_job(cluster.rm_addr, cluster.default_fs,
                        "/stream-in", "/stream-out-m",
                        mapper="/bin/sed -e s/foo/FOO/")
    assert job.wait_for_completion(), job.diagnostics
    out = b"".join(fs.read_all(s.path)
                   for s in sorted(fs.list_status("/stream-out-m"),
                                   key=lambda s: s.path)
                   if "part-m-" in s.path)
    assert b"FOO one" in out and b"FOO two" in out and b"bar three" in out
    assert fs.exists("/stream-out-m/_SUCCESS")


def test_streaming_with_reducer(cluster, fs, tmp_path):
    import sys
    from hadoop_tpu.tools.streaming import streaming_job
    fs.mkdirs("/stream-in2")
    fs.write_all("/stream-in2/x.txt",
                 b"apple\nbanana\napple\ncherry\nbanana\napple\n")
    mapper_py = tmp_path / "map.py"
    mapper_py.write_text(
        "import sys\n"
        "for line in sys.stdin:\n"
        "    print(line.strip() + '\\t1')\n")
    reducer_py = tmp_path / "red.py"
    reducer_py.write_text(
        "import sys, collections\n"
        "c = collections.Counter()\n"
        "for line in sys.stdin:\n"
        "    k, v = line.rstrip('\\n').split('\\t')\n"
        "    c[k] += int(v)\n"
        "for k, v in c.items():\n"
        "    print(f'{k}\\t{v}')\n")
    job = streaming_job(
        cluster.rm_addr, cluster.default_fs, "/stream-in2", "/stream-out-r",
        mapper=f"{sys.executable} {mapper_py}",
        reducer=f"{sys.executable} {reducer_py}",
        num_reduces=1)
    assert job.wait_for_completion(), job.diagnostics
    out = b"".join(fs.read_all(s.path)
                   for s in fs.list_status("/stream-out-r")
                   if "part-r-" in s.path)
    rows = dict(line.split(b"\t") for line in out.splitlines() if line)
    assert rows == {b"apple": b"3", b"banana": b"2", b"cherry": b"1"}


def test_distcp_update_recopies_same_size_changed_file(tmp_path):
    """-update must not trust size alone: a same-length in-place change
    (fixed-width records) re-copies based on mtime (review finding —
    stale bytes could become authoritative after a fedbalance)."""
    import time as _t

    from hadoop_tpu.tools.distcp import distcp
    with MiniMRYarnCluster(num_nodes=1) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/src")
        fs.write_all("/src/fixed.bin", b"A" * 1024)
        base = f"{cluster.default_fs}"
        distcp(cluster.rm_addr, cluster.default_fs,
               f"{base}/src", f"{base}/dst")
        assert fs.read_all("/dst/fixed.bin") == b"A" * 1024
        _t.sleep(1.1)  # mtime resolution
        fs.write_all("/src/fixed.bin", b"B" * 1024)  # same size, new bytes
        distcp(cluster.rm_addr, cluster.default_fs,
               f"{base}/src", f"{base}/dst")
        assert fs.read_all("/dst/fixed.bin") == b"B" * 1024


def test_distcp_single_file_into_existing_dir(tmp_path):
    """Copying one file onto an existing directory lands INSIDE it as
    dst/<name> (review finding — it mapped onto the directory path and
    create() blew up)."""
    from hadoop_tpu.tools.distcp import distcp
    with MiniMRYarnCluster(num_nodes=1) as cluster:
        fs = cluster.get_filesystem()
        fs.mkdirs("/one")
        fs.write_all("/one/file.txt", b"payload")
        fs.mkdirs("/destdir")
        base = f"{cluster.default_fs}"
        distcp(cluster.rm_addr, cluster.default_fs,
               f"{base}/one/file.txt", f"{base}/destdir")
        assert fs.read_all("/destdir/file.txt") == b"payload"
